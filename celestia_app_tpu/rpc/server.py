"""The served node: HTTP JSON-RPC around the app + proposer/replication.

Parity surface (reference):
  * gRPC/API/RPC servers wrapping the app — app/app.go:712-735,
    test/util/testnode/network.go:38-43. Here one JSON-RPC-over-HTTP
    endpoint (Tendermint RPC's own transport) serves broadcast, account,
    tx-status, block, proof, and state-proof queries.
  * Block replication over sockets: a rotating proposer sends each
    finalized proposal to its peer validators (`apply_block`), who
    process_proposal + finalize + commit independently and must land on
    identical app hashes and data roots — the multi-process analog of the
    round-1 in-process Network, now with a real wire between validators.

Threading model: one RLock per node guards all app/mempool access; the
HTTP server is threading (one handler thread per request) and the proposer
loop is a daemon thread. All node methods take/return JSON-safe values at
the HTTP boundary (rpc/codec.py).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from celestia_app_tpu.app import BlockData
from celestia_app_tpu.tx import tx_hash
from celestia_app_tpu.rpc.codec import to_jsonable
from celestia_app_tpu.testutil.testnode import BLOCK_INTERVAL_NS, TestNode


class ReplicationDivergence(RuntimeError):
    """A peer committed a different app hash / data root for the same block."""


class ServingNode(TestNode):
    """TestNode + locking + tx gossip + proposal replication to peers."""

    def __init__(
        self,
        genesis=None,
        keys=None,
        app=None,
        validator_index: int = 0,
        n_validators: int = 1,
        peers: list[str] | None = None,
    ):
        super().__init__(genesis, keys, app=app)
        # (BlockData, time_ns) by height: survives serving a restarted
        # chain (list index != height) and feeds peer catch-up.
        self._blocks_by_height: dict[int, tuple[BlockData, int]] = {}
        # App version per height (the block header's Version.App in the
        # reference): clients reconstructing historical squares need the
        # hard cap in force then, not the current gov param.
        self._version_by_height: dict[int, int] = {}
        self.lock = threading.RLock()
        # Serializes whole produce+replicate rounds so replicated heights
        # reach peers in order even with concurrent produce callers.
        self._produce_lock = threading.Lock()
        self.validator_index = validator_index
        self.n_validators = max(1, n_validators)
        self.peer_urls = list(peers or [])
        self._peers: list = []  # RemoteNode handles, built lazily

    # --- peers --------------------------------------------------------------
    def peers(self):
        if len(self._peers) != len(self.peer_urls):
            from celestia_app_tpu.rpc.client import RemoteNode

            self._peers = [RemoteNode(u, defer_status=True) for u in self.peer_urls]
        return self._peers

    def is_proposer(self, height: int) -> bool:
        return (height - 1) % self.n_validators == self.validator_index

    # --- tx admission + gossip ----------------------------------------------
    def broadcast(self, raw_tx: bytes, relay: bool = True):
        with self.lock:
            res = super().broadcast(raw_tx)
        if res.code == 0 and relay:
            for peer in self.peers():
                try:
                    peer.broadcast(raw_tx, relay=False)
                except Exception:
                    pass  # mempool gossip is best-effort; consensus is not
        return res

    # --- block production + replication --------------------------------------
    def produce_block(self, time_ns: int | None = None):
        with self._produce_lock:
            return self._produce_and_replicate(time_ns)

    def _produce_and_replicate(self, produce_time_ns: int | None = None):
        with self.lock:
            proposal_version = self.app.app_version  # pre-end-block upgrades
            data, results = super().produce_block(produce_time_ns)
            height = self.app.height
            time_ns = self.app.last_block_time_ns
            own_app_hash = self.app.cms.last_app_hash
            self._blocks_by_height[height] = (data, time_ns)
            self._version_by_height[height] = proposal_version
        for peer in self.peers():
            reply = peer.apply_block(height, time_ns, data)
            if (
                bytes.fromhex(reply["app_hash"]) != own_app_hash
                or bytes.fromhex(reply["data_hash"]) != data.hash
            ):
                raise ReplicationDivergence(
                    f"peer {peer.url} diverged at height {height}: "
                    f"{reply['app_hash'][:16]} != {own_app_hash.hex()[:16]}"
                )
        return data, results

    def apply_block(self, height: int, time_ns: int, data: BlockData) -> dict:
        """Peer endpoint: validate + execute a replicated proposal.

        A peer that missed blocks (e.g. it was still starting when the
        proposer advanced) first catches up from whoever serves them, so a
        transient replication failure cannot wedge the devnet permanently.
        """
        with self.lock:
            behind = height > self.app.height + 1
        if behind:
            self._catch_up(height - 1)
        with self.lock:
            if height != self.app.height + 1:
                raise ValueError(
                    f"out-of-order block {height}, at {self.app.height}"
                )
            proposal_version = self.app.app_version  # pre-end-block upgrades
            if not self.app.process_proposal(data):
                raise ValueError(f"proposal rejected at height {height}")
            results = self.app.finalize_block(time_ns, list(data.txs))
            self.app.commit()
            self.mempool.update(self.app.height, list(data.txs))
            self.blocks.append(data)
            self._blocks_by_height[height] = (data, time_ns)
            self._version_by_height[height] = proposal_version
            self.index_block(height, list(data.txs), results)
            return {
                "app_hash": self.app.cms.last_app_hash.hex(),
                "data_hash": data.hash.hex(),
            }

    def _catch_up(self, upto: int) -> None:
        """Fetch + apply committed blocks up to `upto` from any peer."""
        while True:
            with self.lock:
                h = self.app.height + 1
            if h > upto:
                return
            for peer in self.peers():
                try:
                    b = peer.block(h)
                except Exception:
                    continue
                data = BlockData(
                    txs=tuple(bytes.fromhex(t) for t in b["txs"]),
                    square_size=b["square_size"],
                    hash=bytes.fromhex(b["data_hash"]),
                )
                self.apply_block(h, b["time_ns"], data)
                break
            else:
                raise ValueError(f"cannot catch up: no peer serves block {h}")

    # --- JSON-safe RPC methods (the HTTP surface) -----------------------------
    def rpc_status(self) -> dict:
        with self.lock:
            return {
                "chain_id": self.chain_id,
                "height": self.app.height,
                "app_hash": self.app.cms.last_app_hash.hex(),
                "app_version": self.app.app_version,
                "validator_index": self.validator_index,
                "n_validators": self.n_validators,
                "max_square_size": self.app.max_effective_square_size(),
            }

    def rpc_broadcast_tx(self, tx: str, relay: bool = True) -> dict:
        res = self.broadcast(bytes.fromhex(tx), relay=relay)
        return {"code": res.code, "log": res.log,
                "hash": tx_hash(bytes.fromhex(tx)).hex()}

    def rpc_tx_status(self, hash: str) -> dict | None:
        with self.lock:
            st = self.tx_status(bytes.fromhex(hash))
        if st is None:
            return None
        return {"height": st[0], "code": st[1], "log": st[2]}

    def rpc_account(self, address: str) -> dict | None:
        with self.lock:
            acc = self.query_account(address)
        if acc is None:
            return None
        return {"account_number": acc.account_number, "sequence": acc.sequence}

    def rpc_block(self, height: int) -> dict:
        with self.lock:
            entry = self._blocks_by_height.get(height)
            if entry is None:
                raise ValueError(f"no block at height {height}")
            data, time_ns = entry
        return {
            "height": height,
            "time_ns": time_ns,
            "data_hash": data.hash.hex(),
            "square_size": data.square_size,
            "app_version": self._version_by_height.get(height, self.app.app_version),
            "txs": [t.hex() for t in data.txs],
        }

    def rpc_produce_block(self) -> dict:
        data, results = self.produce_block()
        return {
            "height": self.app.height,
            "data_hash": data.hash.hex(),
            "square_size": data.square_size,
            "results": [
                {"code": r.code, "log": r.log, "gas_wanted": r.gas_wanted,
                 "gas_used": r.gas_used}
                for r in results
            ],
        }

    def rpc_apply_block(
        self, height: int, time_ns: int, data_hash: str, square_size: int,
        txs: list[str],
    ) -> dict:
        data = BlockData(
            txs=tuple(bytes.fromhex(t) for t in txs),
            square_size=square_size,
            hash=bytes.fromhex(data_hash),
        )
        return self.apply_block(height, time_ns, data)

    def rpc_tx_inclusion_proof(self, height: int, tx_index: int) -> dict:
        from celestia_app_tpu.proof.querier import query_tx_inclusion_proof

        with self.lock:
            block = self.rpc_block(height)
            max_k = self.app.max_effective_square_size()
        proof = query_tx_inclusion_proof(
            [bytes.fromhex(t) for t in block["txs"]], tx_index, max_k
        )
        return {"proof": to_jsonable(proof), "data_root": block["data_hash"]}

    def rpc_share_inclusion_proof(self, height: int, start: int, end: int) -> dict:
        from celestia_app_tpu.proof.querier import query_share_inclusion_proof

        with self.lock:
            block = self.rpc_block(height)
            max_k = self.app.max_effective_square_size()
        proof = query_share_inclusion_proof(
            [bytes.fromhex(t) for t in block["txs"]], start, end, max_k
        )
        return {"proof": to_jsonable(proof), "data_root": block["data_hash"]}

    def rpc_state_proof(self, key: str) -> dict:
        with self.lock:
            proof = self.app.cms.proof(bytes.fromhex(key))
            app_hash = self.app.cms.last_app_hash
        return {"proof": to_jsonable(proof), "app_hash": app_hash.hex()}

    def rpc_validators(self) -> list[dict]:
        from celestia_app_tpu.state.staking import StakingKeeper

        with self.lock:
            vals = StakingKeeper(self.app.cms.working).validators()
        return [{"address": v.address, "power": v.power} for v in vals]

    # --- blobstream relayer surface -----------------------------------------
    # The query endpoints a BlobstreamX relayer consumes (reference
    # x/blobstream/keeper/query_*.go served over gRPC, plus celestia-core's
    # DataCommitment / DataRootInclusionProof RPCs used by client/verify.go).
    def _blobstream_keeper(self):
        from celestia_app_tpu.modules.blobstream.keeper import BlobstreamKeeper
        from celestia_app_tpu.state.staking import StakingKeeper

        store = self.app.cms.working
        return BlobstreamKeeper(store, StakingKeeper(store))

    @staticmethod
    def _attestation_dict(att) -> dict:
        from celestia_app_tpu.modules.blobstream.keeper import DataCommitment, Valset

        if isinstance(att, Valset):
            return {
                "kind": "valset",
                "nonce": att.nonce,
                "height": att.height,
                "time_ns": att.time_ns,
                "members": [
                    {"address": m.address, "power": m.power} for m in att.members
                ],
            }
        assert isinstance(att, DataCommitment)
        return {
            "kind": "data_commitment",
            "nonce": att.nonce,
            "begin_block": att.begin_block,
            "end_block": att.end_block,
            "height": att.height,
            "time_ns": att.time_ns,
        }

    def rpc_blobstream_attestation(self, nonce: int) -> dict | None:
        """QueryAttestationRequestByNonce."""
        with self.lock:
            att = self._blobstream_keeper().get_attestation(nonce)
        return None if att is None else self._attestation_dict(att)

    def rpc_blobstream_nonces(self) -> dict:
        """LatestAttestationNonce + EarliestAttestationNonce."""
        with self.lock:
            k = self._blobstream_keeper()
            latest = k.latest_nonce()
            try:
                earliest = k.earliest_available_nonce()
            except KeyError:
                earliest = 0
        return {"latest": latest, "earliest": earliest}

    def rpc_data_commitment_range(self, height: int) -> dict:
        """DataCommitmentRangeForHeight (query_data_commitment.go:10-19)."""
        with self.lock:
            att = self._blobstream_keeper().data_commitment_for_height(height)
        return self._attestation_dict(att)

    def rpc_latest_data_commitment(self) -> dict | None:
        """LatestDataCommitment (query_data_commitment.go:21-32)."""
        with self.lock:
            try:
                att = self._blobstream_keeper().latest_data_commitment()
            except KeyError:
                return None
        return self._attestation_dict(att)

    def rpc_latest_valset_before(self, nonce: int) -> dict:
        """LatestValsetRequestBeforeNonce (query_valset.go:12-22)."""
        with self.lock:
            vs = self._blobstream_keeper().latest_valset_before_nonce(nonce)
        return self._attestation_dict(vs)

    def _window_data_roots(self, begin: int, end: int) -> list[tuple[int, bytes]]:
        """(height, data_root) for each height in [begin, end)."""
        out = []
        for h in range(begin, end):
            entry = self._blocks_by_height.get(h)
            if entry is None:
                raise ValueError(f"no block at height {h} (window [{begin},{end}))")
            out.append((h, entry[0].hash))
        return out

    def rpc_data_commitment(self, begin: int, end: int) -> str:
        """Tuple root over [begin, end) — celestia-core's DataCommitment RPC,
        the root the relayer submits to the Blobstream contract."""
        from celestia_app_tpu.modules.blobstream.keeper import data_commitment_root

        with self.lock:
            roots = self._window_data_roots(begin, end)
        return data_commitment_root(roots).hex()

    def rpc_data_root_inclusion_proof(self, height: int, begin: int, end: int) -> dict:
        """Binary-merkle proof of (height, dataRoot) inside the window's
        tuple root — celestia-core's DataRootInclusionProof RPC
        (consumed at client/verify.go:288)."""
        from celestia_app_tpu.modules.blobstream.keeper import (
            data_root_inclusion_proof,
        )

        with self.lock:
            roots = self._window_data_roots(begin, end)
        index, total, path = data_root_inclusion_proof(roots, height)
        return {
            "index": index,
            "total": total,
            "path": [p.hex() for p in path],
        }


def _method_table(node: ServingNode) -> dict:
    return {
        name[len("rpc_"):]: getattr(node, name)
        for name in dir(node)
        if name.startswith("rpc_")
    }


class _Handler(BaseHTTPRequestHandler):
    methods: dict = {}

    def log_message(self, fmt, *args):  # quiet: tests parse stdout
        pass

    def do_GET(self):
        """GET /metrics: Prometheus text exposition (the Tendermint
        instrumentation analog, test/e2e/testnet/setup.go:24)."""
        if self.path.rstrip("/") != "/metrics":
            self.send_response(404)
            self.end_headers()
            return
        from celestia_app_tpu.trace.metrics import registry

        payload = registry().render().encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_POST(self):
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length))
            method = self.methods.get(req.get("method", ""))
            if method is None:
                raise ValueError(f"unknown method {req.get('method')!r}")
            result = method(**req.get("params", {}))
            body = {"jsonrpc": "2.0", "id": req.get("id"), "result": result}
            status = 200
        except Exception as e:  # noqa: BLE001 — every fault becomes an RPC error
            body = {
                "jsonrpc": "2.0",
                "id": None,
                "error": {"code": -32000, "message": f"{type(e).__name__}: {e}"},
            }
            status = 500
        payload = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


class NodeServer:
    """Owns the HTTP server + optional proposer-loop thread."""

    def __init__(self, node: ServingNode, host: str = "127.0.0.1", port: int = 0):
        handler = type("BoundHandler", (_Handler,), {"methods": _method_table(node)})
        self.node = node
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    def start(self, block_interval_s: float | None = None):
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        if block_interval_s is not None:
            p = threading.Thread(
                target=self._proposer_loop, args=(block_interval_s,), daemon=True
            )
            p.start()
            self._threads.append(p)
        return self

    def _proposer_loop(self, interval_s: float):
        while not self._stop.wait(interval_s):
            try:
                if self.node.is_proposer(self.node.app.height + 1):
                    self.node.produce_block()
            except Exception as e:  # noqa: BLE001
                import sys

                print(f"proposer loop error: {e}", file=sys.stderr)

    def stop(self):
        self._stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()


def serve(
    node: ServingNode,
    host: str = "127.0.0.1",
    port: int = 0,
    block_interval_s: float | None = 0.2,
) -> NodeServer:
    """Start serving `node`; returns the running NodeServer (daemon threads)."""
    return NodeServer(node, host, port).start(block_interval_s)

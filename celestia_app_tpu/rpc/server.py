"""The served node: HTTP JSON-RPC around the app + proposer/replication.

Parity surface (reference):
  * gRPC/API/RPC servers wrapping the app — app/app.go:712-735,
    test/util/testnode/network.go:38-43. Here one JSON-RPC-over-HTTP
    endpoint (Tendermint RPC's own transport) serves broadcast, account,
    tx-status, block, proof, and state-proof queries.
  * Block replication over sockets: a rotating proposer sends each
    finalized proposal to its peer validators (`apply_block`), who
    process_proposal + finalize + commit independently and must land on
    identical app hashes and data roots — the multi-process analog of the
    round-1 in-process Network, now with a real wire between validators.

Threading model: one RLock per node guards all app/mempool access; the
HTTP server is threading (one handler thread per request) and the proposer
loop is a daemon thread. All node methods take/return JSON-safe values at
the HTTP boundary (rpc/codec.py).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from celestia_app_tpu.app import BlockData
from celestia_app_tpu.trace.context import trace_span, use_context
from celestia_app_tpu.tx import tx_hash
from celestia_app_tpu.rpc.codec import to_jsonable
from celestia_app_tpu.testutil.testnode import BLOCK_INTERVAL_NS, TestNode


class ReplicationDivergence(RuntimeError):
    """A peer committed a different app hash / data root for the same block."""


class ServingNode(TestNode):
    """TestNode + locking + tx gossip + proposal replication to peers."""

    def __init__(
        self,
        genesis=None,
        keys=None,
        app=None,
        validator_index: int = 0,
        n_validators: int = 1,
        peers: list[str] | None = None,
        validator_key=None,
        snapshot_interval: int = 0,
    ):
        super().__init__(genesis, keys, app=app)
        # State-sync snapshots (reference: every 1500 blocks, keep 2,
        # app/default_overrides.go:293-297).  0 = serving disabled.
        self.snapshot_interval = snapshot_interval
        self._snapshots: dict[int, dict] = {}
        from celestia_app_tpu.crypto.keys import PrivateKey

        # This node's consensus key (signs prevotes/precommits). Defaults
        # to the deterministic seed matching deterministic_genesis's
        # validator set; operators pass their own.
        self.validator_key = validator_key or PrivateKey.from_seed(
            f"validator-{validator_index}".encode()
        )
        # height -> Commit: the +2/3 precommit records light clients verify.
        self._commits: dict[int, "object"] = {}
        # height -> block hash this node prevoted (it precommits only what
        # it prevoted — the vote-consistency rule).
        self._prevoted: dict[int, bytes] = {}
        # The evidence pool: every signature-valid vote this node has
        # witnessed, keyed by height -> (validator, type, block_hash).
        # Conflicting entries are double-sign evidence (x/evidence;
        # Tendermint's evidence pool) shipped with the next proposal.
        self._witnessed: dict[int, dict[tuple[str, int, bytes], "object"]] = {}
        # (validator, height, vote_type) triples already submitted as
        # evidence — one equivocation per key is enough to tombstone.
        self._used_evidence: set[tuple[str, int, int]] = set()
        # (BlockData, time_ns, last_commit_signers, evidence_wire) by
        # height: survives serving a restarted chain (list index != height)
        # and feeds peer catch-up — signers/evidence MUST replicate with
        # the block or x/slashing state diverges across nodes.
        self._blocks_by_height: dict[int, tuple] = {}
        # height -> validator set (addr -> (PublicKey, power)) the height's
        # consensus ran under; kept alongside the block store so catch-up
        # can verify historic LastCommits across jailing boundaries.
        self._valsets_by_height: dict[int, dict] = {}
        # App version per height (the block header's Version.App in the
        # reference): clients reconstructing historical squares need the
        # hard cap in force then, not the current gov param.
        self._version_by_height: dict[int, int] = {}
        self.lock = threading.RLock()
        # The proof-serving plane's retention (serve/): every committed
        # non-empty height's EDS + NMT forests, LRU over
        # $CELESTIA_SERVE_HEIGHTS with host spill — the read side light
        # clients sample against.  Built lazily with its DasProvider so a
        # node that never serves proofs pays nothing.
        self._serve_cache = None
        self._das_provider = None
        # Serializes whole produce+replicate rounds so replicated heights
        # reach peers in order even with concurrent produce callers.
        self._produce_lock = threading.Lock()
        self.validator_index = validator_index
        self.n_validators = max(1, n_validators)
        self.peer_urls = list(peers or [])
        self._peers: list = []  # RemoteNode handles, built lazily

    # --- peers --------------------------------------------------------------
    def peers(self):
        if len(self._peers) != len(self.peer_urls):
            from celestia_app_tpu.rpc.client import RemoteNode

            # Peer handles keep the OLD 30 s cap: replication holds the
            # produce lock, and the long default (sized for a client
            # waiting out a cold jit in produce_block) would stall block
            # production 4x longer per blackholed peer.
            self._peers = [
                RemoteNode(u, timeout=30.0, defer_status=True)
                for u in self.peer_urls
            ]
        return self._peers

    def is_proposer(self, height: int) -> bool:
        return (height - 1) % self.n_validators == self.validator_index

    # --- tx admission + gossip ----------------------------------------------
    def broadcast(self, raw_tx: bytes, relay: bool = True, ctx=None):
        """Mempool gossip: multi-hop flood with mempool-insert dedup.

        A tx relays onward only when it was NEWLY admitted here, so the
        flood terminates (re-received txs are already resident) yet
        crosses partial topologies hop by hop — a tx submitted anywhere
        reaches the proposer without the submitter knowing who that is
        (reference: mempool v1 gossip, app/default_overrides.go:258-284).
        `ctx` is the request's TraceContext (threaded into the mempool
        entry; see trace/context.py).

        Locking: the node lock is held only around CheckTx (inside
        super().broadcast — app check state is the remaining serial
        section); the mempool admission runs under the pool's own
        per-shard locks, so concurrent broadcasts of DIFFERENT tenants
        no longer serialize end-to-end.  The newly-admitted probe is a
        before/after residency read: a same-tx race can at worst relay
        twice (the flood's dedup absorbs it) or skip one relay hop (the
        re-offer path recovers it) — both documented best-effort.
        """
        known = self.mempool.has_tx(raw_tx)
        res = super().broadcast(raw_tx, ctx=ctx)
        inserted = not known and res.code == 0 and self.mempool.has_tx(raw_tx)
        if inserted and relay:
            def _relay():
                for peer in self.peers():
                    try:
                        peer.broadcast(raw_tx, relay=True)
                    except Exception:
                        pass  # mempool gossip is best-effort; consensus is not

            self.gossip_pool.submit(_relay)
        return res

    @property
    def gossip_pool(self):
        """Shared executor for async gossip sends (tx relay + consensus
        flood).  A pool, not ad-hoc threads: NodeServer.stop drains it so
        no send outlives the server (stray daemon threads dying inside
        C-runtime calls abort the interpreter at exit).  Sized up under
        chaos latency injection — injected sleeps park workers, and an
        8-worker pool would serialize a block's worth of sends behind
        them."""
        pool = getattr(self, "_gossip_pool", None)
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            driver = getattr(self, "consensus_driver", None)
            workers = 8
            if driver is not None and (driver.latency_s or driver.jitter_s):
                workers = 48
            pool = self._gossip_pool = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="gossip"
            )
        return pool

    def shutdown_gossip(self) -> None:
        pool = getattr(self, "_gossip_pool", None)
        if pool is not None:
            pool.shutdown(wait=True, cancel_futures=True)
            self._gossip_pool = None

    # --- block production + replication --------------------------------------
    def produce_block(self, time_ns: int | None = None):
        with self._produce_lock:
            return self._produce_and_replicate(time_ns)

    def _validator_set(self):
        """address -> (PublicKey, power), the vote-accounting view.

        Built from the BONDED set: a jailed or tombstoned validator's votes
        stop counting toward quorum the moment the jailing block commits
        (Tendermint rebuilds the consensus valset from bonded validators
        the same way)."""
        from celestia_app_tpu.crypto.keys import PublicKey
        from celestia_app_tpu.state.staking import StakingKeeper

        out = {}
        for v in StakingKeeper(self.app.cms.working).bonded_validators():
            if v.pubkey:
                out[v.address] = (PublicKey(v.pubkey), v.power)
        return out

    def _witness_vote(self, vote, validators) -> None:
        """Feed the evidence pool: record any signature-valid vote by a
        known validator, INCLUDING votes for a block id this node disagrees
        with — a conflicting pair per (validator, height, type) is exactly
        what x/evidence punishes."""
        entry = validators.get(vote.validator)
        if entry is None or not vote.verify(entry[0], self.chain_id):
            return
        self._witnessed.setdefault(vote.height, {})[
            (vote.validator, vote.vote_type, vote.block_hash)
        ] = vote

    def _pending_evidence(self) -> list:
        """Equivocations in the pool not yet submitted (proposer side)."""
        from celestia_app_tpu.consensus.votes import find_equivocations

        votes = [
            v for by_key in self._witnessed.values() for v in by_key.values()
        ]
        return [
            ev
            for ev in find_equivocations(votes)
            if ev.key() not in self._used_evidence
        ]

    def _sign_vote(self, height: int, vote_type: int, block_hash: bytes):
        from celestia_app_tpu.consensus import Vote

        return Vote.sign(
            self.validator_key, self.chain_id, height, vote_type, block_hash,
            validator=self._operator_address(),
        )

    def _operator_address(self) -> str:
        """The bonded validator this node's consensus key speaks for.
        Genesis validators' operator address IS the key's address; a
        validator created via MsgCreateValidator registers the consensus
        pubkey under the operator's account address instead.  Cached per
        committed height — votes are signed twice per round and the
        valset only moves when a block commits."""
        cached = getattr(self, "_operator_cache", None)
        if cached is not None and cached[0] == self.app.height:
            return cached[1]
        from celestia_app_tpu.state.staking import StakingKeeper

        own = self.validator_key.public_key()
        addr = own.address()  # not (yet) a validator: vote as itself
        for v in StakingKeeper(self.app.cms.working).bonded_validators():
            if v.pubkey == own.bytes:
                addr = v.address
                break
        self._operator_cache = (self.app.height, addr)
        return addr

    def _commit_block_data(
        self,
        data: BlockData,
        time_ns: int,
        last_commit_signers: set[str] | None = None,
        evidence: tuple = (),
    ):
        """The shared commit sequence + the serving plane's per-height
        bookkeeping (block store for catch-up, app version for clients).
        Signers/evidence are stored with the block so catch-up replays the
        exact x/slashing inputs every live node executed."""
        proposal_version = self.app.app_version  # pre-end-block upgrades
        # The set THIS height's consensus ran under (bonded set after H-1),
        # captured before the block applies: gossip catch-up restores it to
        # verify height-H LastCommits — the post-H set has already dropped
        # anyone block H jailed, whose legitimate precommit must still count.
        vals_pre_apply = self._validator_set()
        results = super()._commit_block_data(
            data, time_ns,
            last_commit_signers=last_commit_signers, evidence=evidence,
        )
        height = self.app.height
        self._valsets_by_height[height] = vals_pre_apply
        evidence_wire = self._evidence_to_wire(evidence)
        self._blocks_by_height[height] = (
            data, time_ns,
            sorted(last_commit_signers) if last_commit_signers is not None else None,
            evidence_wire,
        )
        self._version_by_height[height] = proposal_version
        self._prevoted.pop(height, None)  # round done
        self._retain_for_serving(height, data)
        for ev in evidence:
            self._used_evidence.add(ev.key())
        # Bound the evidence pool (Tendermint prunes expired evidence).
        for h in [h for h in self._witnessed if h < height - 100]:
            del self._witnessed[h]
        if self.snapshot_interval and height % self.snapshot_interval == 0:
            self._take_snapshot(height)
        return results

    # --- the proof-serving plane (serve/) ------------------------------------
    @property
    def serve_cache(self):
        if self._serve_cache is None:
            from celestia_app_tpu.serve.cache import ForestCache

            self._serve_cache = ForestCache()
        return self._serve_cache

    def das_provider(self):
        """This node's DasProvider (serve/api.py): the cache-backed
        payload builder every plane serves; misses rebuild from the block
        store so an evicted height is slower, never unservable."""
        if self._das_provider is None:
            from celestia_app_tpu.serve.api import DasProvider

            self._das_provider = DasProvider(
                cache=self.serve_cache, rebuild=self._rebuild_eds
            )
        return self._das_provider

    def _retain_for_serving(self, height: int, data: BlockData) -> None:
        """Admit the committed height's EDS + forests to the serve cache.

        The normal path is free of square work: the app extended exactly
        this square during Prepare/Process and still holds the handle
        (App.last_eds_for_root, matched on the committed data hash), so
        retention costs one async forest dispatch — no second layout
        solve, no duplicate square-journal row, no re-extension.  A
        memo miss (e.g. the handle was displaced) falls back to a full
        rebuild.  Never raises into the commit path: the serve plane
        degrading must not stall consensus.
        """
        from celestia_app_tpu.serve import serve_heights

        if serve_heights() <= 0 or not data.txs:
            return  # disabled, or an empty block (the min square)
        try:
            eds = self.app.last_eds_for_root(data.hash)
            if eds is None:
                eds = self._eds_for_block(data)
            if eds is not None:
                self.serve_cache.put(height, eds)
        except Exception as e:  # noqa: BLE001 — read plane must not stall commit
            import sys

            print(f"serve retention failed at height {height}: {e}",
                  file=sys.stderr)

    def _eds_for_block(self, data: BlockData):
        """Reconstruct the block's EDS, ROOT-VERIFIED against the
        committed data hash; None for empty blocks or an unreproducible
        square.

        The square is re-solved under the CURRENT effective cap first
        (the common case) and, when that fails to reproduce, under the
        committed square size as the ceiling — a governance cap change
        after this height would otherwise re-solve a DIFFERENT layout
        whose proofs can never verify against the committed header (the
        block store's own square_size_upper_bound caveat).  The DAH-hash
        check is the gate either way: this node never serves proofs
        against a root it did not commit."""
        import sys

        from celestia_app_tpu.da.dah import DataAvailabilityHeader
        from celestia_app_tpu.square import builder as square

        if not data.txs:
            return None
        caps = [self.app.max_effective_square_size()]
        if data.square_size not in caps:
            caps.append(data.square_size)
        for cap in caps:
            sq = square.construct(list(data.txs), cap)
            if sq.is_empty() or sq.size != data.square_size:
                continue
            eds = self.app.square_eds(sq.size, sq.share_bytes())
            if DataAvailabilityHeader.from_eds(eds).hash() == data.hash:
                return eds
        print(
            f"serve rebuild cannot reproduce the committed square "
            f"(size {data.square_size}, root {data.hash.hex()[:16]}); "
            "refusing to serve unverifiable proofs",
            file=sys.stderr,
        )
        return None

    def _rebuild_eds(self, height: int):
        """DasProvider miss path: rebuild from the block store's raw txs
        (the querier pattern) so proofs outlive every cache tier."""
        with self.lock:
            entry = self._blocks_by_height.get(height)
        if entry is None:
            return None
        return self._eds_for_block(entry[0])

    def rpc_get_share_proof(
        self, height: int, row: int, col: int, axis: str = "row"
    ) -> dict:
        """GetShareProof — one DAS sample of the EXTENDED square (parity
        quadrants included), proven to the height's committed DAH data
        root through the row tree or (axis="col") the column tree.  Same
        payload dict the GET /das/share_proof route renders."""
        from celestia_app_tpu.serve.api import count_served

        payload = self.das_provider().share_proof_payload(
            int(height), int(row), int(col), axis=axis
        )
        count_served("jsonrpc", "share_proof", payload)
        return payload

    def rpc_get_shares_by_namespace(self, height: int, namespace: str) -> dict:
        """GetSharesByNamespace — every share of a namespace with its
        multi-row inclusion proof (namespace as 29-byte hex)."""
        from celestia_app_tpu.serve.api import count_served

        payload = self.das_provider().shares_payload(int(height), namespace)
        count_served("jsonrpc", "shares", payload)
        return payload

    def rpc_get_attestation(self, height: int, samples: str) -> dict:
        """GetAttestation — a deduped multiproof for a SET of samples
        (`samples` = comma-joined row:col[:axis]): shared NMT and root
        nodes serialized once, per-sample proofs reconstructable by
        indexing (rpc/codec.share_proofs_from_attestation).  Same payload
        dict the GET /das/attestation route renders."""
        from celestia_app_tpu.serve.api import count_served

        payload = self.das_provider().attestation_payload(
            int(height), samples
        )
        count_served("jsonrpc", "attestation", payload)
        return payload

    # --- state-sync snapshots -------------------------------------------------
    SNAPSHOT_CHUNK_BYTES = 512 * 1024

    def _take_snapshot(self, height: int) -> None:
        import hashlib

        state = self.app.cms.export(height)
        blob = json.dumps(
            {k.hex(): v.hex() for k, v in sorted(state.items())},
            separators=(",", ":"),
        ).encode()
        chunks = [
            blob[i: i + self.SNAPSHOT_CHUNK_BYTES]
            for i in range(0, max(len(blob), 1), self.SNAPSHOT_CHUNK_BYTES)
        ]
        self._snapshots[height] = {
            "height": height,
            "app_hash": self.app.cms.last_app_hash.hex(),
            "app_version": self.app.app_version,  # post-commit (resume needs it)
            "chain_id": self.chain_id,
            # Mint provisions derive from (genesis time, last block time,
            # supply); both times must restore exactly or the synced node's
            # first minted block diverges from every other validator.
            "genesis_time_ns": self.app.genesis_time_ns,
            "block_time_ns": self.app.last_block_time_ns,
            "chunks": chunks,
            "chunk_hashes": [hashlib.sha256(c).hexdigest() for c in chunks],
        }
        for h in sorted(self._snapshots)[:-2]:  # keep 2
            del self._snapshots[h]

    def _produce_and_replicate(self, produce_time_ns: int | None = None):
        """One voting round per height (celestia-core's consensus shape,
        proposer-driven — scope note in consensus/votes.py):

          propose -> prevotes -> +2/3? -> precommits -> +2/3?
          -> commit everywhere with the Commit record

        Both quorum gates run BEFORE any node commits state: a failed round
        leaves every validator exactly where it was.  Every node that
        applies the block stores the Commit record (rpc_commit serves it).
        """
        from celestia_app_tpu.consensus import (
            PRECOMMIT,
            PREVOTE,
            Commit,
            ConsensusError,
            Vote,
            VoteSet,
            block_id,
        )

        peers = self.peers()
        with self.lock:
            validators = self._validator_set()
            time_ns = (
                produce_time_ns
                if produce_time_ns is not None
                else self.app.last_block_time_ns + BLOCK_INTERVAL_NS
            )
            height = self.app.height + 1
            prev_app_hash = self.app.cms.last_app_hash
            # ABCI LastCommitInfo: who precommitted the previous height
            # (x/slashing liveness input); ByzantineValidators: double-sign
            # pairs from the evidence pool.  Both replicate with the block.
            prev_commit = self._commits.get(height - 1)
            last_signers = (
                {v.validator for v in prev_commit.precommits}
                if prev_commit is not None
                else None
            )
            evidence = tuple(self._pending_evidence())
            reaped = self.mempool.reap(self.block_max_bytes())
            # One trace from the submitting request down to the DAH root:
            # the block adopts the first reaped tx's trace (threaded
            # explicitly through the mempool entry, trace/context.py).
            block_ctx = self._block_trace_context(reaped, height)
            with use_context(block_ctx), trace_span(
                "block_propose", layer="consensus", e2e="propose",
                height=height, n_txs=len(reaped),
            ):
                data = self.app.prepare_proposal(reaped)
                if not self.app.process_proposal(data):
                    raise AssertionError("node rejected its own proposal")
            # Votes commit to block_id(data root, prev app hash, time): a
            # peer whose state diverged computes a DIFFERENT id, so its
            # prevote misses this vote set and divergence blocks quorum
            # BEFORE anyone commits.
            bid = block_id(data.hash, prev_app_hash, time_ns)
            # Phase 1: prevotes (peers validate, nobody commits yet).
            # The node's own vote is best-effort like any peer's: a genesis
            # whose consensus pubkey differs from this node's signing key
            # (custom valsets) must not wedge production — quorum gates
            # decide, and a solo node commits regardless.
            prevotes = VoteSet(self.chain_id, height, PREVOTE, bid, validators)
            try:
                prevotes.add(self._sign_vote(height, PREVOTE, bid))
            except ConsensusError:
                pass
        # Unreachable or refusing peers are tolerated — BFT advances as
        # long as +2/3 answers; they catch up from the block store later.
        with use_context(block_ctx), trace_span(
            "block_prevotes", layer="consensus", e2e="prevote", height=height,
        ) as sp:
            for peer in peers:
                try:
                    reply = peer.propose(height, time_ns, data)
                    vote = Vote.unmarshal(bytes.fromhex(reply["prevote"]))
                    self._witness_vote(vote, validators)
                    prevotes.add(vote)
                except Exception:
                    continue
            sp["power"] = prevotes.signed_power()
            sp["total_power"] = prevotes.total_power()
        # Quorum is enforced when replicating to peers; a solo dev node
        # (one process, however many genesis validators) commits alone.
        if peers and not prevotes.has_two_thirds():
            raise ConsensusError(
                f"no +2/3 prevotes at height {height}: "
                f"{prevotes.signed_power()}/{prevotes.total_power()}"
            )
        prevotes_wire = [v.marshal().hex() for v in prevotes.votes.values()]

        # Phase 2: precommits — still no state committed anywhere.
        precommits = VoteSet(self.chain_id, height, PRECOMMIT, bid, validators)
        with use_context(block_ctx), trace_span(
            "block_precommits", layer="consensus", e2e="precommit",
            height=height,
        ) as sp:
            try:
                precommits.add(self._sign_vote(height, PRECOMMIT, bid))
            except ConsensusError:
                pass
            for peer in peers:
                try:
                    reply = peer.precommit(height, bid, prevotes_wire)
                    vote = Vote.unmarshal(bytes.fromhex(reply["precommit"]))
                    self._witness_vote(vote, validators)
                    precommits.add(vote)
                except Exception:
                    continue
            sp["power"] = precommits.signed_power()
            sp["total_power"] = precommits.total_power()
        if peers and not precommits.has_two_thirds():
            raise ConsensusError(
                f"no +2/3 precommits at height {height}: "
                f"{precommits.signed_power()}/{precommits.total_power()}"
            )
        commit = Commit(
            height, bid, tuple(precommits.votes.values()), data.hash,
            prev_app_hash, time_ns=time_ns,
        )

        # Phase 3: the commit is decided — apply everywhere, carrying the
        # Commit record so every node serves it.
        signers_wire = sorted(last_signers) if last_signers is not None else None
        evidence_wire = self._evidence_to_wire(evidence)
        with self.lock, use_context(block_ctx), trace_span(
            "block_commit", layer="consensus", e2e="commit", height=height,
        ):
            results = self._commit_block_data(
                data, time_ns, last_commit_signers=last_signers, evidence=evidence
            )
            own_app_hash = self.app.cms.last_app_hash
            self._commits[height] = commit
        commit_wire = commit.to_json()
        for peer in peers:
            try:
                reply = peer.finalize_commit(
                    height, time_ns, data, commit_wire,
                    last_commit_signers=signers_wire, evidence=evidence_wire,
                )
            except Exception:
                continue  # down peer: catch-up recovers it later
            if (
                bytes.fromhex(reply["app_hash"]) != own_app_hash
                or bytes.fromhex(reply["data_hash"]) != data.hash
            ):
                # Divergence is never tolerated: identical inputs MUST land
                # on identical state (the determinism contract).
                raise ReplicationDivergence(
                    f"peer {peer.url} diverged at height {height}: "
                    f"{reply['app_hash'][:16]} != {own_app_hash.hex()[:16]}"
                )
        return data, results

    def apply_block(
        self,
        height: int,
        time_ns: int,
        data: BlockData,
        last_commit_signers: set[str] | None = None,
        evidence: tuple = (),
    ) -> dict:
        """Peer endpoint: validate + execute a replicated proposal (with
        the proposer's LastCommitInfo/evidence so slashing state matches).

        A peer that missed blocks (e.g. it was still starting when the
        proposer advanced) first catches up from whoever serves them, so a
        transient replication failure cannot wedge the devnet permanently.
        """
        with self.lock:
            behind = height > self.app.height + 1
        if behind:
            self._catch_up(height - 1)
        with self.lock:
            if height != self.app.height + 1:
                raise ValueError(
                    f"out-of-order block {height}, at {self.app.height}"
                )
            if not self.app.process_proposal(data):
                raise ValueError(f"proposal rejected at height {height}")
            self._commit_block_data(
                data, time_ns,
                last_commit_signers=last_commit_signers, evidence=evidence,
            )
            return {
                "app_hash": self.app.cms.last_app_hash.hex(),
                "data_hash": data.hash.hex(),
            }

    @staticmethod
    def _parse_evidence(pairs: list) -> tuple:
        from celestia_app_tpu.consensus.votes import Equivocation, Vote

        return tuple(
            Equivocation(
                Vote.unmarshal(bytes.fromhex(a)), Vote.unmarshal(bytes.fromhex(b))
            )
            for a, b in pairs
        )

    @staticmethod
    def _evidence_to_wire(evidence: tuple) -> list:
        """Inverse of _parse_evidence — the single definition of the
        evidence wire shape (shipped in finalize_commit AND served to
        catch-up peers; the two must never drift)."""
        return [
            [ev.vote_a.marshal().hex(), ev.vote_b.marshal().hex()]
            for ev in evidence
        ]

    def _catch_up(self, upto: int) -> None:
        """Fetch + apply committed blocks up to `upto` from any peer."""
        while True:
            with self.lock:
                h = self.app.height + 1
            if h > upto:
                return
            for peer in self.peers():
                # Fetch the block AND its Commit record from the same peer
                # BEFORE applying anything: if this node later PROPOSES, it
                # derives LastCommitInfo from records, and peers cross-check
                # the shipped signer set against their own verified records
                # — advancing without the record risks proposing with
                # LastCommitInfo=None while peers derive the real signer
                # set, a guaranteed app-hash divergence.  A transient fetch
                # failure moves on to the next peer like any other.
                try:
                    b = peer.block(h)
                    rec = peer.commit(h)  # parsed Commit, or None
                except Exception:
                    continue
                if rec is None:
                    # This peer applied the block but never held the round's
                    # record (it state-synced past it); ask the others.
                    for other in self.peers():
                        if other is peer:
                            continue
                        try:
                            rec = other.commit(h)
                        except Exception:
                            continue
                        if rec is not None:
                            break
                data = BlockData(
                    txs=tuple(bytes.fromhex(t) for t in b["txs"]),
                    square_size=b["square_size"],
                    hash=bytes.fromhex(b["data_hash"]),
                )
                signers = b.get("last_commit_signers")
                self.apply_block(
                    h, b["time_ns"], data,
                    last_commit_signers=set(signers) if signers is not None else None,
                    evidence=self._parse_evidence(b.get("evidence") or []),
                )
                if rec is not None:
                    with self.lock:
                        self._commits[h] = rec
                break
            else:
                raise ValueError(f"cannot catch up: no peer serves block {h}")

    # --- /healthz layer snapshot ---------------------------------------------
    def health_snapshot(self) -> dict:
        """Per-layer staleness for /healthz (trace/exposition.py): last
        block height and wall-clock age, mempool depth, peer count, and
        (when gossip consensus runs) the live round coordinates.

        The probe must never hang behind block production — a cold jit
        compile can hold the node lock for tens of seconds, which is
        exactly when an orchestrator most needs the probe to answer — so
        the lock is taken with a short timeout and contention itself
        becomes the report (best-effort unlocked reads are safe: ints and
        container sizes, no invariants).

        `last_square` (height, k, occupancy of the most recent square
        build/construct, from trace/square_journal.py) distinguishes a
        node stuck producing empty blocks (height advances, occupancy
        pinned at 0) from a healthy idle one (no recent square at all, or
        mempool empty).  Process-level, like the metrics registry: in a
        multi-node test process it reflects the last square ANY node
        built."""
        import time

        from celestia_app_tpu.trace import square_journal

        out: dict = {
            "height": self.app.height,
            "block_age_s": (
                round(time.time() - self.last_commit_walltime, 3)
                if self.last_commit_walltime is not None else None
            ),
            "mempool": {
                "txs": len(self.mempool),
                "bytes": self.mempool.size_bytes(),
            },
            "peers": len(self.peer_urls),
            "last_square": square_journal.last_square(),
            # The serve plane's cache: resident heights per tier, hit
            # ratio, last eviction — a proof plane stuck at cold (all
            # misses, nothing resident while heights commit) is one
            # probe away, byte-identical on every plane like the rest
            # of /healthz.  Always ForestCache.stats() — one source of
            # the block's shape; a never-touched cache is trivially
            # cheap to instantiate and reports its true empty state.
            "serve": self.serve_cache.stats(),
        }
        if not self.lock.acquire(timeout=0.25):
            out["lock_contended"] = True
            return out
        try:
            driver = getattr(self, "consensus_driver", None)
            if driver is not None and driver.machine is not None:
                m = driver.machine
                out["consensus"] = {
                    "height": m.height, "round": m.round, "step": m.step,
                }
        finally:
            self.lock.release()
        return out

    # --- JSON-safe RPC methods (the HTTP surface) -----------------------------
    def rpc_status(self) -> dict:
        with self.lock:
            return {
                "chain_id": self.chain_id,
                "height": self.app.height,
                "app_hash": self.app.cms.last_app_hash.hex(),
                "app_version": self.app.app_version,
                "validator_index": self.validator_index,
                "n_validators": self.n_validators,
                "max_square_size": self.app.max_effective_square_size(),
            }

    def rpc_broadcast_tx(self, tx: str, relay: bool = True) -> dict:
        """Tx submission — the trace root.  The issued trace_id is
        returned to the client and follows the tx through the mempool,
        the square build, the device dispatch, and consensus
        (GET /trace_tables/spans filters on it).  When the request
        arrived with an x-celestia-trace header the ingress has already
        ADOPTED it (do_POST) — child that context instead of re-minting,
        so a relayed submit stays one trace across nodes."""
        from celestia_app_tpu.trace.context import (
            current_context,
            new_context,
            use_context,
        )

        raw = bytes.fromhex(tx)
        parent = current_context()
        if parent is not None:
            ctx = parent.child(layer="rpc", plane="jsonrpc")
        else:
            ctx = new_context(layer="rpc", plane="jsonrpc")
        with use_context(ctx):
            res = self.broadcast(raw, relay=relay, ctx=ctx)
        return {"code": res.code, "log": res.log,
                "hash": tx_hash(raw).hex(),
                "trace_id": ctx.trace_id}

    def rpc_tx_status(self, hash: str) -> dict | None:
        with self.lock:
            st = self.tx_status(bytes.fromhex(hash))
        if st is None:
            return None
        return {"height": st[0], "code": st[1], "log": st[2]}

    def rpc_subscribe_tx(self, hash: str, timeout_s: float = 25.0) -> dict | None:
        """Long-poll subscription: block until `hash` commits (or timeout).

        The Tendermint websocket `/subscribe tm.event='Tx'` analog over
        JSON-RPC: the server parks the request on the node's commit event
        — one wakeup per block, no client-side polling. Deliberately NOT
        under self.lock (the wait would deadlock the proposer loop);
        tx_index reads are safe against concurrent commit.
        """
        timeout_s = min(float(timeout_s), 110.0)  # stay under socket timeout
        st = self.wait_tx(bytes.fromhex(hash), timeout_s)
        if st is None:
            return None
        return {"height": st[0], "code": st[1], "log": st[2]}

    def rpc_account(self, address: str) -> dict | None:
        with self.lock:
            acc = self.query_account(address)
        if acc is None:
            return None
        return {"account_number": acc.account_number, "sequence": acc.sequence}

    def rpc_block(self, height: int) -> dict:
        with self.lock:
            entry = self._blocks_by_height.get(height)
            if entry is None:
                raise ValueError(f"no block at height {height}")
            data, time_ns, signers, evidence_wire = entry
        return {
            "height": height,
            "time_ns": time_ns,
            "data_hash": data.hash.hex(),
            "square_size": data.square_size,
            "app_version": self._version_by_height.get(height, self.app.app_version),
            "txs": [t.hex() for t in data.txs],
            # x/slashing inputs: a catch-up peer must replay these exactly
            # or its app hash diverges from the nodes that were live.
            "last_commit_signers": signers,
            "evidence": evidence_wire,
            # Clients reconstructing the square (blobstream verify) need
            # the hard cap the block was BUILT under — the versioned 128
            # default, or the benchmark-manifest override if one is set.
            "square_size_upper_bound": self.app.square_size_upper_bound,
        }

    def rpc_produce_block(self) -> dict:
        data, results = self.produce_block()
        return {
            "height": self.app.height,
            "data_hash": data.hash.hex(),
            "square_size": data.square_size,
            "results": [
                {"code": r.code, "log": r.log, "gas_wanted": r.gas_wanted,
                 "gas_used": r.gas_used}
                for r in results
            ],
        }

    def rpc_apply_block(
        self, height: int, time_ns: int, data_hash: str, square_size: int,
        txs: list[str],
    ) -> dict:
        data = BlockData(
            txs=tuple(bytes.fromhex(t) for t in txs),
            square_size=square_size,
            hash=bytes.fromhex(data_hash),
        )
        return self.apply_block(height, time_ns, data)

    # --- the voting round (consensus/votes.py; scope note there) -------------
    def rpc_propose(
        self, height: int, time_ns: int, data_hash: str, square_size: int,
        txs: list[str],
    ) -> dict:
        """Phase 1: validate the proposal, answer with a signed prevote.
        No state is committed here."""
        from celestia_app_tpu.consensus import PREVOTE

        data = BlockData(
            txs=tuple(bytes.fromhex(t) for t in txs),
            square_size=square_size,
            hash=bytes.fromhex(data_hash),
        )
        with self.lock:
            behind = height > self.app.height + 1
        if behind:
            self._catch_up(height - 1)
        from celestia_app_tpu.consensus import block_id

        with self.lock:
            if height != self.app.height + 1:
                raise ValueError(
                    f"cannot prevote height {height}, at {self.app.height}"
                )
            if not self.app.process_proposal(data):
                raise ValueError(f"proposal rejected at height {height}")
            # Computed over THIS node's app hash: divergence yields a
            # different block id, and the prevote simply won't count.
            bid = block_id(data.hash, self.app.cms.last_app_hash, time_ns)
            prevote = self._sign_vote(height, PREVOTE, bid)
            self._prevoted[height] = bid
        return {"prevote": prevote.marshal().hex()}

    def rpc_precommit(
        self, height: int, data_hash: str, prevotes: list[str]
    ) -> dict:
        """Phase 2: shown a +2/3 prevote set for the block this node
        prevoted, sign a precommit.  NO state is committed here — both
        quorum gates precede any application (Tendermint's ordering)."""
        from celestia_app_tpu.consensus import (
            PRECOMMIT,
            PREVOTE,
            ConsensusError,
            Vote,
            VoteSet,
        )

        block_hash = bytes.fromhex(data_hash)
        with self.lock:
            if self._prevoted.get(height) != block_hash:
                raise ConsensusError(
                    f"will not precommit height {height}: not the block "
                    "this node prevoted"
                )
            vote_set = VoteSet(
                self.chain_id, height, PREVOTE, block_hash, self._validator_set()
            )
        for raw in prevotes:
            vote_set.add(Vote.unmarshal(bytes.fromhex(raw)))
        if not vote_set.has_two_thirds():
            raise ConsensusError(
                f"precommit without +2/3 prevotes at height {height}: "
                f"{vote_set.signed_power()}/{vote_set.total_power()}"
            )
        with self.lock:
            precommit = self._sign_vote(height, PRECOMMIT, block_hash)
        return {"precommit": precommit.marshal().hex()}

    def rpc_finalize_commit(
        self, height: int, time_ns: int, data_hash: str, square_size: int,
        txs: list[str], commit: dict,
        last_commit_signers: list[str] | None = None,
        evidence: list | None = None,
    ) -> dict:
        """Phase 3: the round is decided — verify the Commit record
        (+2/3 precommits), apply the block (with the proposer's
        LastCommitInfo + evidence), and keep the record so this node
        serves it too."""
        from celestia_app_tpu.consensus import Commit, ConsensusError, verify_commit

        data = BlockData(
            txs=tuple(bytes.fromhex(t) for t in txs),
            square_size=square_size,
            hash=bytes.fromhex(data_hash),
        )
        record = Commit.from_json(commit)
        with self.lock:
            validators = self._validator_set()
            prev_record = self._commits.get(height - 1)
        if (
            record.height != height
            or record.data_root != data.hash
            or not verify_commit(validators, self.chain_id, record)
        ):
            raise ConsensusError(f"invalid commit record for height {height}")
        signers = set(last_commit_signers) if last_commit_signers is not None else None
        if prev_record is not None:
            # The slashing liveness input is NOT taken on the proposer's
            # word: this node verified height-1's Commit itself, so the
            # signer set must match it exactly — a proposer lying about who
            # signed could otherwise jail an honest validator everywhere.
            expected = {v.validator for v in prev_record.precommits}
            if signers is not None and signers != expected:
                raise ConsensusError(
                    f"last_commit_signers mismatch at height {height}: "
                    f"proposer says {sorted(signers)}, verified commit says "
                    f"{sorted(expected)}"
                )
            signers = expected
        reply = self.apply_block(
            height, time_ns, data,
            last_commit_signers=signers,
            evidence=self._parse_evidence(evidence or []),
        )
        with self.lock:
            self._commits[height] = record
        return reply

    def rpc_commit(self, height: int) -> dict | None:
        """The Commit record (+2/3 precommits) for a height, if this node
        drove or learned that round — what a light client verifies."""
        with self.lock:
            commit = self._commits.get(height)
        return None if commit is None else commit.to_json()

    # --- gossip consensus (rpc/gossip.py) ------------------------------------
    def enable_gossip_consensus(
        self, timeouts=None, interval_s: float = 0.2,
        latency_s: float = 0.0, jitter_s: float = 0.0,
        wal_path: str | None = None,
    ):
        """Attach a ConsensusDriver (multi-round Tendermint machine over
        p2p flood gossip).  Call driver.start() once peers are serving.
        latency_s/jitter_s inject per-send delay (chaos tier); wal_path
        enables the double-sign WAL (consensus/wal.py)."""
        from celestia_app_tpu.rpc.gossip import ConsensusDriver

        self.consensus_driver = ConsensusDriver(
            self, timeouts=timeouts, interval_s=interval_s,
            latency_s=latency_s, jitter_s=jitter_s, wal_path=wal_path,
        )
        # The shared gossip pool may already exist (a broadcast before this
        # call) sized without knowledge of chaos latency; injected sleeps
        # would then serialize a block's worth of sends behind 8 parked
        # workers.  Drop it so the next access re-sizes for the driver.
        pool = getattr(self, "_gossip_pool", None)
        if pool is not None and (latency_s or jitter_s):
            pool.shutdown(wait=True, cancel_futures=False)
            self._gossip_pool = None
        return self.consensus_driver

    def rpc_consensus(self, msg: dict) -> dict:
        driver = getattr(self, "consensus_driver", None)
        if driver is None:
            raise ValueError("gossip consensus is not enabled on this node")
        return driver.handle(msg)

    def rpc_consensus_state(self) -> dict:
        """Round-machine introspection (the consensus reactor's dump_state
        analog): current height/round/step, tallies, backlog depth."""
        driver = getattr(self, "consensus_driver", None)
        if driver is None:
            return {"enabled": False}
        with self.lock:
            m = driver.machine
            out = {
                "enabled": True,
                "app_height": self.app.height,
                "backlog": len(driver.backlog),
                "machine": None,
            }
            if m is not None:
                out["machine"] = {
                    "height": m.height,
                    "round": m.round,
                    "step": m.step,
                    "locked_round": m.locked_round,
                    "proposer": m.proposer(m.round),
                    "my_address": m.my_address,
                    "proposals": sorted(m.proposals),
                    "prevote_power": {
                        r: t.power_any() for r, t in m.prevotes.items()
                    },
                    "precommit_power": {
                        r: t.power_any() for r, t in m.precommits.items()
                    },
                }
            return out

    # --- state-sync serving ---------------------------------------------------
    def rpc_snapshots(self) -> list[dict]:
        """Available snapshot metadata (newest last), chunks elided."""
        with self.lock:
            return [
                {k: v for k, v in snap.items() if k != "chunks"}
                for _, snap in sorted(self._snapshots.items())
            ]

    def rpc_snapshot_chunk(self, height: int, index: int) -> str:
        with self.lock:
            snap = self._snapshots.get(height)
            if snap is None:
                raise ValueError(f"no snapshot at height {height}")
            return snap["chunks"][index].hex()

    def state_sync_from(
        self, peer_url: str, trusted_validators: dict | None = None
    ) -> int:
        """Join the chain from a peer's snapshot instead of replaying every
        block (the reference's state sync): fetch + hash-verify chunks,
        restore into a STAGING store, recompute the app hash from the
        restored data, verify the NEXT height's Commit — its precommits
        sign block_id(data_root, prev_app_hash), so +2/3 of the validator
        power attests exactly the app hash we restored — and only then
        swap the state in and catch up the tail.  Returns the height
        joined at.

        Trust root: the commit is checked against `trusted_validators`
        (address -> (PublicKey, power)) or, by default, this node's OWN
        pre-sync validator set and chain id (its genesis) — never against
        anything the untrusted snapshot carries.  If the real valset has
        drifted past the joiner's genesis, the operator supplies the
        trusted set explicitly (Tendermint state sync's light-block trust
        assumption)."""
        import hashlib

        from celestia_app_tpu.consensus import ConsensusError, verify_commit
        from celestia_app_tpu.rpc.client import RemoteNode
        from celestia_app_tpu.state.store import CommitStore

        with self.lock:
            trusted = trusted_validators or self._validator_set()
            trusted_chain_id = self.chain_id
        peer = RemoteNode(peer_url, timeout=30.0, defer_status=True)
        metas = peer.snapshots()
        if not metas:
            raise ValueError(f"peer {peer_url} serves no snapshots")
        meta = metas[-1]
        height = meta["height"]
        if meta["chain_id"] != trusted_chain_id:
            raise ConsensusError(
                f"snapshot is for chain {meta['chain_id']!r}, "
                f"this node trusts {trusted_chain_id!r}"
            )
        blob = b""
        for i, want in enumerate(meta["chunk_hashes"]):
            chunk = bytes.fromhex(peer.snapshot_chunk(height, i))
            if hashlib.sha256(chunk).hexdigest() != want:
                raise ValueError(f"snapshot chunk {i} hash mismatch")
            blob += chunk
        state = {
            bytes.fromhex(k): bytes.fromhex(v) for k, v in json.loads(blob).items()
        }
        # Staging: nothing touches self.app until every check passes.
        cms = CommitStore()
        cms._committed[height] = state
        cms.load_height(height)  # recomputes the root from the data
        if cms.last_app_hash.hex() != meta["app_hash"]:
            raise ValueError("restored state does not match snapshot app hash")
        # Trust link: the next height's commit must attest this app hash,
        # signed by the TRUSTED validator set.
        commit = peer.commit(height + 1)
        if commit is None or commit.prev_app_hash != cms.last_app_hash:
            raise ConsensusError(
                f"commit at height {height + 1} does not attest the restored "
                "app hash"
            )
        if not verify_commit(trusted, trusted_chain_id, commit):
            raise ConsensusError(f"invalid commit at height {height + 1}")
        with self.lock:
            self.app.cms = cms
            self.app.height = height
            self.app.app_version = meta["app_version"]
            self.app.chain_id = meta["chain_id"]
            self.app.genesis_time_ns = meta["genesis_time_ns"]
            self.app.last_block_time_ns = meta["block_time_ns"]
            self.app._check_state = None
        if not self.peer_urls:
            self.peer_urls = [peer_url]
            self._peers = []
        self._catch_up(peer.status()["height"])
        return height

    def rpc_tx_inclusion_proof(self, height: int, tx_index: int) -> dict:
        from celestia_app_tpu.proof.querier import query_tx_inclusion_proof

        with self.lock:
            block = self.rpc_block(height)
            max_k = self.app.max_effective_square_size()
        proof = query_tx_inclusion_proof(
            [bytes.fromhex(t) for t in block["txs"]], tx_index, max_k
        )
        return {"proof": to_jsonable(proof), "data_root": block["data_hash"]}

    def rpc_share_inclusion_proof(self, height: int, start: int, end: int) -> dict:
        from celestia_app_tpu.proof.querier import query_share_inclusion_proof

        with self.lock:
            block = self.rpc_block(height)
            max_k = self.app.max_effective_square_size()
        proof = query_share_inclusion_proof(
            [bytes.fromhex(t) for t in block["txs"]], start, end, max_k
        )
        return {"proof": to_jsonable(proof), "data_root": block["data_hash"]}

    def rpc_state_proof(self, key: str) -> dict:
        with self.lock:
            proof = self.app.cms.proof(bytes.fromhex(key))
            app_hash = self.app.cms.last_app_hash
        return {"proof": to_jsonable(proof), "app_hash": app_hash.hex()}

    def rpc_validators(self) -> list[dict]:
        from celestia_app_tpu.state.staking import StakingKeeper

        with self.lock:
            vals = StakingKeeper(self.app.cms.working).validators()
        return [{"address": v.address, "power": v.power} for v in vals]

    # --- blobstream relayer surface -----------------------------------------
    # The query endpoints a BlobstreamX relayer consumes (reference
    # x/blobstream/keeper/query_*.go served over gRPC, plus celestia-core's
    # DataCommitment / DataRootInclusionProof RPCs used by client/verify.go).
    def _blobstream_keeper(self):
        from celestia_app_tpu.modules.blobstream.keeper import BlobstreamKeeper
        from celestia_app_tpu.state.staking import StakingKeeper

        store = self.app.cms.working
        return BlobstreamKeeper(store, StakingKeeper(store))

    @staticmethod
    def _attestation_dict(att) -> dict:
        from celestia_app_tpu.modules.blobstream.keeper import DataCommitment, Valset

        if isinstance(att, Valset):
            return {
                "kind": "valset",
                "nonce": att.nonce,
                "height": att.height,
                "time_ns": att.time_ns,
                "members": [
                    {"address": m.address, "power": m.power} for m in att.members
                ],
            }
        assert isinstance(att, DataCommitment)
        return {
            "kind": "data_commitment",
            "nonce": att.nonce,
            "begin_block": att.begin_block,
            "end_block": att.end_block,
            "height": att.height,
            "time_ns": att.time_ns,
        }

    def rpc_blobstream_attestation(self, nonce: int) -> dict | None:
        """QueryAttestationRequestByNonce."""
        with self.lock:
            att = self._blobstream_keeper().get_attestation(nonce)
        return None if att is None else self._attestation_dict(att)

    def rpc_blobstream_nonces(self) -> dict:
        """LatestAttestationNonce + EarliestAttestationNonce."""
        with self.lock:
            k = self._blobstream_keeper()
            latest = k.latest_nonce()
            try:
                earliest = k.earliest_available_nonce()
            except KeyError:
                earliest = 0
        return {"latest": latest, "earliest": earliest}

    def rpc_data_commitment_range(self, height: int) -> dict:
        """DataCommitmentRangeForHeight (query_data_commitment.go:10-19)."""
        with self.lock:
            att = self._blobstream_keeper().data_commitment_for_height(height)
        return self._attestation_dict(att)

    def rpc_latest_data_commitment(self) -> dict | None:
        """LatestDataCommitment (query_data_commitment.go:21-32)."""
        with self.lock:
            try:
                att = self._blobstream_keeper().latest_data_commitment()
            except KeyError:
                return None
        return self._attestation_dict(att)

    def rpc_latest_valset_before(self, nonce: int) -> dict:
        """LatestValsetRequestBeforeNonce (query_valset.go:12-22)."""
        with self.lock:
            vs = self._blobstream_keeper().latest_valset_before_nonce(nonce)
        return self._attestation_dict(vs)

    def _window_data_roots(self, begin: int, end: int) -> list[tuple[int, bytes]]:
        """(height, data_root) for each height in [begin, end)."""
        out = []
        for h in range(begin, end):
            entry = self._blocks_by_height.get(h)
            if entry is None:
                raise ValueError(f"no block at height {h} (window [{begin},{end}))")
            out.append((h, entry[0].hash))
        return out

    def rpc_data_commitment(self, begin: int, end: int) -> str:
        """Tuple root over [begin, end) — celestia-core's DataCommitment RPC,
        the root the relayer submits to the Blobstream contract."""
        from celestia_app_tpu.modules.blobstream.keeper import data_commitment_root

        with self.lock:
            roots = self._window_data_roots(begin, end)
        return data_commitment_root(roots).hex()

    def rpc_data_root_inclusion_proof(self, height: int, begin: int, end: int) -> dict:
        """Binary-merkle proof of (height, dataRoot) inside the window's
        tuple root — celestia-core's DataRootInclusionProof RPC
        (consumed at client/verify.go:288)."""
        from celestia_app_tpu.modules.blobstream.keeper import (
            data_root_inclusion_proof,
        )

        with self.lock:
            roots = self._window_data_roots(begin, end)
        index, total, path = data_root_inclusion_proof(roots, height)
        return {
            "index": index,
            "total": total,
            "path": [p.hex() for p in path],
        }


def _method_table(node: ServingNode) -> dict:
    return {
        name[len("rpc_"):]: getattr(node, name)
        for name in dir(node)
        if name.startswith("rpc_")
    }


class _Handler(BaseHTTPRequestHandler):
    methods: dict = {}
    node_id: str | None = None  # per-server identity (multi-node tests)

    def log_message(self, fmt, *args):  # quiet: tests parse stdout
        pass

    def do_GET(self):
        """GET /metrics + /trace_tables[/<name>] + /healthz: the shared
        observability surface (trace/exposition.py — the Tendermint
        instrumentation analog, test/e2e/testnet/setup.go:24, and the
        pkg/trace table puller, node.go:52-74).  All three serving planes
        mount the same handler, so the exposition is byte-identical.
        An `x-celestia-trace` header is ADOPTED (same trace_id, fresh
        span_id, this node's node_id) so remote DAS fetches stitch."""
        from celestia_app_tpu.trace.exposition import (
            handle_observability_get_adopted,
            send_observability_404,
            send_observability_response,
        )

        resp = handle_observability_get_adopted(
            self, plane="jsonrpc", node_id=self.node_id
        )
        if resp is None:
            send_observability_404(self)
            return
        send_observability_response(self, resp)

    def do_POST(self):
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length))
            method = self.methods.get(req.get("method", ""))
            if method is None:
                raise ValueError(f"unknown method {req.get('method')!r}")
            # Chaos rpc.handle seam: an injected stall models a slow
            # ingress; an injected failure surfaces as a normal RPC error
            # (clients and the gossip retry paths must absorb both).
            from celestia_app_tpu import chaos

            chaos.rpc_handle()
            # Cross-node propagation: a request carrying the peer's
            # x-celestia-trace header runs under an ADOPTED context —
            # same trace_id, fresh span_id, this node's node_id — so the
            # method's own spans (broadcast_tx's mempool submit, the
            # consensus hand-off) join the caller's trace instead of
            # starting a new one.
            from celestia_app_tpu.trace.context import (
                TRACE_HEADER,
                adopt_context,
                use_context,
            )

            ctx = adopt_context(
                self.headers.get(TRACE_HEADER),
                **({"node_id": self.node_id} if self.node_id else {}),
            )
            if ctx is not None:
                with use_context(ctx):
                    result = method(**req.get("params", {}))
            else:
                result = method(**req.get("params", {}))
            body = {"jsonrpc": "2.0", "id": req.get("id"), "result": result}
            status = 200
        except Exception as e:  # noqa: BLE001 — every fault becomes an RPC error
            from celestia_app_tpu.qos import (
                QosThrottled,
                retry_after_header,
                throttle_body,
            )

            if isinstance(e, QosThrottled):
                # Per-tenant QoS refusal: HTTP 429 carrying qos.py's ONE
                # canonical payload (the /das route discipline — the REST
                # twin serves the very same bytes, the gRPC plane the same
                # string as its RESOURCE_EXHAUSTED detail).
                payload = throttle_body(e)
                self.send_response(429)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.send_header("Retry-After", retry_after_header(e))
                self.end_headers()
                self.wfile.write(payload)
                return
            body = {
                "jsonrpc": "2.0",
                "id": None,
                "error": {"code": -32000, "message": f"{type(e).__name__}: {e}"},
            }
            status = 500
        payload = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


class NodeServer:
    """Owns the HTTP server + optional proposer-loop thread."""

    def __init__(
        self,
        node: ServingNode,
        host: str = "127.0.0.1",
        port: int = 0,
        node_id: str | None = None,
    ):
        # node_id overrides the process-wide identity for this server's
        # adopted spans — N in-process NodeServers (the standard test
        # topology) then stitch as N distinct nodes under one trace_id.
        handler = type(
            "BoundHandler",
            (_Handler,),
            {"methods": _method_table(node), "node_id": node_id},
        )
        self.node = node
        self.node_id = node_id
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        # One stable bound-method object: unregistration compares by
        # identity, and attribute access mints a fresh bound method.  The
        # name carries the port so a multi-node process (the standard
        # multi-validator test topology) reports every node, not just the
        # last one constructed.
        self._health_provider = getattr(node, "health_snapshot", None)
        self._health_name = f"node:{self.port}"
        if self._health_provider is not None:
            from celestia_app_tpu.trace.exposition import register_health_provider

            register_health_provider(self._health_name, self._health_provider)
        # Mount the node's DAS surface behind GET /das/* on every plane
        # (the shared handler; last-registered node answers).
        self._das_provider = None
        self._healer = None
        if hasattr(node, "das_provider"):
            from celestia_app_tpu.trace.exposition import register_das_provider

            self._das_provider = node.das_provider()
            register_das_provider(self._das_provider)
            # $CELESTIA_HEAL=1: close the detect->repair->re-serve loop
            # (serve/heal.py) — detections on this node's sampler trigger
            # batched repair + root-verified re-admission on a worker
            # thread instead of ending at a 410/502.
            from celestia_app_tpu.serve import heal

            if heal.heal_enabled():
                self._healer = heal.HealingEngine(
                    self._das_provider, name=f"node:{self.port}"
                ).start()

    def start(self, block_interval_s: float | None = None):
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        if block_interval_s is not None:
            p = threading.Thread(
                target=self._proposer_loop, args=(block_interval_s,), daemon=True
            )
            p.start()
            self._threads.append(p)
        return self

    def _proposer_loop(self, interval_s: float):
        while not self._stop.wait(interval_s):
            try:
                if self.node.is_proposer(self.node.app.height + 1):
                    self.node.produce_block()
            except Exception as e:  # noqa: BLE001
                import sys

                print(f"proposer loop error: {e}", file=sys.stderr)

    def stop(self):
        self._stop.set()
        if self._health_provider is not None:
            from celestia_app_tpu.trace.exposition import unregister_health_provider

            unregister_health_provider(self._health_name, self._health_provider)
        if self._healer is not None:
            self._healer.close()
        if self._das_provider is not None:
            from celestia_app_tpu.trace.exposition import unregister_das_provider

            unregister_das_provider(self._das_provider)
        driver = getattr(self.node, "consensus_driver", None)
        if driver is not None:
            driver.stop()
        self.httpd.shutdown()
        self.httpd.server_close()
        self.node.shutdown_gossip()


def serve(
    node: ServingNode,
    host: str = "127.0.0.1",
    port: int = 0,
    block_interval_s: float | None = 0.2,
) -> NodeServer:
    """Start serving `node`; returns the running NodeServer (daemon threads)."""
    return NodeServer(node, host, port).start(block_interval_s)

"""Multi-process devnet: N validators exchanging proposals over sockets.

The reference's testnode Network starts real nodes with RPC/gRPC servers on
random ports (test/util/testnode/network.go:20-43); its multi-validator
tier runs containers. This devnet is the socket tier for this framework:
each validator is its OWN PROCESS serving JSON-RPC, block production
rotates by height, and every block is replicated over HTTP with app-hash /
data-root equality enforced (ReplicationDivergence otherwise).

Run one validator:   python -m celestia_app_tpu.rpc.devnet --index 0 --n 3 \
                        --base-port 26800 [--block-interval-ms 300]
Spawn a whole devnet in-code (tests): `spawn_devnet(n=3)`.

All validators derive the identical deterministic genesis from the shared
seed set (testutil.testnode.deterministic_genesis), so chain state agrees
from height 0 without any genesis-distribution step.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
import time

from celestia_app_tpu.rpc.client import RemoteNode
from celestia_app_tpu.rpc.server import ServingNode, serve
from celestia_app_tpu.testutil.testnode import deterministic_genesis, funded_keys


def _url(base_port: int, i: int) -> str:
    return f"http://127.0.0.1:{base_port + i}"


def run_validator(
    index: int,
    n: int,
    base_port: int,
    block_interval_ms: int = 300,
    n_accounts: int = 4,
    mode: str = "gossip",
    peer_indices: list[int] | None = None,
    wal_dir: str | None = None,
) -> None:
    """Serve validator `index` of `n`; blocks until killed.

    mode="gossip" (default): the multi-round Tendermint machine over p2p
    flood gossip (rpc/gossip.py) — survives proposer crashes via round
    changes.  mode="push": the legacy proposer-push round (one round per
    height, the round-1/2 plane).  `peer_indices` restricts this node's
    peer list (partial topologies, e.g. a ring, to exercise multi-hop
    relay); default is fully connected.  `wal_dir` enables the
    double-sign WAL (one file per validator index).
    """
    keys = funded_keys(n_accounts)
    if peer_indices is None:
        peer_indices = [j for j in range(n) if j != index]
    node = ServingNode(
        genesis=deterministic_genesis(keys, n_validators=n),
        keys=keys,
        validator_index=index,
        n_validators=n,
        peers=[_url(base_port, j) for j in peer_indices],
    )
    driver = None
    if mode == "gossip":
        import os as _os

        driver = node.enable_gossip_consensus(
            interval_s=block_interval_ms / 1000.0,
            wal_path=(
                _os.path.join(wal_dir, f"wal-{index}.jsonl")
                if wal_dir else None
            ),
        )
    server = serve(node, port=base_port + index, block_interval_s=None)
    print(f"validator {index}/{n} serving on {server.url} ({mode})", flush=True)

    # AOT warmup BEFORE consensus starts (SURVEY §7 hard part 4: compiles
    # must never sit on the block path — a first-block compile under the
    # node lock stalls every round timeout).  Small sizes cover empty/
    # near-empty devnet blocks; bigger squares hit the persistent compile
    # cache (see spawn_devnet's JAX_COMPILATION_CACHE_DIR).
    from celestia_app_tpu.da.eds import warmup

    warmup([1, 2, 4])
    print(f"validator {index} warmed", flush=True)

    # Startup barrier: wait for every peer to serve before proposing.
    for peer_url in node.peer_urls:
        peer = RemoteNode(peer_url, defer_status=True, timeout=2.0)
        deadline = time.monotonic() + 60
        while True:
            try:
                peer.status()
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"peer {peer_url} never came up")
                time.sleep(0.1)
    print(f"validator {index} peers up", flush=True)

    if driver is not None:
        driver.start()
        while True:
            time.sleep(60)  # the driver's timers run the chain

    interval = block_interval_ms / 1000.0
    while True:
        time.sleep(interval)
        try:
            if node.is_proposer(node.app.height + 1):
                node.produce_block()
        except Exception as e:  # noqa: BLE001 — keep serving; surface the fault
            print(f"validator {index} produce error: {e}", file=sys.stderr, flush=True)


class Devnet:
    """Handle to spawned validator processes."""

    def __init__(self, procs: list[subprocess.Popen], urls: list[str]):
        self.procs = procs
        self.urls = urls

    def client(self, i: int = 0) -> RemoteNode:
        return RemoteNode(self.urls[i])

    def stop(self) -> None:
        for p in self.procs:
            p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def spawn_devnet(
    n: int = 3,
    base_port: int = 26800,
    block_interval_ms: int = 300,
    wait_s: float = 120.0,
    env: dict | None = None,
    mode: str = "gossip",
    topology: dict[int, list[int]] | None = None,
) -> Devnet:
    """Launch n validator processes; returns once all serve their RPC.

    `topology` maps validator index -> peer indices (partial meshes, e.g.
    a ring for multi-hop relay tests); default fully connected.
    """
    import os

    procs = []
    child_env = dict(os.environ if env is None else env)
    # Compiles amortize across validator processes and runs; without this
    # every child pays its own first-block jit compile under the node lock.
    child_env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/celestia_jax_cache")
    # Pre-warm the persistent cache ONCE before spawning: n validators
    # compiling the same pipelines concurrently on a small host serializes
    # onto the cores and multiplies the startup time by n; after this
    # one-shot, every child's own warmup is a fast cache deserialization.
    subprocess.run(
        [sys.executable, "-c",
         "from celestia_app_tpu.da.eds import warmup; warmup([1, 2, 4])"],
        env=child_env, timeout=600,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        check=False,
    )
    for i in range(n):
        cmd = [
            sys.executable, "-m", "celestia_app_tpu.rpc.devnet",
            "--index", str(i), "--n", str(n),
            "--base-port", str(base_port),
            "--block-interval-ms", str(block_interval_ms),
            "--mode", mode,
        ]
        if topology is not None:
            cmd += ["--peers", ",".join(str(j) for j in topology[i])]
        procs.append(
            subprocess.Popen(
                cmd,
                env=child_env,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
        )
    urls = [_url(base_port, i) for i in range(n)]
    net = Devnet(procs, urls)
    deadline = time.monotonic() + wait_s
    try:
        for u in urls:
            peer = RemoteNode(u, defer_status=True, timeout=2.0)
            while True:
                try:
                    peer.status()
                    break
                except Exception:
                    if time.monotonic() > deadline:
                        raise TimeoutError(f"validator at {u} never served")
                    time.sleep(0.2)
    except Exception:
        net.stop()
        raise
    return net


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="celestia-tpu devnet validator")
    ap.add_argument("--index", type=int, required=True)
    ap.add_argument("--n", type=int, required=True)
    ap.add_argument("--base-port", type=int, default=26800)
    ap.add_argument("--block-interval-ms", type=int, default=300)
    ap.add_argument("--mode", choices=["gossip", "push"], default="gossip")
    ap.add_argument("--peers", default=None,
                    help="comma-separated peer indices (default: all others)")
    ap.add_argument("--wal-dir", default=None,
                    help="directory for the double-sign WAL (off if unset)")
    args = ap.parse_args(argv)
    peer_indices = (
        [int(x) for x in args.peers.split(",") if x != ""]
        if args.peers is not None
        else None
    )
    run_validator(
        args.index, args.n, args.base_port, args.block_interval_ms,
        mode=args.mode, peer_indices=peer_indices, wal_dir=args.wal_dir,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

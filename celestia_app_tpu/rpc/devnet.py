"""Multi-process devnet: N validators exchanging proposals over sockets.

The reference's testnode Network starts real nodes with RPC/gRPC servers on
random ports (test/util/testnode/network.go:20-43); its multi-validator
tier runs containers. This devnet is the socket tier for this framework:
each validator is its OWN PROCESS serving JSON-RPC, block production
rotates by height, and every block is replicated over HTTP with app-hash /
data-root equality enforced (ReplicationDivergence otherwise).

Run one validator:   python -m celestia_app_tpu.rpc.devnet --index 0 --n 3 \
                        --base-port 26800 [--block-interval-ms 300]
Spawn a whole devnet in-code (tests): `spawn_devnet(n=3)`.

All validators derive the identical deterministic genesis from the shared
seed set (testutil.testnode.deterministic_genesis), so chain state agrees
from height 0 without any genesis-distribution step.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
import time

from celestia_app_tpu.rpc.client import RemoteNode
from celestia_app_tpu.rpc.server import ServingNode, serve
from celestia_app_tpu.testutil.testnode import deterministic_genesis, funded_keys


def _url(base_port: int, i: int) -> str:
    return f"http://127.0.0.1:{base_port + i}"


def run_validator(
    index: int,
    n: int,
    base_port: int,
    block_interval_ms: int = 300,
    n_accounts: int = 4,
) -> None:
    """Serve validator `index` of `n`; blocks until killed."""
    keys = funded_keys(n_accounts)
    node = ServingNode(
        genesis=deterministic_genesis(keys, n_validators=n),
        keys=keys,
        validator_index=index,
        n_validators=n,
        peers=[_url(base_port, j) for j in range(n) if j != index],
    )
    server = serve(
        node, port=base_port + index, block_interval_s=None
    )
    print(f"validator {index}/{n} serving on {server.url}", flush=True)

    # Startup barrier: wait for every peer to serve before proposing.
    for peer_url in node.peer_urls:
        peer = RemoteNode(peer_url, defer_status=True, timeout=2.0)
        deadline = time.monotonic() + 60
        while True:
            try:
                peer.status()
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"peer {peer_url} never came up")
                time.sleep(0.1)
    print(f"validator {index} peers up", flush=True)

    interval = block_interval_ms / 1000.0
    while True:
        time.sleep(interval)
        try:
            if node.is_proposer(node.app.height + 1):
                node.produce_block()
        except Exception as e:  # noqa: BLE001 — keep serving; surface the fault
            print(f"validator {index} produce error: {e}", file=sys.stderr, flush=True)


class Devnet:
    """Handle to spawned validator processes."""

    def __init__(self, procs: list[subprocess.Popen], urls: list[str]):
        self.procs = procs
        self.urls = urls

    def client(self, i: int = 0) -> RemoteNode:
        return RemoteNode(self.urls[i])

    def stop(self) -> None:
        for p in self.procs:
            p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def spawn_devnet(
    n: int = 3,
    base_port: int = 26800,
    block_interval_ms: int = 300,
    wait_s: float = 120.0,
    env: dict | None = None,
) -> Devnet:
    """Launch n validator processes; returns once all serve their RPC."""
    import os

    procs = []
    child_env = dict(os.environ if env is None else env)
    for i in range(n):
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-m", "celestia_app_tpu.rpc.devnet",
                    "--index", str(i), "--n", str(n),
                    "--base-port", str(base_port),
                    "--block-interval-ms", str(block_interval_ms),
                ],
                env=child_env,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
        )
    urls = [_url(base_port, i) for i in range(n)]
    net = Devnet(procs, urls)
    deadline = time.monotonic() + wait_s
    try:
        for u in urls:
            peer = RemoteNode(u, defer_status=True, timeout=2.0)
            while True:
                try:
                    peer.status()
                    break
                except Exception:
                    if time.monotonic() > deadline:
                        raise TimeoutError(f"validator at {u} never served")
                    time.sleep(0.2)
    except Exception:
        net.stop()
        raise
    return net


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="celestia-tpu devnet validator")
    ap.add_argument("--index", type=int, required=True)
    ap.add_argument("--n", type=int, required=True)
    ap.add_argument("--base-port", type=int, default=26800)
    ap.add_argument("--block-interval-ms", type=int, default=300)
    args = ap.parse_args(argv)
    run_validator(args.index, args.n, args.base_port, args.block_interval_ms)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Crypto-free gossip transport: chaotic delivery + flood dedup identity.

The pieces of the gossip plane that do NOT need the signing stack live
here, so a slim image (and scripts/chaos_soak.py's gossip drill) can
exercise the lossy-link machinery without `cryptography`:

  * `deliver` — one peer send through the chaos `gossip.send` seam
    (injected drop / duplicate / reorder-delay) with bounded
    exponential-backoff retry, gated per peer: only a peer whose LAST
    send succeeded earns retries.  Retrying a dead or blackholed link
    would multiply its timeout cost on every message — a liveness
    regression exactly when the mesh most needs to move on — so a peer
    mid-failure-streak gets the classic single attempt.
  * `msg_id` — the flood-termination dedup key (rpc/gossip.ConsensusDriver
    delegates here).  The proposal PAYLOAD is part of the identity: the
    proposal signature does not cover the block bytes (the signed block
    id does, indirectly), so without it a tampered relay copy would
    dedup-block the genuine message mesh-wide and censor an honest
    proposal.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time


def msg_id(msg: dict) -> tuple:
    if msg.get("kind") == "vote":
        return ("vote", msg.get("vote", ""))
    payload = hashlib.sha256(
        json.dumps(
            [msg.get("block"), msg.get("last_commit"), msg.get("evidence")],
            sort_keys=True, separators=(",", ":"), default=str,
        ).encode()
    ).hexdigest()
    return (
        "proposal", msg.get("height"), msg.get("round"),
        msg.get("proposer"), msg.get("block_hash"), payload,
    )


def _recoveries():
    from celestia_app_tpu.chaos.degrade import recoveries

    return recoveries()


# Injected-reorder deliveries in flight (Timer threads): tests and the
# chaos drills join them before asserting convergence.
_DELAYED_LOCK = threading.Lock()
_DELAYED: list[threading.Timer] = []


def drain_delayed(timeout_s: float = 5.0) -> None:
    """Wait out in-flight reorder-delayed deliveries (drills/shutdown)."""
    with _DELAYED_LOCK:
        timers = list(_DELAYED)
    for t in timers:
        t.join(timeout_s)
    with _DELAYED_LOCK:
        _DELAYED[:] = [t for t in _DELAYED if t.is_alive()]


def deliver(send, msg: dict, *, streak: dict, key, retries: int = 2,
            sleep=time.sleep) -> bool:
    """Send one message through the chaos seam with retry; True when it
    was delivered at least once (or handed to the chaos machinery).

    `send(msg)` performs the transport call; `streak[key]` counts the
    peer's consecutive failed sends (shared across calls so the retry
    gate sees history).  Injected DROPS return True without sending —
    they model loss PAST the send, which the receiver-side machinery
    (dedup, round timeouts, catch-up) must absorb; the sender cannot
    know, so it must not react.  An injected reorder-DELAY hands the
    delivery to a timer thread and returns immediately, so messages sent
    after it genuinely OVERTAKE it on the wire (an inline sleep would
    delay every successor equally — latency, not reordering).

    Cross-node propagation: the active trace context rides as a `trace`
    field on the envelope (`<trace_id>-<span_id>`, the x-celestia-trace
    grammar) so the receiving driver ADOPTS the trace.  Safe to attach:
    `msg_id` identity ignores top-level keys it does not name, and vote
    signatures cover msg["vote"] alone — the stamp cannot dedup-split or
    invalidate a relayed message.
    """
    from celestia_app_tpu import chaos
    from celestia_app_tpu.trace.context import serialize_context

    wire_ctx = serialize_context()
    if wire_ctx is not None and "trace" not in msg:
        msg = {**msg, "trace": wire_ctx}

    acts = chaos.gossip_send()
    if acts.get("drop"):
        return True
    deliveries = 2 if acts.get("dup") else 1

    def _attempt_all() -> bool:
        ok = False
        for _ in range(deliveries):
            prior = streak.get(key, 0)
            budget = retries if prior == 0 else 0
            for attempt in range(budget + 1):
                try:
                    send(msg)
                except Exception:  # chaos-ok: unreachable peer — flood routes around
                    if attempt == budget:
                        streak[key] = streak.get(key, 0) + 1
                        _recoveries().inc(
                            seam="gossip.send", outcome="gave_up"
                        )
                        break
                    sleep(0.02 * (2 ** attempt))
                else:
                    streak.pop(key, None)
                    if attempt:
                        _recoveries().inc(
                            seam="gossip.send", outcome="resent"
                        )
                    ok = True
                    break
        return ok

    if acts.get("delay_s"):
        timer = threading.Timer(acts["delay_s"], _attempt_all)
        timer.daemon = True
        with _DELAYED_LOCK:
            _DELAYED[:] = [t for t in _DELAYED if t.is_alive()]
            _DELAYED.append(timer)
        timer.start()
        return True
    return _attempt_all()

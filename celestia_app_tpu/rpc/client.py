"""RemoteNode: the wire-side node handle (reference pkg/user's gRPC conn).

Presents the same duck-typed node surface TxClient and txsim consume from
an in-process TestNode — chain_id / broadcast / query_account / tx_status /
produce_block — but every call is an HTTP JSON-RPC round trip to a served
node this process did not construct (and need not have imported).
"""

from __future__ import annotations

import http.client
import json
import time
from dataclasses import dataclass
from urllib.parse import urlparse


class RPCError(RuntimeError):
    pass


@dataclass
class RemoteAccount:
    account_number: int
    sequence: int


@dataclass
class RemoteTxResult:
    code: int
    log: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: tuple = ()


class RemoteNode:
    """A client handle to a ServingNode's JSON-RPC endpoint."""

    # Socket timeout must exceed a worst-case cold jit compile inside the
    # served node (35-50 s measured for a first-ever square size on this
    # box): produce_block legitimately blocks that long once per size,
    # and a 30 s cap made the devnet txsim test flake exactly there.
    def __init__(self, url: str, timeout: float = 120.0, defer_status: bool = False):
        self.url = url
        parsed = urlparse(url)
        self._host = parsed.hostname
        self._port = parsed.port
        self._timeout = timeout
        self._chain_id: str | None = None
        if not defer_status:
            self._chain_id = self.status()["chain_id"]

    # --- transport ----------------------------------------------------------
    def call(self, method: str, **params):
        from celestia_app_tpu.trace.context import TRACE_HEADER, serialize_context

        conn = http.client.HTTPConnection(self._host, self._port, timeout=self._timeout)
        try:
            payload = json.dumps(
                {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
            )
            headers = {"Content-Type": "application/json"}
            # Cross-node propagation: the active context rides every
            # JSON-RPC hop so the receiving node ADOPTS the trace
            # (adopt_or_new in rpc/server.py) instead of re-minting it.
            wire_ctx = serialize_context()
            if wire_ctx is not None:
                headers[TRACE_HEADER] = wire_ctx
            conn.request("POST", "/", body=payload, headers=headers)
            resp = conn.getresponse()
            body = json.loads(resp.read())
        finally:
            conn.close()
        if "error" in body:
            raise RPCError(body["error"]["message"])
        return body["result"]

    # --- node surface ---------------------------------------------------------
    @property
    def chain_id(self) -> str:
        if self._chain_id is None:
            self._chain_id = self.status()["chain_id"]
        return self._chain_id

    def status(self) -> dict:
        return self.call("status")

    def broadcast(self, raw_tx: bytes, relay: bool = True) -> RemoteTxResult:
        res = self.call("broadcast_tx", tx=raw_tx.hex(), relay=relay)
        return RemoteTxResult(code=res["code"], log=res["log"])

    def query_account(self, address: str) -> RemoteAccount | None:
        res = self.call("account", address=address)
        if res is None:
            return None
        return RemoteAccount(res["account_number"], res["sequence"])

    def tx_status(self, tx_hash: bytes) -> tuple[int, int, str] | None:
        res = self.call("tx_status", hash=tx_hash.hex())
        if res is None:
            return None
        return (res["height"], res["code"], res["log"])

    def wait_tx(self, tx_hash: bytes, timeout_s: float = 30.0):
        """Subscription confirm: long-poll calls that park server-side on
        the commit event (rpc_subscribe_tx) instead of hammering tx_status;
        (height, code, log) or None on timeout. Re-subscribes while the
        deadline remains — the server caps one park at 110 s."""
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            res = self.call(
                "subscribe_tx", hash=tx_hash.hex(), timeout_s=remaining
            )
            if res is not None:
                return (res["height"], res["code"], res["log"])

    def produce_block(self):
        """Trigger one block on the served node (dev/test surface); returns
        (block-info dict, results) shaped like TestNode.produce_block."""
        res = self.call("produce_block")
        results = [
            RemoteTxResult(code=r["code"], log=r["log"],
                           gas_wanted=r["gas_wanted"], gas_used=r["gas_used"])
            for r in res["results"]
        ]
        return res, results

    def block(self, height: int) -> dict:
        return self.call("block", height=height)

    def validators(self) -> list[dict]:
        return self.call("validators")

    def apply_block(self, height: int, time_ns: int, data) -> dict:
        return self.call(
            "apply_block",
            height=height,
            time_ns=time_ns,
            data_hash=data.hash.hex(),
            square_size=data.square_size,
            txs=[t.hex() for t in data.txs],
        )

    # --- voting round (consensus/votes.py) -----------------------------------
    def propose(self, height: int, time_ns: int, data) -> dict:
        return self.call(
            "propose",
            height=height,
            time_ns=time_ns,
            data_hash=data.hash.hex(),
            square_size=data.square_size,
            txs=[t.hex() for t in data.txs],
        )

    def precommit(self, height: int, block_hash: bytes, prevotes: list[str]) -> dict:
        return self.call(
            "precommit",
            height=height,
            data_hash=block_hash.hex(),
            prevotes=prevotes,
        )

    def finalize_commit(
        self, height: int, time_ns: int, data, commit: dict,
        last_commit_signers: list[str] | None = None,
        evidence: list | None = None,
    ) -> dict:
        return self.call(
            "finalize_commit",
            height=height,
            time_ns=time_ns,
            data_hash=data.hash.hex(),
            square_size=data.square_size,
            txs=[t.hex() for t in data.txs],
            commit=commit,
            last_commit_signers=last_commit_signers,
            evidence=evidence or [],
        )

    def consensus(self, msg: dict) -> dict:
        """Deliver a gossip consensus message (rpc/gossip.py flood)."""
        return self.call("consensus", msg=msg)

    def commit(self, height: int):
        """The height's Commit record, parsed — None if the node has none."""
        res = self.call("commit", height=height)
        if res is None:
            return None
        from celestia_app_tpu.consensus import Commit

        return Commit.from_json(res)

    # --- state sync -----------------------------------------------------------
    def snapshots(self) -> list[dict]:
        return self.call("snapshots")

    def snapshot_chunk(self, height: int, index: int) -> str:
        return self.call("snapshot_chunk", height=height, index=index)

    # --- proof queries (verify client-side against the fetched roots) --------
    def tx_inclusion_proof(self, height: int, tx_index: int):
        from celestia_app_tpu.rpc.codec import share_proof_from_json

        res = self.call("tx_inclusion_proof", height=height, tx_index=tx_index)
        return share_proof_from_json(res["proof"]), bytes.fromhex(res["data_root"])

    def share_inclusion_proof(self, height: int, start: int, end: int):
        from celestia_app_tpu.rpc.codec import share_proof_from_json

        res = self.call("share_inclusion_proof", height=height, start=start, end=end)
        return share_proof_from_json(res["proof"]), bytes.fromhex(res["data_root"])

    def state_proof(self, key: bytes):
        from celestia_app_tpu.rpc.codec import state_proof_from_json

        res = self.call("state_proof", key=key.hex())
        return state_proof_from_json(res["proof"]), bytes.fromhex(res["app_hash"])

    # --- blobstream relayer surface -----------------------------------------
    def blobstream_attestation(self, nonce: int) -> dict | None:
        return self.call("blobstream_attestation", nonce=nonce)

    def blobstream_nonces(self) -> dict:
        return self.call("blobstream_nonces")

    def data_commitment_range(self, height: int) -> dict:
        return self.call("data_commitment_range", height=height)

    def latest_data_commitment(self) -> dict | None:
        return self.call("latest_data_commitment")

    def latest_valset_before(self, nonce: int) -> dict:
        return self.call("latest_valset_before", nonce=nonce)

    def data_commitment(self, begin: int, end: int) -> bytes:
        return bytes.fromhex(self.call("data_commitment", begin=begin, end=end))

    def data_root_inclusion_proof(
        self, height: int, begin: int, end: int
    ) -> tuple[int, int, list[bytes]]:
        res = self.call(
            "data_root_inclusion_proof", height=height, begin=begin, end=end
        )
        return res["index"], res["total"], [bytes.fromhex(p) for p in res["path"]]

    def wait_for_height(self, height: int, timeout_s: float = 30.0) -> dict:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            st = self.status()
            if st["height"] >= height:
                return st
            time.sleep(0.05)
        raise TimeoutError(f"node did not reach height {height}")

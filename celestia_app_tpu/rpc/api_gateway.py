"""REST API gateway: the cosmos gRPC-gateway surface over HTTP+JSON.

The reference node serves three planes — Tendermint RPC, gRPC, and a
REST "API" gateway (grpc-gateway routes registered in
/root/reference/app/app.go:712-735; testnode wires all three,
test/util/testnode/network.go:38-43, default port 1317). This module is
the third plane: the standard cosmos REST routes mapped onto the same
node surface the other planes consume, JSON field names in snake_case as
the sdk's gateway emits them.

    GET  /cosmos/base/tendermint/v1beta1/node_info
    GET  /cosmos/base/tendermint/v1beta1/blocks/latest
    GET  /cosmos/auth/v1beta1/accounts/{address}
    GET  /cosmos/bank/v1beta1/balances/{address}
    GET  /cosmos/bank/v1beta1/balances/{address}/by_denom?denom=
    GET  /cosmos/staking/v1beta1/validators[?pagination.offset=&pagination.limit=&pagination.count_total=]
    GET  /cosmos/gov/v1beta1/proposals
    GET  /cosmos/slashing/v1beta1/params
    GET  /celestia/minfee/v1/min_gas_price
    GET  /celestia/blob/v1/params
    GET  /cosmos/tx/v1beta1/txs/{hash}
    POST /cosmos/tx/v1beta1/txs        {"tx_bytes": base64, "mode": ...}
    POST /cosmos/tx/v1beta1/simulate   {"tx_bytes": base64}

plus the shared observability surface every serving plane mounts
(trace/exposition.py): GET /metrics (byte-identical Prometheus exposition
across the JSON-RPC, REST, and gRPC-debug ports), /trace_tables[/<name>],
and /healthz.

Errors follow the gateway shape: {"code": grpc-code, "message": ...}
with HTTP 404 / 400 / 501 as the sdk maps them.
"""

from __future__ import annotations

import base64
import json
import re
import threading
from contextlib import nullcontext
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse


def _node_lock(node):
    return getattr(node, "lock", None) or nullcontext()


def _power_reduction() -> int:
    from celestia_app_tpu.state.staking import POWER_REDUCTION

    return POWER_REDUCTION


def _rest_page_request(q) -> dict:
    """Parse the gateway's pagination.* query params into the shared
    _paginate request shape (same cursor contract as the gRPC plane:
    clients resend next_key as pagination.key).  Raises _BadRequest on
    malformed values."""
    try:
        key = base64.b64decode((q.get("pagination.key") or [""])[0])
        return {
            "offset": int(key.decode()) if key else max(
                int((q.get("pagination.offset") or ["0"])[0]), 0),
            "limit": max(int((q.get("pagination.limit") or ["0"])[0]), 0),
            "count_total":
                (q.get("pagination.count_total") or ["false"])[0] == "true",
            "reverse":
                (q.get("pagination.reverse") or ["false"])[0] == "true",
        }
    except ValueError as e:
        raise _BadRequest(f"invalid pagination: {e}") from e


def _rest_page_response(page_req: dict, page_resp: bytes) -> dict:
    """PageResponse bytes -> the gateway's JSON pagination object."""
    from celestia_app_tpu.rpc.grpc_plane import _parse_page_response

    out: dict = {}
    parsed = _parse_page_response(page_resp)
    if parsed["next_key"]:
        out["next_key"] = base64.b64encode(parsed["next_key"]).decode()
    if page_req["count_total"]:
        out["total"] = str(parsed["total"])
    return out


def _routes(node):
    """[(method, compiled path regex, handler(match, query, body) -> dict)]"""

    def node_info(m, q, body):
        return {
            "default_node_info": {
                "network": node.chain_id,
                "version": "celestia-app-tpu",
                "moniker": "tpu-node",
            },
            "application_version": {
                "app_name": "celestia-app-tpu",
                "version": str(node.app.app_version),
            },
        }

    def latest_block(m, q, body):
        with _node_lock(node):
            height = node.app.height
        return {
            "block": {
                "header": {"chain_id": node.chain_id, "height": str(height)}
            },
        }

    def account(m, q, body):
        with _node_lock(node):
            acc = node.query_account(m.group("address"))
        if acc is None:
            raise _NotFound(f"account {m.group('address')} not found")
        return {
            "account": {
                "@type": "/cosmos.auth.v1beta1.BaseAccount",
                "address": acc.address,
                "account_number": str(acc.account_number),
                "sequence": str(acc.sequence),
            }
        }

    def balances(m, q, body):
        # Every denom the address holds (the bank store is multi-denom:
        # IBC voucher denoms live beside utia), denom-sorted as the sdk
        # pages them. Address-scoped prefix walk — the global supply walk
        # would hold the node lock for O(all accounts).
        from celestia_app_tpu.state.accounts import BankKeeper

        with _node_lock(node):
            bals = BankKeeper(node.app.cms.working).balances_of(
                m.group("address")
            )
        coins = sorted((d, a) for d, a in bals.items() if a)
        return {
            "balances": [
                {"denom": d, "amount": str(a)} for d, a in coins
            ],
            "pagination": {"total": str(len(coins))},
        }

    def balance_by_denom(m, q, body):
        from celestia_app_tpu.state.accounts import BankKeeper

        denom = (q.get("denom") or ["utia"])[0]
        with _node_lock(node):
            amount = BankKeeper(node.app.cms.working).balance(
                m.group("address"), denom
            )
        return {"balance": {"denom": denom, "amount": str(amount)}}

    def validators(m, q, body):
        # Same pagination engine as the gRPC plane (_paginate): honors the
        # sdk cursor contract — clients resend next_key as pagination.key.
        from celestia_app_tpu.rpc.grpc_plane import _paginate

        with _node_lock(node):
            vals = node.validators()
        page_req = _rest_page_request(q)
        page, page_resp = _paginate(vals, page_req)
        return {
            "validators": [
                {
                    "operator_address": v["address"],
                    "status": "BOND_STATUS_BONDED",
                    # sdk convention shared with the gRPC plane:
                    # tokens = power x PowerReduction.
                    "tokens": str(v.get("power", 0) * _power_reduction()),
                }
                for v in page
            ],
            "pagination": _rest_page_response(page_req, page_resp),
        }

    def proposals(m, q, body):
        # Paged like the validators route (shared _paginate engine) and
        # status emitted as the PROPOSAL_STATUS_* enum NAME — the
        # grpc-gateway JSON convention; a bare int here broke clients
        # switch-ing on the string values the sdk emits.
        from celestia_app_tpu.modules.gov import GovKeeper
        from celestia_app_tpu.rpc.grpc_plane import _paginate
        from celestia_app_tpu.state.accounts import BankKeeper
        from celestia_app_tpu.state.staking import StakingKeeper

        with _node_lock(node):
            store = node.app.cms.working
            props = GovKeeper(
                store, StakingKeeper(store), BankKeeper(store)
            ).proposals()
        page_req = _rest_page_request(q)
        page, page_resp = _paginate(props, page_req)
        return {
            "proposals": [
                {
                    "proposal_id": str(p.pid),
                    "status": f"PROPOSAL_STATUS_{p.status.name}",
                }
                for p in page
            ],
            "pagination": _rest_page_response(page_req, page_resp),
        }

    def slashing_params(m, q, body):
        from celestia_app_tpu.modules.slashing.keeper import SlashingKeeper

        with _node_lock(node):
            p = SlashingKeeper(node.app.cms.working).params()
        return {
            "params": {
                "signed_blocks_window": str(p.signed_blocks_window),
                "min_signed_per_window": str(p.min_signed_per_window),
                "downtime_jail_duration":
                    f"{p.downtime_jail_duration_ns / 1e9:.9f}s",
                "slash_fraction_double_sign":
                    str(p.slash_fraction_double_sign),
                "slash_fraction_downtime": str(p.slash_fraction_downtime),
            }
        }

    def min_gas_price(m, q, body):
        from celestia_app_tpu.modules.minfee import MinFeeKeeper

        with _node_lock(node):
            price = MinFeeKeeper(node.app.cms.working).network_min_gas_price()
        return {"network_min_gas_price": str(price)}

    def blob_params(m, q, body):
        with _node_lock(node):
            return {
                "params": {
                    "gas_per_blob_byte": node.app.gas_per_blob_byte,
                    "gov_max_square_size":
                        str(node.app.gov_max_square_size),
                }
            }

    def get_tx(m, q, body):
        txhash = m.group("hash")
        try:
            raw = bytes.fromhex(txhash)
        except ValueError as e:
            raise _BadRequest(f"invalid tx hash: {e}") from e
        with _node_lock(node):
            status = node.tx_status(raw)
        if status is None:
            raise _NotFound(f"tx not found: {txhash}")
        height, code, log = status
        return {
            "tx_response": {
                "height": str(height),
                "txhash": txhash.upper(),
                "code": code,
                "raw_log": log,
            }
        }

    def simulate_tx(m, q, body):
        # POST /cosmos/tx/v1beta1/simulate {"tx_bytes": base64} ->
        # {"gas_info": {...}} on success or a gateway error with the
        # node's log; sdk-waiver semantics (signatures/limits waived,
        # state discarded) via the same App.simulate_tx the gRPC
        # Simulate serves.
        try:
            tx_bytes = base64.b64decode(body["tx_bytes"])
        except (KeyError, TypeError, ValueError) as e:
            raise _BadRequest(f"invalid tx_bytes: {e}") from e
        with _node_lock(node):
            res = node.app.simulate_tx(tx_bytes)
        if res.code != 0:
            raise _BadRequest(f"simulation failed: {res.log}")
        return {
            "gas_info": {
                "gas_wanted": str(res.gas_wanted),
                "gas_used": str(res.gas_used),
            }
        }

    def broadcast_tx(m, q, body):
        try:
            tx_bytes = base64.b64decode(body["tx_bytes"])
        except (KeyError, TypeError, ValueError) as e:
            raise _BadRequest(f"invalid tx_bytes: {e}") from e
        from celestia_app_tpu.trace.context import (
            current_context,
            new_context,
            use_context,
        )
        from celestia_app_tpu.tx import tx_hash

        # Request entry: issue the trace the tx carries through the
        # mempool and into the block that commits it (trace/context.py).
        # When the hop arrived with x-celestia-trace the ingress already
        # ADOPTED it (do_POST) — child it rather than re-minting, so the
        # submit stays one trace across nodes.
        parent = current_context()
        ctx = (
            parent.child(layer="rpc", plane="rest")
            if parent is not None
            else new_context(layer="rpc", plane="rest")
        )
        with use_context(ctx):
            res = node.broadcast(tx_bytes)
        return {
            "tx_response": {
                "txhash": tx_hash(tx_bytes).hex().upper(),
                "code": res.code,
                "raw_log": res.log,
                "gas_wanted": str(res.gas_wanted),
            }
        }

    return [
        ("GET", re.compile(r"^/cosmos/base/tendermint/v1beta1/node_info$"), node_info),
        ("GET", re.compile(r"^/cosmos/base/tendermint/v1beta1/blocks/latest$"), latest_block),
        ("GET", re.compile(r"^/cosmos/auth/v1beta1/accounts/(?P<address>[^/]+)$"), account),
        ("GET", re.compile(r"^/cosmos/bank/v1beta1/balances/(?P<address>[^/]+)$"), balances),
        ("GET", re.compile(r"^/cosmos/bank/v1beta1/balances/(?P<address>[^/]+)/by_denom$"), balance_by_denom),
        ("GET", re.compile(r"^/cosmos/staking/v1beta1/validators$"), validators),
        ("GET", re.compile(r"^/cosmos/gov/v1beta1/proposals$"), proposals),
        ("GET", re.compile(r"^/cosmos/slashing/v1beta1/params$"), slashing_params),
        ("GET", re.compile(r"^/celestia/minfee/v1/min_gas_price$"), min_gas_price),
        ("GET", re.compile(r"^/celestia/blob/v1/params$"), blob_params),
        ("GET", re.compile(r"^/cosmos/tx/v1beta1/txs/(?P<hash>[0-9a-fA-F]+)$"), get_tx),
        ("POST", re.compile(r"^/cosmos/tx/v1beta1/txs$"), broadcast_tx),
        ("POST", re.compile(r"^/cosmos/tx/v1beta1/simulate$"), simulate_tx),
    ]


class _NotFound(Exception):
    pass


class _BadRequest(Exception):
    pass


class _ApiHandler(BaseHTTPRequestHandler):
    routes: list = []

    def log_message(self, fmt, *args):  # quiet
        pass

    def _respond(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _dispatch(self, method: str, body: dict | None) -> None:
        url = urlparse(self.path)
        query = parse_qs(url.query)
        for verb, pattern, handler in self.routes:
            if verb != method:
                continue
            m = pattern.match(url.path)
            if m is None:
                continue
            try:
                self._respond(200, handler(m, query, body))
            except _NotFound as e:
                self._respond(404, {"code": 5, "message": str(e)})
            except _BadRequest as e:
                self._respond(400, {"code": 3, "message": str(e)})
            except Exception as e:  # noqa: BLE001 — gateway internal error
                from celestia_app_tpu.qos import (
                    QosThrottled,
                    retry_after_header,
                    throttle_body,
                )

                if isinstance(e, QosThrottled):
                    # Per-tenant QoS refusal: 429 + qos.py's ONE
                    # canonical body — the same bytes the JSON-RPC plane
                    # serves and the gRPC plane carries as its
                    # RESOURCE_EXHAUSTED detail.
                    raw = throttle_body(e)
                    self.send_response(429)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(raw)))
                    self.send_header("Retry-After", retry_after_header(e))
                    self.end_headers()
                    self.wfile.write(raw)
                    return
                self._respond(500, {"code": 13,
                                    "message": f"{type(e).__name__}: {e}"})
            return
        self._respond(501, {"code": 12,
                            "message": f"Not Implemented: {url.path}"})

    def do_GET(self):  # noqa: N802 — http.server API
        # Observability first: /metrics must serve the SAME bytes as the
        # other planes (shared handler), and none of these paths collide
        # with the cosmos route space.  An x-celestia-trace header is
        # ADOPTED (same trace_id, fresh span_id + this node's node_id)
        # so remote fetches stitch into the caller's trace.
        from celestia_app_tpu.trace.exposition import (
            handle_observability_get_adopted,
            send_observability_response,
        )

        resp = handle_observability_get_adopted(self, plane="rest")
        if resp is not None:
            send_observability_response(self, resp)
            return
        self._dispatch("GET", None)

    def do_POST(self):  # noqa: N802
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(length)) if length else {}
        except (ValueError, json.JSONDecodeError):
            self._respond(400, {"code": 3, "message": "invalid JSON body"})
            return
        # Adopt the peer's trace context (if any) around route dispatch
        # so broadcast_tx childs it instead of re-minting (adopt_context
        # — see trace/context.py; adopt_or_new is the strict variant).
        from celestia_app_tpu.trace.context import (
            TRACE_HEADER,
            adopt_context,
            use_context,
        )

        ctx = adopt_context(self.headers.get(TRACE_HEADER))
        if ctx is not None:
            with use_context(ctx):
                self._dispatch("POST", body)
        else:
            self._dispatch("POST", body)


@dataclass
class ApiGateway:
    httpd: ThreadingHTTPServer
    port: int

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


def serve_api(node, host: str = "127.0.0.1", port: int = 0) -> ApiGateway:
    """Start the REST gateway for `node`; returns the live server."""
    handler = type("BoundApiHandler", (_ApiHandler,),
                   {"routes": _routes(node)})
    httpd = ThreadingHTTPServer((host, port), handler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return ApiGateway(httpd, httpd.server_address[1])

"""ABCI proof queriers (reference pkg/proof/querier.go:29,73).

The reference registers "custom/txInclusionProof" and
"custom/shareInclusionProof" ABCI query routes (app/app.go:393-394); the
querier reconstructs the block's square from the raw txs supplied in the
request and produces proofs against the recomputed data root.
"""

from __future__ import annotations

import json

from celestia_app_tpu.da import extend_shares
from celestia_app_tpu.proof import (
    ShareProof,
    new_share_inclusion_proof,
    new_tx_inclusion_proof,
)
from celestia_app_tpu.square import builder as square

TX_INCLUSION_ROUTE = "custom/txInclusionProof"
SHARE_INCLUSION_ROUTE = "custom/shareInclusionProof"


def query_tx_inclusion_proof(
    raw_txs: list[bytes], tx_index: int, max_square_size: int
) -> ShareProof:
    sq = square.construct(raw_txs, max_square_size)
    eds = extend_shares(sq.share_bytes())
    return new_tx_inclusion_proof(sq, eds, tx_index)


def query_share_inclusion_proof(
    raw_txs: list[bytes], start: int, end: int, max_square_size: int
) -> ShareProof:
    sq = square.construct(raw_txs, max_square_size)
    eds = extend_shares(sq.share_bytes())
    return new_share_inclusion_proof(eds, start, end)


def handle_query(app, path: str, data: bytes) -> ShareProof:
    """Dispatch an ABCI-style query: path = route/arg[/arg], data = JSON
    {"txs": [hex, ...]}."""
    parts = path.split("/")
    payload = json.loads(data)
    raw_txs = [bytes.fromhex(t) for t in payload["txs"]]
    max_k = app.max_effective_square_size()
    if path.startswith(TX_INCLUSION_ROUTE):
        return query_tx_inclusion_proof(raw_txs, int(parts[-1]), max_k)
    if path.startswith(SHARE_INCLUSION_ROUTE):
        return query_share_inclusion_proof(
            raw_txs, int(parts[-2]), int(parts[-1]), max_k
        )
    raise ValueError(f"unknown query path {path}")

"""Share inclusion proofs to the data root.

Parity with reference pkg/proof/proof.go:
  - RowProof (binary merkle paths of row roots into the DAH data root;
    CreateShareToRowRootProofs :151-202 counterpart is the NMT part),
  - ShareProof (NewShareInclusionProofFromEDS :79-140): raw shares + one NMT
    range proof per touched row + the row proof.

Proof generation takes the device-computed EDS (roots from the fused
pipeline); the per-row NMTs for touched rows are rebuilt host-side — a few
rows only, and proof generation is off the consensus hot path.
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu import merkle
from celestia_app_tpu.constants import NAMESPACE_SIZE, PARITY_NAMESPACE_BYTES
from celestia_app_tpu.da.eds import ExtendedDataSquare
from celestia_app_tpu.nmt.proof import NmtRangeProof, prove_range, verify_range
from celestia_app_tpu.nmt.tree import NamespacedMerkleTree


@dataclass(frozen=True)
class RowProof:
    """Proves row roots [start_row, end_row) belong to a data root."""

    row_roots: tuple[bytes, ...]  # 90-byte namespaced roots
    proofs: tuple[tuple[bytes, ...], ...]  # merkle audit paths
    start_row: int
    end_row: int
    total: int  # leaves of the data-root tree (4k)

    def verify(self, data_root: bytes) -> bool:
        if self.end_row - self.start_row != len(self.row_roots):
            return False
        if len(self.proofs) != len(self.row_roots):
            return False
        for i, (root, path) in enumerate(zip(self.row_roots, self.proofs)):
            if not merkle.verify_proof(
                data_root, root, self.start_row + i, self.total, list(path)
            ):
                return False
        return True


@dataclass(frozen=True)
class ShareProof:
    """Proves a contiguous run of shares is committed by a data root."""

    data: tuple[bytes, ...]  # the raw 512-byte shares
    share_proofs: tuple[NmtRangeProof, ...]  # one per touched row
    namespace: bytes  # 29-byte leaf namespace of the proven shares
    row_proof: RowProof

    def verify(self, data_root: bytes) -> bool:
        if not self.row_proof.verify(data_root):
            return False
        cursor = 0
        for row_root, nmt_proof in zip(self.row_proof.row_roots, self.share_proofs):
            count = nmt_proof.end - nmt_proof.start
            leaves = [
                self.namespace + share
                for share in self.data[cursor : cursor + count]
            ]
            if not verify_range(row_root, nmt_proof, leaves):
                return False
            cursor += count
        return cursor == len(self.data)


def _row_tree(eds_row, k: int) -> NamespacedMerkleTree:
    """Extended-row NMT: own namespace in Q0 columns, parity outside."""
    tree = NamespacedMerkleTree()
    for c in range(2 * k):
        raw = bytes(eds_row[c].tobytes())
        ns = raw[:NAMESPACE_SIZE] if c < k else PARITY_NAMESPACE_BYTES
        tree.push(ns + raw)
    return tree


def new_share_inclusion_proof(
    eds: ExtendedDataSquare, start: int, end: int
) -> ShareProof:
    """Proof for ODS shares [start, end) (row-major coordinates).

    All shares in the range must carry one namespace (the square layout
    guarantees this for any single blob or compact run; reference
    pkg/proof/proof.go:79 enforces the same).
    """
    k = eds.k
    if not 0 <= start < end <= k * k:
        raise ValueError(f"invalid ODS share range [{start},{end})")
    eds_np = eds.squared()
    namespace = bytes(eds_np[start // k, start % k, :NAMESPACE_SIZE].tobytes())

    start_row, end_row = start // k, (end - 1) // k + 1
    shares: list[bytes] = []
    nmt_proofs: list[NmtRangeProof] = []
    for r in range(start_row, end_row):
        lo = start % k if r == start_row else 0
        hi = (end - 1) % k + 1 if r == end_row - 1 else k
        row = eds_np[r]
        for c in range(lo, hi):
            raw = bytes(row[c].tobytes())
            if raw[:NAMESPACE_SIZE] != namespace:
                raise ValueError(
                    f"share ({r},{c}) namespace differs from range start"
                )
            shares.append(raw)
        nmt_proofs.append(prove_range(_row_tree(row, k), lo, hi))

    all_roots = eds.row_roots() + eds.col_roots()
    row_proof = RowProof(
        row_roots=tuple(all_roots[r] for r in range(start_row, end_row)),
        proofs=tuple(
            tuple(merkle.proof(all_roots, r)) for r in range(start_row, end_row)
        ),
        start_row=start_row,
        end_row=end_row,
        total=len(all_roots),
    )
    return ShareProof(
        data=tuple(shares),
        share_proofs=tuple(nmt_proofs),
        namespace=namespace,
        row_proof=row_proof,
    )

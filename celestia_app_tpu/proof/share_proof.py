"""Share inclusion proofs to the data root.

Parity with reference pkg/proof/proof.go:
  - RowProof (binary merkle paths of row roots into the DAH data root;
    CreateShareToRowRootProofs :151-202 counterpart is the NMT part),
  - ShareProof (NewShareInclusionProofFromEDS :79-140): raw shares + one NMT
    range proof per touched row + the row proof.

Proof generation takes the device-computed EDS (roots from the fused
pipeline); the per-row NMTs for touched rows are rebuilt host-side — a few
rows only, and proof generation is off the consensus hot path.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from celestia_app_tpu import merkle
from celestia_app_tpu.constants import NAMESPACE_SIZE
from celestia_app_tpu.da.eds import ExtendedDataSquare
from celestia_app_tpu.nmt.proof import NmtRangeProof, verify_range


@dataclass(frozen=True)
class RowProof:
    """Proves row roots [start_row, end_row) belong to a data root."""

    row_roots: tuple[bytes, ...]  # 90-byte namespaced roots
    proofs: tuple[tuple[bytes, ...], ...]  # merkle audit paths
    start_row: int
    end_row: int
    total: int  # leaves of the data-root tree (4k)

    def verify(self, data_root: bytes) -> bool:
        if self.end_row - self.start_row != len(self.row_roots):
            return False
        if len(self.proofs) != len(self.row_roots):
            return False
        for i, (root, path) in enumerate(zip(self.row_roots, self.proofs)):
            if not merkle.verify_proof(
                data_root, root, self.start_row + i, self.total, list(path)
            ):
                return False
        return True


@dataclass(frozen=True)
class ShareProof:
    """Proves a contiguous run of shares is committed by a data root."""

    data: tuple[bytes, ...]  # the raw 512-byte shares
    share_proofs: tuple[NmtRangeProof, ...]  # one per touched row
    namespace: bytes  # 29-byte leaf namespace of the proven shares
    row_proof: RowProof

    def verify(self, data_root: bytes) -> bool:
        if not self.row_proof.verify(data_root):
            return False
        cursor = 0
        for row_root, nmt_proof in zip(self.row_proof.row_roots, self.share_proofs):
            count = nmt_proof.end - nmt_proof.start
            leaves = [
                self.namespace + share
                for share in self.data[cursor : cursor + count]
            ]
            if not verify_range(row_root, nmt_proof, leaves):
                return False
            cursor += count
        return cursor == len(self.data)


def _range_proof(tree, lo: int, hi: int) -> NmtRangeProof:
    """Range proof off a memoized tree: every tree in the square has a
    power-of-two leaf count, so the proof is assembled from the tree's
    precomputed `levels()` by pure indexing (prove_range_from_levels) —
    a host NamespacedMerkleTree pays its hashes once per tree build, a
    forest-backed view (serve/cache.py) pays none at all.  Byte-identical
    to the recursive prove_range walk either way."""
    from celestia_app_tpu.nmt.proof import prove_range_from_levels

    return prove_range_from_levels(tree.levels(), lo, hi)


def _row_proof(eds: ExtendedDataSquare, start_row: int, end_row: int) -> RowProof:
    """RowProof for leaves [start_row, end_row) of the 4k data-root tree
    (row roots first, column roots second — a column-tree proof passes
    indices >= 2k).  With a serve-cache forest attached the audit paths
    index the memoized root-tree levels instead of re-hashing the 4k-leaf
    tree per request; byte-identical either way (pinned by the
    indexing-twin tests)."""
    forest = getattr(eds, "_forest", None)
    if forest is not None:
        all_roots = forest.row_roots + forest.col_roots
        paths = (
            tuple(merkle.path_from_levels(forest.root_levels, r))
            for r in range(start_row, end_row)
        )
    else:
        all_roots = eds.row_roots() + eds.col_roots()
        paths = (
            tuple(merkle.proof(all_roots, r))
            for r in range(start_row, end_row)
        )
    return RowProof(
        row_roots=tuple(all_roots[r] for r in range(start_row, end_row)),
        proofs=tuple(paths),
        start_row=start_row,
        end_row=end_row,
        total=len(all_roots),
    )


def new_share_inclusion_proof(
    eds: ExtendedDataSquare, start: int, end: int
) -> ShareProof:
    """Proof for ODS shares [start, end) (row-major coordinates).

    All shares in the range must carry one namespace (the square layout
    guarantees this for any single blob or compact run; reference
    pkg/proof/proof.go:79 enforces the same).  Row trees come from
    `eds.row_tree` — memoized per handle and forest-backed when the serve
    cache retains this height, so an m-row range pays at most m tree
    builds (zero with a resident forest), never m x shares of hashing.
    """
    k = eds.k
    if not 0 <= start < end <= k * k:
        raise ValueError(f"invalid ODS share range [{start},{end})")
    start_row, end_row = start // k, (end - 1) // k + 1
    spans = [
        (r,
         start % k if r == start_row else 0,
         (end - 1) % k + 1 if r == end_row - 1 else k)
        for r in range(start_row, end_row)
    ]
    coords = [(r, c) for r, lo, hi in spans for c in range(lo, hi)]
    forest = getattr(eds, "_forest", None)
    if forest is not None and forest.eds is eds:
        # Serve-plane path: the whole range in ONE gather, each share
        # fetched from its owning buffer — a share-sharded retained EDS
        # (kernels/panel_sharded) routes every coordinate to its shard
        # instead of materializing the square on the host.  Only when
        # the forest is backed by THIS handle: a detached view (the
        # adversary's tampered copy carries the honest entry's forest)
        # must serve its own bytes, or tampering would be silently
        # masked instead of detected.
        mat = forest.gather_shares(coords)
    else:
        eds_np = eds.squared()
        mat = eds_np[tuple(np.transpose(coords))]
    namespace = bytes(mat[0, :NAMESPACE_SIZE].tobytes())

    shares: list[bytes] = []
    nmt_proofs: list[NmtRangeProof] = []
    pos = 0
    for r, lo, hi in spans:
        for c in range(lo, hi):
            raw = bytes(mat[pos].tobytes())
            pos += 1
            if raw[:NAMESPACE_SIZE] != namespace:
                raise ValueError(
                    f"share ({r},{c}) namespace differs from range start"
                )
            shares.append(raw)
        nmt_proofs.append(_range_proof(eds.row_tree(r), lo, hi))

    return ShareProof(
        data=tuple(shares),
        share_proofs=tuple(nmt_proofs),
        namespace=namespace,
        row_proof=_row_proof(eds, start_row, end_row),
    )


def new_share_sample_proof(
    eds: ExtendedDataSquare, row: int, col: int, axis: str = "row"
) -> ShareProof:
    """Proof for ONE coordinate of the EXTENDED square — the DAS sampling
    unit: light clients draw (row, col) uniformly over all four quadrants,
    so parity shares must prove exactly like data shares.  The leaf's
    namespace follows the quadrant rule (own inside Q0, parity outside);
    `ShareProof.verify` reconstructs the leaf as namespace || share, so
    the existing verifier covers the whole square unchanged.

    `axis` picks which tree commits the share — "row" proves leaf `col`
    of row tree `row`; "col" proves leaf `row` of COLUMN tree `col`,
    whose root sits in the second half of the 4k data-root leaves (index
    2k + col).  Both verify through the same ShareProof.verify; a light
    client that already holds one axis's root samples through the other
    for free."""
    n = 2 * eds.k
    if not (0 <= row < n and 0 <= col < n):
        raise ValueError(f"EDS coordinate ({row},{col}) outside {n}x{n}")
    if axis not in ("row", "col"):
        raise ValueError(f"axis must be 'row' or 'col', got {axis!r}")
    share = bytes(np.asarray(eds._eds[row, col]).tobytes())
    if axis == "col":
        nmt = _range_proof(eds.col_tree(col), row, row + 1)
        root_index = n + col  # column roots are the second 2k leaves
    else:
        nmt = _range_proof(eds.row_tree(row), col, col + 1)
        root_index = row
    return ShareProof(
        data=(share,),
        share_proofs=(nmt,),
        namespace=eds.leaf_namespace(row, col),
        row_proof=_row_proof(eds, root_index, root_index + 1),
    )


def ods_namespace_range(
    eds: ExtendedDataSquare, namespace: bytes
) -> tuple[int, int] | None:
    """The contiguous row-major ODS range [start, end) holding `namespace`,
    or None when the square carries no such share.  The square builder
    lays shares out in namespace order, so one namespace is always one
    contiguous run — the invariant GetSharesByNamespace leans on."""
    if len(namespace) != NAMESPACE_SIZE:
        raise ValueError(f"namespace must be {NAMESPACE_SIZE} bytes")
    ns_grid = eds.ods_namespaces()  # (k*k, NAMESPACE_SIZE) row-major
    matches = np.all(ns_grid == np.frombuffer(namespace, dtype=np.uint8), axis=1)
    idx = np.flatnonzero(matches)
    if idx.size == 0:
        return None
    start, end = int(idx[0]), int(idx[-1]) + 1
    if end - start != idx.size:
        raise ValueError(
            f"namespace {namespace.hex()} is not contiguous in the square"
        )
    return start, end


def new_namespace_proof(
    eds: ExtendedDataSquare, namespace: bytes
) -> ShareProof | None:
    """All shares of `namespace` with their multi-row inclusion proof, or
    None when the namespace is absent from the square."""
    rng = ods_namespace_range(eds, namespace)
    if rng is None:
        return None
    return new_share_inclusion_proof(eds, rng[0], rng[1])

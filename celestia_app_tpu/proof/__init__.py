"""Inclusion proofs to the data root (reference pkg/proof)."""

from celestia_app_tpu.proof.share_proof import (
    RowProof,
    ShareProof,
    new_share_inclusion_proof,
)
from celestia_app_tpu.da.eds import ExtendedDataSquare
from celestia_app_tpu.square.builder import Square


def new_tx_inclusion_proof(
    square: Square, eds: ExtendedDataSquare, tx_index: int
) -> ShareProof:
    """Proof that block tx `tx_index`'s shares are committed by the data root.

    Reference pkg/proof/proof.go:23 NewTxInclusionProof: locate the tx's
    share span in the compact region, then prove those shares.
    """
    lo, hi = square.find_tx_share_range(tx_index)
    return new_share_inclusion_proof(eds, lo, hi)


__all__ = [
    "RowProof",
    "ShareProof",
    "new_share_inclusion_proof",
    "new_tx_inclusion_proof",
]

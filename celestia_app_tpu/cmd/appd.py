"""celestia-appd-tpu: the CLI daemon.

Parity with the reference cmd/celestia-appd surface (root.go:44-130):
`init` writes a home directory with genesis, `start` runs the single-process
node loop (produce -> self-validate -> finalize -> commit, persisting state
each block), `export` dumps app state, `rollback` drops the last height,
`status` prints chain info.  Env prefix CELESTIA_ (root.go:33); state
survives restarts via the commit-store snapshot (LoadHeight analog).

Usage:  python -m celestia_app_tpu.cmd.appd <command> [--home DIR] ...
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time

# Pin the JAX platform from the environment BEFORE any backend client can
# be created: site hooks may pre-register an accelerator platform that
# ignores a later env change (same guard as tests/conftest.py).  When the
# operator explicitly excludes the accelerator, also drop the plugin's
# pool env — a wedged tunnel otherwise stalls even CPU-pinned runs at
# first compile (the plugin initializes regardless of the selected
# platform).
if os.environ.get("JAX_PLATFORMS"):
    if "axon" not in os.environ["JAX_PLATFORMS"]:
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from celestia_app_tpu.app import App, Genesis, GenesisAccount
from celestia_app_tpu.crypto import PrivateKey
from celestia_app_tpu.state.dec import Dec
from celestia_app_tpu.state.staking import Validator
from celestia_app_tpu.state.store import CommitStore

DEFAULT_HOME = os.path.expanduser(
    os.environ.get("CELESTIA_HOME", "~/.celestia-app-tpu")
)


def _genesis_path(home: str) -> str:
    return os.path.join(home, "config", "genesis.json")


def _state_path(home: str) -> str:
    return os.path.join(home, "data", "state.json")


def _meta_path(home: str) -> str:
    return os.path.join(home, "data", "app_meta.json")


def cmd_init(args) -> int:
    home = args.home
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    keys = [PrivateKey.from_seed(f"{args.chain_id}-account-{i}".encode()) for i in range(args.accounts)]
    genesis = {
        "chain_id": args.chain_id,
        "genesis_time_ns": time.time_ns(),
        "app_version": 2,
        "gov_max_square_size": args.gov_max_square_size,
        "accounts": [
            {
                "address": k.public_key().address(),
                "balance": 10**12,
                "pubkey": k.public_key().bytes.hex(),
            }
            for k in keys
        ],
        "validators": [
            {
                "address": PrivateKey.from_seed(f"{args.chain_id}-val-{i}".encode())
                .public_key()
                .address(),
                "pubkey": PrivateKey.from_seed(f"{args.chain_id}-val-{i}".encode())
                .public_key()
                .bytes.hex(),
                "power": 100,
            }
            for i in range(args.validators)
        ],
    }
    with open(_genesis_path(home), "w") as f:
        json.dump(genesis, f, indent=2)
    from celestia_app_tpu.cmd.config import write_default_configs

    cfg_path, app_cfg_path = write_default_configs(home)
    print(f"initialized chain {args.chain_id!r} at {home}")
    print(f"wrote {cfg_path} and {app_cfg_path}")
    return 0


def _load_genesis(home: str) -> Genesis:
    with open(_genesis_path(home)) as f:
        g = json.load(f)
    return Genesis(
        chain_id=g["chain_id"],
        genesis_time_ns=g["genesis_time_ns"],
        app_version=g.get("app_version", 2),
        gov_max_square_size=g.get("gov_max_square_size", 64),
        accounts=tuple(
            GenesisAccount(a["address"], a["balance"], bytes.fromhex(a.get("pubkey", "")))
            for a in g.get("accounts", [])
        ),
        validators=tuple(
            Validator(v["address"], bytes.fromhex(v.get("pubkey", "")), v["power"])
            for v in g.get("validators", [])
        ),
    )


def load_app(home: str, node_min_gas_price: Dec | None = None) -> App:
    """Construct the App from a home dir, resuming committed state if any."""
    genesis = _load_genesis(home)
    app = App(node_min_gas_price=node_min_gas_price or Dec.from_str("0.000001"))
    if os.path.exists(_state_path(home)):
        app.cms = CommitStore.load(_state_path(home))
        with open(_meta_path(home)) as f:
            meta = json.load(f)
        app.chain_id = meta["chain_id"]
        app.height = meta["height"]
        app.app_version = meta["app_version"]
        app.genesis_time_ns = meta["genesis_time_ns"]
        app.last_block_time_ns = meta["last_block_time_ns"]
    else:
        app.init_chain(genesis)
        save_app(home, app)
    return app


def save_app(home: str, app: App) -> None:
    app.cms.save(_state_path(home))
    with open(_meta_path(home), "w") as f:
        json.dump(
            {
                "chain_id": app.chain_id,
                "height": app.height,
                "app_version": app.app_version,
                "genesis_time_ns": app.genesis_time_ns,
                "last_block_time_ns": app.last_block_time_ns,
            },
            f,
        )


def _snapshot_dir(home: str) -> str:
    return os.path.join(home, "data", "snapshots")


def _write_snapshot(home: str, app: App, keep: int = 2) -> str:
    """State-sync snapshot artifact (reference: every 1500 blocks, keep 2,
    app/default_overrides.go:293-297 + snapshot.Cmd at root.go:125)."""
    os.makedirs(_snapshot_dir(home), exist_ok=True)
    path = os.path.join(_snapshot_dir(home), f"{app.height}.json")
    with open(path, "w") as f:
        json.dump(
            {
                "height": app.height,
                "chain_id": app.chain_id,
                "app_version": app.app_version,
                "app_hash": app.cms.last_app_hash.hex(),
                "state": {k.hex(): v.hex() for k, v in app.cms.export().items()},
            },
            f,
        )
    existing = sorted(
        (int(p.split(".")[0]) for p in os.listdir(_snapshot_dir(home))), reverse=True
    )
    for h in existing[keep:]:
        os.remove(os.path.join(_snapshot_dir(home), f"{h}.json"))
    return path


def cmd_start(args) -> int:
    # Tier 2 (files) + tier 1 (CLI/env) resolution, viper-style precedence
    # (cmd/celestia-appd/cmd/root.go:33,55,72-80).
    from celestia_app_tpu.cmd.config import (
        load_configs,
        min_gas_price_from_config,
        resolve_option,
    )

    consensus_cfg, app_cfg = load_configs(args.home)
    args.snapshot_interval = resolve_option(
        args.snapshot_interval, "SNAPSHOT_INTERVAL",
        app_cfg.statesync.snapshot_interval, 1500, cast=int,
    )
    args.block_interval = resolve_option(
        args.block_interval, "BLOCK_INTERVAL", None, 15.0, cast=float
    )
    # Min gas price resolves lazily tier by tier: a malformed app.toml must
    # not block a start that overrides it from the CLI or environment.
    cli_price = getattr(args, "min_gas_price", None)
    env_price = os.environ.get("CELESTIA_MIN_GAS_PRICE")
    if cli_price is not None:
        min_gas = Dec.from_str(cli_price)
    elif env_price is not None:
        min_gas = Dec.from_str(env_price)
    else:
        min_gas = min_gas_price_from_config(app_cfg)
    app = load_app(args.home, node_min_gas_price=min_gas)
    if args.warmup != "none":
        from celestia_app_tpu.da.eds import warmup
        from celestia_app_tpu.parallel.pipeline import env_batch_cap

        upto = app.max_effective_square_size()
        sizes = [1, upto] if args.warmup == "minimal" else None
        # A server running with $CELESTIA_PIPE_BATCH=B (or =auto, whose
        # ceiling is the auto batch) also warms the coalesced-dispatch
        # programs up to that cap, so the dispatcher's first batched
        # block never pays a compile on the block path.
        batch_cap = env_batch_cap()
        batches = tuple(range(2, batch_cap + 1)) if batch_cap > 1 else ()
        t0 = time.time()
        warmed = warmup(square_sizes=sizes, upto=None if sizes else upto,
                        batches=batches)
        # $CELESTIA_WARMUP_K: extra square sizes beyond the app's cap —
        # the giant-square knob.  An operator serving k=1024 blocks with
        # $CELESTIA_PIPE_PANEL set warms the panel lowering's programs
        # here (warmup resolves the mode PER SIZE) — and with
        # $CELESTIA_EXTEND_SHARDS on top, the SHARDED panel partition's
        # collective programs (kernels/panel_sharded.py) — so the first
        # giant block never eats the compile; without it the panel (or
        # collective) compiles would land on the block path (reference
        # TimeoutPropose is 10s).
        from celestia_app_tpu.da.eds import extra_warmup_sizes

        extra = sorted(set(extra_warmup_sizes()) - set(warmed))
        if extra:
            warmed += warmup(square_sizes=extra)
        print(f"warmed square sizes {warmed} in {time.time() - t0:.1f}s"
              + (f" (incl. batch sizes {list(batches)})" if batches else ""),
              flush=True)
    node = None
    peers = [u for u in (getattr(args, "peers", "") or "").split(",") if u]
    if peers and not getattr(args, "n_validators", 0):
        # A peer list without the network size would quietly run a
        # single-validator valset that self-commits with a quorum of one
        # and forks from the network it was told to join.
        print("FATAL: --peers requires --n-validators (the network's "
              "total validator count)", file=sys.stderr)
        return 1
    if (getattr(args, "grpc", False) or getattr(args, "api", False)) and not (
        getattr(args, "serve", False) or peers
    ):
        print("FATAL: --grpc/--api require --serve (the planes share the "
              "serving node)", file=sys.stderr)
        return 1
    if getattr(args, "serve", False) or peers:
        from celestia_app_tpu.rpc.server import ServingNode, serve as rpc_serve

        node = ServingNode(
            app=app,
            validator_index=getattr(args, "validator_index", 0),
            n_validators=getattr(args, "n_validators", 1) or 1,
            peers=peers,
        )
        server = rpc_serve(node, port=args.rpc_port, block_interval_s=None)
        print(f"RPC serving on {server.url}", flush=True)
        if getattr(args, "grpc", False):
            from celestia_app_tpu.rpc.grpc_plane import serve_grpc

            grpc_plane = serve_grpc(node, port=getattr(args, "grpc_port", 0))
            print(f"gRPC serving on {grpc_plane.target} "
                  f"(debug {grpc_plane.debug_url})", flush=True)
        if getattr(args, "api", False):
            from celestia_app_tpu.rpc.api_gateway import serve_api

            api_gw = serve_api(node, port=getattr(args, "api_port", 0))
            print(f"API serving on {api_gw.url}", flush=True)
    if peers:
        # Multi-validator mode: consensus runs through the gossip round
        # machine (rpc/gossip.py) — this daemon is one validator of a
        # network, like `celestia-appd start` joining a chain.  The WAL
        # (double-sign protection) lives under the home dir.
        wal_path = os.path.join(args.home, "data", "consensus-wal.jsonl")
        driver = node.enable_gossip_consensus(
            interval_s=args.block_interval if not args.no_sleep else 0.05,
            wal_path=wal_path,
        )
        from celestia_app_tpu.rpc.client import RemoteNode

        for peer_url in peers:
            # Bounded exponential backoff with deterministic jitter: a
            # peer that takes a minute to warm its jit cache should not be
            # hammered 5x/second the whole time, and when the wait DOES
            # time out the operator sees the last underlying error (DNS?
            # connection refused? a 500?) instead of a bare deadline.
            peer = RemoteNode(peer_url, defer_status=True, timeout=2.0)
            deadline = time.time() + 120
            delay, attempt, last_err = 0.2, 0, None
            while True:
                try:
                    peer.status()
                    break
                except Exception as e:  # chaos-ok: peer warm-up probe loop
                    last_err = e
                    if time.time() > deadline:
                        raise TimeoutError(
                            f"peer {peer_url} never came up after "
                            f"{attempt + 1} attempts "
                            f"(last error: {type(e).__name__}: {e})"
                        ) from e
                    import hashlib

                    digest = hashlib.sha256(
                        f"{peer_url}:{attempt}".encode()
                    ).digest()
                    jitter = 0.25 * delay * (digest[0] / 255.0)
                    time.sleep(min(delay + jitter, 5.0))
                    delay = min(delay * 2, 5.0)
                    attempt += 1
        driver.start()
        print(f"gossip consensus started (wal: {wal_path})", flush=True)
        last_saved = app.height
        try:
            while True:
                time.sleep(max(args.block_interval, 1.0))
                with node.lock:
                    if app.height != last_saved:
                        save_app(args.home, app)
                        last_saved = app.height
        except KeyboardInterrupt:
            return 0
    print(f"chain {app.chain_id} at height {app.height}, producing blocks...",
          flush=True)
    produced = 0
    while args.blocks == 0 or produced < args.blocks:
        time_ns = max(time.time_ns(), app.last_block_time_ns + 1)
        if node is not None:
            # Served mode: production goes through the node so mempool txs
            # from RPC broadcasts are included and indexed for tx queries
            # (produce_block runs the full propose/validate/commit round).
            # Same wall-clock block time as the manual path below — chain
            # time must not depend on the serving mode.
            data, _ = node.produce_block(time_ns=time_ns)
        else:
            data = app.prepare_proposal([])
            if not app.process_proposal(data):
                print("FATAL: node rejected its own proposal", file=sys.stderr)
                return 1
            app.finalize_block(time_ns, list(data.txs))
            app.commit()
        # Under --serve, RPC handler threads can also commit blocks; hold
        # the node lock so the on-disk snapshot is never torn mid-commit.
        with node.lock if node is not None else contextlib.nullcontext():
            save_app(args.home, app)
            if args.snapshot_interval and app.height % args.snapshot_interval == 0:
                _write_snapshot(args.home, app)
        produced += 1
        print(
            f"height={app.height} square={data.square_size} "
            f"data_root={data.hash.hex()[:16]}... app_hash={app.cms.last_app_hash.hex()[:16]}..."
        )
        if args.blocks == 0 or produced < args.blocks:
            time.sleep(args.block_interval if not args.no_sleep else 0)
    return 0


def cmd_snapshot(args) -> int:
    if args.action == "create":
        app = load_app(args.home)
        print(f"wrote {_write_snapshot(args.home, app)}")
        return 0
    if args.action == "list":
        d = _snapshot_dir(args.home)
        for p in sorted(os.listdir(d)) if os.path.isdir(d) else []:
            print(p)
        return 0
    # restore: load a snapshot as the working state (state-sync join).
    path = os.path.join(_snapshot_dir(args.home), f"{args.height}.json")
    with open(path) as f:
        snap = json.load(f)
    app = load_app(args.home)
    app.cms = CommitStore()
    app.cms._committed[snap["height"]] = {
        bytes.fromhex(k): bytes.fromhex(v) for k, v in snap["state"].items()
    }
    app.cms.load_height(snap["height"])
    app.height = snap["height"]
    app.app_version = snap["app_version"]
    save_app(args.home, app)
    print(f"restored height {app.height} (app_hash {app.cms.last_app_hash.hex()[:16]}...)")
    return 0


def cmd_status(args) -> int:
    app = load_app(args.home)
    print(
        json.dumps(
            {
                "chain_id": app.chain_id,
                "height": app.height,
                "app_version": app.app_version,
                "app_hash": app.cms.last_app_hash.hex(),
            },
            indent=2,
        )
    )
    return 0


def cmd_export(args) -> int:
    app = load_app(args.home)
    state = {k.hex(): v.hex() for k, v in app.cms.export().items()}
    json.dump(
        {"height": app.height, "chain_id": app.chain_id, "state": state},
        sys.stdout,
        indent=2,
    )
    print()
    return 0


def cmd_check_invariants(args) -> int:
    """x/crisis on demand (the sdk's MsgVerifyInvariant / invariant-check
    path): run every registered module invariant against committed state."""
    from celestia_app_tpu.modules.crisis import InvariantBroken, assert_invariants

    app = load_app(args.home)
    try:
        names = assert_invariants(app.cms.working)
    except InvariantBroken as e:
        print(f"INVARIANT BROKEN at height {app.height}: {e}", file=sys.stderr)
        return 1
    print(f"ok: {len(names)} invariants hold at height {app.height}: "
          + ", ".join(names))
    return 0


def cmd_rollback(args) -> int:
    app = load_app(args.home)
    if app.height == 0:
        print("nothing to roll back", file=sys.stderr)
        return 1
    # Reference rollback (cmd root.go:129 via sdk server): drop last height.
    app.cms.rollback()
    app.height = app.cms.last_height
    save_app(args.home, app)
    print(f"rolled back to height {app.height}")
    return 0


def cmd_tx_pfb(args) -> int:
    """Single-node devnet PFB submission (BASELINE config 1; reference CLI
    x/blob/client/cli/payforblob.go:43): build, sign with a genesis dev key,
    run one block, verify inclusion, persist."""
    from celestia_app_tpu.crypto import PrivateKey
    from celestia_app_tpu.modules.blob.types import estimate_gas
    from celestia_app_tpu.shares.namespace import Namespace
    from celestia_app_tpu.shares.sparse import Blob
    from celestia_app_tpu.state.accounts import AuthKeeper
    from celestia_app_tpu.user.signer import Signer

    app = load_app(args.home)
    with open(_genesis_path(args.home)) as f:
        chain_id = json.load(f)["chain_id"]
    key = PrivateKey.from_seed(f"{chain_id}-account-{args.account}".encode())
    addr = key.public_key().address()
    acc = AuthKeeper(app.cms.working).get_account(addr)
    if acc is None:
        print(f"dev account {addr} not in genesis", file=sys.stderr)
        return 1

    data = open(args.file, "rb").read() if args.file else os.urandom(args.random_bytes)
    ns = Namespace.v0(bytes.fromhex(args.namespace))
    blob = Blob(ns, data)
    gas = estimate_gas([len(data)])
    signer = Signer(chain_id)
    signer.add_account(key, acc.account_number, acc.sequence)
    raw = signer.create_pay_for_blobs(addr, [blob], gas, gas)

    check = app.check_tx(raw)
    if check.code != 0:
        print(f"CheckTx rejected: {check.log}", file=sys.stderr)
        return 1
    block = app.prepare_proposal([raw])
    if not app.process_proposal(block):
        print("proposal rejected", file=sys.stderr)
        return 1
    results = app.finalize_block(max(time.time_ns(), app.last_block_time_ns + 1), list(block.txs))
    app.commit()
    save_app(args.home, app)
    print(
        json.dumps(
            {
                "height": app.height,
                "code": results[0].code if results else 1,
                "gas_used": results[0].gas_used if results else 0,
                "square_size": block.square_size,
                "data_root": block.hash.hex(),
            }
        )
    )
    return 0


def cmd_query_balance(args) -> int:
    from celestia_app_tpu.state.accounts import BankKeeper

    app = load_app(args.home)
    print(json.dumps({"address": args.address, "balance": BankKeeper(app.cms.working).balance(args.address)}))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="celestia-appd-tpu", description=__doc__)
    parser.add_argument("--home", default=DEFAULT_HOME)
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("init", help="initialize a home dir + genesis")
    p.add_argument("chain_id")
    p.add_argument("--accounts", type=int, default=4)
    p.add_argument("--validators", type=int, default=3)
    p.add_argument("--gov-max-square-size", type=int, default=64)
    p.set_defaults(fn=cmd_init)

    p = sub.add_parser("start", help="run the node loop")
    p.add_argument("--blocks", type=int, default=0, help="0 = forever")
    # None = unset: the 3-tier resolution in cmd_start falls back to env
    # CELESTIA_* then config.toml/app.toml then the built-in defaults.
    p.add_argument("--block-interval", type=float, default=None)
    p.add_argument("--no-sleep", action="store_true")
    p.add_argument("--snapshot-interval", type=int, default=None)
    p.add_argument("--min-gas-price", default=None,
                   help="node min gas price in utia (tier-1 override)")
    p.add_argument("--serve", action="store_true",
                   help="serve the JSON-RPC endpoint (broadcast/query/proofs)")
    p.add_argument("--grpc", action="store_true",
                   help="with --serve: also serve the cosmos gRPC plane")
    p.add_argument("--grpc-port", type=int, default=0,
                   help="gRPC port (0 = ephemeral)")
    p.add_argument("--api", action="store_true",
                   help="with --serve: also serve the REST API gateway "
                        "(the grpc-gateway plane, reference port 1317)")
    p.add_argument("--api-port", type=int, default=0,
                   help="API gateway port (0 = ephemeral)")
    p.add_argument("--peers", default="",
                   help="comma-separated peer RPC URLs: join as one gossip "
                        "validator of a network (implies --serve)")
    p.add_argument("--validator-index", type=int, default=0,
                   help="this validator's index in the network's valset")
    p.add_argument("--n-validators", type=int, default=0,
                   help="total validators in the network (gossip mode)")
    p.add_argument("--rpc-port", type=int, default=26657)
    p.add_argument("--warmup", choices=["none", "minimal", "all"],
                   default="minimal",
                   help="AOT-compile square pipelines at startup: minimal "
                        "(k=1 + max), all (every power of two up to max)")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("snapshot", help="state-sync snapshots")
    p.add_argument("action", choices=["create", "list", "restore"])
    p.add_argument("--height", type=int, default=0)
    p.set_defaults(fn=cmd_snapshot)

    p = sub.add_parser("tx-pay-for-blob", help="submit a PFB on the local devnet")
    p.add_argument("--namespace", default="deadbeef")
    p.add_argument("--file", default=None)
    p.add_argument("--random-bytes", type=int, default=10_000)
    p.add_argument("--account", type=int, default=0)
    p.set_defaults(fn=cmd_tx_pfb)

    p = sub.add_parser("query-balance", help="query an account balance")
    p.add_argument("address")
    p.set_defaults(fn=cmd_query_balance)

    p = sub.add_parser("status", help="print chain status")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("export", help="export app state as JSON")
    p.set_defaults(fn=cmd_export)

    p = sub.add_parser("rollback", help="drop the latest committed height")
    p.set_defaults(fn=cmd_rollback)

    p = sub.add_parser(
        "check-invariants", help="run x/crisis module invariants"
    )
    p.set_defaults(fn=cmd_check_invariants)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

"""Batched fixed-shape SHA-256 on the VPU.

The DA pipeline's hash workload (reference hot loop (2), SURVEY 3.2: 4k NMT
builds x 2k leaves at k=512 ~ 4.2M compressions per block) is thousands of
*independent* fixed-length messages - ideal for lane-parallel execution: one
uint32 lane per message, rounds unrolled, message lengths static so padding
is a compile-time constant concat.

Replaces Go's crypto/sha256 assembly behind appconsts.NewBaseHashFunc
(reference pkg/appconsts/global_consts.go:86).  All message shapes used by
the square pipeline are fixed:

    NMT leaf   0x00 || ns(29) || share(512)        = 542 B -> 9 blocks
    NMT node   0x01 || left(90) || right(90)       = 181 B -> 3 blocks
    merkle leaf 0x00 || row-or-col root(90)        =  91 B -> 2 blocks
    merkle node 0x01 || h(32) || h(32)             =  65 B -> 2 blocks
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_H0 = np.array(
    [0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
     0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19],
    dtype=np.uint32,
)


def _rotr(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _compress(state: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    """One SHA-256 compression. state: (N, 8) uint32; block: (N, 16) uint32.

    Graph-size-conscious: a fori_loop over 4 chunks of 16 rounds each.
    Within a chunk every schedule index is static (round r uses w[r]), so the
    VPU sees straight-line vector code; across chunks the 16-word schedule
    window is recomputed in place.  ~16x smaller HLO than full unrolling,
    which keeps AOT warmup of all square sizes off the critical path
    (SURVEY hard part 4).

    Layout: the 16 schedule words ride the carry as SEPARATE (N,) vectors —
    the batch axis N is the only array axis anywhere in the loop, so every
    op is a full-lane VPU op with no strided (N, 16) column slicing.
    """
    k_chunks = jnp.asarray(_K.reshape(4, 16))

    def chunk(c, carry):
        a, b, cc, d, e, f, g, h = carry[:8]
        ws = list(carry[8:])  # 16 x (N,)
        kc = k_chunks[c]  # (16,) uint32
        for r in range(16):
            s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
            ch = (e & f) ^ (~e & g)
            t1 = h + s1 + ch + kc[r] + ws[r]
            s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
            maj = (a & b) ^ (a & cc) ^ (b & cc)
            t2 = s0 + maj
            h, g, f, e, d, cc, b, a = g, f, e, d + t1, cc, b, a, t1 + t2
        # next 16 schedule words: w'[r] = w[r] + s0(w[r+1]) + w[r+9] + s1(w[r+14])
        # (indices >= 16 refer to already-updated entries, handled by ordering)
        for r in range(16):
            x15 = ws[(r + 1) % 16]
            x2 = ws[(r + 14) % 16]
            s0 = _rotr(x15, 7) ^ _rotr(x15, 18) ^ (x15 >> np.uint32(3))
            s1 = _rotr(x2, 17) ^ _rotr(x2, 19) ^ (x2 >> np.uint32(10))
            ws[r] = ws[r] + s0 + ws[(r + 9) % 16] + s1
        return (a, b, cc, d, e, f, g, h, *ws)

    init = tuple(state[:, i] for i in range(8)) + tuple(
        block[:, r] for r in range(16)
    )
    out = jax.lax.fori_loop(0, 4, chunk, init)
    return state + jnp.stack(out[:8], axis=1)


def _pad_tail(length: int) -> np.ndarray:
    """The constant SHA-256 padding appended to every length-`length` message."""
    padded = ((length + 9 + 63) // 64) * 64
    tail = np.zeros(padded - length, dtype=np.uint8)
    tail[0] = 0x80
    tail[-8:] = np.frombuffer((length * 8).to_bytes(8, "big"), dtype=np.uint8)
    return tail


def _message_words(msgs: jnp.ndarray) -> jnp.ndarray:
    """(N, L) uint8 messages -> (N, nblocks, 16) big-endian uint32 words
    with the constant SHA-256 padding appended."""
    n, length = msgs.shape
    tail = _pad_tail(length)
    full = jnp.concatenate(
        [msgs, jnp.broadcast_to(jnp.asarray(tail), (n, len(tail)))], axis=1
    )
    nblocks = full.shape[1] // 64
    words = full.reshape(n, nblocks, 16, 4).astype(jnp.uint32)
    return (
        (words[..., 0] << np.uint32(24))
        | (words[..., 1] << np.uint32(16))
        | (words[..., 2] << np.uint32(8))
        | words[..., 3]
    )  # (N, nblocks, 16)


def _digest_bytes(out: jnp.ndarray) -> jnp.ndarray:
    """(N, 8) uint32 state -> (N, 32) big-endian digest bytes."""
    shifts = np.uint32(8) * np.arange(3, -1, -1, dtype=np.uint32)
    by = (out[..., None] >> shifts) & np.uint32(0xFF)
    return by.astype(jnp.uint8).reshape(out.shape[0], 32)


def _sha256_jnp(msgs: jnp.ndarray) -> jnp.ndarray:
    """The XLA-fused reference path (every platform)."""
    n = msgs.shape[0]
    words = _message_words(msgs)
    nblocks = words.shape[1]
    state = jnp.broadcast_to(jnp.asarray(_H0), (n, 8))
    if nblocks == 1:
        out = _compress(state, words[:, 0])
    else:
        # scan over blocks: graph size independent of message length
        out, _ = jax.lax.scan(
            lambda s, blk: (_compress(s, blk), None),
            state,
            words.transpose(1, 0, 2),
        )
    return _digest_bytes(out)


# --------------------------------------------------------------------------
# Pallas path: messages ride the LANES, all 64 rounds live in vregs
# --------------------------------------------------------------------------

_LANE_TILE = 1024  # messages per grid step: 8 sublanes x 128 lanes


def _pallas_kernel(nblocks: int):
    """words_ref: (nblocks, 16, TN) uint32 -> out_ref: (8, TN) uint32.

    One kernel instance hashes TN messages in lock-step: every round is a
    full-lane VPU op on (TN,) vectors held in vector registers — the
    schedule window (16 words) + state (8) never round-trip through HBM,
    which is where the jnp path loses ~6x (measured 161 ms for the k=512
    NMT phase at ~16% of VPU int32 peak).
    """
    k_chunks = _K.reshape(4, 16)

    def kernel(words_ref, out_ref):
        state = tuple(
            jnp.full((out_ref.shape[1],), h, dtype=jnp.uint32) for h in _H0
        )

        def block_step(b, st):
            ws0 = words_ref[b]  # (16, TN)
            a, bb, cc, d, e, f, g, h = st
            ws = [ws0[r] for r in range(16)]
            # 4 chunks x 16 rounds, statically unrolled: round constants
            # stay python scalars (a captured K array would have to be a
            # pallas input) and every op is a full-lane vreg op.
            for c in range(4):
                kc = k_chunks[c]
                for r in range(16):
                    s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
                    ch = (e & f) ^ (~e & g)
                    t1 = h + s1 + ch + np.uint32(kc[r]) + ws[r]
                    s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
                    maj = (a & bb) ^ (a & cc) ^ (bb & cc)
                    t2 = s0 + maj
                    h, g, f, e, d, cc, bb, a = g, f, e, d + t1, cc, bb, a, t1 + t2
                if c < 3:
                    for r in range(16):
                        x15 = ws[(r + 1) % 16]
                        x2 = ws[(r + 14) % 16]
                        s0 = _rotr(x15, 7) ^ _rotr(x15, 18) ^ (x15 >> np.uint32(3))
                        s1 = _rotr(x2, 17) ^ _rotr(x2, 19) ^ (x2 >> np.uint32(10))
                        ws[r] = ws[r] + s0 + ws[(r + 9) % 16] + s1
            out = (a, bb, cc, d, e, f, g, h)
            return tuple(s + o for s, o in zip(st, out))

        final = jax.lax.fori_loop(0, nblocks, block_step, state)
        for i in range(8):
            out_ref[i] = final[i]

    return kernel


def _sha256_pallas(msgs: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    from jax.experimental import pallas as pl

    n = msgs.shape[0]
    words = _message_words(msgs)  # (N, nblocks, 16)
    nblocks = words.shape[1]
    pad = (-n) % _LANE_TILE
    if pad:
        words = jnp.concatenate(
            [words, jnp.zeros((pad, nblocks, 16), jnp.uint32)], axis=0
        )
    total = n + pad
    words_t = words.transpose(1, 2, 0)  # (nblocks, 16, N) — lanes = messages
    out = pl.pallas_call(
        _pallas_kernel(nblocks),
        grid=(total // _LANE_TILE,),
        in_specs=[
            pl.BlockSpec((nblocks, 16, _LANE_TILE), lambda i: (0, 0, i))
        ],
        out_specs=pl.BlockSpec((8, _LANE_TILE), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((8, total), jnp.uint32),
        interpret=interpret,
    )(words_t)
    return _digest_bytes(out.T[:n])


# --------------------------------------------------------------------------
# Fused NMT-leaf kernel: message construction + padding + packing in VMEM
# --------------------------------------------------------------------------

from celestia_app_tpu.constants import NAMESPACE_SIZE as _NS, SHARE_SIZE as _SS

_LEAF_LEN = 1 + _NS + _SS  # 0x00 || ns(29) || share(512) = 542
_LEAF_BLOCKS = 9  # padded to 576 bytes


def _leaf_tile_compute(ns_tile, share_tile, tn: int):
    """The fused per-tile computation: (TN, 29) + (TN, 512) uint8 ->
    (8, TN) uint32 digest words of 0x00 || ns || share.

    Pure jnp — the pallas kernel wraps exactly this function, and the
    off-TPU tests jit it directly (interpret mode cannot execute the
    ~7k-op unrolled round structure in reasonable time).

    SEAM: kernels/rs_xor._epi_kernel (the extend+leaf-hash epilogue,
    pipeline mode "fused_epi") also wraps this function, feeding it
    column-phase extend tiles straight from VMEM — keep the signature
    and digest semantics stable or both fused paths fork at once (the
    shared function is what makes their digests provably identical)."""
    k_chunks = _K.reshape(4, 16)
    # 34 tail bytes (0x80, zeros, bit length) as python ints: a captured
    # constant ARRAY would have to be a pallas input; scalar fulls go
    # straight into the kernel as immediates.
    tail = [int(v) for v in _pad_tail(_LEAF_LEN)]

    def message_block(b: int) -> jnp.ndarray:
        """(TN, 64) uint8: bytes [64b, 64b+64) of the padded leaf."""
        if b == 0:
            prefix = jnp.zeros((tn, 1), dtype=jnp.uint8)
            return jnp.concatenate(
                [prefix, ns_tile, share_tile[:, :34]], axis=1
            )
        if b < 8:
            lo = 34 + 64 * (b - 1)
            return share_tile[:, lo:lo + 64]
        pad = jnp.concatenate(
            [jnp.full((tn, 1), v, dtype=jnp.uint8) for v in tail],
            axis=1,
        )
        return jnp.concatenate([share_tile[:, 482:], pad], axis=1)

    a, bb, cc, d, e, f, g, h = (
        jnp.full((tn,), v, dtype=jnp.uint32) for v in _H0
    )
    for b in range(_LEAF_BLOCKS):  # static: shapes fixed per block
        by = message_block(b).astype(jnp.uint32).reshape(tn, 16, 4)
        words = (
            (by[:, :, 0] << np.uint32(24))
            | (by[:, :, 1] << np.uint32(16))
            | (by[:, :, 2] << np.uint32(8))
            | by[:, :, 3]
        )  # (TN, 16)
        ws0 = words.T  # tile-local transpose: lanes = messages
        sa, sb, sc, sd, se, sf, sg, sh = a, bb, cc, d, e, f, g, h
        ws = [ws0[r] for r in range(16)]
        for c in range(4):
            kc = k_chunks[c]
            for r in range(16):
                s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
                ch = (e & f) ^ (~e & g)
                t1 = h + s1 + ch + np.uint32(kc[r]) + ws[r]
                s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
                maj = (a & bb) ^ (a & cc) ^ (bb & cc)
                t2 = s0 + maj
                h, g, f, e, d, cc, bb, a = g, f, e, d + t1, cc, bb, a, t1 + t2
            if c < 3:
                for r in range(16):
                    x15 = ws[(r + 1) % 16]
                    x2 = ws[(r + 14) % 16]
                    s0 = _rotr(x15, 7) ^ _rotr(x15, 18) ^ (x15 >> np.uint32(3))
                    s1 = _rotr(x2, 17) ^ _rotr(x2, 19) ^ (x2 >> np.uint32(10))
                    ws[r] = ws[r] + s0 + ws[(r + 9) % 16] + s1
        a, bb, cc, d = sa + a, sb + bb, sc + cc, sd + d
        e, f, g, h = se + e, sf + f, sg + g, sh + h
    return jnp.stack((a, bb, cc, d, e, f, g, h), axis=0)


def _leaf_kernel(tn: int):
    """ns_ref (TN, 29) + share_ref (TN, 512) uint8 -> out_ref (8, TN).

    The unfused path materializes every leaf's padded 576-byte message
    AND its lane-major transpose in HBM (~2.3 GB each way at k=512)
    before the rounds read them; here each block's 64-byte slice is
    assembled from the natural-layout refs in VMEM — the prefix byte,
    namespace, share window, and the constant SHA padding — packed to
    big-endian words and transposed tile-locally, so HBM sees only the
    raw shares in and 32-byte digests out.
    """

    def kernel(ns_ref, share_ref, out_ref):
        out_ref[...] = _leaf_tile_compute(ns_ref[...], share_ref[...], tn)

    return kernel


def sha256_leaves_pallas(
    ns: jnp.ndarray,
    shares: jnp.ndarray,
    interpret: bool = False,
    tile: int = _LANE_TILE,
) -> jnp.ndarray:
    """NMT leaf digests with fused message construction.

    ns: (N, 29) uint8, shares: (N, 512) uint8 -> (N, 32) digests of
    0x00 || ns || share. Bit-identical to sha256(concat(...)) — pinned
    by tests/test_sha_fused.py.
    """
    from jax.experimental import pallas as pl

    from celestia_app_tpu.constants import NAMESPACE_SIZE

    n = shares.shape[0]
    assert ns.shape == (n, NAMESPACE_SIZE) and shares.shape[1] == 512, (
        ns.shape, shares.shape)
    pad = (-n) % tile
    if pad:
        ns = jnp.concatenate(
            [ns, jnp.zeros((pad, NAMESPACE_SIZE), jnp.uint8)], axis=0)
        shares = jnp.concatenate(
            [shares, jnp.zeros((pad, 512), jnp.uint8)], axis=0)
    total = n + pad
    out = pl.pallas_call(
        _leaf_kernel(tile),
        grid=(total // tile,),
        in_specs=[
            pl.BlockSpec((tile, NAMESPACE_SIZE), lambda i: (i, 0)),
            pl.BlockSpec((tile, 512), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((8, tile), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((8, total), jnp.uint32),
        interpret=interpret,
    )(ns, shares)
    return _digest_bytes(out.T[:n])


def _use_pallas_fused_leaves(n: int) -> bool:
    """$CELESTIA_SHA_FUSED: on / off / auto (default). Auto keeps it OFF
    everywhere — unmeasured on hardware; the bench parts stage measures
    it as the nmt_dah_plf candidate and flips this env for the rows it
    wins. Even when on, tiny batches stay on the jnp path (same
    4-tile gate as _use_pallas: a near-empty lane tile wastes the
    kernel)."""
    import os

    mode = os.environ.get("CELESTIA_SHA_FUSED", "auto")
    if mode == "off" or pl_missing():
        return False
    if mode == "on":
        return n >= 4 * _LANE_TILE
    return False


def pl_missing() -> bool:
    try:
        from jax.experimental import pallas  # noqa: F401

        return False
    except Exception:  # pragma: no cover — chaos-ok: probe-only fallback
        return True


def _use_pallas(n: int) -> bool:
    """$CELESTIA_SHA_PALLAS: on / off / auto (default).  Auto uses the
    Pallas kernel on TPU for batches big enough to fill the lane tiles;
    tiny batches (top merkle levels, host conveniences) stay on the
    fused-jnp path everywhere."""
    import os

    mode = os.environ.get("CELESTIA_SHA_PALLAS", "auto")
    if mode == "off":
        return False
    if mode == "on":
        return True
    try:
        # Device platform, not jax.default_backend(): the axon TPU plugin
        # registers under its own backend name while its devices report
        # platform "tpu" — keying on the backend name would silently leave
        # the Pallas kernel disabled on the real chip.
        platform = jax.devices()[0].platform
    except Exception:  # chaos-ok: no backend: host-side tracing only
        return False
    return platform == "tpu" and n >= 4 * _LANE_TILE


def sha256(msgs: jnp.ndarray) -> jnp.ndarray:
    """Batched SHA-256 over same-length messages: (N, L) uint8 -> (N, 32) uint8.

    L is static (trace-time constant), so padding is a constant-tail concat
    and the block loop fully unrolls.  Large batches on TPU run the Pallas
    lane-parallel kernel; identical digests either way (tests pin it).
    """
    if _use_pallas(msgs.shape[0]):
        return _sha256_pallas(msgs)
    return _sha256_jnp(msgs)


def sha256_bytes(data: bytes) -> bytes:
    """Single-message host convenience (used by tests/tools, not hot paths)."""
    out = sha256(jnp.frombuffer(data, dtype=jnp.uint8).reshape(1, -1))
    return bytes(np.asarray(out)[0])

"""Fused single-dispatch extend+DAH device pipeline.

One jitted program takes the k x k ODS as a single uint8 array and returns
the EDS, the 4k row/col NMT roots, and the final DAH data root with no
intermediate host transfer: RS row-extend -> RS col-extend -> share-to-leaf
namespace prefixing -> batched SHA-256 tree reduction, all inside one XLA
dispatch (reference hot path app/prepare_proposal.go:61-71 ->
pkg/da/data_availability_header.go:44-108).

Differences from the staged composition in da/eds.py's `_pipeline` (which
chains kernels/rs.extend_square_fn and da/eds.roots_fn):

  * `jit_extend_and_dah(..., donate=True)` donates the ODS argument, so
    XLA may reuse the caller's share buffer as scratch for the 4x
    extension instead of holding both live (the HBM high-water mark at
    k=512 drops by one 134 MB ODS);
  * a `roots_only` lowering drops the EDS from the outputs entirely —
    a DAH-only caller (block production needs just the roots once the
    shares are gossiped elsewhere) lets XLA free every share buffer
    before the tree reduction finishes;
  * one compile cache entry and one dispatch own the whole block path, so
    the autotuner can A/B it as a unit against the staged pair (whose
    extend/hash halves are also what the `parts` bench decomposes).
    The leaf schedule itself deliberately matches the staged path — all
    4k^2 leaves hash in ONE batched call; hashing the two square halves
    separately (to overlap with the column encode) was tried and measured
    slower on a serial schedule (smaller SHA batches, no real overlap).

Bit-identity with the staged path is pinned by tests/test_fused_pipeline.py
on the reference golden vectors; the bench autotuner (bench.py `parts` row)
measures `fused` against the seated staged RS + NMT pair and keeps
whichever wins.

Selection seam: $CELESTIA_PIPE_FUSED = "on" / "off" / "auto" (default:
fused).  da/eds.jit_pipeline routes through `pipeline_mode()`, so every
caller — ExtendedDataSquare, extend_block, BlockPipeline, repair's
re-extend — flips together and none can diverge.
"""

from __future__ import annotations

import os
import warnings
from functools import lru_cache

import jax
import jax.numpy as jnp

from celestia_app_tpu.constants import NAMESPACE_SIZE, PARITY_NAMESPACE_BYTES
from celestia_app_tpu.gf.rs import active_construction
from celestia_app_tpu.kernels.merkle import merkle_root_pow2
from celestia_app_tpu.kernels.nmt import leaf_digests, tree_roots_from_digests
from celestia_app_tpu.kernels.rs import encode_fn

@lru_cache(maxsize=None)
def _silence_unusable_donation_warning() -> None:
    """On backends without donation support (CPU), every donated dispatch
    warns and keeps the copy — expected, not actionable, so filter it the
    first time a donating program is built there.  Donation-capable
    backends keep the warning live: a donation that silently stops taking
    effect is a real perf regression someone should see."""
    if jax.default_backend() == "cpu":
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )


def pipeline_mode() -> str:
    """The active extend+DAH lowering: "fused" (default), "fused_epi",
    "staged", or "host" (all four bit-identical).

    $CELESTIA_PIPE_FUSED: "on" / "off" / "epi" / "auto" (default).  Auto
    is fused — the fused program is bit-identical to the staged pair
    (pinned on the golden vectors) and at worst matches it, so the staged
    path exists as a bench A/B candidate and an escape hatch, not a
    default.  "epi" selects the leaf-hash-epilogue variant (the column-
    phase extend feeds the bottom half's NMT leaf rounds from VMEM,
    kernels/rs_xor.extend_leaf_digests).  The bench autotuner flips this
    env for whichever candidate the parts row seats.

    The env choice is then floored by the degradation ladder
    (chaos/degrade.py): a process whose device dispatches keep failing is
    stepped fused_epi -> fused -> staged -> host by the circuit breaker,
    and because every caller routes through here, all of them move
    together.
    """
    from celestia_app_tpu.chaos.degrade import effective_device_mode

    return effective_device_mode(env_base_mode())


def env_base_mode() -> str:
    """The env-selected base lowering, WITHOUT the degradation ladder
    applied — the single parse of $CELESTIA_PIPE_FUSED (the ladder steps
    relative to this, so two copies of the branch must never diverge)."""
    val = os.environ.get("CELESTIA_PIPE_FUSED", "auto")
    if val == "off":
        return "staged"
    if val == "epi":
        return "fused_epi"
    return "fused"


def env_base_mode_for_k(k: int) -> str:
    """The env-selected base lowering for square size k: "sharded_panel"
    when the multi-chip extend partition engages at this k
    ($CELESTIA_EXTEND_SHARDS on top of the panel seam —
    kernels/panel_sharded.shards_for_k), "panel" when only the
    single-device panel-streaming seam engages ($CELESTIA_PIPE_PANEL —
    kernels/panel.panel_rows), else the k-less env_base_mode().  The
    degradation ladder steps relative to THIS, so a faulting sharded
    collective walks sharded_panel -> panel -> fused_epi/fused ->
    staged -> host."""
    from celestia_app_tpu.kernels.panel import panel_rows

    if not panel_rows(k):
        return env_base_mode()
    from celestia_app_tpu.kernels.panel_sharded import shards_for_k

    return "sharded_panel" if shards_for_k(k) else "panel"


def pipeline_mode_for_k(k: int) -> str:
    """The active extend+DAH lowering for square size k — pipeline_mode()
    with the per-k panel-streaming (and multi-chip panel-partition)
    seams applied above the fused rungs.  All six lowerings are
    bit-identical; the per-k selection is a memory/perf choice, never a
    correctness hazard."""
    from celestia_app_tpu.chaos.degrade import effective_device_mode

    return effective_device_mode(env_base_mode_for_k(k))


def extend_and_dah_fn(
    k: int,
    construction: str | None = None,
    roots_only: bool = False,
    epilogue: bool = False,
):
    """Build the fused program for square size k.

    Returns f(ods) where ods is (k, k, SHARE_SIZE) uint8:
      roots_only=False -> (eds, row_roots, col_roots, droot)
      roots_only=True  -> (row_roots, col_roots, droot)
    with eds (2k, 2k, S), roots (2k, 90), droot (32,).  The RS construction
    is resolved at build time; callers caching the result must key on it.

    epilogue=True is the LEAF-HASH-EPILOGUE variant (pipeline mode
    "fused_epi"): the column-phase extend feeds the bottom half's NMT
    leaf rounds directly from VMEM (kernels/rs_xor.extend_leaf_digests on
    TPU; the same ops staged through XLA elsewhere), so the bottom shares
    land in HBM once as output instead of round-tripping before hashing.
    It splits the leaf batch in two — the earlier experiment that split
    WITHOUT fusing into the extend measured slower, which is exactly why
    this variant is a tuned-seat candidate (bench parts row, >3%
    hysteresis) and not the default.  Bit-identical either way.
    """
    encode = encode_fn(k, construction)
    bottom_fn = None
    if epilogue:
        from celestia_app_tpu.kernels.rs_xor import bottom_leaf_fn

        bottom_fn = bottom_leaf_fn(k, construction, fallback_encode=encode)

    def run(ods: jnp.ndarray):
        parity = jnp.frombuffer(PARITY_NAMESPACE_BYTES, dtype=jnp.uint8)
        # Row phase: each of the k rows is a codeword batch along columns.
        q1 = encode(ods, 1)  # (k, k, S)
        top = jnp.concatenate([ods, q1], axis=1)  # (k, 2k, S)
        # Column phase contracts over the row axis directly — Q2/Q3 arrive
        # as the bottom rows with no transpose (row/col encodes commute).
        if epilogue:
            # Bottom shares + their (constant-namespace) leaf digests in
            # one program; only the top half still needs per-leaf
            # namespace bookkeeping (Q0 own ns, Q1 parity).
            bottom, bot_hashes = bottom_fn(top)  # (k,2k,S), (k,2k,32)
            eds = jnp.concatenate([top, bottom], axis=0)
            col = jnp.arange(2 * k)
            top_ns = jnp.where(
                (col < k)[None, :, None], top[..., :NAMESPACE_SIZE], parity
            )
            t_mins, t_maxs, t_hashes = leaf_digests(top_ns, top)
            par_ns = jnp.broadcast_to(parity, (k, 2 * k, NAMESPACE_SIZE))
            mins = jnp.concatenate([t_mins, par_ns], axis=0)
            maxs = jnp.concatenate([t_maxs, par_ns], axis=0)
            hashes = jnp.concatenate([t_hashes, bot_hashes], axis=0)
        else:
            bottom = encode(top, 0)  # (k, 2k, S)
            eds = jnp.concatenate([top, bottom], axis=0)  # (2k, 2k, S)

            # Q0 leaves carry the share's own namespace, every parity leaf
            # the parity namespace (pkg/wrapper/nmt_wrapper.go:93-114).
            # All 4k^2 leaves hash in ONE batched call — splitting by half
            # measured slower (smaller SHA batches, same serial schedule).
            idx = jnp.arange(2 * k)
            q0 = (idx[:, None] < k) & (idx[None, :] < k)
            row_ns = jnp.where(
                q0[..., None], eds[..., :NAMESPACE_SIZE], parity
            )

            # The digest at (i, j) serves both the row-i and col-j trees,
            # so each leaf is hashed exactly once and the column reduction
            # runs on the transpose (leaf hashing is 9 SHA-256 blocks vs 3
            # for nodes).
            mins, maxs, hashes = leaf_digests(row_ns, eds)
        row_roots = tree_roots_from_digests(mins, maxs, hashes)  # (2k, 90)
        col_roots = tree_roots_from_digests(
            mins.transpose(1, 0, 2),
            maxs.transpose(1, 0, 2),
            hashes.transpose(1, 0, 2),
        )
        droot = merkle_root_pow2(
            jnp.concatenate([row_roots, col_roots], axis=0)
        )
        if roots_only:
            return row_roots, col_roots, droot
        return eds, row_roots, col_roots, droot

    return run


# Keys whose jit wrapper has been built this process — the journal's
# compile hit/miss signal (a miss means the next dispatch traces and
# compiles; a hit reuses the cached executable).
_BUILT_KEYS: set[tuple] = set()


def is_built(
    k: int,
    construction: str | None = None,
    *,
    donate: bool = False,
    roots_only: bool = False,
    epilogue: bool = False,
) -> bool:
    key = (k, construction or active_construction(), donate, roots_only,
           epilogue)
    return key in _BUILT_KEYS


@lru_cache(maxsize=None)
def _jit_extend_and_dah(
    k: int, construction: str, donate: bool, roots_only: bool, epilogue: bool
):
    if donate:
        _silence_unusable_donation_warning()
    # Body runs on cache miss only: note the build for the journal's
    # hit/miss column and the celestia_jit_builds_total counter.
    _BUILT_KEYS.add((k, construction, donate, roots_only, epilogue))
    from celestia_app_tpu.trace.device_ledger import track
    from celestia_app_tpu.trace.journal import note_jit_build

    note_jit_build("extend_and_dah")
    return track(
        jax.jit(
            extend_and_dah_fn(k, construction, roots_only, epilogue=epilogue),
            donate_argnums=(0,) if donate else (),
        ),
        "extend_and_dah", k=k, construction=construction,
        mode="fused_epi" if epilogue else "fused",
    )


def jit_extend_and_dah(
    k: int,
    construction: str | None = None,
    *,
    donate: bool = False,
    roots_only: bool = False,
    epilogue: bool = False,
):
    """Cached jitted fused pipeline, keyed on (k, RS construction, donate,
    roots_only, epilogue).

    donate=True invalidates the caller's ODS device buffer — only pass it
    for a buffer the pipeline owns (a fresh `jnp.asarray` upload, a feeder
    thread's `device_put`), never a view of state the caller reads after
    the call (repair's survivor check re-reads its input, so it must not
    donate).  Backends without donation support (this image's CPU) ignore
    the hint and keep the copy — semantics are unchanged either way.
    """
    return _jit_extend_and_dah(
        k, construction or active_construction(), donate, roots_only,
        epilogue,
    )


# --- batched (vmap'd) multi-square dispatch ---------------------------------
#
# The cross-height continuous-batching leg (parallel/pipeline.py): when
# traffic produces many small same-k squares, B of them dispatch as ONE
# vmapped program over a (B, k, k, S) stack instead of paying B dispatch
# round-trips.  Its own compile-cache family, keyed per (k, construction,
# batch, donate, roots_only) — a batch of 4 k=128 squares is a different
# executable than 4 singles, and the journal's hit/miss column must say
# which one a dispatch paid for.
#
# Sharding contract (SNIPPETS.md pjit notes): the batched program takes no
# explicit in/out_shardings — outputs inherit the committed sharding of the
# batched input, so the (B, ...) layout one height's dispatch produces is
# exactly the layout the next height's dispatch consumes and batches never
# reshard between heights.  (On this image's single CPU device that is
# trivially true; on a mesh the batch axis stays wherever the uploader
# committed it.)
#
# The fused_epi seat deliberately folds into the plain fused body here: the
# leaf-hash epilogue is a per-square VMEM tile schedule (kernels/rs_xor),
# and vmapping a Pallas kernel is its own lowering project — all modes are
# bit-identical, so the batched program uses the one fused body and the
# ladder's epi/fused distinction stays an UNBATCHED perf detail.

_BATCHED_BUILT: set[tuple] = set()


def batched_is_built(
    k: int,
    batch: int,
    construction: str | None = None,
    *,
    donate: bool = False,
    roots_only: bool = False,
) -> bool:
    key = (k, construction or active_construction(), batch, donate,
           roots_only)
    return key in _BATCHED_BUILT


@lru_cache(maxsize=None)
def _jit_extend_and_dah_batched(
    k: int, construction: str, batch: int, donate: bool, roots_only: bool
):
    if donate:
        _silence_unusable_donation_warning()
    _BATCHED_BUILT.add((k, construction, batch, donate, roots_only))
    from celestia_app_tpu.trace.device_ledger import track
    from celestia_app_tpu.trace.journal import note_jit_build

    note_jit_build("extend_and_dah_batched")
    return track(
        jax.jit(
            jax.vmap(extend_and_dah_fn(k, construction, roots_only)),
            donate_argnums=(0,) if donate else (),
        ),
        "extend_and_dah_batched",
        k=k, construction=construction, mode="fused", batch=batch,
    )


def jit_extend_and_dah_batched(
    k: int,
    batch: int,
    construction: str | None = None,
    *,
    donate: bool = False,
    roots_only: bool = False,
):
    """Cached vmapped fused pipeline: f(odss) with odss (batch, k, k, S)
    uint8 -> (eds (batch,2k,2k,S), row_roots (batch,2k,90), col_roots,
    droots (batch,32)) — every square computed exactly as the unbatched
    fused program computes it (pinned bit-identical by
    tests/test_continuous_batching.py).  `batch` is part of the cache key:
    the dispatcher compiles one executable per coalesced size it actually
    sees."""
    if batch < 1:
        raise ValueError(f"batch must be >= 1, got {batch}")
    return _jit_extend_and_dah_batched(
        k, construction or active_construction(), batch, donate, roots_only
    )


# --- forest retention (the serve plane's read side) -------------------------
#
# The block-path program above materializes every NMT level on device and
# keeps only the 4k roots; the proof-serving plane (serve/) needs the WHOLE
# forest — every inner node of every row and column tree — so a batch of
# DAS sample requests is answered by gathers instead of host re-hashing.
#
# Deliberately a SEPARATE single-dispatch program over the retained EDS
# rather than a new output arm of extend_and_dah: widening the block-path
# program would add compile-cache keys and donation variants to every rung
# of the degradation ladder for a product only the read side consumes.
# Admission happens at commit, but the forest dispatch is an ASYNC jax
# enqueue — the leaf re-hash overlaps whatever runs next, and the commit
# path only pays the enqueue plus the (memoized) root reads.  The recompute
# is once per RETAINED height, bounded by $CELESTIA_SERVE_HEIGHTS.


def forest_level_layout(k: int) -> tuple[tuple[int, ...], tuple[int, ...]]:
    """(widths, offsets) of the flattened forest for 2k trees of 2k leaves.

    Level h holds 2k trees x (2k >> h) nodes; the flat (N, 90) array
    concatenates levels leaf-first, each level row-major by tree.  The
    node (tree t, level h, index i) lives at flat[offsets[h] + t*widths[h]
    + i] — the indexing contract serve/sampler.py's gather relies on.
    """
    n = 2 * k
    widths = []
    w = n
    while w >= 1:
        widths.append(w)
        w //= 2
    offsets, off = [], 0
    for w in widths:
        offsets.append(off)
        off += n * w
    return tuple(widths), tuple(offsets)


def forest_fn(k: int):
    """Build f(eds) -> (row_flat, col_flat): the complete namespaced-digest
    forests of both axes, flattened per forest_level_layout.

    Each node is the 90-byte min||max||hash digest (nmt/hasher.py wire
    form), so a proof node is a single flat-array row — byte-identical to
    what the host NamespacedMerkleTree computes for the same leaf
    (tests/test_das_proofs.py pins proof-level identity).
    """
    from celestia_app_tpu.kernels.nmt import (
        leaf_digests,
        tree_levels_from_digests,
    )

    def flatten(levels):
        return jnp.concatenate(
            [
                jnp.concatenate([m, x, h], axis=2).reshape(-1, 90)
                for m, x, h in levels
            ],
            axis=0,
        )

    def run(eds: jnp.ndarray):
        from celestia_app_tpu.da.eds import leaf_namespaces

        row_ns, _ = leaf_namespaces(eds, k)
        mins, maxs, hashes = leaf_digests(row_ns, eds)
        row_levels = tree_levels_from_digests(mins, maxs, hashes)
        col_levels = tree_levels_from_digests(
            mins.transpose(1, 0, 2),
            maxs.transpose(1, 0, 2),
            hashes.transpose(1, 0, 2),
        )
        return flatten(row_levels), flatten(col_levels)

    return run


@lru_cache(maxsize=None)
def jit_forest(k: int):
    """Cached jitted forest builder — ONE dispatch per retained height."""
    from celestia_app_tpu.trace.device_ledger import track
    from celestia_app_tpu.trace.journal import note_jit_build

    note_jit_build("forest")
    return track(jax.jit(forest_fn(k)), "forest", k=k)


@lru_cache(maxsize=None)
def jit_forest_sharded(k: int, mesh, axis: str):
    """Forest builder whose OUTPUT layout is the serve plane's committed
    row-wise shard partition (parallel/mesh.row_sharding).

    The flat (N, 90) forests are padded to a shard multiple inside the
    program and land already partitioned via committed `out_shardings`
    — the resident forest is laid out exactly once, at admission, and
    the gather program's matching `in_shardings`
    (parallel/mesh.sharded_gather_fn) means it is never resharded
    between retention and gather: the SNIPPETS pjit contract, applied
    to the read side the way parallel/sharded_eds.py applies it to the
    write side.
    """
    from celestia_app_tpu.parallel.mesh import padded_rows, row_sharding
    from celestia_app_tpu.trace.journal import note_jit_build

    shards = mesh.shape[axis]
    base = forest_fn(k)
    n = 2 * k
    rows = n * (2 * n - 1)  # sum of n*w over widths n, n/2, ..., 1
    pad = padded_rows(rows, shards) - rows

    def run(eds: jnp.ndarray):
        row_flat, col_flat = base(eds)
        if pad:
            row_flat = jnp.pad(row_flat, ((0, pad), (0, 0)))
            col_flat = jnp.pad(col_flat, ((0, pad), (0, 0)))
        return row_flat, col_flat

    out_sh = row_sharding(mesh, axis)
    note_jit_build("forest_sharded")
    from celestia_app_tpu.trace.device_ledger import track

    return track(
        jax.jit(run, out_shardings=(out_sh, out_sh)),
        "forest_sharded", k=k, mode="sharded", shards=shards,
    )

"""Additive-FFT RS encode on the MXU: grouped butterflies as batched bit-matmuls.

Lowers gf/fft.py's LCH butterfly encode (the algorithm behind the
reference's rsmt2d.NewLeoRSCodec — pkg/appconsts/global_consts.go:92) to
TPU-shaped linear algebra.  A single stage's butterflies are too skinny for
the MXU (2-symbol blocks), so stages are fused in groups of g = log2(128/m)
(g=4 for GF(2^8), g=3 for GF(2^16)): the group's composed operator is
block-diagonal with one (2^g x 2^g) GF block per surrounding index, which
bit-expands to a (128, 128) 0/1 matrix — exactly one MXU tile — applied as
ONE batched int8 matmul over all blocks and share bytes.

Op count vs the dense generator path (kernels/rs.py): the dense encode is
(k*m)^2 MACs per symbol-column; the grouped FFT does 2*ceil(log2 k / g)
batched 128-wide contractions — at k=512/GF(2^16) that is 6 groups * 128
vs 8192 contraction depth, ~10x fewer MACs at identical MXU tiling.

Identity contract: the output equals the dense generator encode bit for bit
(same linear map, faster factorization — pinned by tests/test_fft.py), so
golden vectors, repair, and DAH roots are unchanged regardless of which
path extends a square.
"""

from __future__ import annotations

from functools import lru_cache

import jax.numpy as jnp
import numpy as np
from jax import lax

from celestia_app_tpu.gf.fft import encode_params, stage_twiddles
from celestia_app_tpu.gf.rs import codec_for_width

_DOT_DTYPE = jnp.int8


def _group_matrices(
    field, basis: tuple[int, ...], r: int, j0: int, j1: int, shift: int,
    inverse: bool,
) -> np.ndarray:
    """(hi, mid, mid) GF matrices composing butterfly stages [j0, j1).

    mid = 2^(j1-j0) symbols; hi = 2^(r-j1) surrounding blocks (the stage
    twiddles depend only on index bits >= j0 outside the group's low bits,
    so one matrix per hi-block serves every low index).  Rows track the
    butterflies: a[u] ^= w*a[v] is M[u,:] ^= w*M[v,:].
    """
    mid = 1 << (j1 - j0)
    hi = 1 << (r - j1)
    M = np.tile(np.eye(mid, dtype=np.uint32), (hi, 1, 1))
    stages = range(j0, j1) if inverse else range(j1 - 1, j0 - 1, -1)
    for j in stages:
        tw = stage_twiddles(field, basis, r, j, shift)
        d = 1 << (j - j0)
        for h in range(hi):
            for tm in range(mid >> (j - j0 + 1)):
                t = (h << (j1 - j - 1)) | tm
                w = int(tw[t])
                base = tm << (j - j0 + 1)
                u = slice(base, base + d)
                v = slice(base + d, base + 2 * d)
                if inverse:
                    M[h, v] ^= M[h, u]
                    if w:
                        M[h, u] ^= field.mul(w, M[h, v]).astype(np.uint32)
                else:
                    if w:
                        M[h, u] ^= field.mul(w, M[h, v]).astype(np.uint32)
                    M[h, v] ^= M[h, u]
    return M


@lru_cache(maxsize=None)
def encode_groups(k: int, construction: str) -> tuple:
    """The encode program for square size k: a tuple of
    (j0, j1, M_bits (hi, B, B) np.uint8) applied in order — the IFFT over
    the data coset followed by the FFT over the parity coset."""
    codec = codec_for_width(k, construction)
    field, basis, data_shift, parity_shift = encode_params(codec)
    r = max(k.bit_length() - 1, 0)
    if r == 0:
        return ()
    g = max(1, (128 // field.m).bit_length() - 1)  # 4 for m=8, 3 for m=16
    out = []
    # IFFT: stages ascend; group [j0, j1) applied low-to-high.
    bounds = list(range(0, r, g)) + [r]
    for j0, j1 in zip(bounds[:-1], bounds[1:]):
        M = _group_matrices(field, basis, r, j0, j1, data_shift, inverse=True)
        out.append((j0, j1, _expand_blocks(field, M)))
    # FFT: stages descend; group [j0, j1) applied high-to-low.
    for j0, j1 in reversed(list(zip(bounds[:-1], bounds[1:]))):
        M = _group_matrices(field, basis, r, j0, j1, parity_shift, inverse=False)
        out.append((j0, j1, _expand_blocks(field, M)))
    return tuple(out)


def _expand_blocks(field, M: np.ndarray) -> np.ndarray:
    """Bit-expand (hi, mid, mid) GF blocks -> (hi, mid*m, mid*m) uint8."""
    return np.stack([field.expand_bit_matrix(M[h]) for h in range(M.shape[0])])


def _apply_groups(
    bits: jnp.ndarray, groups: tuple, m: int, md: bool | None = None
) -> jnp.ndarray:
    """Run the encode program on bit planes.

    bits: (k, m, cols) int8 in {0,1} — symbol-major bit layout (bit b of
    symbol i at [i, b, :]).  Returns the transformed (k, m, cols).

    Two lowerings, byte-identical ($CELESTIA_RS_FFT_MD selects when `md`
    is None; callers may force one):
      * default — explicit transpose to (hi, B, lo*cols) then a batched
        2D matmul per group;
      * md — one dot_general contracting over BOTH the mid and bit axes
        in their natural positions, no explicit bit-plane transposes:
        the suspected cost of the measured TPU FFT slowdown (0.359 s vs
        0.255 s dense at k=512) is exactly those relayouts, so this
        variant hands the layout problem to XLA.  On CPU at k=512 it
        beats dense 2.3x (60.4 s vs 138.1 s steady, 2026-07-31) — the
        auto policy in kernels/rs.py rides that; on TPU it is still
        unmeasured and stays an autotune candidate.
    """
    import os

    if md is None:
        md = os.environ.get("CELESTIA_RS_FFT_MD") == "1"
    k = bits.shape[0]
    cols = bits.shape[2]
    for j0, j1, M in groups:
        mid = 1 << (j1 - j0)
        lo = 1 << j0
        hi = k // (mid * lo)
        x = bits.reshape(hi, mid, lo, m, cols)
        if md:
            # M5: (hi, mid, m, mid', m') against x dims (mid'=1, m'=3).
            M5 = jnp.asarray(M, dtype=_DOT_DTYPE).reshape(hi, mid, m, mid, m)
            acc = lax.dot_general(
                M5, x,
                (((3, 4), (1, 3)), ((0,), (0,))),
                preferred_element_type=jnp.int32,
            )  # (hi, mid, m, lo, cols)
            y = (acc & 1).astype(_DOT_DTYPE)
        else:
            B = mid * m
            x2 = x.transpose(0, 1, 3, 2, 4).reshape(hi, B, lo * cols)
            acc = lax.dot_general(
                jnp.asarray(M, dtype=_DOT_DTYPE), x2,
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.int32,
            )  # (hi, B, lo*cols)
            y = (acc & 1).astype(_DOT_DTYPE).reshape(hi, mid, m, lo, cols)
        bits = y.transpose(0, 1, 3, 2, 4).reshape(k, m, cols)
    return bits


def encode_axis_fft(
    data: jnp.ndarray,
    k: int,
    construction: str,
    contract_axis: int = 1,
    md: bool | None = None,
) -> jnp.ndarray:
    """FFT-encode over `contract_axis` of (A, B, S) uint8 byte shares.

    Same surface as kernels/rs.encode_axis with the generator implied:
    returns the k parity shares with the contracted axis replaced, other
    axes untouched.  Bit-identical to the dense generator path.
    """
    codec = codec_for_width(k, construction)
    m = codec.field.m
    bps = m // 8
    groups = encode_groups(k, construction)
    x = jnp.moveaxis(data, contract_axis, 0)  # (k, batch, S)
    n, batch, S = x.shape
    nsym = S // bps
    cols = batch * nsym
    planes = jnp.moveaxis(x.reshape(n, batch, nsym, bps), 3, 1)  # (n,bps,batch,nsym)
    planes = planes.reshape(n, bps, cols)
    if not groups:  # k == 1: parity equals data
        out = planes
    else:
        bits = (
            (planes[:, :, None, :] >> jnp.arange(8, dtype=jnp.uint8)[None, None, :, None])
            & 1
        ).astype(_DOT_DTYPE).reshape(n, m, cols)
        tbits = _apply_groups(bits, groups, m, md=md)
        pb = tbits.astype(jnp.uint32).reshape(n, bps, 8, cols)
        weights = (jnp.uint32(1) << jnp.arange(8, dtype=jnp.uint32))[None, None, :, None]
        out = (pb * weights).sum(axis=2).astype(jnp.uint8)  # (n, bps, cols)
    by = jnp.moveaxis(out.reshape(n, bps, batch, nsym), 1, 3)  # (n,batch,nsym,bps)
    return jnp.moveaxis(by.reshape(n, batch, S), 0, contract_axis)


def col_block_encode_fn(k: int, construction: str, md: bool | None = None):
    """The panel-blocked staging of the column-phase butterflies
    (kernels/panel.py's FFT leg): f(top_cols (k, c, S)) -> (k, c, S).

    The butterfly network contracts over the ROW axis, so it cannot be
    XOR-split across row panels the way the dense generator can — but
    every COLUMN's butterfly chain is independent (columns are pure batch
    in _apply_groups), so blocking the batch axis runs the identical
    stage program on c columns at a time.  That bounds the 8x bit-plane
    inflation (and the int32 dot accumulator) to one block instead of
    the whole 2k-column top half: at k=2048 the full column phase would
    stage ~34 GB of int32 accumulator; a 128-column block stages ~1 GB.
    Bytes are identical to the unblocked call sliced at the same columns
    — no butterfly, twiddle, or packing step changes.
    """

    def run(top_cols: jnp.ndarray) -> jnp.ndarray:
        return encode_axis_fft(top_cols, k, construction, contract_axis=0,
                               md=md)

    return run

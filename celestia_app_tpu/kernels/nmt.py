"""Batched NMT construction on device.

Builds all 4k row/column trees of an extended data square in lock-step: one
fused level-by-level reduction where each level is a single batched SHA-256
call plus `where`-lane namespace bookkeeping (SURVEY hard part 3).  Digest
semantics match nmt/hasher.py (pinned against reference
test/util/malicious/hasher.go:186-310):

    leaf:  ns || ns || sha256(0x00 || ns || data)
    node:  min || max || sha256(0x01 || left || right)
    ignore-max rule: right.min == 0xFF^29  =>  parent.max = left.max

Namespace assignment by quadrant (reference pkg/wrapper/nmt_wrapper.go:93-114)
is done by the caller (da/), which passes the per-leaf namespace array.

Trees are power-of-two sized (2k leaves), so every level halves exactly and
the loop unrolls at trace time (log2(2k) <= 10 levels).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from celestia_app_tpu.constants import NAMESPACE_SIZE, PARITY_NAMESPACE_BYTES
from celestia_app_tpu.kernels.sha256 import sha256

_MAX_NS = np.frombuffer(PARITY_NAMESPACE_BYTES, dtype=np.uint8)


def leaf_digests(ns: jnp.ndarray, data: jnp.ndarray):
    """Hash T x L leaves.

    ns: (T, L, 29) uint8, data: (T, L, D) uint8 (the raw shares).
    Returns (mins, maxs, hashes): (T, L, 29), (T, L, 29), (T, L, 32).

    $CELESTIA_SHA_FUSED=on routes full-share leaves through the fused
    Pallas kernel (message construction + padding in VMEM,
    kernels/sha256.sha256_leaves_pallas) — identical digests either way.
    """
    from celestia_app_tpu.kernels.sha256 import (
        _use_pallas_fused_leaves,
        sha256_leaves_pallas,
    )

    from celestia_app_tpu.constants import SHARE_SIZE

    t, l, d = data.shape
    if d == SHARE_SIZE and _use_pallas_fused_leaves(t * l):
        hashes = sha256_leaves_pallas(
            ns.reshape(t * l, NAMESPACE_SIZE), data.reshape(t * l, d)
        ).reshape(t, l, 32)
        return ns, ns, hashes
    prefix = jnp.zeros((t * l, 1), dtype=jnp.uint8)
    msgs = jnp.concatenate(
        [prefix, ns.reshape(t * l, NAMESPACE_SIZE), data.reshape(t * l, d)], axis=1
    )
    hashes = sha256(msgs).reshape(t, l, 32)
    return ns, ns, hashes


def reduce_level(mins, maxs, hashes):
    """One tree level: (T, L, .) -> (T, L/2, .) for all trees at once."""
    t, l, _ = hashes.shape
    lm, ln, lh = mins[:, 0::2], maxs[:, 0::2], hashes[:, 0::2]
    rm, rn, rh = mins[:, 1::2], maxs[:, 1::2], hashes[:, 1::2]
    left = jnp.concatenate([lm, ln, lh], axis=2)  # (T, L/2, 90)
    right = jnp.concatenate([rm, rn, rh], axis=2)
    prefix = jnp.ones((t * (l // 2), 1), dtype=jnp.uint8)
    msgs = jnp.concatenate(
        [prefix, left.reshape(-1, 90), right.reshape(-1, 90)], axis=1
    )
    ph = sha256(msgs).reshape(t, l // 2, 32)
    right_is_parity = jnp.all(rm == jnp.asarray(_MAX_NS), axis=2, keepdims=True)
    pmax = jnp.where(right_is_parity, ln, rn)
    return lm, pmax, ph


def reduce_to_width(mins, maxs, hashes, width: int = 1):
    """Reduce T trees' digest levels (T, L, .) down to (T, width, .).

    L and width must be powers of two with width <= L.  width > 1 yields
    the subtree nodes at that level — the multi-chip row-tree path reduces
    each device's aligned column block to one node per row, all-gathers
    the 90-byte nodes, and finishes the top log2(n_devices) levels with a
    second call (parallel/sharded_eds.py), so only roots cross the
    interconnect, never shares.
    """
    while hashes.shape[1] > width:
        mins, maxs, hashes = reduce_level(mins, maxs, hashes)
    return mins, maxs, hashes


def tree_levels_from_digests(mins, maxs, hashes):
    """Reduce T trees level-by-level starting from precomputed leaf digests.

    Returns a list of (mins, maxs, hashes) tuples, leaf level first; the last
    entry has L=1 (the roots).  This is the device-side replacement for the
    reference's per-row subtree-root cache (pkg/inclusion/nmt_caching.go:80):
    commitments and proofs index into these arrays instead of locking a map.
    """
    levels = [(mins, maxs, hashes)]
    while levels[-1][2].shape[1] > 1:
        levels.append(reduce_level(*levels[-1]))
    return levels


def tree_levels(ns: jnp.ndarray, data: jnp.ndarray):
    """All digest levels for T trees of L leaves (L a power of two)."""
    return tree_levels_from_digests(*leaf_digests(ns, data))


def roots_from_levels(levels) -> jnp.ndarray:
    """Last level (L=1) -> (T, 90) namespaced roots."""
    mins, maxs, hashes = levels[-1]
    return jnp.concatenate([mins[:, 0], maxs[:, 0], hashes[:, 0]], axis=1)


def tree_roots_from_digests(mins, maxs, hashes) -> jnp.ndarray:
    """(T, L, 29)^2 x (T, L, 32) leaf digests -> (T, 90) namespaced roots."""
    return roots_from_levels(tree_levels_from_digests(mins, maxs, hashes))


def tree_roots(ns: jnp.ndarray, data: jnp.ndarray) -> jnp.ndarray:
    """(T, L, 29) x (T, L, D) -> (T, 90) namespaced roots."""
    return roots_from_levels(tree_levels(ns, data))

"""Fused Pallas lowering of the dense RS bit-matmul.

The XLA dense path (kernels/rs.py `_mod2_matmul_planes`) ran at ~9% of the
MXU's int8 peak in its round-3 chip measurement (0.255 s at k=512 against
a ~25 ms roofline): the matmul itself is MXU-shaped, but the byte->bit
unpack before it and the bit->byte pack after it are separate HBM-visible
passes over 8x-inflated bit planes — HBM traffic, not MACs, sets the rate.

This kernel fuses the whole contraction into one Pallas program so the bit
planes NEVER exist in HBM:

    grid (col_tiles, row_tiles), row fastest;
    per col tile, on the first row step, the byte planes (n, bps, TC) are
    unpacked once into a VMEM scratch of {0,1} int8 (n*m, TC);
    every row step then runs one (128, n*m) @ (n*m, TC) int8 MXU matmul
    from scratch and packs its 128 output bit-rows back to bytes in-regs
    before the (16, TC) uint8 tile leaves for HBM.

HBM traffic: bytes in + bytes out + G once per col tile — the 8x bit
inflation stays on-chip. Bit order matches gf/field.expand_bit_matrix
(symbol-major, byte-then-bit within a symbol), so the kernel is
bit-identical to `encode_axis` (pinned by tests/test_rs_pallas.py).

Reference seam: rsmt2d.ComputeExtendedDataSquare's codec.Encode
(/root/reference/pkg/da/data_availability_header.go:74) — this is the
same linear map as kernels/rs.py, only the schedule differs.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_OT = 128  # output bit-rows per grid step: one MXU row tile
_TC = 256  # symbol-columns per grid step (lane axis)

try:  # pallas imports fail on backends without Mosaic; callers gate on TPU
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover — chaos-ok: jax always ships pallas today
    pl = None
    pltpu = None


def _kernel(n: int, m: int, bps: int, tc: int):
    def kernel(x_ref, g_ref, out_ref, bits_ref):
        # Unpack the col tile's byte planes once per col tile (row step 0):
        # (n, bps, TC) uint8 -> {0,1} int8 (n*m, TC), symbol-major rows.
        @jax.named_scope("unpack")
        def unpack():
            x = x_ref[...].astype(jnp.int32)  # (n, bps, TC)
            shifts = jnp.arange(8, dtype=jnp.int32)[None, None, :, None]
            bits = (x[:, :, None, :] >> shifts) & 1  # (n, bps, 8, TC)
            bits_ref[...] = bits.astype(jnp.int8).reshape(n * m, tc)

        @pl.when(pl.program_id(1) == 0)
        def _():
            unpack()

        acc = lax.dot_general(
            g_ref[...],
            bits_ref[...],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # (OT, TC)
        nsym_t = _OT // m
        pb = (acc & 1).reshape(nsym_t, bps, 8, tc)
        weights = (1 << jnp.arange(8, dtype=jnp.int32))[None, None, :, None]
        out_ref[...] = (pb * weights).sum(axis=2).astype(jnp.uint8).reshape(
            _OT // 8, tc
        )

    return kernel


def mod2_matmul_planes_pallas(
    G_bits: jnp.ndarray, x: jnp.ndarray, m: int, interpret: bool = False
) -> jnp.ndarray:
    """Drop-in for kernels/rs._mod2_matmul_planes on the fused kernel.

    G_bits: (P*m, n*m) 0/1; x: (n, bps, cols) uint8 byte planes.
    Returns (P, bps, cols) uint8 parity planes. Requires P*m and n*m to be
    multiples of 128 (MXU tiling) — callers fall back below that.
    """
    n, bps, cols = x.shape
    Pm, nm = G_bits.shape
    assert nm == n * m and Pm % _OT == 0, (G_bits.shape, x.shape, m)
    pad = (-cols) % _TC
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad)))
    total = cols + pad
    out = pl.pallas_call(
        _kernel(n, m, bps, _TC),
        grid=(total // _TC, Pm // _OT),
        in_specs=[
            pl.BlockSpec((n, bps, _TC), lambda c, r: (0, 0, c)),
            pl.BlockSpec((_OT, nm), lambda c, r: (r, 0)),
        ],
        out_specs=pl.BlockSpec((_OT // 8, _TC), lambda c, r: (r, c)),
        out_shape=jax.ShapeDtypeStruct((Pm // 8, total), jnp.uint8),
        scratch_shapes=[pltpu.VMEM((nm, _TC), jnp.int8)],
        interpret=interpret,
    )(x, G_bits.astype(jnp.int8))
    P = Pm // m
    return out[:, :cols].reshape(P, bps, cols)


def encode_axis_pallas(
    data: jnp.ndarray,
    G_bits: jnp.ndarray,
    m: int,
    contract_axis: int = 1,
    interpret: bool = False,
) -> jnp.ndarray:
    """kernels/rs.encode_axis with the fused Pallas core (same byte moves)."""
    bps = m // 8
    x = jnp.moveaxis(data, contract_axis, 0)
    n, batch, S = x.shape
    nsym = S // bps
    cols = batch * nsym
    planes = jnp.moveaxis(x.reshape(n, batch, nsym, bps), 3, 1)
    out = mod2_matmul_planes_pallas(
        G_bits, planes.reshape(n, bps, cols), m, interpret=interpret
    )
    P = out.shape[0]
    by = jnp.moveaxis(out.reshape(P, bps, batch, nsym), 1, 3)
    return jnp.moveaxis(by.reshape(P, batch, S), 0, contract_axis)


@lru_cache(maxsize=None)
def pallas_supported(k: int, m: int) -> bool:
    """MXU tiling wants both matmul dims in 128-multiples."""
    return pl is not None and (k * m) % 128 == 0

"""Device (JAX/XLA/Pallas) kernels: the TPU compute path of the framework.

  rs.py      - Reed-Solomon extension as binary bit-matmuls on the MXU
  sha256.py  - batched fixed-shape SHA-256 over uint32 lanes
  nmt.py     - batched Namespaced-Merkle-Tree level reduction
"""

"""Batched DAS proof verification on device — the verify twin of the
batched sampler.

The serve plane answers a micro-batch of samples in one gather
(serve/sampler); this module closes the read side's last host loop by
re-deciding a whole queue of `(coordinate, share, proof)` samples in one
jitted program:

    one leaf-hash dispatch            (B, 542) -> (B, 32)
    one gathered path-fold per level  (B, 181) -> (B, 32)  NMT levels
    one row-root fold per level       (B,  91) / (B, 65)   data-root path

with the namespace min/max bookkeeping folded in as `where` lanes —
exactly the kernels/nmt.py idiom, reusing the same batched SHA-256
(kernels/sha256.py), so the accept/reject semantics are the host
verifier's (nmt/proof._verify_digests + merkle.compute_root_from_path)
bit for bit:

    * sibling namespaces out of order (left.max > right.min at ANY
      level) rejects — the device accumulates a violation mask instead
      of raising, same final verdict;
    * the ignore-max rule (right.min == 0xFF^29 => parent.max =
      left.max) propagates identically;
    * the computed 90-byte NMT root must equal the proof's claimed row
      root AND that row root's audit path must land on the data root.

Index plans (which proof node sits at which level, which side the
running digest folds from) are host ints prepared by serve/verify.py
from the SAME `range_proof_node_coords` plan the sampler serves proofs
with — shared plan in, shared plan out, which is what makes batched and
host verdicts identical by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from celestia_app_tpu.constants import PARITY_NAMESPACE_BYTES
from celestia_app_tpu.kernels.sha256 import sha256

_MAX_NS = np.frombuffer(PARITY_NAMESPACE_BYTES, dtype=np.uint8)


def _lex_gt(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Bytewise-lexicographic a > b over (B, W) uint8 rows -> (B,) bool.

    The verdict hangs on the first differing byte; argmax over the
    inequality mask finds it without a scan (all-equal rows gate on
    any_neq, so their arbitrary argmax never escapes).
    """
    neq = a != b
    any_neq = jnp.any(neq, axis=1)
    first = jnp.argmax(neq, axis=1)
    av = jnp.take_along_axis(a, first[:, None], axis=1)[:, 0]
    bv = jnp.take_along_axis(b, first[:, None], axis=1)[:, 0]
    return any_neq & (av > bv)


@jax.jit
def nmt_leaf_digests(ns: jnp.ndarray, data: jnp.ndarray) -> jnp.ndarray:
    """(N, 29) namespaces x (N, D) raw leaves -> (N, 90) NMT leaf digests
    (ns || ns || sha256(0x00 || ns || data)) in one batched dispatch —
    the heal engine's survivor check hashes every gathered coordinate
    through this instead of a host loop."""
    prefix = jnp.zeros((ns.shape[0], 1), dtype=jnp.uint8)
    h = sha256(jnp.concatenate([prefix, ns, data], axis=1))
    return jnp.concatenate([ns, ns, h], axis=1)


@jax.jit
def verify_nmt_samples(
    ns: jnp.ndarray,           # (B, 29)  leaf namespaces
    shares: jnp.ndarray,       # (B, D)   raw shares
    sibs: jnp.ndarray,         # (B, Ln, 90) NMT siblings, leaf-to-root
    sib_is_left: jnp.ndarray,  # (B, Ln)  sibling folds from the left
    row_roots: jnp.ndarray,    # (B, 90)  claimed row/col roots
) -> jnp.ndarray:
    """(B,) bool: each sample's NMT fold lands on its claimed row root
    with no namespace-order violation at any level.

    Ln is static per compiled program (one specialization per tree
    shape; serve/verify.py buckets the queue and pads B to a power of
    two so recompilation is bounded)."""
    b = ns.shape[0]
    zeros = jnp.zeros((b, 1), dtype=jnp.uint8)
    ones = jnp.ones((b, 1), dtype=jnp.uint8)
    max_ns = jnp.asarray(_MAX_NS)

    h = sha256(jnp.concatenate([zeros, ns, shares], axis=1))
    mins, maxs = ns, ns
    violated = jnp.zeros((b,), dtype=bool)
    for lvl in range(sibs.shape[1]):
        cur = jnp.concatenate([mins, maxs, h], axis=1)
        sib = sibs[:, lvl]
        isl = sib_is_left[:, lvl][:, None]
        left = jnp.where(isl, sib, cur)
        right = jnp.where(isl, cur, sib)
        l_min, l_max = left[:, :29], left[:, 29:58]
        r_min, r_max = right[:, :29], right[:, 29:58]
        violated |= _lex_gt(l_max, r_min)
        h = sha256(jnp.concatenate([ones, left, right], axis=1))
        right_is_parity = jnp.all(r_min == max_ns, axis=1, keepdims=True)
        mins = l_min
        maxs = jnp.where(right_is_parity, l_max, r_max)
    computed = jnp.concatenate([mins, maxs, h], axis=1)
    return jnp.all(computed == row_roots, axis=1) & ~violated


@jax.jit
def fold_row_roots(
    row_roots: jnp.ndarray,    # (U, 90)  row/col roots, deduped
    row_paths: jnp.ndarray,    # (U, Lr, 32) data-root audit paths
    path_is_left: jnp.ndarray,  # (U, Lr)
    data_roots: jnp.ndarray,   # (U, 32)
) -> jnp.ndarray:
    """(U,) bool: each row root's audit path lands on its data root
    (RFC-6962 fold by index bits).  Runs over the queue's UNIQUE
    (row root, path) pairs — s samples of one height share a handful of
    row roots, so this leg's cost is ~n, not ~s."""
    u = row_roots.shape[0]
    zeros = jnp.zeros((u, 1), dtype=jnp.uint8)
    ones = jnp.ones((u, 1), dtype=jnp.uint8)
    rh = sha256(jnp.concatenate([zeros, row_roots], axis=1))
    for lvl in range(row_paths.shape[1]):
        p = row_paths[:, lvl]
        isl = path_is_left[:, lvl][:, None]
        left = jnp.where(isl, p, rh)
        right = jnp.where(isl, rh, p)
        rh = sha256(jnp.concatenate([ones, left, right], axis=1))
    return jnp.all(rh == data_roots, axis=1)


# Register the batched-verify programs with the device ledger
# (trace/device_ledger.py).  These are module-level jits specializing per
# input shape, so one ledger row covers every shape of a program: the
# first dispatch bills compile_s, later shapes' recompiles accumulate
# into dispatch_s — the family-level view /device needs, not a per-shape
# census (serve/verify.py buckets shapes to keep that census bounded).
from celestia_app_tpu.trace.device_ledger import track as _track_program  # noqa: E402

nmt_leaf_digests = _track_program(
    nmt_leaf_digests, "verify", mode="leaf_digests"
)
verify_nmt_samples = _track_program(
    verify_nmt_samples, "verify", mode="nmt_samples"
)
fold_row_roots = _track_program(
    fold_row_roots, "verify", mode="fold_row_roots"
)

"""Panel-streamed extend+DAH: giant squares without materializing the EDS.

The fused one-dispatch pipeline (kernels/fused.py) holds the whole
(2k, 2k, SHARE_SIZE) extended square — plus XLA's concatenate copies —
live inside a single program.  At k=512 that is ~537 MB of shares; at
k=2048 it is 8.6 GB before a single leaf digest, which is why square
sizes past 512 were memory-blocked, not compute-blocked.  This module is
the same layout-and-scheduling discipline that made the bitsliced XOR
encode fast (arXiv 2108.02692): restructure the SCHEDULE, keep the math
bit-for-bit identical.

The lowering keeps the materializing pipeline's exact two-phase order
(row extend, then one column contraction over all 2k top columns), but
blocks it into host-driven panels of small jitted programs:

  * ROW PHASE — each panel of `rows` ODS rows is row-extended and
    leaf-hashed independently (`_jit_row_panel`: encode(panel, axis=1),
    the per-leaf namespace rule, one batched SHA call — the
    extend_leaf_digests epilogue shape).  Only the (p, 2k, 29) namespace
    and (p, 2k, 32) hash slabs accumulate; roots_only callers drop the
    share panel the moment it is hashed into the column accumulator.
  * COLUMN PHASE — the contraction over the row axis streams as
    XOR-accumulated partial products: mod-2 of a sum is the XOR of the
    per-panel mod-2 partial sums, so `G_bits[:, panel] @ panel` is
    scatter-added (bitwise XOR, accumulator donated) into the parity-row
    accumulator as each top panel completes (`_jit_col_partial`).  On
    platforms where the encode seam selects the additive FFT
    (kernels/rs._fft_choice — CPU at k >= 512), the butterflies contract
    over the row axis and cannot XOR-split, so the column phase is
    staged panel-blocked over the BATCH (column) axis instead
    (kernels/fft.col_block_encode_fn): every column's butterfly chain is
    independent, so blocking the columns bounds the 8x bit-plane
    inflation to one block without touching a single butterfly.
  * ROOTS — row and column trees reduce from the accumulated digest
    grids in one final program (`_jit_panel_roots`), identical to
    da/eds.roots_fn's reduction over the same digests.

Memory model (the honest one): peak device share residency is the
parity-row accumulator (k, 2k, S) — half the EDS — plus ONE extended row
panel, instead of the full square plus the fused program's intermediate
copies; the digest grids accumulate at 61 B/leaf (ns + hash; min == max
for every leaf).  The FFT leg holds the top half instead of the parity
accumulator (the butterflies need whole columns) — the same half-square
bound from the other side.  `roots_only=True` is the shape the
proposer's DAH actually needs; full-EDS callers (ForestCache retention,
repair's re-extend) get the EDS concatenated from panels at the very
end, or simply stay on the materializing path.

Selection seam: $CELESTIA_PIPE_PANEL = "<rows>" | "auto" (default off).
An integer streams EVERY square in panels of that many ODS rows; "auto"
engages only at k >= 512 with 64-row panels.  The mode rides the normal
pipeline routing (da/eds.jit_pipeline / compute / warmup via
kernels/fused.pipeline_mode_for_k) and sits ABOVE the fused rungs on the
degradation ladder (chaos/degrade.LADDER): a faulting panel dispatch
steps the process down to the materializing lowerings, which remain
bit-identical — pinned by tests/test_panel_pipeline.py against the dense
full-square goldens for both RS constructions and uneven panel sizes.
"""

from __future__ import annotations

import os
from functools import lru_cache

import jax
import jax.numpy as jnp

from celestia_app_tpu.constants import (
    NAMESPACE_SIZE,
    PARITY_NAMESPACE_BYTES,
    SHARE_SIZE,
)
from celestia_app_tpu.gf.rs import active_construction, codec_for_width
from celestia_app_tpu.kernels.merkle import merkle_root_pow2
from celestia_app_tpu.kernels.nmt import leaf_digests, tree_roots_from_digests
from celestia_app_tpu.kernels.rs import _fft_choice, encode_axis, encode_fn

#: "auto" panel height (ODS rows per panel) and the square size at which
#: auto engages — below it the whole square is one panel anyway and the
#: fused single-dispatch program wins on dispatch count.
_AUTO_PANEL_ROWS = 64
_AUTO_PANEL_K = 512


def env_panel() -> str:
    return os.environ.get("CELESTIA_PIPE_PANEL", "")


def panel_rows(k: int) -> int:
    """ODS rows per panel for square size k; 0 = panel mode off.

    $CELESTIA_PIPE_PANEL: ""/unset/"off"/"0" disables; "auto" engages
    64-row panels at k >= 512 (the sizes where the materializing
    pipeline's share residency starts to dominate HBM); an integer N
    streams every square in N-row panels (clamped to k — a single-panel
    run degenerates to the materializing schedule through the panel
    code, which the small-k identity tests lean on).
    """
    val = env_panel().strip().lower()
    if val in ("", "0", "off"):
        return 0
    if val == "auto":
        return _AUTO_PANEL_ROWS if k >= _AUTO_PANEL_K else 0
    try:
        rows = int(val)
    except ValueError:
        _warn_malformed(val)
        return 0
    if rows <= 0:
        return 0
    return min(rows, k)


_WARNED_MALFORMED: set[str] = set()


def _warn_malformed(val: str) -> None:
    """A typo'd $CELESTIA_PIPE_PANEL silently falling back to the
    materializing pipeline is exactly the OOM the knob exists to prevent
    — say so, loudly, once per distinct value (the extra_warmup_sizes
    convention)."""
    if val in _WARNED_MALFORMED:
        return
    _WARNED_MALFORMED.add(val)
    import sys

    print(f"ignoring malformed CELESTIA_PIPE_PANEL value {val!r} "
          "(want an integer row count or 'auto'); panel streaming is OFF",
          file=sys.stderr)


def panel_bounds(k: int, rows: int) -> tuple[tuple[int, int], ...]:
    """The row-panel partition [(r0, r1), ...] covering [0, k); the last
    panel is short when `rows` does not divide k."""
    return tuple(
        (r0, min(r0 + rows, k)) for r0 in range(0, k, max(1, rows))
    )


# Fully-resolved configurations (k, construction, rows, use_fft, md)
# whose panel programs have completed one full run this process — the
# journal's compile hit/miss signal for panel mode
# (da/eds.pipeline_cache_state).  The key matches _panel_runner's cache
# key exactly: a panel-height or encode-seam flip mid-process means the
# NEW configuration's per-panel jits are cold, and the compile column
# must say so.
_PANEL_WARM: set[tuple] = set()


def _resolved_config(k: int, construction: str) -> tuple:
    """(rows, use_fft, md) the seam resolves to for k right now — the
    part of _panel_runner's cache key beyond (k, construction)."""
    rows = panel_rows(k) or k
    use_fft, force_md = _fft_choice(k)
    md = (os.environ.get("CELESTIA_RS_FFT_MD") == "1"
          if force_md is None else bool(force_md))
    return rows, use_fft, md


def is_warm(k: int, construction: str | None = None) -> bool:
    construction = construction or active_construction()
    return (k, construction, *_resolved_config(k, construction)) \
        in _PANEL_WARM


def _parity_ns(shape) -> jnp.ndarray:
    parity = jnp.frombuffer(PARITY_NAMESPACE_BYTES, dtype=jnp.uint8)
    return jnp.broadcast_to(parity, (*shape, NAMESPACE_SIZE))


def _note_build() -> None:
    from celestia_app_tpu.trace.journal import note_jit_build

    note_jit_build("panel_pipeline")


def _track(fn, k: int, construction: str | None = None, p: int | None = None):
    """Register one panel sub-program with the device ledger (family
    panel_pipeline; `p` — the panel height — rides the batch column)."""
    from celestia_app_tpu.trace.device_ledger import track

    return track(
        fn, "panel_pipeline",
        k=k, construction=construction, mode="panel", batch=p,
    )


@lru_cache(maxsize=None)
def _jit_row_panel(k: int, p: int, construction: str):
    """f(panel (p, k, S)) -> (ext (p, 2k, S), ns (p, 2k, 29),
    hashes (p, 2k, 32)): row-extend one panel of ODS rows and hash its
    leaves.  The encode rides encode_fn — the same dense/FFT/Pallas/XOR
    selection every other lowering uses, bit-identical per row because
    both phases batch independently over rows."""
    _note_build()
    encode = encode_fn(k, construction)

    def run(panel: jnp.ndarray):
        parity = jnp.frombuffer(PARITY_NAMESPACE_BYTES, dtype=jnp.uint8)
        q1 = encode(panel, 1)  # (p, k, S)
        ext = jnp.concatenate([panel, q1], axis=1)  # (p, 2k, S)
        col = jnp.arange(2 * k)
        ns = jnp.where(
            (col < k)[None, :, None], ext[..., :NAMESPACE_SIZE], parity
        )
        _, _, hashes = leaf_digests(ns, ext)
        return ext, ns, hashes

    return _track(jax.jit(run), k, construction, p)


@lru_cache(maxsize=None)
def _jit_col_partial(k: int, p: int, construction: str):
    """f(acc (k, 2k, S), panel (p, 2k, S), g_slice (k*m, p*m)) -> acc':
    one panel's partial product of the column contraction, XOR-added into
    the donated parity-row accumulator.  Exact: mod-2 of the full
    contraction equals the XOR of per-panel mod-2 partial contractions,
    and byte packing is per-bit, so accumulating packed bytes is
    accumulating bits."""
    _note_build()
    from celestia_app_tpu.kernels.fused import (
        _silence_unusable_donation_warning,
    )

    _silence_unusable_donation_warning()  # CPU ignores donation; expected
    m = codec_for_width(k, construction).field.m

    def step(acc, panel, g_slice):
        part = encode_axis(panel, g_slice, m, contract_axis=0)  # (k, 2k, S)
        return jnp.bitwise_xor(acc, part)

    return _track(jax.jit(step, donate_argnums=(0,)), k, construction, p)


@lru_cache(maxsize=None)
def _col_generator_slices(k: int, construction: str,
                          bounds: tuple) -> tuple:
    """Per-panel block-columns of the bit-expanded generator: the column
    contraction's partial product for panel rows [r0, r1) reads exactly
    G_bits[:, r0*m : r1*m].  Cached as device arrays — together they are
    the same bytes the materializing dense path bakes into its program."""
    codec = codec_for_width(k, construction)
    g_bits = codec.generator_bits()
    m = codec.field.m
    slices = tuple(
        jnp.asarray(g_bits[:, r0 * m: r1 * m]) for r0, r1 in bounds
    )
    from celestia_app_tpu.trace.device_ledger import note_owned_bytes

    note_owned_bytes(
        "panel_generator_slices", (k, construction, bounds),
        sum(int(s.nbytes) for s in slices),
    )
    return slices


@lru_cache(maxsize=None)
def _jit_fft_col_block(k: int, c: int, construction: str, md: bool):
    """f(top_cols (k, c, S)) -> (k, c, S): the column-phase additive-FFT
    encode over one block of columns (kernels/fft.col_block_encode_fn) —
    the panel-blocked butterfly staging."""
    _note_build()
    from celestia_app_tpu.kernels.fft import col_block_encode_fn

    return _track(jax.jit(col_block_encode_fn(k, construction, md=md)),
                  k, construction, c)


@lru_cache(maxsize=None)
def _jit_parity_leaves(rows: int, cols: int):
    """f(block (rows, cols, S)) -> hashes (rows, cols, 32): leaf digests
    for an all-parity-namespace block (every bottom-half leaf)."""
    _note_build()

    def run(block: jnp.ndarray):
        ns = _parity_ns((rows, cols))
        _, _, hashes = leaf_digests(ns, block)
        return hashes

    return _track(jax.jit(run), cols, None, rows)


@lru_cache(maxsize=None)
def _jit_panel_roots(k: int):
    """f(top_ns (k, 2k, 29), hashes (2k, 2k, 32)) -> (row_roots,
    col_roots, droot): the tree reductions over the accumulated digest
    grids — the same tree_roots_from_digests/merkle_root_pow2 composition
    as da/eds.roots_fn, fed precomputed leaf digests (bottom namespaces
    are the parity constant and never shipped)."""
    _note_build()

    def run(top_ns: jnp.ndarray, hashes: jnp.ndarray):
        ns = jnp.concatenate([top_ns, _parity_ns((k, 2 * k))], axis=0)
        row_roots = tree_roots_from_digests(ns, ns, hashes)  # (2k, 90)
        nst = ns.transpose(1, 0, 2)
        col_roots = tree_roots_from_digests(
            nst, nst, hashes.transpose(1, 0, 2)
        )
        droot = merkle_root_pow2(
            jnp.concatenate([row_roots, col_roots], axis=0)
        )
        return row_roots, col_roots, droot

    return _track(jax.jit(run), k)


def _as_panels(x, k: int, bounds: tuple) -> list:
    """Split the input into per-panel arrays.  Accepts the full
    (k, k, S) ODS (host or device; sliced lazily so a host array uploads
    one panel at a time) or an already-split list of panels matching
    `bounds` (the BlockPipeline's panel-granular staging)."""
    if isinstance(x, (list, tuple)):
        if len(x) != len(bounds):
            raise ValueError(
                f"panel list length {len(x)} != plan {len(bounds)}"
            )
        for panel, (r0, r1) in zip(x, bounds):
            if panel.shape[0] != r1 - r0:
                raise ValueError(
                    f"panel rows {panel.shape[0]} != plan rows {r1 - r0}"
                )
        return list(x)
    if x.shape != (k, k, SHARE_SIZE):
        raise ValueError(f"bad ODS shape {x.shape} for k={k}")
    return [x[r0:r1] for r0, r1 in bounds]


def panel_pipeline(k: int, construction: str | None = None,
                   roots_only: bool = False):
    """The panel-streamed pipeline callable for square size k.

    Returns f(ods) -> (eds, row_roots, col_roots, droot), or the
    roots_only twin f(ods) -> (row_roots, col_roots, droot) that never
    assembles the square.  `ods` may be the (k, k, S) array (host numpy
    uploads panel-at-a-time) or a list of per-panel arrays matching
    panel_bounds(k, panel_rows(k)).

    Host-driven: each panel is its own small jitted dispatch, so peak
    device residency is bounded by the accumulator + one panel + the
    digest grids instead of whatever one giant program holds live.  Each
    per-panel dispatch passes the chaos device.dispatch seam under mode
    "panel", so an injected mid-panel fault surfaces to guarded_dispatch
    and walks the ladder down to the materializing lowerings.

    The runner is cached per resolved configuration (panel height and
    encode-leg selection included), so repeated resolution — warmup vs
    compute vs the block pipeline — hands back the same callable while
    the env is stable.
    """
    construction = construction or active_construction()
    rows, use_fft, md = _resolved_config(k, construction)
    return _panel_runner(k, construction, roots_only, rows, use_fft, md)


@lru_cache(maxsize=None)
def _panel_runner(k: int, construction: str, roots_only: bool, rows: int,
                  use_fft: bool, md: bool):
    bounds = panel_bounds(k, rows)

    def run(x):
        from celestia_app_tpu import chaos

        panels = _as_panels(x, k, bounds)
        ns_slabs: list = []
        top_hash_slabs: list = []
        top_panels: list = []
        acc = None
        g_slices = None
        if not use_fft:
            g_slices = _col_generator_slices(k, construction, bounds)
            acc = jnp.zeros((k, 2 * k, SHARE_SIZE), dtype=jnp.uint8)
        for i, (r0, r1) in enumerate(bounds):
            chaos.device_dispatch("panel")
            panel = jnp.asarray(panels[i], dtype=jnp.uint8)
            ext, ns, hashes = _jit_row_panel(k, r1 - r0, construction)(panel)
            ns_slabs.append(ns)
            top_hash_slabs.append(hashes)
            if use_fft:
                # The butterflies need whole columns: the top half stays
                # resident and the bottom streams out column-blocked.
                top_panels.append(ext)
            else:
                acc = _jit_col_partial(k, r1 - r0, construction)(
                    acc, ext, g_slices[i]
                )
                if not roots_only:
                    top_panels.append(ext)
        bot_hash_slabs: list = []
        if use_fft:
            top = (top_panels[0] if len(top_panels) == 1
                   else jnp.concatenate(top_panels, axis=0))
            blocks: list = []
            cwidth = min(2 * rows, 2 * k)
            for c0 in range(0, 2 * k, cwidth):
                c1 = min(c0 + cwidth, 2 * k)
                chaos.device_dispatch("panel")
                blk = _jit_fft_col_block(k, c1 - c0, construction, md)(
                    top[:, c0:c1]
                )
                bot_hash_slabs.append(_jit_parity_leaves(k, c1 - c0)(blk))
                if not roots_only:
                    blocks.append(blk)
            bottom = (None if roots_only else
                      (blocks[0] if len(blocks) == 1
                       else jnp.concatenate(blocks, axis=1)))
            bot_hashes = (bot_hash_slabs[0] if len(bot_hash_slabs) == 1
                          else jnp.concatenate(bot_hash_slabs, axis=1))
        else:
            bottom = acc
            for r0, r1 in bounds:
                chaos.device_dispatch("panel")
                bot_hash_slabs.append(
                    _jit_parity_leaves(r1 - r0, 2 * k)(bottom[r0:r1])
                )
            bot_hashes = (bot_hash_slabs[0] if len(bot_hash_slabs) == 1
                          else jnp.concatenate(bot_hash_slabs, axis=0))
        top_ns = (ns_slabs[0] if len(ns_slabs) == 1
                  else jnp.concatenate(ns_slabs, axis=0))
        top_hashes = (top_hash_slabs[0] if len(top_hash_slabs) == 1
                      else jnp.concatenate(top_hash_slabs, axis=0))
        hashes = jnp.concatenate([top_hashes, bot_hashes], axis=0)
        chaos.device_dispatch("panel")
        row_roots, col_roots, droot = _jit_panel_roots(k)(top_ns, hashes)
        _PANEL_WARM.add((k, construction, rows, use_fft, md))
        if roots_only:
            return row_roots, col_roots, droot
        if use_fft:
            eds = jnp.concatenate([top, bottom], axis=0)
        else:
            top = (top_panels[0] if len(top_panels) == 1
                   else jnp.concatenate(top_panels, axis=0))
            eds = jnp.concatenate([top, bottom], axis=0)
        return eds, row_roots, col_roots, droot

    return run


def panel_count(k: int) -> int:
    """Panels the active seam would stream for square size k (the
    journal's per-dispatch panel-count field); 0 when panel mode is off."""
    rows = panel_rows(k)
    return len(panel_bounds(k, rows)) if rows else 0

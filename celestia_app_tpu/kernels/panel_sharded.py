"""Multi-chip sharded extend+DAH: row panels partitioned over a device mesh.

kernels/panel.py streams a giant square's row panels through small jitted
programs — on ONE device.  This module turns that per-panel dispatch loop
into a per-device partition under the committed-shardings contract the
serve plane already runs (parallel/mesh.py, SNIPPETS pjit notes):

  * $CELESTIA_EXTEND_SHARDS=N ("auto" = every local device, floored to a
    power of two) gives each device one CONTIGUOUS slab of k/N ODS rows —
    a contiguous run of row panels — on the 1D "extend" mesh axis;
  * ROW PHASE — shard-local, no communication: each host-driven panel
    step is one shard_map program in which every device row-extends and
    leaf-hashes its own panel of the slab (the extend_leaf_digests
    epilogue shape, exactly kernels/panel._jit_row_panel batched over
    the mesh).  Panel heights are uniform across devices (every slab is
    the same k/N rows), so a panel height that does not divide the slab
    shortens the LAST step on every device at once — no padding, ever;
  * COLUMN PHASE, dense leg — one collective program per step: each
    shard computes its XOR partial products of the parity-row
    contraction (G_bits block-columns against its extended panel; mod-2
    of a sum is the XOR of per-shard mod-2 partials, the arXiv
    2108.02692 schedule split over the mesh) and a ppermute-butterfly
    XOR all-reduce (parallel/mesh.xor_allreduce — the psum-shaped
    collective for GF(2) bytes) combines them block-by-block into each
    device's OWN slice of the donated parity-row accumulator, so no
    device ever holds more than its half-EDS/N slice plus one panel;
  * COLUMN PHASE, FFT leg — the additive-FFT butterflies contract over
    the whole row axis and cannot XOR-split, but every column's chain is
    independent: one collective program all_to_alls the top half into
    2k/N-column blocks, runs kernels/fft.col_block_encode_fn shard-local
    over the column axis, and all_to_alls the bottom back row-sharded;
  * ROOTS — the digest grids all_gather (like the MULTICHIP subtree
    roots: GSPMD inserts the gather for the committed replicated
    out_shardings) and the final tree reduction is replicated;
  * OUTPUT — the EDS lands as ONE (2k, 2k, S) array under the committed
    row sharding (parallel/mesh.row_sharding3) and is retained AS-IS:
    ForestCache admission keeps the sharded buffers and the serve
    plane's share gathers route each coordinate to its owning shard
    (serve/shard.py via parallel/mesh.route_to_shards) — no reshard
    between extend, retention, and gather, pinned down to buffer
    pointers in tests/test_panel_sharded.py.

The sharded rung tops the degradation ladder (chaos/degrade.LADDER:
sharded_panel -> panel -> fused_epi -> fused -> staged -> host), and the
NEW chaos seam device.extend_shard ($CELESTIA_CHAOS extend_shard_fail=p)
fires mid-collective: a faulting sharded dispatch walks the process down
to the single-device panel runner with roots unchanged — every rung is
bit-identical (the module's whole output is pinned against the dense
full-square goldens for both RS constructions).

Per-device residency: one extended panel + the device's half-EDS/N
accumulator slice + its 61 B/leaf digest slabs — which is what raises
the practical codec ceiling toward k=4096 (MAX_CODEC_SQUARE_SIZE).
"""

from __future__ import annotations

import os
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from celestia_app_tpu.constants import (
    NAMESPACE_SIZE,
    PARITY_NAMESPACE_BYTES,
    SHARE_SIZE,
)
from celestia_app_tpu.gf.rs import active_construction, codec_for_width
from celestia_app_tpu.kernels.merkle import merkle_root_pow2
from celestia_app_tpu.kernels.nmt import leaf_digests, tree_roots_from_digests
from celestia_app_tpu.kernels.panel import (
    _resolved_config,
    panel_bounds,
    panel_rows,
)
from celestia_app_tpu.kernels.rs import encode_axis, encode_fn
from celestia_app_tpu.parallel.mesh import (
    EXTEND_AXIS,
    device_mesh,
    row_sharding,
    row_sharding3,
    xor_allreduce,
)


def _pow2_floor(n: int) -> int:
    return 1 << (n.bit_length() - 1) if n >= 1 else 0


_WARNED: set[str] = set()


def _warn_once(key: str, msg: str) -> None:
    if key in _WARNED:
        return
    _WARNED.add(key)
    import sys

    print(msg, file=sys.stderr)


def extend_shards() -> int:
    """$CELESTIA_EXTEND_SHARDS: how many devices the extend+DAH pipeline
    partitions row panels across (<=1 = the single-device panel runner).

    "auto" takes every local device, floored to a power of two (the XOR
    all-reduce butterfly and the equal-slab layout both need one).  An
    explicit integer is clamped to the device count and pow2-floored,
    LOUDLY — an operator who asked for a sharded extend must never
    silently get an unsharded one (the $CELESTIA_PIPE_PANEL precedent);
    a malformed value warns the same way.
    """
    raw = (os.environ.get("CELESTIA_EXTEND_SHARDS", "") or "").strip().lower()
    if raw in ("", "0", "off", "1"):
        return 0
    have = len(jax.devices())
    if raw == "auto":
        n = _pow2_floor(have)
        return n if n >= 2 else 0
    try:
        want = int(raw)
    except ValueError:
        _warn_once(
            f"malformed:{raw}",
            f"ignoring malformed CELESTIA_EXTEND_SHARDS value {raw!r} "
            "(want an integer shard count or 'auto'); extend sharding is "
            "OFF",
        )
        return 0
    if want <= 1:
        return 0
    n = min(want, have)
    n = _pow2_floor(n)
    if n != want:
        _warn_once(
            f"clamp:{want}:{n}",
            f"CELESTIA_EXTEND_SHARDS={want} clamped to {n} "
            f"({have} devices; power-of-two shard counts only)",
        )
    return n if n >= 2 else 0


def shards_for_k(k: int) -> int:
    """Shard count the sharded-panel seam engages with for square size k:
    0 when the panel seam is off for this k (sharding partitions the
    panel schedule, so there must be one), when $CELESTIA_EXTEND_SHARDS
    asks for <2 devices, or when k is smaller than the mesh (a k=2
    square over 8 devices has no rows to give most of them).  Both k and
    the shard count are powers of two, so engagement implies equal
    slabs."""
    if not panel_rows(k):
        return 0
    n = extend_shards()
    if n < 2 or k < n:
        return 0
    return n


def extend_mesh(shards: int):
    return device_mesh(shards, EXTEND_AXIS)


def local_panel_bounds(k: int, shards: int) -> tuple[tuple[int, int], ...]:
    """The per-device panel schedule: each device's k/shards-row slab,
    split into panels of the active height (clamped to the slab).  The
    schedule is IDENTICAL on every device — slabs are equal — so a
    non-dividing panel height shortens the last step everywhere at once
    and no step ever pads."""
    slab = k // shards
    rows = min(panel_rows(k) or slab, slab)
    return panel_bounds(slab, rows)


# Fully-resolved configurations whose sharded programs completed one run
# this process — the journal's compile hit/miss signal for the sharded
# rung (da/eds.pipeline_cache_state), keyed like kernels/panel._PANEL_WARM
# plus the shard count.
_SHARDED_WARM: set[tuple] = set()


def is_sharded_warm(k: int, construction: str | None = None) -> bool:
    construction = construction or active_construction()
    n = shards_for_k(k)
    return (k, construction, n, *_resolved_config(k, construction)) \
        in _SHARDED_WARM


def _note_build() -> None:
    from celestia_app_tpu.trace.journal import note_jit_build

    note_jit_build("sharded_panel_pipeline")


def _track(fn, k: int, shards: int, construction: str | None = None,
           h: int | None = None, sub: str = ""):
    """Register one sharded-panel sub-program with the device ledger
    (family sharded_panel_pipeline; the step height rides batch, the
    sub-program name rides the mode column so roots/assemble/leaves do
    not merge into one ledger row)."""
    from celestia_app_tpu.trace.device_ledger import track

    mode = f"sharded_panel/{sub}" if sub else "sharded_panel"
    return track(
        fn, "sharded_panel_pipeline",
        k=k, construction=construction, mode=mode,
        batch=h, shards=shards,
    )


def _parity_ns(shape) -> jnp.ndarray:
    parity = jnp.frombuffer(PARITY_NAMESPACE_BYTES, dtype=jnp.uint8)
    return jnp.broadcast_to(parity, (*shape, NAMESPACE_SIZE))


def _shard_map(f, mesh, in_specs, out_specs):
    from celestia_app_tpu.parallel._compat import shard_map

    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


# --- the sharded programs ----------------------------------------------------


@lru_cache(maxsize=None)
def _jit_row_panel_sharded(k: int, h: int, shards: int, construction: str):
    """f(panels (shards*h, k, S) row-sharded) -> (ext (shards*h, 2k, S),
    ns (shards*h, 2k, 29), hashes (shards*h, 2k, 32)), all row-sharded:
    one panel step of the row phase on every device at once — the exact
    kernels/panel._jit_row_panel body inside a collective-free shard_map
    (leaf namespaces depend only on the column inside the top half, so
    the body needs no global row index)."""
    _note_build()
    from jax.sharding import PartitionSpec as P

    mesh = extend_mesh(shards)
    encode = encode_fn(k, construction)

    def local(panel: jnp.ndarray):
        parity = jnp.frombuffer(PARITY_NAMESPACE_BYTES, dtype=jnp.uint8)
        q1 = encode(panel, 1)  # (h, k, S)
        ext = jnp.concatenate([panel, q1], axis=1)  # (h, 2k, S)
        col = jnp.arange(2 * k)
        ns = jnp.where(
            (col < k)[None, :, None], ext[..., :NAMESPACE_SIZE], parity
        )
        _, _, hashes = leaf_digests(ns, ext)
        return ext, ns, hashes

    body = _shard_map(
        local, mesh,
        in_specs=P(EXTEND_AXIS, None, None),
        out_specs=(P(EXTEND_AXIS, None, None),) * 3,
    )
    sh = row_sharding3(mesh, EXTEND_AXIS)
    return _track(
        jax.jit(body, in_shardings=sh, out_shardings=(sh, sh, sh)),
        k, shards, construction, h, sub="row",
    )


def _bounds_from_heights(heights: tuple) -> tuple:
    out, r0 = [], 0
    for h in heights:
        out.append((r0, r0 + h))
        r0 += h
    return tuple(out)


@lru_cache(maxsize=None)
def _step_generator_slices(k: int, construction: str, shards: int,
                           heights: tuple):
    """Per-step SHARDED block-columns of the bit-expanded generator:
    device i's slice for step (r0, r1) is G_bits[:, (i*slab+r0)*m :
    (i*slab+r1)*m] — together across steps and devices they are the same
    bytes the single-device panel runner caches, laid out once with the
    committed row sharding (leading device axis).  Keyed on the panel
    SCHEDULE (`heights`), not the env: a mid-process
    $CELESTIA_PIPE_PANEL flip resolves a new runner, and its slices
    must never alias a stale height's."""
    codec = codec_for_width(k, construction)
    g_bits = codec.generator_bits()
    m = codec.field.m
    slab = k // shards
    out = []
    for r0, r1 in _bounds_from_heights(heights):
        stacked = np.stack([
            g_bits[:, (i * slab + r0) * m: (i * slab + r1) * m]
            for i in range(shards)
        ])  # (shards, k*m, h*m)
        out.append(jax.device_put(
            stacked, row_sharding3(extend_mesh(shards), EXTEND_AXIS)
        ))
    from celestia_app_tpu.trace.device_ledger import note_owned_bytes

    note_owned_bytes(
        "sharded_generator_slices", (k, construction, shards, heights),
        sum(int(s.nbytes) for s in out),
    )
    return tuple(out)


@lru_cache(maxsize=None)
def _jit_zero_acc(k: int, shards: int):
    """The donated parity-row accumulator, born row-sharded: allocating
    it through a committed-out_shardings program (not a host device_put)
    means no host ever materializes the half-EDS zeros."""
    _note_build()
    sh = row_sharding3(extend_mesh(shards), EXTEND_AXIS)
    return _track(
        jax.jit(
            lambda: jnp.zeros((k, 2 * k, SHARE_SIZE), dtype=jnp.uint8),
            out_shardings=sh,
        ),
        k, shards, sub="zero_acc",
    )


@lru_cache(maxsize=None)
def _jit_col_partial_sharded(k: int, h: int, shards: int, construction: str):
    """One step of the sharded column contraction — THE collective
    program of the dense leg.

    f(acc (k, 2k, S) row-sharded [donated], ext (shards*h, 2k, S)
    row-sharded, g (shards, k*m, h*m) row-sharded) -> acc'.

    Every device computes its panel's XOR partial product one OUTPUT
    BLOCK at a time (slab*m generator rows against its h*m local
    columns), the block is XOR all-reduced across the mesh
    (parallel/mesh.xor_allreduce), and only the owning device folds it
    into its accumulator slice — working set one (slab, 2k, S) block,
    never the whole half-EDS."""
    _note_build()
    from jax.sharding import PartitionSpec as P

    from celestia_app_tpu.kernels.fused import (
        _silence_unusable_donation_warning,
    )

    _silence_unusable_donation_warning()
    mesh = extend_mesh(shards)
    m = codec_for_width(k, construction).field.m
    slab = k // shards

    def local(acc_local, ext_local, g_local):
        # acc_local (slab, 2k, S); ext_local (h, 2k, S);
        # g_local (1, k*m, h*m)
        g = g_local[0]
        idx = lax.axis_index(EXTEND_AXIS)
        for b in range(shards):
            gb = g[b * slab * m: (b + 1) * slab * m, :]
            part = encode_axis(ext_local, gb, m, contract_axis=0)
            red = xor_allreduce(part, EXTEND_AXIS, shards)
            acc_local = jnp.where(idx == b, acc_local ^ red, acc_local)
        return acc_local

    body = _shard_map(
        local, mesh,
        in_specs=(P(EXTEND_AXIS, None, None),) * 3,
        out_specs=P(EXTEND_AXIS, None, None),
    )
    sh = row_sharding3(mesh, EXTEND_AXIS)
    return _track(
        jax.jit(
            body, donate_argnums=(0,),
            in_shardings=(sh, sh, sh), out_shardings=sh,
        ),
        k, shards, construction, h, sub="col",
    )


@lru_cache(maxsize=None)
def _jit_fft_col_sharded(k: int, shards: int, heights: tuple,
                         construction: str, md: bool):
    """The FFT leg's ONE collective program: f(*ext_steps) -> bottom
    (k, 2k, S) row-sharded.

    The butterflies contract over the whole row axis, so each device's
    top slab all_to_alls into a 2k/shards-column block (columns are pure
    batch in the butterfly network — kernels/fft.col_block_encode_fn),
    the block encodes shard-local, and a second all_to_all lands the
    parity rows back on the committed row sharding.  Shares cross the
    interconnect exactly twice; nothing else moves."""
    _note_build()
    from jax.sharding import PartitionSpec as P

    from celestia_app_tpu.kernels.fft import col_block_encode_fn

    mesh = extend_mesh(shards)
    col_encode = col_block_encode_fn(k, construction, md=md)

    def local(*ext_locals):
        # each (h_j, 2k, S); concatenated = this device's contiguous slab
        top_local = (ext_locals[0] if len(ext_locals) == 1
                     else jnp.concatenate(ext_locals, axis=0))
        top_local = lax.optimization_barrier(top_local)
        cols_blk = lax.all_to_all(
            top_local, EXTEND_AXIS, split_axis=1, concat_axis=0, tiled=True
        )  # (k, 2k/shards, S) — device-major stacking == natural rows
        bottom_cols = col_encode(cols_blk)  # (k, 2k/shards, S)
        bottom_cols = lax.optimization_barrier(bottom_cols)
        return lax.all_to_all(
            bottom_cols, EXTEND_AXIS, split_axis=0, concat_axis=1,
            tiled=True,
        )  # (k/shards, 2k, S)

    body = _shard_map(
        local, mesh,
        in_specs=(P(EXTEND_AXIS, None, None),) * len(heights),
        out_specs=P(EXTEND_AXIS, None, None),
    )
    sh = row_sharding3(mesh, EXTEND_AXIS)
    return _track(
        jax.jit(
            body, in_shardings=(sh,) * len(heights), out_shardings=sh
        ),
        k, shards, construction, sub="fft_col",
    )


@lru_cache(maxsize=None)
def _jit_parity_leaves_sharded(k: int, shards: int):
    """f(bottom (k, 2k, S) row-sharded) -> hashes (k, 2k, 32) row-sharded:
    leaf digests of the all-parity-namespace bottom half, shard-local."""
    _note_build()
    from jax.sharding import PartitionSpec as P

    mesh = extend_mesh(shards)
    slab = k // shards

    def local(block: jnp.ndarray):
        ns = _parity_ns((slab, 2 * k))
        _, _, hashes = leaf_digests(ns, block)
        return hashes

    body = _shard_map(
        local, mesh,
        in_specs=P(EXTEND_AXIS, None, None),
        out_specs=P(EXTEND_AXIS, None, None),
    )
    sh = row_sharding3(mesh, EXTEND_AXIS)
    return _track(jax.jit(body, in_shardings=sh, out_shardings=sh),
                  k, shards, sub="parity_leaves")


@lru_cache(maxsize=None)
def _natural_perm(k: int, shards: int, heights: tuple) -> tuple:
    """Static permutation from step-major stacking to natural row order.

    The per-step sharded outputs concatenate (step-major, then
    device-major, then row); natural ODS row i*slab + r0_j + r sits at
    stacked position (steps offset j) + i*h_j + r.  Pure layout math,
    keyed on the panel schedule (the env can re-resolve it
    mid-process)."""
    bounds = _bounds_from_heights(heights)
    slab = k // shards
    perm = np.empty(k, dtype=np.int32)
    off = 0
    for (r0, r1) in bounds:
        h = r1 - r0
        for i in range(shards):
            rows = np.arange(h)
            perm[i * slab + r0 + rows] = off + i * h + rows
        off += shards * h
    return tuple(int(x) for x in perm)


def _take_natural(steps, perm):
    x = (steps[0] if len(steps) == 1
         else jnp.concatenate(steps, axis=0))
    if perm == tuple(range(len(perm))):
        return x
    return jnp.take(x, jnp.asarray(perm, dtype=jnp.int32), axis=0)


@lru_cache(maxsize=None)
def _jit_roots_sharded(k: int, shards: int, heights: tuple):
    """f(*ns_steps, *hash_steps, bot_hashes) -> (row_roots, col_roots,
    droot), replicated: the digest grids reassemble in natural row order
    (static permutation), all_gather under the committed replicated
    out_shardings — the MULTICHIP subtree-root shape — and the tree
    reduction runs replicated, identical to kernels/panel's
    _jit_panel_roots over the same digests."""
    _note_build()
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = extend_mesh(shards)
    perm = _natural_perm(k, shards, heights)
    n_steps = len(heights)

    def run(*args):
        ns_steps = args[:n_steps]
        hash_steps = args[n_steps:2 * n_steps]
        bot_hashes = args[2 * n_steps]
        top_ns = _take_natural(ns_steps, perm)  # (k, 2k, 29)
        top_hashes = _take_natural(hash_steps, perm)  # (k, 2k, 32)
        ns = jnp.concatenate([top_ns, _parity_ns((k, 2 * k))], axis=0)
        hashes = jnp.concatenate([top_hashes, bot_hashes], axis=0)
        row_roots = tree_roots_from_digests(ns, ns, hashes)  # (2k, 90)
        nst = ns.transpose(1, 0, 2)
        col_roots = tree_roots_from_digests(
            nst, nst, hashes.transpose(1, 0, 2)
        )
        droot = merkle_root_pow2(
            jnp.concatenate([row_roots, col_roots], axis=0)
        )
        return row_roots, col_roots, droot

    sh = row_sharding3(mesh, EXTEND_AXIS)
    rep = NamedSharding(mesh, P())
    return _track(
        jax.jit(
            run,
            in_shardings=(sh,) * (2 * n_steps + 1),
            out_shardings=(rep, rep, rep),
        ),
        k, shards, sub="roots",
    )


@lru_cache(maxsize=None)
def _jit_eds_assemble(k: int, shards: int, heights: tuple):
    """f(*ext_steps, bottom) -> eds (2k, 2k, S) under THE committed row
    sharding (parallel/mesh.row_sharding3) — the one layout commit of the
    whole pipeline.  GSPMD lowers the natural-order gather across shards
    (this is the distributed twin of the panel runner's final
    concatenate); everything downstream — retention, the serve share
    gather — names this sharding back and never moves a byte."""
    _note_build()
    mesh = extend_mesh(shards)
    perm = _natural_perm(k, shards, heights)
    n_steps = len(heights)

    def run(*args):
        ext_steps = args[:n_steps]
        bottom = args[n_steps]
        top = _take_natural(ext_steps, perm)  # (k, 2k, S)
        return jnp.concatenate([top, bottom], axis=0)

    sh = row_sharding3(mesh, EXTEND_AXIS)
    return _track(
        jax.jit(
            run, in_shardings=(sh,) * (n_steps + 1), out_shardings=sh
        ),
        k, shards, sub="assemble",
    )


# --- the runner --------------------------------------------------------------


def sharded_panel_pipeline(k: int, construction: str | None = None,
                           roots_only: bool = False):
    """The sharded panel-streamed pipeline callable for square size k.

    Same surface as kernels/panel.panel_pipeline: f(ods) ->
    (eds, row_roots, col_roots, droot) or the roots_only twin — with the
    EDS returned ROW-SHARDED across the extend mesh under
    parallel/mesh.row_sharding3 (roots replicated, read as host bytes
    like any other lowering's).  `ods` is the (k, k, S) array (host
    numpy uploads one panel step at a time, each step already laid out
    row-sharded).

    Host-driven like the single-device runner: every dispatch passes the
    chaos device.dispatch seam under mode "sharded_panel" AND the NEW
    device.extend_shard seam ($CELESTIA_CHAOS extend_shard_fail=p), so
    an injected mid-collective fault surfaces to guarded_dispatch and
    walks the ladder down to the single-device panel rung.
    """
    construction = construction or active_construction()
    shards = shards_for_k(k)
    if not shards:
        raise ValueError(
            f"sharded panel mode not engaged for k={k} "
            f"(CELESTIA_EXTEND_SHARDS={os.environ.get('CELESTIA_EXTEND_SHARDS')!r}, "
            f"CELESTIA_PIPE_PANEL={os.environ.get('CELESTIA_PIPE_PANEL')!r})"
        )
    rows, use_fft, md = _resolved_config(k, construction)
    return _sharded_runner(k, construction, roots_only, shards, rows,
                           use_fft, md)


@lru_cache(maxsize=None)
def _sharded_runner(k: int, construction: str, roots_only: bool,
                    shards: int, rows: int, use_fft: bool, md: bool):
    # The schedule derives from the CACHE KEY (`rows`), never the live
    # env: a $CELESTIA_PIPE_PANEL flip resolves a different runner, and
    # this one keeps the bounds it was built for.
    slab = k // shards
    bounds = panel_bounds(slab, min(rows or slab, slab))
    heights = tuple(r1 - r0 for r0, r1 in bounds)
    sh3 = row_sharding3(extend_mesh(shards), EXTEND_AXIS)

    def _seams():
        from celestia_app_tpu import chaos

        chaos.device_dispatch("sharded_panel")
        chaos.extend_shard()

    def run(x):
        if isinstance(x, (list, tuple)):
            raise ValueError(
                "sharded panel mode takes the whole (k, k, S) ODS "
                "(panel staging is the runner's own slab layout)"
            )
        if x.shape != (k, k, SHARE_SIZE):
            raise ValueError(f"bad ODS shape {x.shape} for k={k}")
        ods = x if isinstance(x, np.ndarray) else np.asarray(x)
        ext_steps: list = []
        ns_steps: list = []
        hash_steps: list = []
        acc = None
        g_steps = None
        if not use_fft:
            g_steps = _step_generator_slices(k, construction, shards,
                                             heights)
            _seams()
            acc = _jit_zero_acc(k, shards)()
        for j, (r0, r1) in enumerate(bounds):
            h = r1 - r0
            _seams()
            stacked = np.concatenate([
                ods[i * slab + r0: i * slab + r1] for i in range(shards)
            ], axis=0)
            panel_dev = jax.device_put(
                np.ascontiguousarray(stacked, dtype=np.uint8), sh3
            )
            ext, ns, hashes = _jit_row_panel_sharded(
                k, h, shards, construction
            )(panel_dev)
            ns_steps.append(ns)
            hash_steps.append(hashes)
            if not use_fft:
                _seams()
                acc = _jit_col_partial_sharded(
                    k, h, shards, construction
                )(acc, ext, g_steps[j])
            if use_fft or not roots_only:
                ext_steps.append(ext)
        if use_fft:
            _seams()
            bottom = _jit_fft_col_sharded(
                k, shards, heights, construction, md
            )(*ext_steps)
        else:
            bottom = acc
        _seams()
        bot_hashes = _jit_parity_leaves_sharded(k, shards)(bottom)
        _seams()
        row_roots, col_roots, droot = _jit_roots_sharded(
            k, shards, heights
        )(*ns_steps, *hash_steps, bot_hashes)
        _SHARDED_WARM.add((k, construction, shards, rows, use_fft, md))
        if roots_only:
            return row_roots, col_roots, droot
        _seams()
        eds = _jit_eds_assemble(k, shards, heights)(*ext_steps, bottom)
        return eds, row_roots, col_roots, droot

    return run


def sharded_panel_count(k: int) -> int:
    """Panel STEPS the sharded seam would stream for square size k (each
    step is one mesh-wide dispatch); 0 when the sharded seam is off."""
    n = shards_for_k(k)
    return len(local_panel_bounds(k, n)) if n else 0

"""Bitsliced XOR lowering of the RS bit-matmul + fused leaf-hash epilogue.

"Accelerating XOR-based Erasure Coding using Program Optimization
Techniques" (arXiv 2108.02692) re-expresses GF(2^8) encode as scheduled
XOR planes.  On TPU that maps to this kernel: the mod-2 matmul

    parity_bits = (G_bits @ data_bits) mod 2      (kernels/rs.py)

never touches the MXU, the int32 accumulator, or the `& 1` reduction.
Instead the CONTRACTION axis is packed 32 bits per uint32 word, and
because bit-parity is GF(2)-linear — parity(a ^ b) = parity(a) ^
parity(b) — the whole row-times-column dot collapses to

    acc[i, c]    = XOR_w ( G_words[w, i] & B_words[w, c] )
    parity[i, c] = 5-step xor-fold of acc[i, c]'s 32 bits

i.e. NW = ceil(n*m/32) AND+XOR vector ops per (output-row, column) tile
plus one fold.  Nothing is ever inflated 8x: the packed words are
byte-for-byte the size of the input shares (4 uint8 byte-planes -> 1
uint32), the fold and the bit->byte repack happen in vregs, and HBM sees
only shares in and parity bytes out.

Bit order matches gf/field.expand_bit_matrix (symbol-major, byte-then-bit
within a symbol; bit t of byte b is LSB-first), so the kernel is
bit-identical to `kernels/rs.encode_axis` — pinned across k and both RS
constructions by tests/test_rs_xor.py.

Second kernel, the fused LEAF-HASH EPILOGUE: the column phase of the
square extension produces only parity shares (namespace = the constant
parity namespace), so their NMT leaf digests depend on nothing but the
extend output itself.  `extend_leaf_digests` computes the column-phase
extend tile and feeds it straight into kernels/sha256._leaf_tile_compute
while it is still in VMEM — the bottom half of the EDS lands in HBM once
(as output) instead of being written, re-read, and re-materialized as 542
-byte padded messages before hashing.  kernels/fused.extend_and_dah_fn's
`epilogue=True` variant rides it (pipeline mode "fused_epi", seated by
the bench autotuner like every other lowering).

Both kernels run under interpret mode off-TPU (`interpret=None` resolves
by platform), so the library paths are CPU-runnable — slowly, which is
fine: CPU carries the tests; the chip carries the bench.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from celestia_app_tpu.constants import NAMESPACE_SIZE, PARITY_NAMESPACE_BYTES

_TC = 256  # symbol-columns per grid step (lane axis), standalone kernel
_OT_MAX = 128  # output bit-rows per grid step, standalone kernel
_EPI_OT_MAX = 1024  # output bit-rows per grid step, epilogue kernel

try:  # pallas imports fail on backends without Mosaic; interpret covers CPU
    from jax.experimental import pallas as pl
except Exception:  # pragma: no cover — chaos-ok: jax always ships pallas today
    pl = None


def _default_interpret() -> bool:
    """Compiled Mosaic on the chip, interpret everywhere else."""
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:  # chaos-ok: no backend — interpret is the safe floor
        return True


def pack_generator_words(G_bits: np.ndarray) -> np.ndarray:
    """(P*m, n*m) 0/1 generator -> (NW, P*m) uint32, contraction packed.

    Word w, output-row i holds contraction bits [32w, 32w+32) of G's row i
    (LSB first).  Transposed so the kernel's per-word read G_words[w] is a
    contiguous row.  The contraction axis is zero-padded to a multiple of
    32 — AND with a 0 bit contributes nothing, so padding never changes a
    parity.  Host-side, once per (k, construction): G is a constant.
    """
    Pm, nm = G_bits.shape
    pad = (-nm) % 32
    if pad:
        G_bits = np.concatenate(
            [G_bits, np.zeros((Pm, pad), dtype=G_bits.dtype)], axis=1
        )
    nw = (nm + pad) // 32
    w = G_bits.reshape(Pm, nw, 32).astype(np.uint64)
    words = (w << np.arange(32, dtype=np.uint64)).sum(axis=2)
    return np.ascontiguousarray(words.astype(np.uint32).T)  # (NW, Pm)


def pack_data_words(x: jnp.ndarray) -> jnp.ndarray:
    """(n, bps, cols) uint8 byte planes -> (NW, cols) uint32.

    Contraction row j*m + 8*b + t (share j, byte b, bit t — the
    encode_axis unpack order) lands on bit 8*q + t of word w where the
    flat byte row j*bps + b = 4*w + q: packing 4 consecutive byte rows
    little-endian IS the bit order the generator packing uses.  Byte rows
    are zero-padded to a multiple of 4 (see pack_generator_words).
    """
    n, bps, cols = x.shape
    rows = n * bps
    flat = x.reshape(rows, cols)
    pad = (-rows) % 4
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((pad, cols), dtype=jnp.uint8)], axis=0
        )
    w = flat.reshape((rows + pad) // 4, 4, cols).astype(jnp.uint32)
    return (
        w[:, 0]
        | (w[:, 1] << np.uint32(8))
        | (w[:, 2] << np.uint32(16))
        | (w[:, 3] << np.uint32(24))
    )  # (NW, cols)


def _fold_parity(v: jnp.ndarray) -> jnp.ndarray:
    """Per-element parity of a uint32: 5 xor-folds, result in bit 0."""
    v = v ^ (v >> np.uint32(16))
    v = v ^ (v >> np.uint32(8))
    v = v ^ (v >> np.uint32(4))
    v = v ^ (v >> np.uint32(2))
    v = v ^ (v >> np.uint32(1))
    return v & np.uint32(1)


def _pack_bit_rows(bits: jnp.ndarray) -> jnp.ndarray:
    """(R, C) 0/1 uint32 bit rows -> (R/8, C) uint8, LSB-first within a
    byte — the encode_axis repack order."""
    r, c = bits.shape
    pb = bits.reshape(r // 8, 8, c)
    weights = (jnp.uint32(1) << jnp.arange(8, dtype=jnp.uint32))[None, :, None]
    return (pb * weights).sum(axis=1).astype(jnp.uint8)


def _xor_kernel(nw: int, ot: int, tc: int):
    """b_ref (NW, TC) + g_ref (NW, OT) uint32 -> out_ref (OT/8, TC) uint8."""

    def kernel(b_ref, g_ref, out_ref):
        def step(w, acc):
            return acc ^ (g_ref[w][:, None] & b_ref[w][None, :])

        acc = jax.lax.fori_loop(
            0, nw, step, jnp.zeros((ot, tc), dtype=jnp.uint32)
        )
        out_ref[...] = _pack_bit_rows(_fold_parity(acc))

    return kernel


def _out_tile(Pm: int, cap: int) -> int:
    """Output bit-rows per grid step: Pm is k*m (a power of two >= 16 for
    every supported field), so min(cap, Pm) always divides it."""
    return min(cap, Pm)


def mod2_matmul_planes_xor(
    G_words: jnp.ndarray, x: jnp.ndarray, m: int, interpret: bool | None = None
) -> jnp.ndarray:
    """Drop-in for kernels/rs._mod2_matmul_planes on the XOR schedule.

    G_words: (NW, P*m) uint32 from pack_generator_words; x: (n, bps, cols)
    uint8 byte planes.  Returns (P, bps, cols) uint8 parity planes.
    """
    n, bps, cols = x.shape
    nw, Pm = G_words.shape
    assert nw == (n * m + 31) // 32 and Pm % 8 == 0, (G_words.shape, x.shape, m)
    if interpret is None:
        interpret = _default_interpret()
    ot = _out_tile(Pm, _OT_MAX)
    B = pack_data_words(x)
    pad = (-cols) % _TC
    if pad:
        B = jnp.pad(B, ((0, 0), (0, pad)))
    total = cols + pad
    out = pl.pallas_call(
        _xor_kernel(nw, ot, _TC),
        grid=(total // _TC, Pm // ot),
        in_specs=[
            pl.BlockSpec((nw, _TC), lambda c, r: (0, c)),
            pl.BlockSpec((nw, ot), lambda c, r: (0, r)),
        ],
        out_specs=pl.BlockSpec((ot // 8, _TC), lambda c, r: (r, c)),
        out_shape=jax.ShapeDtypeStruct((Pm // 8, total), jnp.uint8),
        interpret=interpret,
    )(B, G_words)
    P = Pm // m
    return out[:, :cols].reshape(P, bps, cols)


def encode_axis_xor(
    data: jnp.ndarray,
    G_words: jnp.ndarray,
    m: int,
    contract_axis: int = 1,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """kernels/rs.encode_axis with the bitsliced XOR core (same byte moves)."""
    bps = m // 8
    x = jnp.moveaxis(data, contract_axis, 0)
    n, batch, S = x.shape
    nsym = S // bps
    cols = batch * nsym
    planes = jnp.moveaxis(x.reshape(n, batch, nsym, bps), 3, 1)
    out = mod2_matmul_planes_xor(
        G_words, planes.reshape(n, bps, cols), m, interpret=interpret
    )
    P = out.shape[0]
    by = jnp.moveaxis(out.reshape(P, bps, batch, nsym), 1, 3)
    return jnp.moveaxis(by.reshape(P, batch, S), 0, contract_axis)


@lru_cache(maxsize=None)
def xor_supported(k: int, m: int) -> bool:
    """Byte-granular fields only (m a multiple of 8 — every construction
    in gf/ qualifies); the padding inside the packers removes every other
    alignment constraint, so unlike the dense Pallas kernel this one has
    no MXU-tile floor."""
    return pl is not None and m % 8 == 0


# --------------------------------------------------------------------------
# Fused leaf-hash epilogue: column-phase extend feeds the NMT leaf rounds
# straight from VMEM
# --------------------------------------------------------------------------


def _epi_kernel(nw: int, ot: int, nsym: int, bps: int, m: int):
    """One batch-column's worth of bottom shares AND their leaf digests.

    b_ref (NW, nsym) + g_ref (NW, OT) uint32 ->
      shares_ref (OT/8, nsym) uint8   (the packed byte planes, the same
                                       layout the standalone kernel emits)
      dig_ref    (8, OT/m)    uint32  (one digest column per share)

    Every bottom-half leaf carries the constant parity namespace, so its
    message is 0x00 || 0xFF^29 || share — nothing but the extend output,
    which is exactly why the hash can ride the extend tile without ever
    seeing HBM.  _leaf_tile_compute is the SAME per-tile function the
    fused-leaf SHA kernel wraps, so digest bytes cannot fork between the
    two fused paths.
    """
    from celestia_app_tpu.kernels.sha256 import _leaf_tile_compute

    tn = ot // m
    s = nsym * bps
    parity = [int(v) for v in PARITY_NAMESPACE_BYTES]

    def kernel(b_ref, g_ref, shares_ref, dig_ref):
        def step(w, acc):
            return acc ^ (g_ref[w][:, None] & b_ref[w][None, :])

        acc = jax.lax.fori_loop(
            0, nw, step, jnp.zeros((ot, nsym), dtype=jnp.uint32)
        )
        by = _pack_bit_rows(_fold_parity(acc))  # (tn*bps, nsym)
        shares_ref[...] = by
        # Byte (sym, b) of share p sits at by[p*bps + b, sym]: regroup to
        # the (share, 512-byte) rows the leaf rounds consume — a tile-
        # local transpose, never an HBM round trip.
        share_tile = by.reshape(tn, bps, nsym).transpose(0, 2, 1).reshape(tn, s)
        ns_tile = jnp.concatenate(
            [jnp.full((tn, 1), v, dtype=jnp.uint8) for v in parity], axis=1
        )
        dig_ref[...] = _leaf_tile_compute(ns_tile, share_tile, tn)

    return kernel


def extend_leaf_digests(
    top: jnp.ndarray,
    G_words: jnp.ndarray,
    m: int,
    interpret: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Column-phase extend + bottom-half NMT leaf digests, one program.

    top: (k, 2k, S) uint8 — the row-extended top half; contraction runs
    over axis 0 (the transpose-free column phase).  Returns
    (bottom (k, 2k, S) uint8, leaf_hashes (k, 2k, 32) uint8) with bottom
    bit-identical to encode(top, 0) and hashes bit-identical to
    sha256(0x00 || parity_ns || share) — tests/test_rs_xor.py pins both.
    """
    from celestia_app_tpu.constants import SHARE_SIZE
    from celestia_app_tpu.kernels.sha256 import _digest_bytes

    k, n2, S = top.shape
    assert S == SHARE_SIZE, top.shape  # _leaf_tile_compute is share-shaped
    bps = m // 8
    nsym = S // bps
    nw, Pm = G_words.shape
    ot = _out_tile(Pm, _EPI_OT_MAX)
    row_tiles = Pm // ot
    tn = ot // m
    if interpret is None:
        interpret = _default_interpret()
    planes = jnp.moveaxis(top.reshape(k, n2, nsym, bps), 3, 1)  # (k,bps,n2,nsym)
    B = pack_data_words(planes.reshape(k, bps, n2 * nsym))
    shares, dig = pl.pallas_call(
        _epi_kernel(nw, ot, nsym, bps, m),
        grid=(n2, row_tiles),  # row tiles fastest; B block constant per b
        in_specs=[
            pl.BlockSpec((nw, nsym), lambda b, r: (0, b)),
            pl.BlockSpec((nw, ot), lambda b, r: (0, r)),
        ],
        out_specs=[
            pl.BlockSpec((ot // 8, nsym), lambda b, r: (r, b)),
            pl.BlockSpec((8, tn), lambda b, r: (0, b * row_tiles + r)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Pm // 8, n2 * nsym), jnp.uint8),
            jax.ShapeDtypeStruct((8, n2 * Pm // m), jnp.uint32),
        ],
        interpret=interpret,
    )(B, G_words)
    P = Pm // m  # == k for the square generator
    by = jnp.moveaxis(shares.reshape(P, bps, n2, nsym), 1, 3)
    bottom = by.reshape(P, n2, S)
    # Digest lanes are batch-major then share (b * P + p): back to the
    # (row, col) grid of the bottom half.
    d = dig.reshape(8, n2, P).transpose(2, 1, 0)  # (P, n2, 8)
    hashes = _digest_bytes(d.reshape(P * n2, 8)).reshape(P, n2, 32)
    return bottom, hashes


def _use_epilogue_kernel(k: int, m: int) -> bool:
    """The compiled epilogue kernel runs on the chip; everywhere else the
    fused_epi mode rides the XLA composition below (same ops, same bytes
    — interpret mode cannot execute the ~7k-op unrolled SHA rounds at
    square scale in reasonable time, the same reason the fused-leaf SHA
    tests jit _leaf_tile_compute directly)."""
    if not xor_supported(k, m):
        return False
    try:
        platform = jax.devices()[0].platform
    except Exception:  # chaos-ok: no backend — XLA composition floor
        return False
    return platform == "tpu"


def bottom_leaf_fn(k: int, construction: str | None = None, *,
                   fallback_encode=None):
    """f(top) -> (bottom, leaf_hashes) for the fused_epi pipeline.

    On TPU: the fused Pallas epilogue (extend tile -> leaf rounds in
    VMEM).  Elsewhere: the staged XLA composition through the SEATED
    encode lowering (`fallback_encode`, required — the caller already
    built it, and the epilogue mode must not silently fork the RS seat
    off-chip).  Both branches are bit-identical; the mode choice is a
    perf detail, never a correctness hazard.
    """
    from celestia_app_tpu.gf.rs import codec_for_width

    codec = codec_for_width(k, construction)
    m = codec.field.m
    if _use_epilogue_kernel(k, m):
        G_words = jnp.asarray(pack_generator_words(codec.generator_bits()))

        def fn(top: jnp.ndarray):
            return extend_leaf_digests(top, G_words, m)

        return fn

    assert fallback_encode is not None, "off-TPU epilogue needs the seat's encode"
    from celestia_app_tpu.kernels.nmt import leaf_digests

    def fn(top: jnp.ndarray):
        bottom = fallback_encode(top, 0)
        parity = jnp.frombuffer(PARITY_NAMESPACE_BYTES, dtype=jnp.uint8)
        par_ns = jnp.broadcast_to(parity, (k, 2 * k, NAMESPACE_SIZE))
        _, _, hashes = leaf_digests(par_ns, bottom)
        return bottom, hashes

    return fn

"""Reed-Solomon square extension as MXU bit-matmuls.

TPU-first lowering of the rsmt2d encode (reference
pkg/da/data_availability_header.go:74 -> rsmt2d.ComputeExtendedDataSquare):
GF(2^m) arithmetic never reaches the device as table lookups.  Multiplication
by a field constant is GF(2)-linear on the symbol's bit vector, so the whole
systematic generator G (gf/rs.py) bit-expands to a constant 0/1 matrix G_bits
of shape (k*m, k*m), and

    parity_bits = (G_bits @ data_bits) mod 2

is one dense matmul per axis phase - exactly the shape the MXU wants.  The
mod-2 is a final `& 1` on the int32 accumulator (max k*m = 8192 partial
products, far below 2^31).

Layout discipline (measured on v5e: uint8 relayouts are ~50x the matmul
cost, so they decide everything):

  * all transposes happen on BYTE arrays, never on the 8x larger bit
    planes;
  * bit unpack/pack keep the huge batch axis (R*nsym) as the trailing
    lane dimension and put the 8-wide bit axis in the middle;
  * `encode_axis` contracts over a caller-chosen axis, so the column
    phase of the square extension consumes the row-extended top half
    with NO transpose at all - its parity lands directly as the bottom
    rows.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from celestia_app_tpu.gf.rs import active_construction, codec_for_width

# int8 feeds the MXU's integer path on TPU; exactness: 0/1 products
# accumulated mod 256 (int8 wraparound) keep bit 0 — the only bit the
# mod-2 result reads — exact at any contraction depth.
_DOT_DTYPE = jnp.int8


def _mod2_matmul_planes(G_bits: jnp.ndarray, x: jnp.ndarray, m: int) -> jnp.ndarray:
    """Core bit-sliced product: bytes (n, bps, cols) -> bytes (P, bps, cols).

    `x` holds the contraction-axis shares as byte planes: x[j, b, c] is
    byte b of symbol-column c of share j.  Unpacks to {0,1} int8 with the
    bit axis in the middle, runs ONE dense (P*m, n*m) x (n*m, cols) int8
    matmul, and repacks.  cols is the flattened (batch x symbol) axis and
    stays the innermost lane dimension throughout.
    """
    n, bps, cols = x.shape
    bits = (x[:, :, None, :] >> jnp.arange(8, dtype=jnp.uint8)[None, None, :, None]) & 1
    B = bits.reshape(n * m, cols).astype(_DOT_DTYPE)
    # int32 accumulation: int8 accumulation would be exact too (parity
    # survives mod-256 wraparound) but measured ~100x slower on the axon
    # TPU backend — XLA has no fast int8-accumulate MXU path there.
    acc = lax.dot_general(
        G_bits.astype(_DOT_DTYPE),
        B,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # (P*m, cols)
    P = acc.shape[0] // m
    pb = (acc & 1).astype(jnp.uint32).reshape(P, bps, 8, cols)
    weights = (jnp.uint32(1) << jnp.arange(8, dtype=jnp.uint32))[None, None, :, None]
    return (pb * weights).sum(axis=2).astype(jnp.uint8)  # (P, bps, cols)


def encode_axis(
    data: jnp.ndarray, G_bits: jnp.ndarray, m: int, contract_axis: int = 1
) -> jnp.ndarray:
    """Systematic encode contracting over `contract_axis` of (A, B, S) bytes.

    Returns parity with the contracted axis replaced by P = G rows / m at
    the same position; the other two axes are untouched.  contract_axis=0
    runs with zero byte transposes (the square extension's column phase).
    """
    bps = m // 8
    x = jnp.moveaxis(data, contract_axis, 0)  # (n, batch, S)
    n, batch, S = x.shape
    nsym = S // bps
    cols = batch * nsym
    planes = jnp.moveaxis(x.reshape(n, batch, nsym, bps), 3, 1)  # (n, bps, batch, nsym)
    out = _mod2_matmul_planes(G_bits, planes.reshape(n, bps, cols), m)
    P = out.shape[0]
    by = jnp.moveaxis(out.reshape(P, bps, batch, nsym), 1, 3)  # (P, batch, nsym, bps)
    return jnp.moveaxis(by.reshape(P, batch, S), 0, contract_axis)


def _fft_choice(k: int) -> tuple[bool, bool | None]:
    """(use_fft, force_md) for size k.

    $CELESTIA_RS_FFT: "on" / "off" / "auto" (default). "on" honors
    $CELESTIA_RS_FFT_MD as before (force_md None = env-controlled).

    Auto is platform- and size-aware, from measurement:
      * TPU — dense everywhere: the grouped butterflies measured 0.359 s
        vs 0.255 s dense at k=512 (r3); the transpose-free md variant is
        unmeasured on the chip, so it stays an autotune candidate
        (bench parts row) rather than the default;
      * elsewhere — the md FFT at k >= 512, where dense's O(k^3) MACs
        overwhelm a CPU: measured 60.4 s vs 138.1 s dense steady-state
        at k=512 (2026-07-31, this image), dead heat at k=256 (11.7 vs
        11.6 s), dense faster below.
    Both paths produce identical bytes (tests/test_fft.py pins it), so a
    stale cached choice is a perf detail, never a correctness hazard —
    caches key on (k, construction) only.
    """
    import os

    mode = os.environ.get("CELESTIA_RS_FFT", "auto")
    if mode == "on":
        return True, None
    if mode != "auto":
        return False, None
    try:
        platform = jax.devices()[0].platform
    except Exception:  # chaos-ok: no backend: tracing only
        return False, None
    if platform == "cpu" and k >= 512:
        # Only CPU was measured; other accelerators stay on dense until
        # a measurement says otherwise (GPUs in particular excel at the
        # dense matmul the FFT avoids).
        return True, True
    return False, None


def _use_pallas_rs(k: int, m: int) -> bool:
    """$CELESTIA_RS_PALLAS: "on" / "off" (default).  The fused Pallas
    dense kernel (kernels/rs_pallas.py) keeps the 8x bit planes in VMEM —
    unmeasured on hardware yet, so it is opt-in until a chip run (the
    bench autotuner measures it as the rs_dense_pl candidate and flips
    the env for the rows it wins). Requires MXU-tileable dims."""
    import os

    if os.environ.get("CELESTIA_RS_PALLAS", "off") != "on":
        return False
    from celestia_app_tpu.kernels.rs_pallas import pallas_supported

    return pallas_supported(k, m)


def _use_xor_rs(k: int, m: int) -> bool:
    """$CELESTIA_RS_XOR: "on" / "off" (default).  The bitsliced XOR/AND-
    popcount Pallas lowering (kernels/rs_xor.py): no MXU, no int32
    accumulator, no 8x bit inflation — the arXiv 2108.02692 schedule.
    Opt-in until a chip run; the bench autotuner measures it as the
    rs_xor parts candidate and flips this env for the rows it wins.
    Off-TPU the kernel runs in interpret mode (slow but correct), so the
    seam is CPU-runnable."""
    import os

    if os.environ.get("CELESTIA_RS_XOR", "off") != "on":
        return False
    from celestia_app_tpu.kernels.rs_xor import xor_supported

    return xor_supported(k, m)


def encode_fn(k: int, construction: str | None = None):
    """The encode-path selector: f(data, contract_axis) -> parity shares.

    ONE owner for the FFT-vs-dense-vs-pallas-vs-xor policy — both the
    single-chip square extension and the sharded pipeline build their
    encode through here, so the selection (and any future threshold/env
    change) cannot diverge between them.  Auto picks per platform and
    size (see _fft_choice for the measured rationale: dense on TPU,
    md-FFT on other platforms at k >= 512); CELESTIA_RS_FFT=on forces
    the additive-FFT butterflies, CELESTIA_RS_PALLAS=on the fused Pallas
    dense kernel, and CELESTIA_RS_XOR=on the bitsliced XOR schedule
    (kernels/rs_xor.py) — identical bytes any way.
    """
    from celestia_app_tpu.gf.rs import active_construction as _active

    codec = codec_for_width(k, construction)
    m = codec.field.m
    resolved = construction or _active()

    use_fft, force_md = _fft_choice(k)
    if use_fft:
        from celestia_app_tpu.kernels.fft import encode_axis_fft

        def encode(data: jnp.ndarray, contract_axis: int = 1) -> jnp.ndarray:
            return encode_axis_fft(data, k, resolved, contract_axis,
                                   md=force_md)
    elif _use_pallas_rs(k, m):
        from celestia_app_tpu.kernels.rs_pallas import encode_axis_pallas

        G_bits_pl = jnp.asarray(codec.generator_bits())

        def encode(data: jnp.ndarray, contract_axis: int = 1) -> jnp.ndarray:
            return encode_axis_pallas(data, G_bits_pl, m, contract_axis)
    elif _use_xor_rs(k, m):
        from celestia_app_tpu.kernels.rs_xor import (
            encode_axis_xor,
            pack_generator_words,
        )

        G_words = jnp.asarray(pack_generator_words(codec.generator_bits()))

        def encode(data: jnp.ndarray, contract_axis: int = 1) -> jnp.ndarray:
            return encode_axis_xor(data, G_words, m, contract_axis)
    else:
        G_bits = jnp.asarray(codec.generator_bits())

        def encode(data: jnp.ndarray, contract_axis: int = 1) -> jnp.ndarray:
            return encode_axis(data, G_bits, m, contract_axis)

    return encode


def extend_square_fn(k: int, construction: str | None = None):
    """Returns eds = f(ods) for a fixed square size k.

    ods: (k, k, SHARE_SIZE) uint8 -> eds: (2k, 2k, SHARE_SIZE) uint8 with
    quadrants [[Q0, Q1], [Q2, Q3]] (row-parity right, column-parity below),
    matching rsmt2d's quadrant layout.  The RS construction is resolved at
    build time; callers caching the result must key on it.
    """
    encode = encode_fn(k, construction)

    def extend(ods: jnp.ndarray) -> jnp.ndarray:
        # Row phase: each of the k rows is a codeword batch along cols.
        q1 = encode(ods, 1)  # (k, k, S)
        top = jnp.concatenate([ods, q1], axis=1)  # (k, 2k, S)
        # Column phase: contract over the row axis directly - Q2 and Q3
        # arrive as the bottom rows with no transpose (row/col encodes
        # commute: EDS = [[Q0, Q0 G^T], [G Q0, G Q0 G^T]]).
        bottom = encode(top, 0)  # (k, 2k, S)
        return jnp.concatenate([top, bottom], axis=0)  # (2k, 2k, S)

    return extend


@lru_cache(maxsize=None)
def _jit_extend_square(k: int, construction: str):
    from celestia_app_tpu.trace.device_ledger import track

    return track(
        jax.jit(extend_square_fn(k, construction)),
        "extend_square", k=k, construction=construction,
    )


def jit_extend_square(k: int):
    """Cached jitted extension for square size k (one compile per
    (k, active RS construction))."""
    return _jit_extend_square(k, active_construction())


def extend_square(ods: np.ndarray) -> np.ndarray:
    """Host convenience: numpy ODS (k, k, S) -> numpy EDS (2k, 2k, S)."""
    k = ods.shape[0]
    assert ods.shape[1] == k, ods.shape
    return np.asarray(jit_extend_square(k)(jnp.asarray(ods, dtype=jnp.uint8)))


def decode_axis_fn(k: int, construction: str | None = None):
    """Erasure decode along an axis as a constant matmul.

    Returns f(shares, R_bits) where shares is (R, k, S) holding the k known
    shares (already gathered) and R_bits the bit-expanded (2k*m, k*m) recovery
    matrix from RSCodec.recover_matrix - output is the full (R, 2k, S).
    """
    codec = codec_for_width(k, construction)
    m = codec.field.m

    def decode(known: jnp.ndarray, R_bits: jnp.ndarray) -> jnp.ndarray:
        return encode_axis(known, R_bits, m, contract_axis=1)

    from celestia_app_tpu.trace.device_ledger import track

    return track(
        jax.jit(decode),
        "rs_decode_axis", k=k, construction=construction,
    )

"""Reed-Solomon square extension as MXU bit-matmuls.

TPU-first lowering of the rsmt2d encode (reference
pkg/da/data_availability_header.go:74 -> rsmt2d.ComputeExtendedDataSquare):
GF(2^m) arithmetic never reaches the device as table lookups.  Multiplication
by a field constant is GF(2)-linear on the symbol's bit vector, so the whole
systematic generator G (gf/rs.py) bit-expands to a constant 0/1 matrix G_bits
of shape (k*m, k*m), and

    parity_bits = (G_bits @ data_bits) mod 2

is one dense matmul per axis phase - exactly the shape the MXU wants.  The
mod-2 is a final `& 1` on the int32 accumulator (max k*m = 8192 partial
products, far below 2^31).

Data layout: a square is (rows, cols, SHARE_SIZE) uint8.  Bit-planes put the
contraction axis (share-index x bit) first and batch (row x symbol) columns
into one wide matmul.  The column phase extends all 2k columns of the
row-extended top half in a single matmul, yielding Q2 and Q3 at once - valid
because row/col encodes commute (EDS = [[Q0, Q0 G^T], [G Q0, G Q0 G^T]]).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from celestia_app_tpu.gf.rs import codec_for_width

# int8 feeds the MXU's integer path on TPU; float32 is an exact fallback
# (0/1 products, sums <= 8192 << 2^24).
_DOT_DTYPE = jnp.int8


def _bits_from_bytes(shares: jnp.ndarray, m: int) -> jnp.ndarray:
    """(R, n, S) uint8 -> (R, n*m, n_symbols) bit-planes in {0,1}.

    Bit t of a symbol (t in [0,m)) lives at byte t//8 (little-endian within
    the symbol) bit t%8 - matching gf.field.GF.mul_bit_matrix's convention.
    """
    R, n, S = shares.shape
    bps = m // 8  # bytes per symbol
    nsym = S // bps
    x = shares.reshape(R, n, nsym, bps)
    bits = (x[..., None] >> jnp.arange(8, dtype=jnp.uint8)) & jnp.uint8(1)
    bits = bits.reshape(R, n, nsym, m)
    return bits.transpose(0, 1, 3, 2).reshape(R, n * m, nsym)


def _bytes_from_bits(bits: jnp.ndarray, m: int) -> jnp.ndarray:
    """Inverse of _bits_from_bytes: (R, n*m, nsym) -> (R, n, S)."""
    R, nm, nsym = bits.shape
    n = nm // m
    bps = m // 8
    b = bits.reshape(R, n, m, nsym).transpose(0, 1, 3, 2)
    b = b.reshape(R, n, nsym, bps, 8).astype(jnp.uint8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8)).astype(jnp.uint8)
    by = (b * weights).sum(axis=-1, dtype=jnp.uint32).astype(jnp.uint8)
    return by.reshape(R, n, nsym * bps)


def _mod2_matmul(G_bits: jnp.ndarray, bits: jnp.ndarray) -> jnp.ndarray:
    """(P, Q) x (R, Q, nsym) -> (R, P, nsym), all in {0,1}.

    Collapses the (R, nsym) batch into matmul columns so the device sees one
    large dense dot per phase.
    """
    R, Q, nsym = bits.shape
    x = bits.transpose(1, 0, 2).reshape(Q, R * nsym)
    acc = jax.lax.dot_general(
        G_bits.astype(_DOT_DTYPE),
        x.astype(_DOT_DTYPE),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out = (acc & 1).astype(jnp.uint8)
    return out.reshape(-1, R, nsym).transpose(1, 0, 2)


def encode_axis(data: jnp.ndarray, G_bits: jnp.ndarray, m: int) -> jnp.ndarray:
    """Batched systematic encode along axis 1: (R, k, S) -> (R, k, S) parity."""
    return _bytes_from_bits(_mod2_matmul(G_bits, _bits_from_bytes(data, m)), m)


def extend_square_fn(k: int):
    """Returns eds = f(ods) for a fixed square size k.

    ods: (k, k, SHARE_SIZE) uint8 -> eds: (2k, 2k, SHARE_SIZE) uint8 with
    quadrants [[Q0, Q1], [Q2, Q3]] (row-parity right, column-parity below),
    matching rsmt2d's quadrant layout.
    """
    codec = codec_for_width(k)
    m = codec.field.m
    G_bits = jnp.asarray(codec.generator_bits())

    def extend(ods: jnp.ndarray) -> jnp.ndarray:
        # Row phase: each of the k rows is a codeword batch along cols.
        q1 = encode_axis(ods, G_bits, m)  # (k, k, S)
        top = jnp.concatenate([ods, q1], axis=1)  # (k, 2k, S)
        # Column phase: extend all 2k columns of the top half at once.
        cols = top.transpose(1, 0, 2)  # (2k, k, S)
        bottom_cols = encode_axis(cols, G_bits, m)  # (2k, k, S)
        bottom = bottom_cols.transpose(1, 0, 2)  # (k, 2k, S)
        return jnp.concatenate([top, bottom], axis=0)  # (2k, 2k, S)

    return extend


@lru_cache(maxsize=None)
def jit_extend_square(k: int):
    """Cached jitted extension for square size k (one compile per k)."""
    return jax.jit(extend_square_fn(k))


def extend_square(ods: np.ndarray) -> np.ndarray:
    """Host convenience: numpy ODS (k, k, S) -> numpy EDS (2k, 2k, S)."""
    k = ods.shape[0]
    assert ods.shape[1] == k, ods.shape
    return np.asarray(jit_extend_square(k)(jnp.asarray(ods, dtype=jnp.uint8)))


def decode_axis_fn(k: int):
    """Erasure decode along an axis as a constant matmul.

    Returns f(shares, R_bits) where shares is (R, k, S) holding the k known
    shares (already gathered) and R_bits the bit-expanded (2k*m, k*m) recovery
    matrix from RSCodec.recover_matrix - output is the full (R, 2k, S).
    """
    codec = codec_for_width(k)
    m = codec.field.m

    def decode(known: jnp.ndarray, R_bits: jnp.ndarray) -> jnp.ndarray:
        return _bytes_from_bits(_mod2_matmul(R_bits, _bits_from_bytes(known, m)), m)

    return jax.jit(decode)

"""Device twin of merkle/: binary Merkle root for power-of-two leaf counts.

Used for the DAH data root (4k row+col roots, always a power of two since k
is).  For power-of-two n the RFC-6962 split rule halves exactly, so the tree
is a plain level reduction of batched SHA-256 calls.
"""

from __future__ import annotations

import jax.numpy as jnp

from celestia_app_tpu.kernels.sha256 import sha256


def merkle_root_pow2(leaves: jnp.ndarray) -> jnp.ndarray:
    """(N, L) uint8 leaves, N a power of two -> (32,) uint8 root."""
    n = leaves.shape[0]
    assert n & (n - 1) == 0 and n > 0, f"leaf count must be a power of two, got {n}"
    prefix = jnp.zeros((n, 1), dtype=jnp.uint8)
    level = sha256(jnp.concatenate([prefix, leaves], axis=1))  # (N, 32)
    while level.shape[0] > 1:
        m = level.shape[0] // 2
        msgs = jnp.concatenate(
            [jnp.ones((m, 1), dtype=jnp.uint8), level[0::2], level[1::2]], axis=1
        )
        level = sha256(msgs)
    return level[0]

"""Minimal protobuf wire-format helpers (proto3, deterministic encoding).

Just enough of the wire format for the consensus-critical envelopes the
framework must round-trip byte-exactly — BlobTx / Blob / IndexWrapper
(reference proto/celestia/core/v1/blob/blob.proto; spec
specs/src/specs/data_structures.md "IndexWrapper") — without a protobuf
runtime dependency.  Encoding is canonical: fields in ascending field-number
order, packed repeated scalars, no defaults emitted.
"""

from __future__ import annotations

WIRE_VARINT = 0
WIRE_I64 = 1
WIRE_LEN = 2
WIRE_I32 = 5


def encode_uvarint(n: int) -> bytes:
    if n < 0:
        raise ValueError("uvarint must be non-negative")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def read_uvarint(buf: bytes, pos: int) -> tuple[int, int]:
    """Returns (value, new_pos)."""
    shift = 0
    value = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated uvarint")
        b = buf[pos]
        pos += 1
        value |= (b & 0x7F) << shift
        if not b & 0x80:
            return value, pos
        shift += 7
        if shift > 63:
            raise ValueError("uvarint overflow")


def _tag(field_number: int, wire_type: int) -> bytes:
    return encode_uvarint((field_number << 3) | wire_type)


def encode_bytes_field(field_number: int, data: bytes) -> bytes:
    """Length-delimited field (bytes / string / embedded message)."""
    return _tag(field_number, WIRE_LEN) + encode_uvarint(len(data)) + data


def encode_varint_field(field_number: int, value: int) -> bytes:
    """Scalar varint field; proto3 omits zero-valued scalars."""
    if value == 0:
        return b""
    return _tag(field_number, WIRE_VARINT) + encode_uvarint(value)


def encode_packed_uint32_field(field_number: int, values: list[int]) -> bytes:
    """Packed repeated uint32 (proto3 default packing)."""
    if not values:
        return b""
    payload = b"".join(encode_uvarint(v) for v in values)
    return encode_bytes_field(field_number, payload)


def int64_from_uvarint(v: int) -> int:
    """Interpret an unsigned varint as a proto int64 (two's complement):
    values >= 2^63 are negative.  Decoders for int64 fields must apply
    this, or a negative wire value (10-byte varint) silently becomes a
    huge positive and dodges < 0 / <= 0 validation.  NOT for proto
    `sint64` fields — those are zigzag-encoded."""
    return v - (1 << 64) if v >= (1 << 63) else v


def decode_fields(buf: bytes) -> list[tuple[int, int, object]]:
    """Parse a message into [(field_number, wire_type, value)].

    LEN fields yield bytes; varints yield int.  Raises ValueError on any
    malformed input (the caller treats that as "not this message type").
    """
    out: list[tuple[int, int, object]] = []
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = read_uvarint(buf, pos)
        field_number, wire_type = key >> 3, key & 7
        if field_number == 0:
            raise ValueError("field number 0 is invalid")
        if wire_type == WIRE_VARINT:
            value, pos = read_uvarint(buf, pos)
        elif wire_type == WIRE_LEN:
            ln, pos = read_uvarint(buf, pos)
            if pos + ln > n:
                raise ValueError("truncated length-delimited field")
            value = buf[pos : pos + ln]
            pos += ln
        elif wire_type == WIRE_I64:
            if pos + 8 > n:
                raise ValueError("truncated i64 field")
            value = buf[pos : pos + 8]
            pos += 8
        elif wire_type == WIRE_I32:
            if pos + 4 > n:
                raise ValueError("truncated i32 field")
            value = buf[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire_type}")
        out.append((field_number, wire_type, value))
    return out


def decode_packed_uint32(payload: bytes) -> list[int]:
    values = []
    pos = 0
    while pos < len(payload):
        v, pos = read_uvarint(payload, pos)
        values.append(v)
    return values

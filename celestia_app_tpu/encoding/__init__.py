from celestia_app_tpu.encoding.proto import (
    decode_fields,
    decode_packed_uint32,
    encode_bytes_field,
    encode_packed_uint32_field,
    encode_uvarint,
    encode_varint_field,
    read_uvarint,
)

__all__ = [
    "decode_fields",
    "decode_packed_uint32",
    "encode_bytes_field",
    "encode_packed_uint32_field",
    "encode_uvarint",
    "encode_varint_field",
    "read_uvarint",
]

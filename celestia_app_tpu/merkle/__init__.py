"""Plain binary Merkle tree (RFC-6962 style), host side.

Parity with the reference's go-square/merkle (used for the DAH data root,
pkg/da/data_availability_header.go:100-107, and tx share commitments):

    empty root = sha256("")
    leaf       = sha256(0x00 || data)
    inner      = sha256(0x01 || left || right)
    split point = largest power of two strictly less than n

The device twin for power-of-two leaf counts lives in kernels/merkle.py.
"""

from __future__ import annotations

import hashlib

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def leaf_hash(data: bytes) -> bytes:
    return hashlib.sha256(LEAF_PREFIX + data).digest()


def inner_hash(left: bytes, right: bytes) -> bytes:
    return hashlib.sha256(INNER_PREFIX + left + right).digest()


def split_point(n: int) -> int:
    """Largest power of two strictly less than n (n >= 2)."""
    p = 1 << (n - 1).bit_length() - 1
    return p if p < n else p // 2


def hash_from_byte_slices(items: list[bytes]) -> bytes:
    """Merkle root of a list of byte slices."""
    n = len(items)
    if n == 0:
        return hashlib.sha256(b"").digest()
    if n == 1:
        return leaf_hash(items[0])
    k = split_point(n)
    return inner_hash(hash_from_byte_slices(items[:k]), hash_from_byte_slices(items[k:]))


def proof(items: list[bytes], index: int) -> list[bytes]:
    """Audit path (sibling hashes, leaf-to-root) for items[index]."""
    n = len(items)
    if not 0 <= index < n:
        raise IndexError(index)
    if n == 1:
        return []
    k = split_point(n)
    if index < k:
        return proof(items[:k], index) + [hash_from_byte_slices(items[k:])]
    return proof(items[k:], index - k) + [hash_from_byte_slices(items[:k])]


def levels_from_leaves(items: list[bytes]) -> list[list[bytes]]:
    """All tree levels (leaf hashes first, [root] last) for a power-of-two
    leaf count — the memoized twin of `proof`: building this once per
    4k-root set lets a proof-serving cache answer every audit path by
    indexing (`path_from_levels`) instead of re-hashing O(n log n) per
    request.  Power-of-two only: split_point(n) == n/2 exactly then, so
    level indexing and the recursive split agree."""
    n = len(items)
    if n & (n - 1) or n == 0:
        raise ValueError(f"levels_from_leaves needs a power of two, got {n}")
    level = [leaf_hash(i) for i in items]
    levels = [level]
    while len(level) > 1:
        level = [
            inner_hash(level[i], level[i + 1]) for i in range(0, len(level), 2)
        ]
        levels.append(level)
    return levels


def path_from_levels(levels: list[list[bytes]], index: int) -> list[bytes]:
    """Audit path (sibling hashes, leaf-to-root) from precomputed levels —
    byte-identical to `proof(items, index)` for power-of-two item counts
    (pinned by tests/test_das_proofs.py)."""
    n = len(levels[0])
    if not 0 <= index < n:
        raise IndexError(index)
    path = []
    for level in levels[:-1]:
        path.append(level[index ^ 1])
        index //= 2
    return path


def compute_root_from_path(index: int, total: int, leaf_h: bytes, path: list[bytes]) -> bytes:
    """Root implied by a leaf hash and its audit path (leaf-to-root order)."""
    if total <= 0 or not 0 <= index < total:
        raise ValueError(f"bad index {index} / total {total}")
    if total == 1:
        if path:
            raise ValueError("path too long")
        return leaf_h
    if not path:
        raise ValueError("path too short")
    k = split_point(total)
    if index < k:
        left = compute_root_from_path(index, k, leaf_h, path[:-1])
        return inner_hash(left, path[-1])
    right = compute_root_from_path(index - k, total - k, leaf_h, path[:-1])
    return inner_hash(path[-1], right)


def verify_proof(root: bytes, leaf: bytes, index: int, total: int, path: list[bytes]) -> bool:
    """Verify an audit path produced by `proof`."""
    try:
        return compute_root_from_path(index, total, leaf_hash(leaf), path) == root
    except ValueError:
        return False

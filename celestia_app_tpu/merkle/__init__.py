"""Plain binary Merkle tree (RFC-6962 style), host side.

Parity with the reference's go-square/merkle (used for the DAH data root,
pkg/da/data_availability_header.go:100-107, and tx share commitments):

    empty root = sha256("")
    leaf       = sha256(0x00 || data)
    inner      = sha256(0x01 || left || right)
    split point = largest power of two strictly less than n

The device twin for power-of-two leaf counts lives in kernels/merkle.py.
"""

from __future__ import annotations

import hashlib

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def leaf_hash(data: bytes) -> bytes:
    return hashlib.sha256(LEAF_PREFIX + data).digest()


def inner_hash(left: bytes, right: bytes) -> bytes:
    return hashlib.sha256(INNER_PREFIX + left + right).digest()


def split_point(n: int) -> int:
    """Largest power of two strictly less than n (n >= 2)."""
    p = 1 << (n - 1).bit_length() - 1
    return p if p < n else p // 2


def hash_from_byte_slices(items: list[bytes]) -> bytes:
    """Merkle root of a list of byte slices."""
    n = len(items)
    if n == 0:
        return hashlib.sha256(b"").digest()
    if n == 1:
        return leaf_hash(items[0])
    k = split_point(n)
    return inner_hash(hash_from_byte_slices(items[:k]), hash_from_byte_slices(items[k:]))


def proof(items: list[bytes], index: int) -> list[bytes]:
    """Audit path (sibling hashes, leaf-to-root) for items[index]."""
    n = len(items)
    if not 0 <= index < n:
        raise IndexError(index)
    if n == 1:
        return []
    k = split_point(n)
    if index < k:
        return proof(items[:k], index) + [hash_from_byte_slices(items[k:])]
    return proof(items[k:], index - k) + [hash_from_byte_slices(items[:k])]


def compute_root_from_path(index: int, total: int, leaf_h: bytes, path: list[bytes]) -> bytes:
    """Root implied by a leaf hash and its audit path (leaf-to-root order)."""
    if total <= 0 or not 0 <= index < total:
        raise ValueError(f"bad index {index} / total {total}")
    if total == 1:
        if path:
            raise ValueError("path too long")
        return leaf_h
    if not path:
        raise ValueError("path too short")
    k = split_point(total)
    if index < k:
        left = compute_root_from_path(index, k, leaf_h, path[:-1])
        return inner_hash(left, path[-1])
    right = compute_root_from_path(index - k, total - k, leaf_h, path[:-1])
    return inner_hash(path[-1], right)


def verify_proof(root: bytes, leaf: bytes, index: int, total: int, path: list[bytes]) -> bool:
    """Verify an audit path produced by `proof`."""
    try:
        return compute_root_from_path(index, total, leaf_hash(leaf), path) == root
    except ValueError:
        return False

"""Request/block-scoped trace context: one trace_id from RPC submission
to DAH root.

PR 2 made the device pipeline legible; this layer makes everything above
it attributable: a `TraceContext` is issued at request entry (the three
serving planes' BroadcastTx handlers, or locally by `TestNode.broadcast`)
and threaded EXPLICITLY through the layers — mempool entries store the
submitting request's context, the block built from a reap adopts the
first reaped tx's trace_id, and every span below (square build, device
dispatch, consensus round, commit) joins that trace.  The contextvar here
is an in-thread convenience so deep call stacks (square.build inside
App.prepare_proposal) pick up the active context without threading a
parameter through every signature; across threads the context object
itself is passed (mempool entry -> proposer thread), never the
thread-local.

`trace_span` is the measurement primitive: it opens a child context,
makes it current for the body, and on exit exports the span THREE ways —

  * a row in the per-name event table (same shape tracer.span wrote, plus
    trace_id/span_id/parent_span_id columns), keeping the existing
    `celestia_<name>_seconds` histogram families alive;
  * an OTLP-shaped row in the `spans` table (trace/spans.py), pulled via
    GET /trace_tables/spans or mirrored to $CELESTIA_SPANS_OUT JSONL —
    the whole-block lifecycle tree reconstructs from this one table;
  * optionally one observation on the end-to-end phase histogram
    `celestia_e2e_seconds{phase=...}` (the `e2e=` argument).

$CELESTIA_TRACE=off mutes every export; context PROPAGATION still runs so
explicit threading (mempool-entry contexts, block adoption) never breaks
when tracing is muted.  No device syncs anywhere: spans time host calls
the layers already make.

Cross-NODE propagation (the fleet era): `serialize_context` renders the
active identity as the `x-celestia-trace` header value
(`<32-hex trace_id>-<16-hex span_id>`), and `adopt_context` /
`adopt_or_new` rebuild it on the receiving process — SAME trace_id, fresh
span_id, the sender's span as parent — so a request crossing the wire
stays one trace.  Every root/adopted context stamps a `node_id` baggage
entry (a stable per-process identity, `$CELESTIA_NODE_ID` override) so
merged spans tables attribute each row to its emitting process.
"""

from __future__ import annotations

import os
import re
import socket
import time
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass, field

#: The one header name every inter-node hop uses (HTTP header, gRPC
#: metadata key, and the gossip envelope's "trace" field all carry the
#: same serialized value).
TRACE_HEADER = "x-celestia-trace"

_NODE_ID: str | None = None
_HEADER_RE = re.compile(r"^([0-9a-f]{32})-([0-9a-f]{16})$")


def node_id() -> str:
    """Stable per-process node identity: `$CELESTIA_NODE_ID` when set,
    else `<hostname>-<pid>` — computed once so every span, flight bundle,
    and fleet row a process emits carries the same value.  Sanitized to
    `[A-Za-z0-9._-]` (it lands in filenames and header values)."""
    global _NODE_ID
    if _NODE_ID is None:
        raw = os.environ.get("CELESTIA_NODE_ID") or (
            f"{socket.gethostname()}-{os.getpid()}"
        )
        _NODE_ID = re.sub(r"[^A-Za-z0-9._-]", "_", raw) or "node"
    return _NODE_ID


def _reset_node_id_for_tests() -> None:
    global _NODE_ID
    _NODE_ID = None


@dataclass(frozen=True)
class TraceContext:
    """Identity + baggage of one request or block trace.

    `trace_id` is stable for the whole tree; each span gets its own
    `span_id` with `parent_id` linking it to its creator.  `baggage`
    carries low-volume attribution (height, round, k, source) copied onto
    every descendant span's attributes.  `start_unix_ns` is the wall
    clock at trace issue — the anchor the e2e `total` phase measures
    from.
    """

    trace_id: str
    span_id: str
    parent_id: str | None = None
    baggage: dict = field(default_factory=dict)
    start_unix_ns: int = 0

    def child(self, **baggage) -> "TraceContext":
        """A child context: same trace, fresh span id, merged baggage."""
        return TraceContext(
            trace_id=self.trace_id,
            span_id=_new_span_id(),
            parent_id=self.span_id,
            baggage={**self.baggage, **baggage},
            start_unix_ns=self.start_unix_ns,
        )


def _new_span_id() -> str:
    return os.urandom(8).hex()


def new_context(**baggage) -> TraceContext:
    """Issue a fresh root context (a new trace_id) — request entry.  The
    issuing process's `node_id` rides the baggage (explicit baggage wins,
    so a per-server identity can override the process default)."""
    return TraceContext(
        trace_id=os.urandom(16).hex(),
        span_id=_new_span_id(),
        baggage={"node_id": node_id(), **baggage},
        start_unix_ns=time.time_ns(),
    )


def serialize_context(ctx: TraceContext | None = None) -> str | None:
    """The wire form of `ctx` (default: the current context) for the
    `x-celestia-trace` header / gRPC metadata / gossip `trace` field:
    `<trace_id>-<span_id>`, or None outside a trace (the hop then carries
    no header and the receiver mints its own root)."""
    ctx = ctx if ctx is not None else current_context()
    if ctx is None:
        return None
    return f"{ctx.trace_id}-{ctx.span_id}"


def adopt_context(header: str | None, **baggage) -> TraceContext | None:
    """Rebuild an incoming wire context: SAME trace_id, fresh span_id,
    the sender's span as parent — the receiving process JOINS the trace
    instead of re-minting it, which is what stitches a multi-node drill
    under one trace_id.  Returns None on an absent or malformed header
    (a bad header must never fail the request — the caller falls back to
    `new_context`).  This process's `node_id` is stamped into baggage
    (explicit baggage wins, for per-server identities in one process)."""
    if not header:
        return None
    m = _HEADER_RE.match(header.strip().lower())
    if m is None:
        return None
    trace_id, parent_span = m.group(1), m.group(2)
    return TraceContext(
        trace_id=trace_id,
        span_id=_new_span_id(),
        parent_id=parent_span,
        baggage={"node_id": node_id(), **baggage},
        start_unix_ns=time.time_ns(),
    )


def adopt_or_new(header: str | None, **baggage) -> TraceContext:
    """Request entry on a serving plane: adopt the peer's context when the
    hop carried one, else issue a fresh root — the ONE pattern every rpc/
    ingress threads (trace_lint rule 7 pins this)."""
    return adopt_context(header, **baggage) or new_context(**baggage)


_CURRENT: ContextVar[TraceContext | None] = ContextVar(
    "celestia_trace_context", default=None
)


def current_context() -> TraceContext | None:
    """The context active on THIS thread/task, or None outside a trace."""
    return _CURRENT.get()


@contextmanager
def use_context(ctx: TraceContext | None):
    """Make `ctx` current for the body — the explicit hand-off point when
    a context crosses a thread boundary (block production adopting a
    mempool entry's context)."""
    token = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        _CURRENT.reset(token)


@contextmanager
def trace_span(
    name: str,
    ctx: TraceContext | None = None,
    e2e: str | None = None,
    buckets: tuple[float, ...] | None = None,
    **attrs,
):
    """Measure one span of trace `ctx` (explicit, else the current one,
    else a fresh root).  Yields a mutable attr dict so results discovered
    inside the body (square size, vote power) land on the span.  `e2e`
    names the celestia_e2e_seconds phase this span feeds, if any.
    """
    from celestia_app_tpu.trace.tracer import trace_enabled

    parent = ctx if ctx is not None else current_context()
    child = parent.child() if parent is not None else new_context()
    token = _CURRENT.set(child)
    if not trace_enabled():
        try:
            yield dict(attrs)
        finally:
            _CURRENT.reset(token)
        return
    mutable = dict(attrs)
    start_unix_ns = time.time_ns()
    t0 = time.perf_counter_ns()
    try:
        yield mutable
    finally:
        elapsed_ns = time.perf_counter_ns() - t0
        _CURRENT.reset(token)
        export_span(name, child, start_unix_ns, elapsed_ns, mutable,
                    buckets=buckets, e2e=e2e)


def export_span(name, ctx, start_unix_ns, elapsed_ns, attrs,
                buckets=None, e2e=None) -> None:
    """The span's three exports (event table + histogram + OTLP row) plus
    the optional e2e phase — all off the timed region.  Public for call
    sites that must pick the span's context AFTER the measured work (the
    mempool reap learns which trace it belongs to by doing the reap)."""
    from celestia_app_tpu.trace import spans
    from celestia_app_tpu.trace.metrics import registry
    from celestia_app_tpu.trace.tracer import SPAN_LABEL_ATTRS, traced

    traced().write(
        name,
        duration_ms=elapsed_ns / 1e6,
        trace_id=ctx.trace_id,
        span_id=ctx.span_id,
        parent_span_id=ctx.parent_id,
        **attrs,
    )
    labels = {a: str(attrs[a]) for a in SPAN_LABEL_ATTRS if a in attrs}
    registry().histogram(
        f"celestia_{name}_seconds", f"wall time of {name}",
        **({"buckets": buckets} if buckets else {}),
    ).observe(elapsed_ns / 1e9, **labels)
    spans.record_span(
        name, ctx, start_unix_ns, start_unix_ns + elapsed_ns,
        {**ctx.baggage, **attrs},
    )
    if e2e is not None:
        spans.observe_e2e(
            e2e, elapsed_ns / 1e9, namespace=ctx.baggage.get("namespace")
        )

"""Request/block-scoped trace context: one trace_id from RPC submission
to DAH root.

PR 2 made the device pipeline legible; this layer makes everything above
it attributable: a `TraceContext` is issued at request entry (the three
serving planes' BroadcastTx handlers, or locally by `TestNode.broadcast`)
and threaded EXPLICITLY through the layers — mempool entries store the
submitting request's context, the block built from a reap adopts the
first reaped tx's trace_id, and every span below (square build, device
dispatch, consensus round, commit) joins that trace.  The contextvar here
is an in-thread convenience so deep call stacks (square.build inside
App.prepare_proposal) pick up the active context without threading a
parameter through every signature; across threads the context object
itself is passed (mempool entry -> proposer thread), never the
thread-local.

`trace_span` is the measurement primitive: it opens a child context,
makes it current for the body, and on exit exports the span THREE ways —

  * a row in the per-name event table (same shape tracer.span wrote, plus
    trace_id/span_id/parent_span_id columns), keeping the existing
    `celestia_<name>_seconds` histogram families alive;
  * an OTLP-shaped row in the `spans` table (trace/spans.py), pulled via
    GET /trace_tables/spans or mirrored to $CELESTIA_SPANS_OUT JSONL —
    the whole-block lifecycle tree reconstructs from this one table;
  * optionally one observation on the end-to-end phase histogram
    `celestia_e2e_seconds{phase=...}` (the `e2e=` argument).

$CELESTIA_TRACE=off mutes every export; context PROPAGATION still runs so
explicit threading (mempool-entry contexts, block adoption) never breaks
when tracing is muted.  No device syncs anywhere: spans time host calls
the layers already make.
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass, field


@dataclass(frozen=True)
class TraceContext:
    """Identity + baggage of one request or block trace.

    `trace_id` is stable for the whole tree; each span gets its own
    `span_id` with `parent_id` linking it to its creator.  `baggage`
    carries low-volume attribution (height, round, k, source) copied onto
    every descendant span's attributes.  `start_unix_ns` is the wall
    clock at trace issue — the anchor the e2e `total` phase measures
    from.
    """

    trace_id: str
    span_id: str
    parent_id: str | None = None
    baggage: dict = field(default_factory=dict)
    start_unix_ns: int = 0

    def child(self, **baggage) -> "TraceContext":
        """A child context: same trace, fresh span id, merged baggage."""
        return TraceContext(
            trace_id=self.trace_id,
            span_id=_new_span_id(),
            parent_id=self.span_id,
            baggage={**self.baggage, **baggage},
            start_unix_ns=self.start_unix_ns,
        )


def _new_span_id() -> str:
    return os.urandom(8).hex()


def new_context(**baggage) -> TraceContext:
    """Issue a fresh root context (a new trace_id) — request entry."""
    return TraceContext(
        trace_id=os.urandom(16).hex(),
        span_id=_new_span_id(),
        baggage=baggage,
        start_unix_ns=time.time_ns(),
    )


_CURRENT: ContextVar[TraceContext | None] = ContextVar(
    "celestia_trace_context", default=None
)


def current_context() -> TraceContext | None:
    """The context active on THIS thread/task, or None outside a trace."""
    return _CURRENT.get()


@contextmanager
def use_context(ctx: TraceContext | None):
    """Make `ctx` current for the body — the explicit hand-off point when
    a context crosses a thread boundary (block production adopting a
    mempool entry's context)."""
    token = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        _CURRENT.reset(token)


@contextmanager
def trace_span(
    name: str,
    ctx: TraceContext | None = None,
    e2e: str | None = None,
    buckets: tuple[float, ...] | None = None,
    **attrs,
):
    """Measure one span of trace `ctx` (explicit, else the current one,
    else a fresh root).  Yields a mutable attr dict so results discovered
    inside the body (square size, vote power) land on the span.  `e2e`
    names the celestia_e2e_seconds phase this span feeds, if any.
    """
    from celestia_app_tpu.trace.tracer import trace_enabled

    parent = ctx if ctx is not None else current_context()
    child = parent.child() if parent is not None else new_context()
    token = _CURRENT.set(child)
    if not trace_enabled():
        try:
            yield dict(attrs)
        finally:
            _CURRENT.reset(token)
        return
    mutable = dict(attrs)
    start_unix_ns = time.time_ns()
    t0 = time.perf_counter_ns()
    try:
        yield mutable
    finally:
        elapsed_ns = time.perf_counter_ns() - t0
        _CURRENT.reset(token)
        export_span(name, child, start_unix_ns, elapsed_ns, mutable,
                    buckets=buckets, e2e=e2e)


def export_span(name, ctx, start_unix_ns, elapsed_ns, attrs,
                buckets=None, e2e=None) -> None:
    """The span's three exports (event table + histogram + OTLP row) plus
    the optional e2e phase — all off the timed region.  Public for call
    sites that must pick the span's context AFTER the measured work (the
    mempool reap learns which trace it belongs to by doing the reap)."""
    from celestia_app_tpu.trace import spans
    from celestia_app_tpu.trace.metrics import registry
    from celestia_app_tpu.trace.tracer import SPAN_LABEL_ATTRS, traced

    traced().write(
        name,
        duration_ms=elapsed_ns / 1e6,
        trace_id=ctx.trace_id,
        span_id=ctx.span_id,
        parent_span_id=ctx.parent_id,
        **attrs,
    )
    labels = {a: str(attrs[a]) for a in SPAN_LABEL_ATTRS if a in attrs}
    registry().histogram(
        f"celestia_{name}_seconds", f"wall time of {name}",
        **({"buckets": buckets} if buckets else {}),
    ).observe(elapsed_ns / 1e9, **labels)
    spans.record_span(
        name, ctx, start_unix_ns, start_unix_ns + elapsed_ns,
        {**ctx.baggage, **attrs},
    )
    if e2e is not None:
        spans.observe_e2e(
            e2e, elapsed_ns / 1e9, namespace=ctx.baggage.get("namespace")
        )

"""Block journal: one row per block through the device pipeline.

The observability spine of the PR-1 device pipeline: every block that
crosses `da/eds` (fused or staged), `parallel/pipeline.BlockPipeline`
(stream mode), or `parallel/sharded_eds` (multi-chip) records one
`block_journal` row — square size, pipeline mode, jit-cache hit/miss,
the stage timings its path measured (upload ms, dispatch ms, queue-stall
ms, drain latency), and the continuous-batching facts: `batch_size`
(squares coalesced into the row's dispatch; 1 = unbatched) on stream
rows, the `speculation` outcome (hit / discard) on compute rows when
$CELESTIA_PIPE_SPECULATE is armed, and `panels` (row panels the square
streamed through) on panel-mode rows ($CELESTIA_PIPE_PANEL) — read next
to the per-dispatch `celestia_hbm_peak_bytes{point,k,source}` refresh
below, the pair is the giant-square memory story per dispatch.  The batch-size distribution itself
lands on `celestia_pipeline_batch_size` (observed once per dispatch by
the pipeline, not once per row — a 4-square batch is ONE dispatch).  Rows are written from whichever thread ran the stage
(the uploader/dispatcher threads in stream mode) into the thread-safe
tracer tables and pulled node-side via GET /trace_tables — the
test/e2e/testnet/node.go:52-74 analog.

The same funnel feeds the Prometheus side: every `*_ms` timing lands on a
`celestia_block_<stage>_seconds` histogram with sub-millisecond buckets
(metrics.DEVICE_SECONDS_BUCKETS) and {source, k} labels, and each row
ticks the per-dispatch HBM high-water gauge plus the env-gated N-block
jax.profiler window (trace/profiler.py).

Nothing here syncs the device: all timings are host perf_counter deltas
around calls the pipeline already makes, and the HBM gauge reads allocator
stats only (None on CPU).
"""

from __future__ import annotations

TABLE = "block_journal"


def note_jit_build(program: str) -> None:
    """Count a jit program-cache build (the compile-counter the /metrics
    planes expose as celestia_jit_builds_total{program=...}).  Called from
    the lru_cache-missed builder bodies, so hits cost nothing."""
    from celestia_app_tpu.trace.metrics import registry

    registry().counter(
        "celestia_jit_builds_total",
        "jit pipeline wrapper builds (a miss traces + compiles on first dispatch)",
    ).inc(program=program)


def record(source: str, k: int, *, mode: str | None = None,
           compile: str | None = None, **fields) -> None:
    """Write one block-journal row + its Prometheus reflections.

    `source` names the path (compute | stream | sharded | warmup);
    `compile` is "hit"/"miss" against the jit wrapper cache.  Extra
    `fields` ending in `_ms` are stage timings: each is observed on
    `celestia_block_<stage>_seconds` with device-scale buckets; other
    fields (tags, depth, device counts) land only in the table row.
    """
    from celestia_app_tpu.trace.metrics import DEVICE_SECONDS_BUCKETS, registry
    from celestia_app_tpu.trace.tracer import traced

    # The profiler window and HBM gauge carry their OWN gates
    # ($CELESTIA_PROFILE_BLOCKS; stats availability) and must keep firing
    # when $CELESTIA_TRACE=off mutes the table/metric layer — profiling
    # with tracing muted is exactly the low-overhead measurement combo.
    from celestia_app_tpu.trace import profiler

    profiler.block_profiler().note_block()
    profiler.record_hbm_high_water(point=source, k=k)

    # The SLO engine ticks on the block funnel (rate-limited to
    # $CELESTIA_SLO_TICK_S): every block through the device pipeline is
    # a chance to notice the budget burning WITHOUT an external poller.
    # Outside the $CELESTIA_TRACE gate, like the profiler hooks — the
    # degraded/occupancy gauges it judges keep updating when tracing is
    # muted, so judgment must too.
    from celestia_app_tpu.trace.slo import engine

    engine().maybe_tick()

    tracer = traced()
    if not tracer._on():
        return
    # A dispatch running under a request/block trace stamps its row with
    # the trace_id — tying the device journal to the RPC-to-DAH span
    # tree — and the height riding the context baggage (the block trace
    # child minted in mempool reap), so the height timeline
    # (trace/timeline.py) can stitch the row without a join table.
    if "trace_id" not in fields or "height" not in fields:
        from celestia_app_tpu.trace.context import current_context

        ctx = current_context()
        if ctx is not None:
            fields.setdefault("trace_id", ctx.trace_id)
            height = ctx.baggage.get("height")
            if height is not None:
                fields.setdefault("height", height)
    tracer.write(TABLE, source=source, k=k, mode=mode, compile=compile,
                 **fields)
    reg = registry()
    if compile is not None:
        reg.counter(
            "celestia_pipeline_compile_total",
            "block dispatches by jit wrapper cache outcome",
        ).inc(result=compile, source=source)
    for name, value in fields.items():
        if not name.endswith("_ms") or value is None:
            continue
        reg.histogram(
            f"celestia_block_{name[:-3]}_seconds",
            f"per-block {name[:-3].replace('_', ' ')} time",
            buckets=DEVICE_SECONDS_BUCKETS,
        ).observe(value / 1e3, source=source, k=str(k))

from celestia_app_tpu.trace.context import (
    TraceContext,
    current_context,
    new_context,
    trace_span,
    use_context,
)
from celestia_app_tpu.trace.tracer import Tracer, trace_enabled, traced

__all__ = [
    "TraceContext",
    "Tracer",
    "current_context",
    "new_context",
    "trace_enabled",
    "trace_span",
    "traced",
    "use_context",
]

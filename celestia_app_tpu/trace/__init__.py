from celestia_app_tpu.trace.tracer import Tracer, trace_enabled, traced

__all__ = ["Tracer", "trace_enabled", "traced"]

from celestia_app_tpu.trace.tracer import Tracer, traced

__all__ = ["Tracer", "traced"]

"""Declarative SLOs with multi-window burn-rate evaluation — the layer
that makes the telemetry plane judge itself.

PRs 2-4 built a passive telemetry plane (`celestia_e2e_seconds{phase}`,
block/square journals, per-tenant accounting); nothing in-process
evaluated it — an operator had to eyeball /metrics to notice a burning
p99.  This module closes that loop: a small set of declarative `SLOSpec`s
(histogram-quantile objectives and gauge predicates) is evaluated over
rolling windows built from in-process histogram snapshots
(metrics.HistogramSnapshot delta-diffing, never cumulative counts), in
the multi-window burn-rate shape of SRE alerting: the FAST window catches
pages (sustained BAD OBSERVATIONS show up within a tick or two), the SLOW
window catches slow burns that would quietly eat the error budget.

Scope boundary: burn rates judge observations that HAPPENED.  A pipeline
that stalls outright produces no observations and no ticks — that
liveness failure is /healthz's job (the "layers" staleness report: last
block age, mempool depth), not this engine's; an empty window reads as
burn 0, deliberately, so an idle-but-healthy node never pages.

Burn rate is budget-normalized: `bad_fraction / error_budget`, so 1.0
means "exactly consuming budget", and the page threshold (default 14.4,
the classic 1h/30d number) is meaningful across SLOs with different
objectives.  Gauge predicates burn on the fraction of evaluation ticks
the predicate was violated inside the window — a tripped breaker
(`celestia_degraded` != 0) burns at 1/budget immediately.

Surfaces:

    celestia_slo_burn_rate{slo,window}    gauge, refreshed per tick
    celestia_slo_violations_total{slo}    counter, ticked on the ok ->
                                          burning transition (a page)
    GET /slo                              the full evaluation payload on
                                          the shared exposition handler
                                          (byte-identical across planes)
    /healthz "slo" block                  BURNING vs OK in one probe,
                                          next to DEGRADED

A page transition also writes an `slo_page` trace row and fires the
flight recorder (trigger `slo_fast_burn`), so the forensic state around
the moment of anomaly is captured before the ring buffers evict it.

Ticking: `maybe_tick()` is called from the block-journal funnel
(trace/journal.record — every block through the device pipeline) and
from GET /slo; it re-evaluates at most every $CELESTIA_SLO_TICK_S
(default 1.0s), so the hot path pays one clock read + compare when not
due.  Windows come from $CELESTIA_SLO_FAST_S / $CELESTIA_SLO_SLOW_S
(default 60s / 600s).  Everything is injectable (clock, specs) for
deterministic tests.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

#: Page when the FAST window burns this many times faster than budget
#: (the SRE 1h-window page threshold; a gauge predicate fully violated
#: burns at 1/budget = 100x, so pages fire on the first bad tick).
DEFAULT_FAST_BURN = 14.4
#: Ticket-severity threshold on the SLOW window (slow burns).
DEFAULT_SLOW_BURN = 6.0


def _env_float(name: str, default: float) -> float:
    try:
        v = float(os.environ.get(name, "") or default)
    except ValueError:
        return default
    return v if v >= 0 else default


def fast_window_s() -> float:
    """$CELESTIA_SLO_FAST_S: the paging window (default 60s)."""
    return _env_float("CELESTIA_SLO_FAST_S", 60.0)


def slow_window_s() -> float:
    """$CELESTIA_SLO_SLOW_S: the slow-burn window (default 600s)."""
    return _env_float("CELESTIA_SLO_SLOW_S", 600.0)


def tick_interval_s() -> float:
    """$CELESTIA_SLO_TICK_S: minimum seconds between evaluations (0 =
    evaluate on every maybe_tick, the drill/test setting)."""
    return _env_float("CELESTIA_SLO_TICK_S", 1.0)


@dataclass(frozen=True)
class SLOSpec:
    """One declarative objective.

    kind="quantile": `metric` names a histogram family; the objective is
    "the `quantile` of observations (matching `labels`) stays <=
    `threshold`" and the error budget is `1 - quantile` unless `budget`
    overrides it (bad events = observations over the threshold).

    kind="gauge": `metric` names a gauge; the objective is "every child
    sample (matching `labels`) satisfies `value <op> threshold`"; the
    budget is the tolerated fraction of violated evaluation ticks.
    """

    name: str
    metric: str
    kind: str = "quantile"  # "quantile" | "gauge"
    labels: tuple[tuple[str, str], ...] = ()
    quantile: float = 0.99
    threshold: float = 1.0
    op: str = "<="  # gauge predicate operator: <= >= == < >
    budget: float | None = None
    fast_burn: float = DEFAULT_FAST_BURN
    slow_burn: float = DEFAULT_SLOW_BURN

    def effective_budget(self) -> float:
        if self.budget is not None:
            return max(self.budget, 1e-9)
        if self.kind == "quantile":
            return max(1.0 - self.quantile, 1e-9)
        return 0.01

    def objective_text(self) -> str:
        sel = ",".join(f'{k}="{v}"' for k, v in self.labels)
        target = f"{self.metric}{{{sel}}}" if sel else self.metric
        if self.kind == "quantile":
            return f"p{self.quantile * 100:g} of {target} <= {self.threshold:g}"
        return f"{target} {self.op} {self.threshold:g}"


_OPS = {
    "<=": lambda v, t: v <= t,
    ">=": lambda v, t: v >= t,
    "==": lambda v, t: v == t,
    "<": lambda v, t: v < t,
    ">": lambda v, t: v > t,
}


def default_slos() -> tuple[SLOSpec, ...]:
    """The shipped objectives: the e2e lifecycle p99s the ROADMAP calls
    the SLO family, the square-occupancy floor (a proposer quietly
    shipping near-empty squares is an incident, not idle traffic), and
    degraded==0 (a tripped breaker IS budget burn, even though the node
    keeps serving bit-identical roots)."""
    return (
        SLOSpec(
            name="e2e_total_p99", metric="celestia_e2e_seconds",
            labels=(("phase", "total"),), quantile=0.99, threshold=5.0,
        ),
        SLOSpec(
            name="dispatch_p99", metric="celestia_e2e_seconds",
            labels=(("phase", "dispatch"),), quantile=0.99, threshold=1.0,
        ),
        SLOSpec(
            name="mempool_wait_p99", metric="celestia_e2e_seconds",
            labels=(("phase", "mempool_wait"),), quantile=0.99, threshold=2.5,
        ),
        SLOSpec(
            name="square_occupancy",
            metric="celestia_square_last_occupancy_ratio",
            kind="gauge", op=">=", threshold=0.05, budget=0.1,
        ),
        # The read side: a DAS sample must come back fast at p99 — light
        # clients time out and resample, so a slow proof plane IS an
        # availability incident even while blocks commit on schedule.
        # Judged per served sample (serve/sampler's {phase="total"}
        # child); a node serving no proofs observes nothing and burns 0.
        SLOSpec(
            name="proof_p99", metric="celestia_proof_latency_seconds",
            labels=(("phase", "total"),), quantile=0.99, threshold=0.5,
        ),
        SLOSpec(
            name="degraded", metric="celestia_degraded",
            kind="gauge", op="==", threshold=0.0, budget=0.01,
        ),
    )


class SLOEngine:
    """Rolling-window evaluator over the in-process registry.

    Keeps a ring of timestamped histogram snapshots (one per family any
    quantile spec references) and a per-gauge-SLO ring of predicate
    verdicts; each tick() diffs the newest snapshot against the one just
    outside each window, turns bad-event fractions into budget-normalized
    burn rates, publishes the burn gauges, and detects page transitions.
    """

    def __init__(self, specs: tuple[SLOSpec, ...] | None = None,
                 clock=time.monotonic, wall=time.time):
        self.specs = tuple(specs) if specs is not None else default_slos()
        self._clock = clock
        self._wall = wall
        self._lock = threading.Lock()
        # (monotonic t, {family: HistogramSnapshot}) ring, oldest first.
        self._snaps: deque = deque()
        # gauge SLO name -> deque[(monotonic t, violated 0/1)]
        self._gauge_ticks: dict[str, deque] = {
            s.name: deque() for s in self.specs if s.kind == "gauge"
        }
        # slo name -> last evaluation dict (the /slo payload rows).
        self._results: dict[str, dict] = {}
        self._last_tick: float | None = None
        self._last_wall_ms: int | None = None
        self._paging: set[str] = set()  # SLOs currently in a burning state

    # -- evaluation ---------------------------------------------------------

    def maybe_tick(self) -> bool:
        """tick() if the rate limit allows; the hot-path entry (one clock
        read + compare when not due).  Returns whether a tick ran."""
        interval = tick_interval_s()
        now = self._clock()
        with self._lock:
            due = self._last_tick is None or now - self._last_tick >= interval
        if not due:
            return False
        self.tick()
        return True

    def tick(self) -> dict:
        """One full evaluation; returns {slo: result} (also retained for
        payload()).  Never raises into a caller: evaluation failures for
        one SLO mark that SLO errored and the rest proceed."""
        from celestia_app_tpu.trace.metrics import registry

        now = self._clock()
        fast_s, slow_s = fast_window_s(), slow_window_s()
        families = sorted({
            s.metric for s in self.specs if s.kind == "quantile"
        })
        snaps = {}
        for fam in families:
            hist = registry().get(fam)
            if hist is not None and hasattr(hist, "snapshot"):
                snaps[fam] = hist.snapshot()
        pages: list[dict] = []
        with self._lock:
            self._snaps.append((now, snaps))
            # Retain one snapshot older than the slow window so the
            # window diff always has a baseline to subtract.
            while len(self._snaps) > 2 and self._snaps[1][0] <= now - slow_s:
                self._snaps.popleft()
            for spec in self.specs:
                try:
                    result = self._evaluate_locked(spec, now, fast_s, slow_s)
                except Exception as e:
                    result = {"state": "error",
                              "error": f"{type(e).__name__}: {e}"}
                result["objective"] = spec.objective_text()
                prev_burning = spec.name in self._paging
                burning = result.get("state") in ("fast_burn", "slow_burn")
                if burning:
                    self._paging.add(spec.name)
                elif result.get("state") == "ok":
                    self._paging.discard(spec.name)
                if burning and not prev_burning:
                    pages.append({"slo": spec.name, **result})
                self._results[spec.name] = result
            self._last_tick = now
            self._last_wall_ms = int(self._wall() * 1000)
            results = dict(self._results)
        self._publish(results)
        for page in pages:
            self._page(page)
        return results

    def _window_snapshot(self, fam: str, now: float, window_s: float):
        """The delta snapshot covering [now - window_s, now], or None
        when the family has no snapshots yet.  Baseline: the newest
        snapshot at least `window_s` old, else the oldest retained one —
        so a fresh engine's first tick diffs against itself (zero delta)
        instead of counting the process's whole cumulative history as
        one window."""
        newest = self._snaps[-1][1].get(fam)
        if newest is None:
            return None
        baseline_snaps = self._snaps[0][1]
        for t, snaps in self._snaps:
            if t <= now - window_s:
                baseline_snaps = snaps
            else:
                break
        base = baseline_snaps.get(fam)
        if base is None:
            # The family first appeared after the baseline was taken:
            # everything it holds landed inside the window.
            return newest
        return newest.delta(base)

    def _evaluate_locked(self, spec: SLOSpec, now: float,
                         fast_s: float, slow_s: float) -> dict:
        budget = spec.effective_budget()
        if spec.kind == "quantile":
            labels = dict(spec.labels)
            out: dict = {"kind": "quantile", "threshold": spec.threshold,
                         "quantile": spec.quantile, "budget": budget}
            burns = {}
            for window, span in (("fast", fast_s), ("slow", slow_s)):
                delta = self._window_snapshot(spec.metric, now, span)
                if delta is None:
                    burns[window] = 0.0
                    continue
                frac = delta.fraction_over(spec.threshold, **labels)
                burns[window] = 0.0 if frac is None else frac / budget
                if window == "fast":
                    out["window_count"] = delta.count(**labels)
                    q = delta.quantile(spec.quantile, **labels)
                    if q is not None:
                        out["current"] = round(q, 9)
            out["burn"] = {w: round(b, 6) for w, b in burns.items()}
        else:
            from celestia_app_tpu.trace.metrics import registry

            gauge = registry().get(spec.metric)
            want = dict(spec.labels)
            violated = 0
            worst = None
            if gauge is not None and hasattr(gauge, "samples"):
                op = _OPS[spec.op]
                for labels, value in gauge.samples():
                    if all(labels.get(k) == v for k, v in want.items()):
                        if not op(value, spec.threshold):
                            violated = 1
                            worst = value
            ticks = self._gauge_ticks[spec.name]
            ticks.append((now, violated))
            while ticks and ticks[0][0] < now - slow_s:
                ticks.popleft()
            burns = {}
            for window, span in (("fast", fast_s), ("slow", slow_s)):
                inside = [v for t, v in ticks if t >= now - span]
                frac = sum(inside) / len(inside) if inside else 0.0
                burns[window] = frac / budget
            out = {"kind": "gauge", "threshold": spec.threshold,
                   "op": spec.op, "budget": budget, "violated_now": violated,
                   "burn": {w: round(b, 6) for w, b in burns.items()}}
            if worst is not None:
                out["current"] = worst
        if out["burn"]["fast"] >= spec.fast_burn:
            out["state"] = "fast_burn"
        elif out["burn"]["slow"] >= spec.slow_burn:
            out["state"] = "slow_burn"
        else:
            out["state"] = "ok"
        return out

    # -- side effects -------------------------------------------------------

    def _publish(self, results: dict) -> None:
        from celestia_app_tpu.trace.metrics import registry

        burn = registry().gauge(
            "celestia_slo_burn_rate",
            "budget-normalized SLO burn rate per evaluation window "
            "(1.0 = consuming budget exactly; pages fire on the fast window)",
        )
        for name, result in results.items():
            for window, value in result.get("burn", {}).items():
                burn.set(value, slo=name, window=window)

    def _page(self, page: dict) -> None:
        """The ok -> burning transition: violation counter, trace row,
        flight-recorder capture.  Must never raise into tick()'s caller
        (the block journal funnel)."""
        from celestia_app_tpu.trace.metrics import registry
        from celestia_app_tpu.trace.tracer import traced

        registry().counter(
            "celestia_slo_violations_total",
            "SLO page transitions (entering a fast/slow burning state)",
        ).inc(slo=page["slo"])
        traced().write(
            "slo_page", slo=page["slo"], state=page.get("state"),
            burn_fast=page.get("burn", {}).get("fast"),
            burn_slow=page.get("burn", {}).get("slow"),
            objective=page.get("objective"),
        )
        if page.get("state") == "fast_burn":
            from celestia_app_tpu.trace.flight_recorder import note_trigger

            note_trigger(
                "slo_fast_burn", slo=page["slo"],
                burn_fast=page.get("burn", {}).get("fast"),
                burn_slow=page.get("burn", {}).get("slow"),
                objective=page.get("objective"),
            )

    # -- read side ----------------------------------------------------------

    def payload(self) -> dict:
        """The GET /slo JSON: a pure function of the last tick's retained
        state, so concurrent scrapes on different planes see identical
        bytes until the next evaluation."""
        with self._lock:
            slos = {name: dict(r) for name, r in sorted(self._results.items())}
            evaluated_ms = self._last_wall_ms
        return {
            "windows": {"fast_s": fast_window_s(), "slow_s": slow_window_s()},
            "evaluated_unix_ms": evaluated_ms,
            "slos": slos,
        }

    def health_block(self) -> dict:
        """The /healthz "slo" face: BURNING when any SLO is in a burning
        state, with the offenders listed — so DEGRADED-vs-BURNING is one
        probe.  Read-only: the probe never forces an evaluation."""
        with self._lock:
            burning = sorted(
                name for name, r in self._results.items()
                if r.get("state") in ("fast_burn", "slow_burn")
            )
        return {"status": "BURNING" if burning else "OK", "burning": burning}

    def paged(self, name: str) -> bool:
        """Whether `name` is currently in a burning state (the chaos
        drill's detection probe)."""
        with self._lock:
            return name in self._paging


_ENGINE = SLOEngine()
_ENGINE_LOCK = threading.Lock()
#: Per-tenant SLOSpecs installed by the QoS layer (qos.py
#: `<tenant>.slo_p99_ms`): evaluated NEXT TO the shipped defaults.
_TENANT_SPECS: tuple[SLOSpec, ...] = ()


def engine() -> SLOEngine:
    return _ENGINE


def tenant_specs() -> tuple[SLOSpec, ...]:
    return _TENANT_SPECS


def set_tenant_specs(specs: tuple[SLOSpec, ...]) -> SLOEngine:
    """Swap the per-tenant SLO tier (the observe -> enforce wire from
    qos.py): rebuilds the engine over default_slos() + the tenant specs.
    Config changes drop the rolling windows — a tenant objective
    evaluated over windows collected under a different spec set would
    page on stale arithmetic."""
    global _ENGINE, _TENANT_SPECS
    specs = tuple(specs)
    with _ENGINE_LOCK:
        if specs == _TENANT_SPECS:
            return _ENGINE
        _TENANT_SPECS = specs
        _ENGINE = SLOEngine(default_slos() + specs)
    return _ENGINE


def _reset_for_tests(specs: tuple[SLOSpec, ...] | None = None) -> SLOEngine:
    """Swap in a fresh engine (drops windows, page state, results)."""
    global _ENGINE, _TENANT_SPECS
    with _ENGINE_LOCK:
        _TENANT_SPECS = ()
        _ENGINE = SLOEngine(specs)
    return _ENGINE

"""Prometheus-style metrics: registry + text exposition.

The reference exposes node metrics through Tendermint's Prometheus
instrumentation (test/e2e/testnet/setup.go:24, node.go:125) and counts
app-level events via sdk telemetry (rejected txs/panics,
app/validate_txs.go:61,91, process_proposal.go:32).  This module carries
the same role: counters/gauges/histograms incremented at those points,
rendered in the Prometheus text exposition format on the serving plane's
GET /metrics.
"""

from __future__ import annotations

import threading
from collections import defaultdict


def _fmt_value(v: float) -> str:
    """Full-precision exposition (prometheus_client style): integers stay
    integral; %g would round counters past ~1e6."""
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._values: dict[tuple, float] = defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] += amount

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            items = list(self._values.items()) or [((), 0.0)]
        for key, val in items:
            out.append(f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(val)}")
        return out


class Gauge(Counter):
    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] = value

    def render(self) -> list[str]:
        return [
            line.replace(" counter", " gauge", 1) if line.startswith("# TYPE") else line
            for line in super().render()
        ]


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics)."""

    def __init__(self, name: str, help_text: str, buckets: tuple[float, ...]):
        self.name = name
        self.help = help_text
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self._counts[i] += 1
                    break
            else:
                self._counts[-1] += 1

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            cumulative = 0
            for b, c in zip(self.buckets, self._counts):
                cumulative += c
                out.append(f'{self.name}_bucket{{le="{b:g}"}} {cumulative}')
            cumulative += self._counts[-1]
            out.append(f'{self.name}_bucket{{le="+Inf"}} {cumulative}')
            out.append(f"{self.name}_sum {_fmt_value(self._sum)}")
            out.append(f"{self.name}_count {cumulative}")
        return out


class Registry:
    def __init__(self):
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get_or_make(name, lambda: Counter(name, help_text), Counter)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get_or_make(name, lambda: Gauge(name, help_text), Gauge)

    def histogram(
        self, name: str, help_text: str = "",
        buckets: tuple[float, ...] = (0.005, 0.025, 0.1, 0.5, 2.5, 10.0),
    ) -> Histogram:
        return self._get_or_make(
            name, lambda: Histogram(name, help_text, buckets), Histogram
        )

    def _get_or_make(self, name, factory, kind):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif type(m) is not kind:
                raise TypeError(f"metric {name} already registered as {type(m).__name__}")
            return m

    def render(self) -> str:
        lines: list[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            lines += m.render()
        return "\n".join(lines) + "\n"


_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY

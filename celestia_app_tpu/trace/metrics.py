"""Prometheus-style metrics: registry + text exposition.

The reference exposes node metrics through Tendermint's Prometheus
instrumentation (test/e2e/testnet/setup.go:24, node.go:125) and counts
app-level events via sdk telemetry (rejected txs/panics,
app/validate_txs.go:61,91, process_proposal.go:32).  This module carries
the same role: counters/gauges/histograms incremented at those points,
rendered in the Prometheus text exposition format on the serving plane's
GET /metrics.
"""

from __future__ import annotations

import threading
from collections import defaultdict

# Explicit bucket tuple for DEVICE timings: the default request-scale
# buckets (5 ms floor) collapse every sub-millisecond kernel dispatch into
# one bucket.  Spans/journal rows measuring device work pass these
# explicitly at the call site; the floor is 100 µs — below the cheapest
# observed dispatch — and the ceiling covers a cold k=512 transfer.
DEVICE_SECONDS_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0,
)


def _fmt_value(v: float) -> str:
    """Full-precision exposition (prometheus_client style): integers stay
    integral; %g would round counters past ~1e6."""
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._values: dict[tuple, float] = defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] += amount

    def samples(self) -> list[tuple[dict, float]]:
        """[(labels dict, value)] for every child series — the read-side
        accessor gauge predicates (trace/slo.py) evaluate over."""
        with self._lock:
            return [(dict(key), val) for key, val in sorted(self._values.items())]

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            # Sorted by label set: the exposition is a stable function of
            # the registry STATE, never of sample arrival order.
            items = sorted(self._values.items()) or [((), 0.0)]
        for key, val in items:
            out.append(f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(val)}")
        return out


class Gauge(Counter):
    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] = value

    def render(self) -> list[str]:
        return [
            line.replace(" counter", " gauge", 1) if line.startswith("# TYPE") else line
            for line in super().render()
        ]


class HistogramSnapshot:
    """Point-in-time copy of a Histogram's children, the unit of windowed
    evaluation: `delta(earlier)` subtracts an older snapshot child-wise
    (what arrived IN the window, not since process start — Prometheus
    counters are cumulative, SLO windows are not), and `quantile` /
    `fraction_over` estimate from bucket counts with linear interpolation
    inside the bounding bucket.  `**labels` on the estimators is a subset
    selector: children whose label sets contain every given pair are
    merged before estimating (so `phase="total"` covers the per-tenant
    `{phase="total",namespace=...}` children too)."""

    def __init__(self, buckets: tuple[float, ...], children: dict):
        self.buckets = buckets
        # label key tuple -> (per-bucket counts incl. +Inf tail, sum)
        self.children = children

    def delta(self, earlier: "HistogramSnapshot") -> "HistogramSnapshot":
        """This snapshot minus `earlier`: the observations of the window
        between them.  Children absent earlier keep their full counts; a
        reset (counts going backwards, e.g. a test registry swap) clamps
        at zero rather than going negative."""
        out = {}
        for key, (counts, total) in self.children.items():
            old = earlier.children.get(key)
            if old is None:
                out[key] = (list(counts), total)
                continue
            out[key] = (
                [max(0, c - o) for c, o in zip(counts, old[0])],
                max(0.0, total - old[1]),
            )
        return HistogramSnapshot(self.buckets, out)

    def _merged(self, labels: dict) -> list[int]:
        """Summed per-bucket counts over children matching the subset
        selector (stringified values, like observe())."""
        want = {(k, str(v)) for k, v in labels.items()}
        merged = [0] * (len(self.buckets) + 1)
        for key, (counts, _) in self.children.items():
            if want <= set(key):
                for i, c in enumerate(counts):
                    merged[i] += c
        return merged

    def count(self, **labels) -> int:
        return sum(self._merged(labels))

    def quantile(self, q: float, **labels) -> float | None:
        """Bucket-interpolated quantile estimate in [0, 1] -> value, or
        None with no observations.  Ranks landing in the +Inf tail clamp
        to the largest finite bound (the estimate cannot exceed what the
        buckets resolve)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        counts = self._merged(labels)
        total = sum(counts)
        if total == 0:
            return None
        rank = q * total
        cumulative = 0
        for i, bound in enumerate(self.buckets):
            prev_cum, cumulative = cumulative, cumulative + counts[i]
            if cumulative >= rank:
                lower = self.buckets[i - 1] if i else 0.0
                if counts[i] == 0:
                    return bound
                frac = (rank - prev_cum) / counts[i]
                return lower + (bound - lower) * frac
        return self.buckets[-1] if self.buckets else None

    def fraction_over(self, threshold: float, **labels) -> float | None:
        """Estimated fraction of observations strictly above `threshold`
        (the SLO bad-event rate), interpolating inside the bucket that
        contains it; the +Inf tail always counts as over.  None with no
        observations."""
        counts = self._merged(labels)
        total = sum(counts)
        if total == 0:
            return None
        under = 0.0
        for i, bound in enumerate(self.buckets):
            if bound <= threshold:
                under += counts[i]
                continue
            lower = self.buckets[i - 1] if i else 0.0
            if threshold > lower:
                under += counts[i] * (threshold - lower) / (bound - lower)
            break
        return max(0.0, min(1.0, (total - under) / total))


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics), labeled: each
    distinct label set is its own child series with its own bucket counts,
    `le` merged into the labels on _bucket lines."""

    def __init__(self, name: str, help_text: str, buckets: tuple[float, ...]):
        self.name = name
        self.help = help_text
        self.buckets = tuple(sorted(buckets))
        # label key tuple -> [per-bucket counts (+Inf tail), sum]
        self._children: dict[tuple, list] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, **labels) -> None:
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = [
                    [0] * (len(self.buckets) + 1), 0.0
                ]
            child[1] += value
            counts = child[0]
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1

    def snapshot(self) -> HistogramSnapshot:
        """Copy the current child counts for windowed evaluation: two
        snapshots bracket a window, `later.delta(earlier)` is what landed
        inside it (trace/slo.py's rolling-window input)."""
        with self._lock:
            children = {
                key: (list(child[0]), child[1])
                for key, child in self._children.items()
            }
        return HistogramSnapshot(self.buckets, children)

    def quantile(self, q: float, **labels) -> float | None:
        """Bucket-interpolated quantile over the CUMULATIVE counts (all
        observations since process start); window-scoped quantiles go
        through snapshot()/delta() instead."""
        return self.snapshot().quantile(q, **labels)

    @staticmethod
    def merge(snapshots) -> HistogramSnapshot:
        """Bucket-wise merge of per-host snapshots into ONE fleet
        snapshot: children with the same label set sum count-for-count,
        disjoint label sets union — so a cross-host quantile off the
        result is EXACT at bucket resolution (bucket counts are additive
        across processes; no resampling, no quantile-of-quantiles bias).
        Empty snapshots are identity elements; the +Inf tail sums like
        any other bucket (quantile() still clamps tail ranks to the
        largest finite bound).  All non-empty snapshots must share one
        bucket layout — merging counts across different layouts would
        silently misbucket, so that raises ValueError."""
        buckets: tuple[float, ...] | None = None
        merged: dict[tuple, list] = {}
        for snap in snapshots:
            if not snap.children:
                continue
            if buckets is None:
                buckets = snap.buckets
            elif snap.buckets != buckets:
                raise ValueError(
                    f"cannot merge histograms with bucket layouts "
                    f"{buckets} and {snap.buckets}"
                )
            for key, (counts, total) in snap.children.items():
                child = merged.get(key)
                if child is None:
                    merged[key] = [list(counts), total]
                    continue
                for i, c in enumerate(counts):
                    child[0][i] += c
                child[1] += total
        return HistogramSnapshot(
            buckets or (),
            {key: (child[0], child[1]) for key, child in merged.items()},
        )

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            children = [
                (key, (list(child[0]), child[1]))
                for key, child in sorted(self._children.items())
            ] or [((), ([0] * (len(self.buckets) + 1), 0.0))]
        for key, (counts, total) in children:
            labels = dict(key)
            cumulative = 0
            for b, c in zip(self.buckets, counts):
                cumulative += c
                out.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels({**labels, 'le': f'{b:g}'})} {cumulative}"
                )
            cumulative += counts[-1]
            out.append(
                f"{self.name}_bucket"
                f"{_fmt_labels({**labels, 'le': '+Inf'})} {cumulative}"
            )
            out.append(
                f"{self.name}_sum{_fmt_labels(labels)} {_fmt_value(total)}"
            )
            out.append(f"{self.name}_count{_fmt_labels(labels)} {cumulative}")
        return out


class Registry:
    def __init__(self):
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get_or_make(name, lambda: Counter(name, help_text), Counter)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get_or_make(name, lambda: Gauge(name, help_text), Gauge)

    def histogram(
        self, name: str, help_text: str = "",
        buckets: tuple[float, ...] = (0.005, 0.025, 0.1, 0.5, 2.5, 10.0),
    ) -> Histogram:
        return self._get_or_make(
            name, lambda: Histogram(name, help_text, buckets), Histogram
        )

    def get(self, name: str) -> "Counter | Gauge | Histogram | None":
        """The registered metric by name, or None — the read-side lookup
        (SLO evaluation) that must never create a family as a side
        effect of observing it."""
        with self._lock:
            return self._metrics.get(name)

    def _get_or_make(self, name, factory, kind):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif type(m) is not kind:
                raise TypeError(f"metric {name} already registered as {type(m).__name__}")
            return m

    def render(self) -> str:
        lines: list[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            lines += m.render()
        return "\n".join(lines) + "\n"


_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY

"""Prometheus-style metrics: registry + text exposition.

The reference exposes node metrics through Tendermint's Prometheus
instrumentation (test/e2e/testnet/setup.go:24, node.go:125) and counts
app-level events via sdk telemetry (rejected txs/panics,
app/validate_txs.go:61,91, process_proposal.go:32).  This module carries
the same role: counters/gauges/histograms incremented at those points,
rendered in the Prometheus text exposition format on the serving plane's
GET /metrics.
"""

from __future__ import annotations

import threading
from collections import defaultdict

# Explicit bucket tuple for DEVICE timings: the default request-scale
# buckets (5 ms floor) collapse every sub-millisecond kernel dispatch into
# one bucket.  Spans/journal rows measuring device work pass these
# explicitly at the call site; the floor is 100 µs — below the cheapest
# observed dispatch — and the ceiling covers a cold k=512 transfer.
DEVICE_SECONDS_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0,
)


def _fmt_value(v: float) -> str:
    """Full-precision exposition (prometheus_client style): integers stay
    integral; %g would round counters past ~1e6."""
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._values: dict[tuple, float] = defaultdict(float)
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] += amount

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            # Sorted by label set: the exposition is a stable function of
            # the registry STATE, never of sample arrival order.
            items = sorted(self._values.items()) or [((), 0.0)]
        for key, val in items:
            out.append(f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(val)}")
        return out


class Gauge(Counter):
    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] = value

    def render(self) -> list[str]:
        return [
            line.replace(" counter", " gauge", 1) if line.startswith("# TYPE") else line
            for line in super().render()
        ]


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics), labeled: each
    distinct label set is its own child series with its own bucket counts,
    `le` merged into the labels on _bucket lines."""

    def __init__(self, name: str, help_text: str, buckets: tuple[float, ...]):
        self.name = name
        self.help = help_text
        self.buckets = tuple(sorted(buckets))
        # label key tuple -> [per-bucket counts (+Inf tail), sum]
        self._children: dict[tuple, list] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, **labels) -> None:
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = [
                    [0] * (len(self.buckets) + 1), 0.0
                ]
            child[1] += value
            counts = child[0]
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            children = [
                (key, (list(child[0]), child[1]))
                for key, child in sorted(self._children.items())
            ] or [((), ([0] * (len(self.buckets) + 1), 0.0))]
        for key, (counts, total) in children:
            labels = dict(key)
            cumulative = 0
            for b, c in zip(self.buckets, counts):
                cumulative += c
                out.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels({**labels, 'le': f'{b:g}'})} {cumulative}"
                )
            cumulative += counts[-1]
            out.append(
                f"{self.name}_bucket"
                f"{_fmt_labels({**labels, 'le': '+Inf'})} {cumulative}"
            )
            out.append(
                f"{self.name}_sum{_fmt_labels(labels)} {_fmt_value(total)}"
            )
            out.append(f"{self.name}_count{_fmt_labels(labels)} {cumulative}")
        return out


class Registry:
    def __init__(self):
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get_or_make(name, lambda: Counter(name, help_text), Counter)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get_or_make(name, lambda: Gauge(name, help_text), Gauge)

    def histogram(
        self, name: str, help_text: str = "",
        buckets: tuple[float, ...] = (0.005, 0.025, 0.1, 0.5, 2.5, 10.0),
    ) -> Histogram:
        return self._get_or_make(
            name, lambda: Histogram(name, help_text, buckets), Histogram
        )

    def _get_or_make(self, name, factory, kind):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif type(m) is not kind:
                raise TypeError(f"metric {name} already registered as {type(m).__name__}")
            return m

    def render(self) -> str:
        lines: list[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            lines += m.render()
        return "\n".join(lines) + "\n"


_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY

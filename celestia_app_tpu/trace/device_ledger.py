"""Device-attribution ledger: who owns the chip, program by program.

Every other observability surface watches the HOST side (spans, SLOs,
flight bundles, the fleet merge).  The thing the paper actually
accelerates — the jitted GF(2^8)/XOR extend, forest, gather, repair and
verify programs (arXiv 2108.02692 schedule) — was a black box: we could
not say which program family owned device time, which compiles were paid
when, or who owns the resident HBM/RSS bytes.  This module is that
ledger, in two halves:

PROGRAM LEDGER — every jit-cache family in `da/`, `kernels/`, `serve/`,
`parallel/` wraps its freshly built program with `track(fn, family,
**key)` (enforced by trace_lint rule 8).  Per program key (family, k,
construction, mode, batch, shards) the ledger records:

    compile_s          wall-seconds of the FIRST dispatch (jax traces +
                       compiles lazily, so first-call wall time is the
                       compile bill; later dispatches are the steady state)
    dispatches         total calls through the wrapper
    dispatch_s         cumulative wall-seconds across all dispatches
    last_dispatch_age  seconds since the program last ran (at tick time)
    resident           whether the builder cache still holds the program
                       (a weakref: bounded caches — da/repair's lru(64) —
                       evict, the weakref dies, residency flips false
                       while the historical counters persist)

OWNERSHIP LEDGER — the big resident-bytes holders (ForestCache entries,
retained sharded EDS buffers, BlockPipeline `_BufferRing` slots, panel
accumulators, generator/bit-plane caches, mempool shards) report owned
bytes, either via a live `register_owner(name, callback)` or by
`note_owned_bytes(owner, key, nbytes)` at allocation time.  Each tick
reconciles the sum against the measured high-water —
`device.memory_stats()` peak on real accelerators, the RSS high-water
fallback on CPU (trace/profiler.py, the PR 11 instrument) — and
publishes the unattributed slack as its own gauge.  A residual that
GROWS for `$CELESTIA_DEVICE_LEAK_TICKS` consecutive reconciliations is
the leak signature: bytes nobody claims, trending up — it fires the
`device_residual_growth` flight trigger (trace/flight_recorder.py).

Exposition:

    celestia_jit_programs_resident{family}        gauge
    celestia_jit_compile_seconds_total{family}    counter
    celestia_dispatch_seconds_total{family,k,mode} counter
    celestia_device_bytes{owner}                  gauge (+ the
                                                  unattributed_residual
                                                  pseudo-owner)
    GET /device                                   ledger table + ownership
                                                  + currently-applied
                                                  autotuner seats + warmup
                                                  state, byte-identical on
                                                  all three planes and
                                                  merged into /fleet

Byte-identity across planes follows the /slo maybe_tick pattern: the
payload is a pure function of a snapshot refreshed at most once per
`$CELESTIA_DEVICE_TICK_S` (default 0 = every render; tests freeze it
like $CELESTIA_SLO_TICK_S), rendered canonically (sorted keys, tight
separators) so sequential fetches inside one tick serve identical bytes.

`$CELESTIA_DEVICE_SNAPSHOT=<path>`: dump one snapshot JSON at process
exit — how `scripts/chip_sweep.py` embeds each leg's ledger into the
sweep journal without the leg needing a serving plane.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
import weakref

__all__ = [
    "track",
    "register_owner",
    "unregister_owner",
    "note_owned_bytes",
    "forget_owned_bytes",
    "note_warmup",
    "reconcile",
    "snapshot",
    "device_payload",
    "device_response",
    "_reset_for_tests",
]

_LOCK = threading.Lock()

#: program key -> mutable stats record (see _program_row for the shape).
_PROGRAMS: dict[tuple, dict] = {}

#: owner name -> zero-arg callable returning currently owned bytes.
_OWNER_CALLBACKS: dict[str, object] = {}

#: owner name -> {key: nbytes} for allocation-time accounting
#: (note_owned_bytes) where no live object can answer a callback.
_OWNED_KEYED: dict[str, dict] = {}

#: owners ever published, so an evicted owner's gauge re-zeros instead
#: of serving its last value forever.
_PUBLISHED_OWNERS: set[str] = set()

#: warmup notes: (k, construction, mode) -> unix seconds of the warmup.
_WARMED: dict[tuple, float] = {}

#: consecutive reconciliations where the unattributed residual grew.
_RESIDUAL_STREAK = 0
_LAST_RESIDUAL: int | None = None

_TICK_LOCK = threading.Lock()
_LAST_TICK: float | None = None
_CACHED_BODY: bytes | None = None


class _TriggerGuard(threading.local):
    busy = False


_IN_TRIGGER = _TriggerGuard()

_SNAPSHOT_HOOKED = False


def _dispatch_seconds_counter():
    from celestia_app_tpu.trace.metrics import registry

    return registry().counter(
        "celestia_dispatch_seconds_total",
        "cumulative host wall-seconds spent dispatching jitted programs, "
        "by family/k/mode (first dispatch excluded: that is the compile)",
    )


def _compile_seconds_counter():
    from celestia_app_tpu.trace.metrics import registry

    return registry().counter(
        "celestia_jit_compile_seconds_total",
        "wall-seconds of first dispatches (trace+compile bill), by family",
    )


def _resident_gauge():
    from celestia_app_tpu.trace.metrics import registry

    return registry().gauge(
        "celestia_jit_programs_resident",
        "jit programs still held by their builder caches, by family "
        "(bounded caches evict; evicted programs keep their counters "
        "but stop counting here)",
    )


def _device_bytes_gauge():
    from celestia_app_tpu.trace.metrics import registry

    return registry().gauge(
        "celestia_device_bytes",
        "resident bytes by owner, reconciled against the measured "
        "high-water (owner=unattributed_residual is the slack nobody "
        "claims — its sustained growth is the leak trigger)",
    )


def leak_ticks() -> int:
    """$CELESTIA_DEVICE_LEAK_TICKS: consecutive residual-growth
    reconciliations before the flight trigger fires (default 3)."""
    try:
        return max(2, int(os.environ.get("CELESTIA_DEVICE_LEAK_TICKS", "") or 3))
    except ValueError:
        return 3


def _key(family: str, k, construction, mode, batch, shards) -> tuple:
    return (
        str(family),
        int(k) if k is not None else 0,
        str(construction or ""),
        str(mode or ""),
        int(batch) if batch is not None else 0,
        int(shards) if shards is not None else 0,
    )


class _Tracked:
    """The wrapper a builder cache holds instead of the bare jitted fn.

    First call bills compile_s (jax traces + compiles on first dispatch);
    every later call accumulates dispatches/dispatch_s.  Attribute access
    falls through to the wrapped program (`.lower`, shardings, etc.), so
    callers cannot tell they hold the wrapper — except that the ledger
    can weakref THIS object to observe builder-cache eviction, which the
    C-level jit callable does not always allow."""

    __slots__ = ("_fn", "_rec", "__weakref__")

    def __init__(self, fn, rec: dict):
        self._fn = fn
        self._rec = rec

    def __call__(self, *args, **kwargs):
        rec = self._rec
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        with _LOCK:
            first = rec["dispatches"] == 0 and rec["compile_s"] == 0.0
            if first:
                rec["compile_s"] = dt
            else:
                rec["dispatch_s"] += dt
            rec["dispatches"] += 1
            rec["last_dispatch_unix"] = time.time()
        if first:
            _compile_seconds_counter().inc(dt, family=rec["family"])
            # The compile bill as a TRACE ROW, stamped with whatever
            # block/request context paid it: the height timeline
            # (trace/timeline.py) attributes a first-dispatch
            # trace+compile stall to the height that hit it.
            from celestia_app_tpu.trace.context import current_context
            from celestia_app_tpu.trace.tracer import traced

            ctx = current_context()
            traced().write(
                "compile_bill", family=rec["family"], k=rec["k"],
                mode=rec["mode"], compile_ms=dt * 1e3,
                trace_id=ctx.trace_id if ctx is not None else None,
                height=ctx.baggage.get("height") if ctx is not None else None,
            )
        else:
            _dispatch_seconds_counter().inc(
                dt, family=rec["family"], k=str(rec["k"]), mode=rec["mode"]
            )
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


def track(fn, family: str, *, k=None, construction=None, mode=None,
          batch=None, shards=None):
    """Register a freshly built jit program under (family, k,
    construction, mode, batch, shards) and return the tracked wrapper
    the builder cache should hold.  Called from lru_cache-MISSED builder
    bodies (beside trace/journal.note_jit_build), so cache hits cost
    nothing.  Rebuilding an evicted key revives the same stats record —
    compile_s then accumulates the re-compile bill too."""
    key = _key(family, k, construction, mode, batch, shards)
    with _LOCK:
        rec = _PROGRAMS.get(key)
        if rec is None:
            rec = _PROGRAMS[key] = {
                "family": key[0],
                "k": key[1],
                "construction": key[2],
                "mode": key[3],
                "batch": key[4],
                "shards": key[5],
                "compile_s": 0.0,
                "dispatches": 0,
                "dispatch_s": 0.0,
                "last_dispatch_unix": None,
                "builds": 0,
                "ref": None,
            }
        rec["builds"] += 1
    wrapper = _Tracked(fn, rec)
    with _LOCK:
        rec["ref"] = weakref.ref(wrapper)
    _hook_snapshot_dump()
    return wrapper


def register_owner(name: str, callback) -> None:
    """Mount `callback()` -> currently-owned bytes under `name` in the
    ownership ledger.  Last registration per name wins (the health-
    provider convention); a callback that raises reports 0 for that tick
    rather than taking the exposition down."""
    with _LOCK:
        _OWNER_CALLBACKS[str(name)] = callback
    _hook_snapshot_dump()


def unregister_owner(name: str) -> None:
    with _LOCK:
        _OWNER_CALLBACKS.pop(str(name), None)


def note_owned_bytes(owner: str, key, nbytes: int) -> None:
    """Allocation-time accounting for caches with no natural callback
    object (generator/bit-plane tables, panel accumulators): record that
    `owner` holds `nbytes` under `key`; re-noting a key replaces its
    figure.  Unbounded caches never call forget_owned_bytes — that is
    the point: the bytes really are resident forever."""
    with _LOCK:
        _OWNED_KEYED.setdefault(str(owner), {})[key] = max(0, int(nbytes))
    _hook_snapshot_dump()


def forget_owned_bytes(owner: str, key=None) -> None:
    """Drop one key's figure (or the whole owner with key=None) — the
    eviction half of note_owned_bytes; the owner's gauge re-zeros on the
    next reconciliation."""
    with _LOCK:
        if key is None:
            _OWNED_KEYED.pop(str(owner), None)
        else:
            _OWNED_KEYED.get(str(owner), {}).pop(key, None)


def note_warmup(k: int, construction: str, mode: str) -> None:
    """Record that da/eds.warmup pre-built (k, construction, mode) — the
    /device warmup block: which program shapes were paid for up front."""
    with _LOCK:
        _WARMED[(int(k), str(construction), str(mode))] = time.time()


def _measured_bytes() -> tuple[int, str]:
    """(high-water bytes, source) — device allocator peak when a real
    accelerator answers memory_stats, else the RSS high-water fallback
    (trace/profiler.py)."""
    from celestia_app_tpu.trace.profiler import hbm_high_water, rss_high_water

    hbm = hbm_high_water()
    if hbm is not None:
        return int(hbm), "device_memory_stats"
    rss = rss_high_water()
    if rss is not None:
        return int(rss), "rss_high_water"
    return 0, "unavailable"


def reconcile() -> dict:
    """One ownership-ledger tick: collect every owner's bytes, measure
    the high-water, publish `celestia_device_bytes{owner}` (re-zeroing
    owners that vanished), compute the unattributed residual, and track
    its growth streak — firing the `device_residual_growth` flight
    trigger when the streak reaches leak_ticks()."""
    global _RESIDUAL_STREAK, _LAST_RESIDUAL
    with _LOCK:
        callbacks = dict(_OWNER_CALLBACKS)
        keyed = {o: sum(d.values()) for o, d in _OWNED_KEYED.items()}
    owners: dict[str, int] = {}
    for name, cb in callbacks.items():
        try:
            owners[name] = max(0, int(cb()))
        except Exception:  # noqa: BLE001 — ledger must not kill the probe
            owners[name] = 0
    for name, total in keyed.items():
        owners[name] = owners.get(name, 0) + total
    owned_total = sum(owners.values())
    measured, source = _measured_bytes()
    residual = max(0, measured - owned_total)

    gauge = _device_bytes_gauge()
    with _LOCK:
        stale = _PUBLISHED_OWNERS - set(owners)
        _PUBLISHED_OWNERS.update(owners)
        _PUBLISHED_OWNERS.add("unattributed_residual")
    for name in stale:
        if name != "unattributed_residual":
            gauge.set(0, owner=name)
    for name, val in owners.items():
        gauge.set(val, owner=name)
    gauge.set(residual, owner="unattributed_residual")

    with _LOCK:
        if _IN_TRIGGER.busy:
            # The bundle's own embedded snapshot reconciles for the
            # numbers, not the accounting: advancing the streak or the
            # last-residual mark here would let the capture itself
            # re-prime the episode it is documenting.
            streak = _RESIDUAL_STREAK
            fire = False
        else:
            grew = _LAST_RESIDUAL is not None and residual > _LAST_RESIDUAL
            _RESIDUAL_STREAK = _RESIDUAL_STREAK + 1 if grew else 0
            _LAST_RESIDUAL = residual
            streak = _RESIDUAL_STREAK
            fire = streak >= leak_ticks()
            if fire:
                # Re-arm only after the residual stops growing: one
                # bundle per sustained-growth episode, not one per tick.
                _RESIDUAL_STREAK = 0
    if fire:
        from celestia_app_tpu.trace.flight_recorder import note_trigger

        # The guard breaks the capture -> snapshot -> reconcile cycle:
        # a bundle's own embedded /device snapshot must not fire the
        # trigger it is being captured FOR (unbounded recursion when the
        # per-trigger rate limit is disabled for drills).
        _IN_TRIGGER.busy = True
        try:
            note_trigger(
                "device_residual_growth",
                residual_bytes=residual,
                owned_bytes=owned_total,
                measured_bytes=measured,
                streak=streak,
                source=source,
            )
        finally:
            _IN_TRIGGER.busy = False
    return {
        "owners": {k: owners[k] for k in sorted(owners)},
        "owned_bytes": owned_total,
        "measured_bytes": measured,
        "measured_source": source,
        "unattributed_residual": residual,
        "residual_growth_streak": streak,
    }


def _applied_seats() -> dict:
    """The autotuner seats currently APPLIED via env — the same knobs
    bench.py's `_env_for_tuned` writes when a tuned pick lands, read
    back so /device shows what the library will actually run."""
    seats = {}
    for var in (
        "CELESTIA_RS_FFT", "CELESTIA_RS_FFT_MD", "CELESTIA_RS_PALLAS",
        "CELESTIA_RS_XOR", "CELESTIA_SHA_PALLAS", "CELESTIA_SHA_FUSED",
        "CELESTIA_PIPE_FUSED", "CELESTIA_PIPE_PANEL",
        "CELESTIA_EXTEND_SHARDS", "CELESTIA_SERVE_SHARDS",
        "CELESTIA_MEMPOOL_SHARDS", "CELESTIA_SPECULATE",
    ):
        val = os.environ.get(var)
        if val is not None:
            seats[var] = val
    return seats


def _program_row(rec: dict, now: float) -> dict:
    ref = rec.get("ref")
    alive = ref is not None and ref() is not None
    last = rec["last_dispatch_unix"]
    return {
        "family": rec["family"],
        "k": rec["k"],
        "construction": rec["construction"],
        "mode": rec["mode"],
        "batch": rec["batch"],
        "shards": rec["shards"],
        "builds": rec["builds"],
        "compile_s": round(rec["compile_s"], 6),
        "dispatches": rec["dispatches"],
        "dispatch_s": round(rec["dispatch_s"], 6),
        "last_dispatch_age_s": (
            round(max(0.0, now - last), 3) if last is not None else None
        ),
        "resident": alive,
    }


def snapshot() -> dict:
    """A FRESH ledger view (programs + ownership reconciliation + seats
    + warmup) — what flight bundles and $CELESTIA_DEVICE_SNAPSHOT dumps
    embed.  /device serves the rate-limited cached render of this."""
    now = time.time()
    with _LOCK:
        recs = [dict(r) for r in _PROGRAMS.values()]
        warmed = dict(_WARMED)
    rows = sorted(
        (_program_row(r, now) for r in recs),
        key=lambda r: (r["family"], r["k"], r["construction"], r["mode"],
                       r["batch"], r["shards"]),
    )
    resident = _resident_gauge()
    by_family: dict[str, int] = {}
    for row in rows:
        by_family.setdefault(row["family"], 0)
        if row["resident"]:
            by_family[row["family"]] += 1
    for family, count in sorted(by_family.items()):
        resident.set(count, family=family)
    return {
        "programs": rows,
        "programs_resident": {k: by_family[k] for k in sorted(by_family)},
        "ownership": reconcile(),
        "autotuner_seats": _applied_seats(),
        "warmup": [
            {"k": k, "construction": c, "mode": m}
            for (k, c, m) in sorted(warmed)
        ],
    }


def _tick_interval_s() -> float:
    try:
        return max(0.0, float(
            os.environ.get("CELESTIA_DEVICE_TICK_S", "") or 0.0
        ))
    except ValueError:
        return 0.0


def device_payload() -> bytes:
    """The canonical /device bytes: a snapshot refreshed at most once per
    $CELESTIA_DEVICE_TICK_S, rendered with sorted keys + tight
    separators — the pure-function-of-retained-state shape that makes
    cross-plane byte-identity structural (the /slo maybe_tick pattern)."""
    global _LAST_TICK, _CACHED_BODY
    now = time.monotonic()
    min_s = _tick_interval_s()
    with _TICK_LOCK:
        if (
            _CACHED_BODY is not None
            and _LAST_TICK is not None
            and now - _LAST_TICK < min_s
        ):
            return _CACHED_BODY
    body = json.dumps(
        snapshot(), sort_keys=True, separators=(",", ":")
    ).encode()
    with _TICK_LOCK:
        _LAST_TICK = now
        _CACHED_BODY = body
    return body


def device_response():
    """GET /device for trace/exposition.handle_observability_get."""
    return 200, "application/json", device_payload()


def _hook_snapshot_dump() -> None:
    """Arm the $CELESTIA_DEVICE_SNAPSHOT atexit dump once, lazily — only
    processes that actually touch the ledger pay the hook."""
    global _SNAPSHOT_HOOKED
    if _SNAPSHOT_HOOKED or not os.environ.get("CELESTIA_DEVICE_SNAPSHOT"):
        return
    with _LOCK:
        if _SNAPSHOT_HOOKED:
            return
        _SNAPSHOT_HOOKED = True
    atexit.register(_dump_snapshot)


def _dump_snapshot() -> None:
    path = os.environ.get("CELESTIA_DEVICE_SNAPSHOT")
    if not path:
        return
    try:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snapshot(), f, sort_keys=True, default=repr)
            f.write("\n")
        os.replace(tmp, path)
    except Exception:  # noqa: BLE001 — an exit hook must never raise
        pass


def _reset_for_tests() -> None:
    """Drop ledger state + the tick cache (test isolation).  Registered
    owner callbacks survive only if re-registered by the module under
    test — module-import-time registrations (mempool, caches) re-arm on
    next use."""
    global _RESIDUAL_STREAK, _LAST_RESIDUAL, _LAST_TICK, _CACHED_BODY
    with _LOCK:
        _PROGRAMS.clear()
        _OWNER_CALLBACKS.clear()
        _OWNED_KEYED.clear()
        _PUBLISHED_OWNERS.clear()
        _WARMED.clear()
        _RESIDUAL_STREAK = 0
        _LAST_RESIDUAL = None
    with _TICK_LOCK:
        _LAST_TICK = None
        _CACHED_BODY = None

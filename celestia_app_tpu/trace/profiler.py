"""JAX profiler + HBM accounting hooks (env-gated, off the hot path).

Two device-side instruments the journal funnel drives per block:

  * an N-block `jax.profiler.start_trace`/`stop_trace` window:
    $CELESTIA_PROFILE_BLOCKS=N arms it; the trace starts on the first
    journaled block and stops after N, writing the TensorBoard-loadable
    trace under $CELESTIA_PROFILE_DIR (default /tmp/celestia_jax_trace).
    One window per process — profiling is a measurement run, not a
    steady-state cost;
  * an HBM high-water gauge from `device.memory_stats()`:
    celestia_hbm_peak_bytes{point=...,k=...}, refreshed per journaled
    dispatch.  CPU backends return no stats — the gauge simply never
    appears there (guarded None, never an exception on the block path).

This is the instrument for the ROADMAP TODO "measure whether donation
moves the k=512 HBM high-water mark enough to deepen the stream pipeline
past depth 2": run the stream bench once with $CELESTIA_PIPE_FUSED=auto
and once =off, diff the gauge.
"""

from __future__ import annotations

import os
import threading


def profile_blocks_target() -> int:
    """$CELESTIA_PROFILE_BLOCKS: how many journaled blocks the jax
    profiler window spans (0 = disabled)."""
    try:
        return int(os.environ.get("CELESTIA_PROFILE_BLOCKS", "0") or "0")
    except ValueError:
        return 0


def profile_dir() -> str:
    return os.environ.get("CELESTIA_PROFILE_DIR", "/tmp/celestia_jax_trace")


class BlockProfiler:
    """One env-gated profiler window per process, advanced per block."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active = False
        self._remaining = 0
        self._done = False

    def note_block(self) -> None:
        target = profile_blocks_target()
        if target <= 0 or self._done:
            return
        with self._lock:
            if self._done:
                return
            if not self._active:
                if not self._start(target):
                    return
            self._remaining -= 1
            if self._remaining <= 0:
                self._stop()

    def _start(self, target: int) -> bool:
        from celestia_app_tpu.trace.tracer import traced

        logdir = profile_dir()
        try:
            import jax

            os.makedirs(logdir, exist_ok=True)
            jax.profiler.start_trace(logdir)
        except Exception as e:  # noqa: BLE001 — profiling must never take
            # down the block path; record the failure once and disarm.
            self._done = True
            traced().write("profiler", event="start_failed",
                           error=f"{type(e).__name__}: {e}"[:200])
            return False
        self._active = True
        self._remaining = target
        traced().write("profiler", event="started", blocks=target,
                       logdir=logdir)
        return True

    def _stop(self) -> None:
        from celestia_app_tpu.trace.tracer import traced

        try:
            import jax

            jax.profiler.stop_trace()
            traced().write("profiler", event="stopped", logdir=profile_dir())
        except Exception as e:  # noqa: BLE001
            traced().write("profiler", event="stop_failed",
                           error=f"{type(e).__name__}: {e}"[:200])
        self._active = False
        self._done = True  # one window per process


_PROFILER = BlockProfiler()


def block_profiler() -> BlockProfiler:
    return _PROFILER


def hbm_high_water(device=None) -> int | None:
    """Peak device-memory bytes from the allocator, or None when the
    backend keeps no stats (CPU).  A stats read, never a device sync."""
    try:
        import jax

        device = device or jax.devices()[0]
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — absent API / uninitialized backend
        return None
    if not stats:
        return None
    peak = stats.get("peak_bytes_in_use", stats.get("bytes_in_use"))
    return int(peak) if peak else None


def record_hbm_high_water(point: str = "dispatch",
                          k: int | None = None) -> int | None:
    """Refresh celestia_hbm_peak_bytes{point,k} and journal the sample;
    returns the peak (None on CPU, where the gauge never appears)."""
    peak = hbm_high_water()
    if peak is None:
        return None
    from celestia_app_tpu.trace.metrics import registry
    from celestia_app_tpu.trace.tracer import traced

    labels = {"point": point}
    if k is not None:
        labels["k"] = str(k)
    registry().gauge(
        "celestia_hbm_peak_bytes",
        "device memory high-water mark (allocator peak_bytes_in_use)",
    ).set(peak, **labels)
    traced().write("hbm_high_water", point=point, k=k, peak_bytes=peak)
    return peak

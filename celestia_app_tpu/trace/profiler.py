"""JAX profiler + HBM accounting hooks (env-gated, off the hot path).

Two device-side instruments the journal funnel drives per block:

  * an N-block `jax.profiler.start_trace`/`stop_trace` window:
    $CELESTIA_PROFILE_BLOCKS=N arms it; the trace starts on the first
    journaled block and stops after N, writing the TensorBoard-loadable
    trace under $CELESTIA_PROFILE_DIR (default /tmp/celestia_jax_trace).
    One window per process — profiling is a measurement run, not a
    steady-state cost;
  * a memory high-water gauge:
    celestia_hbm_peak_bytes{point=...,k=...,source=...}, refreshed per
    journaled dispatch.  `source="device"` is the allocator's
    peak_bytes_in_use from `device.memory_stats()`; backends that keep
    no stats (this image's CPU) fall back to `source="rss"` — the
    process peak RSS from resource.getrusage — so the giant-square
    memory-high-water claims stay MEASURABLE off-chip.  The label keeps
    the two sources from ever being compared as one series: RSS is a
    process-lifetime peak (it never goes down, and it includes the host
    heap), device stats are the allocator's own.

This is the instrument for the ROADMAP TODO "measure whether donation
moves the k=512 HBM high-water mark enough to deepen the stream pipeline
past depth 2" and for the panel-vs-materializing residency comparison
(README "Giant squares"): run the bench once per seam setting, diff the
gauge (or, on CPU, one process per setting — RSS peaks are per-process).
"""

from __future__ import annotations

import os
import threading


def profile_blocks_target() -> int:
    """$CELESTIA_PROFILE_BLOCKS: how many journaled blocks the jax
    profiler window spans (0 = disabled)."""
    try:
        return int(os.environ.get("CELESTIA_PROFILE_BLOCKS", "0") or "0")
    except ValueError:
        return 0


def profile_dir() -> str:
    return os.environ.get("CELESTIA_PROFILE_DIR", "/tmp/celestia_jax_trace")


class BlockProfiler:
    """One env-gated profiler window per process, advanced per block."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active = False
        self._remaining = 0
        self._done = False

    def note_block(self) -> None:
        target = profile_blocks_target()
        if target <= 0 or self._done:
            return
        with self._lock:
            if self._done:
                return
            if not self._active:
                if not self._start(target):
                    return
            self._remaining -= 1
            if self._remaining <= 0:
                self._stop()

    def _start(self, target: int) -> bool:
        from celestia_app_tpu.trace.tracer import traced

        logdir = profile_dir()
        try:
            import jax

            os.makedirs(logdir, exist_ok=True)
            jax.profiler.start_trace(logdir)
        except Exception as e:  # noqa: BLE001 — profiling must never take
            # down the block path; record the failure once and disarm.
            self._done = True
            traced().write("profiler", event="start_failed",
                           error=f"{type(e).__name__}: {e}"[:200])
            return False
        self._active = True
        self._remaining = target
        traced().write("profiler", event="started", blocks=target,
                       logdir=logdir)
        return True

    def _stop(self) -> None:
        from celestia_app_tpu.trace.tracer import traced

        try:
            import jax

            jax.profiler.stop_trace()
            traced().write("profiler", event="stopped", logdir=profile_dir())
        except Exception as e:  # noqa: BLE001
            traced().write("profiler", event="stop_failed",
                           error=f"{type(e).__name__}: {e}"[:200])
        self._active = False
        self._done = True  # one window per process


_PROFILER = BlockProfiler()


def block_profiler() -> BlockProfiler:
    return _PROFILER


def hbm_high_water(device=None) -> int | None:
    """Peak device-memory bytes from the allocator, or None when the
    backend keeps no stats (CPU).  A stats read, never a device sync."""
    try:
        import jax

        device = device or jax.devices()[0]
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — absent API / uninitialized backend
        return None
    if not stats:
        return None
    peak = stats.get("peak_bytes_in_use", stats.get("bytes_in_use"))
    return int(peak) if peak else None


def rss_high_water() -> int | None:
    """Process peak RSS in bytes (resource.getrusage ru_maxrss) — the
    CPU-fallback memory high-water.  A lifetime peak, never a per-phase
    one: comparing two pipeline configurations needs one process each."""
    try:
        import resource

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    except Exception:  # noqa: BLE001 — absent module/odd platform: no sample
        return None
    if not peak:
        return None
    import sys

    # Linux reports KiB; macOS bytes.
    return int(peak) * (1 if sys.platform == "darwin" else 1024)


def record_hbm_high_water(point: str = "dispatch",
                          k: int | None = None) -> int | None:
    """Refresh celestia_hbm_peak_bytes{point,k,source} and journal the
    sample; returns the peak bytes.  Device allocator stats when the
    backend keeps them (source="device"), else the process peak RSS
    (source="rss") so the high-water stays measurable on CPU images;
    None only when neither source can answer."""
    peak, source = hbm_high_water(), "device"
    if peak is None:
        peak, source = rss_high_water(), "rss"
    if peak is None:
        return None
    from celestia_app_tpu.trace.metrics import registry
    from celestia_app_tpu.trace.tracer import traced

    labels = {"point": point, "source": source}
    if k is not None:
        labels["k"] = str(k)
    registry().gauge(
        "celestia_hbm_peak_bytes",
        "memory high-water mark (device allocator peak_bytes_in_use, or "
        "process peak RSS on stat-less backends — see the source label)",
    ).set(peak, **labels)
    traced().write("hbm_high_water", point=point, k=k, peak_bytes=peak,
                   source=source)
    return peak

"""Shared observability HTTP surface for every serving plane.

The reference exposes Tendermint's Prometheus endpoint from the node and
lets the e2e harness pull pkg/trace's columnar tables off it
(test/e2e/testnet/setup.go:24, node.go:52-74).  Here one handler serves
both, and all three planes mount it — the JSON-RPC server, the REST
api_gateway, and the gRPC plane's debug port — so the exposition is
byte-identical for the same registry state no matter which port a scraper
hits:

    GET /metrics                 Prometheus text exposition (version 0.0.4)
    GET /trace_tables            {"tables": {name: row_count}}
    GET /trace_tables/<name>     the table as JSONL (application/x-ndjson);
                                 ?tail=N serves only the last N rows
    GET /healthz                 liveness + per-layer staleness + SLO block
    GET /namespaces              per-tenant data-plane summary (cumulative
                                 blob/share/byte totals + last square)
    GET /slo                     SLO burn-rate evaluation (trace/slo.py)
    GET /das/share_proof         one DAS sample: ?height=&row=&col= ->
                                 ShareProof vs the committed DAH data root
                                 (serve/, the batched proof plane)
    GET /das/shares              namespace-ranged query: ?height=&namespace=
                                 (29-byte hex) -> shares + multi-row proof
    GET /das/attestation         deduped multiproof for a SET of samples:
                                 ?height=&samples=r:c[:axis],... -> shared
                                 NMT/root node tables + per-tree ranges
                                 (serve/api.attestation_payload)
    GET /heal                    the self-healing loop's state: heights
                                 mid-heal, quarantined heights, last heal
                                 outcome per engine (serve/heal.py)
    GET /das/coverage            the DAS coverage map: ?height= -> the
                                 per-coordinate sampled/verified/refused
                                 bitmap; no args -> per-height summary
                                 (serve/api.py coverage registry)
    GET /fleet                   merged cluster telemetry over the
                                 configured peers (trace/fleet.py):
                                 per-host rates + cross-host quantiles
    GET /device                  the device-attribution ledger
                                 (trace/device_ledger.py): per-program
                                 compile/dispatch stats, memory
                                 ownership + unattributed residual,
                                 applied autotuner seats, warmup state

/healthz is the SLO face: beyond {"status": "SERVING"}, any registered
health providers (a ServingNode registers its own snapshot: last block
height and age, mempool depth, peer count, consensus round state) report
under "layers" — the first place to look when blocks stop, before
touching the trace tables.  A provider that throws reports its error
instead of taking the probe down.
"""

from __future__ import annotations

import json
import threading

METRICS_CONTENT_TYPE = "text/plain; version=0.0.4"

_HEALTH_LOCK = threading.Lock()
_HEALTH_PROVIDERS: dict[str, object] = {}

_DAS_LOCK = threading.Lock()
_DAS_PROVIDER = None  # serve/api.DasProvider; last registration wins


def register_das_provider(provider) -> None:
    """Mount a serve/api.DasProvider behind GET /das/* on every plane.
    Last registration wins (one serving node per process answers DAS;
    multi-node test processes register explicitly per scenario)."""
    global _DAS_PROVIDER
    with _DAS_LOCK:
        _DAS_PROVIDER = provider


def unregister_das_provider(provider=None) -> None:
    """Remove the provider; with `provider` given, only if still the
    registered one (a stopped node must not unhook its replacement)."""
    global _DAS_PROVIDER
    with _DAS_LOCK:
        if provider is None or _DAS_PROVIDER is provider:
            _DAS_PROVIDER = None


def das_provider():
    with _DAS_LOCK:
        return _DAS_PROVIDER


def register_health_provider(name: str, provider) -> None:
    """Mount `provider()` (-> JSON-safe dict) under /healthz "layers".
    Last registration per name wins (one live node per name)."""
    with _HEALTH_LOCK:
        _HEALTH_PROVIDERS[name] = provider


def unregister_health_provider(name: str, provider=None) -> None:
    """Remove a provider; with `provider` given, only if it is still the
    registered one (a stopped node must not unhook its replacement)."""
    with _HEALTH_LOCK:
        if provider is None or _HEALTH_PROVIDERS.get(name) is provider:
            _HEALTH_PROVIDERS.pop(name, None)


def health_payload() -> dict:
    with _HEALTH_LOCK:
        providers = dict(_HEALTH_PROVIDERS)
    payload: dict = {"status": "SERVING"}
    # Degradation ladder state (chaos/degrade.py): a process whose device
    # path has been stepped down keeps serving — correctness is intact,
    # latency is not — so the probe stays green but SAYS SO, and an
    # orchestrator can schedule a restart to re-arm the fast path.
    from celestia_app_tpu.chaos.degrade import degraded_state

    degraded = degraded_state()
    if degraded:
        payload["status"] = "DEGRADED"
        payload["degraded"] = degraded
    # The SLO face: DEGRADED answers "is the device path stepped down",
    # the slo block answers "is the error budget burning" — one probe
    # distinguishes the two.  Read-only: the probe reports the LAST
    # evaluation, it never forces one.
    from celestia_app_tpu.trace.slo import engine

    payload["slo"] = engine().health_block()
    # The self-healing face (serve/heal.py): which heights are mid-heal,
    # which are quarantined, and the last heal's outcome — absent when no
    # HealingEngine is registered (detection without reaction).
    from celestia_app_tpu.serve.heal import heal_health_block

    heal = heal_health_block()
    if heal is not None:
        payload["heal"] = heal
    # The enforcement face (qos.py): configured per-tenant limits, tokens
    # remaining, throttle counts — absent when no $CELESTIA_QOS policy is
    # installed (presence means enforcement, like the heal block).
    from celestia_app_tpu import qos

    qos_block = qos.health_block()
    if qos_block is not None:
        payload["qos"] = qos_block
    if providers:
        layers = {}
        for name, provider in sorted(providers.items()):
            try:
                layers[name] = provider()
            except Exception as e:  # noqa: BLE001 — probe must stay up
                layers[name] = {"error": f"{type(e).__name__}: {e}"}
        payload["layers"] = layers
    return payload


_SCRAPE_TS_LOCK = threading.Lock()
_LAST_SCRAPE_TS: float | None = None


def _refresh_scrape_timestamp() -> None:
    """Refresh `celestia_scrape_timestamp_seconds` — the render-time
    wall clock a fleet aggregator uses to judge staleness of a cached or
    proxied exposition.  $CELESTIA_SCRAPE_TS_S rate-limits the refresh
    (default 0 = every render); byte-identity tests freeze it the same
    way they freeze $CELESTIA_SLO_TICK_S, since a wall-clock gauge is
    exactly the kind of state two sequential scrapes may disagree on."""
    import os
    import time

    from celestia_app_tpu.trace.metrics import registry

    global _LAST_SCRAPE_TS
    try:
        min_s = max(0.0, float(
            os.environ.get("CELESTIA_SCRAPE_TS_S", "") or 0.0
        ))
    except ValueError:
        min_s = 0.0
    now = time.time()
    with _SCRAPE_TS_LOCK:
        if _LAST_SCRAPE_TS is not None and now - _LAST_SCRAPE_TS < min_s:
            return
        _LAST_SCRAPE_TS = now
    registry().gauge(
        "celestia_scrape_timestamp_seconds",
        "unix time this exposition was rendered (scrape staleness "
        "marker for fleet aggregation)",
    ).set(now)


def metrics_payload() -> bytes:
    """The Prometheus exposition bytes — THE single renderer every plane
    serves, which is what makes cross-plane byte-identity structural
    rather than a test invariant."""
    from celestia_app_tpu.trace.metrics import registry

    _refresh_scrape_timestamp()
    return registry().render().encode()


#: Ceiling on /trace_tables/<name>?tail=N — matches the tracer's default
#: ring size; a larger ask is a whole-table pull, which the uncapped
#: endpoint already serves.
MAX_TAIL = 10_000


def _parse_tail(query: str):
    """The `tail` parameter of a /trace_tables/<name> query: (ok, value)
    where value is None when absent, else the capped int; ok=False means
    the parameter was present but not a positive integer (a 400)."""
    for pair in query.split("&"):
        if not pair.startswith("tail="):
            continue
        raw = pair[len("tail="):]
        if not raw.isdigit() or int(raw) < 1:
            return False, raw
        return True, min(int(raw), MAX_TAIL)
    return True, None


def _query_params(query: str) -> dict[str, str]:
    from urllib.parse import parse_qs

    return {k: v[0] for k, v in parse_qs(query).items() if v}


def _das_response(kind: str, query: str, plane: str):
    """GET /das/* -> the registered DasProvider's canonical payload bytes
    (serve/api.render — the SAME bytes the gRPC Das service carries), with
    gateway-shaped errors: 503 no provider, 400 bad params, 404 unknown
    height."""
    provider = das_provider()
    if provider is None:
        return 503, "application/json", json.dumps(
            {"error": "no DAS provider registered (serve/ plane not wired)"}
        ).encode()
    from celestia_app_tpu.serve.api import UnknownHeight, count_served, render
    from celestia_app_tpu.serve.heal import HealingInProgress
    from celestia_app_tpu.serve.sampler import BadProofDetected, ShareWithheld

    params = _query_params(query)
    try:
        if kind == "share_proof":
            payload = provider.share_proof_payload(
                int(params.get("height", "")),
                int(params.get("row", "")),
                int(params.get("col", "")),
                axis=params.get("axis", "row"),
            )
        elif kind == "attestation":
            payload = provider.attestation_payload(
                int(params.get("height", "")),
                params.get("samples", ""),
            )
        else:
            payload = provider.shares_payload(
                int(params.get("height", "")),
                params.get("namespace", ""),
            )
    except UnknownHeight as e:
        return 404, "application/json", json.dumps({"error": str(e)}).encode()
    except HealingInProgress as e:
        # 503 + Retry-After: the height is mid-heal (serve/heal.py) — a
        # RETRYABLE gap, never the terminal 410/502.  The body is a pure
        # function of the exception, so the JSON-RPC and REST twins stay
        # byte-identical; the gRPC Das service maps the same condition
        # to UNAVAILABLE.
        return (
            503,
            "application/json",
            json.dumps({
                "error": str(e),
                "healing": True,
                "retry_after_s": e.retry_after_s,
            }).encode(),
            {"Retry-After": str(max(1, int(-(-e.retry_after_s // 1))))},
        )
    except ShareWithheld as e:
        # 410 Gone: the share exists in the commitment but is being
        # withheld — the light client's detection signal, distinct from
        # 404 (height unknown) and 400 (bad request).
        return 410, "application/json", json.dumps(
            {"error": str(e), "detected": "withholding"}
        ).encode()
    except BadProofDetected as e:
        # 502: the committed root and the served square disagree — a
        # malformed-square / wrong-root attack caught at the
        # verification gate, never served as a valid proof.
        return 502, "application/json", json.dumps(
            {"error": str(e), "detected": "root_mismatch"}
        ).encode()
    except (TypeError, ValueError) as e:
        return 400, "application/json", json.dumps({"error": str(e)}).encode()
    except Exception as e:  # noqa: BLE001 — a proof fault must not kill the probe port
        from celestia_app_tpu.qos import (
            QosThrottled,
            retry_after_header,
            throttle_body,
        )

        if isinstance(e, QosThrottled):
            # 429 + Retry-After: a per-tenant proof-rate limit (qos.py)
            # refused this read.  The body is qos.py's ONE canonical
            # payload, so the JSON-RPC and REST GET /das twins stay
            # byte-identical; the gRPC Das service maps the same
            # condition to RESOURCE_EXHAUSTED carrying the same string.
            return (
                429,
                "application/json",
                throttle_body(e),
                {"Retry-After": retry_after_header(e)},
            )
        return 500, "application/json", json.dumps(
            {"error": f"{type(e).__name__}: {e}"}
        ).encode()
    count_served(plane, kind, payload)
    return 200, "application/json", render(payload)


def handle_observability_get(path: str, plane: str = "shared"):
    """Route an HTTP GET path; returns (status, content_type, body-bytes)
    or None when the path is not an observability endpoint (the caller
    falls through to its own routes / 404).  `plane` names the mounting
    plane for per-plane serving counters (the BODY never depends on it —
    byte-identity across planes is the contract)."""
    from celestia_app_tpu.trace.tracer import traced

    p, _, query = path.partition("?")
    if p != "/":
        p = p.rstrip("/")
    if p == "/das/share_proof":
        return _das_response("share_proof", query, plane)
    if p == "/das/shares":
        return _das_response("shares", query, plane)
    if p == "/das/attestation":
        return _das_response("attestation", query, plane)
    if p == "/das/coverage":
        from celestia_app_tpu.serve.api import coverage_response

        # A pure function of the coverage-map state (serve/api.py) —
        # byte-identical on every plane, like /heal.
        return coverage_response(_query_params(query))
    if p == "/fleet":
        from celestia_app_tpu.trace.fleet import fleet_response

        # The merged cluster view (trace/fleet.py); scrapes are
        # rate-limited by the aggregator interval, so planes asked
        # inside one round serve identical bytes.
        return fleet_response()
    if p == "/device":
        from celestia_app_tpu.trace.device_ledger import device_response

        # The device-attribution ledger (trace/device_ledger.py): a
        # snapshot refreshed at most once per $CELESTIA_DEVICE_TICK_S,
        # so planes asked inside one tick serve identical bytes.
        return device_response()
    if p == "/timeline":
        from celestia_app_tpu.trace.timeline import timeline_response

        # The per-height anatomy index (trace/timeline.py): a pure
        # function of retained row state — no ticks, no clocks at
        # render time — so every plane serves identical bytes.
        return timeline_response(_query_params(query))
    if p == "/metrics":
        return 200, METRICS_CONTENT_TYPE, metrics_payload()
    if p == "/healthz":
        return 200, "application/json", json.dumps(health_payload()).encode()
    if p == "/heal":
        from celestia_app_tpu.serve.heal import heal_payload

        # A pure function of registered-engine state: all planes serve
        # identical bytes (the /metrics pattern).
        return 200, "application/json", json.dumps(heal_payload()).encode()
    if p == "/namespaces":
        from celestia_app_tpu.trace import square_journal

        return 200, "application/json", json.dumps(
            square_journal.namespaces_payload()
        ).encode()
    if p == "/slo":
        from celestia_app_tpu.trace.slo import engine

        # One rate-limited evaluation per scrape window: the payload is a
        # pure function of the retained evaluation state, so planes
        # scraped inside one tick interval serve identical bytes.
        eng = engine()
        eng.maybe_tick()
        return 200, "application/json", json.dumps(eng.payload()).encode()
    if p == "/trace_tables":
        return 200, "application/json", json.dumps(
            {"tables": traced().row_counts()}
        ).encode()
    if p.startswith("/trace_tables/"):
        name = p[len("/trace_tables/"):]
        ok, tail = _parse_tail(query)
        if not ok:
            return 400, "application/json", json.dumps(
                {"error": f"tail must be a positive integer, got {tail!r}"}
            ).encode()
        tracer = traced()
        if name not in tracer.tables():
            return 404, "application/json", json.dumps(
                {"error": f"no trace table {name!r}"}
            ).encode()
        body = tracer.export_jsonl(name, tail=tail)
        return 200, "application/x-ndjson", (body + "\n").encode()
    return None


def handle_observability_get_adopted(handler, plane: str,
                                     node_id: str | None = None):
    """Route `handler`'s GET with cross-node trace adoption: when the
    request carries an `x-celestia-trace` header the serving process
    JOINS that trace (same trace_id, fresh span_id) and answers inside
    an `rpc_get` span — so a das_loadgen --url fetch or a peer's probe
    leaves spans rows HERE that stitch to the caller's own under one
    trace_id.  `node_id` overrides the process identity for multi-server
    test processes.  Headerless requests route exactly as before (no
    span minted for plain scrapes)."""
    from celestia_app_tpu.trace.context import (
        TRACE_HEADER,
        adopt_context,
        trace_span,
        use_context,
    )

    ctx = adopt_context(
        handler.headers.get(TRACE_HEADER),
        **({"node_id": node_id} if node_id else {}),
    )
    if ctx is None:
        return handle_observability_get(handler.path, plane=plane)
    with use_context(ctx):
        with trace_span(
            "rpc_get", ctx=ctx,
            path=handler.path.partition("?")[0], plane=plane,
        ) as attrs:
            resp = handle_observability_get(handler.path, plane=plane)
            attrs["status"] = resp[0] if resp is not None else 404
    return resp


def send_observability_response(handler, resp) -> None:
    """Write a handle_observability_get result through a
    BaseHTTPRequestHandler (the shape all three planes' handlers share).
    A result may carry an optional 4th element of extra headers (the
    healing-in-progress 503's Retry-After)."""
    status, content_type, body = resp[0], resp[1], resp[2]
    extra = resp[3] if len(resp) > 3 else {}
    handler.send_response(status)
    handler.send_header("Content-Type", content_type)
    handler.send_header("Content-Length", str(len(body)))
    for name, value in extra.items():
        handler.send_header(name, value)
    handler.end_headers()
    handler.wfile.write(body)


def send_observability_404(handler) -> None:
    """The shared not-found response for paths neither the observability
    surface nor the mounting plane routes.  Always carries
    Content-Length: a keep-alive scraper must never block on a
    length-less response waiting for a close that ThreadingHTTPServer
    does not send."""
    body = b'{"error":"not found"}'
    handler.send_response(404)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)


def serve_observability(host: str = "127.0.0.1", port: int = 0,
                        node_id: str | None = None, plane: str = "rest"):
    """A standalone HTTP mount of the shared observability surface —
    the das_loadgen --serve mini-node and the fleet tests' stub peers.
    GET-only; adoption-aware (handle_observability_get_adopted), with an
    optional per-SERVER `node_id` so several in-process servers emit
    distinguishable spans.  Returns an object with .url and .stop()."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _ObsHandler(BaseHTTPRequestHandler):
        _node_id = node_id
        _plane = plane

        def log_message(self, fmt, *args):  # quiet
            pass

        def do_GET(self):  # noqa: N802 — http.server API
            resp = handle_observability_get_adopted(
                self, plane=self._plane, node_id=self._node_id
            )
            if resp is None:
                send_observability_404(self)
                return
            send_observability_response(self, resp)

    httpd = ThreadingHTTPServer((host, port), _ObsHandler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()

    class _Server:
        def __init__(self):
            self.httpd = httpd
            self.port = httpd.server_address[1]
            self.url = f"http://{host}:{self.port}"

        def stop(self):
            httpd.shutdown()
            httpd.server_close()

    return _Server()

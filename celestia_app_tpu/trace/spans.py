"""OTLP-shaped span export + the end-to-end phase histogram.

Every finished `trace_span` (trace/context.py) lands here as one row in
the `spans` tracer table, shaped like an OTLP JSON span (camelCase ids,
stringified unix-nano timestamps, attributes as {key, value} pairs) so
standard trace tooling can ingest the JSONL verbatim:

    GET /trace_tables/spans          the live ring buffer, JSONL
    $CELESTIA_SPANS_OUT=<dir>        mirror every span to
                                     <dir>/spans-<pid>.jsonl as it closes

Filtering the table on `traceId` reconstructs one request/block tree:
submit -> mempool insert -> (wait) -> reap -> square build -> fused
dispatch -> DAH -> propose -> prevotes -> precommits -> commit.

`celestia_e2e_seconds{phase=...}` is the SLO face of the same data: each
lifecycle phase (submit, mempool_wait, reap, square_build, dispatch,
propose, prevote, precommit, commit, total) observes once per event onto
a single histogram family with request-scale buckets.

The file mirror never throws into a serving plane: the first write
failure disarms it for the process (the in-memory table keeps working).
"""

from __future__ import annotations

import json
import os
import threading

SPANS_TABLE = "spans"

# Request-scale buckets: sub-ms device spans up through multi-second
# consensus rounds and a mempool wait that spans several blocks.
E2E_SECONDS_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, 5.0, 15.0, 60.0,
)

# Phases that measure ONE request's own lifecycle and may therefore carry
# its namespace label.  Block-scoped phases (reap, square_build, dispatch,
# propose, ..., commit) run under the adopting block's context, whose
# baggage still holds the FIRST reaped tx's namespace — labeling them
# would bill whole-block time to whichever tenant reaped first and
# fragment the phase series by reap order, so the label is dropped here,
# at the single emission point, regardless of what baggage says.
E2E_TENANT_PHASES = frozenset({"submit", "mempool_wait", "total"})

_FILE_LOCK = threading.Lock()
_FILE_HANDLE = None
_FILE_DIR = None
_FILE_BROKEN = False


def spans_out_dir() -> str | None:
    """$CELESTIA_SPANS_OUT: directory for the JSONL span mirror (None =
    in-memory table only)."""
    return os.environ.get("CELESTIA_SPANS_OUT") or None


def record_span(
    name: str,
    ctx,
    start_unix_ns: int,
    end_unix_ns: int,
    attributes: dict,
) -> None:
    """Export one finished span: OTLP-shaped row into the spans table,
    plus the env-gated JSONL mirror."""
    from celestia_app_tpu.trace.tracer import traced

    row = {
        "name": name,
        "traceId": ctx.trace_id,
        "spanId": ctx.span_id,
        "parentSpanId": ctx.parent_id or "",
        "startTimeUnixNano": str(start_unix_ns),
        "endTimeUnixNano": str(end_unix_ns),
        "attributes": [
            {"key": k, "value": {"stringValue": str(v)}}
            for k, v in sorted(attributes.items())
            if v is not None
        ],
    }
    traced().write(SPANS_TABLE, **row)
    _mirror_to_file(row)


def observe_e2e(phase: str, seconds: float, namespace: str | None = None) -> None:
    """One observation on the end-to-end lifecycle histogram.  `namespace`
    (the submitting namespace from TraceContext baggage, when the request
    carried a blob) adds the per-tenant view on the request-scoped phases
    (E2E_TENANT_PHASES) — routed through the top-N cardinality cap
    (trace/square_journal.py) before it becomes a label."""
    from celestia_app_tpu.trace.metrics import registry
    from celestia_app_tpu.trace.tracer import trace_enabled

    if not trace_enabled():
        return
    labels = {"phase": phase}
    if namespace is not None and phase in E2E_TENANT_PHASES:
        from celestia_app_tpu.trace.square_journal import capped_namespace_label

        labels["namespace"] = capped_namespace_label(namespace)
    registry().histogram(
        "celestia_e2e_seconds",
        "end-to-end block/request lifecycle time by phase",
        buckets=E2E_SECONDS_BUCKETS,
    ).observe(seconds, **labels)


def _mirror_to_file(row: dict) -> None:
    global _FILE_HANDLE, _FILE_DIR, _FILE_BROKEN

    out_dir = spans_out_dir()
    if out_dir is None or _FILE_BROKEN:
        return
    try:
        line = json.dumps(row) + "\n"
        with _FILE_LOCK:
            if _FILE_HANDLE is None or _FILE_DIR != out_dir:
                os.makedirs(out_dir, exist_ok=True)
                if _FILE_HANDLE is not None:
                    _FILE_HANDLE.close()
                _FILE_HANDLE = open(
                    os.path.join(out_dir, f"spans-{os.getpid()}.jsonl"), "a"
                )
                _FILE_DIR = out_dir
            _FILE_HANDLE.write(line)
            _FILE_HANDLE.flush()
    except OSError:
        # Disk faults must never reach a serving plane; the in-memory
        # table is the durable-enough copy.
        _FILE_BROKEN = True


def span_attributes(row: dict) -> dict:
    """{key: stringValue} view of an OTLP-shaped span row (the test /
    analysis convenience for the attributes list)."""
    return {
        a["key"]: a["value"]["stringValue"]
        for a in row.get("attributes", [])
    }

"""Consensus round journal: one `round_journal` row per (height, round).

The RoundMachine (consensus/machine.py) stays pure — no sockets, no
clocks; it only tells this journal WHEN things happen (round open, step
transition, timeout fire, close).  The journal owns the clock (injectable
for deterministic tests) and writes the trace row on round close with:

  * the proposer and wall-clock step deltas (propose -> prevote ->
    precommit -> close);
  * prevote/precommit power fractions for the round that closed (or, on
    a decide, the round whose tally decided);
  * which step timeouts fired;
  * the WAL append+fsync time the round paid (`fsync_ms_source` reads
    consensus/wal.VoteWAL.fsync_ms_total, the delta is per round);
  * the block's trace_id when the driver knows it (proposer side:
    adopted from the first reaped tx — rpc/gossip.py).

This module lives under trace/ (not consensus/) so it imports without
the signing stack: it duck-types the machine and pins the two vote-type
ints locally.
"""

from __future__ import annotations

# Pinned to consensus.votes.PREVOTE/PRECOMMIT — importing them would pull
# the signing stack into slim images where this journal must still load.
PREVOTE_TYPE = 1
PRECOMMIT_TYPE = 2

# Step names, pinned to consensus.machine.PROPOSE/PREVOTE_STEP/PRECOMMIT_STEP.
PROPOSE_STEP_NAME = "propose"
PREVOTE_STEP_NAME = "prevote"
PRECOMMIT_STEP_NAME = "precommit"


class RoundJournal:
    TABLE = "round_journal"

    def __init__(self, clock=None, fsync_ms_source=None):
        import time as _time

        self.clock = clock or _time.monotonic
        self.fsync_ms_source = fsync_ms_source
        self.trace_id: str | None = None
        self._row: dict | None = None

    def _fsync_ms(self) -> float:
        return float(self.fsync_ms_source()) if self.fsync_ms_source else 0.0

    def open_round(self, machine) -> None:
        # trace_id is per round: the driver re-stamps it when THIS node's
        # proposal is the one in play (rpc/gossip._propose_locked runs
        # after the round opens); without the reset, rounds proposed by
        # other validators would inherit a stale trace.
        self.trace_id = None
        self._row = {
            "height": machine.height,
            "round": machine.round,
            "proposer": machine.proposer(machine.round),
            "t0": self.clock(),
            "steps": {PROPOSE_STEP_NAME: 0.0},
            "timeouts": [],
            "fsync0": self._fsync_ms(),
        }

    def record_step(self, machine, step: str) -> None:
        row = self._row
        if row is None or machine.round != row["round"]:
            return
        row["steps"].setdefault(step, (self.clock() - row["t0"]) * 1e3)

    def record_timeout(self, machine, round: int, step: str) -> None:
        from celestia_app_tpu.trace.metrics import registry

        registry().counter(
            "celestia_consensus_timeouts_total",
            "consensus step timeouts that fired and acted",
        ).inc(step=step)
        row = self._row
        if row is not None and round == row["round"]:
            row["timeouts"].append(step)

    def close_round(self, machine, reason: str, round: int | None = None) -> None:
        """Write the (height, round) row; `reason` is decided|round_bump.
        For a decide in an EARLIER round than the open one, `round` names
        the round whose tallies decided."""
        from celestia_app_tpu.trace.metrics import registry
        from celestia_app_tpu.trace.tracer import traced

        row, self._row = self._row, None
        if row is None:
            return
        tally_round = row["round"] if round is None else round
        total_ms = (self.clock() - row["t0"]) * 1e3
        steps = row["steps"]
        prevote_at = steps.get(PREVOTE_STEP_NAME)
        precommit_at = steps.get(PRECOMMIT_STEP_NAME)
        prevotes = machine._tally(machine.prevotes, tally_round, PREVOTE_TYPE)
        precommits = machine._tally(
            machine.precommits, tally_round, PRECOMMIT_TYPE
        )
        total_power = prevotes.total_power() or 1
        traced().write(
            self.TABLE,
            height=row["height"],
            round=row["round"],
            proposer=row["proposer"],
            result=reason,
            trace_id=self.trace_id,
            propose_ms=prevote_at,
            prevote_ms=(
                precommit_at - prevote_at
                if prevote_at is not None and precommit_at is not None
                else None
            ),
            precommit_ms=(
                total_ms - precommit_at if precommit_at is not None else None
            ),
            total_ms=total_ms,
            timeouts=row["timeouts"],
            prevote_power=prevotes.power_any() / total_power,
            precommit_power=precommits.power_any() / total_power,
            wal_fsync_ms=self._fsync_ms() - row["fsync0"],
        )
        registry().histogram(
            "celestia_consensus_round_seconds",
            "consensus round wall time by outcome",
        ).observe(total_ms / 1e3, result=reason)

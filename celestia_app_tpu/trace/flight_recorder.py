"""Anomaly flight recorder: black-box capture at the moment of failure.

The trace tables are ring buffers: by the time an operator asks "what
happened around the breaker trip three hours ago", the journal rows that
explain it have been evicted.  This module is the aircraft-style black
box: when an anomaly TRIGGER fires —

    breaker_trip      chaos/degrade.py: the device ladder stepped down
    parity_mismatch   da/eds.py: the fused-vs-staged sentinel diverged
    worker_death      parallel/pipeline.py: an uploader/dispatcher died
    wal_salvage       consensus/wal.py: replay dropped a torn tail
    slo_fast_burn     trace/slo.py: an SLO entered fast-burn (a page)
    root_mismatch     da/repair.py: repair rejected an inconsistent
                      survivor set or a square that contradicts its DAH
                      (the wrong-root / malformed-square attack face)
    withholding_detected  serve/sampler.py: a DAS sample hit a withheld
                      share (the data-withholding attack face)
    heal_completed    serve/heal.py: the detect->repair->re-serve loop
                      recovered a height (context carries the per-phase
                      latencies — the moment the node healed itself)
    heal_quarantined  serve/heal.py: a heal exhausted its retry budget
                      or the height is below the k-survivor threshold —
                      the height is quarantined, operator input needed
    fleet_fast_burn   trace/fleet.py: the MERGED cross-host burn rate of
                      an SLO crossed the paging threshold (context
                      carries peers' recent bundle indexes so the fleet
                      bundle points at the per-node black boxes)
    device_residual_growth  trace/device_ledger.py: the unattributed
                      memory residual (measured high-water minus every
                      claimed owner) grew for N consecutive
                      reconciliations — the leak signature

— `note_trigger` atomically dumps one JSON bundle under
$CELESTIA_FLIGHT_DIR: the last-N rows of EVERY trace table, the
degradation/chaos/SLO state, and the /healthz payload, all stamped with
the trigger and its context.  Atomic = write to a dot-tmp file then
os.replace, so a reader (scripts/slo_report.py) never sees a torn
bundle.

Rate-limited per trigger ($CELESTIA_FLIGHT_MIN_INTERVAL_S, default 30s):
a flapping fault produces `celestia_flight_dumps_suppressed_total`
ticks, not unbounded disk writes.  Unset $CELESTIA_FLIGHT_DIR disables
the recorder entirely (the default — tests and embedded uses opt in).

`note_trigger` NEVER raises: it is called from the device dispatch
path, worker-death handlers, and WAL replay — a diagnostic layer that
can take down the thing it is diagnosing is worse than no layer at all.
"""

from __future__ import annotations

import json
import os
import threading
import time

TRIGGERS = (
    "breaker_trip",
    "parity_mismatch",
    "worker_death",
    "wal_salvage",
    "slo_fast_burn",
    "root_mismatch",
    "withholding_detected",
    "heal_completed",
    "heal_quarantined",
    "fleet_fast_burn",
    "device_residual_growth",
)

#: Hard ceiling on per-table tail rows in a bundle.
MAX_TAIL_ROWS = 2000

_LOCK = threading.Lock()
_LAST_DUMP: dict[str, float] = {}  # trigger -> monotonic time of last dump
_SEQ = 0  # per-process bundle sequence (uniqueness within one ns tick)
#: Recent successful dumps, NOT $CELESTIA_TRACE-gated (the gated
#: flight_dump trace row vanishes when tracing is muted, but a dump that
#: happened must stay observable — drills measure time-to-detection from
#: this log).  Bounded; oldest evicted.
_RECENT: list[dict] = []
_RECENT_MAX = 256


def flight_dir() -> str | None:
    """$CELESTIA_FLIGHT_DIR: bundle directory (unset = recorder off)."""
    return os.environ.get("CELESTIA_FLIGHT_DIR") or None


def min_interval_s() -> float:
    """$CELESTIA_FLIGHT_MIN_INTERVAL_S: per-trigger dump rate limit
    (default 30s; 0 disables suppression — test/drill setting)."""
    try:
        return max(0.0, float(
            os.environ.get("CELESTIA_FLIGHT_MIN_INTERVAL_S", "") or 30.0
        ))
    except ValueError:
        return 30.0


def tail_rows() -> int:
    """$CELESTIA_FLIGHT_TAIL: rows captured per trace table (default
    200, capped at MAX_TAIL_ROWS)."""
    try:
        n = int(os.environ.get("CELESTIA_FLIGHT_TAIL", "") or 200)
    except ValueError:
        return 200
    return max(1, min(n, MAX_TAIL_ROWS))


def _dumps_counter():
    from celestia_app_tpu.trace.metrics import registry

    return registry().counter(
        "celestia_flight_dumps_total",
        "flight-recorder bundles written, by trigger",
    )


def _suppressed_counter():
    from celestia_app_tpu.trace.metrics import registry

    return registry().counter(
        "celestia_flight_dumps_suppressed_total",
        "flight dumps suppressed by the per-trigger rate limit "
        "(a flapping fault must not fill the disk)",
    )


def _failed_counter():
    from celestia_app_tpu.trace.metrics import registry

    return registry().counter(
        "celestia_flight_dumps_failed_total",
        "flight dump attempts that failed to capture or write",
    )


def note_trigger(trigger: str, **context) -> str | None:
    """Capture one bundle for `trigger`; returns the bundle path, or
    None when the recorder is disabled, the trigger is rate-limited, or
    the capture failed.  Never raises (see module docstring)."""
    try:
        return _note_trigger(trigger, context)
    except Exception:
        # A diagnostic layer must never take down the layer it watches.
        try:
            _failed_counter().inc(trigger=trigger)
        except Exception:
            pass
        return None


def _note_trigger(trigger: str, context: dict) -> str | None:
    global _SEQ

    out_dir = flight_dir()
    if out_dir is None:
        return None
    now = time.monotonic()
    with _LOCK:
        last = _LAST_DUMP.get(trigger)
        interval = min_interval_s()
        if last is not None and interval > 0 and now - last < interval:
            _SEQ += 1  # keep filenames unique even across suppression
            suppressed = True
        else:
            # Claim the slot now (concurrent callers of the same trigger
            # suppress against it) ...
            _LAST_DUMP[trigger] = now
            _SEQ += 1
            seq = _SEQ
            suppressed = False
    if suppressed:
        _suppressed_counter().inc(trigger=trigger)
        return None
    try:
        bundle = capture(trigger, context)
        os.makedirs(out_dir, exist_ok=True)
        ts_ns = bundle["captured_unix_ns"]
        # node_id in the name: N nodes of one drill share a
        # $CELESTIA_FLIGHT_DIR without colliding, and peer_bundle_index
        # attributes bundles by filename alone.  ts_ns and seq stay the
        # LAST two fields (slo_report sorts on split("-")[-2]).
        name = f"flight-{trigger}-{bundle['node_id']}-{ts_ns}-{seq}.json"
        tmp = os.path.join(out_dir, f".tmp-{name}")
        path = os.path.join(out_dir, name)
        with open(tmp, "w", encoding="utf-8") as f:
            # default=repr: one exotic value in a trace row must not
            # cost the whole bundle.
            json.dump(bundle, f, sort_keys=True, default=repr)
            f.write("\n")
        os.replace(tmp, path)  # atomic: readers never see a torn bundle
    except Exception:
        # ... but release it on failure: a transient disk fault must not
        # silently consume the trigger's budget with no bundle on disk —
        # the NEXT firing should retry, not be suppressed.
        with _LOCK:
            if _LAST_DUMP.get(trigger) == now:
                if last is None:
                    _LAST_DUMP.pop(trigger, None)
                else:
                    _LAST_DUMP[trigger] = last
        raise
    _dumps_counter().inc(trigger=trigger)
    with _LOCK:
        _RECENT.append(
            {"trigger": trigger, "path": path, "ts_ns": ts_ns}
        )
        del _RECENT[:-_RECENT_MAX]
    from celestia_app_tpu.trace.tracer import traced

    traced().write("flight_dump", trigger=trigger, path=path, **{
        k: v for k, v in context.items() if isinstance(v, (str, int, float))
    })
    return path


def recent_dumps(since_ns: int = 0, trigger: str | None = None) -> list[dict]:
    """Successful dumps at/after `since_ns` (unix ns), oldest first,
    optionally filtered by trigger.  Unlike the `flight_dump` trace row
    this log ignores $CELESTIA_TRACE — a bundle that was written is a
    fact about the disk, not about tracing."""
    with _LOCK:
        return [
            dict(d) for d in _RECENT
            if d["ts_ns"] >= since_ns
            and (trigger is None or d["trigger"] == trigger)
        ]


def capture(trigger: str, context: dict | None = None) -> dict:
    """Assemble the bundle dict (separated from the write so tests and
    slo_report can inspect the capture shape without touching disk)."""
    from celestia_app_tpu import chaos
    from celestia_app_tpu.chaos.degrade import degraded_state
    from celestia_app_tpu.serve.api import coverage_snapshot
    from celestia_app_tpu.trace.device_ledger import snapshot as device_snapshot
    from celestia_app_tpu.trace import slo, square_journal
    from celestia_app_tpu.trace.context import node_id
    from celestia_app_tpu.trace.exposition import health_payload
    from celestia_app_tpu.trace.timeline import timeline
    from celestia_app_tpu.trace.tracer import traced

    tracer = traced()
    n = tail_rows()
    tables = {name: tracer.tail(name, n) for name in tracer.tables()}
    inj = chaos.injector()
    bundle = {
        "trigger": trigger,
        "context": _jsonable(context or {}),
        "captured_unix_ns": time.time_ns(),
        "pid": os.getpid(),
        "node_id": node_id(),
        "healthz": health_payload(),
        "slo": slo.engine().payload(),
        "degraded": degraded_state(),
        "chaos_spec": getattr(inj, "raw", "") if inj is not None else "",
        "namespaces": square_journal.namespaces_payload(),
        # The DAS coverage summary (serve/api.py): which retained
        # heights had how much of their square decided when the anomaly
        # fired — the withholding drill's context in one block.
        "coverage": coverage_snapshot(),
        # The device-attribution ledger (trace/device_ledger.py): what
        # was compiled/resident and who owned the bytes at the moment of
        # failure — a FRESH snapshot, not the rate-limited /device cache.
        "device": device_snapshot(),
        # The height-anatomy timeline (trace/timeline.py): the last-N
        # per-height critical paths plus the latest full record — what
        # phase the node was spending its height time on when the
        # anomaly fired (slo_report renders this block).
        "timeline": timeline().bundle_block(tail=8),
        "tail_rows": n,
        "tables": tables,
    }
    return bundle


def _jsonable(obj):
    """Best-effort JSON-safe view of trigger context (exception reprs,
    numpy scalars, arbitrary tags)."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return repr(obj)


def peer_bundle_index(limit_per_node: int = 8) -> dict:
    """Recent bundles OTHER nodes dropped in this process's
    $CELESTIA_FLIGHT_DIR, grouped by the node_id parsed from the
    filename (`flight-<trigger>-<node_id>-<ts_ns>-<seq>.json`) — in a
    local multi-node drill all nodes share one dir, so a fleet
    fast-burn bundle can point at every peer's own black box without a
    network fetch.  Newest `limit_per_node` per node; never raises
    (unreadable dir -> empty index)."""
    from celestia_app_tpu.trace.context import node_id as own_node_id

    out_dir = flight_dir()
    if out_dir is None:
        return {}
    own = own_node_id()
    by_node: dict[str, list] = {}
    try:
        names = sorted(os.listdir(out_dir))
    except OSError:
        return {}
    for name in names:
        if not (name.startswith("flight-") and name.endswith(".json")):
            continue
        parts = name[:-len(".json")].split("-")
        # flight / trigger / node_id (may itself contain dashes) / ts / seq
        if len(parts) < 5:
            continue  # pre-node_id bundle name: no node to attribute
        node, ts_raw = "-".join(parts[2:-2]), parts[-2]
        if not ts_raw.isdigit() or node == own:
            continue
        by_node.setdefault(node, []).append(
            {"name": name, "trigger": parts[1], "ts_ns": int(ts_raw)}
        )
    return {
        node: sorted(dumps, key=lambda d: d["ts_ns"])[-limit_per_node:]
        for node, dumps in sorted(by_node.items())
    }


def _reset_for_tests() -> None:
    """Drop the per-trigger rate-limit clocks + the recent-dump log
    (test isolation)."""
    with _LOCK:
        _LAST_DUMP.clear()
        _RECENT.clear()

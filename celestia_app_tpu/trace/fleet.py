"""Fleet aggregator: one merged observability view over N peers.

Every telemetry surface the repo grew — /metrics, /healthz, /slo, /heal
— is process-private: a 3-node drill means three browser tabs and
hand-merged quantiles.  This module is the fleet face: an aggregator
scrapes each peer's observability port on an interval, merges what
composes —

  * counters by SUMMATION (fleet proofs served = sum of per-host
    cumulative counters; per-host rates from successive scrape deltas),
  * histograms by BUCKET-WISE merge (`Histogram.merge`, exact at bucket
    resolution — cross-host p99 comes from summed bucket counts, never
    from averaging per-host quantiles),
  * SLO burn from the MERGED histogram delta between the last two
    scrape rounds, budget-normalized against the same SLOSpec the
    per-node engine judges (the fleet "fast window" is the scrape
    interval),

— and reports what doesn't (per-host degraded rung, quarantined
heights, QoS throttle counts) side by side.  A peer that stops
answering is never silently dropped: its row stays in the payload with
`reachable: false` + the error, and `celestia_fleet_peer_unreachable`
marks it for alerting — absence of data is itself a datum.

`GET /fleet` rides the shared exposition handler on all three planes;
the payload is a pure function of the aggregator's last merged state
(scrapes are rate-limited by the interval, like /slo's maybe_tick), so
cross-plane byte-identity is structural here too.

Configuration: `configure([urls], interval_s=...)` explicitly, or
`$CELESTIA_FLEET_PEERS` (comma-separated base URLs) +
`$CELESTIA_FLEET_INTERVAL_S` lazily on the first /fleet request.

On a fleet fast-burn page (merged burn >= the spec's paging threshold)
the aggregator drops a `fleet_fast_burn` flight bundle whose context
carries `peer_bundle_index()` — the per-node black boxes of a shared
$CELESTIA_FLIGHT_DIR, attributable by filename since bundles are
node_id-stamped.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time

from celestia_app_tpu.trace.metrics import Histogram, HistogramSnapshot

#: Routes this module publishes on the shared exposition handler
#: (trace_lint rule 7: every one must have a README endpoint-table row).
FLEET_ROUTES = ("/fleet", "/das/coverage")

#: The peer paths one scrape round pulls.
SCRAPE_PATHS = ("/metrics", "/healthz", "/slo", "/heal", "/device",
                "/timeline")

DEFAULT_INTERVAL_S = 5.0
DEFAULT_TIMEOUT_S = 2.0

_SAMPLE_RE = re.compile(r"^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*)\})?$")
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="([^"]*)"')


def parse_prometheus_text(text: str):
    """Parse one /metrics exposition (the trace/metrics.py dialect:
    no escaped quotes or spaces inside label values) into

        (kinds, scalars, histograms)

    where `kinds` maps family -> counter/gauge/histogram, `scalars` maps
    counter/gauge family -> {sorted-label-tuple: value} (the Counter
    children key shape), and `histograms` maps family ->
    HistogramSnapshot rebuilt from the cumulative _bucket lines (counts
    de-cumulated per child, +Inf tail restored) — the merge-ready form
    `Histogram.merge` consumes."""
    kinds: dict[str, str] = {}
    scalars: dict[str, dict[tuple, float]] = {}
    raw_hists: dict[str, dict[tuple, dict]] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            if line.startswith("# TYPE "):
                parts = line.split()
                if len(parts) >= 4:
                    kinds[parts[2]] = parts[3]
            continue
        name_part, _, value_part = line.rpartition(" ")
        try:
            value = float(value_part)
        except ValueError:
            continue
        m = _SAMPLE_RE.match(name_part)
        if m is None:
            continue
        name, labels_raw = m.group(1), m.group(2) or ""
        labels = dict(_LABEL_RE.findall(labels_raw))
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            cand = name[:-len(suffix)] if name.endswith(suffix) else None
            if cand and kinds.get(cand) == "histogram":
                base, part = cand, suffix
                break
        if base is not None:
            le = labels.pop("le", None)
            key = tuple(sorted(labels.items()))
            child = raw_hists.setdefault(base, {}).setdefault(
                key, {"cum": {}, "sum": 0.0}
            )
            if part == "_bucket" and le is not None:
                child["cum"][
                    float("inf") if le == "+Inf" else float(le)
                ] = value
            elif part == "_sum":
                child["sum"] = value
            continue
        scalars.setdefault(name, {})[tuple(sorted(labels.items()))] = value
    hists: dict[str, HistogramSnapshot] = {}
    for name, children in raw_hists.items():
        bounds = sorted({
            b for ch in children.values() for b in ch["cum"]
            if b != float("inf")
        })
        buckets = tuple(bounds)
        snap_children = {}
        for key, ch in children.items():
            counts, prev = [], 0.0
            for b in buckets:
                cum = ch["cum"].get(b, prev)
                counts.append(max(0, int(round(cum - prev))))
                prev = cum
            tail = ch["cum"].get(float("inf"), prev)
            counts.append(max(0, int(round(tail - prev))))
            snap_children[key] = (counts, ch["sum"])
        hists[name] = HistogramSnapshot(buckets, snap_children)
    return kinds, scalars, hists


def _sum_family(scalars: dict, name: str) -> float:
    return float(sum(scalars.get(name, {}).values()))


def _round6(v):
    return None if v is None else round(float(v), 6)


def _http_fetch(url: str, path: str, timeout_s: float) -> str:
    import urllib.request

    with urllib.request.urlopen(url + path, timeout=timeout_s) as resp:
        return resp.read().decode()


class FleetAggregator:
    """Scrapes `peers` and keeps the last two merged rounds (rates and
    SLO deltas need a window).  `fetch(url, path) -> text` is the test
    seam; the default is urllib with a per-request timeout."""

    def __init__(self, peers, interval_s: float | None = None,
                 timeout_s: float = DEFAULT_TIMEOUT_S, fetch=None):
        self.peers = tuple(peers)
        self.interval_s = (
            float(interval_s) if interval_s is not None else DEFAULT_INTERVAL_S
        )
        self.timeout_s = timeout_s
        self._fetch = fetch or (
            lambda url, path: _http_fetch(url, path, self.timeout_s)
        )
        self._lock = threading.RLock()
        self._rounds: list[dict] = []  # last two scrape rounds
        self._state: dict | None = None
        self._last_scrape: float | None = None  # monotonic
        self._burning: set[str] = set()  # fleet-fast-burning SLO names

    # --- scraping -----------------------------------------------------------
    def _scrape_peer(self, url: str) -> dict:
        try:
            metrics_text = self._fetch(url, "/metrics")
            healthz = json.loads(self._fetch(url, "/healthz"))
            slo = json.loads(self._fetch(url, "/slo"))
            heal = json.loads(self._fetch(url, "/heal"))
        except Exception as e:  # noqa: BLE001 — a dead peer is a DATUM
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        try:
            # A peer predating the device ledger still merges — its host
            # row just carries no device block (rolling-upgrade safety).
            device = json.loads(self._fetch(url, "/device"))
        except Exception:  # noqa: BLE001 — optional surface
            device = None
        try:
            # Same rolling-upgrade stance for the height timeline.
            timeline = json.loads(self._fetch(url, "/timeline"))
        except Exception:  # noqa: BLE001 — optional surface
            timeline = None
        kinds, scalars, hists = parse_prometheus_text(metrics_text)
        return {
            "ok": True,
            "kinds": kinds,
            "scalars": scalars,
            "hists": hists,
            "healthz": healthz,
            "slo": slo,
            "heal": heal,
            "device": device,
            "timeline": timeline,
        }

    def scrape(self) -> dict:
        """One full round over every peer, then re-merge.  Returns the
        merged state (also retained for payload())."""
        mono = time.monotonic()
        wall_ms = int(time.time() * 1000)
        round_data: dict = {"mono": mono, "wall_ms": wall_ms, "peers": {}}
        for url in self.peers:
            round_data["peers"][url] = self._scrape_peer(url)
        with self._lock:
            self._rounds.append(round_data)
            del self._rounds[:-2]
            self._last_scrape = mono
            state = self._merge_locked()
            self._state = state
        self._publish(state)
        self._maybe_page(state)
        return state

    def maybe_scrape(self) -> None:
        """Scrape at most once per interval — the /slo maybe_tick
        pattern, which is what keeps GET /fleet pure (and byte-identical
        across planes) between rounds."""
        with self._lock:
            due = (
                self._last_scrape is None
                or time.monotonic() - self._last_scrape >= self.interval_s
            )
        if due:
            self.scrape()

    # --- merging ------------------------------------------------------------
    def _merge_locked(self) -> dict:
        cur = self._rounds[-1]
        prev = self._rounds[-2] if len(self._rounds) > 1 else None
        dt = (cur["mono"] - prev["mono"]) if prev is not None else None
        hosts: dict = {}
        ok_urls = []
        for url in self.peers:
            d = cur["peers"][url]
            if not d["ok"]:
                hosts[url] = {
                    "reachable": False,
                    "peer_unreachable": True,
                    "error": d["error"],
                }
                continue
            ok_urls.append(url)
            proofs_total = _sum_family(d["scalars"],
                                       "celestia_proofs_served_total")
            per_s = None
            if prev is not None and dt and prev["peers"][url]["ok"]:
                prev_total = _sum_family(
                    prev["peers"][url]["scalars"],
                    "celestia_proofs_served_total",
                )
                per_s = max(0.0, proofs_total - prev_total) / dt
            quarantined = sorted({
                h
                for eng in d["heal"].get("engines", {}).values()
                for h in (eng.get("quarantined") or {})
            })
            hosts[url] = {
                "reachable": True,
                "peer_unreachable": False,
                "status": d["healthz"].get("status"),
                "degraded": d["healthz"].get("degraded") or {},
                "proofs_served_total": proofs_total,
                "proofs_per_s": _round6(per_s),
                "qos_throttled_total": _sum_family(
                    d["scalars"], "celestia_qos_throttled_total"
                ),
                "quarantined_heights": quarantined,
                "slo": {
                    name: {"state": s.get("state"), "burn": s.get("burn")}
                    for name, s in d["slo"].get("slos", {}).items()
                },
            }
            dev = d.get("device")
            if dev is not None:
                own = dev.get("ownership") or {}
                hosts[url]["device"] = {
                    "programs": len(dev.get("programs") or []),
                    "programs_resident": sum(
                        (dev.get("programs_resident") or {}).values()
                    ),
                    "owned_bytes": own.get("owned_bytes"),
                    "measured_bytes": own.get("measured_bytes"),
                    "unattributed_residual": own.get("unattributed_residual"),
                }
            from celestia_app_tpu.trace.timeline import fleet_block

            tl = fleet_block(d.get("timeline"))
            if tl is not None:
                hosts[url]["timeline"] = tl

        def merged_hist(round_data, name):
            return Histogram.merge([
                round_data["peers"][u]["hists"][name]
                for u in self.peers
                if round_data["peers"][u]["ok"]
                and name in round_data["peers"][u]["hists"]
            ])

        lat = merged_hist(cur, "celestia_proof_latency_seconds")
        fleet: dict = {
            "hosts_total": len(self.peers),
            "hosts_reachable": len(ok_urls),
            "proofs_served_total": sum(
                hosts[u]["proofs_served_total"] for u in ok_urls
            ),
            "proof_latency": {
                "p50_s": _round6(lat.quantile(0.5, phase="total")),
                "p99_s": _round6(lat.quantile(0.99, phase="total")),
                "samples": lat.count(phase="total"),
            },
            # Device-attribution rollup across hosts that serve /device:
            # the cluster's resident-program count and claimed-vs-slack
            # bytes in one block (per-host detail in hosts[url]["device"]).
            "device": {
                "programs_resident": sum(
                    hosts[u]["device"]["programs_resident"]
                    for u in ok_urls if "device" in hosts[u]
                ),
                "owned_bytes": sum(
                    hosts[u]["device"]["owned_bytes"] or 0
                    for u in ok_urls if "device" in hosts[u]
                ),
                "unattributed_residual": sum(
                    hosts[u]["device"]["unattributed_residual"] or 0
                    for u in ok_urls if "device" in hosts[u]
                ),
                "hosts_reporting": sum(
                    1 for u in ok_urls if "device" in hosts[u]
                ),
            },
        }
        # Fleet-level SLO burn: the per-node engine's own quantile specs
        # judged over the MERGED bucket delta of the last scrape window.
        # Budget-normalized exactly like trace/slo.py (burn 1.0 =
        # consuming error budget exactly), the window being the scrape
        # interval — a fleet-wide fast window.
        from celestia_app_tpu.trace.slo import engine

        slo_block: dict = {}
        if prev is not None and dt:
            for spec in engine().specs:
                if spec.kind != "quantile":
                    continue
                try:
                    now_snap = merged_hist(cur, spec.metric)
                    prev_snap = merged_hist(prev, spec.metric)
                except ValueError:
                    continue  # peers disagree on bucket layout: skip
                if not now_snap.children:
                    continue
                delta = (
                    now_snap.delta(prev_snap)
                    if prev_snap.children else now_snap
                )
                bad = delta.fraction_over(
                    spec.threshold, **dict(spec.labels)
                )
                if bad is None:
                    continue
                burn = bad / spec.effective_budget()
                slo_block[spec.name] = {
                    "burn": _round6(burn),
                    "window_s": _round6(dt),
                    "paging": burn >= spec.fast_burn,
                }
        fleet["slo"] = slo_block
        return {
            "node_id": _own_node_id(),
            "scraped_unix_ms": cur["wall_ms"],
            "interval_s": self.interval_s,
            "hosts": hosts,
            "fleet": fleet,
        }

    # --- exports ------------------------------------------------------------
    def _publish(self, state: dict) -> None:
        """The celestia_fleet_* families — the merged view in the same
        exposition the per-node families live in."""
        from celestia_app_tpu.trace.metrics import registry

        reg = registry()
        hosts = state["hosts"]
        reachable = sum(1 for h in hosts.values() if h["reachable"])
        peers_g = reg.gauge(
            "celestia_fleet_peers",
            "configured fleet peers by scrape outcome",
        )
        peers_g.set(float(reachable), state="reachable")
        peers_g.set(float(len(hosts) - reachable), state="unreachable")
        unreachable_g = reg.gauge(
            "celestia_fleet_peer_unreachable",
            "1 when the last scrape of this peer failed (staleness "
            "marker: the host row is stale, not silently dropped)",
        )
        per_s_g = reg.gauge(
            "celestia_fleet_proofs_per_s",
            "per-host proofs served per second over the last scrape "
            "window",
        )
        quarantined_g = reg.gauge(
            "celestia_fleet_quarantined_heights",
            "per-host count of quarantined heights (serve/heal.py)",
        )
        throttled_g = reg.gauge(
            "celestia_fleet_qos_throttled_total",
            "per-host cumulative QoS refusals as last scraped",
        )
        for url, h in hosts.items():
            unreachable_g.set(
                0.0 if h["reachable"] else 1.0, peer=url
            )
            if not h["reachable"]:
                continue
            if h["proofs_per_s"] is not None:
                per_s_g.set(h["proofs_per_s"], peer=url)
            quarantined_g.set(
                float(len(h["quarantined_heights"])), peer=url
            )
            throttled_g.set(h["qos_throttled_total"], peer=url)
        lat = state["fleet"]["proof_latency"]
        lat_g = reg.gauge(
            "celestia_fleet_proof_latency_seconds",
            "cross-host DAS proof latency quantiles off the bucket-"
            "merged per-host histograms",
        )
        for q in ("p50_s", "p99_s"):
            if lat[q] is not None:
                lat_g.set(lat[q], q=q[:-2])
        burn_g = reg.gauge(
            "celestia_fleet_slo_burn_rate",
            "budget-normalized fleet burn per SLO over the merged "
            "scrape-window delta",
        )
        for name, s in state["fleet"]["slo"].items():
            if s["burn"] is not None:
                burn_g.set(s["burn"], slo=name)

    def _maybe_page(self, state: dict) -> None:
        """Edge-detect fleet fast burn and drop ONE bundle per
        transition, its context pointing at the peers' own bundles."""
        from celestia_app_tpu.trace.flight_recorder import (
            note_trigger,
            peer_bundle_index,
        )

        paging = {
            name for name, s in state["fleet"]["slo"].items() if s["paging"]
        }
        with self._lock:
            new = paging - self._burning
            self._burning = paging
        for name in sorted(new):
            note_trigger(
                "fleet_fast_burn",
                slo=name,
                burn=state["fleet"]["slo"][name]["burn"],
                hosts_reachable=state["fleet"]["hosts_reachable"],
                peer_bundles=peer_bundle_index(),
            )

    def payload(self) -> dict:
        """The last merged state (scrape() first if none yet) — what
        GET /fleet renders."""
        with self._lock:
            state = self._state
        return state if state is not None else self.scrape()


def _own_node_id() -> str:
    from celestia_app_tpu.trace.context import node_id

    return node_id()


_AGG_LOCK = threading.Lock()
_AGGREGATOR: FleetAggregator | None = None


def configure(peers, interval_s: float | None = None,
              timeout_s: float = DEFAULT_TIMEOUT_S,
              fetch=None) -> FleetAggregator:
    """Install the process's aggregator (last call wins); returns it."""
    global _AGGREGATOR
    agg = FleetAggregator(peers, interval_s=interval_s,
                          timeout_s=timeout_s, fetch=fetch)
    with _AGG_LOCK:
        _AGGREGATOR = agg
    return agg


def aggregator() -> FleetAggregator | None:
    """The installed aggregator, lazily built from $CELESTIA_FLEET_PEERS
    on first ask; None when the fleet plane is unconfigured."""
    global _AGGREGATOR
    with _AGG_LOCK:
        if _AGGREGATOR is not None:
            return _AGGREGATOR
    peers = [
        u.strip()
        for u in os.environ.get("CELESTIA_FLEET_PEERS", "").split(",")
        if u.strip()
    ]
    if not peers:
        return None
    try:
        interval = float(
            os.environ.get("CELESTIA_FLEET_INTERVAL_S", "")
            or DEFAULT_INTERVAL_S
        )
    except ValueError:
        interval = DEFAULT_INTERVAL_S
    return configure(peers, interval_s=interval)


def _reset_for_tests() -> None:
    global _AGGREGATOR
    with _AGG_LOCK:
        _AGGREGATOR = None


def fleet_response():
    """GET /fleet -> (status, content_type, bytes): the merged view, or
    a 503 when no aggregator is configured.  Canonical render (sorted
    keys, compact separators — the serve/api.render shape) so the bytes
    are a pure function of the merged state on every plane."""
    agg = aggregator()
    if agg is None:
        return 503, "application/json", json.dumps({
            "error": "no fleet aggregator configured "
                     "(set $CELESTIA_FLEET_PEERS or trace.fleet.configure())"
        }).encode()
    agg.maybe_scrape()
    return 200, "application/json", json.dumps(
        agg.payload(), sort_keys=True, separators=(",", ":")
    ).encode()

"""Height-anatomy timeline: every journal plane stitched per height.

The repo's telemetry planes — the span tree (tx_submit / block_propose /
block_commit / mempool_reap rows from trace/context.export_span), the
block journal (trace/journal.py: upload / stall / dispatch / starve /
drain stage ms), the square journal (occupancy, per-tenant shares), the
round journal (prevote/precommit deltas, WAL fsync, round bumps), the
device ledger's compile bills (`compile_bill` rows, trace/device_ledger),
ForestCache admissions and evictions (`forest_cache` rows, serve/cache),
heal completions, and the serve plane's first-answer events
(serve/api.count_served / `proof_serve` rows) — are individually useful
but siloed: none answers "for height H, where did the time go?".

This module is the stitcher.  A HeightTimeline subscribes to the default
Tracer (Tracer.add_observer, installed lazily the first time traced() is
called) and folds every row carrying a `height=` — or a `trace_id=`
that some other row has already bound to a height — into ONE ordered
per-height record:

  * phase intervals, anchored in wall time: span rows cover
    [ts_ns - duration_ms, ts_ns]; a block-journal stream row is unrolled
    BACKWARDS from its drain-time write into
    intake_wait | upload | upload_stall | dispatch_starve | dispatch |
    drain; round rows contribute prevote/precommit/wal_fsync (their
    propose delta is skipped — the block_propose span already covers
    it); compile bills, forest builds, and heals anchor on their own
    durations.
  * inter-phase GAPS: the explicitly measured queue waits
    (intake_wait / upload_stall / dispatch_starve) plus every implicit
    hole the critical-path walk finds between intervals — a hole
    directly before the propose span is the mempool wait and is named
    `mempool_wait`.
  * the computed critical path: a cursor walk over the sorted intervals
    credits each phase only the wall time it alone covered, so
    overlapping phases (wal_fsync under precommit, serve-plane work
    under drain) never double-bill the height.

A record FINALIZES when the serving plane first answers for its height
(serve/api.count_served -> note_first_serve, or a height-stamped
proof_serve row) or when the ring evicts it; finalization observes the
Prometheus reflections exactly once:

  celestia_height_critical_seconds{phase}   histogram
  celestia_height_gap_seconds{phase}        histogram
  celestia_height_critical_phase{phase}     one-hot gauge (last height)

The ring keeps the last $CELESTIA_TIMELINE_HEIGHTS heights (default 64;
0 disables the observer entirely).  Rows with only a trace_id buffer in
a bounded pending map until some row binds that trace to a height (the
tx_submit -> block_propose adoption), so the submit leg of a block's
trace lands on the height record even though the submit predates the
height assignment.

Surfaces: `GET /timeline` (shared exposition handler — byte-identical
on the JSON-RPC, REST, and gRPC planes; `?height=` full record,
`?tail=N` summaries), the flight-recorder bundle's `timeline` block,
a per-host `timeline` block in `GET /fleet`, and
scripts/block_anatomy.py's waterfall / phase-budget / TL_rNN.json
renderings, gated for trend regressions by scripts/bench_trend.py.

Everything here is a pure function of retained row state: no ticks, no
clocks at render time, so two planes asked in any order serve identical
bytes (the /heal pattern).
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict

#: Ring capacity env knob; 0 disables timeline assembly.
HEIGHTS_ENV = "CELESTIA_TIMELINE_HEIGHTS"
DEFAULT_HEIGHTS = 64

#: Bounded stitching state: how many distinct unbound trace_ids may hold
#: pending rows, and how many rows each may hold (a runaway writer must
#: never grow the index unboundedly).
MAX_PENDING_TRACES = 256
MAX_PENDING_ROWS = 64
MAX_BINDINGS = 1024

#: Span-table rows (trace/context.export_span writes one event table per
#: span name) that become phases, and the phase each maps to.
SPAN_PHASES = {
    "tx_submit": "tx_submit",
    "mempool_reap": "mempool_reap",
    "block_propose": "propose",
    "block_commit": "commit",
}

#: An implicit hole found directly before one of these phases is the
#: named wait, not an anonymous gap (the hole between the submit span
#: and the reap/propose span IS the mempool wait).
GAP_ALIASES = {"propose": "mempool_wait", "mempool_reap": "mempool_wait"}

#: block_journal stage fields unrolled backwards from the row's write
#: time (drain end), innermost first: (field, phase, kind).
_STREAM_CHAIN = (
    ("drain_ms", "drain", "phase"),
    ("dispatch_ms", "dispatch", "phase"),
    ("dispatch_starve_ms", "dispatch_starve", "gap"),
    ("upload_stall_ms", "upload_stall", "gap"),
    ("upload_ms", "upload", "phase"),
    ("intake_wait_ms", "intake_wait", "gap"),
)

#: block_journal meta fields copied onto the record (facts, not time).
_JOURNAL_META = ("source", "k", "mode", "compile", "batch_size", "panels",
                 "shards")


def timeline_heights() -> int:
    try:
        return int(os.environ.get(HEIGHTS_ENV, str(DEFAULT_HEIGHTS))
                   or DEFAULT_HEIGHTS)
    except ValueError:
        return DEFAULT_HEIGHTS


def _round3(v: float) -> float:
    return round(float(v), 3)


def _as_height(v) -> int | None:
    """Row/baggage height -> int (baggage adopted off the wire arrives
    stringified; bools are not heights)."""
    if isinstance(v, bool):
        return None
    if isinstance(v, int):
        return v
    if isinstance(v, str) and v.isdigit():
        return int(v)
    return None


class _Record:
    """Mutable per-height assembly state (rendered lazily)."""

    __slots__ = ("height", "intervals", "meta", "trace_ids",
                 "first_serve_ts_ns", "finalized")

    def __init__(self, height: int):
        self.height = height
        # (start_ns, end_ns, phase, kind) with kind phase|gap.
        self.intervals: list[tuple[int, int, str, str]] = []
        self.meta: dict = {}
        self.trace_ids: set[str] = set()
        self.first_serve_ts_ns: int | None = None
        self.finalized = False


def critical_path(intervals) -> tuple[dict[str, float], dict[str, float]]:
    """Cursor walk over (start_ns, end_ns, phase, kind) intervals ->
    ({phase: critical_ms}, {gap: gap_ms}).

    Each interval is credited only the wall time past the cursor, so
    overlapping phases never double-bill; an implicit hole between the
    cursor and the next interval is charged as a gap to the FOLLOWING
    phase (aliased via GAP_ALIASES), unless that interval is itself an
    explicitly measured gap (which already covers the hole)."""
    crit: dict[str, float] = {}
    gaps: dict[str, float] = {}
    cursor: int | None = None
    for start, end, phase, kind in sorted(intervals):
        if cursor is None:
            cursor = start
        if start > cursor:
            name = GAP_ALIASES.get(phase, phase)
            gaps[name] = gaps.get(name, 0.0) + (start - cursor) / 1e6
            cursor = start
        contrib_ns = end - max(start, cursor)
        if contrib_ns > 0:
            bucket = gaps if kind == "gap" else crit
            bucket[phase] = bucket.get(phase, 0.0) + contrib_ns / 1e6
        if end > cursor:
            cursor = end
    return crit, gaps


class HeightTimeline:
    """Bounded ring of per-height records stitched from trace rows."""

    def __init__(self, capacity: int | None = None):
        self.capacity = (
            capacity if capacity is not None else timeline_heights()
        )
        self._lock = threading.Lock()
        self._records: OrderedDict[int, _Record] = OrderedDict()
        # trace_id -> height, learned from any row carrying both.
        self._bindings: OrderedDict[str, int] = OrderedDict()
        # trace_id -> [(table, row)] parked until the trace binds.
        self._pending: OrderedDict[str, list] = OrderedDict()
        # Every phase/gap name ever finalized (the one-hot gauge's span).
        self._phases_seen: set[str] = set()

    # --- ingest -------------------------------------------------------------

    def observe(self, table: str, row: dict) -> None:
        """Tracer observer entry point: fold one written row in.  Cheap
        for rows the timeline does not consume (one dict probe)."""
        if self.capacity <= 0:
            return
        if table not in _EXTRACTORS and table not in SPAN_PHASES:
            return
        height = _as_height(row.get("height"))
        trace_id = row.get("trace_id")
        finalize = None
        with self._lock:
            if height is None:
                height = self._bindings.get(trace_id) if trace_id else None
                if height is None:
                    if isinstance(trace_id, str):
                        self._park(table, row, trace_id)
                    return
            elif isinstance(trace_id, str):
                self._bind(trace_id, height)
            rec, evicted = self._record(height)
            self._fold(rec, table, row)
            if isinstance(trace_id, str):
                flushed = self._pending.pop(trace_id, None)
                if flushed:
                    for ptable, prow in flushed:
                        self._fold(rec, ptable, prow)
            if rec.first_serve_ts_ns is not None and not rec.finalized:
                rec.finalized = True
                finalize = rec
        # Metric observation happens OUTSIDE the lock (registry locks
        # internally; never nest).
        for old in evicted:
            self._observe_metrics(old)
        if finalize is not None:
            self._observe_metrics(finalize)

    def note_first_serve(self, height, plane: str | None = None,
                         kind: str | None = None) -> None:
        """The serve plane answered for `height` (serve/api.count_served).
        First call per retained height stamps the first-serve point and
        finalizes the record; later calls only bump the serve counter."""
        height = _as_height(height)
        if self.capacity <= 0 or height is None:
            return
        import time

        finalize = None
        with self._lock:
            rec = self._records.get(height)
            if rec is None:
                return
            rec.meta["serves"] = rec.meta.get("serves", 0) + 1
            if rec.first_serve_ts_ns is None:
                rec.first_serve_ts_ns = time.time_ns()
                if kind:
                    rec.meta["first_serve_kind"] = kind
                if not rec.finalized:
                    rec.finalized = True
                    finalize = rec
        if finalize is not None:
            self._observe_metrics(finalize)

    # --- internals (caller holds the lock) ----------------------------------

    def _park(self, table: str, row: dict, trace_id: str) -> None:
        rows = self._pending.get(trace_id)
        if rows is None:
            rows = self._pending[trace_id] = []
            while len(self._pending) > MAX_PENDING_TRACES:
                self._pending.popitem(last=False)
        if len(rows) < MAX_PENDING_ROWS:
            rows.append((table, row))

    def _bind(self, trace_id: str, height: int) -> None:
        self._bindings[trace_id] = height
        self._bindings.move_to_end(trace_id)
        while len(self._bindings) > MAX_BINDINGS:
            self._bindings.popitem(last=False)

    def _record(self, height: int) -> tuple[_Record, list]:
        rec = self._records.get(height)
        evicted = []
        if rec is None:
            rec = self._records[height] = _Record(height)
            while len(self._records) > self.capacity:
                _, old = self._records.popitem(last=False)
                if not old.finalized:
                    old.finalized = True
                    evicted.append(old)
        return rec, evicted

    def _fold(self, rec: _Record, table: str, row: dict) -> None:
        trace_id = row.get("trace_id")
        if isinstance(trace_id, str):
            rec.trace_ids.add(trace_id)
        phase = SPAN_PHASES.get(table)
        if phase is not None:
            self._fold_span(rec, phase, row)
            return
        _EXTRACTORS[table](self, rec, row)

    @staticmethod
    def _anchor(rec: _Record, row: dict, duration_ms, phase: str,
                kind: str = "phase") -> None:
        """One interval ending at the row's write time, `duration_ms`
        long (the span / bill / heal shape)."""
        if not isinstance(duration_ms, (int, float)) or duration_ms < 0:
            return
        end = row.get("ts_ns")
        if not isinstance(end, int):
            return
        rec.intervals.append(
            (end - int(duration_ms * 1e6), end, phase, kind)
        )

    def _fold_span(self, rec: _Record, phase: str, row: dict) -> None:
        self._anchor(rec, row, row.get("duration_ms"), phase)

    def _fold_block_journal(self, rec: _Record, row: dict) -> None:
        end = row.get("ts_ns")
        if not isinstance(end, int):
            return
        for field, phase, kind in _STREAM_CHAIN:
            ms = row.get(field)
            if not isinstance(ms, (int, float)) or ms <= 0:
                continue
            start = end - int(ms * 1e6)
            rec.intervals.append((start, end, phase, kind))
            end = start
        for field in _JOURNAL_META:
            if row.get(field) is not None:
                rec.meta[field] = row[field]

    def _fold_square_journal(self, rec: _Record, row: dict) -> None:
        sq = {}
        for field in ("phase", "k", "occupancy", "used_shares",
                      "n_blobs", "n_namespaces"):
            if row.get(field) is not None:
                sq[field] = row[field]
        if sq:
            rec.meta["square"] = sq

    def _fold_round_journal(self, rec: _Record, row: dict) -> None:
        if row.get("result") == "round_bump":
            rec.meta["round_bumps"] = rec.meta.get("round_bumps", 0) + 1
        end = row.get("ts_ns")
        if not isinstance(end, int):
            return
        # propose_ms is skipped: the block_propose span already covers
        # that wall time; double-entering it would double-bill the walk.
        for field, phase in (("precommit_ms", "precommit"),
                             ("prevote_ms", "prevote")):
            ms = row.get(field)
            if not isinstance(ms, (int, float)) or ms <= 0:
                continue
            start = end - int(ms * 1e6)
            rec.intervals.append((start, end, phase, "phase"))
            end = start
        self._anchor(rec, row, row.get("wal_fsync_ms"), "wal_fsync")

    def _fold_compile_bill(self, rec: _Record, row: dict) -> None:
        self._anchor(rec, row, row.get("compile_ms"), "jit_compile")
        bills = rec.meta.setdefault("compile_bills", [])
        if len(bills) < 16:
            bills.append({
                "family": row.get("family"),
                "compile_ms": _round3(row.get("compile_ms") or 0.0),
            })

    def _fold_forest_cache(self, rec: _Record, row: dict) -> None:
        event = row.get("event")
        if event in ("admit", "readmit"):
            self._anchor(rec, row, row.get("forest_build_ms"),
                         "forest_build")
        if isinstance(event, str):
            cache = rec.meta.setdefault("cache", {})
            cache[event] = cache.get(event, 0) + 1

    def _fold_heal(self, rec: _Record, row: dict) -> None:
        self._anchor(rec, row, row.get("total_ms"), "heal")
        rec.meta["heal"] = {
            "kind": row.get("kind"),
            "outcome": row.get("outcome"),
            "attempts": row.get("attempts"),
        }

    def _fold_proof_serve(self, rec: _Record, row: dict) -> None:
        batch = row.get("batch")
        rec.meta["serves"] = rec.meta.get("serves", 0) + (
            batch if isinstance(batch, int) else 1
        )
        # A height-stamped serve row is the serve plane answering: it
        # stamps first-serve even on paths that bypass count_served
        # (direct sampler drives).
        if rec.first_serve_ts_ns is None and isinstance(
                row.get("ts_ns"), int):
            rec.first_serve_ts_ns = row["ts_ns"]

    # --- rendering ----------------------------------------------------------

    def _render(self, rec: _Record, full: bool) -> dict:
        crit, gaps = critical_path(rec.intervals)
        critical_phase = (
            max(sorted(crit), key=lambda p: crit[p]) if crit else None
        )
        first = min((s for s, _e, _p, _k in rec.intervals), default=None)
        last_candidates = [e for _s, e, _p, _k in rec.intervals]
        if rec.first_serve_ts_ns is not None:
            last_candidates.append(rec.first_serve_ts_ns)
        last = max(last_candidates, default=None)
        out = {
            "height": rec.height,
            "critical_phase": critical_phase,
            "critical_ms": _round3(crit.get(critical_phase, 0.0))
            if critical_phase else 0.0,
            "phases": {p: _round3(v) for p, v in sorted(crit.items())},
            "gaps": {p: _round3(v) for p, v in sorted(gaps.items())},
            "span_ms": _round3((last - first) / 1e6)
            if first is not None and last is not None else 0.0,
            "finalized": rec.finalized,
        }
        if not full:
            return out
        out["trace_ids"] = sorted(rec.trace_ids)
        out["meta"] = rec.meta
        out["first_serve_ms"] = (
            _round3((rec.first_serve_ts_ns - first) / 1e6)
            if rec.first_serve_ts_ns is not None and first is not None
            else None
        )
        out["intervals"] = [
            {
                "phase": p,
                "kind": k,
                "start_ms": _round3((s - first) / 1e6),
                "end_ms": _round3((e - first) / 1e6),
            }
            for s, e, p, k in sorted(rec.intervals)
        ] if first is not None else []
        return out

    def record_payload(self, height: int) -> dict | None:
        with self._lock:
            rec = self._records.get(height)
            return self._render(rec, full=True) if rec is not None else None

    def summaries(self, tail: int | None = None) -> list[dict]:
        with self._lock:
            recs = list(self._records.values())
        if tail is not None:
            recs = recs[-tail:] if tail > 0 else []
        return [self._render(r, full=False) for r in recs]

    def index_payload(self) -> dict:
        with self._lock:
            recs = list(self._records.values())
        return {
            "capacity": self.capacity,
            "heights": [r.height for r in recs],
            "latest": self._render(recs[-1], full=True) if recs else None,
        }

    def bundle_block(self, tail: int = 8) -> dict:
        """The flight-recorder / slo_report block: last-`tail` summaries
        plus the latest full record (what phase was critical when the
        page fired)."""
        with self._lock:
            recs = list(self._records.values())
        return {
            "capacity": self.capacity,
            "records": [self._render(r, full=False) for r in recs[-tail:]],
            "latest": self._render(recs[-1], full=True) if recs else None,
        }

    # --- metrics ------------------------------------------------------------

    def _observe_metrics(self, rec: _Record) -> None:
        from celestia_app_tpu.trace.metrics import (
            DEVICE_SECONDS_BUCKETS,
            registry,
        )

        crit, gaps = critical_path(rec.intervals)
        reg = registry()
        crit_hist = reg.histogram(
            "celestia_height_critical_seconds",
            "per-height critical-path wall time, by phase",
            buckets=DEVICE_SECONDS_BUCKETS,
        )
        for phase, ms in sorted(crit.items()):
            crit_hist.observe(ms / 1e3, phase=phase)
        gap_hist = reg.histogram(
            "celestia_height_gap_seconds",
            "per-height inter-phase queue-wait time, by gap",
            buckets=DEVICE_SECONDS_BUCKETS,
        )
        for phase, ms in sorted(gaps.items()):
            gap_hist.observe(ms / 1e3, phase=phase)
        critical_phase = (
            max(sorted(crit), key=lambda p: crit[p]) if crit else None
        )
        with self._lock:
            self._phases_seen.update(crit)
            self._phases_seen.update(gaps)
            phases = sorted(self._phases_seen)
        gauge = reg.gauge(
            "celestia_height_critical_phase",
            "one-hot: which phase was critical for the last finalized "
            "height",
        )
        for phase in phases:
            gauge.set(1.0 if phase == critical_phase else 0.0, phase=phase)


#: table -> fold method (unknown tables cost one failed dict probe).
_EXTRACTORS = {
    "block_journal": HeightTimeline._fold_block_journal,
    "square_journal": HeightTimeline._fold_square_journal,
    "round_journal": HeightTimeline._fold_round_journal,
    "compile_bill": HeightTimeline._fold_compile_bill,
    "forest_cache": HeightTimeline._fold_forest_cache,
    "heal": HeightTimeline._fold_heal,
    "proof_serve": HeightTimeline._fold_proof_serve,
}


# --- process-wide instance ----------------------------------------------------

_TIMELINE: HeightTimeline | None = None
_TL_LOCK = threading.Lock()


def timeline() -> HeightTimeline:
    global _TIMELINE
    tl = _TIMELINE
    if tl is None:
        with _TL_LOCK:
            tl = _TIMELINE
            if tl is None:
                tl = _TIMELINE = HeightTimeline()
    return tl


def install(tracer) -> None:
    """Subscribe the process timeline to `tracer` (idempotent; called
    lazily from trace/tracer.traced())."""
    tracer.add_observer(_observer)


def _observer(table: str, row: dict) -> None:
    timeline().observe(table, row)


def _reset_for_tests(capacity: int | None = None) -> None:
    global _TIMELINE
    with _TL_LOCK:
        _TIMELINE = HeightTimeline(capacity)


# --- exposition -----------------------------------------------------------

def timeline_response(query_params: dict):
    """GET /timeline -> (status, content_type, bytes): the full latest
    record + retained heights without params, one full record with
    ?height=, last-N summaries with ?tail=N — a pure function of
    retained timeline state, byte-identical on every plane."""
    tl = timeline()
    raw_height = query_params.get("height")
    raw_tail = query_params.get("tail")
    if raw_height is not None:
        if raw_height == "latest":
            with tl._lock:
                height = next(reversed(tl._records), None)
            if height is None:
                return 404, "application/json", json.dumps(
                    {"error": "no heights retained yet"}
                ).encode()
        else:
            try:
                height = int(raw_height)
            except ValueError:
                return 400, "application/json", json.dumps(
                    {"error": "height must be an integer or 'latest', "
                              f"got {raw_height!r}"}
                ).encode()
        payload = tl.record_payload(height)
        if payload is None:
            return 404, "application/json", json.dumps(
                {"error": f"no timeline record at height {height}"}
            ).encode()
        return 200, "application/json", _render(payload)
    if raw_tail is not None:
        try:
            tail = int(raw_tail)
        except ValueError:
            tail = -1
        if tail <= 0:
            return 400, "application/json", json.dumps(
                {"error": f"tail must be a positive integer, got {raw_tail!r}"}
            ).encode()
        return 200, "application/json", _render(
            {"timelines": tl.summaries(tail)}
        )
    return 200, "application/json", _render(tl.index_payload())


def _render(payload: dict) -> bytes:
    """Canonical bytes (sorted keys, compact separators) — sorted so the
    per-height meta dict, whose insertion order follows event arrival,
    can never leak arrival order into the byte-identity contract."""
    return json.dumps(payload, sort_keys=True, separators=(",", ":")).encode()


def fleet_block(payload: dict | None) -> dict | None:
    """Fold one peer's GET /timeline payload into the per-host block
    trace/fleet.py merges (None when the peer predates the surface)."""
    if not isinstance(payload, dict):
        return None
    latest = payload.get("latest")
    block = {
        "retained": len(payload.get("heights") or []),
        "latest_height": None,
        "critical_phase": None,
        "span_ms": None,
    }
    if isinstance(latest, dict):
        block["latest_height"] = latest.get("height")
        block["critical_phase"] = latest.get("critical_phase")
        block["span_ms"] = latest.get("span_ms")
    return block

"""Square journal: one row per built square — the data-plane spine.

PR 2 lit the device plane (block_journal) and PR 3 the request plane
(spans); this table answers the remaining multi-tenant questions: who is
filling the square, how much of k*k is padding waste, and which
namespace's blobs are paying the latency.  `square/builder.py` computes
the exact share breakdown during export (`Square.accounting`) and both
entry points (square.build on the proposer, square.construct on every
validator) journal it here, stamped with the block's trace_id so the row
joins the PR 3 span tree.  A proposer therefore records TWO rows per
block (phase=build then phase=construct); counters count exported
squares, not blocks.

Prometheus reflections per row:

    celestia_square_occupancy_ratio{k}            used / k*k of the last square
    celestia_square_padding_shares_total{kind}    reserved | namespace | tail
    celestia_namespace_blobs_total{namespace}     per-tenant blob count
    celestia_namespace_shares_total{namespace}    per-tenant share count
    celestia_namespace_bytes_total{namespace}     per-tenant payload bytes

Namespace label cardinality is BOUNDED by construction: every namespace
label on a metric goes through `capped_namespace_label`, which admits at
most $CELESTIA_NAMESPACE_TOP_N distinct labels per process (biggest
share-count first within a square) and folds everything else into the
reserved `other` bucket.  scripts/trace_lint.py enforces that no other
module puts a namespace label on a metric without routing through this
helper.  The full, uncapped per-namespace breakdown still lands in the
journal ROW (tables tolerate unbounded cardinality; label sets don't).

GET /namespaces (trace/exposition.py, all three planes) serves the
cumulative per-tenant summary + the last square snapshot as JSON, and
`last_square()` feeds /healthz so a stuck-at-empty-blocks node is
distinguishable from a healthy idle one.  Both are process-level views
(a multi-node test process shares them), like the rest of the registry.
"""

from __future__ import annotations

import os
import threading

TABLE = "square_journal"

# Always-allowed labels that can never collide with a real namespace
# label (namespace labels are pure hex): the overflow bucket and the
# bucket normal (non-blob) txs account under in the mempool gauges.
OTHER_LABEL = "other"
TX_LABEL = "tx"

_LOCK = threading.Lock()
_ADMITTED: set[str] = set()
_TOTALS: dict[str, list[int]] = {}  # capped label -> [blobs, shares, bytes]
_LAST: dict | None = None  # last recorded square snapshot (for /healthz)


def namespace_top_n() -> int:
    """$CELESTIA_NAMESPACE_TOP_N: max distinct namespace label values per
    process (default 20); everything past the cap folds into `other`."""
    try:
        return max(1, int(os.environ.get("CELESTIA_NAMESPACE_TOP_N", "20")))
    except ValueError:
        return 20


def namespace_label(ns_bytes: bytes) -> str:
    """Deterministic short label for a 29-byte namespace: the full hex
    with leading zeros stripped (injective for fixed-width input)."""
    return ns_bytes.hex().lstrip("0") or "0"


def capped_namespace_label(label: str) -> str:
    """THE cardinality gate: admit up to top-N distinct labels per
    process (first come, first admitted), fold the rest into `other`.
    Reserved buckets pass through without consuming a slot."""
    if label in (OTHER_LABEL, TX_LABEL):
        return label
    with _LOCK:
        if label in _ADMITTED:
            return label
        if len(_ADMITTED) < namespace_top_n():
            _ADMITTED.add(label)
            return label
    return OTHER_LABEL


def tx_namespace_label(raw_tx: bytes) -> str | None:
    """The submitting namespace of a tx: first blob's namespace label for
    a BlobTx, None for a normal tx (or anything unparseable) — what
    BroadcastTx drops into TraceContext baggage."""
    from celestia_app_tpu.tx.envelopes import unmarshal_blob_tx

    try:
        btx = unmarshal_blob_tx(raw_tx)
    except Exception:
        return None
    if btx is None or not btx.blobs:
        return None
    return namespace_label(btx.blobs[0].namespace.to_bytes())


def record(sq, *, phase: str, layout_solves: int | None = None) -> None:
    """Journal one exported square (square/builder.py build/construct).

    Writes the `square_journal` row (share counts summing exactly to
    k*k), refreshes the occupancy gauge, ticks the padding + per-tenant
    counters (capped labels), and updates the /namespaces + /healthz
    snapshots.  `phase` is build (proposer) or construct (validator).
    """
    global _LAST

    acct = sq.accounting
    if acct is None:  # a Square assembled without the builder's export
        return
    from celestia_app_tpu.trace.context import current_context
    from celestia_app_tpu.trace.metrics import registry
    from celestia_app_tpu.trace.tracer import traced

    ctx = current_context()
    height = ctx.baggage.get("height") if ctx is not None else None
    occupancy = round(acct.occupancy, 6)
    # Biggest tenants first: when the admission cap has slots left, they
    # go to the namespaces paying for the most shares in this square.
    by_shares = sorted(acct.namespaces, key=lambda u: -u.shares)
    snapshot = {
        "height": height,
        "k": acct.size,
        "phase": phase,
        "occupancy": occupancy,
        "used_shares": acct.used_shares,
        "padding_shares": acct.padding_shares,
    }

    # The /healthz + /namespaces snapshots sit OUTSIDE the $CELESTIA_TRACE
    # gate (like the profiler hooks): liveness probing must keep working
    # with tracing muted.
    with _LOCK:
        _LAST = snapshot
    labeled: list[tuple[str, object]] = [
        (capped_namespace_label(namespace_label(u.namespace)), u)
        for u in by_shares
    ]
    with _LOCK:
        for lbl, u in labeled:
            agg = _TOTALS.setdefault(lbl, [0, 0, 0])
            agg[0] += u.blobs
            agg[1] += u.shares
            agg[2] += u.data_bytes

    tracer = traced()
    if not tracer._on():
        return
    tracer.write(
        TABLE,
        phase=phase,
        k=acct.size,
        total_shares=acct.total_shares,
        used_shares=acct.used_shares,
        tx_shares=acct.tx_shares,
        pfb_shares=acct.pfb_shares,
        blob_shares=acct.blob_shares,
        reserved_padding=acct.reserved_padding,
        namespace_padding=acct.namespace_padding,
        tail_padding=acct.tail_padding,
        occupancy=occupancy,
        layout_solves=layout_solves,
        n_blobs=sum(u.blobs for u in acct.namespaces),
        n_namespaces=len(acct.namespaces),
        # Full (uncapped) per-tenant breakdown: rows tolerate unbounded
        # cardinality, the label space does not.
        namespaces={
            namespace_label(u.namespace): {
                "blobs": u.blobs, "shares": u.shares, "bytes": u.data_bytes,
            }
            for u in acct.namespaces
        },
        height=height,
        trace_id=ctx.trace_id if ctx is not None else None,
    )

    reg = registry()
    reg.gauge(
        "celestia_square_occupancy_ratio",
        "used/total share ratio of the last built square, by k",
    ).set(occupancy, k=str(acct.size))
    # The UNLABELED twin always holds the latest square regardless of k:
    # the SLO engine judges this one, because a per-k child for a size
    # no longer being built would otherwise pin its stale ratio forever
    # (one near-empty k=2 square during idle must not read as a
    # permanently burning occupancy floor after traffic resumes at k=32).
    reg.gauge(
        "celestia_square_last_occupancy_ratio",
        "used/total share ratio of the most recent exported square "
        "(unlabeled: always the latest, never a stale per-k child)",
    ).set(occupancy)
    pad = reg.counter(
        "celestia_square_padding_shares_total",
        "padding shares in exported squares by kind",
    )
    pad.inc(acct.reserved_padding, kind="reserved")
    pad.inc(acct.namespace_padding, kind="namespace")
    pad.inc(acct.tail_padding, kind="tail")
    blobs_c = reg.counter(
        "celestia_namespace_blobs_total",
        "blobs placed in exported squares per namespace (top-N capped)",
    )
    shares_c = reg.counter(
        "celestia_namespace_shares_total",
        "shares occupied in exported squares per namespace (top-N capped)",
    )
    bytes_c = reg.counter(
        "celestia_namespace_bytes_total",
        "blob payload bytes in exported squares per namespace (top-N capped)",
    )
    for lbl, u in labeled:
        blobs_c.inc(u.blobs, namespace=lbl)
        shares_c.inc(u.shares, namespace=lbl)
        bytes_c.inc(u.data_bytes, namespace=lbl)


def last_square() -> dict | None:
    """The last recorded square's snapshot (height, k, phase, occupancy)
    — the /healthz "is this node building empty blocks?" probe input."""
    with _LOCK:
        return dict(_LAST) if _LAST is not None else None


def namespaces_payload() -> dict:
    """The GET /namespaces JSON: cumulative per-tenant totals (capped
    label space, so the payload is bounded) + the last square snapshot."""
    with _LOCK:
        totals = {
            lbl: {"blobs": b, "shares": s, "bytes": by}
            for lbl, (b, s, by) in sorted(_TOTALS.items())
        }
        last = dict(_LAST) if _LAST is not None else None
        admitted = len(_ADMITTED)
    payload = {
        "top_n": namespace_top_n(),
        "admitted": admitted,
        "namespaces": totals,
        "last_square": last,
    }
    # Enforcement fields (qos.py): per-tenant limits / tokens remaining /
    # throttle counts, present only when a $CELESTIA_QOS policy is
    # installed — the /namespaces page then answers both "who is using
    # the square" AND "who is being held to what".
    from celestia_app_tpu import qos

    enf = qos.enforcer()
    if enf is not None:
        payload["qos"] = enf.health_block()
    return payload


def _reset_for_tests() -> None:
    """Drop the process-level admission set + summaries (test isolation)."""
    global _LAST
    with _LOCK:
        _ADMITTED.clear()
        _TOTALS.clear()
        _LAST = None

"""Columnar event tracing (the pkg/trace + telemetry analog).

Parity with the reference's two tracing mechanisms (SURVEY §5): sdk
telemetry.MeasureSince around the ABCI hot methods
(app/prepare_proposal.go:23, app/process_proposal.go:25) and celestia-core
pkg/trace's columnar event tables written node-side and pulled for analysis.

Here both collapse into one in-process Tracer: named event tables holding
homogeneous dict rows, with a `span` context manager for wall-time
measurements (device kernel timings from jax block_until_ready land in the
same tables).  Export is JSONL per table, the same shape the reference's
table puller consumes (test/e2e/testnet/node.go:52-74); the serving planes
expose it live on GET /trace_tables (trace/exposition.py).

The tracer is written to from the block pipeline's uploader/dispatcher
threads concurrently with serving-plane readers, so every table mutation
holds `_lock`; buffer eviction is counted in the Prometheus counter
`celestia_trace_rows_dropped` instead of disappearing silently.

$CELESTIA_TRACE=off gates the whole layer: writes and span observations
become no-ops (span still times nothing into the registry), so a latency
bisection can rule tracing out without a rebuild.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager

from celestia_app_tpu.trace.metrics import registry

# Span attrs in this set also become Prometheus labels on the span's
# histogram (bounded cardinality by construction: square sizes, pipeline
# modes, phases).  Everything else — heights, tags, counts — lands only in
# the event table, where unbounded cardinality is just another column.
SPAN_LABEL_ATTRS = ("k", "mode", "phase", "result", "construction", "source")


def trace_enabled() -> bool:
    """The $CELESTIA_TRACE gate (default on; "off"/"0" disables)."""
    return os.environ.get("CELESTIA_TRACE", "on") not in ("off", "0")


class Tracer:
    def __init__(self, buffer_size: int = 10_000, env_gated: bool = True):
        self.buffer_size = buffer_size
        self._tables: dict[str, list[dict]] = {}
        self._lock = threading.Lock()
        self.enabled = True
        # env_gated=False opts a PRIVATE tracer out of $CELESTIA_TRACE:
        # an explicitly requested artifact (bench --metrics-out) must not
        # come back empty because the operator muted the global layer.
        self.env_gated = env_gated
        # Row observers (trace/timeline.py's height stitcher): called
        # with (table, row) after every write, outside the table lock.
        self._observers: list = []

    def add_observer(self, fn) -> None:
        """Subscribe `fn(table, row)` to every row written through this
        tracer (idempotent).  Observers run outside `_lock` and must not
        mutate the row (it is the retained ring object)."""
        with self._lock:
            if fn not in self._observers:
                self._observers.append(fn)

    def _on(self) -> bool:
        return self.enabled and (not self.env_gated or trace_enabled())

    def write(self, table: str, **row) -> None:
        if not self._on():
            return
        # Every row says WHICH node wrote it: in a shared-artifact
        # multi-node drill (one $CELESTIA_FLIGHT_DIR, merged table pulls)
        # provenance must ride the row, not the transport.  Lazy import:
        # context.py imports from this module.
        from celestia_app_tpu.trace.context import node_id

        dropped = 0
        with self._lock:
            rows = self._tables.setdefault(table, [])
            stamped = {"ts_ns": time.time_ns(), "node_id": node_id(), **row}
            rows.append(stamped)
            if len(rows) > self.buffer_size:
                dropped = len(rows) - self.buffer_size
                del rows[:dropped]
        for obs in self._observers:
            try:
                obs(table, stamped)
            except Exception:  # chaos-ok: observers must never fail a write
                pass
        if dropped:
            registry().counter(
                "celestia_trace_rows_dropped",
                "trace table rows evicted by the ring buffer",
            ).inc(dropped, table=table)

    @contextmanager
    def span(self, table: str, *, buckets: tuple[float, ...] | None = None,
             **attrs):
        """Measure a wall-time span into `table` (MeasureSince analog); the
        same measurement lands on the Prometheus histogram
        celestia_<table>_seconds, with the low-cardinality attrs
        (SPAN_LABEL_ATTRS, e.g. k=...) as labels.  Device-scale call sites
        pass an explicit `buckets` tuple (metrics.DEVICE_SECONDS_BUCKETS);
        the histogram lookup happens on entry, off the timed region and out
        of the finally block.
        """
        if not self._on():
            yield
            return
        hist = registry().histogram(
            f"celestia_{table}_seconds", f"wall time of {table}",
            **({"buckets": buckets} if buckets else {}),
        )
        labels = {a: str(attrs[a]) for a in SPAN_LABEL_ATTRS if a in attrs}
        start = time.perf_counter_ns()
        try:
            yield
        finally:
            elapsed_ns = time.perf_counter_ns() - start
            self.write(table, duration_ms=elapsed_ns / 1e6, **attrs)
            hist.observe(elapsed_ns / 1e9, **labels)

    def table(self, name: str) -> list[dict]:
        with self._lock:
            return list(self._tables.get(name, []))

    def tables(self) -> list[str]:
        with self._lock:
            return sorted(self._tables)

    def row_counts(self) -> dict[str, int]:
        """{table: row count} in one lock acquisition, no row copies (the
        /trace_tables listing's accessor)."""
        with self._lock:
            return {name: len(rows) for name, rows in sorted(self._tables.items())}

    def tail(self, name: str, n: int) -> list[dict]:
        """The last `n` rows of a table (row copies) — what the flight
        recorder bundles and /trace_tables/<name>?tail=N serves."""
        if n <= 0:
            return []
        with self._lock:
            rows = self._tables.get(name, [])
            return list(rows[-n:])

    def export_jsonl(self, name: str, tail: int | None = None) -> str:
        # Delegate the tail slice so the two accessors cannot diverge
        # (tail=0 means zero rows, never the whole ring).
        if tail is None:
            with self._lock:
                rows = list(self._tables.get(name, []))
        else:
            rows = self.tail(name, tail)
        return "\n".join(json.dumps(r) for r in rows)

    def clear(self) -> None:
        with self._lock:
            self._tables.clear()


# Process-wide default tracer (the node wires its own when needed).
_default = Tracer()

# The height timeline subscribes lazily on first access: the flag is set
# BEFORE the import so timeline.py's own traced() calls during install
# return immediately instead of recursing.
_TIMELINE_INSTALLED = False


def traced() -> Tracer:
    global _TIMELINE_INSTALLED
    if not _TIMELINE_INSTALLED:
        _TIMELINE_INSTALLED = True
        from celestia_app_tpu.trace import timeline

        timeline.install(_default)
    return _default

"""Columnar event tracing (the pkg/trace + telemetry analog).

Parity with the reference's two tracing mechanisms (SURVEY §5): sdk
telemetry.MeasureSince around the ABCI hot methods
(app/prepare_proposal.go:23, app/process_proposal.go:25) and celestia-core
pkg/trace's columnar event tables written node-side and pulled for analysis.

Here both collapse into one in-process Tracer: named event tables holding
homogeneous dict rows, with a `span` context manager for wall-time
measurements (device kernel timings from jax block_until_ready land in the
same tables).  Export is JSONL per table, the same shape the reference's
table puller consumes (test/e2e/testnet/node.go:52-74).
"""

from __future__ import annotations

import json
import time
from collections import defaultdict
from contextlib import contextmanager


class Tracer:
    def __init__(self, buffer_size: int = 10_000):
        self.buffer_size = buffer_size
        self._tables: dict[str, list[dict]] = defaultdict(list)
        self.enabled = True

    def write(self, table: str, **row) -> None:
        if not self.enabled:
            return
        rows = self._tables[table]
        rows.append({"ts_ns": time.time_ns(), **row})
        if len(rows) > self.buffer_size:
            del rows[: len(rows) - self.buffer_size]

    @contextmanager
    def span(self, table: str, **attrs):
        """Measure a wall-time span into `table` (MeasureSince analog);
        the same measurement lands in the Prometheus histogram
        celestia_<table>_seconds for the /metrics exposition."""
        start = time.perf_counter_ns()
        try:
            yield
        finally:
            elapsed_ns = time.perf_counter_ns() - start
            self.write(table, duration_ms=elapsed_ns / 1e6, **attrs)
            if self.enabled:
                from celestia_app_tpu.trace.metrics import registry

                registry().histogram(
                    f"celestia_{table}_seconds", f"wall time of {table}"
                ).observe(elapsed_ns / 1e9)

    def table(self, name: str) -> list[dict]:
        return list(self._tables.get(name, []))

    def tables(self) -> list[str]:
        return sorted(self._tables)

    def export_jsonl(self, name: str) -> str:
        return "\n".join(json.dumps(r) for r in self._tables.get(name, []))

    def clear(self) -> None:
        self._tables.clear()


# Process-wide default tracer (the node wires its own when needed).
_default = Tracer()


def traced() -> Tracer:
    return _default

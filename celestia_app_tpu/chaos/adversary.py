"""Protocol adversaries: the chaos layer's attack model.

chaos/spec.py injects INFRASTRUCTURE faults — stalls, drops, torn writes.
This module injects ADVERSARIES: deterministic, seeded misbehaviour shaped
after the availability-attack model of the Polar Coded Merkle Tree papers
(arXiv 2301.08295 / 2201.07287 — a malicious block producer who commits a
root and then denies or corrupts the data behind it).  Three adversaries,
each behind its own $CELESTIA_CHAOS key:

    withhold_frac=<f>    the WITHHOLDING PROPOSER: commits the honest DAH
                         but hides a uniform-random fraction f of the EDS
                         shares from the serve path.  A DAS sample landing
                         on a withheld coordinate cannot be answered —
                         that failed sample IS the light client's
                         detection signal (serve/sampler.ShareWithheld),
                         and P(detect | s samples) = 1 - (1-f)^s is the
                         curve scripts/chaos_soak.py measures.
    malform_shares=<n>   MALFORMED-SQUARE INJECTION: after commit, n
                         share's bytes in the served square are corrupted
                         while the committed root stays honest.  Every
                         proof assembled over a corrupted share fails the
                         sampler's verification gate — detected, never
                         served as valid.
    wrong_root=1         WRONG-ROOT INJECTION: the served DAH data root
                         does not match the square.  No honest proof can
                         chain to it (sampler verification), and a repair
                         against it raises RootMismatch.

Determinism contract (stronger than the ordinal-draw seams): each
adversary derives its RNG from (spec seed, its own seam name, height,
square width) — `adversary.withhold`, `adversary.malform`,
`adversary.root` — so the withheld/corrupted coordinate set for a given
height is a pure function of the spec, independent of request order,
thread interleaving, or how many samples were already served.  The same
spec over the same chain withholds the same shares; the soak's honest leg
(every adversary key at 0) is bit-identical to no chaos at all.

Detections land on ONE family, `celestia_da_detections_total{kind}`
(kinds: withheld / bad_proof / root_mismatch), and each adversary event
black-boxes through its flight-recorder trigger (`withholding_detected`,
`root_mismatch`) — rate-limited, so a drill fires each exactly once.
"""

from __future__ import annotations

import hashlib
import random
import threading

import numpy as np

#: The $CELESTIA_CHAOS keys this module owns (chaos/spec.py admits them).
ADVERSARY_KEYS = ("withhold_frac", "malform_shares", "wrong_root")


def detections():
    """THE adversary-detection counter — repair and the serve plane both
    register through here so the family cannot fork."""
    from celestia_app_tpu.trace.metrics import registry

    return registry().counter(
        "celestia_da_detections_total",
        "data-availability attacks detected, by kind (root_mismatch: "
        "repair rejected an inconsistent survivor set or a wrong DAH; "
        "withheld / bad_proof: serve-plane sampler detections)",
    )


class Adversary:
    """The live adversary for one parsed chaos spec.

    Stateless between calls except for per-height memos (the tampered
    view of a square must be the SAME bytes on every request — a real
    attacker serves one corrupted square, not a fresh one per sample).
    """

    def __init__(self, seed: int, withhold_frac: float,
                 malform_shares: int, wrong_root: bool):
        self.seed = seed
        self.withhold_frac = min(max(withhold_frac, 0.0), 1.0)
        self.malform_shares = max(int(malform_shares), 0)
        self.wrong_root = bool(wrong_root)
        self._lock = threading.Lock()
        self._withheld: dict[tuple[int, int], frozenset] = {}
        self._malformed: dict[tuple[int, int], tuple] = {}
        self._tampered: dict[int, object] = {}

    @classmethod
    def from_params(cls, params: dict) -> "Adversary | None":
        """None when no adversary key is set — the fast path every
        honest request takes."""
        f = float(params.get("withhold_frac", 0.0))
        n = int(float(params.get("malform_shares", 0.0)))
        w = float(params.get("wrong_root", 0.0)) > 0
        if f <= 0 and n <= 0 and not w:
            return None
        return cls(int(params.get("seed", 0)), f, n, w)

    def _rng(self, seam: str, height: int, n: int) -> random.Random:
        """Per-seam, per-(height, width) RNG: the spec contract's
        interleaving independence, strengthened to request-order
        independence (the coordinate sets are pure functions)."""
        return random.Random(
            f"celestia-chaos:{self.seed}:{seam}:{height}:{n}"
        )

    # --- withholding proposer ----------------------------------------------
    def withheld_set(self, height: int, n: int) -> frozenset:
        """The withheld (row, col) set for one height's n x n EDS:
        floor(withhold_frac * n^2) coordinates drawn without
        replacement."""
        if self.withhold_frac <= 0:
            return frozenset()
        key = (height, n)
        with self._lock:
            cached = self._withheld.get(key)
            if cached is not None:
                return cached
        rng = self._rng("adversary.withhold", height, n)
        count = int(self.withhold_frac * n * n)
        flat = rng.sample(range(n * n), count)
        out = frozenset((i // n, i % n) for i in flat)
        with self._lock:
            self._withheld[key] = out
        return out

    def withholds(self, height: int, n: int, row: int, col: int) -> bool:
        return (row, col) in self.withheld_set(height, n)

    # --- malformed square ---------------------------------------------------
    def malformed_coords(self, height: int, n: int) -> tuple:
        if self.malform_shares <= 0:
            return ()
        key = (height, n)
        with self._lock:
            cached = self._malformed.get(key)
            if cached is not None:
                return cached
        rng = self._rng("adversary.malform", height, n)
        count = min(self.malform_shares, n * n)
        flat = rng.sample(range(n * n), count)
        out = tuple((i // n, i % n) for i in flat)
        with self._lock:
            self._malformed[key] = out
        return out

    def corrupt_square(self, height: int, eds_bytes: np.ndarray) -> np.ndarray:
        """A corrupted COPY of the (n, n, S) share array: one byte of
        each malformed share XOR-flipped (deterministic position), the
        rest untouched."""
        n = eds_bytes.shape[0]
        out = np.array(eds_bytes, copy=True)
        rng = self._rng("adversary.malform", height, n)
        for row, col in self.malformed_coords(height, n):
            pos = rng.randrange(out.shape[-1])
            out[row, col, pos] ^= 0xFF
        return out

    # --- wrong root ---------------------------------------------------------
    def forged_root(self, honest_root: bytes) -> bytes:
        """A deterministic root that is NOT the square's: committed by
        the adversarial proposer in place of the honest one."""
        return hashlib.sha256(
            b"celestia-adversary-wrong-root:" + honest_root
        ).digest()

    # --- serve-path tampering ----------------------------------------------
    def tampers(self) -> bool:
        return self.malform_shares > 0 or self.wrong_root

    def tamper_entry(self, entry):
        """The adversarial VIEW of one cached serve entry: corrupted
        share bytes (malform_shares) and/or a forged committed root
        (wrong_root), with the honest forests left in place — exactly
        the state a malicious proposer creates, where the committed
        structure and the served bytes disagree.  Memoized per height so
        every sample sees the same attack."""
        if not self.tampers():
            return entry
        with self._lock:
            cached = self._tampered.get(entry.height)
            if cached is not None:
                return cached
        import copy

        tampered = copy.copy(entry)
        if self.malform_shares > 0:
            self.count_injection("adversary.malform", "malform_shares")
        if self.wrong_root:
            self.count_injection("adversary.root", "wrong_root")
        if self.malform_shares > 0:
            eds_view = copy.copy(entry.eds)
            n = 2 * entry.k
            host = np.asarray(entry.eds._eds)
            eds_view._eds = self.corrupt_square(entry.height, host)
            # Never share the honest entry's memoized trees: the host
            # fallback must rebuild from the corrupted bytes.
            eds_view._tree_memo = {}
            tampered.eds = eds_view
        if self.wrong_root:
            tampered.data_root = self.forged_root(entry.data_root)
        with self._lock:
            self._tampered[entry.height] = tampered
        return tampered

    def invalidate_tampered(self, height: int) -> None:
        """Drop the memoized tampered view of one height.

        The memo's contract is "one attack serves ONE corrupted square",
        which holds only while the underlying height is the same state:
        after a repair-driven re-admission (serve/cache.ForestCache.put /
        readmit call this) the stale tampered copy would keep serving the
        PRE-heal bytes and hide the recovery until a restart.  The
        withheld/malformed coordinate SETS stay memoized — they are pure
        functions of the spec, and a still-active adversary re-tampers a
        freshly fetched square with exactly the same coordinates."""
        with self._lock:
            self._tampered.pop(height, None)

    def count_injection(self, seam: str, fault: str) -> None:
        """Adversary events ride the same injection accounting as the
        infrastructure seams (celestia_chaos_injections_total + the
        chaos_injection trace row)."""
        from celestia_app_tpu.trace.metrics import registry
        from celestia_app_tpu.trace.tracer import traced

        registry().counter(
            "celestia_chaos_injections_total",
            "chaos faults injected, by seam",
        ).inc(seam=seam)
        traced().write("chaos_injection", seam=seam, fault=fault)

"""Graceful degradation: the device-path circuit breaker and mode ladder.

The device pipeline sits on the consensus hot path, so a dispatch failure
must degrade LATENCY, never correctness.  All six lowerings of the
extend+DAH pipeline are bit-identical (pinned on the golden vectors), so
stepping down the ladder

    sharded_panel  ->  panel  ->  fused_epi  ->  fused  ->  staged  ->  host

changes how a block's roots are computed, never what they are — a
degraded validator keeps signing the same DAH roots as its healthy peers.

  * sharded_panel: the multi-chip panel partition for giant squares
    (kernels/panel_sharded.py, $CELESTIA_EXTEND_SHARDS on top of the
    panel seam) — collective programs over a device mesh, so it has the
    most infrastructure under it (ICI links, every chip in the mesh) and
    is the very first rung distrusted; a faulting collective (the chaos
    seam device.extend_shard, or any real mesh fault) falls to the
    single-device panel runner below, roots unchanged;
  * panel:  the panel-streamed lowering for giant squares
    (kernels/panel.py, $CELESTIA_PIPE_PANEL, selected PER square size
    via kernels/fused.pipeline_mode_for_k) — a host-driven loop of small
    jitted programs rather than one dispatch, so it is the rung with the
    most moving parts and the first distrusted; a faulting mid-panel
    dispatch falls to the materializing lowerings below;
  * fused_epi: the fused program with the leaf-hash epilogue (column
    extend feeds the bottom half's NMT leaf rounds from VMEM,
    kernels/rs_xor) — active only when the autotuner seats it
    ($CELESTIA_PIPE_FUSED=epi); its custom kernel is the most exotic
    lowering, so it is the first rung distrusted;
  * fused:  one donated single-dispatch jitted program (the default);
  * staged: the extend-then-hash jit pair (da/eds._pipeline) — the
    escape hatch when the fused program itself is what keeps faulting;
  * host:   the same staged composition executed EAGERLY (op-by-op, no
    compiled program dispatch) — the floor when compiled execution on
    this process keeps failing at all.

A process based below the top rung enters the ladder where its env put
it (base "fused" never climbs to "fused_epi"): degradation only ever
steps DOWN from the seated mode.

`guarded_dispatch` wraps every extend+DAH dispatch: bounded exponential
backoff retries within a rung, and a consecutive-failure circuit breaker
that steps the per-process ladder down one rung when a rung keeps
failing.  The ladder rides the existing `pipeline_mode()` seam
(kernels/fused.py consults `effective_device_mode`), so EVERY caller —
ExtendedDataSquare.compute, the BlockPipeline dispatcher, repair's
re-extend — degrades together and none can diverge.

State surfaces: `celestia_degraded{layer,mode}` (1 on the active
degraded mode), `celestia_recoveries_total{seam,outcome}` (retried /
degraded counts), and /healthz reports `{"status": "DEGRADED",
"degraded": {"device": "<mode>"}}` via trace/exposition.py.

Degradation is one-way per process (like a tripped breaker, it wants a
human or an orchestrator restart to re-arm): a device that flapped once
is not trusted back onto the hot path by timer.  `reset_for_tests()`
re-arms everything in-process.
"""

from __future__ import annotations

import threading
import time

LADDER = ("sharded_panel", "panel", "fused_epi", "fused", "staged", "host")

#: Consecutive same-rung dispatch failures before the breaker trips and
#: the ladder steps down ($CELESTIA_BREAKER_THRESHOLD).
DEFAULT_THRESHOLD = 3
#: Backoff between same-rung retries: base * 2^attempt, capped.
BACKOFF_BASE_S = 0.002
BACKOFF_CAP_S = 0.25


def _breaker_threshold() -> int:
    import os

    try:
        n = int(os.environ.get("CELESTIA_BREAKER_THRESHOLD", "") or 0)
    except ValueError:
        n = 0
    return n if n > 0 else DEFAULT_THRESHOLD


def recoveries():
    """The shared fault-survival counter — the ONE registration every
    seam's recovery accounting (ladder, WAL salvage, gossip resend) goes
    through, so the name and help text cannot fork."""
    from celestia_app_tpu.trace.metrics import registry

    return registry().counter(
        "celestia_recoveries_total",
        "faults survived, by seam and how (retried / degraded / salvaged "
        "/ resent / gave_up)",
    )


_recoveries = recoveries  # internal alias (module-local call sites)


class CircuitBreaker:
    """Consecutive-failure breaker: `record_failure` returns True once
    the failure streak reaches the threshold.  `>=`, not `==`: the
    floor-of-the-ladder raise path leaves the streak AT the threshold,
    and an exact-equality check would let the count sail past it on the
    next caller — which would then retry forever instead of tripping."""

    def __init__(self, threshold: int | None = None):
        self._threshold = threshold
        self._failures = 0
        self._lock = threading.Lock()

    @property
    def threshold(self) -> int:
        return self._threshold or _breaker_threshold()

    def record_failure(self) -> bool:
        with self._lock:
            self._failures += 1
            return self._failures >= self.threshold

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0

    def reset(self) -> None:
        self.record_success()


class DeviceDegradation:
    """Per-process floor on the pipeline mode ladder."""

    def __init__(self):
        self._lock = threading.Lock()
        self._floor = 0  # index into LADDER; 0 = nothing degraded

    def effective_mode(self, base: str) -> str:
        """The mode callers should run: the env-selected base, unless the
        ladder has degraded past it."""
        with self._lock:
            floor = self._floor
        if floor == 0:
            return base
        return LADDER[max(LADDER.index(base), floor)]

    def degrade(self, base: str, observed: str | None = None) -> str | None:
        """Step one rung down from the current effective mode; returns the
        new (or already-stepped-to) mode, or None when already at the
        floor of the ladder.

        `observed` is the rung the CALLER saw fail: when another thread's
        concurrent breaker trip already stepped past it, this call
        returns the current mode WITHOUT stepping again — otherwise one
        burst of failures on two threads would double-step the one-way
        ladder and park the process on the host floor without the staged
        rung (possibly perfectly healthy) ever being tried."""
        with self._lock:
            cur = max(LADDER.index(base), self._floor)
            if observed is not None and LADDER.index(observed) < cur:
                return LADDER[cur]  # a concurrent trip already stepped
            if cur >= len(LADDER) - 1:
                return None
            nxt = cur + 1
            if LADDER[cur] == "panel":
                # Stepping off the panel rung lands on the process's
                # MATERIALIZING base — the rung warmup/autotuning seated
                # (usually "fused") — never on a colder in-between
                # variant nothing compiled: a giant-k fused_epi compile
                # on the consensus hot path is exactly the stall the
                # ladder exists to avoid.
                nxt = max(LADDER.index(_env_base_mode()), nxt)
            self._floor = nxt
            new = LADDER[self._floor]
        self._publish(new)
        _recoveries().inc(seam="device.dispatch", outcome="degraded")
        import sys

        print(f"device pipeline degraded to {new!r} "
              f"(breaker tripped on repeated dispatch failure)",
              file=sys.stderr)
        # Black-box the moment of the trip: the journal rows explaining
        # WHY are still in the ring buffers right now; in an hour they
        # won't be.  note_trigger never raises and rate-limits itself.
        from celestia_app_tpu.trace.flight_recorder import note_trigger

        note_trigger("breaker_trip", layer="device", mode=new,
                     observed=observed, base=base)
        return new

    def state(self) -> dict | None:
        """{"device": mode} when degraded, else None (the /healthz face)."""
        with self._lock:
            floor = self._floor
        return {"device": LADDER[floor]} if floor else None

    def reset(self) -> None:
        with self._lock:
            self._floor = 0
        self._publish(None)

    def _publish(self, active: str | None) -> None:
        from celestia_app_tpu.trace.metrics import registry

        gauge = registry().gauge(
            "celestia_degraded",
            "1 on the active degraded mode per layer (all 0 when healthy)",
        )
        for mode in LADDER[1:]:
            gauge.set(1.0 if mode == active else 0.0,
                      layer="device", mode=mode)


DEVICE_DEGRADATION = DeviceDegradation()
DEVICE_BREAKER = CircuitBreaker()


def effective_device_mode(base: str) -> str:
    return DEVICE_DEGRADATION.effective_mode(base)


def degraded_state() -> dict | None:
    return DEVICE_DEGRADATION.state()


def reset_for_tests() -> None:
    DEVICE_DEGRADATION.reset()
    DEVICE_BREAKER.reset()


def note_async_device_failure(observed: str, base: str | None = None) -> None:
    """Feed a DEFERRED device-execution failure into the breaker.

    JAX dispatch is an async enqueue: a real execution fault often
    surfaces at a later sync (the pipeline drain's block_until_ready, a
    host read) where guarded_dispatch cannot catch it.  The block that
    hit the fault is lost either way — its caller sees the error — but
    routing the failure through the breaker here means a PERSISTENT
    deferred fault still steps the ladder, so future blocks move off the
    failing rung instead of dying one by one.

    `base` is the caller's base rung when it runs a per-k seat above the
    env base (the panel lowering): degrade() steps relative to it, so a
    persistent panel fault moves future giant blocks off the panel rung
    instead of being mistaken for an already-handled concurrent trip."""
    if DEVICE_BREAKER.record_failure():
        if DEVICE_DEGRADATION.degrade(
            base or _env_base_mode(), observed=observed
        ) is not None:
            DEVICE_BREAKER.reset()
        else:
            # Already on the ladder floor: degrade() (which black-boxes
            # the step) did nothing, but a PERSISTENT deferred fault at
            # the floor is exactly a flight-recorder moment — capture it
            # here (rate-limited) since no step will.
            from celestia_app_tpu.trace.flight_recorder import note_trigger

            note_trigger("breaker_trip", layer="device", mode="host",
                         observed=observed, at_floor=True)


def guarded_dispatch(resolve, x, *, refresh=None,
                     breaker: CircuitBreaker | None = None,
                     sleep=time.sleep, k: int | None = None):
    """One extend+DAH dispatch with chaos injection, bounded retry, and
    ladder fallback.

    `resolve(mode)` returns the pipeline callable for that lowering (the
    caller owns cache policy and donation semantics).  Returns
    (mode, outputs) so the caller can journal the mode that actually ran.

    `k` routes the dispatch through the PER-SQUARE-SIZE mode seam
    (kernels/fused.pipeline_mode_for_k): the panel-streamed lowering only
    engages for the square sizes $CELESTIA_PIPE_PANEL names, so the
    active rung — and the base the ladder degrades from — depends on k.
    Callers without a per-k seat (repair's re-extend, which wants the
    materializing full-EDS path anyway) omit it and ride the process
    mode as before.

    Each rung gets `threshold` attempts with exponential backoff; when a
    rung's streak trips the breaker the ladder steps down and the next
    rung starts with a fresh streak.  Only when the HOST rung (eager,
    no compiled dispatch) also exhausts its streak does the failure
    propagate — at that point the process genuinely cannot compute roots.

    Retry safety: the chaos seam raises BEFORE the real dispatch, so the
    input is intact on an injected fault.  A REAL mid-dispatch failure of
    a donating program may have consumed its buffer — callers that donate
    pass `refresh` (rebuilds the device input from a host copy), and it
    runs before any retry that follows a non-injected failure.
    """
    from celestia_app_tpu import chaos
    from celestia_app_tpu.chaos.spec import ChaosInjected
    from celestia_app_tpu.kernels.fused import (
        env_base_mode_for_k,
        pipeline_mode,
        pipeline_mode_for_k,
    )

    if k is None:
        mode_of, base_of = pipeline_mode, _env_base_mode
    else:
        def mode_of(): return pipeline_mode_for_k(k)
        def base_of(): return env_base_mode_for_k(k)
    breaker = breaker or DEVICE_BREAKER
    attempt = 0
    # Per-CALL termination backstop, independent of the shared breaker:
    # the breaker counts CONSECUTIVE process-wide failures, so a caller
    # whose dispatches persistently fail while a concurrent caller keeps
    # succeeding (each success zeroes the shared streak) would otherwise
    # retry forever without ever tripping it.  Enough budget to walk the
    # whole ladder twice over before giving up.
    total_attempts = 0
    attempt_cap = max(breaker.threshold, 1) * 2 * len(LADDER)
    while True:
        mode = mode_of()  # re-read: a degrade below moves it
        try:
            chaos.device_dispatch(mode)
            out = resolve(mode)(x)
            breaker.record_success()
            if attempt:
                _recoveries().inc(seam="device.dispatch", outcome="retried")
            return mode, out
        except Exception as e:  # chaos-ok: every rung retries, the floor re-raises
            if (refresh is not None and mode in ("fused", "fused_epi")
                    and not isinstance(e, ChaosInjected)):
                # Only the fused-family rungs donate, so only THEIR real
                # failures can have consumed the input; refresh is itself
                # guarded — an upload blip during recovery must feed the
                # normal retry/degrade accounting, not abort it.
                try:
                    x = refresh()
                except Exception:  # chaos-ok: next attempt re-lands here
                    pass
            total_attempts += 1
            if total_attempts >= attempt_cap:
                raise  # this call alone has failed across the whole budget
            if breaker.record_failure():
                if DEVICE_DEGRADATION.degrade(
                    base_of(), observed=mode
                ) is not None:
                    breaker.reset()
                    attempt = 0
                    continue  # fresh streak on the new rung
                raise  # host rung exhausted: nothing left to degrade to
            sleep(min(BACKOFF_BASE_S * (2 ** attempt), BACKOFF_CAP_S))
            attempt += 1


def _env_base_mode() -> str:
    """The env-selected base mode, WITHOUT the ladder applied (degrade()
    must step relative to it, not to its own output).  One parse lives in
    kernels/fused.py; both imports are lazy, so no cycle."""
    from celestia_app_tpu.kernels.fused import env_base_mode

    return env_base_mode()

"""Chaos seams + graceful degradation (see chaos/spec.py, chaos/degrade.py).

Hot paths call the module-level seam helpers below; with no chaos
configured each is one cached-injector check (no env parse, no RNG draw),
so the seams cost nothing in production.

Activation, in precedence order:
  * `install(spec_str)` — programmatic (tests, scripts/chaos_soak.py);
  * `$CELESTIA_CHAOS`    — the env spec, re-parsed when the string changes
    so a test flipping the env mid-process takes effect.

`uninstall()` drops a programmatic install; `reset_for_tests()` (from
chaos.degrade) additionally re-arms the breaker and ladder.
"""

from __future__ import annotations

import os

from celestia_app_tpu.chaos.spec import (  # noqa: F401  (public surface)
    SEAMS,
    ChaosInjected,
    ChaosInjector,
    parse_spec,
    validate_params,
)

_INSTALLED: ChaosInjector | None = None
# (raw env string, injector-or-None) — the parse cache for the env path.
_ENV_CACHE: tuple[str, ChaosInjector | None] = ("", None)


def install(spec: str | dict) -> ChaosInjector:
    """Install a chaos spec for this process (overrides $CELESTIA_CHAOS)."""
    global _INSTALLED
    # Both activation shapes get key validation — a typo'd fault name in
    # a dict must fail as loudly as one in the env string.
    params = (
        parse_spec(spec) if isinstance(spec, str)
        else validate_params(dict(spec))
    )
    _INSTALLED = ChaosInjector(
        params, raw=spec if isinstance(spec, str) else ""
    )
    return _INSTALLED


def uninstall() -> None:
    global _INSTALLED
    _INSTALLED = None


def injector() -> ChaosInjector | None:
    """The active injector, or None when no chaos is configured."""
    global _ENV_CACHE
    if _INSTALLED is not None:
        return _INSTALLED
    raw = os.environ.get("CELESTIA_CHAOS", "")
    cached_raw, cached = _ENV_CACHE
    if raw == cached_raw:
        return cached
    inj = ChaosInjector(parse_spec(raw), raw=raw) if raw.strip() else None
    _ENV_CACHE = (raw, inj)
    return inj


# --- seam helpers (the names hot paths import) ------------------------------

def device_dispatch(mode: str) -> None:
    inj = injector()
    if inj is not None:
        inj.device_dispatch(mode)


def device_upload() -> None:
    inj = injector()
    if inj is not None:
        inj.device_upload()


def gossip_send() -> dict:
    inj = injector()
    return inj.gossip_send() if inj is not None else {}


def wal_torn_tail() -> bytes | None:
    inj = injector()
    return inj.wal_torn_tail() if inj is not None else None


def rpc_handle() -> None:
    inj = injector()
    if inj is not None:
        inj.rpc_handle()


def mempool_insert(shard: int | None = None) -> bool:
    inj = injector()
    return inj.mempool_insert(shard=shard) if inj is not None else False


def proof_serve() -> None:
    inj = injector()
    if inj is not None:
        inj.proof_serve()


def proof_verify() -> None:
    inj = injector()
    if inj is not None:
        inj.proof_verify()


def proof_shard() -> None:
    inj = injector()
    if inj is not None:
        inj.proof_shard()


def extend_shard() -> None:
    inj = injector()
    if inj is not None:
        inj.extend_shard()


def active_adversary():
    """The active protocol adversary (chaos/adversary.Adversary), or
    None — honest paths and specs with every adversary key at 0 both
    land here.  (Named to avoid shadowing by the chaos.adversary
    submodule attribute once that module is imported.)"""
    inj = injector()
    return inj.adversary() if inj is not None else None

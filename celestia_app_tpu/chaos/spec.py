"""$CELESTIA_CHAOS: seeded, deterministic fault injection.

Data-availability systems are designed to survive adversarial and faulty
conditions (ACeD; Polar Coded Merkle Tree) — but a design survives only
what its code actually exercises.  This module turns a one-line spec into
an injection registry over NAMED SEAMS, the points where this node talks
to something that can fail:

    device.dispatch   the extend+DAH program dispatch (da/eds, BlockPipeline)
    device.upload     the host->device share transfer (BlockPipeline feeder)
    gossip.send       one consensus message to one peer (rpc/gossip)
    wal.append        one consensus WAL record append+fsync (consensus/wal)
    rpc.handle        one JSON-RPC request (rpc/server)
    mempool.insert    one tx admission (mempool; fires PER SHARD — each
                      namespace shard draws from its own seeded RNG
                      stream, so the injection set a shard sees depends
                      only on the spec and that shard's admission
                      ordinals, never on how admissions interleave
                      across shards/threads)
    proof.serve       one batched DAS proof dispatch (serve/sampler)
    proof.verify      one batched proof VERIFICATION dispatch
                      (serve/verify) — the read side's verify twin

Spec grammar — comma-separated `key=value` pairs, e.g.

    CELESTIA_CHAOS="seed=7,dispatch_fail=0.05,upload_stall_ms=200,\
gossip_drop=0.1,wal_torn_tail=1,rpc_slow_ms=100"

    seed=<int>            per-seam RNG seed (default 0)
    dispatch_fail=<p>     device.dispatch raises (panel/fused lowerings
                          only, so the degradation ladder has somewhere
                          to go; dispatch_fail_all=1 widens it to every
                          rung)
    dispatch_stall_ms=<ms> [dispatch_stall=<p>, default 1.0 when ms set]
    upload_fail=<p>       device.upload raises
    upload_stall_ms=<ms>  [upload_stall=<p>]
    gossip_drop=<p>       message silently lost after "send"
    gossip_dup=<p>        message delivered twice (dedup must absorb it)
    gossip_delay_ms=<ms>  [gossip_reorder=<p>] delayed delivery, so later
                          messages overtake it (reordering)
    wal_torn_tail=<n>     the first n WAL appends leave a torn partial
                          record at the tail (crash mid-write)
    rpc_slow_ms=<ms>      [rpc_slow=<p>] request handling stalls
    rpc_fail=<p>          request fails with an injected server error
    mempool_drop=<p>      admission transiently rejects
    mempool_slow_ms=<ms>  [mempool_slow=<p>]
    proof_fail=<p>        batched proof dispatch raises (host fallback
                          must answer bit-identically)
    proof_slow_ms=<ms>    [proof_slow=<p>] proof dispatch stalls
    verify_fail=<p>       batched proof VERIFICATION raises (serve/verify
                          must fall back to the per-proof host verify
                          with an identical accept/reject vector)
    shard_fail=<p>        SHARDED forest gather raises (serve/shard):
                          the gather degrades to the single-device
                          batched path, then — compounded with
                          proof_fail — to the host rung, every rung
                          bit-identical
    extend_shard_fail=<p> SHARDED extend+DAH dispatch raises mid-
                          collective (kernels/panel_sharded): the
                          ladder walks sharded_panel -> panel (the
                          single-device runner) with roots unchanged

Protocol ADVERSARIES (chaos/adversary.py — attack model, not fault
model; deterministic per (seed, height) rather than per call ordinal):

    withhold_frac=<f>     withholding proposer: hide a random fraction f
                          of each height's EDS shares from the serve path
                          (honest root committed; a DAS sample hitting a
                          withheld share is the detection signal)
    malform_shares=<n>    corrupt n served shares' bytes post-commit
                          (sampler verification must detect)
    wrong_root=1          served DAH data root does not match the square
                          (sampler verification / repair RootMismatch)

Determinism: every seam draws from its OWN `random.Random` seeded by
(seed, seam name), so the injection sequence a seam sees depends only on
the spec and that seam's call ordinals — never on how calls from
different seams interleave across threads.  The same spec over the same
workload injects the same faults; scripts/chaos_soak.py leans on this to
assert bit-identical DAH roots under failure.

Every fired fault ticks `celestia_chaos_injections_total{seam}` and
writes a `chaos_injection` trace row, so a soak can print per-seam
injection counts and a test can assert a seam actually fired.
"""

from __future__ import annotations

import random
import threading
import time


class ChaosInjected(RuntimeError):
    """An injected fault (never raised unless chaos is configured)."""

    def __init__(self, seam: str, fault: str):
        super().__init__(f"chaos: injected {fault} at {seam}")
        self.seam = seam
        self.fault = fault


SEAMS = (
    "device.dispatch",
    "device.upload",
    "gossip.send",
    "wal.append",
    "rpc.handle",
    "mempool.insert",
    "proof.serve",
    "proof.verify",
    "proof.shard",
    "device.extend_shard",
)

_KNOWN_KEYS = {
    "seed",
    "dispatch_fail", "dispatch_fail_all", "dispatch_stall_ms",
    "dispatch_stall",
    "upload_fail", "upload_stall_ms", "upload_stall",
    "gossip_drop", "gossip_dup", "gossip_delay_ms", "gossip_reorder",
    "wal_torn_tail",
    "rpc_slow_ms", "rpc_slow", "rpc_fail",
    "mempool_drop", "mempool_slow_ms", "mempool_slow",
    "proof_fail", "proof_slow_ms", "proof_slow",
    "verify_fail",
    "shard_fail",
    "extend_shard_fail",
    "withhold_frac", "malform_shares", "wrong_root",
}


def validate_params(params: dict) -> dict[str, float]:
    """Reject unknown fault keys: a chaos run with a typo'd fault
    silently testing nothing is worse than no run at all.  Applied to
    BOTH activation paths (string spec and programmatic dict)."""
    unknown = set(params) - _KNOWN_KEYS
    if unknown:
        raise ValueError(
            f"chaos spec: unknown keys {sorted(unknown)!r} "
            f"(known: {sorted(_KNOWN_KEYS)!r})"
        )
    return {k: float(v) for k, v in params.items()}


def parse_spec(raw: str) -> dict[str, float]:
    """`"k=v,k=v"` -> {key: float}.  Unknown keys and malformed pairs
    raise ValueError (see validate_params)."""
    out: dict[str, float] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        key, eq, val = part.partition("=")
        key = key.strip()
        if not eq or key not in _KNOWN_KEYS:
            raise ValueError(f"chaos spec: unknown entry {part!r}")
        try:
            out[key] = float(val.strip())
        except ValueError:
            raise ValueError(f"chaos spec: bad value in {part!r}") from None
    return out


class ChaosInjector:
    """The live injection registry for one parsed spec.

    Thread-safe: each seam's RNG and ordinal counter sit behind one lock
    (seam decisions are a few float draws — contention is irrelevant next
    to the faults being injected)."""

    def __init__(self, params: dict[str, float], raw: str = ""):
        self.params = dict(params)
        self.raw = raw
        self.seed = int(self.params.get("seed", 0))
        self._lock = threading.Lock()
        self._rngs = {
            seam: random.Random(f"celestia-chaos:{self.seed}:{seam}")
            for seam in SEAMS
        }
        # Per-SHARD streams of the sharded seams (today: mempool.insert),
        # created lazily per shard index; keyed like the adversary's
        # per-height streams so each shard's injection sequence is a pure
        # function of (seed, seam, shard, ordinal).
        self._shard_rngs: dict[tuple[str, int], random.Random] = {}
        self._torn_remaining = int(self.params.get("wal_torn_tail", 0))
        # Lazily-built protocol adversary (chaos/adversary.py); None when
        # no adversary key is set, so honest paths pay one attr read.
        self._adversary = None
        self._adversary_built = False

    def adversary(self):
        """The spec's protocol adversary, or None when every adversary
        key is absent/zero (the honest fast path)."""
        with self._lock:
            if not self._adversary_built:
                from celestia_app_tpu.chaos.adversary import Adversary

                self._adversary = Adversary.from_params(self.params)
                self._adversary_built = True
            return self._adversary

    # --- plumbing -----------------------------------------------------------
    def _p(self, key: str) -> float:
        return float(self.params.get(key, 0.0))

    def _fire(self, seam: str, key: str, default: float = 0.0,
              shard: int | None = None) -> bool:
        p = float(self.params.get(key, default))
        if p <= 0.0:
            return False
        with self._lock:
            if shard is None:
                return p >= 1.0 or self._rngs[seam].random() < p
            rng = self._shard_rngs.get((seam, shard))
            if rng is None:
                rng = self._shard_rngs[(seam, shard)] = random.Random(
                    f"celestia-chaos:{self.seed}:{seam}#{shard}"
                )
            return p >= 1.0 or rng.random() < p

    def _count(self, seam: str, fault: str) -> None:
        from celestia_app_tpu.trace.metrics import registry
        from celestia_app_tpu.trace.tracer import traced

        registry().counter(
            "celestia_chaos_injections_total",
            "chaos faults injected, by seam",
        ).inc(seam=seam)
        traced().write("chaos_injection", seam=seam, fault=fault)

    def _stall(self, seam: str, ms_key: str, p_key: str) -> bool:
        ms = self._p(ms_key)
        if ms > 0 and self._fire(seam, p_key, default=1.0):
            self._count(seam, ms_key)
            time.sleep(ms / 1e3)
            return True
        return False

    # --- seams --------------------------------------------------------------
    def device_dispatch(self, mode: str) -> None:
        """Stall and/or fail one extend+DAH dispatch.  `dispatch_fail`
        targets the compiled-program family the ladder can step away
        from — "fused", the leaf-hash-epilogue "fused_epi" rung above
        it, and the panel-streamed "panel" rung above both (whose
        host-driven loop passes this seam once per panel dispatch, so an
        injection lands MID-panel) — unless `dispatch_fail_all` widens
        it to every rung."""
        self._stall("device.dispatch", "dispatch_stall_ms", "dispatch_stall")
        applies = (mode in ("sharded_panel", "panel", "fused", "fused_epi")
                   or self._p("dispatch_fail_all") > 0)
        if applies and self._fire("device.dispatch", "dispatch_fail"):
            self._count("device.dispatch", "dispatch_fail")
            raise ChaosInjected("device.dispatch", "dispatch_fail")

    def device_upload(self) -> None:
        self._stall("device.upload", "upload_stall_ms", "upload_stall")
        if self._fire("device.upload", "upload_fail"):
            self._count("device.upload", "upload_fail")
            raise ChaosInjected("device.upload", "upload_fail")

    def gossip_send(self) -> dict:
        """Per-message verdict for one peer send: {} on the happy path,
        else any of drop=True, dup=True, delay_s=<float>."""
        out: dict = {}
        if self._fire("gossip.send", "gossip_drop"):
            self._count("gossip.send", "gossip_drop")
            out["drop"] = True
            return out  # a dropped message is neither duplicated nor late
        if self._fire("gossip.send", "gossip_dup"):
            self._count("gossip.send", "gossip_dup")
            out["dup"] = True
        delay_ms = self._p("gossip_delay_ms")
        if delay_ms > 0 and self._fire("gossip.send", "gossip_reorder",
                                       default=1.0):
            self._count("gossip.send", "gossip_delay_ms")
            out["delay_s"] = delay_ms / 1e3
        return out

    def wal_torn_tail(self) -> bytes | None:
        """The partial record to leave at the WAL tail after this append
        (crash mid-write of the NEXT record), for the first
        `wal_torn_tail` appends; None afterwards."""
        with self._lock:
            if self._torn_remaining <= 0:
                return None
            self._torn_remaining -= 1
        self._count("wal.append", "wal_torn_tail")
        # A prefix of a plausible record, no terminating newline: exactly
        # the bytes a crash between write() and completion leaves behind.
        return b'{"k":"vote","h":9999999,"r":0,"t"'

    def rpc_handle(self) -> None:
        self._stall("rpc.handle", "rpc_slow_ms", "rpc_slow")
        if self._fire("rpc.handle", "rpc_fail"):
            self._count("rpc.handle", "rpc_fail")
            raise ChaosInjected("rpc.handle", "rpc_fail")

    def mempool_insert(self, shard: int | None = None) -> bool:
        """True when this admission should be transiently rejected.
        `shard` selects that namespace shard's OWN seeded RNG stream
        (None keeps the legacy per-seam stream), so a sharded pool's
        injection sets are interleaving-independent across shards."""
        self._stall("mempool.insert", "mempool_slow_ms", "mempool_slow")
        if self._fire("mempool.insert", "mempool_drop", shard=shard):
            self._count("mempool.insert", "mempool_drop")
            return True
        return False

    def proof_serve(self) -> None:
        """Stall and/or fail one BATCHED proof dispatch (serve/sampler):
        the sampler must absorb the failure by answering the batch on the
        pure-host path with bit-identical proof bytes — the serve plane's
        analog of the extend pipeline's fused->staged seam."""
        self._stall("proof.serve", "proof_slow_ms", "proof_slow")
        if self._fire("proof.serve", "proof_fail"):
            self._count("proof.serve", "proof_fail")
            raise ChaosInjected("proof.serve", "proof_fail")

    def proof_verify(self) -> None:
        """Fail one BATCHED proof-verification dispatch (serve/verify):
        the verifier must absorb the failure by re-deciding the whole
        queue on the per-proof host path with an IDENTICAL accept/reject
        vector — the read side's verify twin of the proof.serve seam."""
        if self._fire("proof.verify", "verify_fail"):
            self._count("proof.verify", "verify_fail")
            raise ChaosInjected("proof.verify", "verify_fail")

    def proof_shard(self) -> None:
        """Fail one SHARDED forest gather (serve/shard): the gather must
        degrade to the single-device batched path — and, when proof_fail
        compounds the injection, on down to the host rung — with
        bit-identical proof bytes at every rung (the read-side ladder's
        top seam)."""
        if self._fire("proof.shard", "shard_fail"):
            self._count("proof.shard", "shard_fail")
            raise ChaosInjected("proof.shard", "shard_fail")

    def extend_shard(self) -> None:
        """Fail one SHARDED extend+DAH dispatch (kernels/panel_sharded:
        the seam fires between the host-driven collective programs, so
        an injection lands MID-collective-schedule).  guarded_dispatch
        must walk the ladder sharded_panel -> panel — the single-device
        runner — with bit-identical roots (the write-side ladder's top
        seam)."""
        if self._fire("device.extend_shard", "extend_shard_fail"):
            self._count("device.extend_shard", "extend_shard_fail")
            raise ChaosInjected("device.extend_shard", "extend_shard_fail")

"""Protocol constants (parity with reference pkg/appconsts).

These cannot change during the lifetime of a network.  Sources (reference,
for parity checking only): pkg/appconsts/global_consts.go:15-78,
pkg/appconsts/v1/app_consts.go:3-7, pkg/appconsts/v2/app_consts.go,
pkg/appconsts/initial_consts.go, pkg/appconsts/consensus_consts.go.
"""

from fractions import Fraction

# --- share geometry (global_consts.go) ---
NAMESPACE_VERSION_SIZE = 1
NAMESPACE_ID_SIZE = 28
NAMESPACE_SIZE = NAMESPACE_VERSION_SIZE + NAMESPACE_ID_SIZE  # 29
SHARE_SIZE = 512
SHARE_INFO_BYTES = 1
SEQUENCE_LEN_BYTES = 4
SHARE_VERSION_ZERO = 0
DEFAULT_SHARE_VERSION = SHARE_VERSION_ZERO
MAX_SHARE_VERSION = 127
COMPACT_SHARE_RESERVED_BYTES = 4

FIRST_COMPACT_SHARE_CONTENT_SIZE = (
    SHARE_SIZE - NAMESPACE_SIZE - SHARE_INFO_BYTES - SEQUENCE_LEN_BYTES - COMPACT_SHARE_RESERVED_BYTES
)  # 474
CONTINUATION_COMPACT_SHARE_CONTENT_SIZE = (
    SHARE_SIZE - NAMESPACE_SIZE - SHARE_INFO_BYTES - COMPACT_SHARE_RESERVED_BYTES
)  # 478
FIRST_SPARSE_SHARE_CONTENT_SIZE = (
    SHARE_SIZE - NAMESPACE_SIZE - SHARE_INFO_BYTES - SEQUENCE_LEN_BYTES
)  # 478
CONTINUATION_SPARSE_SHARE_CONTENT_SIZE = SHARE_SIZE - NAMESPACE_SIZE - SHARE_INFO_BYTES  # 482

MIN_SQUARE_SIZE = 1
MIN_SHARE_COUNT = MIN_SQUARE_SIZE * MIN_SQUARE_SIZE

# The parity-share namespace (29 x 0xFF): assigned to every erasure-coded
# leaf outside Q0 and the trigger for the NMT ignore-max rule.  Single
# source of truth — shares.PARITY_SHARE_NAMESPACE and all device kernels
# derive from this.
PARITY_NAMESPACE_BYTES = bytes([0xFF]) * NAMESPACE_SIZE

# --- hashing ---
HASH_LENGTH = 32  # SHA-256
NMT_NODE_SIZE = 2 * NAMESPACE_SIZE + HASH_LENGTH  # 90: minNs || maxNs || digest

# --- versioned consts (v1/app_consts.go, v2/app_consts.go; constant across v1/v2) ---
V1_VERSION = 1
V2_VERSION = 2
LATEST_VERSION = V2_VERSION
SQUARE_SIZE_UPPER_BOUND = 128
# Codec capability bound: the largest ODS the DA pipeline kernels support.
# Wider than the versioned protocol cap (128) because the reference's own
# e2e benchmarks push 512-class squares; app-level validation still enforces
# square_size_upper_bound() per app version.  Raised 512 -> 2048 with the
# giant-square frontier (O(n log n) FFT encode + panel-streamed extend+DAH,
# $CELESTIA_PIPE_PANEL): GF(2^16) covers codewords to 65536 symbols, so the
# bound is memory discipline, not field arithmetic — and the panel pipeline
# is that discipline.  Raised 2048 -> 4096 with the multi-chip sharded
# extend ($CELESTIA_EXTEND_SHARDS, kernels/panel_sharded.py): per-device
# share residency is half-EDS/N + one panel, so the square a mesh can hold
# scales with the mesh — 2*4096 = 8192-symbol codewords remain far inside
# GF(2^16)'s 65536-symbol reach.
MAX_CODEC_SQUARE_SIZE = 4096
SUBTREE_ROOT_THRESHOLD = 64
# Exact decimal (consensus-critical): binary floats would diverge from peers
# doing exact-decimal arithmetic on fee boundaries.
NETWORK_MIN_GAS_PRICE = Fraction(1, 10**6)  # utia per gas (v2+, x/minfee)


def subtree_root_threshold(_app_version: int = LATEST_VERSION) -> int:
    return SUBTREE_ROOT_THRESHOLD


def square_size_upper_bound(_app_version: int = LATEST_VERSION) -> int:
    return SQUARE_SIZE_UPPER_BOUND


# --- initial (governance-modifiable) params (initial_consts.go) ---
DEFAULT_GOV_MAX_SQUARE_SIZE = 64
DEFAULT_MAX_BYTES = (
    DEFAULT_GOV_MAX_SQUARE_SIZE * DEFAULT_GOV_MAX_SQUARE_SIZE * CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
)
DEFAULT_GAS_PER_BLOB_BYTE = 8
DEFAULT_MIN_GAS_PRICE = Fraction(2, 1000)  # utia per gas (node-local default)
DEFAULT_UNBONDING_TIME_SECONDS = 3 * 7 * 24 * 3600
BOND_DENOM = "utia"

# --- consensus timing (consensus_consts.go) ---
TIMEOUT_PROPOSE_SECONDS = 10
TIMEOUT_COMMIT_SECONDS = 11
GOAL_BLOCK_TIME_SECONDS = 15

# --- PFB gas (x/blob/types/payforblob.go) ---
PFB_GAS_FIXED_COST = 75_000
BYTES_PER_BLOB_INFO = 70

# Square sizes the framework precompiles kernels for (powers of two).
SUPPORTED_SQUARE_SIZES = tuple(1 << i for i in range(10))  # 1..512

"""x/authz: grant another account the authority to execute msgs for you.

The reference wires cosmos-sdk x/authz (app/modules.go:153-155).  A
granter issues a Grant (authorization + optional expiration) to a grantee;
the grantee then submits MsgExec wrapping messages whose *inner* signer is
the granter — the app checks each inner msg against the grant before
dispatching it through the normal handlers.

Authorization types (sdk authz semantics):

  * GenericAuthorization: unconditional authority over one msg type URL;
  * SendAuthorization: bank sends up to a rolling spend limit (the limit
    decrements per accepted send; exhausted grants prune themselves).
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    encode_bytes_field,
    encode_varint_field,
)
from celestia_app_tpu.state.store import KVStore

_GRANT_PREFIX = b"authz/"

URL_GENERIC_AUTHORIZATION = "/cosmos.authz.v1beta1.GenericAuthorization"
URL_SEND_AUTHORIZATION = "/cosmos.bank.v1beta1.SendAuthorization"
URL_MSG_SEND = "/cosmos.bank.v1beta1.MsgSend"


class AuthzError(ValueError):
    pass


@dataclass(frozen=True)
class Grant:
    """authorization for one msg type URL; spend_limit applies only to
    SendAuthorization (0 = generic/no limit)."""

    msg_type_url: str
    spend_limit: int = 0
    expiration_ns: int = 0  # 0 = never

    def marshal(self) -> bytes:
        return (
            encode_bytes_field(1, self.msg_type_url.encode())
            + encode_varint_field(2, self.spend_limit)
            + encode_varint_field(3, self.expiration_ns)
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Grant":
        url = ""
        ints = {}
        for n, wt, v in decode_fields(raw):
            if n == 1 and wt == WIRE_LEN:
                url = v.decode()
            elif wt == WIRE_VARINT:
                ints[n] = v
        return cls(url, ints.get(2, 0), ints.get(3, 0))


class AuthzKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    def _key(self, granter: str, grantee: str, url: str) -> bytes:
        return (
            _GRANT_PREFIX + granter.encode() + b"/" + grantee.encode()
            + b"/" + url.encode()
        )

    def grant(self, granter: str, grantee: str, g: Grant) -> None:
        """MsgGrant: overwrites an existing grant for the same
        (granter, grantee, msg type) — sdk SaveGrant semantics."""
        if granter == grantee:
            raise AuthzError("cannot self-grant")
        if not g.msg_type_url:
            raise AuthzError("authorization needs a msg type url")
        self.store.set(self._key(granter, grantee, g.msg_type_url), g.marshal())

    def revoke(self, granter: str, grantee: str, url: str) -> None:
        if self.store.get(self._key(granter, grantee, url)) is None:
            raise AuthzError(f"no grant {granter} -> {grantee} for {url}")
        self.store.delete(self._key(granter, grantee, url))

    def get(self, granter: str, grantee: str, url: str) -> Grant | None:
        raw = self.store.get(self._key(granter, grantee, url))
        # `is not None`, not truthiness — defensive symmetry with feegrant
        # (a Grant always carries its url so never marshals empty, but the
        # existence check must not depend on that).
        return Grant.unmarshal(raw) if raw is not None else None

    def accept(self, granter: str, grantee: str, msg, time_ns: int) -> None:
        """Authorize one inner msg of a MsgExec (sdk DispatchActions):
        checks existence/expiry, and for SendAuthorization decrements the
        spend limit (exhausted grants prune)."""
        url = msg.TYPE_URL
        g = self.get(granter, grantee, url)
        if g is None:
            raise AuthzError(
                f"no authorization {granter} -> {grantee} for {url}"
            )
        if g.expiration_ns and time_ns >= g.expiration_ns:
            self.store.delete(self._key(granter, grantee, url))
            raise AuthzError("authorization expired")
        # SendAuthorization (spend_limit) covers MsgSend ONLY, as in the
        # sdk: its Accept() rejects every other msg type, and the wire
        # shape carries no msg-type field.  A MsgMultiSend under authz
        # needs a GenericAuthorization of the MultiSend URL — unlimited,
        # exactly the sdk's semantics (MsgAuthzGrant.validate_basic
        # refuses spend_limit on non-MsgSend grants, so a limited
        # MultiSend grant cannot exist on the wire).
        if g.spend_limit and url == URL_MSG_SEND:
            total = sum(c.amount for c in msg.amount if c.denom == "utia")
            if total > g.spend_limit:
                raise AuthzError(
                    f"send of {total} exceeds authorization limit {g.spend_limit}"
                )
            g = replace(g, spend_limit=g.spend_limit - total)
            if g.spend_limit == 0:
                self.store.delete(self._key(granter, grantee, url))
                return
            self.store.set(self._key(granter, grantee, url), g.marshal())

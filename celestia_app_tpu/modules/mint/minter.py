"""x/mint: the fixed (non-governable) inflation schedule.

Behavioral parity with reference x/mint/types/{minter.go,constants.go} and
x/mint/abci.go:14-20: 8% initial inflation decaying 10% per year to a 1.5%
floor, with time-based block provisions minted to the fee collector every
BeginBlock.
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.constants import BOND_DENOM
from celestia_app_tpu.state.dec import Dec

SECONDS_PER_YEAR = int(60 * 60 * 24 * 365.2425)  # 31,556,952
NANOSECONDS_PER_YEAR = SECONDS_PER_YEAR * 1_000_000_000

INITIAL_INFLATION_RATE = Dec.from_str("0.08")
DISINFLATION_RATE = Dec.from_str("0.1")
TARGET_INFLATION_RATE = Dec.from_str("0.015")


def years_since_genesis(genesis_time_ns: int, block_time_ns: int) -> int:
    """Whole elapsed years (x/mint/types/minter.go yearsSinceGenesis)."""
    if block_time_ns < genesis_time_ns:
        return 0
    return (block_time_ns - genesis_time_ns) // NANOSECONDS_PER_YEAR


def calculate_inflation_rate(genesis_time_ns: int, block_time_ns: int) -> Dec:
    years = years_since_genesis(genesis_time_ns, block_time_ns)
    one_minus = Dec.from_int(1).sub(DISINFLATION_RATE)
    rate = INITIAL_INFLATION_RATE.mul(one_minus.power(years))
    return TARGET_INFLATION_RATE if rate < TARGET_INFLATION_RATE else rate


@dataclass
class Minter:
    inflation_rate: Dec
    annual_provisions: Dec
    bond_denom: str = BOND_DENOM
    previous_block_time_ns: int | None = None

    @classmethod
    def default(cls) -> "Minter":
        return cls(INITIAL_INFLATION_RATE, Dec.from_int(0))

    def calculate_block_provision(
        self, current_ns: int, previous_ns: int
    ) -> int:
        """utia to mint this block (minter.go CalculateBlockProvision)."""
        if current_ns < previous_ns:
            raise ValueError("current block time before previous block time")
        elapsed = current_ns - previous_ns
        portion = Dec.from_fraction(elapsed, NANOSECONDS_PER_YEAR)
        return self.annual_provisions.mul(portion).truncate_int()

    def update(self, genesis_time_ns: int, block_time_ns: int, total_supply: int) -> None:
        """BeginBlock maybeUpdateMinter: refresh rate + annual provisions."""
        new_rate = calculate_inflation_rate(genesis_time_ns, block_time_ns)
        if new_rate.raw == self.inflation_rate.raw and self.annual_provisions.raw != 0:
            return
        self.inflation_rate = new_rate
        self.annual_provisions = new_rate.mul_int(total_supply)

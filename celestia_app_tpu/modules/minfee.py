"""x/minfee: the network-wide minimum gas price (v2+).

Parity with reference x/minfee/params.go:20-26 (default from
pkg/appconsts/v2/app_consts.go:9) and its enforcement in
app/ante/fee_checker.go:54-60.
"""

from __future__ import annotations

from celestia_app_tpu.state.dec import Dec
from celestia_app_tpu.state.store import KVStore

_KEY = b"minfee/network_min_gas_price"
DEFAULT_NETWORK_MIN_GAS_PRICE = Dec.from_str("0.000001")  # utia per gas


class MinFeeKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    def network_min_gas_price(self) -> Dec:
        raw = self.store.get(_KEY)
        if raw is None:
            return DEFAULT_NETWORK_MIN_GAS_PRICE
        return Dec(int.from_bytes(raw, "big", signed=True))

    def set_network_min_gas_price(self, price: Dec) -> None:
        if price.raw < 0:
            raise ValueError("min gas price cannot be negative")
        self.store.set(_KEY, price.raw.to_bytes(16, "big", signed=True))

"""EVM byte-parity digests for Blobstream attestations.

Reproduces the exact keccak256-over-ABI constructions the reference signs
and the Blobstream contract verifies (x/blobstream/types/valset.go:32-77,
abi_consts.go:113-116, overview.md "data commitment digest"):

  valset_hash      = keccak256(abi.encode(Validator[]{addr, power}))
                     — computeValidatorSetHash's arguments, selector
                     stripped (valset.go:70-76);
  valset_sign_bytes = keccak256(
        "checkpoint"||0.. (bytes32) || nonce (uint256)
        || powerThreshold (uint256) || valset_hash (bytes32))
                     — domainSeparateValidatorSetHash (valset.go:42-56);
  data_commitment_sign_bytes = keccak256(
        "transactionBatch"||0.. || nonce (uint256) || tupleRoot (bytes32))
                     — domainSeparateDataRootTupleRoot.

A validator's EVM address defaults to its operator address bytes
(types/types.go:13 DefaultEVMAddress = BytesToAddress(valAddress)), i.e.
the 20-byte bech32 payload, unless it registered one via
MsgRegisterEVMAddress.  powerThreshold = 2*(total/3 + 1)
(valset.go:80-88 TwoThirdsThreshold).
"""

from __future__ import annotations

from celestia_app_tpu.crypto import bech32
from celestia_app_tpu.crypto.keccak import keccak256

# Domain separator constants copied from the contracts
# (abi_consts.go:113-116).
VS_DOMAIN_SEPARATOR = b"checkpoint".ljust(32, b"\x00")
DC_DOMAIN_SEPARATOR = b"transactionBatch".ljust(32, b"\x00")


def _uint256(n: int) -> bytes:
    return n.to_bytes(32, "big")


def evm_address_bytes(evm_or_bech32: str) -> bytes:
    """20-byte EVM address from a 0x-hex string (registered via
    MsgRegisterEVMAddress) or a bech32 operator address (the
    DefaultEVMAddress rule: the operator's own 20 payload bytes).  Any
    other identifier falls back to geth BytesToAddress semantics over its
    raw utf-8 bytes (harness fixtures use plain labels)."""
    if evm_or_bech32.startswith("0x"):
        raw = bytes.fromhex(evm_or_bech32[2:])
    else:
        try:
            _, raw = bech32.decode(evm_or_bech32)
        except ValueError:
            raw = evm_or_bech32.encode()
    if len(raw) > 20:
        raw = raw[-20:]  # geth BytesToAddress keeps the last 20 bytes
    return raw.rjust(20, b"\x00")


def _abi_address(addr20: bytes) -> bytes:
    return addr20.rjust(32, b"\x00")


def valset_hash(members) -> bytes:
    """computeValidatorSetHash: keccak256 of the ABI encoding of
    Validator[] (a dynamic array of (address, uint256) tuples).

    ABI layout (selector already stripped, valset.go:76 `encodedVals[4:]`):
      word 0: 0x20 — offset of the array
      word 1: len(members)
      then per member: address (left-padded) || power (uint256).
    `members` entries need `.power` and either `.evm_address` (0x-hex or
    None) plus `.address` (bech32), or just `.address`.
    """
    out = _uint256(0x20) + _uint256(len(members))
    for m in members:
        evm = getattr(m, "evm_address", None) or m.address
        out += _abi_address(evm_address_bytes(evm)) + _uint256(m.power)
    return keccak256(out)


def two_thirds_threshold(members) -> int:
    """valset.go:80-88: 2 * (total/3 + 1), integer division."""
    total = sum(m.power for m in members)
    return 2 * (total // 3 + 1)


def valset_sign_bytes(nonce: int, members) -> bytes:
    """Valset.SignBytes (valset.go:32-56): the digest orchestrators sign
    and updateValidatorSet verifies."""
    return keccak256(
        VS_DOMAIN_SEPARATOR
        + _uint256(nonce)
        + _uint256(two_thirds_threshold(members))
        + valset_hash(members)
    )


def data_commitment_sign_bytes(nonce: int, tuple_root: bytes) -> bytes:
    """DataCommitment sign bytes (domainSeparateDataRootTupleRoot): the
    digest behind submitDataRootTupleRoot."""
    if len(tuple_root) != 32:
        raise ValueError("tuple root must be 32 bytes")
    return keccak256(DC_DOMAIN_SEPARATOR + _uint256(nonce) + tuple_root)

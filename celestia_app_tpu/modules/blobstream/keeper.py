"""x/blobstream: Ethereum-bridge attestations (v1 only; off in v2+).

Behavioral parity with reference x/blobstream (abci.go:28 EndBlocker,
keeper_valset.go, keeper_data_commitment.go): every block, (a) snapshot the
validator set when it first appears or when normalized power shifts by more
than 5%, (b) emit a DataCommitment attestation for every elapsed
DataCommitmentWindow of blocks (catching up in a loop), (c) prune
attestations older than the 3-week expiry.  Attestations carry a global
monotonically increasing nonce consumed by the BlobstreamX relayer.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from fractions import Fraction

from celestia_app_tpu import merkle
from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    encode_bytes_field,
    encode_varint_field,
)
from celestia_app_tpu.state.staking import StakingKeeper
from celestia_app_tpu.state.store import KVStore

DEFAULT_DATA_COMMITMENT_WINDOW = 400  # types/genesis.go:29
SIGNIFICANT_POWER_DIFF = Fraction(5, 100)  # abci.go:26
ATTESTATION_EXPIRY_NS = 3 * 7 * 24 * 3600 * 10**9  # 3 weeks

_NONCE_KEY = b"blobstream/latest_nonce"
_ATT_PREFIX = b"blobstream/att/"
_EVM_PREFIX = b"blobstream/evm/"
_WINDOW_KEY = b"blobstream/params/data_commitment_window"


def set_data_commitment_window(store: KVStore, window: int) -> None:
    """On-chain DataCommitmentWindow param (genesis/gov-settable, as the
    reference keeper reads it via GetDataCommitmentWindowParam)."""
    if window <= 0:
        raise ValueError("data commitment window must be positive")
    store.set(_WINDOW_KEY, window.to_bytes(8, "big"))


def get_data_commitment_window(store: KVStore) -> int:
    raw = store.get(_WINDOW_KEY)
    return int.from_bytes(raw, "big") if raw else DEFAULT_DATA_COMMITMENT_WINDOW


@dataclass(frozen=True)
class BridgeValidator:
    address: str
    power: int
    # The EVM address the orchestrator signs with: a 0x-hex string when
    # registered via MsgRegisterEVMAddress, else None and the digest layer
    # falls back to DefaultEVMAddress (the operator's own 20 payload
    # bytes, reference types/types.go:13).  It MUST ride in the valset
    # snapshot: the contract's stored valset uses the registered address,
    # so a digest built from the default would diverge byte-for-byte.
    evm_address: str | None = None


@dataclass(frozen=True)
class Valset:
    nonce: int
    height: int
    time_ns: int
    members: tuple[BridgeValidator, ...]

    KIND = 1

    def marshal(self) -> bytes:
        out = (
            encode_varint_field(1, self.KIND)
            + encode_varint_field(2, self.nonce)
            + encode_varint_field(3, self.height)
            + encode_varint_field(4, self.time_ns)
        )
        for m in self.members:
            member = encode_bytes_field(1, m.address.encode())
            member += encode_varint_field(2, m.power)
            if m.evm_address:
                member += encode_bytes_field(3, m.evm_address.encode())
            out += encode_bytes_field(5, member)
        return out


@dataclass(frozen=True)
class DataCommitment:
    nonce: int
    begin_block: int  # inclusive
    end_block: int  # exclusive (matches reference window semantics)
    height: int
    time_ns: int

    KIND = 2

    def marshal(self) -> bytes:
        return (
            encode_varint_field(1, self.KIND)
            + encode_varint_field(2, self.nonce)
            + encode_varint_field(3, self.begin_block)
            + encode_varint_field(4, self.end_block)
            + encode_varint_field(5, self.height)
            + encode_varint_field(6, self.time_ns)
        )


def _unmarshal_attestation(raw: bytes):
    fields = {num: val for num, wt, val in decode_fields(raw) if wt == WIRE_VARINT}
    kind = fields.get(1)
    if kind == Valset.KIND:
        members = []
        for num, wt, val in decode_fields(raw):
            if num == 5 and wt == WIRE_LEN:
                addr, power, evm = "", 0, None
                for mn, mwt, mval in decode_fields(val):
                    if mn == 1 and mwt == WIRE_LEN:
                        addr = mval.decode()
                    elif mn == 2 and mwt == WIRE_VARINT:
                        power = mval
                    elif mn == 3 and mwt == WIRE_LEN:
                        evm = mval.decode()
                members.append(BridgeValidator(addr, power, evm))
        return Valset(
            fields.get(2, 0), fields.get(3, 0), fields.get(4, 0), tuple(members)
        )
    if kind == DataCommitment.KIND:
        return DataCommitment(
            fields.get(2, 0), fields.get(3, 0), fields.get(4, 0),
            fields.get(5, 0), fields.get(6, 0),
        )
    raise ValueError(f"unknown attestation kind {kind}")


def encode_data_root_tuple(height: int, data_root: bytes) -> bytes:
    """abi.encode(DataRootTuple{uint256 height, bytes32 dataRoot}) — 64 bytes.

    The exact leaf the Blobstream contract hashes when verifying a
    data-root inclusion proof (x/blobstream/client/verify.go:336-344
    builds this tuple; the solidity type is pinned in
    x/blobstream/types/abi_consts.go): 32-byte big-endian height followed
    by the 32-byte data root.
    """
    if len(data_root) != 32:
        raise ValueError(f"data root must be 32 bytes, got {len(data_root)}")
    return height.to_bytes(32, "big") + data_root


def data_commitment_root(data_roots: list[tuple[int, bytes]]) -> bytes:
    """Merkle root over (height, data_root) tuples for a commitment window.

    The relayer-facing commitment the reference obtains from celestia-core's
    DataCommitment RPC: an RFC-6962 binary merkle over 64-byte
    abi-encoded DataRootTuple leaves (encode_data_root_tuple)."""
    leaves = [encode_data_root_tuple(h, root) for h, root in data_roots]
    return merkle.hash_from_byte_slices(leaves)


def data_root_inclusion_proof(
    data_roots: list[tuple[int, bytes]], height: int
) -> tuple[int, int, list[bytes]]:
    """(index, total, audit_path) of `height`'s tuple within the window.

    The core-RPC DataRootInclusionProof the relayer feeds to the contract
    (x/blobstream/client/verify.go:288,310-344).
    """
    heights = [h for h, _ in data_roots]
    index = heights.index(height)
    leaves = [encode_data_root_tuple(h, root) for h, root in data_roots]
    return index, len(leaves), merkle.proof(leaves, index)


def _normalized_power_diff(
    curr: list[BridgeValidator], last: list[BridgeValidator]
) -> Fraction:
    """Sum of |Δ normalized power| (Gravity PowerDiff semantics)."""
    pc = sum(m.power for m in curr) or 1
    pl = sum(m.power for m in last) or 1
    addrs = {m.address for m in curr} | {m.address for m in last}
    cm = {m.address: m.power for m in curr}
    lm = {m.address: m.power for m in last}
    return sum(
        abs(Fraction(cm.get(a, 0), pc) - Fraction(lm.get(a, 0), pl)) for a in addrs
    )


class BlobstreamKeeper:
    def __init__(
        self,
        store: KVStore,
        staking: StakingKeeper,
        data_commitment_window: int | None = None,
    ):
        self.store = store
        self.staking = staking
        # None -> the on-chain param (keeper_data_commitment.go:44-50);
        # an explicit argument pins it (unit tests).
        self.window = (
            data_commitment_window
            if data_commitment_window is not None
            else get_data_commitment_window(store)
        )

    # --- nonces / storage --------------------------------------------------
    def latest_nonce(self) -> int:
        raw = self.store.get(_NONCE_KEY)
        return int.from_bytes(raw, "big") if raw else 0

    def _next_nonce(self) -> int:
        n = self.latest_nonce() + 1
        self.store.set(_NONCE_KEY, n.to_bytes(8, "big"))
        return n

    def _set_attestation(self, att) -> None:
        self.store.set(_ATT_PREFIX + att.nonce.to_bytes(8, "big"), att.marshal())

    def get_attestation(self, nonce: int):
        raw = self.store.get(_ATT_PREFIX + nonce.to_bytes(8, "big"))
        return _unmarshal_attestation(raw) if raw else None

    def attestations(self) -> list:
        return [_unmarshal_attestation(v) for _, v in self.store.iterate(_ATT_PREFIX)]

    # --- EVM address registration (keeper/msg_server.go) -------------------
    def register_evm_address(self, validator: str, evm_address: str) -> None:
        if not self.staking.has_validator(validator):
            raise ValueError(f"no validator {validator}")
        if not (evm_address.startswith("0x") and len(evm_address) == 42):
            raise ValueError(f"invalid EVM address {evm_address}")
        self.store.set(_EVM_PREFIX + validator.encode(), evm_address.encode())

    def evm_address(self, validator: str) -> str | None:
        raw = self.store.get(_EVM_PREFIX + validator.encode())
        return raw.decode() if raw else None

    # --- EndBlocker --------------------------------------------------------
    def end_blocker(self, height: int, time_ns: int) -> list:
        created: list = []
        created += self._handle_valset_request(height, time_ns)
        created += self._handle_data_commitments(height, time_ns)
        self._prune(time_ns)
        return created

    def _current_members(self) -> tuple[BridgeValidator, ...]:
        # Valsets snapshot the ACTIVE set: a jailed validator must drop out
        # (the sdk builds them from bonded validators, keeper_valset.go).
        return tuple(
            BridgeValidator(v.address, v.power, self.evm_address(v.address))
            for v in self.staking.bonded_validators()
        )

    def _latest_valset(self) -> Valset | None:
        for att in reversed(self.attestations()):
            if isinstance(att, Valset):
                return att
        return None

    def _handle_valset_request(self, height: int, time_ns: int) -> list:
        members = self._current_members()
        if not members:
            return []
        latest = self._latest_valset()
        need = latest is None or _normalized_power_diff(
            list(members), list(latest.members)
        ) > SIGNIFICANT_POWER_DIFF
        if not need:
            return []
        vs = Valset(self._next_nonce(), height, time_ns, members)
        self._set_attestation(vs)
        return [vs]

    def _latest_data_commitment(self) -> DataCommitment | None:
        for att in reversed(self.attestations()):
            if isinstance(att, DataCommitment):
                return att
        return None

    # --- query surface (what the BlobstreamX relayer consumes) -------------
    # keeper/query_data_commitment.go, query_valset.go, query_attestation.go
    def latest_data_commitment(self) -> DataCommitment:
        """GetLatestDataCommitment (keeper_data_commitment.go:98-123)."""
        dc = self._latest_data_commitment()
        if dc is None:
            raise KeyError("no data commitment yet")
        return dc

    def data_commitment_for_height(self, height: int) -> DataCommitment:
        """Attestation whose [begin, end) window contains `height`
        (keeper_data_commitment.go:54-96: begin <= h < end, newest first)."""
        latest = self.latest_data_commitment()
        # <= (not the reference's <): end_block is exclusive, so a height
        # equal to it belongs to the *next* window — the reference misreports
        # that boundary as "not found or pruned" instead of "not yet
        # generated"; this keeps the retry-later signal accurate.
        if latest.end_block <= height:
            raise KeyError(
                f"data commitment for height {height} not yet generated "
                f"(latest end {latest.end_block})"
            )
        for att in reversed(self.attestations()):
            if (
                isinstance(att, DataCommitment)
                and att.begin_block <= height < att.end_block
            ):
                return att
        raise KeyError(f"data commitment for height {height} not found or pruned")

    def earliest_available_nonce(self) -> int:
        """Earliest attestation nonce still in store (post-pruning)."""
        atts = self.attestations()
        if not atts:
            raise KeyError("no attestations yet")
        return atts[0].nonce

    def latest_valset_before_nonce(self, nonce: int) -> Valset:
        """Newest valset with nonce <= the given nonce
        (keeper_valset.go GetLatestValsetBeforeNonce via query_valset.go)."""
        for att in reversed(self.attestations()):
            if isinstance(att, Valset) and att.nonce <= nonce:
                return att
        raise KeyError(f"no valset at or before nonce {nonce}")

    def _handle_data_commitments(self, height: int, time_ns: int) -> list:
        """Catch-up loop (abci.go:37-81): for window 400 the ranges are
        [1,401), [401,801), … — the first commitment fires at height 400
        (`height >= window`, abci.go:73) and every later one at
        end_block + window (`height - end >= window`, abci.go:63): 400,
        801, 1201, … — the reference's own cadence, deliberately mirrored
        (the second window is complete at height 800 but the reference
        does not emit it until 801)."""
        created: list = []
        while True:
            latest = self._latest_data_commitment()
            if latest is None:
                if height < self.window:
                    return created
                begin = 1
            else:
                if height - latest.end_block < self.window:
                    return created
                begin = latest.end_block
            dc = DataCommitment(
                self._next_nonce(), begin, begin + self.window, height, time_ns
            )
            self._set_attestation(dc)
            created.append(dc)

    def _prune(self, time_ns: int) -> None:
        for key, raw in self.store.iterate(_ATT_PREFIX):
            att = _unmarshal_attestation(raw)
            if time_ns - att.time_ns > ATTESTATION_EXPIRY_NS:
                self.store.delete(key)

"""The Blobstream relayer circuit: orchestrator -> relayer -> verifying client.

Reference shape (x/blobstream/client/verify.go, overview.md):

  * every validator runs an *orchestrator* signing each attestation's
    commitment (valset hash or data-root tuple root) with its EVM key;
  * a *relayer* collects those signatures and submits the tuple root to the
    Blobstream contract on Ethereum (submitDataRootTupleRoot), which checks
    that >2/3 of the registered validator power signed;
  * a *verifying client* (rollup, bridge) proves a share range against the
    contract: shares -> NMT row roots -> data root (self-verifying
    ShareProof), then data root -> tuple root via a binary-merkle
    DataRootInclusionProof (verify.go:206-344).

This module provides TPU-repo equivalents of all three roles against the
JSON-RPC serving plane plus `BlobstreamContract`, an in-process stand-in
for the Ethereum contract (storage layout and checks modeled on
Blobstream.sol via x/blobstream/types/abi_consts.go).  Digests are
EVM-byte-parity keccak256 over the reference's ABI layouts
(modules/blobstream/evm.py, crypto/keccak.py) — the round-2 sha256
stand-in (then recorded as a PARITY deviation) is gone.
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction

from celestia_app_tpu import merkle
from celestia_app_tpu.crypto.keys import PrivateKey, PublicKey
from celestia_app_tpu.modules.blobstream.evm import (
    data_commitment_sign_bytes,
    valset_sign_bytes,
)
from celestia_app_tpu.modules.blobstream.keeper import (
    BridgeValidator,
    encode_data_root_tuple,
)


def data_commitment_digest(nonce: int, tuple_root: bytes) -> bytes:
    """The message an orchestrator signs for a DataCommitment attestation
    (reference domainSeparateDataRootTupleRoot keccak digest)."""
    return data_commitment_sign_bytes(nonce, tuple_root)


def valset_checkpoint(
    nonce: int, members: tuple[BridgeValidator, ...]
) -> bytes:
    """Checkpoint digest registering a validator set in the contract
    (reference Valset.SignBytes, valset.go:32-56)."""
    return valset_sign_bytes(nonce, members)


@dataclass(frozen=True)
class OrchestratorSignature:
    validator: str  # bech32 operator address (the contract key here)
    signature: bytes


class ContractError(ValueError):
    pass


class BlobstreamContract:
    """In-process Blobstream.sol stand-in.

    state_dataRootTupleRoots[nonce] plus the currently registered validator
    set; submitDataRootTupleRoot enforces the reference's 2/3 signed-power
    threshold before accepting a root.
    """

    def __init__(self, valset_nonce: int, members: tuple[BridgeValidator, ...],
                 pubkeys: dict[str, PublicKey]):
        self.valset_nonce = valset_nonce
        self.members = tuple(members)
        self.pubkeys = dict(pubkeys)  # validator address -> secp256k1 key
        self.tuple_roots: dict[int, bytes] = {}  # nonce -> commitment root
        self.latest_nonce = valset_nonce

    def update_valset(
        self,
        new_nonce: int,
        new_members: tuple[BridgeValidator, ...],
        new_pubkeys: dict[str, PublicKey],
        signatures: list[OrchestratorSignature],
    ) -> None:
        """updateValidatorSet: the *old* set signs the new checkpoint."""
        digest = valset_checkpoint(new_nonce, tuple(new_members))
        self._check_threshold(digest, signatures)
        if new_nonce <= self.valset_nonce:
            raise ContractError("valset nonce must increase")
        self.valset_nonce = new_nonce
        self.members = tuple(new_members)
        self.pubkeys = dict(new_pubkeys)
        self.latest_nonce = max(self.latest_nonce, new_nonce)

    def submit_data_root_tuple_root(
        self, nonce: int, tuple_root: bytes, signatures: list[OrchestratorSignature]
    ) -> None:
        """submitDataRootTupleRoot: accept a window root signed by >2/3."""
        if nonce in self.tuple_roots:
            raise ContractError(f"nonce {nonce} already relayed")
        if len(tuple_root) != 32:
            raise ContractError("tuple root must be 32 bytes")
        self._check_threshold(data_commitment_digest(nonce, tuple_root), signatures)
        self.tuple_roots[nonce] = tuple_root
        self.latest_nonce = max(self.latest_nonce, nonce)

    def _check_threshold(
        self, digest: bytes, signatures: list[OrchestratorSignature]
    ) -> None:
        total = sum(m.power for m in self.members)
        power_by_addr = {m.address: m.power for m in self.members}
        signed = 0
        seen: set[str] = set()
        for sig in signatures:
            if sig.validator in seen or sig.validator not in power_by_addr:
                continue
            pub = self.pubkeys.get(sig.validator)
            if pub is None or not pub.verify(digest, sig.signature):
                raise ContractError(f"bad signature from {sig.validator}")
            seen.add(sig.validator)
            signed += power_by_addr[sig.validator]
        if Fraction(signed, total or 1) <= Fraction(2, 3):
            raise ContractError(
                f"insufficient signed power {signed}/{total} (needs >2/3)"
            )

    def verify_attestation(
        self,
        nonce: int,
        height: int,
        data_root: bytes,
        index: int,
        total: int,
        path: list[bytes],
    ) -> bool:
        """verifyAttestation: prove (height, dataRoot) under a relayed root."""
        root = self.tuple_roots.get(nonce)
        if root is None:
            return False
        leaf = encode_data_root_tuple(height, data_root)
        return merkle.verify_proof(root, leaf, index, total, path)


class Orchestrator:
    """Per-validator attestation signer (reference: the orchestrator daemon)."""

    def __init__(self, validator: str, key: PrivateKey):
        self.validator = validator
        self.key = key

    def sign_data_commitment(self, nonce: int, tuple_root: bytes) -> OrchestratorSignature:
        return OrchestratorSignature(
            self.validator, self.key.sign(data_commitment_digest(nonce, tuple_root))
        )

    def sign_valset(
        self, nonce: int, members: tuple[BridgeValidator, ...]
    ) -> OrchestratorSignature:
        return OrchestratorSignature(
            self.validator, self.key.sign(valset_checkpoint(nonce, members))
        )


def relay_pending(remote, contract: BlobstreamContract, orchestrators) -> int:
    """Relayer main loop body: walk un-relayed attestations in nonce order —
    valset updates first registered in the contract (signed by the *old*
    set), data commitments submitted against the set current at their
    nonce, as the reference relayer sequences updateValidatorSet /
    submitDataRootTupleRoot.  Returns the number of commitments relayed."""
    nonces = remote.blobstream_nonces()
    by_validator = {o.validator: o for o in orchestrators}
    relayed = 0
    for nonce in range(1, nonces["latest"] + 1):
        att = remote.blobstream_attestation(nonce)
        if att is None:
            continue
        if att["kind"] == "valset":
            if att["nonce"] <= contract.valset_nonce:
                continue  # genesis valset already registered
            members = tuple(
                BridgeValidator(m["address"], m["power"]) for m in att["members"]
            )
            # The relayer knows each orchestrator's key; the contract needs
            # the new members' verification keys alongside the old set's
            # signatures over the checkpoint.
            new_pubkeys = {
                m.address: by_validator[m.address].key.public_key()
                for m in members
                if m.address in by_validator
            }
            sigs = [o.sign_valset(att["nonce"], members) for o in orchestrators]
            contract.update_valset(att["nonce"], members, new_pubkeys, sigs)
            continue
        if nonce in contract.tuple_roots:
            continue
        root = remote.data_commitment(att["begin_block"], att["end_block"])
        sigs = [o.sign_data_commitment(nonce, root) for o in orchestrators]
        contract.submit_data_root_tuple_root(nonce, root, sigs)
        relayed += 1
    return relayed


def verify_shares(
    remote, contract: BlobstreamContract, height: int, start: int, end: int
) -> bool:
    """The full verify.go:206-344 client flow against contract + node."""
    proof, data_root = remote.share_inclusion_proof(height, start, end)
    if not proof.verify(data_root):
        return False
    dc = remote.data_commitment_range(height)
    index, total, path = remote.data_root_inclusion_proof(
        height, dc["begin_block"], dc["end_block"]
    )
    return contract.verify_attestation(
        dc["nonce"], height, data_root, index, total, path
    )


def _locate_tx(remote, tx_hash: bytes):
    """(height, tx_index, n_txs, reconstructed square) for a committed tx,
    or None.

    The square is rebuilt with the *hard cap of the app version the block
    was produced under* — verify.go:86-89 uses
    appconsts.SquareSizeUpperBound(header.Version.App), never the current
    governance param, so historical blocks re-layout identically even
    after a gov max-square change.
    """
    from celestia_app_tpu.constants import square_size_upper_bound
    from celestia_app_tpu.square import builder as square
    from celestia_app_tpu.tx import tx_hash as hash_fn

    status = remote.tx_status(tx_hash)
    if status is None:
        return None
    height, _code, _log = status
    block = remote.block(height)
    txs = [bytes.fromhex(t) for t in block["txs"]]
    tx_index = next((i for i, t in enumerate(txs) if hash_fn(t) == tx_hash), None)
    if tx_index is None:
        return None
    # Chains run under the benchmark-manifest square-cap override report it
    # with the block; default to the versioned hard cap (verify.go:86-89).
    bound = block.get("square_size_upper_bound") or square_size_upper_bound(
        block["app_version"]
    )
    sq = square.construct(txs, bound)
    return height, tx_index, len(txs), sq


def verify_tx(remote, contract: BlobstreamContract, tx_hash: bytes) -> bool:
    """verify.go txCmd: tx hash -> share range -> verify_shares."""
    located = _locate_tx(remote, tx_hash)
    if located is None:
        return False
    height, tx_index, _n_txs, sq = located
    start, end = sq.find_tx_share_range(tx_index)
    return verify_shares(remote, contract, height, start, end)


def verify_blob(
    remote, contract: BlobstreamContract, tx_hash: bytes, blob_index: int
) -> bool:
    """verify.go blobCmd: (tx hash, blob index) -> blob share range."""
    located = _locate_tx(remote, tx_hash)
    if located is None:
        return False
    height, tx_index, n_txs, sq = located
    # pfb_index = position among the square's blob txs (block order keeps
    # normal txs first, then blob txs — square/builder.py find_tx_share_range).
    n_normal = n_txs - len(sq.wrapped_pfb_txs())
    if tx_index < n_normal:
        return False  # a committed tx, but not a blob tx: nothing to prove
    try:
        start, end = sq.blob_share_range(tx_index - n_normal, blob_index)
    except KeyError:
        return False  # blob_index out of range for this PFB
    return verify_shares(remote, contract, height, start, end)

"""x/slashing + x/evidence: liveness tracking, downtime jailing, and
equivocation (double-sign) punishment.

The reference wires cosmos-sdk x/slashing and x/evidence
(app/modules.go:133-135,147-149) with celestia-tuned genesis
(app/default_overrides.go:100-111):

    SignedBlocksWindow       5000 blocks
    MinSignedPerWindow       0.75
    DowntimeJailDuration     1 minute
    SlashFractionDoubleSign  0.02 (2%)
    SlashFractionDowntime    0    (downtime jails but does NOT slash)

Liveness follows the sdk's sliding-window scheme: each bonded validator
has a missed-block bitmap over the window; when misses exceed
window - ceil(0.75 x window), the validator is jailed (and slashed by the
downtime fraction — zero on celestia) and its window resets.  MsgUnjail
restores a downtime-jailed validator after the jail duration; an
equivocation tombstones forever (sdk Tombstone semantics).

Evidence here is native to this framework's consensus plane: an
Equivocation is two verified votes by one validator for the SAME height
and vote type but DIFFERENT block ids (consensus/votes.py), the exact
condition Tendermint's evidence pool gossips as DuplicateVoteEvidence.
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.state.dec import Dec
from celestia_app_tpu.state.store import KVStore

SIGNED_BLOCKS_WINDOW = 5000
MIN_SIGNED_PER_WINDOW = Dec.from_str("0.75")
DOWNTIME_JAIL_DURATION_NS = 60 * 10**9  # 1 minute
SLASH_FRACTION_DOUBLE_SIGN = Dec.from_str("0.02")
SLASH_FRACTION_DOWNTIME = Dec.from_str("0")

# Evidence max age in blocks: UnbondingTime / GoalBlockTime + 1 (reference
# app/default_overrides.go:254 DefaultEvidenceParams).
EVIDENCE_MAX_AGE_BLOCKS = (3 * 7 * 24 * 3600) // 15 + 1

_INFO_PREFIX = b"slash/info/"
_BITMAP_PREFIX = b"slash/bitmap/"
_PARAMS_KEY = b"slash/params"


class SlashingError(ValueError):
    pass


@dataclass
class SigningInfo:
    """sdk ValidatorSigningInfo: the liveness ledger for one validator."""

    index_offset: int = 0
    missed_blocks: int = 0
    jailed_until_ns: int = 0
    tombstoned: bool = False

    def marshal(self) -> bytes:
        return (
            f"{self.index_offset}/{self.missed_blocks}/"
            f"{self.jailed_until_ns}/{int(self.tombstoned)}"
        ).encode()

    @classmethod
    def unmarshal(cls, raw: bytes) -> "SigningInfo":
        a, b, c, d = raw.decode().split("/")
        return cls(int(a), int(b), int(c), bool(int(d)))


@dataclass(frozen=True)
class Params:
    signed_blocks_window: int = SIGNED_BLOCKS_WINDOW
    min_signed_per_window: Dec = MIN_SIGNED_PER_WINDOW
    downtime_jail_duration_ns: int = DOWNTIME_JAIL_DURATION_NS
    slash_fraction_double_sign: Dec = SLASH_FRACTION_DOUBLE_SIGN
    slash_fraction_downtime: Dec = SLASH_FRACTION_DOWNTIME

    @property
    def max_missed(self) -> int:
        """Misses beyond this jail the validator: window - ceil(min x window)."""
        min_signed = self.min_signed_per_window.mul_int(
            self.signed_blocks_window
        ).ceil_int()
        return self.signed_blocks_window - min_signed


class SlashingKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    # --- params -------------------------------------------------------------
    def params(self) -> Params:
        raw = self.store.get(_PARAMS_KEY)
        if not raw:
            return Params()
        w, m, j, ds, dt = raw.decode().split("|")
        return Params(int(w), Dec(int(m)), int(j), Dec(int(ds)), Dec(int(dt)))

    def set_params(self, p: Params) -> None:
        self.store.set(
            _PARAMS_KEY,
            f"{p.signed_blocks_window}|{p.min_signed_per_window.raw}|"
            f"{p.downtime_jail_duration_ns}|{p.slash_fraction_double_sign.raw}|"
            f"{p.slash_fraction_downtime.raw}".encode(),
        )

    # --- signing info --------------------------------------------------------
    def signing_info(self, validator: str) -> SigningInfo:
        raw = self.store.get(_INFO_PREFIX + validator.encode())
        return SigningInfo.unmarshal(raw) if raw else SigningInfo()

    def signing_infos(self) -> list[tuple[str, SigningInfo]]:
        """Every recorded (validator, SigningInfo), address-ordered — the
        sdk SigningInfos query's walk of the info prefix."""
        return [
            (key[len(_INFO_PREFIX):].decode(), SigningInfo.unmarshal(raw))
            for key, raw in self.store.iterate(_INFO_PREFIX)
        ]

    def _set_info(self, validator: str, info: SigningInfo) -> None:
        self.store.set(_INFO_PREFIX + validator.encode(), info.marshal())

    def _bitmap(self, validator: str, info: SigningInfo, window: int) -> bytearray:
        raw = self.store.get(_BITMAP_PREFIX + validator.encode())
        bm = bytearray(raw) if raw else bytearray((window + 7) // 8)
        if len(bm) != (window + 7) // 8:
            # Window param changed: the whole ledger resets together — a
            # fresh bitmap with a stale missed_blocks counter could never
            # decrement (every slot reads un-missed) and would jail a
            # validator that signs perfectly.
            bm = bytearray((window + 7) // 8)
            info.index_offset = 0
            info.missed_blocks = 0
        return bm

    def _reset_window(self, validator: str, info: SigningInfo, window: int) -> None:
        info.missed_blocks = 0
        info.index_offset = 0
        self.store.set(
            _BITMAP_PREFIX + validator.encode(), bytes((window + 7) // 8)
        )

    # --- liveness (BeginBlocker per bonded validator) ------------------------
    def handle_validator_signature(
        self, staking, bank, dist, validator: str, signed: bool, time_ns: int
    ) -> bool:
        """The sdk's HandleValidatorSignature: advance the sliding window,
        jail (+ slash the downtime fraction) when misses cross the line.
        Returns True if the validator was jailed by this call."""
        p = self.params()
        info = self.signing_info(validator)
        bm = self._bitmap(validator, info, p.signed_blocks_window)
        idx = info.index_offset % p.signed_blocks_window
        byte_i, bit = divmod(idx, 8)
        was_missed = bool(bm[byte_i] >> bit & 1)
        now_missed = not signed
        if was_missed != now_missed:
            bm[byte_i] ^= 1 << bit
            info.missed_blocks += 1 if now_missed else -1
            self.store.set(_BITMAP_PREFIX + validator.encode(), bytes(bm))
        info.index_offset += 1

        jailed = False
        if info.missed_blocks > p.max_missed and not staking.is_jailed(validator):
            if p.slash_fraction_downtime.raw:
                staking.slash(bank, dist, validator, p.slash_fraction_downtime.raw)
            staking.jail(validator)
            info.jailed_until_ns = time_ns + p.downtime_jail_duration_ns
            self._reset_window(validator, info, p.signed_blocks_window)
            jailed = True
        self._set_info(validator, info)
        return jailed

    # --- equivocation (x/evidence Equivocation handling) ----------------------
    def handle_equivocation(
        self, staking, bank, dist, chain_id: str, vote_a, vote_b,
        current_height: int | None = None,
    ) -> int:
        """Verify the two conflicting votes, slash 2%, tombstone, jail
        forever.  Returns the burned amount.  A tombstoned validator is
        punished once (sdk: evidence for a tombstoned validator is a
        no-op).  Evidence older than the unbonding window is rejected
        (reference app/default_overrides.go:249-254: MaxAgeNumBlocks =
        UnbondingTime/GoalBlockTime + 1) — slashing for an infraction the
        current delegators could not have witnessed would burn stake that
        joined after the fault."""
        from celestia_app_tpu.crypto.keys import PublicKey

        if (
            vote_a.validator != vote_b.validator
            or vote_a.height != vote_b.height
            or getattr(vote_a, "round", 0) != getattr(vote_b, "round", 0)
            or vote_a.vote_type != vote_b.vote_type
            or vote_a.block_hash == vote_b.block_hash
        ):
            raise SlashingError("votes are not an equivocation pair")
        if current_height is not None and (
            vote_a.height < current_height - EVIDENCE_MAX_AGE_BLOCKS
        ):
            raise SlashingError(
                f"equivocation at height {vote_a.height} is older than the "
                f"evidence window ({EVIDENCE_MAX_AGE_BLOCKS} blocks before "
                f"{current_height})"
            )
        val = staking.get_validator(vote_a.validator)
        if val is None:
            raise SlashingError(f"no validator {vote_a.validator}")
        pubkey = PublicKey(val.pubkey)
        if not (vote_a.verify(pubkey, chain_id) and vote_b.verify(pubkey, chain_id)):
            raise SlashingError("equivocation votes fail signature verification")

        info = self.signing_info(val.address)
        if info.tombstoned:
            return 0
        p = self.params()
        burned = staking.slash(
            bank, dist, val.address, p.slash_fraction_double_sign.raw
        )
        staking.jail(val.address)
        info.tombstoned = True
        info.jailed_until_ns = (1 << 62)  # never
        self._set_info(val.address, info)
        return burned

    # --- MsgUnjail ------------------------------------------------------------
    def unjail(self, staking, validator: str, time_ns: int) -> None:
        """x/slashing MsgUnjail (operator-signed)."""
        if not staking.is_jailed(validator):
            raise SlashingError(f"validator {validator} is not jailed")
        info = self.signing_info(validator)
        if info.tombstoned:
            raise SlashingError(f"validator {validator} is tombstoned")
        if time_ns < info.jailed_until_ns:
            raise SlashingError(
                f"validator {validator} jailed until {info.jailed_until_ns}"
            )
        # sdk Unjail refuses while the operator's self-bond sits below its
        # declared min_self_delegation (ErrSelfDelegationTooLowToUnjail): a
        # validator jailed by the undelegate-below-min path has
        # jailed_until_ns == 0 and would otherwise unjail immediately
        # without restoring its bond.  Genesis validators' notional
        # self-bond counts as operator stake (state/staking.py header).
        min_self = staking.min_self_delegation(validator)
        if min_self:
            from celestia_app_tpu.modules.distribution import DistributionKeeper

            self_bond = staking.delegation(validator, validator)
            self_bond += DistributionKeeper(self.store).notional(validator)
            if self_bond < min_self:
                raise SlashingError(
                    f"validator {validator} self-delegation {self_bond} is "
                    f"below its min self delegation {min_self}"
                )
        staking.unjail(validator)

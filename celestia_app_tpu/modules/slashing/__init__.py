from celestia_app_tpu.modules.slashing.keeper import (
    Params,
    SigningInfo,
    SlashingError,
    SlashingKeeper,
)

__all__ = ["Params", "SigningInfo", "SlashingError", "SlashingKeeper"]

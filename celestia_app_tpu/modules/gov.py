"""Governance-lite: validator-voted parameter changes.

The reference runs full cosmos-sdk x/gov with celestia's paramfilter wrapped
around the param-change handler (x/paramfilter/gov_handler.go:36, blocklist
wired at app/app.go:739-750).  This module keeps the governance surface that
matters to the framework — propose a parameter change, vote by validator
power, execute on majority — with the paramfilter gate enforced at both
submission and execution.  Deposit/period machinery from the SDK is
intentionally out: proposals here tally when asked.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from celestia_app_tpu.modules.paramfilter import validate_param_changes
from celestia_app_tpu.state.dec import Dec
from celestia_app_tpu.state.staking import StakingKeeper
from celestia_app_tpu.state.store import KVStore


@dataclass(frozen=True)
class ParamChange:
    subspace: str
    key: str
    value: str


class GovError(ValueError):
    pass


def default_param_setters(store: KVStore) -> dict[tuple[str, str], Callable[[str], None]]:
    """The governance-settable parameter registry."""
    from celestia_app_tpu.modules.blob.params import BlobParamsKeeper
    from celestia_app_tpu.modules.minfee import MinFeeKeeper

    blob = BlobParamsKeeper(store)
    minfee = MinFeeKeeper(store)
    return {
        ("blob", "GasPerBlobByte"): lambda v: blob.set_gas_per_blob_byte(int(v)),
        ("blob", "GovMaxSquareSize"): lambda v: blob.set_gov_max_square_size(int(v)),
        ("minfee", "NetworkMinGasPrice"): lambda v: minfee.set_network_min_gas_price(
            Dec.from_str(v)
        ),
    }


class GovKeeper:
    def __init__(self, store: KVStore, staking: StakingKeeper):
        self.store = store
        self.staking = staking
        self._setters = default_param_setters(store)

    # --- proposals ---------------------------------------------------------
    def _next_id(self) -> int:
        raw = self.store.get(b"gov/next_id")
        n = int.from_bytes(raw, "big") if raw else 1
        self.store.set(b"gov/next_id", (n + 1).to_bytes(8, "big"))
        return n

    def submit_param_change(self, proposer: str, changes: list[ParamChange]) -> int:
        if not changes:
            raise GovError("empty proposal")
        validate_param_changes([(c.subspace, c.key, c.value) for c in changes])
        for c in changes:
            if (c.subspace, c.key) not in self._setters:
                raise GovError(f"unknown parameter {c.subspace}/{c.key}")
        pid = self._next_id()
        payload = "\x1e".join(f"{c.subspace}\x1f{c.key}\x1f{c.value}" for c in changes)
        self.store.set(f"gov/prop/{pid}".encode(), payload.encode())
        return pid

    def _changes(self, proposal_id: int) -> list[ParamChange]:
        raw = self.store.get(f"gov/prop/{proposal_id}".encode())
        if raw is None:
            raise GovError(f"no proposal {proposal_id}")
        out = []
        for rec in raw.decode().split("\x1e"):
            subspace, key, value = rec.split("\x1f")
            out.append(ParamChange(subspace, key, value))
        return out

    # --- voting ------------------------------------------------------------
    def vote(self, proposal_id: int, validator: str, approve: bool) -> None:
        self._changes(proposal_id)  # existence check
        if not self.staking.has_validator(validator):
            raise GovError(f"no validator {validator}")
        self.store.set(
            f"gov/vote/{proposal_id}/{validator}".encode(),
            b"\x01" if approve else b"\x00",
        )

    def tally_and_execute(self, proposal_id: int) -> bool:
        """Execute the change set iff yes-power > half the total power."""
        changes = self._changes(proposal_id)
        yes = 0
        prefix = f"gov/vote/{proposal_id}/".encode()
        for key, val in self.store.iterate(prefix):
            if val == b"\x01":
                yes += self.staking.get_power(key[len(prefix) :].decode())
        if 2 * yes <= self.staking.total_power():
            return False
        # Re-check the filter at execution (the blocklist is consensus law).
        validate_param_changes([(c.subspace, c.key, c.value) for c in changes])
        for c in changes:
            self._setters[(c.subspace, c.key)](c.value)
        self.store.delete(f"gov/prop/{proposal_id}".encode())
        return True

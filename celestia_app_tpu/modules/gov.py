"""Governance: the proposal lifecycle with celestia's paramfilter gate.

The reference runs cosmos-sdk x/gov v1 with celestia's overrides
(app/default_overrides.go:192-199: MinDeposit 10,000 TIA, MaxDepositPeriod
and VotingPeriod one week) and the paramfilter wrapped around the
param-change handler (x/paramfilter/gov_handler.go:36, blocklist wired at
app/app.go:739-750).  This module implements that lifecycle:

  submit (escrow initial deposit) -> DEPOSIT_PERIOD
    -> min deposit reached -> VOTING_PERIOD (one-week clock)
    -> end blocker tallies at voting end: quorum 33.4%, threshold 50% of
       non-abstain, veto 33.4% (sdk v1 tally defaults); deposits burned on
       quorum failure / veto / deposit-period expiry, refunded otherwise
       (sdk gov keeper/tally.go + abci.go semantics)
    -> PASSED proposals execute their param changes through the registry,
       re-checking the paramfilter blocklist at execution.

Voting follows sdk tally.go: any address votes (MsgVote or weighted
MsgVoteWeighted); delegators vote their own staked tokens directly, and a
bonded validator votes its remaining tokens — self-bond plus delegations
whose delegators did not override it (inherit-unless-overridden).  The
tally is token-weighted against total bonded tokens.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from enum import IntEnum
from fractions import Fraction
from typing import Callable

from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    encode_bytes_field,
    encode_varint_field,
)
from celestia_app_tpu.modules.paramfilter import validate_param_changes
from celestia_app_tpu.state.accounts import BankKeeper
from celestia_app_tpu.state.dec import Dec
from celestia_app_tpu.state.staking import StakingKeeper
from celestia_app_tpu.state.store import KVStore

# Celestia genesis overrides (default_overrides.go:197-199).
DEFAULT_MIN_DEPOSIT = 10_000_000_000  # 10,000 TIA in utia
WEEK_NS = 7 * 24 * 3600 * 10**9
DEFAULT_MAX_DEPOSIT_PERIOD_NS = WEEK_NS
DEFAULT_VOTING_PERIOD_NS = WEEK_NS

# sdk x/gov v1 tally defaults (unchanged by celestia).
QUORUM = Fraction(334, 1000)
THRESHOLD = Fraction(1, 2)
VETO_THRESHOLD = Fraction(334, 1000)

GOV_MODULE = "gov"  # escrow account for deposits


class ProposalStatus(IntEnum):
    DEPOSIT_PERIOD = 1
    VOTING_PERIOD = 2
    PASSED = 3
    REJECTED = 4
    FAILED = 5  # passed the vote but the handler errored


class VoteOption(IntEnum):
    YES = 1
    ABSTAIN = 2
    NO = 3
    NO_WITH_VETO = 4


@dataclass(frozen=True)
class ParamChange:
    subspace: str
    key: str
    value: str


@dataclass(frozen=True)
class Proposal:
    pid: int
    proposer: str
    changes: tuple[ParamChange, ...]
    status: ProposalStatus
    submit_time_ns: int
    deposit_end_ns: int
    voting_start_ns: int  # 0 until activated
    voting_end_ns: int  # 0 until activated
    total_deposit: int
    # CommunityPoolSpendProposal content (the distrclient.ProposalHandler
    # the reference registers in its gov router, default_overrides.go:207);
    # a proposal carries EITHER param changes OR a spend.
    spend_recipient: str = ""
    spend_amount: int = 0


class GovError(ValueError):
    pass


def default_param_setters(store: KVStore) -> dict[tuple[str, str], Callable[[str], None]]:
    """The governance-settable parameter registry."""
    from celestia_app_tpu.modules.blob.params import BlobParamsKeeper
    from celestia_app_tpu.modules.blobstream.keeper import set_data_commitment_window
    from celestia_app_tpu.modules.minfee import MinFeeKeeper

    from celestia_app_tpu.modules.consensus_params import ConsensusParamsKeeper

    blob = BlobParamsKeeper(store)
    minfee = MinFeeKeeper(store)
    consensus = ConsensusParamsKeeper(store)
    return {
        ("blob", "GasPerBlobByte"): lambda v: blob.set_gas_per_blob_byte(int(v)),
        ("blob", "GovMaxSquareSize"): lambda v: blob.set_gov_max_square_size(int(v)),
        ("minfee", "NetworkMinGasPrice"): lambda v: minfee.set_network_min_gas_price(
            Dec.from_str(v)
        ),
        ("blobstream", "DataCommitmentWindow"): lambda v: set_data_commitment_window(
            store, int(v)
        ),
        # baseapp BlockParams (gov-settable in the reference — the big-block
        # e2e raises MaxBytes through governance).
        ("baseapp", "BlockMaxBytes"): lambda v: consensus.set_block_max_bytes(int(v)),
        ("baseapp", "BlockMaxGas"): lambda v: consensus.set_block_max_gas(int(v)),
    }


class GovKeeper:
    def __init__(
        self,
        store: KVStore,
        staking: StakingKeeper,
        bank: BankKeeper | None = None,
        min_deposit: int = DEFAULT_MIN_DEPOSIT,
        max_deposit_period_ns: int = DEFAULT_MAX_DEPOSIT_PERIOD_NS,
        voting_period_ns: int = DEFAULT_VOTING_PERIOD_NS,
    ):
        self.store = store
        self.staking = staking
        self.bank = bank  # None = deposits tracked but not escrowed (unit tests)
        self.min_deposit = min_deposit
        self.max_deposit_period_ns = max_deposit_period_ns
        self.voting_period_ns = voting_period_ns
        self._setters = default_param_setters(store)

    # --- storage ------------------------------------------------------------
    def _next_id(self) -> int:
        raw = self.store.get(b"gov/next_id")
        n = int.from_bytes(raw, "big") if raw else 1
        self.store.set(b"gov/next_id", (n + 1).to_bytes(8, "big"))
        return n

    def _save(self, p: Proposal) -> None:
        """Binary-safe proto-style record: user strings (proposer, param
        values) are length-delimited, so no byte sequence in them can
        corrupt the record (a \\x1e in a value halted the chain under the
        earlier text format)."""
        out = (
            encode_varint_field(1, p.pid)
            + encode_bytes_field(2, p.proposer.encode())
            + encode_varint_field(3, int(p.status))
            + encode_varint_field(4, p.submit_time_ns)
            + encode_varint_field(5, p.deposit_end_ns)
            + encode_varint_field(6, p.voting_start_ns)
            + encode_varint_field(7, p.voting_end_ns)
            + encode_varint_field(8, p.total_deposit)
        )
        for c in p.changes:
            out += encode_bytes_field(
                9,
                encode_bytes_field(1, c.subspace.encode())
                + encode_bytes_field(2, c.key.encode())
                + encode_bytes_field(3, c.value.encode()),
            )
        if p.spend_recipient:
            out += encode_bytes_field(
                10,
                encode_bytes_field(1, p.spend_recipient.encode())
                + encode_varint_field(2, p.spend_amount),
            )
        self.store.set(f"gov/prop/{p.pid:016d}".encode(), out)
        # Active index: end_blocker scans only live proposals (the sdk's
        # Active/InactiveProposalQueue analog).
        active_key = f"gov/active/{p.pid:016d}".encode()
        if p.status in (ProposalStatus.DEPOSIT_PERIOD, ProposalStatus.VOTING_PERIOD):
            self.store.set(active_key, b"\x01")
        else:
            self.store.delete(active_key)

    def get_proposal(self, pid: int) -> Proposal:
        raw = self.store.get(f"gov/prop/{pid:016d}".encode())
        if raw is None:
            raise GovError(f"no proposal {pid}")
        ints = {num: val for num, wt, val in decode_fields(raw) if wt == WIRE_VARINT}
        proposer = ""
        changes: list[ParamChange] = []
        spend_recipient, spend_amount = "", 0
        for num, wt, val in decode_fields(raw):
            if num == 2 and wt == WIRE_LEN:
                proposer = val.decode()
            elif num == 9 and wt == WIRE_LEN:
                f = {cn: cv for cn, cwt, cv in decode_fields(val) if cwt == WIRE_LEN}
                changes.append(
                    ParamChange(
                        f.get(1, b"").decode(), f.get(2, b"").decode(),
                        f.get(3, b"").decode(),
                    )
                )
            elif num == 10 and wt == WIRE_LEN:
                for sn, swt, sv in decode_fields(val):
                    if sn == 1 and swt == WIRE_LEN:
                        spend_recipient = sv.decode()
                    elif sn == 2 and swt == WIRE_VARINT:
                        spend_amount = sv
        return Proposal(
            ints.get(1, 0), proposer, tuple(changes),
            ProposalStatus(ints.get(3, 1)), ints.get(4, 0), ints.get(5, 0),
            ints.get(6, 0), ints.get(7, 0), ints.get(8, 0),
            spend_recipient, spend_amount,
        )

    def proposals(self) -> list[Proposal]:
        out = []
        for key, _ in self.store.iterate(b"gov/prop/"):
            out.append(self.get_proposal(int(key.rsplit(b"/", 1)[-1])))
        return out

    def active_proposals(self) -> list[Proposal]:
        out = []
        for key, _ in self.store.iterate(b"gov/active/"):
            out.append(self.get_proposal(int(key.rsplit(b"/", 1)[-1])))
        return out

    def _delete_votes(self, pid: int) -> None:
        prefix = f"gov/vote/{pid}/".encode()
        for key, _ in self.store.iterate(prefix):
            self.store.delete(key)

    def _delete(self, pid: int) -> None:
        self.store.delete(f"gov/prop/{pid:016d}".encode())
        self.store.delete(f"gov/active/{pid:016d}".encode())
        self._delete_votes(pid)
        dep_prefix = f"gov/dep/{pid}/".encode()
        for key, _ in self.store.iterate(dep_prefix):
            self.store.delete(key)

    # --- lifecycle ----------------------------------------------------------
    def submit(
        self,
        proposer: str,
        changes: list[ParamChange],
        initial_deposit: int,
        time_ns: int,
        spend: tuple[str, int] | None = None,
    ) -> int:
        """MsgSubmitProposal: validates against the paramfilter + registry,
        escrows the initial deposit, and opens the deposit period (or goes
        straight to voting when the deposit already meets the minimum).
        Content is EITHER param changes OR a community-pool spend
        (recipient, amount)."""
        if bool(changes) == (spend is not None):
            raise GovError(
                "proposal must carry exactly one content: param changes or "
                "a community pool spend"
            )
        validate_param_changes([(c.subspace, c.key, c.value) for c in changes])
        for c in changes:
            if (c.subspace, c.key) not in self._setters:
                raise GovError(f"unknown parameter {c.subspace}/{c.key}")
        if spend is not None and (not spend[0] or spend[1] <= 0):
            raise GovError("community pool spend needs a recipient and a positive amount")
        if initial_deposit < 0:
            raise GovError("negative deposit")
        pid = self._next_id()
        p = Proposal(
            pid, proposer, tuple(changes), ProposalStatus.DEPOSIT_PERIOD,
            time_ns, time_ns + self.max_deposit_period_ns, 0, 0, 0,
            spend[0] if spend else "", spend[1] if spend else 0,
        )
        self._save(p)
        if initial_deposit:
            self._add_deposit(p, proposer, initial_deposit, time_ns)
        return pid

    def _add_deposit(self, p: Proposal, depositor: str, amount: int, time_ns: int) -> None:
        if self.bank is not None:
            try:
                self.bank.send(depositor, GOV_MODULE, amount)
            except ValueError as e:
                raise GovError(str(e)) from e
        key = f"gov/dep/{p.pid}/{depositor}".encode()
        prev = self.store.get(key)
        total = (int.from_bytes(prev, "big") if prev else 0) + amount
        self.store.set(key, total.to_bytes(16, "big"))
        p = replace(p, total_deposit=p.total_deposit + amount)
        if (
            p.status == ProposalStatus.DEPOSIT_PERIOD
            and p.total_deposit >= self.min_deposit
        ):
            p = replace(
                p,
                status=ProposalStatus.VOTING_PERIOD,
                voting_start_ns=time_ns,
                voting_end_ns=time_ns + self.voting_period_ns,
            )
        self._save(p)

    def deposit(self, pid: int, depositor: str, amount: int, time_ns: int) -> None:
        """MsgDeposit: only while the proposal is still collecting."""
        p = self.get_proposal(pid)
        if p.status not in (ProposalStatus.DEPOSIT_PERIOD, ProposalStatus.VOTING_PERIOD):
            raise GovError(f"proposal {pid} no longer accepts deposits")
        if amount <= 0:
            raise GovError("deposit must be positive")
        self._add_deposit(p, depositor, amount, time_ns)

    def vote(self, pid: int, voter: str, option, time_ns: int | None = None) -> None:
        """MsgVote: a single full-weight option (bool accepted for the
        round-1 expedited test path).  Any address may vote; tally weighs
        it by the voter's staked power (delegations + validator self-bond,
        sdk tally.go)."""
        if isinstance(option, bool):
            option = VoteOption.YES if option else VoteOption.NO
        self.vote_weighted(pid, voter, [(VoteOption(option), Dec.from_int(1))], time_ns)

    def vote_weighted(
        self,
        pid: int,
        voter: str,
        options: list[tuple[VoteOption, Dec]],
        time_ns: int | None = None,
    ) -> None:
        """MsgVoteWeighted: split one vote across options; weights must be
        positive and sum to exactly 1 (sdk ValidWeightedVoteOption)."""
        p = self.get_proposal(pid)
        if p.status != ProposalStatus.VOTING_PERIOD:
            raise GovError(f"proposal {pid} is not in its voting period")
        if time_ns is not None and time_ns >= p.voting_end_ns:
            raise GovError(f"voting period for proposal {pid} has ended")
        if not options:
            raise GovError("vote needs at least one option")
        total = Dec(0)
        seen: set[VoteOption] = set()
        for opt, weight in options:
            VoteOption(opt)  # raises on junk
            if weight <= Dec(0):
                raise GovError("vote weights must be positive")
            if opt in seen:
                raise GovError(f"duplicate vote option {opt}")
            seen.add(opt)
            total = total.add(weight)
        if total.raw != Dec.from_int(1).raw:
            raise GovError(f"vote weights must sum to 1, got {total}")
        from celestia_app_tpu.tx.messages import encode_weighted_option

        out = b""
        for opt, weight in options:
            # Stored in the proto WeightedVoteOption shape (one codec for
            # the wire msg and the vote record).
            out += encode_bytes_field(
                1, encode_weighted_option(int(opt), str(weight))
            )
        self.store.set(f"gov/vote/{pid}/{voter}".encode(), out)

    @staticmethod
    def _parse_vote(raw: bytes) -> list[tuple[VoteOption, int]]:
        """[(option, weight_raw)] — weight_raw is a Dec raw (1e18 = 1)."""
        from celestia_app_tpu.tx.messages import decode_weighted_option

        out = []
        for n, wt, v in decode_fields(raw):
            if n == 1 and wt == WIRE_LEN:
                opt, weight = decode_weighted_option(v)
                out.append((VoteOption(opt), Dec.from_str(weight).raw))
        return out

    def _tally(self, pid: int) -> tuple[bool, bool]:
        """(passes, burn_deposits) — sdk gov keeper/tally.go:

        every voter's DELEGATED stake votes directly; a validator votes
        its remaining tokens (self-bond + delegations whose delegators
        did not vote themselves — inherit-unless-overridden).  Votes are
        token-weighted against total bonded tokens.  Outcomes: no quorum
        -> fail+burn; veto > 1/3 of votes -> fail+burn; yes <= 1/2 of
        non-abstain -> fail+refund; else pass+refund."""
        from celestia_app_tpu.state.staking import _DEL_PREFIX  # noqa: PLC2701

        votes: dict[str, list[tuple[VoteOption, int]]] = {}
        prefix = f"gov/vote/{pid}/".encode()
        for key, val in self.store.iterate(prefix):
            votes[key[len(prefix):].decode()] = self._parse_vote(val)

        bonded = {v.address for v in self.staking.bonded_validators()}
        # delegator -> [(validator, stake)] over bonded validators only.
        by_delegator: dict[str, list[tuple[str, int]]] = {}
        for key, val in self.store.iterate(_DEL_PREFIX):
            validator, delegator = key[len(_DEL_PREFIX):].split(b"/", 1)
            validator = validator.decode()
            if validator in bonded:
                by_delegator.setdefault(delegator.decode(), []).append(
                    (validator, int.from_bytes(val, "big"))
                )

        PREC = 10**18
        power_raw: dict[VoteOption, int] = {o: 0 for o in VoteOption}
        deductions: dict[str, int] = {}
        for voter, opts in votes.items():
            stake = 0
            for validator, amount in by_delegator.get(voter, ()):
                stake += amount
                deductions[validator] = deductions.get(validator, 0) + amount
            for opt, weight_raw in opts:
                power_raw[opt] += stake * weight_raw
        for validator in bonded:
            opts = votes.get(validator)
            if not opts:
                continue  # non-voting validators contribute nothing (sdk)
            vp = self.staking.tokens(validator) - deductions.get(validator, 0)
            if vp <= 0:
                continue
            for opt, weight_raw in opts:
                power_raw[opt] += vp * weight_raw

        total_bonded = sum(self.staking.tokens(v) for v in bonded)
        voted = sum(power_raw.values())  # token-units x 1e18
        if total_bonded == 0 or Fraction(voted, total_bonded * PREC) < QUORUM:
            return False, True
        if voted and Fraction(power_raw[VoteOption.NO_WITH_VETO], voted) > VETO_THRESHOLD:
            return False, True
        non_abstain = voted - power_raw[VoteOption.ABSTAIN]
        if non_abstain == 0 or Fraction(power_raw[VoteOption.YES], non_abstain) <= THRESHOLD:
            return False, False
        return True, False

    def _settle_deposits(self, pid: int, burn: bool) -> None:
        prefix = f"gov/dep/{pid}/".encode()
        for key, val in self.store.iterate(prefix):
            depositor = key[len(prefix):].decode()
            amount = int.from_bytes(val, "big")
            if self.bank is not None and amount:
                if burn:
                    self.bank.burn(GOV_MODULE, amount)
                else:
                    self.bank.send(GOV_MODULE, depositor, amount)
            self.store.delete(key)

    def _execute(self, p: Proposal) -> ProposalStatus:
        try:
            # Re-check the filter at execution (the blocklist is consensus law).
            validate_param_changes(
                [(c.subspace, c.key, c.value) for c in p.changes]
            )
            for c in p.changes:
                self._setters[(c.subspace, c.key)](c.value)
            if p.spend_recipient:
                from celestia_app_tpu.modules.distribution import DistributionKeeper

                # Fails (not halts) when the pool shrank below the ask
                # between submission and execution.
                DistributionKeeper(self.store).community_pool_spend(
                    self.bank, p.spend_recipient, p.spend_amount
                )
        except (ValueError, OverflowError):
            # OverflowError included: a passed proposal with an absurd value
            # (e.g. BlockMaxBytes >= 2^64) must FAIL cleanly, not halt the
            # chain out of the end blocker.
            return ProposalStatus.FAILED
        return ProposalStatus.PASSED

    def end_blocker(self, time_ns: int) -> list[tuple]:
        """gov abci.go: expire deposit periods (burn), tally ended voting
        periods, execute passed proposals.  Returns lifecycle events."""
        events: list[tuple] = []
        for p in self.active_proposals():
            if (
                p.status == ProposalStatus.DEPOSIT_PERIOD
                and time_ns > p.deposit_end_ns
            ):
                self._settle_deposits(p.pid, burn=True)
                self._delete(p.pid)
                events.append(("gov.proposal_dropped", p.pid))
            elif (
                p.status == ProposalStatus.VOTING_PERIOD
                and time_ns >= p.voting_end_ns
            ):
                passes, burn = self._tally(p.pid)
                self._settle_deposits(p.pid, burn=burn)
                status = self._execute(p) if passes else ProposalStatus.REJECTED
                self._save(replace(p, status=status))  # drops the active key
                self._delete_votes(p.pid)
                events.append((f"gov.proposal_{status.name.lower()}", p.pid))
        return events

    # --- round-1 expedited API (kept: unit tests drive tallies directly) ----
    def submit_param_change(self, proposer: str, changes: list[ParamChange]) -> int:
        """Submit with the minimum deposit pre-met: voting opens at t=0."""
        pid = self.submit(proposer, changes, 0, 0)
        p = self.get_proposal(pid)
        self._save(
            replace(
                p,
                status=ProposalStatus.VOTING_PERIOD,
                voting_start_ns=0,
                voting_end_ns=self.voting_period_ns,
            )
        )
        return pid

    def tally_and_execute(self, pid: int) -> bool:
        """Force an immediate tally (test convenience; production goes
        through end_blocker's clocks)."""
        p = self.get_proposal(pid)
        if p.status != ProposalStatus.VOTING_PERIOD:
            raise GovError(f"proposal {pid} is not in its voting period")
        passes, burn = self._tally(p.pid)
        self._settle_deposits(p.pid, burn=burn)
        if not passes:
            self._save(replace(p, status=ProposalStatus.REJECTED))
            return False
        status = self._execute(p)
        if status == ProposalStatus.FAILED:
            raise GovError(f"proposal {pid} execution failed")
        self._delete(pid)
        return True

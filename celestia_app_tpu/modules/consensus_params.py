"""On-chain consensus params (tier 3 of the config system).

The reference keeps consensus params (incl. Block.MaxBytes and the app
version) on-chain, set from DefaultConsensusParams at genesis
(app/default_overrides.go:217-247: MaxBytes = 64x64x478 ~ 1.87 MiB,
MaxGas = -1) and mutable through governance except the paramfilter
blocklist.  PrepareProposal respects MaxBytes when packing a block (the
reference's celestia-core reaps the mempool under this cap).
"""

from __future__ import annotations

from celestia_app_tpu.constants import CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
from celestia_app_tpu.state.store import KVStore

# DefaultMaxBytes (pkg/appconsts/initial_consts.go:10-14).
DEFAULT_BLOCK_MAX_BYTES = 64 * 64 * CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
DEFAULT_BLOCK_MAX_GAS = -1  # unlimited, as the reference ships

_MAX_BYTES_KEY = b"consensus/block/max_bytes"
_MAX_GAS_KEY = b"consensus/block/max_gas"


class ConsensusParamsKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    def block_max_bytes(self) -> int:
        raw = self.store.get(_MAX_BYTES_KEY)
        return int.from_bytes(raw, "big") if raw else DEFAULT_BLOCK_MAX_BYTES

    def set_block_max_bytes(self, value: int) -> None:
        if value <= 0:
            raise ValueError("block max bytes must be positive")
        if value >= 1 << 63:
            raise ValueError(f"block max bytes {value} out of range")
        self.store.set(_MAX_BYTES_KEY, value.to_bytes(8, "big"))

    def block_max_gas(self) -> int:
        raw = self.store.get(_MAX_GAS_KEY)
        return int.from_bytes(raw, "big", signed=True) if raw else DEFAULT_BLOCK_MAX_GAS

    def set_block_max_gas(self, value: int) -> None:
        if not (-(1 << 63) <= value < 1 << 63):
            raise ValueError(f"block max gas {value} out of range")
        self.store.set(_MAX_GAS_KEY, value.to_bytes(8, "big", signed=True))

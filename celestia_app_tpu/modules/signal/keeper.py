"""x/signal: validator-signaled rolling upgrades (v2+).

Behavioral parity with reference x/signal/keeper.go: validators signal an
app version; MsgTryUpgrade tallies power and, on a 5/6 quorum, schedules the
upgrade DefaultUpgradeHeightDelay blocks out.  The app's EndBlocker consumes
ShouldUpgrade (app/app.go:472-477).
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.state.store import KVStore

# 7 days at 12s blocks (x/signal/keeper.go:18).
DEFAULT_UPGRADE_HEIGHT_DELAY = 7 * 24 * 60 * 60 // 12  # 50,400
THRESHOLD_NUM, THRESHOLD_DEN = 5, 6

_SIGNAL_PREFIX = b"signal/vote/"
_UPGRADE_KEY = b"signal/upgrade"


class SignalError(ValueError):
    pass


@dataclass(frozen=True)
class Upgrade:
    app_version: int
    upgrade_height: int


class SignalKeeper:
    def __init__(self, store: KVStore, staking):
        self.store = store
        self.staking = staking  # needs: get_power(addr) -> int, total_power() -> int, has_validator(addr) -> bool

    # --- msg handlers -----------------------------------------------------
    def signal_version(self, validator: str, version: int, current_version: int) -> None:
        if self.pending_upgrade() is not None:
            raise SignalError("upgrade pending: cannot signal")
        if version < current_version:
            raise SignalError(
                f"signalled version {version} < current version {current_version}"
            )
        if not self.staking.has_validator(validator):
            raise SignalError(f"no validator {validator}")
        self.store.set(_SIGNAL_PREFIX + validator.encode(), version.to_bytes(8, "big"))

    def try_upgrade(self, height: int, current_version: int) -> Upgrade | None:
        if self.pending_upgrade() is not None:
            raise SignalError("upgrade pending: cannot try upgrade")
        has_quorum, version = self.tally()
        if not has_quorum:
            return None
        if version <= current_version:
            raise SignalError(
                f"cannot upgrade to {version} <= current version {current_version}"
            )
        up = Upgrade(version, height + DEFAULT_UPGRADE_HEIGHT_DELAY)
        self.store.set(
            _UPGRADE_KEY,
            up.app_version.to_bytes(8, "big") + up.upgrade_height.to_bytes(8, "big"),
        )
        return up

    # --- tally ------------------------------------------------------------
    def version_tally(self, version: int) -> tuple[int, int, int]:
        """(signalled_power, threshold_power, total_power) for a version."""
        total = self.staking.total_power()
        power = 0
        for key, val in self.store.iterate(_SIGNAL_PREFIX):
            addr = key[len(_SIGNAL_PREFIX) :].decode()
            if int.from_bytes(val, "big") == version:
                power += self.staking.get_power(addr)
        threshold = -(-(total * THRESHOLD_NUM) // THRESHOLD_DEN)  # ceil(5/6 total)
        return power, threshold, total

    def tally(self) -> tuple[bool, int]:
        """Highest version with quorum, if any."""
        versions = {
            int.from_bytes(v, "big") for _, v in self.store.iterate(_SIGNAL_PREFIX)
        }
        for version in sorted(versions, reverse=True):
            power, threshold, _ = self.version_tally(version)
            if power >= threshold:
                return True, version
        return False, 0

    # --- upgrade lifecycle ------------------------------------------------
    def pending_upgrade(self) -> Upgrade | None:
        raw = self.store.get(_UPGRADE_KEY)
        if raw is None:
            return None
        return Upgrade(
            int.from_bytes(raw[:8], "big"), int.from_bytes(raw[8:], "big")
        )

    def should_upgrade(self, height: int) -> Upgrade | None:
        up = self.pending_upgrade()
        if up is not None and height >= up.upgrade_height:
            return up
        return None

    def reset_tally(self) -> None:
        for key, _ in self.store.iterate(_SIGNAL_PREFIX):
            self.store.delete(key)
        self.store.delete(_UPGRADE_KEY)

"""x/feegrant: fee allowances — one account pays another's tx fees.

The reference wires cosmos-sdk x/feegrant (app/modules.go:117-119) and its
own load generator depends on it: txsim's master account grants a
BasicAllowance to every sub-account so one funded account pays all fees
(test/txsim/account.go:238-239,318-330).  A tx opts in by setting
Fee.granter; the DeductFee ante decorator then charges the granter through
`use_grant` instead of the signer.

Allowance types (sdk x/feegrant/feegrant.pb.go semantics):

  * BasicAllowance: optional total spend limit + optional expiration;
  * PeriodicAllowance: a rolling per-period limit that refills every
    `period`, capped by an optional overall basic limit;
  * AllowedMsgAllowance: any allowance, restricted to a set of msg type
    URLs.

`use_grant` mutates state exactly like the sdk: a spent-out or expired
allowance is pruned; a periodic refill advances `period_reset` in whole
periods so a long-idle grant does not accumulate unboundedly.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    encode_bytes_field,
    encode_varint_field,
)
from celestia_app_tpu.state.store import KVStore

_GRANT_PREFIX = b"feegrant/"


class FeegrantError(ValueError):
    pass


@dataclass(frozen=True)
class Allowance:
    """One stored allowance (the three sdk shapes flattened: a basic
    allowance is the periodic fields zeroed; msg restrictions empty =
    any msg)."""

    spend_limit: int = 0  # 0 = unlimited
    expiration_ns: int = 0  # 0 = never
    period_ns: int = 0  # 0 = not periodic
    period_spend_limit: int = 0
    period_can_spend: int = 0
    period_reset_ns: int = 0
    allowed_msgs: tuple[str, ...] = ()  # empty = all

    def marshal(self) -> bytes:
        out = (
            encode_varint_field(1, self.spend_limit)
            + encode_varint_field(2, self.expiration_ns)
            + encode_varint_field(3, self.period_ns)
            + encode_varint_field(4, self.period_spend_limit)
            + encode_varint_field(5, self.period_can_spend)
            + encode_varint_field(6, self.period_reset_ns)
        )
        for url in self.allowed_msgs:
            out += encode_bytes_field(7, url.encode())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Allowance":
        ints = {n: v for n, wt, v in decode_fields(raw) if wt == WIRE_VARINT}
        msgs = [
            v.decode() for n, wt, v in decode_fields(raw)
            if n == 7 and wt == WIRE_LEN
        ]
        return cls(
            ints.get(1, 0), ints.get(2, 0), ints.get(3, 0),
            ints.get(4, 0), ints.get(5, 0), ints.get(6, 0), tuple(msgs),
        )


class FeegrantKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    def _key(self, granter: str, grantee: str) -> bytes:
        return _GRANT_PREFIX + granter.encode() + b"/" + grantee.encode()

    def grant(self, granter: str, grantee: str, allowance: Allowance) -> None:
        """MsgGrantAllowance; granting on top of an existing grant is an
        error in the sdk (revoke first)."""
        if granter == grantee:
            raise FeegrantError("cannot self-grant a fee allowance")
        if self.store.get(self._key(granter, grantee)) is not None:
            raise FeegrantError(
                f"fee allowance {granter} -> {grantee} already exists"
            )
        self.store.set(self._key(granter, grantee), allowance.marshal())

    def revoke(self, granter: str, grantee: str) -> None:
        if self.store.get(self._key(granter, grantee)) is None:
            raise FeegrantError(f"no fee allowance {granter} -> {grantee}")
        self.store.delete(self._key(granter, grantee))

    def get(self, granter: str, grantee: str) -> Allowance | None:
        raw = self.store.get(self._key(granter, grantee))
        # `is not None`, not truthiness: an unlimited/no-expiry allowance
        # marshals to zero bytes and is still a grant.
        return Allowance.unmarshal(raw) if raw is not None else None

    def use_grant(
        self,
        granter: str,
        grantee: str,
        fee: int,
        msg_urls: list[str],
        time_ns: int,
    ) -> None:
        """Charge `fee` against the allowance (the DeductFeeDecorator's
        feegrant path).  Raises FeegrantError if the grant is missing,
        expired, spent out, or doesn't cover one of the msg types."""
        a = self.get(granter, grantee)
        if a is None:
            raise FeegrantError(f"no fee allowance {granter} -> {grantee}")
        if a.expiration_ns and time_ns >= a.expiration_ns:
            self.store.delete(self._key(granter, grantee))
            raise FeegrantError("fee allowance expired")
        if a.allowed_msgs:
            for url in msg_urls:
                if url not in a.allowed_msgs:
                    raise FeegrantError(
                        f"fee allowance does not cover {url}"
                    )
        if a.period_ns:
            # Refill in whole periods (sdk tryResetPeriod).
            if time_ns >= a.period_reset_ns:
                periods = (time_ns - a.period_reset_ns) // a.period_ns + 1
                can = min(
                    a.period_spend_limit,
                    a.spend_limit if a.spend_limit else a.period_spend_limit,
                )
                a = replace(
                    a,
                    period_can_spend=can,
                    period_reset_ns=a.period_reset_ns + periods * a.period_ns,
                )
            if fee > a.period_can_spend:
                raise FeegrantError(
                    f"fee {fee} exceeds period allowance {a.period_can_spend}"
                )
            a = replace(a, period_can_spend=a.period_can_spend - fee)
        if a.spend_limit:
            if fee > a.spend_limit:
                raise FeegrantError(
                    f"fee {fee} exceeds allowance {a.spend_limit}"
                )
            a = replace(a, spend_limit=a.spend_limit - fee)
            if a.spend_limit == 0:
                # Spent out: prune (sdk deletes zero allowances).
                self.store.delete(self._key(granter, grantee))
                return
        self.store.set(self._key(granter, grantee), a.marshal())

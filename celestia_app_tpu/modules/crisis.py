"""x/crisis: invariant registry + assertion.

The reference registers cosmos-sdk x/crisis (app/modules.go:123-125),
whose job is to let any module declare invariants ("total supply equals
the sum of balances") and halt the chain — or fail a check command — when
one breaks.  The sdk runs them at genesis (unless
`skipGenesisInvariants`, the flag celestia threads through app.New) and on
demand via MsgVerifyInvariant / `appd check-invariants`.

Here the registry is a plain list of (name, check) pairs over the store;
`assert_invariants` raises InvariantBroken with the failing invariant's
name.  TestNode runs them after genesis, and the CLI exposes
`check-invariants` against a running chain's state.
"""

from __future__ import annotations

from typing import Callable

from celestia_app_tpu.state.store import KVStore


class InvariantBroken(AssertionError):
    pass


def _supply_matches_balances(store: KVStore) -> None:
    """bank: per-denom supply equals the sum over all balance records."""
    from celestia_app_tpu.state.accounts import BankKeeper

    bank = BankKeeper(store)
    totals: dict[str, int] = {}
    for (addr, denom), amount in bank.balances().items():
        totals[denom] = totals.get(denom, 0) + amount
    for denom, total in totals.items():
        if bank.supply(denom) != total:
            raise InvariantBroken(
                f"bank/total-supply: supply({denom}) = {bank.supply(denom)} "
                f"but balances sum to {total}"
            )


def _bonded_pool_backs_delegations(store: KVStore) -> None:
    """staking: the bonded pool holds exactly the delegated tokens (the
    notional genesis self-bonds are power-book-only, by design)."""
    from celestia_app_tpu.state.accounts import BankKeeper
    from celestia_app_tpu.state.staking import (
        _DEL_PREFIX,  # noqa: PLC2701 — the invariant audits raw records
        BONDED_POOL,
        StakingKeeper,
    )

    bank = BankKeeper(store)
    delegated = sum(
        int.from_bytes(v, "big") for _, v in store.iterate(_DEL_PREFIX)
    )
    pool = bank.balance(BONDED_POOL)
    if pool != delegated:
        raise InvariantBroken(
            f"staking/bonded-pool: pool holds {pool} but delegations sum to "
            f"{delegated}"
        )
    # tokens == notional + delegations per validator.
    from celestia_app_tpu.modules.distribution import DistributionKeeper

    sk = StakingKeeper(store)
    dist = DistributionKeeper(store)
    for v in sk.validators():
        prefix = _DEL_PREFIX + v.address.encode() + b"/"
        per_val = sum(int.from_bytes(x, "big") for _, x in store.iterate(prefix))
        expected = dist.notional(v.address) + per_val
        if sk.tokens(v.address) != expected:
            raise InvariantBroken(
                f"staking/tokens: validator {v.address} has "
                f"{sk.tokens(v.address)} tokens but notional+delegations = "
                f"{expected}"
            )


def _distribution_module_solvent(store: KVStore) -> None:
    """distribution: the module account covers every entitlement — the
    community pool, accrued commissions, and all settled + pending
    delegator rewards (sdk ModuleAccountInvariant)."""
    from celestia_app_tpu.modules.distribution import (
        DISTRIBUTION_MODULE,
        DistributionKeeper,
    )
    from celestia_app_tpu.state.accounts import BankKeeper
    from celestia_app_tpu.state.dec import Dec
    from celestia_app_tpu.state.staking import StakingKeeper

    bank = BankKeeper(store)
    dist = DistributionKeeper(store)
    sk = StakingKeeper(store)
    owed = dist.community_pool()
    for v in sk.validators():
        owed = owed.add(dist.accrued_commission(v.address))
        for d in dist.settle_all(sk, v.address):
            owed = owed.add(
                Dec.from_int(dist.pending_rewards(sk, d, v.address))
            )
    balance = bank.balance(DISTRIBUTION_MODULE)
    if owed.truncate_int() > balance:
        raise InvariantBroken(
            f"distribution/solvency: module holds {balance} but owes "
            f"{owed.truncate_int()}"
        )


def _gov_deposits_escrowed(store: KVStore) -> None:
    """gov: the module account holds at least the live deposits."""
    from celestia_app_tpu.modules.gov import GOV_MODULE
    from celestia_app_tpu.state.accounts import BankKeeper

    deposits = sum(
        int.from_bytes(v, "big") for k, v in store.iterate(b"gov/dep/")
    )
    balance = BankKeeper(store).balance(GOV_MODULE)
    if balance < deposits:
        raise InvariantBroken(
            f"gov/deposits: module holds {balance} but active deposits sum "
            f"to {deposits}"
        )


INVARIANTS: list[tuple[str, Callable[[KVStore], None]]] = [
    ("bank/total-supply", _supply_matches_balances),
    ("staking/bonded-pool", _bonded_pool_backs_delegations),
    ("distribution/solvency", _distribution_module_solvent),
    ("gov/deposits", _gov_deposits_escrowed),
]


def assert_invariants(store: KVStore) -> list[str]:
    """Run every registered invariant; returns the names checked.

    NOTE: runs against a BRANCH of the given store — some checks (reward
    settling) write intermediate state that must not leak into consensus
    state."""
    branch = store.branch()
    for name, check in INVARIANTS:
        check(branch)
    return [name for name, _ in INVARIANTS]

"""x/paramfilter: governance cannot touch consensus-critical params.

Parity with reference x/paramfilter/gov_handler.go:16-36 and the blocked set
wired at app/app.go:739-750.
"""

from __future__ import annotations

# (module subspace, key) pairs governance may never change.
PARAM_BLOCK_LIST: frozenset[tuple[str, str]] = frozenset(
    {
        ("bank", "SendEnabled"),
        ("staking", "UnbondingTime"),
        ("staking", "BondDenom"),
        ("consensus", "validator.pub_key_types"),
    }
)


class ForbiddenParamError(ValueError):
    pass


def validate_param_changes(changes: list[tuple[str, str, str]]) -> None:
    """Reject a gov proposal touching any blocked (subspace, key).

    The reference handler rejects the whole proposal if any change is
    blocked (gov_handler.go:36 GovHandler).
    """
    for subspace, key, _value in changes:
        if (subspace, key) in PARAM_BLOCK_LIST:
            raise ForbiddenParamError(
                f"parameter {subspace}/{key} cannot be changed by governance"
            )

"""x/tokenfilter: reject inbound non-native IBC tokens (TIA-only chain).

Behavioral parity with reference x/tokenfilter/ibc_middleware.go:21-78: on a
received transfer packet, accept only if the receiver chain is the token's
source (the denom path starts with this packet's source port/channel, i.e.
the token is TIA returning home); everything else gets an error ack.  The
middleware is stateless and unilateral, stacked first in the transfer stack
(app/app.go:329-346).
"""

from __future__ import annotations

import json
from dataclasses import dataclass


@dataclass(frozen=True)
class FungibleTokenPacketData:
    denom: str
    amount: str
    sender: str
    receiver: str
    memo: str = ""

    @classmethod
    def from_json(cls, raw: bytes) -> "FungibleTokenPacketData":
        d = json.loads(raw)
        return cls(
            denom=d["denom"],
            amount=str(d.get("amount", "")),
            sender=d.get("sender", ""),
            receiver=d.get("receiver", ""),
            memo=d.get("memo", ""),
        )


def receiver_chain_is_source(source_port: str, source_channel: str, denom: str) -> bool:
    """ibc-go transfertypes.ReceiverChainIsSource: the denom path begins with
    the packet's source port/channel iff the token originated here."""
    return denom.startswith(f"{source_port}/{source_channel}/")


@dataclass(frozen=True)
class Ack:
    success: bool
    error: str = ""


def on_recv_packet(source_port: str, source_channel: str, packet_data: bytes) -> Ack:
    """The middleware decision for one received packet."""
    try:
        data = FungibleTokenPacketData.from_json(packet_data)
    except (ValueError, KeyError, TypeError):
        # Not a transfer packet: pass through to the wrapped module
        # (ibc_middleware.go:44-51).
        return Ack(success=True)
    if receiver_chain_is_source(source_port, source_channel, data.denom):
        return Ack(success=True)
    return Ack(
        success=False,
        error=f"only native denom transfers accepted, got {data.denom}",
    )

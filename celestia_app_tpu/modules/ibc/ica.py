"""ICS-27 interchain accounts — host side.

The reference wires ica host-only at v2 (app/modules.go:185-187;
default_overrides.go:161-166 enables the host, disables the controller)
with a governance-curated message whitelist (app/ica_host.go:3-17).

A controller chain opens a channel to port "icahost" from its own port
"icacontroller-{owner}"; the host derives and registers a fresh account
bound to (connection, controller port).  EXECUTE_TX packets then carry
msgs whose signer must be exactly that account, executed through the
app's normal handlers and answered with a success/error ack.

Wire shapes (ibc-go ICS-27 protos):
    InterchainAccountPacketData {type=1, data=2, memo=3}
    CosmosTx                    {messages=1 (repeated Any)}
    type EXECUTE_TX = 1
"""

from __future__ import annotations

import hashlib

from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    encode_bytes_field,
    encode_varint_field,
)
from celestia_app_tpu.modules.ibc.core import IBCError
from celestia_app_tpu.state.store import KVStore

ICA_HOST_PORT = "icahost"
ICA_VERSION = "ics27-1"
CONTROLLER_PORT_PREFIX = "icacontroller-"
EXECUTE_TX = 1

_ACCOUNT_PREFIX = b"ica/account/"
_PARAMS_KEY = b"ica/host_params"

# The celestia whitelist, now matching app/ica_host.go:3-17 row for row
# (gov votes ride the v1 url there, implemented since round 4).
DEFAULT_ALLOW_MESSAGES = (
    "/ibc.applications.transfer.v1.MsgTransfer",
    "/cosmos.bank.v1beta1.MsgSend",
    "/cosmos.staking.v1beta1.MsgDelegate",
    "/cosmos.staking.v1beta1.MsgBeginRedelegate",
    "/cosmos.staking.v1beta1.MsgUndelegate",
    "/cosmos.staking.v1beta1.MsgCancelUnbondingDelegation",
    "/cosmos.distribution.v1beta1.MsgSetWithdrawAddress",
    "/cosmos.distribution.v1beta1.MsgWithdrawDelegatorReward",
    "/cosmos.distribution.v1beta1.MsgFundCommunityPool",
    "/cosmos.gov.v1.MsgVote",
    "/cosmos.feegrant.v1beta1.MsgGrantAllowance",
    "/cosmos.feegrant.v1beta1.MsgRevokeAllowance",
)


def encode_packet_data(msgs, memo: str = "") -> bytes:
    """InterchainAccountPacketData wrapping a CosmosTx of `msgs`
    (controller-side helper; each msg needs .to_any())."""
    cosmos_tx = b""
    for m in msgs:
        cosmos_tx += encode_bytes_field(1, m.to_any().marshal())
    out = encode_varint_field(1, EXECUTE_TX)
    out += encode_bytes_field(2, cosmos_tx)
    if memo:
        out += encode_bytes_field(3, memo.encode())
    return out


def decode_packet_data(raw: bytes) -> tuple[int, list, str]:
    """(type, [decoded msgs], memo) — raises on unknown inner msg types."""
    from celestia_app_tpu.tx.messages import Any, decode_msg

    ptype, data, memo = 0, b"", ""
    for n, wt, v in decode_fields(raw):
        if n == 1 and wt == WIRE_VARINT:
            ptype = v
        elif n == 2 and wt == WIRE_LEN:
            data = v
        elif n == 3 and wt == WIRE_LEN:
            memo = v.decode()
    msgs = []
    for n, wt, v in decode_fields(data):
        if n == 1 and wt == WIRE_LEN:
            msgs.append(decode_msg(Any.unmarshal(v)))
    return ptype, msgs, memo


class ICAHostKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    # --- params --------------------------------------------------------------
    def host_enabled(self) -> bool:
        raw = self.store.get(_PARAMS_KEY)
        return True if raw is None else bool(raw[0])

    def set_host_enabled(self, enabled: bool) -> None:
        allow = self.allow_messages()
        self._save_params(enabled, allow)

    def allow_messages(self) -> tuple[str, ...]:
        raw = self.store.get(_PARAMS_KEY)
        if raw is None:
            return DEFAULT_ALLOW_MESSAGES
        urls = [
            v.decode() for n, wt, v in decode_fields(raw[1:])
            if n == 1 and wt == WIRE_LEN
        ]
        return tuple(urls)

    def _save_params(self, enabled: bool, allow: tuple[str, ...]) -> None:
        out = bytes([int(enabled)])
        for url in allow:
            out += encode_bytes_field(1, url.encode())
        self.store.set(_PARAMS_KEY, out)

    # --- registration --------------------------------------------------------
    @staticmethod
    def derive_address(connection_id: str, controller_port: str) -> str:
        """Deterministic host address for (connection, controller port) —
        the ibc-go scheme hashes the same pair."""
        from celestia_app_tpu.crypto import bech32

        digest = hashlib.sha256(
            b"ics27-host|" + connection_id.encode() + b"|"
            + controller_port.encode()
        ).digest()[:20]
        return bech32.encode("celestia", digest)

    def register_account(
        self, auth, connection_id: str, controller_port: str
    ) -> str:
        """Bind (connection, controller port) to a fresh host account —
        the channel-open half of ICS-27 registration.  Idempotent: an
        existing registration returns its address (channel reopen)."""
        if not controller_port.startswith(CONTROLLER_PORT_PREFIX):
            raise IBCError(
                f"controller port {controller_port!r} must start with "
                f"{CONTROLLER_PORT_PREFIX!r}"
            )
        key = (
            _ACCOUNT_PREFIX + connection_id.encode() + b"/"
            + controller_port.encode()
        )
        existing = self.store.get(key)
        if existing is not None:
            return existing.decode()
        address = self.derive_address(connection_id, controller_port)
        auth.get_or_create(address)
        self.store.set(key, address.encode())
        return address

    def interchain_account(
        self, connection_id: str, controller_port: str
    ) -> str | None:
        raw = self.store.get(
            _ACCOUNT_PREFIX + connection_id.encode() + b"/"
            + controller_port.encode()
        )
        return raw.decode() if raw is not None else None


class ICAHostModule:
    """The IBC app module mounted at port `icahost` (the recv-side
    callback the app's packet router dispatches to).  `execute` is the
    app's msg dispatcher: (ctx, msg, gas_remaining) -> (gas, events)."""

    def __init__(self, keeper: ICAHostKeeper, execute):
        self.keeper = keeper
        self.execute = execute

    def on_recv_packet(self, ctx, packet) -> tuple[bytes, list]:
        """Returns (ack, events).  Any failure is an error ack — never a
        state change (the app runs this on a cache like transfer's recv)."""
        from celestia_app_tpu.modules.ibc.transfer import SUCCESS_ACK, error_ack

        try:
            if not self.keeper.host_enabled():
                raise IBCError("ica host is disabled")
            # The account is bound to the CHANNEL's identity, not packet
            # bytes a relayer could rewrite: the source port names the
            # controller, and recv_packet has already matched it against
            # the destination channel's counterparty.
            from celestia_app_tpu.modules.ibc.core import ChannelKeeper

            chan = ChannelKeeper(ctx.store).channel(
                packet.destination_port, packet.destination_channel
            )
            account = self.keeper.interchain_account(
                chan.connection_id, packet.source_port
            )
            if account is None:
                raise IBCError(
                    f"no interchain account for {packet.source_port}"
                )
            ptype, msgs, _memo = decode_packet_data(packet.data)
            if ptype != EXECUTE_TX:
                raise IBCError(f"unsupported ICA packet type {ptype}")
            if not msgs:
                raise IBCError("ICA packet carries no messages")
            allow = self.keeper.allow_messages()
            events: list = []
            for m in msgs:
                if m.TYPE_URL not in allow:
                    raise IBCError(
                        f"message {m.TYPE_URL} not in the ICA allow list"
                    )
                signer = getattr(m, "signer", None) or getattr(
                    m, "from_address", None
                )
                if signer != account:
                    raise IBCError(
                        f"ICA msg signer {signer} is not the interchain "
                        f"account {account}"
                    )
                _gas, evts = self.execute(ctx, m, 1_000_000)
                events.extend(evts)
            return SUCCESS_ACK, events
        except (IBCError, ValueError) as e:
            return error_ack(str(e)), []

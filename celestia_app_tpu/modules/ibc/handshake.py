"""03-connection + 04-channel handshakes, proof-verified end to end.

The reference's handshakes live in ibc-go core (02/03/04 keepers).  Here
each step verifies the counterparty's PREVIOUS step through the
connection's light client (modules/ibc/client.py): the counterparty wrote
its connection/channel record into its SMT-committed store, the relayer
ships `cms.proof(key)` for that record, and `verify_membership` checks it
against the app hash a verified Commit pinned.  Both chains run this same
code, so the storage keys proven are symmetric by construction:

    connection record:  ibc/conn/{connection_id}
    channel record:     ibc/chan/{port}/{channel_id}
    packet commitment:  ibc/commit/{port}/{channel}/{seq, 8B BE}
    packet receipt:     ibc/receipt/{port}/{channel}/{seq, 8B BE}
    packet ack:         ibc/ack/{port}/{channel}/{seq, 8B BE}

State machines (ibc-go semantics):
    connection: INIT -> TRYOPEN -> OPEN        (Init/Try/Ack/Confirm)
    channel:    INIT -> TRYOPEN -> OPEN        (Init/Try/Ack/Confirm)
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    decode_fields,
    encode_bytes_field,
)
from celestia_app_tpu.modules.ibc.client import ClientKeeper
from celestia_app_tpu.modules.ibc.core import Channel, IBCError, _chan_key
from celestia_app_tpu.state.store import KVStore

_CONN_PREFIX = b"ibc/conn/"
_NEXT_CONN_KEY = b"ibc/next_connection_id"
_NEXT_CHAN_KEY = b"ibc/next_channel_id"


def connection_key(connection_id: str) -> bytes:
    return _CONN_PREFIX + connection_id.encode()


def channel_key(port: str, channel_id: str) -> bytes:
    return _chan_key(b"chan", port, channel_id)


@dataclass(frozen=True)
class ConnectionEnd:
    connection_id: str
    client_id: str  # our client of the counterparty chain
    counterparty_connection_id: str = ""
    counterparty_client_id: str = ""
    state: str = "INIT"

    def marshal(self) -> bytes:
        return (
            encode_bytes_field(1, self.connection_id.encode())
            + encode_bytes_field(2, self.client_id.encode())
            + encode_bytes_field(3, self.counterparty_connection_id.encode())
            + encode_bytes_field(4, self.counterparty_client_id.encode())
            + encode_bytes_field(5, self.state.encode())
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "ConnectionEnd":
        f = {n: v for n, wt, v in decode_fields(raw) if wt == WIRE_LEN}
        return cls(
            f[1].decode(), f[2].decode(), f.get(3, b"").decode(),
            f.get(4, b"").decode(), f.get(5, b"OPEN").decode(),
        )


class ConnectionKeeper:
    """03-connection: the four-step handshake, each step proving the
    counterparty's record through the light client."""

    def __init__(self, store: KVStore):
        self.store = store
        self.clients = ClientKeeper(store)

    def _next_id(self) -> str:
        from celestia_app_tpu.modules.ibc.core import next_counter

        return f"connection-{next_counter(self.store, _NEXT_CONN_KEY)}"

    def _save(self, end: ConnectionEnd) -> None:
        self.store.set(connection_key(end.connection_id), end.marshal())

    def connection(self, connection_id: str) -> ConnectionEnd:
        raw = self.store.get(connection_key(connection_id))
        if raw is None:
            raise IBCError(f"no connection {connection_id}")
        return ConnectionEnd.unmarshal(raw)

    def open_init(self, client_id: str, counterparty_client_id: str) -> str:
        """ConnOpenInit (chain A): record intent; nothing to prove yet."""
        self.clients.client_state(client_id)  # must exist
        end = ConnectionEnd(
            self._next_id(), client_id,
            counterparty_client_id=counterparty_client_id, state="INIT",
        )
        self._save(end)
        return end.connection_id

    def open_try(
        self, client_id: str, counterparty_connection_id: str,
        counterparty_client_id: str, proof_init, proof_height: int,
    ) -> str:
        """ConnOpenTry (chain B): verify A really has an INIT record
        naming our client.  A's INIT doesn't know B's connection id yet —
        it recorded only the client pair, which is exactly what we verify."""
        expected = ConnectionEnd(
            counterparty_connection_id,
            client_id=counterparty_client_id,  # A's client of us
            counterparty_connection_id="",
            counterparty_client_id=client_id,  # our client of A, as A named it
            state="INIT",
        )
        self.clients.verify_membership(
            client_id, proof_height,
            connection_key(counterparty_connection_id),
            expected.marshal(), proof_init,
        )
        end = ConnectionEnd(
            self._next_id(), client_id,
            counterparty_connection_id=counterparty_connection_id,
            counterparty_client_id=counterparty_client_id, state="TRYOPEN",
        )
        self._save(end)
        return end.connection_id

    def open_ack(
        self, connection_id: str, counterparty_connection_id: str,
        proof_try, proof_height: int,
    ) -> None:
        """ConnOpenAck (chain A): verify B's TRYOPEN names our connection."""
        end = self.connection(connection_id)
        if end.state != "INIT":
            raise IBCError(
                f"connection {connection_id} is {end.state}, expected INIT"
            )
        expected = ConnectionEnd(
            counterparty_connection_id, end.counterparty_client_id,
            counterparty_connection_id=connection_id,
            counterparty_client_id=end.client_id, state="TRYOPEN",
        )
        self.clients.verify_membership(
            end.client_id, proof_height,
            connection_key(counterparty_connection_id),
            expected.marshal(), proof_try,
        )
        self._save(replace(
            end, state="OPEN",
            counterparty_connection_id=counterparty_connection_id,
        ))

    def open_confirm(
        self, connection_id: str, proof_ack, proof_height: int
    ) -> None:
        """ConnOpenConfirm (chain B): verify A went OPEN."""
        end = self.connection(connection_id)
        if end.state != "TRYOPEN":
            raise IBCError(
                f"connection {connection_id} is {end.state}, expected TRYOPEN"
            )
        expected = ConnectionEnd(
            end.counterparty_connection_id, end.counterparty_client_id,
            counterparty_connection_id=connection_id,
            counterparty_client_id=end.client_id, state="OPEN",
        )
        self.clients.verify_membership(
            end.client_id, proof_height,
            connection_key(end.counterparty_connection_id),
            expected.marshal(), proof_ack,
        )
        self._save(replace(end, state="OPEN"))


class ChannelHandshake:
    """04-channel handshake over an OPEN connection.  Channels created
    this way carry their connection id, which marks them proof-required
    on the packet path (modules/ibc/__init__ relay verification)."""

    def __init__(self, store: KVStore):
        self.store = store
        self.connections = ConnectionKeeper(store)

    def _next_channel_id(self) -> str:
        from celestia_app_tpu.modules.ibc.core import next_counter

        return f"channel-{next_counter(self.store, _NEXT_CHAN_KEY)}"

    def _save(self, chan: Channel) -> None:
        self.store.set(channel_key(chan.port, chan.channel_id), chan.marshal())

    def _get(self, port: str, channel_id: str) -> Channel:
        raw = self.store.get(channel_key(port, channel_id))
        if raw is None:
            raise IBCError(f"unknown channel {port}/{channel_id}")
        return Channel.unmarshal(raw)

    def _open_connection(self, connection_id: str) -> ConnectionEnd:
        end = self.connections.connection(connection_id)
        if end.state != "OPEN":
            raise IBCError(
                f"connection {connection_id} is {end.state}, expected OPEN"
            )
        return end

    @staticmethod
    def _ordering_for(port: str, counterparty_port: str) -> str:
        """Channel ordering by application (ibc-go: the app module picks
        it at handshake time): ICA runs over ORDERED channels, transfer
        (and everything else here) over UNORDERED.  Both ends derive the
        same answer (the ports swap but the rule is symmetric), and it is
        part of the proven channel ends, so a mismatch fails the
        handshake."""
        from celestia_app_tpu.modules.ibc.ica import (
            CONTROLLER_PORT_PREFIX,
            ICA_HOST_PORT,
        )

        ica = (
            port == ICA_HOST_PORT
            or counterparty_port == ICA_HOST_PORT
            or port.startswith(CONTROLLER_PORT_PREFIX)
            or counterparty_port.startswith(CONTROLLER_PORT_PREFIX)
        )
        return "ORDERED" if ica else "UNORDERED"

    def open_init(self, connection_id: str, port: str,
                  counterparty_port: str, version: str = "ics20-1") -> str:
        self._open_connection(connection_id)
        chan = Channel(
            port, self._next_channel_id(), counterparty_port, "",
            state="INIT", version=version, connection_id=connection_id,
            ordering=self._ordering_for(port, counterparty_port),
        )
        self._save(chan)
        return chan.channel_id

    def open_try(
        self, connection_id: str, port: str, counterparty_port: str,
        counterparty_channel_id: str, proof_init, proof_height: int,
        version: str = "ics20-1",
    ) -> str:
        end = self._open_connection(connection_id)
        ordering = self._ordering_for(port, counterparty_port)
        expected = Channel(
            counterparty_port, counterparty_channel_id, port, "",
            state="INIT", version=version,
            connection_id=end.counterparty_connection_id,
            ordering=ordering,
        )
        self.connections.clients.verify_membership(
            end.client_id, proof_height,
            channel_key(counterparty_port, counterparty_channel_id),
            expected.marshal(), proof_init,
        )
        chan = Channel(
            port, self._next_channel_id(), counterparty_port,
            counterparty_channel_id, state="TRYOPEN", version=version,
            connection_id=connection_id, ordering=ordering,
        )
        self._save(chan)
        self._on_chan_open_try(chan)
        return chan.channel_id

    def _on_chan_open_try(self, chan: Channel) -> None:
        """App-module channel-open callback (ibc-go OnChanOpenTry): a
        channel opened TO port `icahost` registers the interchain account
        for (connection, controller port) — without this the handshake
        would open a channel no EXECUTE_TX could ever use."""
        from celestia_app_tpu.modules.ibc.ica import ICA_HOST_PORT, ICAHostKeeper

        if chan.port == ICA_HOST_PORT:
            from celestia_app_tpu.state.accounts import AuthKeeper

            ICAHostKeeper(self.store).register_account(
                AuthKeeper(self.store), chan.connection_id,
                chan.counterparty_port,
            )

    def open_ack(
        self, port: str, channel_id: str, counterparty_channel_id: str,
        proof_try, proof_height: int,
    ) -> None:
        chan = self._get(port, channel_id)
        if chan.state != "INIT":
            raise IBCError(f"channel {channel_id} is {chan.state}, expected INIT")
        end = self._open_connection(chan.connection_id)
        expected = Channel(
            chan.counterparty_port, counterparty_channel_id, port, channel_id,
            state="TRYOPEN", version=chan.version,
            connection_id=end.counterparty_connection_id,
            ordering=chan.ordering,
        )
        self.connections.clients.verify_membership(
            end.client_id, proof_height,
            channel_key(chan.counterparty_port, counterparty_channel_id),
            expected.marshal(), proof_try,
        )
        self._save(replace(
            chan, state="OPEN",
            counterparty_channel_id=counterparty_channel_id,
        ))
        self._init_sequence(port, channel_id)

    def open_confirm(
        self, port: str, channel_id: str, proof_ack, proof_height: int
    ) -> None:
        chan = self._get(port, channel_id)
        if chan.state != "TRYOPEN":
            raise IBCError(
                f"channel {channel_id} is {chan.state}, expected TRYOPEN"
            )
        end = self._open_connection(chan.connection_id)
        expected = Channel(
            chan.counterparty_port, chan.counterparty_channel_id, port,
            channel_id, state="OPEN", version=chan.version,
            connection_id=end.counterparty_connection_id,
            ordering=chan.ordering,
        )
        self.connections.clients.verify_membership(
            end.client_id, proof_height,
            channel_key(chan.counterparty_port, chan.counterparty_channel_id),
            expected.marshal(), proof_ack,
        )
        self._save(replace(chan, state="OPEN"))
        self._init_sequence(port, channel_id)

    def _init_sequence(self, port: str, channel_id: str) -> None:
        key = _chan_key(b"nextseq", port, channel_id)
        if self.store.get(key) is None:
            self.store.set(key, (1).to_bytes(8, "big"))

    # --- closing (ChanCloseInit / ChanCloseConfirm) -------------------------
    @staticmethod
    def _user_close_forbidden(port: str) -> str | None:
        """ibc-go app-module OnChanCloseInit parity: ICS-20 refuses
        (escrowed funds must stay redeemable) and BOTH ICA sides refuse
        (ICA channels close only through the ordered-channel timeout
        path, never by users)."""
        from celestia_app_tpu.modules.ibc.ica import (
            CONTROLLER_PORT_PREFIX,
            ICA_HOST_PORT,
        )
        from celestia_app_tpu.modules.ibc.transfer import TRANSFER_PORT

        if port == TRANSFER_PORT:
            return (
                "transfer channels cannot be closed by users "
                "(ics20 OnChanCloseInit)"
            )
        if port == ICA_HOST_PORT or port.startswith(CONTROLLER_PORT_PREFIX):
            return (
                "interchain-account channels cannot be closed by users "
                "(ica OnChanCloseInit; they close via the timeout path)"
            )
        return None

    def close_init(self, port: str, channel_id: str) -> None:
        """ChanCloseInit: the local end goes CLOSED (only for app ports
        whose module allows user-initiated closes)."""
        chan = self._get(port, channel_id)
        if chan.state != "OPEN":
            raise IBCError(
                f"channel {channel_id} is {chan.state}, expected OPEN"
            )
        refusal = self._user_close_forbidden(port)
        if refusal is not None:
            raise IBCError(refusal)
        self._save(replace(chan, state="CLOSED"))

    def close_confirm(
        self, port: str, channel_id: str, proof_init, proof_height: int
    ) -> None:
        """ChanCloseConfirm: close the local end after PROVING the
        counterparty already closed (connection-backed channels only).
        In-flight packets still flush: timeout_packet works on CLOSED
        channels (core.py), so escrows refund after a close."""
        chan = self._get(port, channel_id)
        if chan.state == "CLOSED":
            return  # idempotent
        if not chan.connection_id:
            raise IBCError(
                "close-confirm needs a connection-backed channel "
                "(direct-OPEN test channels close via close_init on both "
                "ends)"
            )
        end = self.connections.connection(chan.connection_id)
        expected = Channel(
            chan.counterparty_port, chan.counterparty_channel_id, port,
            channel_id, state="CLOSED", version=chan.version,
            connection_id=end.counterparty_connection_id,
            ordering=chan.ordering,
        )
        self.connections.clients.verify_membership(
            end.client_id, proof_height,
            channel_key(chan.counterparty_port, chan.counterparty_channel_id),
            expected.marshal(), proof_init,
        )
        self._save(replace(chan, state="CLOSED"))


# --- packet-proof verification (the relay msgs' proof path) -----------------


def _require_proof(proof, what: str):
    if proof is None:
        raise IBCError(
            f"channel is connection-backed: a verified {what} proof is "
            "required (IBC-lite trusted relay only applies to direct-OPEN "
            "channels)"
        )


def verify_recv_proof(store, chan: Channel, packet, proof, proof_height: int) -> None:
    """MsgRecvPacket on a connection-backed channel: the packet's
    commitment must exist in the SENDER's proven state."""
    _require_proof(proof, "commitment")
    conn = ConnectionKeeper(store)
    end = conn.connection(chan.connection_id)
    key = _chan_key(
        b"commit", packet.source_port, packet.source_channel, packet.sequence
    )
    conn.clients.verify_membership(
        end.client_id, proof_height, key, packet.commitment(), proof
    )


def verify_ack_proof(
    store, chan: Channel, packet, ack: bytes, proof, proof_height: int
) -> None:
    """MsgAcknowledgement: the RECEIVER's proven state holds
    sha256(ack) under the packet's ack key (ibc-go
    CommitAcknowledgement)."""
    import hashlib

    _require_proof(proof, "acknowledgement")
    conn = ConnectionKeeper(store)
    end = conn.connection(chan.connection_id)
    key = _chan_key(
        b"ack", packet.destination_port, packet.destination_channel,
        packet.sequence,
    )
    conn.clients.verify_membership(
        end.client_id, proof_height, key, hashlib.sha256(ack).digest(), proof
    )


def verify_timeout_proof(
    store, chan: Channel, packet, proof, proof_height: int
) -> None:
    """MsgTimeout: the RECEIVER's proven state has NO receipt for the
    packet at `proof_height` (it never arrived), and the proof height
    itself is past the packet's height timeout — so it can never arrive
    before timing out.  Timestamp timeouts verify against the
    counterparty's +2/3-attested block time (counterparty_proof_time),
    not anyone's local clock."""
    _require_proof(proof, "non-receipt")
    conn = ConnectionKeeper(store)
    end = conn.connection(chan.connection_id)
    key = _chan_key(
        b"receipt", packet.destination_port, packet.destination_channel,
        packet.sequence,
    )
    conn.clients.verify_non_membership(end.client_id, proof_height, key, proof)


def counterparty_proof_time(store, chan: Channel, proof_height: int) -> int:
    """The attested counterparty time bounding a non-receipt at
    `proof_height` (ibc-go GetTimestampAtHeight over the 07-tendermint
    consensus state).

    The proven state is the counterparty's app hash AFTER its block
    `proof_height`, pinned by the consensus state at proof_height + 1 —
    whose time_ns is inside the +2/3-signed block id (consensus/votes.py
    block_id).  Any future receipt lands in a block >= proof_height + 1
    with a strictly later time (BFT time monotonicity, enforced at
    proposal validation), so `cs.time_ns >= packet.timeout_timestamp`
    proves the packet can never be accepted.  Returns 0 (= timestamp
    timeout never provable; use a height timeout) for consensus states
    recorded without a time."""
    conn = ConnectionKeeper(store)
    end = conn.connection(chan.connection_id)
    cs = conn.clients.consensus_state(end.client_id, proof_height + 1)
    return cs.time_ns

"""IBC core-lite: channels, packets, commitments, receipts, acks.

The 04-channel state machine as the transfer stack consumes it
(ibc-go v6 modules/core/04-channel/keeper): SendPacket stores a packet
commitment, RecvPacket writes a receipt (the replay guard the reference's
RedundantRelayDecorator consults), WriteAcknowledgement stores the ack,
AcknowledgePacket / TimeoutPacket delete the commitment.  Commitment bytes
follow ibc-go's CommitPacket: sha256(timeout_timestamp BE8 ||
revision_number BE8 || revision_height BE8 || sha256(data)).

Handshakes and light-client proof verification are out of scope (channels
are created OPEN, proofs are the relayer's word — see package docstring).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    encode_bytes_field,
    encode_varint_field,
)
from celestia_app_tpu.state.store import KVStore


class IBCError(ValueError):
    pass


@dataclass(frozen=True)
class Height:
    """ibc-go exported.Height (revision number + height); 0-0 = no timeout."""

    revision_number: int = 0
    revision_height: int = 0

    def is_zero(self) -> bool:
        return self.revision_number == 0 and self.revision_height == 0


@dataclass(frozen=True)
class Channel:
    port: str
    channel_id: str
    counterparty_port: str
    counterparty_channel_id: str
    state: str = "OPEN"
    version: str = "ics20-1"
    # Set when the channel was created by the proof-verified handshake
    # (modules/ibc/handshake.py); empty for direct-OPEN test channels.
    # A connection-backed channel REQUIRES packet proofs on relay.
    connection_id: str = ""
    # ibc-go channeltypes.Order: UNORDERED (transfer) or ORDERED (ICA).
    # ORDERED channels enforce exact receive sequencing and CLOSE on a
    # packet timeout (a gap can never be filled once its packet expired).
    ordering: str = "UNORDERED"

    def marshal(self) -> bytes:
        out = (
            encode_bytes_field(1, self.port.encode())
            + encode_bytes_field(2, self.channel_id.encode())
            + encode_bytes_field(3, self.counterparty_port.encode())
            + encode_bytes_field(4, self.counterparty_channel_id.encode())
            + encode_bytes_field(5, self.state.encode())
            + encode_bytes_field(6, self.version.encode())
        )
        if self.connection_id:
            out += encode_bytes_field(7, self.connection_id.encode())
        if self.ordering != "UNORDERED":
            out += encode_bytes_field(8, self.ordering.encode())
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Channel":
        f = {num: val for num, wt, val in decode_fields(raw) if wt == WIRE_LEN}
        return cls(
            f[1].decode(), f[2].decode(), f[3].decode(), f[4].decode(),
            f[5].decode(), f[6].decode(), f.get(7, b"").decode(),
            f.get(8, b"UNORDERED").decode(),
        )


@dataclass(frozen=True)
class Packet:
    """channeltypes.Packet (ibc-go proto field numbers)."""

    sequence: int
    source_port: str
    source_channel: str
    destination_port: str
    destination_channel: str
    data: bytes
    timeout_height: Height = Height()
    timeout_timestamp_ns: int = 0

    def marshal(self) -> bytes:
        return (
            encode_varint_field(1, self.sequence)
            + encode_bytes_field(2, self.source_port.encode())
            + encode_bytes_field(3, self.source_channel.encode())
            + encode_bytes_field(4, self.destination_port.encode())
            + encode_bytes_field(5, self.destination_channel.encode())
            + encode_bytes_field(6, self.data)
            + encode_bytes_field(
                7,
                encode_varint_field(1, self.timeout_height.revision_number)
                + encode_varint_field(2, self.timeout_height.revision_height),
            )
            + encode_varint_field(8, self.timeout_timestamp_ns)
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Packet":
        ints = {num: val for num, wt, val in decode_fields(raw) if wt == WIRE_VARINT}
        strs = {num: val for num, wt, val in decode_fields(raw) if wt == WIRE_LEN}
        th = Height()
        if 7 in strs:
            hf = {n: v for n, wt, v in decode_fields(strs[7]) if wt == WIRE_VARINT}
            th = Height(hf.get(1, 0), hf.get(2, 0))
        return cls(
            ints.get(1, 0), strs[2].decode(), strs[3].decode(),
            strs[4].decode(), strs[5].decode(), strs.get(6, b""),
            th, ints.get(8, 0),
        )

    def commitment(self) -> bytes:
        """ibc-go channeltypes.CommitPacket."""
        buf = self.timeout_timestamp_ns.to_bytes(8, "big")
        buf += self.timeout_height.revision_number.to_bytes(8, "big")
        buf += self.timeout_height.revision_height.to_bytes(8, "big")
        buf += hashlib.sha256(self.data).digest()
        return hashlib.sha256(buf).digest()


def next_counter(store: KVStore, key: bytes) -> int:
    """Monotonic 8-byte-BE counter starting at 0 (client / connection /
    channel id allocation — one definition of the byte width and start)."""
    n = int.from_bytes(store.get(key) or b"\x00", "big")
    store.set(key, (n + 1).to_bytes(8, "big"))
    return n


def _chan_key(kind: bytes, port: str, channel_id: str, seq: int | None = None) -> bytes:
    key = b"ibc/" + kind + b"/" + port.encode() + b"/" + channel_id.encode()
    if seq is not None:
        key += b"/" + seq.to_bytes(8, "big")
    return key


class ChannelKeeper:
    """04-channel keeper over the app's KV store."""

    def __init__(self, store: KVStore):
        self.store = store

    # --- channel registry ----------------------------------------------------
    def create_channel(self, channel: Channel) -> None:
        """Direct-OPEN channel creation (the ibctesting Setup shortcut)."""
        key = _chan_key(b"chan", channel.port, channel.channel_id)
        if self.store.get(key) is not None:
            raise IBCError(f"channel {channel.channel_id} already exists")
        self.store.set(key, channel.marshal())
        self.store.set(
            _chan_key(b"nextseq", channel.port, channel.channel_id),
            (1).to_bytes(8, "big"),
        )

    def channel(self, port: str, channel_id: str) -> Channel:
        raw = self.store.get(_chan_key(b"chan", port, channel_id))
        if raw is None:
            raise IBCError(f"unknown channel {port}/{channel_id}")
        return Channel.unmarshal(raw)

    def channels(self) -> list[Channel]:
        return [Channel.unmarshal(v) for _, v in self.store.iterate(b"ibc/chan/")]

    # --- send ---------------------------------------------------------------
    def send_packet(
        self,
        source_port: str,
        source_channel: str,
        data: bytes,
        timeout_height: Height = Height(),
        timeout_timestamp_ns: int = 0,
    ) -> Packet:
        chan = self.channel(source_port, source_channel)
        if chan.state != "OPEN":
            raise IBCError(f"channel {source_channel} not open")
        seq_key = _chan_key(b"nextseq", source_port, source_channel)
        seq = int.from_bytes(self.store.get(seq_key) or b"\x01", "big")
        self.store.set(seq_key, (seq + 1).to_bytes(8, "big"))
        packet = Packet(
            seq, source_port, source_channel,
            chan.counterparty_port, chan.counterparty_channel_id,
            data, timeout_height, timeout_timestamp_ns,
        )
        self.store.set(
            _chan_key(b"commit", source_port, source_channel, seq),
            packet.commitment(),
        )
        return packet

    def packet_commitment(self, port: str, channel_id: str, seq: int) -> bytes | None:
        return self.store.get(_chan_key(b"commit", port, channel_id, seq))

    # --- receive ------------------------------------------------------------
    def has_receipt(self, packet: Packet) -> bool:
        return (
            self.store.get(
                _chan_key(
                    b"receipt", packet.destination_port,
                    packet.destination_channel, packet.sequence,
                )
            )
            is not None
        )

    def recv_packet(self, packet: Packet, height: int, time_ns: int) -> None:
        """Receipt write + replay/timeout checks (RecvPacket core half)."""
        chan = self.channel(packet.destination_port, packet.destination_channel)
        if chan.state != "OPEN":
            # Reachable since handshakes exist: a TRYOPEN channel awaiting
            # open_confirm must not accept packets (ibc-go RecvPacket).
            raise IBCError(
                f"channel {packet.destination_channel} is {chan.state}, not OPEN"
            )
        if (
            chan.counterparty_port != packet.source_port
            or chan.counterparty_channel_id != packet.source_channel
        ):
            raise IBCError("packet routed to the wrong channel")
        if chan.ordering == "ORDERED":
            # ibc-go ORDERED semantics: the receive sequence must be
            # exactly the next expected (ErrPacketSequenceOutOfOrder);
            # the counter, not receipts, is the replay protection.
            recv_key = _chan_key(
                b"nextrecvseq", packet.destination_port,
                packet.destination_channel,
            )
            expected = int.from_bytes(self.store.get(recv_key) or b"\x01", "big")
            if packet.sequence != expected:
                raise IBCError(
                    f"ordered channel {packet.destination_channel}: packet "
                    f"sequence {packet.sequence} != next expected {expected}"
                )
            self.store.set(recv_key, (expected + 1).to_bytes(8, "big"))
        elif self.has_receipt(packet):
            raise IBCError(
                f"packet sequence {packet.sequence} already received"
            )
        if (
            not packet.timeout_height.is_zero()
            and height >= packet.timeout_height.revision_height
        ):
            raise IBCError("packet timeout height elapsed on receiver")
        if packet.timeout_timestamp_ns and time_ns >= packet.timeout_timestamp_ns:
            raise IBCError("packet timeout timestamp elapsed on receiver")
        self.store.set(
            _chan_key(
                b"receipt", packet.destination_port,
                packet.destination_channel, packet.sequence,
            ),
            b"\x01",
        )

    def write_acknowledgement(self, packet: Packet, ack: bytes) -> None:
        self.store.set(
            _chan_key(
                b"ack", packet.destination_port,
                packet.destination_channel, packet.sequence,
            ),
            hashlib.sha256(ack).digest(),
        )

    def acknowledgement(self, port: str, channel_id: str, seq: int) -> bytes | None:
        return self.store.get(_chan_key(b"ack", port, channel_id, seq))

    # --- ack / timeout on the sender ----------------------------------------
    def _check_counterparty_routing(self, packet: Packet) -> None:
        """packet.destination MUST be the source channel's counterparty.
        CommitPacket excludes the destination fields, so without this check
        a relayer could rewrite them and prove non-receipt (or replay an
        ack) under a key nothing was ever written to — ibc-go's
        AcknowledgePacket/TimeoutPacket make the same check for the same
        reason."""
        chan = self.channel(packet.source_port, packet.source_channel)
        if (
            chan.counterparty_port != packet.destination_port
            or chan.counterparty_channel_id != packet.destination_channel
        ):
            raise IBCError(
                f"packet destination {packet.destination_port}/"
                f"{packet.destination_channel} is not channel "
                f"{packet.source_channel}'s counterparty"
            )
        return chan

    def _delete_commitment(self, packet: Packet) -> None:
        key = _chan_key(
            b"commit", packet.source_port, packet.source_channel, packet.sequence
        )
        stored = self.store.get(key)
        if stored is None:
            raise IBCError(
                f"packet sequence {packet.sequence} has no commitment "
                "(already acked or timed out)"
            )
        if stored != packet.commitment():
            raise IBCError("packet commitment mismatch")
        self.store.delete(key)

    def acknowledge_packet(self, packet: Packet) -> None:
        chan = self._check_counterparty_routing(packet)
        if chan.state != "OPEN":
            raise IBCError(
                f"channel {packet.source_channel} is {chan.state}, not OPEN"
            )
        self._delete_commitment(packet)

    def timeout_packet(self, packet: Packet, proof_height: int, proof_time_ns: int) -> None:
        """TimeoutPacket: the packet must actually be past its timeout as
        observed on the counterparty (height/time from the relayer's
        verified proof / attested consensus time).  NO channel-state
        check: in-flight packets on a CLOSED channel must still flush
        through timeouts (ibc-go TimeoutPacket works on any state so
        escrows can refund after a close).  On an ORDERED channel the
        timeout CLOSES the channel (ibc-go timeoutExecuted): the expired
        sequence leaves a hole the receiver's exact-order rule can never
        accept past."""
        chan = self._check_counterparty_routing(packet)
        timed_out = (
            not packet.timeout_height.is_zero()
            and proof_height >= packet.timeout_height.revision_height
        ) or (
            packet.timeout_timestamp_ns
            and proof_time_ns >= packet.timeout_timestamp_ns
        )
        if not timed_out:
            raise IBCError("packet has not timed out yet")
        self._delete_commitment(packet)
        if chan.ordering == "ORDERED" and chan.state != "CLOSED":
            self.store.set(
                _chan_key(b"chan", chan.port, chan.channel_id),
                Channel(
                    chan.port, chan.channel_id, chan.counterparty_port,
                    chan.counterparty_channel_id, "CLOSED", chan.version,
                    chan.connection_id, chan.ordering,
                ).marshal(),
            )

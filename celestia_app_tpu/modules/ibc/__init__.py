"""IBC-lite: the channel/packet machinery the transfer stack mounts on.

Scope (PARITY.md): packet lifecycle parity — send/recv/ack/timeout with
commitments, receipts (relay dedup), and acks in state; ICS-20 transfer
with escrow/voucher denom tracing; the reference's middleware stack order
(tokenfilter > packet-forward [v2] > transfer, app/app.go:329-346).
Light clients and the 4-step handshakes are out of scope: channels are
established directly (the ibctesting `path.Setup` shortcut), and proof
verification is delegated to the consensus layer driving the app.
"""

from celestia_app_tpu.modules.ibc.core import (
    Channel,
    ChannelKeeper,
    Height,
    IBCError,
    Packet,
)
from celestia_app_tpu.modules.ibc.transfer import (
    IBCModule,
    TransferKeeper,
    TransferModule,
    voucher_denom,
)
from celestia_app_tpu.modules.ibc.stack import (
    PacketForwardMiddleware,
    TokenFilterMiddleware,
    build_transfer_stack,
)

__all__ = [
    "Channel",
    "ChannelKeeper",
    "Height",
    "IBCError",
    "IBCModule",
    "Packet",
    "PacketForwardMiddleware",
    "TokenFilterMiddleware",
    "TransferKeeper",
    "TransferModule",
    "build_transfer_stack",
    "voucher_denom",
]

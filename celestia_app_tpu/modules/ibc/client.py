"""02-client: on-chain light clients of counterparty chains.

The reference chain delegates this to ibc-go's 02-client + the
07-tendermint light client (wired transitively via app/app.go:300-346).
This framework's chains commit with their OWN consensus plane
(consensus/votes.py): +2/3 secp256k1 precommits over
block_id(data_root_H, app_hash_{H-1}), state rooted in an SMT
(state/smt.py).  The native light client therefore verifies exactly that:

  * ClientState: counterparty chain id + trusted validator set + latest
    height + frozen flag;
  * UpdateClient(commit): `verify_commit` against the trusted set; a
    valid Commit at height H yields the counterparty's data root at H and
    its app hash at H-1 (Tendermint's header offset: the header at H
    carries the app hash of H-1) — stored as the consensus state;
  * VerifyMembership / VerifyNonMembership: SMT state proofs
    (state/smt.py::verify) against the proven app hash — the proof
    surface connection/channel handshakes and packet relay verify
    against;
  * Misbehaviour: two verified commits for the same height with different
    block ids freeze the client (07-tendermint's CheckMisbehaviour).

Valset rotation: sequential UpdateClient calls may carry a new validator
set (07-tendermint trusting-period semantics) — accepted when the commit
has +2/3 of the NEW set and >1/3 of the TRUSTED set's power in valid
precommits, so a chain can rotate 100% of its set across several hops
without the client being recreated (closes round-3 PARITY gap #2).
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.crypto.keys import PublicKey
from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    encode_bytes_field,
    encode_varint_field,
)
from celestia_app_tpu.modules.ibc.core import IBCError
from celestia_app_tpu.state.store import KVStore

_CLIENT_PREFIX = b"ibc/client/"
_CONSENSUS_PREFIX = b"ibc/consensus/"
_NEXT_CLIENT_KEY = b"ibc/next_client_id"


@dataclass(frozen=True)
class ClientState:
    client_id: str
    chain_id: str
    # (operator address, consensus pubkey, power) triples — the trusted set.
    validators: tuple[tuple[str, bytes, int], ...]
    latest_height: int = 0
    frozen: bool = False

    def validator_map(self) -> dict[str, tuple[PublicKey, int]]:
        return {a: (PublicKey(pk), p) for a, pk, p in self.validators}

    def marshal(self) -> bytes:
        out = (
            encode_bytes_field(1, self.client_id.encode())
            + encode_bytes_field(2, self.chain_id.encode())
            + encode_varint_field(3, self.latest_height)
            + encode_varint_field(4, int(self.frozen))
        )
        for addr, pk, power in self.validators:
            out += encode_bytes_field(
                5,
                encode_bytes_field(1, addr.encode())
                + encode_bytes_field(2, pk)
                + encode_varint_field(3, power),
            )
        return out

    @classmethod
    def unmarshal(cls, raw: bytes) -> "ClientState":
        ints = {n: v for n, wt, v in decode_fields(raw) if wt == WIRE_VARINT}
        cid, chain = "", ""
        vals = []
        for n, wt, v in decode_fields(raw):
            if n == 1 and wt == WIRE_LEN:
                cid = v.decode()
            elif n == 2 and wt == WIRE_LEN:
                chain = v.decode()
            elif n == 5 and wt == WIRE_LEN:
                f = {fn: fv for fn, fwt, fv in decode_fields(v) if fwt == WIRE_LEN}
                fi = {fn: fv for fn, fwt, fv in decode_fields(v) if fwt == WIRE_VARINT}
                vals.append((f[1].decode(), f[2], fi.get(3, 0)))
        return cls(cid, chain, tuple(vals), ints.get(3, 0), bool(ints.get(4, 0)))


@dataclass(frozen=True)
class ConsensusState:
    """What a verified Commit at `height` pins: the counterparty's data
    root at `height`, its app hash at `height - 1`, and the block time —
    all inside the signed block id, so timestamp timeouts verify against
    a +2/3-attested clock, not anyone's local one (ibc-go's
    ConsensusState carries Timestamp from the Tendermint header the same
    way)."""

    height: int
    data_root: bytes
    prev_app_hash: bytes
    time_ns: int = 0

    def marshal(self) -> bytes:
        return (
            encode_varint_field(1, self.height)
            + encode_bytes_field(2, self.data_root)
            + encode_bytes_field(3, self.prev_app_hash)
            + encode_varint_field(4, self.time_ns)
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "ConsensusState":
        ints = {n: v for n, wt, v in decode_fields(raw) if wt == WIRE_VARINT}
        b = {n: v for n, wt, v in decode_fields(raw) if wt == WIRE_LEN}
        return cls(ints.get(1, 0), b.get(2, b""), b.get(3, b""), ints.get(4, 0))


class ClientKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    # --- lifecycle -----------------------------------------------------------
    def create_client(
        self,
        chain_id: str,
        validators: dict[str, tuple[PublicKey, int]],
    ) -> str:
        """MsgCreateClient: pin the counterparty's chain id + validator
        set; returns the new client id (07-tendermint-style numbering)."""
        if not validators:
            raise IBCError("client needs a non-empty validator set")
        from celestia_app_tpu.modules.ibc.core import next_counter

        client_id = f"07-tpu-{next_counter(self.store, _NEXT_CLIENT_KEY)}"
        cs = ClientState(
            client_id, chain_id,
            tuple(
                (addr, pk.bytes, power)
                for addr, (pk, power) in sorted(validators.items())
            ),
        )
        self.store.set(_CLIENT_PREFIX + client_id.encode(), cs.marshal())
        return client_id

    def client_state(self, client_id: str) -> ClientState:
        raw = self.store.get(_CLIENT_PREFIX + client_id.encode())
        if raw is None:
            raise IBCError(f"no client {client_id}")
        return ClientState.unmarshal(raw)

    def _save(self, cs: ClientState) -> None:
        self.store.set(_CLIENT_PREFIX + cs.client_id.encode(), cs.marshal())

    def update_client(
        self, client_id: str, commit, new_validators=None
    ) -> ConsensusState:
        """MsgUpdateClient: verify the Commit, store the consensus state it
        pins.  A conflicting verified commit for an already-known height is
        misbehaviour: the client freezes (07-tendermint CheckForMisbehaviour
        + frozen clients reject everything).

        Valset rotation (07-tendermint trusting-period semantics, the rule
        ibc-go's VerifyClientMessage applies through sequential headers):
        pass `new_validators` (addr -> (PublicKey, power)) to rotate trust.
        The commit must then carry +2/3 of the NEW set's power AND valid
        precommits from MORE THAN 1/3 of the currently TRUSTED set's power
        — forging a rotation requires corrupting >1/3 of the trusted
        validators, Tendermint's light-client security bound.  Chains can
        rotate 100% of their set across several such hops.
        """
        from celestia_app_tpu.consensus import verify_commit
        from celestia_app_tpu.consensus.votes import PRECOMMIT

        cs = self.client_state(client_id)
        if cs.frozen:
            raise IBCError(f"client {client_id} is frozen")
        if new_validators is None:
            if not verify_commit(cs.validator_map(), cs.chain_id, commit):
                raise IBCError(
                    f"commit at height {commit.height} fails verification "
                    f"against client {client_id}"
                )
        else:
            if commit.height <= cs.latest_height:
                raise IBCError(
                    "valset rotation must move the client forward "
                    f"(height {commit.height} <= {cs.latest_height})"
                )
            if not verify_commit(dict(new_validators), cs.chain_id, commit):
                raise IBCError(
                    f"rotation commit at height {commit.height} lacks +2/3 "
                    "of the proposed validator set"
                )
            trusted = cs.validator_map()
            total = sum(p for _, p in trusted.values())
            counted: set[str] = set()
            overlap = 0
            for vote in commit.precommits:
                entry = trusted.get(vote.validator)
                if entry is None or vote.validator in counted:
                    continue
                pub, power = entry
                if (
                    vote.height == commit.height
                    and vote.round == commit.round
                    and vote.vote_type == PRECOMMIT
                    and vote.block_hash == commit.block_hash
                    and vote.verify(pub, cs.chain_id)
                ):
                    counted.add(vote.validator)
                    overlap += power
            if 3 * overlap <= total:
                raise IBCError(
                    f"rotation commit at height {commit.height} carries only "
                    f"{overlap}/{total} trusted power; need > 1/3"
                )
            # No save here: rotation requires height > latest_height, so
            # the latest-height save below always persists this rebuilt
            # state (validators rotated, height advanced) in one write.
            cs = ClientState(
                cs.client_id, cs.chain_id,
                tuple(
                    (addr, pk.bytes, power)
                    for addr, (pk, power) in sorted(dict(new_validators).items())
                ),
                cs.latest_height, cs.frozen,
            )
        new = ConsensusState(
            commit.height, commit.data_root, commit.prev_app_hash,
            getattr(commit, "time_ns", 0),
        )
        key = (
            _CONSENSUS_PREFIX + client_id.encode() + b"/"
            + commit.height.to_bytes(8, "big")
        )
        existing = self.store.get(key)
        if existing is not None:
            prior = ConsensusState.unmarshal(existing)
            if (prior.data_root, prior.prev_app_hash, prior.time_ns) != (
                new.data_root, new.prev_app_hash, new.time_ns,
            ):
                # Two +2/3-signed commits for one height: equivocation at
                # chain scale.  Freeze; never serve this client again.
                self._save(
                    ClientState(
                        cs.client_id, cs.chain_id, cs.validators,
                        cs.latest_height, frozen=True,
                    )
                )
                raise IBCError(
                    f"misbehaviour on client {client_id} at height "
                    f"{commit.height}: conflicting commits — client frozen"
                )
            return prior
        self.store.set(key, new.marshal())
        if commit.height > cs.latest_height:
            self._save(
                ClientState(
                    cs.client_id, cs.chain_id, cs.validators, commit.height
                )
            )
        return new

    def consensus_state(self, client_id: str, height: int) -> ConsensusState:
        raw = self.store.get(
            _CONSENSUS_PREFIX + client_id.encode() + b"/" + height.to_bytes(8, "big")
        )
        if raw is None:
            raise IBCError(
                f"client {client_id} has no consensus state at height {height}"
            )
        return ConsensusState.unmarshal(raw)

    def app_hash_at(self, client_id: str, height: int) -> bytes:
        """The counterparty app hash state proofs at `height` verify
        against — pinned by the commit at height+1 (the header offset)."""
        return self.consensus_state(client_id, height + 1).prev_app_hash

    # --- proof verification (what handshakes + relay call) -------------------
    def verify_membership(
        self, client_id: str, height: int, key: bytes, value: bytes, proof
    ) -> None:
        """The counterparty's state at `height` contains key -> value."""
        from celestia_app_tpu.state import smt

        cs = self.client_state(client_id)
        if cs.frozen:
            raise IBCError(f"client {client_id} is frozen")
        if proof.key != key or proof.value != value:
            raise IBCError(
                f"proof is for {proof.key!r}={proof.value!r}, "
                f"expected {key!r}={value!r}"
            )
        if not smt.verify(proof, self.app_hash_at(client_id, height)):
            raise IBCError(
                f"membership proof for {key!r} fails against client "
                f"{client_id} at height {height}"
            )

    def verify_non_membership(
        self, client_id: str, height: int, key: bytes, proof
    ) -> None:
        """The counterparty's state at `height` does NOT contain `key`."""
        from celestia_app_tpu.state import smt

        cs = self.client_state(client_id)
        if cs.frozen:
            raise IBCError(f"client {client_id} is frozen")
        if proof.key != key or proof.value is not None:
            raise IBCError("proof is not a non-membership proof for the key")
        if not smt.verify(proof, self.app_hash_at(client_id, height)):
            raise IBCError(
                f"non-membership proof for {key!r} fails against client "
                f"{client_id} at height {height}"
            )

"""The transfer middleware stack, in the reference's order.

app/app.go:329-346 (top to bottom): Token Filter > Packet Forward
Middleware (app version 2 only, via the versioned IBC module) > Transfer.
"""

from __future__ import annotations

import json

from celestia_app_tpu.modules.ibc.core import Height, IBCError, Packet
from celestia_app_tpu.modules.ibc.transfer import (
    TransferKeeper,
    TransferModule,
    error_ack,
    ack_is_error,
)
from celestia_app_tpu.modules.tokenfilter import on_recv_packet as tokenfilter_decision


class TokenFilterMiddleware:
    """x/tokenfilter mounted as middleware (ibc_middleware.go:21-78):
    wraps only OnRecvPacket; everything else passes straight through."""

    def __init__(self, inner):
        self.inner = inner

    def on_recv_packet(self, ctx, packet: Packet) -> bytes:
        decision = tokenfilter_decision(
            packet.source_port, packet.source_channel, packet.data
        )
        if not decision.success:
            return error_ack(decision.error)
        return self.inner.on_recv_packet(ctx, packet)

    def on_acknowledgement_packet(self, ctx, packet: Packet, ack: bytes) -> None:
        self.inner.on_acknowledgement_packet(ctx, packet, ack)

    def on_timeout_packet(self, ctx, packet: Packet) -> None:
        self.inner.on_timeout_packet(ctx, packet)


class PacketForwardMiddleware:
    """packet-forward-middleware, reduced to the one-hop forward the
    reference's PFM tests exercise (test/pfm): a transfer whose memo is
    {"forward": {"receiver": ..., "port": ..., "channel": ...}} is
    delivered to this chain, then immediately re-sent onward; the onward
    leg's failure refunds the intermediate receiver here (simplified
    non-atomic retry model; the reference's escrow-chaining is noted in
    PARITY.md)."""

    def __init__(self, inner, transfer_keeper: TransferKeeper):
        self.inner = inner
        self.keeper = transfer_keeper

    @staticmethod
    def _forward_directive(packet: Packet) -> dict | None:
        try:
            data = json.loads(packet.data)
            memo = data.get("memo", "")
            fwd = json.loads(memo).get("forward") if memo else None
        except (ValueError, TypeError, AttributeError):
            return None
        if not isinstance(fwd, dict):
            return None
        if not all(isinstance(fwd.get(k), str) for k in ("receiver", "channel")):
            return None
        return fwd

    def on_recv_packet(self, ctx, packet: Packet) -> bytes:
        fwd = self._forward_directive(packet)
        if fwd is None:
            return self.inner.on_recv_packet(ctx, packet)
        from celestia_app_tpu.modules.ibc.transfer import local_denom_on_recv

        try:
            # Deliver locally first (mint/unescrow to the hop receiver)...
            data = json.loads(packet.data)
            hop_receiver = data["receiver"]
            amount = int(data["amount"])
            local_denom = local_denom_on_recv(packet, data["denom"])
        except (ValueError, KeyError, TypeError) as e:
            # Malformed packet data becomes an error ack (prompt refund on
            # the origin chain), never a failed tx that strands the packet.
            return error_ack(f"invalid packet data: {e}")
        ack = self.inner.on_recv_packet(ctx, packet)
        if ack_is_error(ack):
            return ack
        # ...then send onward from the hop account.
        try:
            self.keeper.send_transfer(
                source_channel=fwd["channel"],
                sender=hop_receiver,
                receiver=fwd["receiver"],
                denom=local_denom,
                amount=amount,
                source_port=fwd.get("port", packet.destination_port),
                memo=fwd.get("next", ""),
            )
        except (IBCError, ValueError) as e:
            return error_ack(f"forward failed: {e}")
        return ack

    def on_acknowledgement_packet(self, ctx, packet: Packet, ack: bytes) -> None:
        self.inner.on_acknowledgement_packet(ctx, packet, ack)

    def on_timeout_packet(self, ctx, packet: Packet) -> None:
        self.inner.on_timeout_packet(ctx, packet)


def build_transfer_stack(
    app_version: int, transfer_keeper: TransferKeeper, token_filter: bool = True
):
    """Reference stack wiring incl. the versioned-IBC-module gate:
    PFM participates only at app version >= 2 (app/app.go:336-344).
    `token_filter=False` builds the counterparty simapp's stack (the
    reference keeps such a chain in test/pfm/simapp.go for exactly this)."""
    stack = TransferModule(transfer_keeper)
    if app_version >= 2:
        stack = PacketForwardMiddleware(stack, transfer_keeper)
    if token_filter:
        stack = TokenFilterMiddleware(stack)
    return stack

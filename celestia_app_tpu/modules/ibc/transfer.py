"""ICS-20 fungible token transfer (ibc-go modules/apps/transfer).

Semantics mirrored from the ibc-go transfer keeper the reference mounts
(app/app.go:324-334):

  send:  sender chain is source  -> escrow native tokens (module account)
         sender chain is sink    -> burn the voucher
  recv:  receiver chain is source-> unescrow (strip one hop from the trace)
         receiver chain is sink  -> mint voucher "port/channel/denom"
  error ack / timeout            -> refund exactly what send took

Packet data is the ICS-20 JSON FungibleTokenPacketData, byte-compatible
with what a counterparty ibc-go chain would produce (sorted keys are NOT
required by the spec; we emit the ibc-go field order).
"""

from __future__ import annotations

import json
from typing import Protocol

from celestia_app_tpu.modules.ibc.core import ChannelKeeper, Height, IBCError, Packet
from celestia_app_tpu.modules.tokenfilter import (
    FungibleTokenPacketData,
    receiver_chain_is_source,
)
from celestia_app_tpu.state.accounts import BankKeeper

TRANSFER_PORT = "transfer"


def escrow_address(port: str, channel_id: str) -> str:
    """Per-channel escrow module account (ibc-go GetEscrowAddress)."""
    return f"ibc-escrow/{port}/{channel_id}"


def voucher_denom(dest_port: str, dest_channel: str, denom: str) -> str:
    """The received token's denom on the sink chain (one more trace hop)."""
    return f"{dest_port}/{dest_channel}/{denom}"


def sender_chain_is_source(source_port: str, source_channel: str, denom: str) -> bool:
    return not denom.startswith(f"{source_port}/{source_channel}/")


def local_denom_on_recv(packet: Packet, denom: str) -> str:
    """The denom a received token carries on THIS chain: strip one trace
    hop when the token is returning home, else add this channel's hop."""
    if receiver_chain_is_source(packet.source_port, packet.source_channel, denom):
        return denom[len(f"{packet.source_port}/{packet.source_channel}/"):]
    return voucher_denom(packet.destination_port, packet.destination_channel, denom)


def packet_data_bytes(data: FungibleTokenPacketData) -> bytes:
    """ibc-go ModuleCdc JSON encoding of FungibleTokenPacketData."""
    obj = {
        "denom": data.denom,
        "amount": data.amount,
        "sender": data.sender,
        "receiver": data.receiver,
    }
    if data.memo:
        obj["memo"] = data.memo
    return json.dumps(obj, separators=(",", ":")).encode()


SUCCESS_ACK = b'{"result":"AQ=="}'  # ibc-go channeltypes.NewResultAcknowledgement([]byte{1})


def error_ack(msg: str) -> bytes:
    return json.dumps({"error": msg}, separators=(",", ":")).encode()


def ack_is_error(ack: bytes) -> bool:
    try:
        return "error" in json.loads(ack)
    except (ValueError, TypeError):
        return True


class IBCModule(Protocol):
    """porttypes.IBCModule, reduced to the packet callbacks the stack uses."""

    def on_recv_packet(self, ctx, packet: Packet) -> bytes: ...
    def on_acknowledgement_packet(self, ctx, packet: Packet, ack: bytes) -> None: ...
    def on_timeout_packet(self, ctx, packet: Packet) -> None: ...


class TransferKeeper:
    """Send-side + refund half of the transfer app."""

    def __init__(self, channels: ChannelKeeper, bank: BankKeeper):
        self.channels = channels
        self.bank = bank
        # Packets sent during this keeper's lifetime (one msg execution):
        # middleware like PFM sends from inside OnRecvPacket, and the msg
        # handler surfaces these as ibc.send_packet events for relayers.
        self.sent: list[Packet] = []

    def send_transfer(
        self,
        source_channel: str,
        sender: str,
        receiver: str,
        denom: str,
        amount: int,
        timeout_height: Height = Height(),
        timeout_timestamp_ns: int = 0,
        memo: str = "",
        source_port: str = TRANSFER_PORT,
    ) -> Packet:
        if amount <= 0:
            raise IBCError("transfer amount must be positive")
        if sender_chain_is_source(source_port, source_channel, denom):
            # Escrow natives in the per-channel module account.
            self.bank.send(
                sender, escrow_address(source_port, source_channel), amount,
                denom=denom,
            )
        else:
            self.bank.burn(sender, amount, denom=denom)
        data = FungibleTokenPacketData(denom, str(amount), sender, receiver, memo)
        packet = self.channels.send_packet(
            source_port, source_channel, packet_data_bytes(data),
            timeout_height, timeout_timestamp_ns,
        )
        self.sent.append(packet)
        return packet

    def _refund(self, packet: Packet) -> None:
        data = FungibleTokenPacketData.from_json(packet.data)
        amount = int(data.amount)
        if sender_chain_is_source(packet.source_port, packet.source_channel, data.denom):
            self.bank.send(
                escrow_address(packet.source_port, packet.source_channel),
                data.sender, amount, denom=data.denom,
            )
        else:
            self.bank.mint(data.sender, amount, denom=data.denom)


class TransferModule:
    """The IBCModule at the bottom of the stack (receive + ack/timeout)."""

    def __init__(self, keeper: TransferKeeper):
        self.keeper = keeper

    def on_recv_packet(self, ctx, packet: Packet) -> bytes:
        try:
            data = FungibleTokenPacketData.from_json(packet.data)
            amount = int(data.amount)
            if amount <= 0:
                return error_ack("invalid amount")
            bank = self.keeper.bank
            local = local_denom_on_recv(packet, data.denom)
            if receiver_chain_is_source(
                packet.source_port, packet.source_channel, data.denom
            ):
                # Token returning home: release escrow.
                bank.send(
                    escrow_address(packet.destination_port, packet.destination_channel),
                    data.receiver, amount, denom=local,
                )
            else:
                bank.mint(data.receiver, amount, denom=local)
            return SUCCESS_ACK
        except (ValueError, KeyError) as e:
            return error_ack(str(e))

    def on_acknowledgement_packet(self, ctx, packet: Packet, ack: bytes) -> None:
        if ack_is_error(ack):
            self.keeper._refund(packet)

    def on_timeout_packet(self, ctx, packet: Packet) -> None:
        self.keeper._refund(packet)

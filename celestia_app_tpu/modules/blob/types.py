"""x/blob types: PFB construction, BlobTx validation, gas model.

Behavioral parity with reference x/blob/types (payforblob.go, blob_tx.go):
NewMsgPayForBlobs computes share commitments; ValidateBlobTx re-derives and
compares them (the consensus-critical check run in CheckTx and
ProcessProposal, app/check_tx.go:43, app/process_proposal.go:107).
"""

from __future__ import annotations

from celestia_app_tpu.constants import (
    DEFAULT_GAS_PER_BLOB_BYTE,
    PFB_GAS_FIXED_COST,
    SHARE_SIZE,
    SUBTREE_ROOT_THRESHOLD,
)
from celestia_app_tpu.crypto.keys import validate_address
from celestia_app_tpu.inclusion import create_commitment
from celestia_app_tpu.shares.namespace import Namespace
from celestia_app_tpu.shares.share import SUPPORTED_SHARE_VERSIONS
from celestia_app_tpu.shares.sparse import Blob, sparse_shares_needed
from celestia_app_tpu.tx.envelopes import BlobTx
from celestia_app_tpu.tx.messages import MsgPayForBlobs
from celestia_app_tpu.tx.sign import Tx


class BlobTxError(ValueError):
    """A BlobTx failed stateless validation."""


def new_msg_pay_for_blobs(
    signer: str,
    blobs: list[Blob],
    subtree_root_threshold: int = SUBTREE_ROOT_THRESHOLD,
) -> MsgPayForBlobs:
    """Reference x/blob/types/payforblob.go:48 NewMsgPayForBlobs."""
    if not blobs:
        raise BlobTxError("at least one blob required")
    for b in blobs:
        b.namespace.validate_for_blob()
    msg = MsgPayForBlobs(
        signer=signer,
        namespaces=tuple(b.namespace.to_bytes() for b in blobs),
        blob_sizes=tuple(len(b.data) for b in blobs),
        share_commitments=tuple(
            create_commitment(b, subtree_root_threshold) for b in blobs
        ),
        share_versions=tuple(b.share_version for b in blobs),
    )
    validate_msg_pay_for_blobs(msg)
    return msg


def validate_msg_pay_for_blobs(msg: MsgPayForBlobs) -> None:
    """Stateless MsgPayForBlobs checks (payforblob.go ValidateBasic)."""
    n = len(msg.namespaces)
    if n == 0:
        raise BlobTxError("no namespaces in MsgPayForBlobs")
    if not (len(msg.blob_sizes) == len(msg.share_commitments) == len(msg.share_versions) == n):
        raise BlobTxError("MsgPayForBlobs field lengths differ")
    validate_address(msg.signer)
    for raw_ns in msg.namespaces:
        Namespace.from_bytes(raw_ns).validate_for_blob()
    for v in msg.share_versions:
        if v not in SUPPORTED_SHARE_VERSIONS:
            raise BlobTxError(f"unsupported share version {v}")
    for c in msg.share_commitments:
        if len(c) != 32:
            raise BlobTxError(f"share commitment must be 32 bytes, got {len(c)}")


def _structural_checks(btx: BlobTx) -> MsgPayForBlobs:
    """Everything in ValidateBlobTx except the commitment recompute."""
    try:
        tx = Tx.unmarshal(btx.tx)
        msgs = tx.msgs()
    except ValueError as e:
        raise BlobTxError(f"undecodable inner tx: {e}") from e
    pfbs = [m for m in msgs if isinstance(m, MsgPayForBlobs)]
    if len(pfbs) != 1 or len(msgs) != 1:
        raise BlobTxError("BlobTx inner tx must contain exactly one MsgPayForBlobs")
    msg = pfbs[0]
    validate_msg_pay_for_blobs(msg)
    if len(btx.blobs) != len(msg.namespaces):
        raise BlobTxError(
            f"blob count {len(btx.blobs)} != PFB namespace count {len(msg.namespaces)}"
        )
    for i, blob in enumerate(btx.blobs):
        if blob.namespace.to_bytes() != msg.namespaces[i]:
            raise BlobTxError(f"blob {i} namespace differs from PFB")
        if len(blob.data) != msg.blob_sizes[i]:
            raise BlobTxError(f"blob {i} size differs from PFB")
        if blob.share_version != msg.share_versions[i]:
            raise BlobTxError(f"blob {i} share version differs from PFB")
    return msg


def validate_blob_tx(
    btx: BlobTx, subtree_root_threshold: int = SUBTREE_ROOT_THRESHOLD
) -> MsgPayForBlobs:
    """Full stateless BlobTx validation (blob_tx.go:37-108).

    Decodes the inner tx, requires exactly one MsgPayForBlobs, and checks
    every blob against the message: namespace match, size match, share
    version match, and commitment equality (the expensive recompute).
    Returns the validated message.
    """
    from celestia_app_tpu.inclusion.batched import create_commitments_batched

    msg = _structural_checks(btx)
    # Through the batched path for its content memo: the same blob is
    # re-validated at Prepare/Process after CheckTx admission, and the
    # memo collapses those recomputes to one device pass.
    commitments = create_commitments_batched(
        list(btx.blobs), subtree_root_threshold
    )
    for i, commitment in enumerate(commitments):
        if commitment != msg.share_commitments[i]:
            raise BlobTxError(f"blob {i} share commitment mismatch")
    return msg


def validate_blob_txs_batched(
    btxs: list[BlobTx], subtree_root_threshold: int = SUBTREE_ROOT_THRESHOLD
) -> list[MsgPayForBlobs | BlobTxError]:
    """ValidateBlobTx over many txs with ALL commitment hashing batched on
    the device (hot loop (3) of ProcessProposal, SURVEY §3.3).

    Returns, per tx, the validated MsgPayForBlobs or the BlobTxError that
    rejected it — callers drop (Prepare) or reject (Process) as they
    choose.  Equivalent to [validate_blob_tx(b) for b in btxs].
    """
    from celestia_app_tpu.inclusion.batched import create_commitments_batched

    results: list[MsgPayForBlobs | BlobTxError] = []
    todo: list[tuple[int, MsgPayForBlobs]] = []
    all_blobs = []
    for btx in btxs:
        try:
            msg = _structural_checks(btx)
        except BlobTxError as e:
            results.append(e)
            continue
        todo.append((len(results), msg))
        results.append(msg)
        all_blobs.extend(btx.blobs)

    commitments = create_commitments_batched(all_blobs, subtree_root_threshold)
    pos = 0
    for idx, msg in todo:
        n = len(msg.share_commitments)
        got = commitments[pos : pos + n]
        pos += n
        for i, c in enumerate(got):
            if c != msg.share_commitments[i]:
                results[idx] = BlobTxError(f"blob {i} share commitment mismatch")
                break
    return results


def gas_to_consume(blob_sizes: tuple[int, ...], gas_per_blob_byte: int) -> int:
    """payforblob.go:158 GasToConsume: shares x 512 x gasPerBlobByte."""
    total_shares = sum(sparse_shares_needed(s) for s in blob_sizes)
    return total_shares * SHARE_SIZE * gas_per_blob_byte


def estimate_gas(
    blob_sizes: list[int],
    gas_per_blob_byte: int = DEFAULT_GAS_PER_BLOB_BYTE,
    fixed_cost: int = PFB_GAS_FIXED_COST,
) -> int:
    """payforblob.go:171 linear PFB gas model (fit R^2 ~ 0.996):
    blob gas + txSizeCost x BytesPerBlobInfo per blob + fixed cost."""
    from celestia_app_tpu.app.gas import TX_SIZE_COST_PER_BYTE
    from celestia_app_tpu.constants import BYTES_PER_BLOB_INFO

    return (
        gas_to_consume(tuple(blob_sizes), gas_per_blob_byte)
        + TX_SIZE_COST_PER_BYTE * BYTES_PER_BLOB_INFO * len(blob_sizes)
        + fixed_cost
    )

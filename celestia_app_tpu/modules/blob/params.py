"""x/blob on-chain params (keeper/params.go analog).

GasPerBlobByte and GovMaxSquareSize are governance-modifiable module params
in the reference (x/blob/types/params.go, read at app/square_size.go:20-22
and x/blob/keeper/keeper.go:43); storing them in app state means a gov
change lands in the app hash like any other write.
"""

from __future__ import annotations

from celestia_app_tpu.constants import (
    DEFAULT_GAS_PER_BLOB_BYTE,
    DEFAULT_GOV_MAX_SQUARE_SIZE,
)
from celestia_app_tpu.state.store import KVStore

_GAS_PER_BLOB_BYTE = b"blob/params/gas_per_blob_byte"
_GOV_MAX_SQUARE_SIZE = b"blob/params/gov_max_square_size"


class BlobParamsKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    def _get(self, key: bytes, default: int) -> int:
        raw = self.store.get(key)
        return int.from_bytes(raw, "big") if raw else default

    def gas_per_blob_byte(self) -> int:
        return self._get(_GAS_PER_BLOB_BYTE, DEFAULT_GAS_PER_BLOB_BYTE)

    def set_gas_per_blob_byte(self, v: int) -> None:
        if v <= 0:
            raise ValueError("GasPerBlobByte must be positive")
        self.store.set(_GAS_PER_BLOB_BYTE, int(v).to_bytes(8, "big"))

    def gov_max_square_size(self) -> int:
        return self._get(_GOV_MAX_SQUARE_SIZE, DEFAULT_GOV_MAX_SQUARE_SIZE)

    def set_gov_max_square_size(self, v: int) -> None:
        if v < 1 or v & (v - 1):
            raise ValueError("GovMaxSquareSize must be a power of two")
        self.store.set(_GOV_MAX_SQUARE_SIZE, int(v).to_bytes(8, "big"))

from celestia_app_tpu.modules.distribution.keeper import (
    DISTRIBUTION_MODULE,
    DistributionError,
    DistributionKeeper,
)

__all__ = ["DISTRIBUTION_MODULE", "DistributionError", "DistributionKeeper"]

"""x/distribution: fee allocation, delegator rewards, commission, community pool.

The reference runs cosmos-sdk x/distribution (wired at app/modules.go:137-139)
with celestia-tuned genesis: BaseProposerReward and BonusProposerReward are
both zero (app/default_overrides.go:129-135), so every block's fee-collector
balance splits exactly two ways — the community tax (sdk default 2%) into the
community pool and the rest across bonded validators proportional to power.
txsim's stake sequence depends on this module: it continuously claims rewards
via MsgWithdrawDelegatorReward (test/txsim/stake.go:95-104).

Accounting design (an F1 simplification that fits this store):

  * per validator, a cumulative-rewards-per-token Dec accumulator
    (`cum`); allocating `r` tokens of reward to a validator with `t`
    staked tokens advances cum by r/t;
  * per (validator, delegator), a snapshot of cum at the last settle and
    an accrued-but-unwithdrawn Dec balance; settle() realizes
    stake x (cum - snap) into accrued and re-snapshots. Any change to a
    delegation's stake MUST settle first (the app's staking msg handlers
    do), mirroring the sdk's before-shares-modified hook;
  * genesis validators' notional self-bond (power declared without an
    escrowed delegation, state/staking.py) is treated as an implicit
    delegation from the operator address, so their reward share accrues
    to the operator instead of leaking;
  * all reward tokens live in the `distribution` module account from the
    moment of allocation; withdraws pay the truncated integer amount and
    keep the Dec remainder accrued (sdk truncation semantics).
"""

from __future__ import annotations

from celestia_app_tpu.state.dec import Dec
from celestia_app_tpu.state.store import KVStore

DISTRIBUTION_MODULE = "distribution"

_CUM_PREFIX = b"dist/cum/"
_SNAP_PREFIX = b"dist/snap/"
_ACCR_PREFIX = b"dist/accr/"
_NOTIONAL_PREFIX = b"dist/notional/"
_COMM_RATE_PREFIX = b"dist/commrate/"
_COMM_PREFIX = b"dist/comm/"
_COMMUNITY_KEY = b"dist/community"
_WITHDRAW_ADDR_PREFIX = b"dist/withdrawaddr/"
_PARAMS_KEY = b"dist/params"

# sdk defaults (x/distribution DefaultParams); proposer rewards are zeroed
# by celestia's genesis override so they do not appear here at all.
DEFAULT_COMMUNITY_TAX = "0.020000000000000000"


class DistributionError(ValueError):
    pass


class DistributionKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    # --- Dec-valued cells ---------------------------------------------------
    def _get_dec(self, key: bytes) -> Dec:
        raw = self.store.get(key)
        return Dec(int(raw.decode())) if raw else Dec(0)

    def _set_dec(self, key: bytes, d: Dec) -> None:
        self.store.set(key, str(d.raw).encode())

    # --- params -------------------------------------------------------------
    def community_tax(self) -> Dec:
        raw = self.store.get(_PARAMS_KEY)
        return Dec(int(raw.decode())) if raw else Dec.from_str(DEFAULT_COMMUNITY_TAX)

    def set_community_tax(self, tax: Dec) -> None:
        self.store.set(_PARAMS_KEY, str(tax.raw).encode())

    # --- commission ---------------------------------------------------------
    def commission_rate(self, validator: str) -> Dec:
        return self._get_dec(_COMM_RATE_PREFIX + validator.encode())

    def set_commission_rate(self, validator: str, rate: Dec) -> None:
        if rate < Dec(0) or Dec.from_int(1) < rate:
            raise DistributionError(f"commission rate {rate} outside [0, 1]")
        self._set_dec(_COMM_RATE_PREFIX + validator.encode(), rate)

    def accrued_commission(self, validator: str) -> Dec:
        return self._get_dec(_COMM_PREFIX + validator.encode())

    # Commission bounds declared at creation (sdk CommissionRates): the
    # operator's own promise to delegators, enforced on every edit.
    def set_commission_bounds(
        self, validator: str, max_rate: Dec, max_change_rate: Dec
    ) -> None:
        self.store.set(
            _COMM_RATE_PREFIX + validator.encode() + b"/bounds",
            f"{max_rate.raw}|{max_change_rate.raw}".encode(),
        )

    def commission_bounds(self, validator: str) -> tuple[Dec, Dec]:
        """(max_rate, max_change_rate); unlimited for validators that
        never declared bounds (genesis validators)."""
        raw = self.store.get(_COMM_RATE_PREFIX + validator.encode() + b"/bounds")
        if raw is None:
            return Dec.from_int(1), Dec.from_int(1)
        a, b = raw.decode().split("|")
        return Dec(int(a)), Dec(int(b))

    def change_commission_rate(self, validator: str, new_rate: Dec) -> None:
        """MsgEditValidator's rate change, against the declared bounds
        (sdk ErrCommissionGTMaxRate / max-change-rate checks)."""
        max_rate, max_change = self.commission_bounds(validator)
        if max_rate < new_rate:
            raise DistributionError(
                f"commission rate {new_rate} exceeds declared max {max_rate}"
            )
        old = self.commission_rate(validator)
        delta = Dec(abs(new_rate.raw - old.raw))
        if max_change < delta:
            raise DistributionError(
                f"commission change {delta} exceeds max change rate {max_change}"
            )
        self.set_commission_rate(validator, new_rate)

    # --- community pool -----------------------------------------------------
    def community_pool(self) -> Dec:
        return self._get_dec(_COMMUNITY_KEY)

    def fund_community_pool(self, bank, depositor: str, amount: int) -> None:
        """MsgFundCommunityPool: real tokens move into the module account."""
        if amount <= 0:
            raise DistributionError("community pool deposit must be positive")
        bank.send(depositor, DISTRIBUTION_MODULE, amount)
        self._set_dec(_COMMUNITY_KEY, self.community_pool().add(Dec.from_int(amount)))

    # --- notional self-bond (genesis validators) ----------------------------
    def notional(self, validator: str) -> int:
        raw = self.store.get(_NOTIONAL_PREFIX + validator.encode())
        return int(raw.decode()) if raw else 0

    def set_notional(self, validator: str, tokens: int) -> None:
        self.store.set(_NOTIONAL_PREFIX + validator.encode(), str(tokens).encode())

    def _stake(self, staking, delegator: str, validator: str) -> int:
        """Effective reward-bearing stake, incl. the operator's implicit bond."""
        stake = staking.delegation(delegator, validator)
        if delegator == validator:
            stake += self.notional(validator)
        return stake

    # --- allocation (BeginBlocker) ------------------------------------------
    def allocate(self, bank, staking) -> int:
        """Sweep the fee collector into rewards: community tax first, the
        rest across validators by power (proposer bonus is zero on celestia,
        default_overrides.go:129-135).  Returns the amount swept."""
        from celestia_app_tpu.state.accounts import FEE_COLLECTOR

        fees = bank.balance(FEE_COLLECTOR)
        if fees == 0:
            return 0
        bank.send(FEE_COLLECTOR, DISTRIBUTION_MODULE, fees)

        fees_dec = Dec.from_int(fees)
        community = fees_dec.mul(self.community_tax())
        pool = fees_dec.sub(community)

        # Jailed validators earn nothing while out of the active set.
        validators = [
            v for v in staking.bonded_validators() if staking.tokens(v.address)
        ]
        total_tokens = sum(staking.tokens(v.address) for v in validators)
        if total_tokens == 0:
            # No bonded power: everything is community funds (sdk edge case).
            self._set_dec(_COMMUNITY_KEY, self.community_pool().add(fees_dec))
            return fees

        distributed = Dec(0)
        for v in validators:
            tokens = staking.tokens(v.address)
            reward = pool.mul(Dec.from_fraction(tokens, total_tokens))
            commission = reward.mul(self.commission_rate(v.address))
            shared = reward.sub(commission)
            if commission.raw:
                key = _COMM_PREFIX + v.address.encode()
                self._set_dec(key, self._get_dec(key).add(commission))
            cum_key = _CUM_PREFIX + v.address.encode()
            self._set_dec(
                cum_key,
                self._get_dec(cum_key).add(shared.quo(Dec.from_int(tokens))),
            )
            distributed = distributed.add(reward)
        # Allocation dust (rounding) joins the community pool, as in the sdk.
        self._set_dec(
            _COMMUNITY_KEY,
            self.community_pool().add(community).add(pool.sub(distributed)),
        )
        return fees

    # --- settle / withdraw --------------------------------------------------
    def settle(self, staking, delegator: str, validator: str) -> None:
        """Realize pending rewards into the accrued balance and re-snapshot.
        MUST run before any stake change for (delegator, validator) — the
        sdk's BeforeDelegationSharesModified hook."""
        cum = self._get_dec(_CUM_PREFIX + validator.encode())
        snap_key = _SNAP_PREFIX + validator.encode() + b"/" + delegator.encode()
        snap = self._get_dec(snap_key)
        stake = self._stake(staking, delegator, validator)
        if stake and cum.raw != snap.raw:
            accr_key = _ACCR_PREFIX + validator.encode() + b"/" + delegator.encode()
            pending = cum.sub(snap).mul(Dec.from_int(stake))
            self._set_dec(accr_key, self._get_dec(accr_key).add(pending))
        self._set_dec(snap_key, cum)

    def pending_rewards(self, staking, delegator: str, validator: str) -> int:
        """Query surface: what a withdraw would pay right now (truncated)."""
        cum = self._get_dec(_CUM_PREFIX + validator.encode())
        snap = self._get_dec(
            _SNAP_PREFIX + validator.encode() + b"/" + delegator.encode()
        )
        accr = self._get_dec(
            _ACCR_PREFIX + validator.encode() + b"/" + delegator.encode()
        )
        stake = self._stake(staking, delegator, validator)
        return accr.add(cum.sub(snap).mul(Dec.from_int(stake))).truncate_int()

    def withdraw_address(self, delegator: str) -> str:
        raw = self.store.get(_WITHDRAW_ADDR_PREFIX + delegator.encode())
        return raw.decode() if raw else delegator

    def set_withdraw_address(self, delegator: str, addr: str) -> None:
        self.store.set(_WITHDRAW_ADDR_PREFIX + delegator.encode(), addr.encode())

    def withdraw_rewards(self, bank, staking, delegator: str, validator: str) -> int:
        """MsgWithdrawDelegatorReward: pay the truncated integer, keep the
        Dec remainder accrued."""
        self.settle(staking, delegator, validator)
        accr_key = _ACCR_PREFIX + validator.encode() + b"/" + delegator.encode()
        accrued = self._get_dec(accr_key)
        amount = accrued.truncate_int()
        if amount < 0:
            raise DistributionError("negative accrued rewards (corrupt state)")
        if amount:
            bank.send(DISTRIBUTION_MODULE, self.withdraw_address(delegator), amount)
        self._set_dec(accr_key, accrued.sub(Dec.from_int(amount)))
        return amount

    def withdraw_commission(self, bank, validator: str) -> int:
        """MsgWithdrawValidatorCommission (operator-signed)."""
        key = _COMM_PREFIX + validator.encode()
        accrued = self._get_dec(key)
        amount = accrued.truncate_int()
        if amount == 0:
            raise DistributionError("no commission to withdraw")
        bank.send(DISTRIBUTION_MODULE, self.withdraw_address(validator), amount)
        self._set_dec(key, accrued.sub(Dec.from_int(amount)))
        return amount

    def community_pool_spend(self, bank, recipient: str, amount: int) -> None:
        """Gov-directed community pool spend (distrclient.ProposalHandler is
        registered in the reference's gov router, default_overrides.go:207)."""
        pool = self.community_pool()
        if Dec.from_int(amount).raw > pool.raw or amount <= 0:
            raise DistributionError(
                f"community pool has {pool}, cannot spend {amount}"
            )
        bank.send(DISTRIBUTION_MODULE, recipient, amount)
        self._set_dec(_COMMUNITY_KEY, pool.sub(Dec.from_int(amount)))

    # --- slashing support ---------------------------------------------------
    def settle_all(self, staking, validator: str) -> list[str]:
        """Settle every delegator of `validator` (incl. the operator's
        implicit bond).  Called before a slash changes the token/stake ratio
        so no delegator's pending rewards are computed against post-slash
        stake.  Returns the settled delegator addresses."""
        from celestia_app_tpu.state.staking import _DEL_PREFIX  # noqa: PLC2701

        delegators = {validator} if self.notional(validator) else set()
        prefix = _DEL_PREFIX + validator.encode() + b"/"
        for key, _ in staking.store.iterate(prefix):
            delegators.add(key[len(prefix):].decode())
        for d in sorted(delegators):
            self.settle(staking, d, validator)
        return sorted(delegators)

"""The Tendermint round state machine: round changes, nil votes, locking.

Parity target: celestia-core's consensus (Tendermint v0.34 — SURVEY §1 L1),
whose defining property the single-round plane lacked (VERDICT r2 missing
#2): a crashed or faulty proposer must not halt the chain.  The algorithm
follows the Tendermint consensus paper (arXiv:1807.04938, Algorithm 1) —
the same pseudocode celestia-core implements:

  * proposer rotation per (height, round);
  * propose / prevote / precommit steps with per-step timeouts that grow
    with the round number;
  * nil prevotes when no acceptable proposal arrives in time;
  * polka locking: +2/3 prevotes for a block in round r lock this
    validator on that block (it refuses to prevote anything else in later
    rounds unless a NEWER polka justifies unlocking — the safety rule);
  * a commit happens in whichever round first gathers +2/3 precommits for
    a block; all later rounds for that height stop.

Design: the machine is PURE — no sockets, no threads, no clocks.  Inputs
are events (`start`, `on_proposal`, `on_vote`, `on_timeout`); the output
of every input is a list of Effects (votes/proposals to broadcast,
timeouts to schedule, a proposal request, evidence, a decision).  The
serving plane (rpc/server.py) owns IO: it feeds gossip into the machine
and executes the effects.  This splits consensus correctness
(deterministically testable, tests/test_round_machine.py) from transport.

Vote verification happens inside the machine via the validator map
(address -> (PublicKey, power)); equivocations surface as EvidenceFound
effects for the slashing pipeline (modules/slashing).
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.consensus.votes import (
    NIL,
    PRECOMMIT,
    PREVOTE,
    ConsensusError,
    Equivocation,
    Vote,
)

# Round observability (one round_journal trace row per (height, round)).
# Defined under trace/ so slim images load it without the signing stack;
# re-exported here because it is part of the machine's construction API.
from celestia_app_tpu.trace.round_journal import RoundJournal

# Steps within a round.
PROPOSE, PREVOTE_STEP, PRECOMMIT_STEP = "propose", "prevote", "precommit"

# Default timeouts (seconds) and their per-round growth — celestia-core's
# config shape (TimeoutPropose + TimeoutProposeDelta etc.); devnets scale
# them down via RoundMachine(timeouts=...).
DEFAULT_TIMEOUTS = {
    PROPOSE: (3.0, 0.5),
    PREVOTE_STEP: (1.0, 0.5),
    PRECOMMIT_STEP: (1.0, 0.5),
}


@dataclass(frozen=True)
class Proposal:
    """A signed proposal for (height, round).

    `block_hash` is the block id votes target; `pol_round` (proof-of-lock
    round) is the round of the polka that justifies re-proposing a value
    from an earlier round, or -1 for a fresh proposal.  The block payload
    itself (BlockData) travels alongside in gossip, keyed by block_hash —
    the machine only reasons about ids.
    """

    height: int
    round: int
    block_hash: bytes
    pol_round: int
    proposer: str
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        from celestia_app_tpu.encoding.proto import (
            encode_bytes_field,
            encode_varint_field,
        )

        return (
            encode_bytes_field(1, b"celestia-tpu/proposal")
            + encode_bytes_field(2, chain_id.encode())
            + encode_varint_field(3, self.height)
            + encode_varint_field(4, self.round)
            + encode_bytes_field(5, self.block_hash)
            + encode_varint_field(6, self.pol_round + 1)  # -1 -> 0
            + encode_bytes_field(7, self.proposer.encode())
        )


# --------------------------------------------------------------------------
# Effects: what the driver must do after feeding an event.
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class BroadcastVote:
    """Gossip this vote to the peers (the machine already counted it)."""

    vote: Vote


@dataclass(frozen=True)
class BroadcastProposal:
    """Gossip this (own) proposal + its block payload to the peers."""

    proposal: Proposal


@dataclass(frozen=True)
class ScheduleTimeout:
    """Arrange on_timeout(round, step) to fire after `delay` seconds
    unless the height moves on first."""

    round: int
    step: str
    delay: float


@dataclass(frozen=True)
class RequestProposal:
    """This node proposes for (round): build a block (or reuse
    `block_hash` if not NIL — the valid value from an earlier polka) and
    feed it back via on_own_proposal."""

    round: int
    block_hash: bytes  # NIL => build a fresh block
    pol_round: int


@dataclass(frozen=True)
class Decided:
    """+2/3 precommits for `block_hash` in `round`: commit it."""

    round: int
    block_hash: bytes
    precommits: tuple[Vote, ...]


@dataclass(frozen=True)
class EvidenceFound:
    equivocation: Equivocation


@dataclass(frozen=True)
class Locked:
    """This validator just locked on a value (drivers journal it to the
    WAL so a restart resumes with the lock — cross-round safety)."""

    round: int
    block_hash: bytes


class RoundTally:
    """All votes of one type for one (height, round): per-block-id power
    tally including nil, with equivocation capture.

    Unlike VoteSet (single target, used for commit verification), the
    tally accepts any target — Tendermint counts a validator once per
    (round, type); a second, conflicting vote is evidence and does not
    change the count (first vote wins, as in celestia-core's VoteSet).
    """

    def __init__(self, chain_id, height, round, vote_type, validators):
        self.chain_id = chain_id
        self.height = height
        self.round = round
        self.vote_type = vote_type
        self.validators = validators
        self.votes: dict[str, Vote] = {}  # validator -> first vote
        self.evidence: list[Equivocation] = []

    def add(self, vote: Vote) -> bool:
        """Count a verified vote; returns True if it was new.  Raises
        ConsensusError for votes that cannot be counted (unknown
        validator, bad signature, wrong coordinates)."""
        if (
            vote.height != self.height
            or vote.round != self.round
            or vote.vote_type != self.vote_type
        ):
            raise ConsensusError(
                f"vote for h{vote.height}/r{vote.round}/t{vote.vote_type} fed "
                f"to tally h{self.height}/r{self.round}/t{self.vote_type}"
            )
        entry = self.validators.get(vote.validator)
        if entry is None:
            raise ConsensusError(f"vote from non-validator {vote.validator}")
        if not vote.verify(entry[0], self.chain_id):
            raise ConsensusError(f"bad vote signature from {vote.validator}")
        prior = self.votes.get(vote.validator)
        if prior is not None:
            if prior.block_hash != vote.block_hash:
                self.evidence.append(Equivocation(prior, vote))
            return False
        self.votes[vote.validator] = vote
        return True

    def _power(self, pred) -> int:
        return sum(
            self.validators[v][1] for v, vote in self.votes.items() if pred(vote)
        )

    def total_power(self) -> int:
        return sum(p for _, p in self.validators.values())

    def power_for(self, block_hash: bytes) -> int:
        return self._power(lambda v: v.block_hash == block_hash)

    def power_any(self) -> int:
        return self._power(lambda v: True)

    def has_two_thirds_for(self, block_hash: bytes) -> bool:
        return 3 * self.power_for(block_hash) > 2 * self.total_power()

    def has_two_thirds_any(self) -> bool:
        """+2/3 voted in this round, not necessarily for one value."""
        return 3 * self.power_any() > 2 * self.total_power()

    def has_one_third_any(self) -> bool:
        """>1/3 voted in this round (at least one honest validator there)."""
        return 3 * self.power_any() > self.total_power()

    def two_thirds_value(self) -> bytes | None:
        """The block id (or NIL) holding +2/3, if any."""
        for bh in {v.block_hash for v in self.votes.values()}:
            if self.has_two_thirds_for(bh):
                return bh
        return None

    def votes_for(self, block_hash: bytes) -> tuple[Vote, ...]:
        return tuple(
            v for v in self.votes.values() if v.block_hash == block_hash
        )


class RoundMachine:
    """One height's consensus instance for one validator.

    Drivers construct it at each new height, call `start()`, feed
    `on_proposal` / `on_vote` / `on_timeout` / `on_own_proposal`, execute
    the returned effects, and tear it down once a `Decided` effect is
    handled.  A node without a bonded validator key participates as an
    observer: it tallies votes and decides, but never signs (my_key=None).

    The driver's contract per event:
      * on_proposal: the driver MUST first call verify_proposal (wire
        checks) and validate the block payload (ProcessProposal), passing
        the verdict as `valid`;
      * on_vote: feed any gossiped vote; ConsensusError means drop it;
      * on_timeout: fire ScheduleTimeout effects after their delay, at
        most once each, only while the machine is still at that height.
    """

    def __init__(
        self,
        chain_id: str,
        height: int,
        validators: dict,  # address -> (PublicKey, power)
        proposer_order: list[str],  # rotation: proposer for round r = order[r % n]
        my_address: str | None = None,
        my_key=None,
        timeouts: dict | None = None,
        sign_guard=None,  # f(height, round, type, block_hash) -> bool (WAL)
        locked_value: bytes | None = None,
        locked_round: int = -1,
        journal: RoundJournal | None = None,
    ):
        self.chain_id = chain_id
        self.height = height
        self.validators = validators
        self.proposer_order = proposer_order
        self.my_address = my_address
        self.my_key = my_key
        self.timeouts = timeouts or DEFAULT_TIMEOUTS
        # The double-sign gate (consensus/wal.py): consulted before every
        # own signature; False => this validator already signed something
        # conflicting for these coordinates (possibly before a restart).
        self.sign_guard = sign_guard
        # Round observability (one round_journal row per (height, round));
        # None keeps the machine journal-free for pure-logic tests.
        self.journal = journal

        self.round = 0
        self.step = PROPOSE
        # Lock state may be restored from the WAL on restart: safety
        # requires honoring a pre-crash lock in later rounds.
        self.locked_value = locked_value
        self.locked_round = locked_round
        self.valid_value: bytes | None = None
        self.valid_round = -1
        self.decided: Decided | None = None

        # round -> VALID Proposal (driver validated the block payload);
        # rounds whose proposal failed validation are tracked separately
        # (their only effect: an immediate nil prevote at entry).
        self.proposals: dict[int, Proposal] = {}
        self._invalid_rounds: set[int] = set()
        self.prevotes: dict[int, RoundTally] = {}
        self.precommits: dict[int, RoundTally] = {}
        # fire-once keys for the paper's "for the first time" rules
        self._fired: set = set()

    # --- plumbing ----------------------------------------------------------
    def proposer(self, round: int) -> str:
        return self.proposer_order[round % len(self.proposer_order)]

    def _tally(self, table: dict, round: int, vote_type: int) -> RoundTally:
        t = table.get(round)
        if t is None:
            t = table[round] = RoundTally(
                self.chain_id, self.height, round, vote_type, self.validators
            )
        return t

    def _timeout(self, step: str, round: int) -> ScheduleTimeout:
        base, delta = self.timeouts[step]
        return ScheduleTimeout(round, step, base + delta * round)

    def _set_step(self, step: str) -> None:
        self.step = step
        if self.journal is not None:
            self.journal.record_step(self, step)

    def _vote(self, vote_type: int, block_hash: bytes, effects: list) -> None:
        """Sign, self-count, and broadcast a vote (no-op for observers;
        refused by the sign guard if these coordinates were already
        signed differently — the WAL's double-sign protection)."""
        if self.my_key is None or self.my_address not in self.validators:
            return
        if self.sign_guard is not None and not self.sign_guard(
            self.height, self.round, vote_type, block_hash
        ):
            return
        vote = Vote.sign(
            self.my_key, self.chain_id, self.height, vote_type, block_hash,
            validator=self.my_address, round=self.round,
        )
        table = self.prevotes if vote_type == PREVOTE else self.precommits
        self._tally(table, self.round, vote_type).add(vote)
        effects.append(BroadcastVote(vote))

    # --- the algorithm -----------------------------------------------------
    def start(self) -> list:
        """StartRound(0)."""
        return self._start_round(0)

    def _start_round(self, round: int) -> list:
        if self.journal is not None and round > self.round:
            # The previous round failed to decide; journal it on the way out.
            self.journal.close_round(self, "round_bump")
        self.round = round
        self.step = PROPOSE
        if self.journal is not None:
            self.journal.open_round(self)
        effects: list = []
        if self.my_address == self.proposer(round) and self.my_key is not None:
            effects.append(
                RequestProposal(
                    round,
                    self.valid_value if self.valid_value is not None else NIL,
                    self.valid_round,
                )
            )
        else:
            effects.append(self._timeout(PROPOSE, round))
        # Re-apply anything that arrived early for this round.
        effects += self._check_rules()
        return effects

    def on_own_proposal(self, block_hash: bytes) -> list:
        """The driver built (or fetched, for a valid_value re-proposal)
        the block answering RequestProposal.  Emits the gossip effect and
        processes the proposal locally (the driver built it => valid)."""
        assert self.my_key is not None
        unsigned = Proposal(
            self.height, self.round, block_hash, self.valid_round,
            self.my_address,
        )
        prop = Proposal(
            unsigned.height, unsigned.round, unsigned.block_hash,
            unsigned.pol_round, unsigned.proposer,
            self.my_key.sign(unsigned.sign_bytes(self.chain_id)),
        )
        return [BroadcastProposal(prop)] + self.on_proposal(prop, valid=True)

    def verify_proposal(self, prop: Proposal) -> bool:
        """Wire-level checks the driver runs before block validation:
        right height, from the round's proposer, signature valid."""
        if prop.height != self.height or prop.proposer != self.proposer(prop.round):
            return False
        entry = self.validators.get(prop.proposer)
        if entry is None:
            return False
        return entry[0].verify(prop.sign_bytes(self.chain_id), prop.signature)

    def on_proposal(self, prop: Proposal, valid: bool) -> list:
        """A proposal for (height, round), wire-verified by the driver,
        with the driver's block-validation verdict.  An invalid proposal
        still advances the step — with a nil prevote (the paper's
        `valid(v)` guard)."""
        if self.decided is not None:
            return []
        if valid:
            self.proposals.setdefault(prop.round, prop)
        else:
            self._invalid_rounds.add(prop.round)
        return self._check_rules()

    def on_vote(self, vote: Vote) -> list:
        """A gossiped vote.  Returns effects; raises ConsensusError for
        uncountable votes (driver drops them)."""
        if self.decided is not None:
            return []
        if vote.height != self.height:
            raise ConsensusError(
                f"vote for height {vote.height}, machine at {self.height}"
            )
        table = self.prevotes if vote.vote_type == PREVOTE else self.precommits
        tally = self._tally(table, vote.round, vote.vote_type)
        n_evidence = len(tally.evidence)
        fresh = tally.add(vote)
        effects: list = [
            EvidenceFound(ev) for ev in tally.evidence[n_evidence:]
        ]
        if not fresh:
            return effects
        # Round catch-up (paper line 55): >1/3 voting in a later round
        # means at least one honest validator moved on — follow.
        if vote.round > self.round and tally.has_one_third_any():
            effects += self._start_round(vote.round)
            return effects
        effects += self._check_rules()
        return effects

    def on_timeout(self, round: int, step: str) -> list:
        """A ScheduleTimeout fired (driver filters stale heights)."""
        if self.decided is not None:
            return []
        effects: list = []
        if step == PROPOSE and round == self.round and self.step == PROPOSE:
            # No acceptable proposal in time: prevote nil (paper line 57).
            self._journal_timeout(round, step)
            self._vote(PREVOTE, NIL, effects)
            self._set_step(PREVOTE_STEP)
            effects += self._check_rules()
        elif step == PREVOTE_STEP and round == self.round and self.step == PREVOTE_STEP:
            # Prevotes diverged (no polka in time): precommit nil (line 61).
            self._journal_timeout(round, step)
            self._vote(PRECOMMIT, NIL, effects)
            self._set_step(PRECOMMIT_STEP)
            effects += self._check_rules()
        elif step == PRECOMMIT_STEP and round == self.round:
            # The round failed to commit: move on (line 65).
            self._journal_timeout(round, step)
            effects += self._start_round(round + 1)
        return effects

    def _journal_timeout(self, round: int, step: str) -> None:
        if self.journal is not None:
            self.journal.record_timeout(self, round, step)

    # --- standing rules ----------------------------------------------------
    def _enter_prevote(self, effects: list) -> None:
        """The propose-step entry rules (paper lines 22 + 28), applied
        when a proposal for the current round is actionable."""
        r = self.round
        prop = self.proposals.get(r)
        if prop is None:
            if r in self._invalid_rounds:
                # Proposal arrived but its block failed validation.
                self._vote(PREVOTE, NIL, effects)
                self._set_step(PREVOTE_STEP)
            return
        if prop.pol_round == -1:
            acceptable = (
                self.locked_round == -1 or self.locked_value == prop.block_hash
            )
        elif 0 <= prop.pol_round < r:
            # A re-proposal acts only once its claimed polka is visible
            # (it may arrive after the proposal; _check_rules re-runs).
            polka = self._tally(self.prevotes, prop.pol_round, PREVOTE)
            if not polka.has_two_thirds_for(prop.block_hash):
                return
            acceptable = (
                self.locked_round <= prop.pol_round
                or self.locked_value == prop.block_hash
            )
        else:
            return  # malformed pol_round (>= own round): let the timeout run
        self._vote(PREVOTE, prop.block_hash if acceptable else NIL, effects)
        self._set_step(PREVOTE_STEP)

    def _check_rules(self) -> list:
        """The paper's standing 'upon' clauses.  Idempotent: fire-once
        rules are keyed in _fired; step transitions guard the rest."""
        effects: list = []
        if self.decided is not None:
            return effects
        r = self.round
        if self.step == PROPOSE:
            self._enter_prevote(effects)
        prevotes = self._tally(self.prevotes, r, PREVOTE)
        precommits_r = self._tally(self.precommits, r, PRECOMMIT)

        # Line 34: +2/3 prevotes (any mix) while at prevote step =>
        # schedule the prevote timeout once per round.
        key = ("prevote-any", r)
        if (
            self.step == PREVOTE_STEP
            and prevotes.has_two_thirds_any()
            and key not in self._fired
        ):
            self._fired.add(key)
            effects.append(self._timeout(PREVOTE_STEP, r))

        # Line 36: polka for a valid proposed block while step >= prevote
        # => lock it, precommit it, remember it as the valid value.
        prop = self.proposals.get(r)
        if prop is not None and self.step != PROPOSE:
            key = ("polka", r)
            if (
                key not in self._fired
                and prevotes.has_two_thirds_for(prop.block_hash)
            ):
                self._fired.add(key)
                if self.step == PREVOTE_STEP:
                    self.locked_value = prop.block_hash
                    self.locked_round = r
                    effects.append(Locked(r, prop.block_hash))
                    self._vote(PRECOMMIT, prop.block_hash, effects)
                    self._set_step(PRECOMMIT_STEP)
                self.valid_value = prop.block_hash
                self.valid_round = r

        # Line 44: polka for nil while at prevote step => precommit nil.
        if self.step == PREVOTE_STEP and prevotes.has_two_thirds_for(NIL):
            self._vote(PRECOMMIT, NIL, effects)
            self._set_step(PRECOMMIT_STEP)

        # Line 47: +2/3 precommits (any mix) => schedule precommit timeout.
        key = ("precommit-any", r)
        if precommits_r.has_two_thirds_any() and key not in self._fired:
            self._fired.add(key)
            effects.append(self._timeout(PRECOMMIT_STEP, r))

        # Line 49: +2/3 precommits for a block in ANY round => decide
        # (gated on holding the round's valid proposal => the driver has
        # the block payload; it arrives via on_proposal otherwise).
        for round_r, tally in self.precommits.items():
            value = tally.two_thirds_value()
            if value is None or value == NIL:
                continue
            prop_r = self.proposals.get(round_r)
            if prop_r is not None and prop_r.block_hash == value:
                self.decided = Decided(round_r, value, tally.votes_for(value))
                effects.append(self.decided)
                if self.journal is not None:
                    self.journal.close_round(self, "decided", round=round_r)
                break
        return effects

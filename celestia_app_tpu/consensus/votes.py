"""BFT votes: signed prevotes/precommits with +2/3 power aggregation.

The reference's consensus (celestia-core, Tendermint v0.34) gossips votes
over p2p; a block commits only with >2/3 of validator power precommitting
its block id, and the resulting Commit is what light clients verify.  This
module carries that vote layer: votes are (height, round, type, block id)
with per-vote secp256k1 signatures over domain-separated sign bytes; a
nil vote is block_hash == b"" (Tendermint's nil prevote/precommit).  The
multi-round state machine (round changes, polka locking, proposer
rotation) lives in consensus/machine.py; VoteSet here is the
single-target tally the commit-verification path uses.
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.crypto.keys import PrivateKey, PublicKey
from celestia_app_tpu.encoding.proto import (
    WIRE_LEN,
    WIRE_VARINT,
    decode_fields,
    encode_bytes_field,
    encode_varint_field,
)

PREVOTE = 1
PRECOMMIT = 2
_TYPE_NAMES = {PREVOTE: "prevote", PRECOMMIT: "precommit"}


class ConsensusError(RuntimeError):
    pass


def block_id(data_root: bytes, prev_app_hash: bytes, time_ns: int = 0) -> bytes:
    """What votes commit to: the block's data root, the app hash the
    proposer executed from (Tendermint's header chains the previous app
    hash the same way), and the block time.  Three consequences: diverged
    state shows up as a different block id BEFORE anyone commits; a Commit
    at height H+1 attests height H's app hash — the trust anchor state
    sync verifies a restored snapshot against; and the block time is
    +2/3-attested, so IBC timestamp timeouts verify against a committed
    consensus timestamp instead of anyone's local clock (Tendermint
    headers carry Time inside the signed header for the same reason)."""
    import hashlib

    return hashlib.sha256(
        b"celestia-tpu/block" + data_root + prev_app_hash
        + time_ns.to_bytes(12, "big")
    ).digest()


#: A nil vote's block hash (Tendermint's nil prevote/precommit).
NIL = b""


def vote_sign_bytes(
    chain_id: str, height: int, vote_type: int, block_hash: bytes,
    round: int = 0,
) -> bytes:
    """Canonical vote sign bytes (the CanonicalVote analog): chain-id
    domain separation so votes can never be replayed across chains; the
    round is signed so a round-r vote can never be replayed as round-r'
    (CanonicalVote carries Round the same way)."""
    return (
        encode_bytes_field(1, b"celestia-tpu/vote")
        + encode_bytes_field(2, chain_id.encode())
        + encode_varint_field(3, height)
        + encode_varint_field(4, vote_type)
        + encode_bytes_field(5, block_hash)
        + encode_varint_field(6, round)
    )


@dataclass(frozen=True)
class Vote:
    height: int
    vote_type: int  # PREVOTE | PRECOMMIT
    block_hash: bytes  # NIL (b"") for a nil vote
    validator: str  # operator address
    signature: bytes
    round: int = 0

    @property
    def is_nil(self) -> bool:
        return self.block_hash == NIL

    @classmethod
    def sign(
        cls, key: PrivateKey, chain_id: str, height: int, vote_type: int,
        block_hash: bytes, validator: str | None = None, round: int = 0,
    ) -> "Vote":
        """`validator` is the OPERATOR address this vote speaks for; it
        defaults to the key's own derived address (genesis validators),
        but a validator created via MsgCreateValidator has an operator
        address distinct from its consensus key's — such nodes pass it
        explicitly.  Verification is by the registered pubkey either way."""
        return cls(
            height, vote_type, block_hash,
            validator if validator is not None else key.public_key().address(),
            key.sign(vote_sign_bytes(chain_id, height, vote_type, block_hash, round)),
            round,
        )

    def verify(self, pubkey: PublicKey, chain_id: str) -> bool:
        return pubkey.verify(
            vote_sign_bytes(
                chain_id, self.height, self.vote_type, self.block_hash, self.round
            ),
            self.signature,
        )

    def marshal(self) -> bytes:
        return (
            encode_varint_field(1, self.height)
            + encode_varint_field(2, self.vote_type)
            + encode_bytes_field(3, self.block_hash)
            + encode_bytes_field(4, self.validator.encode())
            + encode_bytes_field(5, self.signature)
            + encode_varint_field(6, self.round)
        )

    @classmethod
    def unmarshal(cls, raw: bytes) -> "Vote":
        ints = {n: v for n, wt, v in decode_fields(raw) if wt == WIRE_VARINT}
        b = {n: v for n, wt, v in decode_fields(raw) if wt == WIRE_LEN}
        return cls(
            ints.get(1, 0), ints.get(2, 0), b.get(3, b""),
            b.get(4, b"").decode(), b.get(5, b""), ints.get(6, 0),
        )


class VoteSet:
    """One (height, type, block hash) aggregation with power accounting.

    `validators` maps operator address -> (PublicKey, power); add() verifies
    membership, target, and signature before counting the power."""

    def __init__(
        self,
        chain_id: str,
        height: int,
        vote_type: int,
        block_hash: bytes,
        validators: dict[str, tuple[PublicKey, int]],
        round: int = 0,
    ):
        self.chain_id = chain_id
        self.height = height
        self.round = round
        self.vote_type = vote_type
        self.block_hash = block_hash
        self.validators = validators
        self.votes: dict[str, Vote] = {}

    def add(self, vote: Vote) -> None:
        kind = _TYPE_NAMES.get(self.vote_type, "?")
        if (
            vote.height != self.height
            or vote.vote_type != self.vote_type
            or vote.round != self.round
        ):
            raise ConsensusError(
                f"{kind} for wrong height/round/type: "
                f"{vote.height}/{vote.round}/{vote.vote_type}"
            )
        if vote.block_hash != self.block_hash:
            raise ConsensusError(
                f"{kind} from {vote.validator} for a different block"
            )
        entry = self.validators.get(vote.validator)
        if entry is None:
            raise ConsensusError(f"{kind} from non-validator {vote.validator}")
        if vote.validator in self.votes:
            return  # idempotent
        pubkey, _power = entry
        if not vote.verify(pubkey, self.chain_id):
            raise ConsensusError(f"bad {kind} signature from {vote.validator}")
        self.votes[vote.validator] = vote

    def signed_power(self) -> int:
        return sum(self.validators[v][1] for v in self.votes)

    def total_power(self) -> int:
        return sum(p for _, p in self.validators.values())

    def has_two_thirds(self) -> bool:
        """Tendermint's strict rule: 3 x signed > 2 x total."""
        return 3 * self.signed_power() > 2 * self.total_power()


@dataclass(frozen=True)
class Commit:
    """The queryable proof a height committed: +2/3 precommits over
    block_id(data_root, prev_app_hash), all from the same round."""

    height: int
    block_hash: bytes  # = block_id(data_root, prev_app_hash)
    precommits: tuple[Vote, ...]
    data_root: bytes = b""
    prev_app_hash: bytes = b""
    round: int = 0
    time_ns: int = 0  # block time (see commit timestamps, machine.py)

    def to_json(self) -> dict:
        return {
            "height": self.height,
            "round": self.round,
            "block_hash": self.block_hash.hex(),
            "precommits": [v.marshal().hex() for v in self.precommits],
            "data_root": self.data_root.hex(),
            "prev_app_hash": self.prev_app_hash.hex(),
            "time_ns": self.time_ns,
        }

    @classmethod
    def from_json(cls, d: dict) -> "Commit":
        return cls(
            d["height"], bytes.fromhex(d["block_hash"]),
            tuple(Vote.unmarshal(bytes.fromhex(v)) for v in d["precommits"]),
            bytes.fromhex(d.get("data_root", "")),
            bytes.fromhex(d.get("prev_app_hash", "")),
            d.get("round", 0),
            d.get("time_ns", 0),
        )


@dataclass(frozen=True)
class Equivocation:
    """Double-sign evidence: one validator, two votes for the same height,
    ROUND, and vote type but different block ids — what Tendermint's
    evidence pool gossips as DuplicateVoteEvidence.  (Voting for different
    blocks in different rounds is the protocol working, not a fault.)
    Verification (signatures + pair validity) happens in the slashing
    keeper, which holds the validator set."""

    vote_a: Vote
    vote_b: Vote

    @property
    def validator(self) -> str:
        return self.vote_a.validator

    @property
    def height(self) -> int:
        return self.vote_a.height

    def key(self) -> tuple:
        """The dedup identity (one equivocation per coordinates is enough
        to tombstone) — the single definition every pool/used-set uses."""
        return (
            self.validator, self.height, self.vote_a.round,
            self.vote_a.vote_type,
        )


def find_equivocations(votes) -> list[Equivocation]:
    """Scan votes (any iterable) for conflicting pairs per
    (validator, height, round, vote type).  First conflicting pair per key
    wins — one equivocation is enough to tombstone."""
    seen: dict[tuple[str, int, int, int], Vote] = {}
    found: list[Equivocation] = []
    flagged: set[tuple[str, int, int, int]] = set()
    for v in votes:
        key = (v.validator, v.height, v.round, v.vote_type)
        prior = seen.get(key)
        if prior is None:
            seen[key] = v
        elif prior.block_hash != v.block_hash and key not in flagged:
            found.append(Equivocation(prior, v))
            flagged.add(key)
    return found


def verify_commit(
    validators: dict[str, tuple[PublicKey, int]],
    chain_id: str,
    commit: Commit,
) -> bool:
    """Light-client check: does this Commit carry >2/3 of the given
    validator set's power in valid precommit signatures, over a block id
    consistent with its claimed data root + previous app hash?

    The binding is unconditional: a commit whose (data_root,
    prev_app_hash, time_ns) parts don't hash to the signed block id is
    rejected — otherwise the unsigned part fields could be rewritten
    freely and a state-sync joiner shown a forged prev_app_hash (or an
    IBC light client a forged consensus timestamp)."""
    if commit.block_hash != block_id(
        commit.data_root, commit.prev_app_hash, commit.time_ns
    ):
        return False
    vs = VoteSet(
        chain_id, commit.height, PRECOMMIT, commit.block_hash, validators,
        round=commit.round,
    )
    for vote in commit.precommits:
        try:
            vs.add(vote)
        except ConsensusError:
            return False  # a forged/foreign vote poisons the commit
    return vs.has_two_thirds()

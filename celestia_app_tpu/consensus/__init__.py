from celestia_app_tpu.consensus.votes import (
    PRECOMMIT,
    PREVOTE,
    Commit,
    ConsensusError,
    Vote,
    VoteSet,
    block_id,
    verify_commit,
)

__all__ = [
    "Commit",
    "ConsensusError",
    "PRECOMMIT",
    "PREVOTE",
    "Vote",
    "VoteSet",
    "block_id",
    "verify_commit",
]

"""Consensus: Tendermint round machine, vote wire types, WAL.

Lazy exports (the rpc/__init__ pattern): the vote types pull in the
signing backend's optional `cryptography` dependency, but the WAL
(consensus/wal.py, double-sign protection) and the round journal are
crypto-free — a slim image's crash-restart and chaos drills must reach
`celestia_app_tpu.consensus.wal` without paying the signing import.
"""

__all__ = [
    "Commit",
    "ConsensusError",
    "PRECOMMIT",
    "PREVOTE",
    "Vote",
    "VoteSet",
    "block_id",
    "verify_commit",
]


def __getattr__(name: str):
    if name in __all__:
        from celestia_app_tpu.consensus import votes

        return getattr(votes, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

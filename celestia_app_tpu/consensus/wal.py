"""Consensus write-ahead log: double-sign protection across restarts.

celestia-core persists a WAL and replays it on boot so a restarted
validator never signs twice for the same (height, round, step) — the
fault x/slashing tombstones for (VERDICT r2 §2.2: "no WAL").  This is
the minimal safety core of that mechanism:

  * every OWN vote is journaled (fsync) BEFORE it is broadcast; signing
    a conflicting vote for coordinates already in the journal is refused
    — even after a crash+restart wiped the in-memory machine;
  * polka locks are journaled too, so a restarted validator resumes
    locked on what it locked on (the cross-round safety input) instead
    of prevoting fresh values.

The journal is line-JSON, append-only, pruned by rewriting once the
height moves far past (prune()).  It deliberately does NOT replay the
full message stream (celestia-core's WAL also recovers liveness state);
crash recovery here re-joins via catch-up, which this framework already
does — the WAL only has to prevent equivocation.
"""

from __future__ import annotations

import json
import os
import time


class VoteWAL:
    def __init__(self, path: str):
        self.path = path
        # (height, round, vote_type) -> block_hash hex
        self.votes: dict[tuple[int, int, int], str] = {}
        # height -> (locked_round, locked_value hex)
        self.locks: dict[int, tuple[int, str]] = {}
        # Cumulative append+fsync wall time: the round journal reads the
        # delta per round (consensus/machine.RoundJournal.fsync_ms_source).
        self.fsync_ms_total = 0.0
        # Torn-tail bookkeeping: bytes dropped by the replay salvage, and
        # whether an INJECTED torn tail (chaos wal.append seam) currently
        # sits past _offset on disk awaiting the next append's self-heal.
        self.salvaged_bytes = 0
        self._torn = False
        self._load()
        self._fh = open(path, "a", buffering=1)
        self._offset = self._fh.tell()  # end of the last complete record

    def _load(self) -> None:
        """Replay the journal, salvaging a torn tail.

        A crash mid-append leaves a partial final record (often without
        its newline).  Replay keeps every COMPLETE fsync'd record and
        truncates the torn bytes away — without the truncate, the append
        handle would write the next record onto the tail of the fragment
        and corrupt BOTH (the record a later restart then fails to
        replay is exactly the one double-sign protection needed).
        Mid-file garbage (a corrupted but newline-terminated line) is
        skipped, never truncated: records after it are still valid.

        The torn record itself is safely LOST, not violated: its vote was
        never broadcast (may_sign records durably BEFORE the caller
        signs), so forgetting it can at worst re-sign the same
        coordinates later — the idempotent case, never an equivocation.
        """
        if not os.path.exists(self.path):
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            return
        with open(self.path, "rb") as f:
            data = f.read()
        pos = 0
        good = 0  # offset just past the last complete (newline'd) line
        # Split strictly on b"\n" — the only terminator _append writes.
        # bytes.splitlines() also splits on bare \r, which would make
        # mid-file garbage CONTAINING a carriage return look like a torn
        # tail and truncate every later (valid, durably fsync'd) record:
        # exactly the double-sign window this journal exists to close.
        while pos < len(data):
            nl = data.find(b"\n", pos)
            if nl == -1:
                break  # torn tail: no terminator — everything past `good` goes
            line = data[pos:nl]
            pos = nl + 1
            stripped = line.strip()
            if not stripped:
                good = pos
                continue
            try:
                rec = json.loads(stripped)
                if rec.get("k") == "vote":
                    self.votes[(rec["h"], rec["r"], rec["t"])] = rec["b"]
                elif rec.get("k") == "lock":
                    self.locks[rec["h"]] = (rec["r"], rec["b"])
            except (json.JSONDecodeError, KeyError, TypeError,
                    AttributeError):
                # Mid-file garbage: skip the record, keep walking.  The
                # broad net matters — `123` or `null` parse fine and then
                # fail attribute/key access, and a replay that CRASHES on
                # corruption is the failure mode this path exists to
                # survive.
                continue
            good = pos
        if good < len(data):
            self.salvaged_bytes = len(data) - good
            os.truncate(self.path, good)
            self._note_salvage("replay", self.salvaged_bytes)

    @staticmethod
    def _note_salvage(where: str, dropped: int) -> None:
        from celestia_app_tpu.chaos.degrade import recoveries
        from celestia_app_tpu.trace.flight_recorder import note_trigger
        from celestia_app_tpu.trace.tracer import traced

        recoveries().inc(seam="wal.append", outcome="salvaged")
        traced().write("wal_salvage", where=where, dropped_bytes=dropped)
        # A salvage means a crash tore the double-sign guard's journal:
        # snapshot the surrounding state while it still exists
        # (note_trigger rate-limits per trigger and never raises).
        note_trigger("wal_salvage", where=where, dropped_bytes=dropped)

    def _append(self, rec: dict) -> None:
        from celestia_app_tpu import chaos

        if self._torn:
            # A prior injected torn tail sits past _offset: heal exactly
            # the way a restart would, by truncating to the last complete
            # record before writing anything new.
            self._fh.truncate(self._offset)
            self._torn = False
            self._note_salvage("append", 0)
        t0 = time.perf_counter()
        self._fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._offset = self._fh.tell()
        elapsed = time.perf_counter() - t0
        self.fsync_ms_total += elapsed * 1e3
        frag = chaos.wal_torn_tail()
        if frag is not None:
            # The chaos seam: durably tear the tail (a crash mid-write of
            # the NEXT record) so replay/self-heal have something real to
            # salvage.  _offset deliberately not advanced.
            self._fh.write(frag.decode())
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._torn = True
        # The fsync sits on the vote-signing path: its latency is a direct
        # input to round time, so it gets its own histogram.
        from celestia_app_tpu.trace.metrics import DEVICE_SECONDS_BUCKETS, registry

        registry().histogram(
            "celestia_wal_fsync_seconds",
            "consensus WAL append+fsync wall time",
            buckets=DEVICE_SECONDS_BUCKETS,
        ).observe(elapsed)

    # --- the sign guard -----------------------------------------------------
    def may_sign(self, height: int, round: int, vote_type: int,
                 block_hash: bytes) -> bool:
        """True iff signing this vote cannot be an equivocation.  Records
        the vote (durably) when allowed — record-then-sign ordering, so a
        crash between the two can at worst lose a vote, never double
        one."""
        key = (height, round, vote_type)
        prior = self.votes.get(key)
        if prior is not None:
            return prior == block_hash.hex()  # idempotent re-sign is fine
        self.votes[key] = block_hash.hex()
        self._append({
            "k": "vote", "h": height, "r": round, "t": vote_type,
            "b": block_hash.hex(),
        })
        return True

    # --- lock persistence ---------------------------------------------------
    def record_lock(self, height: int, round: int, value: bytes) -> None:
        self.locks[height] = (round, value.hex())
        self._append({"k": "lock", "h": height, "r": round, "b": value.hex()})

    def lock_for(self, height: int) -> tuple[int, bytes] | None:
        got = self.locks.get(height)
        if got is None:
            return None
        return got[0], bytes.fromhex(got[1])

    # --- maintenance --------------------------------------------------------
    def prune(self, below_height: int) -> bool:
        """Drop records for long-committed heights (rewrite in place).

        Best-effort: a failed rewrite (disk full, EIO) leaves the on-disk
        journal with its pre-prune content — superset of the in-memory
        state, so double-sign protection is intact — and returns False.
        The append handle is reopened in a finally either way: a failed
        prune must never crash may_sign()/record_lock() on a running
        validator, which is the vote-signing path.
        """
        self.votes = {k: v for k, v in self.votes.items() if k[0] >= below_height}
        self.locks = {h: v for h, v in self.locks.items() if h >= below_height}
        if self._torn:
            # Heal an injected torn tail before the handle swap: a failed
            # rewrite keeps the ORIGINAL file, whose offset bookkeeping
            # must stay truthful for the next append.
            self._fh.truncate(self._offset)
            self._torn = False
        self._fh.close()
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as f:
                for (h, r, t), b in sorted(self.votes.items()):
                    f.write(json.dumps(
                        {"k": "vote", "h": h, "r": r, "t": t, "b": b},
                        separators=(",", ":"),
                    ) + "\n")
                for h, (r, b) in sorted(self.locks.items()):
                    f.write(json.dumps(
                        {"k": "lock", "h": h, "r": r, "b": b},
                        separators=(",", ":"),
                    ) + "\n")
                # The retained records still guard against double-signing:
                # fsync BEFORE the rename (and the directory after), or a
                # crash can persist the rename with an empty file and lose
                # exactly the durability the journal exists for.
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            try:
                dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
                os.fsync(dfd)
                os.close(dfd)
            except OSError:
                pass  # directory fsync is best-effort on odd filesystems
        except OSError:
            return False
        finally:
            self._fh = open(self.path, "a", buffering=1)
        return True

    def close(self) -> None:
        try:
            self._fh.close()
        except OSError:
            pass

"""Client-side error parsing (reference app/errors).

ParseInsufficientMinGasPrice (app/errors/insufficient_gas_price.go:23):
recover the node's actual minimum gas price from the fee-rejection message
so the client can bump its gas price and retry exactly once per level.
"""

from __future__ import annotations

import re
from fractions import Fraction

_MIN_GAS_PRICE_RE = re.compile(r"insufficient fees; got: (\d+)utia required: (\d+)utia")
_SEQ_MISMATCH_RE = re.compile(
    r"account sequence mismatch, expected (\d+), got (\d+)"
)


def parse_insufficient_min_gas_price(log: str, gas_limit: int) -> Fraction | None:
    """The node's min gas price implied by a fee-rejection log, or None."""
    m = _MIN_GAS_PRICE_RE.search(log)
    if not m:
        return None
    required = int(m.group(2))
    if required == 0 or gas_limit == 0:
        return None
    return Fraction(required, gas_limit)


def parse_nonce_mismatch(log: str) -> tuple[int, int] | None:
    """(expected, got) sequence numbers from a nonce-mismatch log, or None
    (reference app/errors/nonce_mismatch.go)."""
    m = _SEQ_MISMATCH_RE.search(log)
    if not m:
        return None
    return int(m.group(1)), int(m.group(2))

"""Signer: builds and signs txs and BlobTxs for known accounts.

Parity with reference pkg/user/signer.go:23-36 + account.go: tracks
(account number, sequence) per local key, produces TxRaw bytes for message
txs and BlobTx envelopes for PFBs.
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.crypto import PrivateKey
from celestia_app_tpu.modules.blob.types import new_msg_pay_for_blobs
from celestia_app_tpu.shares.sparse import Blob
from celestia_app_tpu.tx.envelopes import BlobTx
from celestia_app_tpu.tx.messages import Coin
from celestia_app_tpu.tx.sign import Fee, build_and_sign


@dataclass
class SignerAccount:
    key: PrivateKey
    account_number: int
    sequence: int

    @property
    def address(self) -> str:
        return self.key.public_key().address()


class Signer:
    def __init__(self, chain_id: str):
        self.chain_id = chain_id
        self._accounts: dict[str, SignerAccount] = {}

    def add_account(self, key: PrivateKey, account_number: int, sequence: int = 0) -> str:
        acc = SignerAccount(key, account_number, sequence)
        self._accounts[acc.address] = acc
        return acc.address

    def account(self, address: str) -> SignerAccount:
        return self._accounts[address]

    def addresses(self) -> list[str]:
        return list(self._accounts)

    def create_tx(
        self, address: str, msgs: list, gas: int, fee_utia: int,
        fee_granter: str = "",
    ) -> bytes:
        acc = self._accounts[address]
        raw = build_and_sign(
            msgs,
            acc.key,
            self.chain_id,
            acc.account_number,
            acc.sequence,
            Fee((Coin("utia", fee_utia),), gas, granter=fee_granter),
        )
        return raw

    def create_pay_for_blobs(
        self, address: str, blobs: list[Blob], gas: int, fee_utia: int,
        fee_granter: str = "",
    ) -> bytes:
        """BlobTx bytes for a PFB (signer.CreatePayForBlobs)."""
        msg = new_msg_pay_for_blobs(address, blobs)
        raw_tx = self.create_tx(address, [msg], gas, fee_utia, fee_granter)
        return BlobTx(raw_tx, tuple(blobs)).marshal()

    def increment_sequence(self, address: str) -> None:
        self._accounts[address].sequence += 1

    def set_sequence(self, address: str, sequence: int) -> None:
        self._accounts[address].sequence = sequence

from celestia_app_tpu.user.signer import Signer, SignerAccount
from celestia_app_tpu.user.tx_client import (
    TxClient,
    TxResponse,
    TxSubmissionError,
)
from celestia_app_tpu.user.errors import (
    parse_insufficient_min_gas_price,
    parse_nonce_mismatch,
)

__all__ = [
    "Signer",
    "SignerAccount",
    "TxClient",
    "TxResponse",
    "TxSubmissionError",
    "parse_insufficient_min_gas_price",
    "parse_nonce_mismatch",
]

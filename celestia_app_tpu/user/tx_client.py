"""TxClient: the high-level thread-safe submission client.

Parity with reference pkg/user/tx_client.go:90-455: build/sign/broadcast
message txs and BlobTxs against a node, estimate gas, bump the gas price and
retry on parseable fee rejections, resync sequences on nonce mismatch, and
confirm inclusion.  The node here is anything with the TestNode surface
(broadcast / produce_block / app) — in production the same calls ride gRPC.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from fractions import Fraction

from celestia_app_tpu.crypto import PrivateKey
from celestia_app_tpu.modules.blob.types import estimate_gas
from celestia_app_tpu.shares.sparse import Blob
from celestia_app_tpu.user.errors import (
    parse_insufficient_min_gas_price,
    parse_nonce_mismatch,
)
from celestia_app_tpu.tx import tx_hash
from celestia_app_tpu.user.signer import Signer

DEFAULT_GAS_PRICE = Fraction(2, 1000)  # matches appconsts.DefaultMinGasPrice
DEFAULT_GAS_MULTIPLIER = Fraction(11, 10)
MAX_RETRIES = 5


class TxSubmissionError(RuntimeError):
    def __init__(self, code: int, log: str):
        super().__init__(f"tx rejected (code {code}): {log}")
        self.code = code
        self.log = log


@dataclass
class TxResponse:
    height: int
    code: int
    log: str = ""
    gas_wanted: int = 0
    tx_hash: bytes = b""


class TxClient:
    """Mutex-serialized client bound to one node and a set of local keys."""

    def __init__(
        self,
        node,
        keys: list[PrivateKey],
        gas_price: Fraction = DEFAULT_GAS_PRICE,
        gas_multiplier: Fraction = DEFAULT_GAS_MULTIPLIER,
        fee_granter: str = "",
    ):
        self._node = node
        self._lock = threading.Lock()
        self.gas_price = gas_price
        self.gas_multiplier = gas_multiplier
        # pkg/user SetFeeGranter: every tx's fee is charged to this
        # account's x/feegrant allowance instead of the signer.
        self.fee_granter = fee_granter
        self.signer = Signer(node.chain_id)
        for k in keys:
            addr = k.public_key().address()
            acc = node.query_account(addr)
            if acc is None:
                raise ValueError(f"account {addr} not found on chain")
            self.signer.add_account(k, acc.account_number, acc.sequence)
        self.default_address = self.signer.addresses()[0]

    # --- public API --------------------------------------------------------
    def submit_pay_for_blob(self, blobs: list[Blob], address: str | None = None) -> TxResponse:
        """Broadcast a PFB and wait for inclusion (SubmitPayForBlob :202)."""
        with self._lock:
            resp = self._broadcast_pfb(blobs, address or self.default_address)
        return self._confirm(resp)

    def simulate_gas(self, msgs: list, address: str | None = None) -> int | None:
        """Gas for `msgs` via the node's Simulate endpoint: simulated
        gas_used scaled by this client's gas_multiplier (the pkg/user
        estimation recipe).  Returns None only when the node doesn't
        expose simulation (in-process TestNode surfaces); a FAILED
        simulation raises with the node's log — silently falling back on
        a tx that would fail on-chain helps nobody.  The simulated tx
        carries a placeholder zero fee (simulate waives the limit) and
        does not bump the sequence."""
        sim = getattr(self._node, "simulate", None)
        if sim is None:
            return None
        with self._lock:
            addr = address or self.default_address
            raw = self.signer.create_tx(addr, msgs, 0, 0)
            _, used, log = sim(raw)
        if used == 0:
            raise ValueError(f"simulation failed: {log}")
        m = self.gas_multiplier
        return used * m.numerator // m.denominator

    def submit_tx(self, msgs: list, address: str | None = None, gas: int = 200_000) -> TxResponse:
        with self._lock:
            resp = self._broadcast_msgs(msgs, address or self.default_address, gas)
        return self._confirm(resp)

    def estimate_gas(self, blobs: list[Blob]) -> int:
        return estimate_gas([len(b.data) for b in blobs])

    # --- internals ---------------------------------------------------------
    def _fee_for(self, gas: int, price: Fraction) -> int:
        return -(-(gas * price.numerator) // price.denominator)  # ceil

    def _granter_for(self, address: str) -> str:
        # The master account pays its own fees directly.
        return self.fee_granter if self.fee_granter != address else ""

    def _broadcast_pfb(self, blobs, address: str) -> TxResponse:
        gas = self.estimate_gas(blobs)
        build = lambda price: self.signer.create_pay_for_blobs(
            address, blobs, gas, self._fee_for(gas, price),
            self._granter_for(address),
        )
        return self._broadcast_with_retry(build, address, gas)

    def _broadcast_msgs(self, msgs, address: str, gas: int) -> TxResponse:
        build = lambda price: self.signer.create_tx(
            address, msgs, gas, self._fee_for(gas, price),
            self._granter_for(address),
        )
        return self._broadcast_with_retry(build, address, gas)

    def _broadcast_with_retry(self, build, address: str, gas: int) -> TxResponse:
        """broadcastTx + retryBroadcastingTx (:320-410): on a parseable
        fee rejection adopt the implied price; on nonce mismatch resync."""
        price = self.gas_price
        last = None
        for _ in range(MAX_RETRIES):
            raw = build(price)
            res = self._node.broadcast(raw)
            if res.code == 0:
                self.signer.increment_sequence(address)
                return TxResponse(
                    height=0, code=0, gas_wanted=gas,
                    tx_hash=tx_hash(raw),
                )
            last = res
            implied = parse_insufficient_min_gas_price(res.log, gas)
            if implied is not None:
                price = max(implied, price * self.gas_multiplier)
                continue
            nonce = parse_nonce_mismatch(res.log)
            if nonce is not None:
                self.signer.set_sequence(address, nonce[0])
                continue
            break
        raise TxSubmissionError(last.code, last.log)

    def _confirm(self, resp: TxResponse, timeout_s: float = 30.0) -> TxResponse:
        """ConfirmTx (:412): wait for inclusion and report its height.

        Against an in-process node (TestNode surface) this drives a block
        directly; against a served node (no produce_block, e.g. the RPC
        client) it polls the tx index until the server's proposer loop
        commits the tx — the reference's poll-by-hash behavior.
        """
        if hasattr(self._node, "app"):  # in-process node: drive a block
            _, results = self._node.produce_block()
            for r in results:
                if r.code != 0:
                    raise TxSubmissionError(r.code, r.log)
            return TxResponse(
                height=self._node.app.height, code=0, gas_wanted=resp.gas_wanted
            )
        if hasattr(self._node, "wait_tx"):
            # Subscription path: one call that parks on the node's commit
            # event (the /subscribe analog) — no polling.
            status = self._node.wait_tx(resp.tx_hash, timeout_s)
            if status is None:
                raise TxSubmissionError(-1, "timed out waiting for tx inclusion")
            height, code, log = status
            if code != 0:
                raise TxSubmissionError(code, log)
            return TxResponse(height=height, code=0, gas_wanted=resp.gas_wanted)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            status = self._node.tx_status(resp.tx_hash)
            if status is not None:
                height, code, log = status
                if code != 0:
                    raise TxSubmissionError(code, log)
                return TxResponse(height=height, code=0, gas_wanted=resp.gas_wanted)
            time.sleep(0.05)
        raise TxSubmissionError(-1, "timed out waiting for tx inclusion")

"""Host-side Namespaced Merkle Tree (oracle + proof engine).

Push-ordered, power-of-two-friendly NMT retaining every level, so inclusion
proofs and cached subtree roots (the reference's EDSSubTreeRootCacher,
pkg/inclusion/nmt_caching.go:80-124) are plain array indexing here - the
device kernel returns the same levels in one buffer (SURVEY P7).
"""

from __future__ import annotations

from celestia_app_tpu.constants import NAMESPACE_SIZE
from celestia_app_tpu.nmt.hasher import NmtHasher


class NamespacedMerkleTree:
    """An NMT built by pushing namespaced leaves in namespace order."""

    def __init__(self) -> None:
        self._leaves: list[bytes] = []  # raw ndata = ns || data
        self._levels: list[list[bytes]] | None = None

    def push(self, ndata: bytes) -> None:
        """Push ns(29)-prefixed leaf data. Namespaces must be non-decreasing."""
        if self._levels is not None:
            raise RuntimeError("tree already finalized")
        ns = ndata[:NAMESPACE_SIZE]
        if self._leaves and ns < self._leaves[-1][:NAMESPACE_SIZE]:
            raise ValueError("leaves must be pushed in namespace order")
        self._leaves.append(bytes(ndata))

    def __len__(self) -> int:
        return len(self._leaves)

    def _build(self) -> list[list[bytes]]:
        """Levels bottom-up: levels[0] = leaf digests, levels[-1] = [root]."""
        if self._levels is not None:
            return self._levels
        level = [NmtHasher.hash_leaf(l) for l in self._leaves]
        levels = [level]
        while len(level) > 1:
            nxt = []
            for i in range(0, len(level) - 1, 2):
                nxt.append(NmtHasher.hash_node(level[i], level[i + 1]))
            if len(level) % 2:
                # odd node promotes (trees in the square are powers of two;
                # this branch only serves ad-hoc host uses)
                nxt.append(level[-1])
            levels.append(nxt)
            level = nxt
        self._levels = levels
        return levels

    def root(self) -> bytes:
        if not self._leaves:
            return NmtHasher.empty_root()
        return self._build()[-1][0]

    def levels(self) -> list[list[bytes]]:
        """All digest levels (leaf level first). Finalizes the tree."""
        return self._build()

    def leaf_digests(self) -> list[bytes]:
        return self._build()[0]

    def subtree_root(self, start: int, end: int) -> bytes:
        """Root of the complete subtree over leaves [start, end).

        The range must be aligned: end-start a power of two dividing start.
        This is the cached-inner-node lookup of the reference's
        EDSSubTreeRootCacher.walk (pkg/inclusion/nmt_caching.go:52).
        """
        size = end - start
        if size <= 0 or size & (size - 1) or start % size:
            raise ValueError(f"unaligned subtree range [{start},{end})")
        if end > len(self._leaves):
            raise ValueError(f"subtree range [{start},{end}) exceeds {len(self._leaves)} leaves")
        height = size.bit_length() - 1
        return self._build()[height][start // size]

"""NMT digest rules (host oracle).

Digest format: minNs(29) || maxNs(29) || sha256-digest(32) = 90 bytes.

    leaf:  ns || ns || sha256(0x00 || ns || data)
    node:  minNs || maxNs || sha256(0x01 || left(90) || right(90))

with the IgnoreMaxNamespace rule: if the right child's min namespace is the
maximum namespace (29 x 0xFF - parity shares), the parent's max namespace is
taken from the left child, so parity leaves never widen Q0 ranges.  Semantics
pinned against reference test/util/malicious/hasher.go:186-310 and
pkg/wrapper/nmt_wrapper.go:59-62 (sha256, 29-byte IDs, IgnoreMaxNamespace
= true).
"""

from __future__ import annotations

import hashlib

from celestia_app_tpu.constants import PARITY_NAMESPACE_BYTES, NAMESPACE_SIZE, NMT_NODE_SIZE

LEAF_PREFIX = b"\x00"
NODE_PREFIX = b"\x01"
MAX_NAMESPACE = PARITY_NAMESPACE_BYTES


class NmtHasher:
    """Stateless digest rules for 29-byte-namespace, sha256, ignore-max NMTs."""

    @staticmethod
    def hash_leaf(ndata: bytes) -> bytes:
        """ndata = ns(29) || raw data."""
        if len(ndata) < NAMESPACE_SIZE:
            raise ValueError("leaf shorter than a namespace")
        ns = ndata[:NAMESPACE_SIZE]
        return ns + ns + hashlib.sha256(LEAF_PREFIX + ndata).digest()

    @staticmethod
    def hash_node(left: bytes, right: bytes) -> bytes:
        if len(left) != NMT_NODE_SIZE or len(right) != NMT_NODE_SIZE:
            raise ValueError("NMT node children must be 90 bytes")
        l_min, l_max = left[:NAMESPACE_SIZE], left[NAMESPACE_SIZE : 2 * NAMESPACE_SIZE]
        r_min, r_max = right[:NAMESPACE_SIZE], right[NAMESPACE_SIZE : 2 * NAMESPACE_SIZE]
        if l_max > r_min:
            raise ValueError("sibling namespaces out of order")
        min_ns = l_min
        max_ns = l_max if r_min == MAX_NAMESPACE else r_max
        return min_ns + max_ns + hashlib.sha256(NODE_PREFIX + left + right).digest()

    @staticmethod
    def empty_root() -> bytes:
        zero = b"\x00" * NAMESPACE_SIZE
        return zero + zero + hashlib.sha256(b"").digest()

    @staticmethod
    def min_namespace(node: bytes) -> bytes:
        return node[:NAMESPACE_SIZE]

    @staticmethod
    def max_namespace(node: bytes) -> bytes:
        return node[NAMESPACE_SIZE : 2 * NAMESPACE_SIZE]

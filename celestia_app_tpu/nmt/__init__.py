"""Namespaced Merkle Trees.

Host-side reference implementation (hasher semantics matching the reference's
nmt dep, pinned by reference test/util/malicious/hasher.go:186-310) plus the
batched device kernel in kernels/nmt.py.  The host tree is the oracle and the
proof engine; the device kernel produces the same digests for 4k trees at
once.
"""

from celestia_app_tpu.nmt.hasher import NmtHasher, MAX_NAMESPACE
from celestia_app_tpu.nmt.tree import NamespacedMerkleTree

__all__ = ["NmtHasher", "NamespacedMerkleTree", "MAX_NAMESPACE"]

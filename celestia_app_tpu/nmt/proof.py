"""NMT range proofs (inclusion of a contiguous leaf range).

Parity with the nmt library's Prove/ProveRange + VerifyInclusion as used by
the reference proof path (pkg/wrapper/nmt_wrapper.go:127 ProveRange;
pkg/proof/proof.go:151-202): the proof carries the subtree roots adjacent to
the range in left-to-right DFS order; verification re-computes the root from
the claimed leaves plus those nodes, propagating namespace ranges with the
ignore-max rule.
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.merkle import split_point
from celestia_app_tpu.nmt.hasher import NmtHasher
from celestia_app_tpu.nmt.tree import NamespacedMerkleTree


@dataclass(frozen=True)
class NmtRangeProof:
    """Inclusion proof for leaves [start, end) of an NMT."""

    start: int
    end: int
    nodes: tuple[bytes, ...]  # 90-byte namespaced digests, DFS order
    total: int  # leaf count of the proven tree


def _subtree_digest(digests: list[bytes], lo: int, hi: int) -> bytes:
    if hi - lo == 1:
        return digests[lo]
    sp = split_point(hi - lo)
    return NmtHasher.hash_node(
        _subtree_digest(digests, lo, lo + sp), _subtree_digest(digests, lo + sp, hi)
    )


def prove_range(tree: NamespacedMerkleTree, start: int, end: int) -> NmtRangeProof:
    digests = tree.leaf_digests()
    n = len(digests)
    if not 0 <= start < end <= n:
        raise ValueError(f"invalid range [{start},{end}) of {n} leaves")
    nodes: list[bytes] = []

    def walk(lo: int, hi: int) -> None:
        if hi <= start or lo >= end:
            nodes.append(_subtree_digest(digests, lo, hi))
            return
        if hi - lo == 1:
            return  # in-range leaf: supplied by the verifier
        sp = split_point(hi - lo)
        walk(lo, lo + sp)
        walk(lo + sp, hi)

    walk(0, n)
    return NmtRangeProof(start, end, tuple(nodes), n)


def verify_range(
    root: bytes, proof: NmtRangeProof, leaf_ndata: list[bytes]
) -> bool:
    """Verify leaves (ns-prefixed raw data, in order) against a 90-byte root."""
    if len(leaf_ndata) != proof.end - proof.start:
        return False
    if not 0 <= proof.start < proof.end <= proof.total:
        return False
    leaf_digests = [NmtHasher.hash_leaf(nd) for nd in leaf_ndata]
    it = iter(proof.nodes)

    def walk(lo: int, hi: int) -> bytes:
        if hi <= proof.start or lo >= proof.end:
            return next(it)
        if hi - lo == 1:
            return leaf_digests[lo - proof.start]
        sp = split_point(hi - lo)
        left = walk(lo, lo + sp)
        right = walk(lo + sp, hi)
        return NmtHasher.hash_node(left, right)

    try:
        computed = walk(0, proof.total)
    except (StopIteration, ValueError):
        # ValueError: hash_node rejects namespace-order violations.
        return False
    if next(it, None) is not None:
        return False  # unconsumed proof nodes
    return computed == root

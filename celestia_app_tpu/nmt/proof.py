"""NMT range proofs (inclusion of a contiguous leaf range).

Parity with the nmt library's Prove/ProveRange + VerifyInclusion as used by
the reference proof path (pkg/wrapper/nmt_wrapper.go:127 ProveRange;
pkg/proof/proof.go:151-202): the proof carries the subtree roots adjacent to
the range in left-to-right DFS order; verification re-computes the root from
the claimed leaves plus those nodes, propagating namespace ranges with the
ignore-max rule.
"""

from __future__ import annotations

from dataclasses import dataclass

from celestia_app_tpu.merkle import split_point
from celestia_app_tpu.nmt.hasher import NmtHasher
from celestia_app_tpu.nmt.tree import NamespacedMerkleTree


@dataclass(frozen=True)
class NmtRangeProof:
    """Inclusion proof for leaves [start, end) of an NMT."""

    start: int
    end: int
    nodes: tuple[bytes, ...]  # 90-byte namespaced digests, DFS order
    total: int  # leaf count of the proven tree


def _subtree_digest(digests: list[bytes], lo: int, hi: int) -> bytes:
    if hi - lo == 1:
        return digests[lo]
    sp = split_point(hi - lo)
    return NmtHasher.hash_node(
        _subtree_digest(digests, lo, lo + sp), _subtree_digest(digests, lo + sp, hi)
    )


def prove_range(tree: NamespacedMerkleTree, start: int, end: int) -> NmtRangeProof:
    digests = tree.leaf_digests()
    n = len(digests)
    if not 0 <= start < end <= n:
        raise ValueError(f"invalid range [{start},{end}) of {n} leaves")
    nodes: list[bytes] = []

    def walk(lo: int, hi: int) -> None:
        if hi <= start or lo >= end:
            nodes.append(_subtree_digest(digests, lo, hi))
            return
        if hi - lo == 1:
            return  # in-range leaf: supplied by the verifier
        sp = split_point(hi - lo)
        walk(lo, lo + sp)
        walk(lo + sp, hi)

    walk(0, n)
    return NmtRangeProof(start, end, tuple(nodes), n)


def range_proof_node_coords(
    total: int, start: int, end: int
) -> list[tuple[int, int]]:
    """The (level, index) coordinates of a range proof's nodes, in the
    exact DFS order `prove_range` emits them — level 0 = leaves.

    Power-of-two totals only: every out-of-range subtree the DFS visits
    is then a complete ALIGNED block, so its digest is one entry of a
    precomputed level (a NamespacedMerkleTree's `levels()`, or the
    device-resident forest serve/cache.py retains) and proof extraction
    becomes pure indexing — no hashing per request.  This is the shared
    index plan of the batched sampler AND the host fallback, which is
    what makes their proof bytes identical by construction.
    """
    if total & (total - 1) or total <= 0:
        raise ValueError(f"range_proof_node_coords needs a power of two, got {total}")
    if not 0 <= start < end <= total:
        raise ValueError(f"invalid range [{start},{end}) of {total} leaves")
    coords: list[tuple[int, int]] = []

    def walk(lo: int, hi: int) -> None:
        if hi <= start or lo >= end:
            size = hi - lo
            coords.append((size.bit_length() - 1, lo // size))
            return
        if hi - lo == 1:
            return
        sp = (hi - lo) // 2  # power-of-two split == split_point
        walk(lo, lo + sp)
        walk(lo + sp, hi)

    walk(0, total)
    return coords


def prove_range_from_levels(
    levels: list[list[bytes]], start: int, end: int
) -> NmtRangeProof:
    """`prove_range` from precomputed digest levels (leaf level first) —
    byte-identical output for power-of-two trees, zero hashing."""
    total = len(levels[0])
    nodes = tuple(
        levels[lvl][idx]
        for lvl, idx in range_proof_node_coords(total, start, end)
    )
    return NmtRangeProof(start, end, nodes, total)


def _verify_digests(
    root: bytes, proof: NmtRangeProof, leaf_digests: list[bytes]
) -> bool:
    if len(leaf_digests) != proof.end - proof.start:
        return False
    if not 0 <= proof.start < proof.end <= proof.total:
        return False
    it = iter(proof.nodes)

    def walk(lo: int, hi: int) -> bytes:
        if hi <= proof.start or lo >= proof.end:
            return next(it)
        if hi - lo == 1:
            return leaf_digests[lo - proof.start]
        sp = split_point(hi - lo)
        left = walk(lo, lo + sp)
        right = walk(lo + sp, hi)
        return NmtHasher.hash_node(left, right)

    try:
        computed = walk(0, proof.total)
    except (StopIteration, ValueError):
        # ValueError: hash_node rejects namespace-order violations.
        return False
    if next(it, None) is not None:
        return False  # unconsumed proof nodes
    return computed == root


def verify_range(
    root: bytes, proof: NmtRangeProof, leaf_ndata: list[bytes]
) -> bool:
    """Verify leaves (ns-prefixed raw data, in order) against a 90-byte root."""
    return _verify_digests(
        root, proof, [NmtHasher.hash_leaf(nd) for nd in leaf_ndata]
    )


# --- deduped multiproofs (the attestation plane's wire unit) ---------------


@dataclass(frozen=True)
class NmtMultiProof:
    """Inclusion proof for a SET of leaf ranges of one NMT.

    s ranges of one tree share most of their upper path nodes; here each
    shared node is serialized ONCE (`nodes`, first-use order) and every
    range consumes its nodes by index (`node_refs`, the exact DFS order
    `prove_range` emits) — so reconstructing any range's NmtRangeProof
    is pure indexing and byte-identical to proving it alone, while the
    wire stops paying s x for shared interior nodes."""

    ranges: tuple[tuple[int, int], ...]  # sorted, disjoint [start, end)
    nodes: tuple[bytes, ...]  # unique 90-byte digests, first-use order
    node_refs: tuple[tuple[int, ...], ...]  # per range, DFS order
    total: int  # leaf count of the proven tree


def multiproof_from_levels(
    levels: list[list[bytes]], ranges
) -> NmtMultiProof:
    """Deduped proof for sorted disjoint `ranges` from precomputed digest
    levels (leaf level first; power-of-two trees).  Deterministic: node
    table order is first use, walking ranges in their sorted order and
    each range's nodes in DFS order."""
    total = len(levels[0])
    rs = tuple((int(s), int(e)) for s, e in ranges)
    prev_end = 0
    for s, e in rs:
        if not 0 <= s < e <= total:
            raise ValueError(f"invalid range [{s},{e}) of {total} leaves")
        if s < prev_end:
            raise ValueError(
                f"ranges must be sorted and disjoint (range [{s},{e}) "
                f"overlaps or precedes end {prev_end})"
            )
        prev_end = e
    if not rs:
        raise ValueError("multiproof needs at least one range")
    table: dict[tuple[int, int], int] = {}
    nodes: list[bytes] = []
    refs: list[tuple[int, ...]] = []
    for s, e in rs:
        rr: list[int] = []
        for coord in range_proof_node_coords(total, s, e):
            j = table.get(coord)
            if j is None:
                j = table[coord] = len(nodes)
                lvl, idx = coord
                nodes.append(levels[lvl][idx])
            rr.append(j)
        refs.append(tuple(rr))
    return NmtMultiProof(rs, tuple(nodes), tuple(refs), total)


def split_multiproof(mp: NmtMultiProof) -> list[NmtRangeProof]:
    """Per-range NmtRangeProofs reconstructed from the deduped table —
    byte-identical to `prove_range` of each range alone.  Raises
    IndexError on out-of-table refs (attacker-shaped input)."""
    return [
        NmtRangeProof(s, e, tuple(mp.nodes[j] for j in refs), mp.total)
        for (s, e), refs in zip(mp.ranges, mp.node_refs)
    ]


def verify_multiproof(
    root: bytes, mp: NmtMultiProof, leaf_ndata_per_range: list[list[bytes]]
) -> bool:
    """Host verification: every range's leaves (ns-prefixed raw data)
    verify against the 90-byte root.  The batched path reconstructs the
    same per-range proofs and decides them in one device program
    (serve/verify.py)."""
    if len(leaf_ndata_per_range) != len(mp.ranges):
        return False
    if len(mp.node_refs) != len(mp.ranges):
        return False
    try:
        parts = split_multiproof(mp)
    except IndexError:
        return False
    return all(
        verify_range(root, proof, leaves)
        for proof, leaves in zip(parts, leaf_ndata_per_range)
    )


# --- namespace proofs (nmt ProveNamespace / VerifyNamespace parity) --------


def prove_namespace(
    tree: NamespacedMerkleTree, namespace: bytes
) -> tuple[NmtRangeProof, list[bytes]]:
    """Prove all leaves of `namespace` (inclusion), or its absence.

    Returns (proof, leaf_ndata).  Empty leaf list = absence proof: the proof
    covers the single leaf at the namespace's would-be position (verified by
    digest), mirroring the nmt library's absence proofs.
    """
    ns_list = [l[: len(namespace)] for l in tree._leaves]
    n = len(ns_list)
    start = next((i for i, ns in enumerate(ns_list) if ns >= namespace), n)
    end = next((i for i, ns in enumerate(ns_list) if ns > namespace), n)
    if start < end:  # present
        return prove_range(tree, start, end), list(tree._leaves[start:end])
    # Absent: prove the leaf at the insertion position (clamped for
    # beyond-the-last-namespace queries).
    pos = min(start, n - 1)
    return prove_range(tree, pos, pos + 1), []


def verify_namespace(
    root: bytes,
    proof: NmtRangeProof,
    namespace: bytes,
    leaf_ndata: list[bytes],
    absence_leaf_digest: bytes | None = None,
) -> bool:
    """Verify a namespace proof: inclusion completeness or absence.

    Inclusion: every proven leaf carries `namespace`, and the proof's
    sibling nodes show nothing with that namespace exists outside the range
    (left siblings' max < ns, right siblings' min > ns).  Absence: the
    single covered leaf digest has a different namespace and the same
    completeness bounds hold.
    """
    size = len(namespace)
    if leaf_ndata:
        if any(l[:size] != namespace for l in leaf_ndata):
            return False
        if not verify_range(root, proof, leaf_ndata):
            return False
    else:
        if absence_leaf_digest is None:
            return False
        if proof.end - proof.start != 1:
            return False
        leaf_min = NmtHasher.min_namespace(absence_leaf_digest)[:size]
        if leaf_min == namespace:
            return False  # the leaf IS the namespace: not an absence proof
        if not _verify_digests(root, proof, [absence_leaf_digest]):
            return False
        # For an interior absence the covered leaf must sit past the
        # namespace; a leaf below it only proves absence if it is the last
        # leaf of the tree.
        if leaf_min < namespace and proof.end != proof.total:
            return False

    # Completeness: no leaf with `namespace` hidden inside a sibling node.
    it = iter(proof.nodes)

    def walk(lo: int, hi: int) -> None:
        if hi <= proof.start:
            node = next(it)
            if NmtHasher.max_namespace(node)[:size] >= namespace:
                raise ValueError("namespace leaks left of the proven range")
            return
        if lo >= proof.end:
            node = next(it)
            if NmtHasher.min_namespace(node)[:size] <= namespace:
                raise ValueError("namespace leaks right of the proven range")
            return
        if hi - lo == 1:
            return
        sp = split_point(hi - lo)
        walk(lo, lo + sp)
        walk(lo + sp, hi)

    try:
        walk(0, proof.total)
    except (ValueError, StopIteration):
        return False
    return True

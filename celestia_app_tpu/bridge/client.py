"""ctypes consumer of the C bridge (the Go/cgo integration shape).

Loads libcelestia_square_bridge.so and drives the same C ABI a Go node
would (SURVEY §2.3): init spawns the persistent worker with AOT warmup,
extend_and_dah round-trips one square, shutdown reaps the worker.
"""

from __future__ import annotations

import ctypes
import sys

import numpy as np

from celestia_app_tpu.constants import NMT_NODE_SIZE, SHARE_SIZE


class BridgeClient:
    def __init__(self, lib_path: str, warmup_ks: list[int] | None = None):
        self._lib = ctypes.CDLL(lib_path)
        self._lib.cstpu_init.restype = ctypes.c_void_p
        self._lib.cstpu_init.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_size_t,
        ]
        self._lib.cstpu_ping.argtypes = [ctypes.c_void_p]
        self._lib.cstpu_extend_and_dah.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        self._lib.cstpu_shutdown.argtypes = [ctypes.c_void_p]

        argv_list = [
            sys.executable.encode(),
            b"-m",
            b"celestia_app_tpu.bridge.worker",
        ]
        argv = (ctypes.c_char_p * (len(argv_list) + 1))(*argv_list, None)
        ks = warmup_ks or []
        ks_arr = (ctypes.c_uint32 * len(ks))(*ks) if ks else None
        self._client = self._lib.cstpu_init(argv, ks_arr, len(ks))
        if not self._client:
            raise RuntimeError("bridge init failed (worker did not start)")

    def ping(self) -> bool:
        return self._lib.cstpu_ping(self._client) == 0

    def extend_and_dah(self, ods: np.ndarray):
        """(k,k,512) uint8 -> (eds, row_roots, col_roots, data_root)."""
        k = ods.shape[0]
        assert ods.shape == (k, k, SHARE_SIZE)
        ods_flat = np.ascontiguousarray(ods, dtype=np.uint8)
        eds = np.empty((2 * k, 2 * k, SHARE_SIZE), dtype=np.uint8)
        row_roots = np.empty((2 * k, NMT_NODE_SIZE), dtype=np.uint8)
        col_roots = np.empty((2 * k, NMT_NODE_SIZE), dtype=np.uint8)
        droot = np.empty(32, dtype=np.uint8)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        rc = self._lib.cstpu_extend_and_dah(
            self._client,
            ods_flat.ctypes.data_as(u8p),
            k,
            eds.ctypes.data_as(u8p),
            row_roots.ctypes.data_as(u8p),
            col_roots.ctypes.data_as(u8p),
            droot.ctypes.data_as(u8p),
        )
        if rc != 0:
            raise RuntimeError("bridge extend_and_dah failed (fall back to CPU)")
        return eds, row_roots, col_roots, droot.tobytes()

    def shutdown(self) -> None:
        if self._client:
            self._lib.cstpu_shutdown(self._client)
            self._client = None

"""The persistent XLA runtime worker behind the C bridge.

Run as `python -m celestia_app_tpu.bridge.worker`; speaks the bridge's
length-prefixed binary protocol on stdin/stdout (see
bridge/celestia_square_bridge.cpp).  Holds jitted pipelines per square size;
the warmup op compiles ahead of time so extend requests never pay a compile
on the consensus critical path (SURVEY §7 hard part 4).
"""

from __future__ import annotations

import os
import struct
import sys

# The worker runs ALONGSIDE the host process's own JAX runtime, and some
# accelerator transports (single-session loopback tunnels) wedge when two
# clients attach concurrently. Default the worker to the CPU backend —
# the pipeline is integer-only, so its output is bit-identical on any
# platform; set CELESTIA_BRIDGE_PLATFORM to opt a deployment into device
# execution when the host is NOT also a device client.
os.environ["JAX_PLATFORMS"] = os.environ.get("CELESTIA_BRIDGE_PLATFORM", "cpu")
if os.environ["JAX_PLATFORMS"] == "cpu":
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

REQ_MAGIC = 0x31515343  # "CSQ1"
RESP_MAGIC = 0x52515343  # "CSQR"
OP_EXTEND = 1
OP_PING = 2
OP_WARMUP = 3
OP_SHUTDOWN = 4

SHARE_SIZE = 512


def _respond(out, status: int, payload: bytes = b"") -> None:
    out.write(struct.pack("<IIQ", RESP_MAGIC, status, len(payload)))
    if payload:
        out.write(payload)
    out.flush()


def _extend(ods_bytes: bytes, k: int) -> bytes:
    import numpy as np

    from celestia_app_tpu.da.eds import ExtendedDataSquare

    ods = np.frombuffer(ods_bytes, dtype=np.uint8).reshape(k, k, SHARE_SIZE)
    eds = ExtendedDataSquare.compute(ods)
    return (
        np.asarray(eds.squared()).tobytes()
        + b"".join(eds.row_roots())
        + b"".join(eds.col_roots())
        + eds.data_root()
    )


def _warmup(k: int) -> None:
    import numpy as np

    from celestia_app_tpu.da.eds import ExtendedDataSquare

    ods = np.zeros((k, k, SHARE_SIZE), dtype=np.uint8)
    ExtendedDataSquare.compute(ods).data_root()


def main() -> int:
    # A sitecustomize may pre-register an accelerator platform; pin the
    # live config too — the env var alone does not take.
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer
    # Anything the runtime prints must not corrupt the protocol stream.
    sys.stdout = sys.stderr

    while True:
        header = stdin.read(20)
        if len(header) < 20:
            return 0  # parent closed the pipe
        magic, op, k, payload_len = struct.unpack("<IIIQ", header)
        if magic != REQ_MAGIC:
            return 1
        payload = stdin.read(payload_len) if payload_len else b""
        if payload_len and len(payload) < payload_len:
            return 1

        if op == OP_PING:
            _respond(stdout, 0)
        elif op == OP_WARMUP:
            try:
                _warmup(k)
                _respond(stdout, 0)
            except Exception:
                _respond(stdout, 1)
        elif op == OP_EXTEND:
            try:
                if len(payload) != k * k * SHARE_SIZE:
                    raise ValueError("payload size mismatch")
                _respond(stdout, 0, _extend(payload, k))
            except Exception:
                _respond(stdout, 1)
        elif op == OP_SHUTDOWN:
            _respond(stdout, 0)
            return 0
        else:
            _respond(stdout, 2)


if __name__ == "__main__":
    sys.exit(main())

"""Request/block-scoped tracing: one trace_id from RPC submission to the
DAH root (trace/context.py + trace/spans.py) plus the layer
instrumentation it threads through — mempool, square builder, device
journal, consensus phases — the e2e phase histogram, the upgraded
/healthz, and the fused-vs-staged parity sentinel.

The context/mempool/square/sentinel layers run without the signing stack;
the five-layer acceptance leg (rpc -> mempool -> square -> device journal
-> consensus under ONE trace_id) importorskips onto `cryptography`.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from celestia_app_tpu.constants import SHARE_SIZE
from celestia_app_tpu.mempool import PriorityMempool
from celestia_app_tpu.trace.context import (
    current_context,
    new_context,
    node_id,
    trace_span,
    use_context,
)
from celestia_app_tpu.trace.exposition import (
    handle_observability_get,
    register_health_provider,
    unregister_health_provider,
)
from celestia_app_tpu.trace.metrics import registry
from celestia_app_tpu.trace.spans import SPANS_TABLE, span_attributes
from celestia_app_tpu.trace.tracer import traced


def _spans_for(trace_id: str) -> list[dict]:
    return [r for r in traced().table(SPANS_TABLE) if r["traceId"] == trace_id]


def _metric_line(name: str, **labels) -> float | None:
    """Sum of every series of `name` matching the label filter (series
    carrying EXTRA labels — e.g. the per-namespace e2e/eviction children
    — aggregate instead of shadowing the unlabeled one)."""
    total, seen = 0.0, False
    for line in registry().render().splitlines():
        if line.startswith(name) and all(
            f'{k}="{v}"' in line for k, v in labels.items()
        ):
            total += float(line.rsplit(" ", 1)[1])
            seen = True
    return total if seen else None


class TestTraceContext:
    def test_child_keeps_trace_links_parent_and_merges_baggage(self):
        root = new_context(layer="rpc", plane="jsonrpc")
        child = root.child(height=7)
        assert child.trace_id == root.trace_id
        assert child.parent_id == root.span_id
        assert child.span_id != root.span_id
        # new_context stamps node_id so cross-node rows carry provenance;
        # the caller's baggage must survive the merge untouched.
        assert child.baggage["node_id"] == node_id()
        assert {k: v for k, v in child.baggage.items() if k != "node_id"} == {
            "layer": "rpc", "plane": "jsonrpc", "height": 7}
        assert child.start_unix_ns == root.start_unix_ns

    def test_use_context_and_nesting(self):
        assert current_context() is None
        ctx = new_context()
        with use_context(ctx):
            assert current_context() is ctx
            with trace_span("tracing_nested_span", k=4):
                inner = current_context()
                assert inner.trace_id == ctx.trace_id
                assert inner.parent_id == ctx.span_id
        assert current_context() is None

    def test_span_exports_otlp_row_and_event_table(self):
        ctx = new_context(layer="test")
        with trace_span("tracing_export_span", ctx=ctx, k=8) as sp:
            sp["result"] = "ok"
        rows = _spans_for(ctx.trace_id)
        assert len(rows) == 1
        row = rows[0]
        assert row["name"] == "tracing_export_span"
        assert row["parentSpanId"] == ctx.span_id
        assert int(row["endTimeUnixNano"]) >= int(row["startTimeUnixNano"])
        attrs = span_attributes(row)
        assert attrs["k"] == "8" and attrs["result"] == "ok"
        assert attrs["layer"] == "test"  # baggage lands on attributes
        event = traced().table("tracing_export_span")[-1]
        assert event["trace_id"] == ctx.trace_id
        assert event["duration_ms"] >= 0
        # The span histogram family exists with k as a label.
        assert _metric_line(
            "celestia_tracing_export_span_seconds_count", k="8"
        ) >= 1

    def test_trace_gate_mutes_exports_but_propagates_context(self, monkeypatch):
        monkeypatch.setenv("CELESTIA_TRACE", "off")
        ctx = new_context()
        with trace_span("tracing_muted_span", ctx=ctx):
            # Explicit threading must survive the mute.
            assert current_context().trace_id == ctx.trace_id
        assert _spans_for(ctx.trace_id) == []

    def test_spans_out_mirror(self, monkeypatch, tmp_path):
        from celestia_app_tpu.trace import spans as spans_mod

        monkeypatch.setenv("CELESTIA_SPANS_OUT", str(tmp_path))
        monkeypatch.setattr(spans_mod, "_FILE_HANDLE", None)
        monkeypatch.setattr(spans_mod, "_FILE_DIR", None)
        monkeypatch.setattr(spans_mod, "_FILE_BROKEN", False)
        ctx = new_context()
        with trace_span("tracing_mirror_span", ctx=ctx):
            pass
        files = list(tmp_path.glob("spans-*.jsonl"))
        assert len(files) == 1
        rows = [json.loads(l) for l in files[0].read_text().splitlines()]
        assert any(r["traceId"] == ctx.trace_id for r in rows)


class TestMempoolTracing:
    def _tx(self, i: int, size: int = 8) -> bytes:
        return bytes([i]) * size

    def test_insert_reap_update_share_the_submission_trace(self):
        mp = PriorityMempool()
        ctx = new_context(layer="rpc")
        with use_context(ctx):
            assert mp.insert(self._tx(1), 10, 0)  # picks up current ctx
        assert mp.ctx_for(self._tx(1)).trace_id == ctx.trace_id
        assert mp.insert(self._tx(2), 5, 0, ctx=new_context())
        out = mp.reap()
        assert out[0] == self._tx(1)  # priority order
        names = {
            r["name"]: r for r in _spans_for(ctx.trace_id)
        }
        assert "mempool_insert" in names
        # The reap span joins the FIRST reaped tx's trace.
        assert "mempool_reap" in names
        reap_attrs = span_attributes(names["mempool_reap"])
        assert reap_attrs["n_txs"] == "2"
        # Committing tx 1 journals the update and closes its lifecycle.
        total_before = _metric_line(
            "celestia_e2e_seconds_count", phase="total"
        ) or 0
        mp.update(1, [self._tx(1)])
        upd = traced().table("mempool_update")[-1]
        assert upd["committed"] == 1 and upd["expired"] == 0
        assert _metric_line(
            "celestia_e2e_seconds_count", phase="total"
        ) == total_before + 1
        assert _metric_line("celestia_mempool_txs") == 1.0
        assert _metric_line("celestia_mempool_size_bytes") == 8.0

    def test_eviction_reasons_reconcile_gauges(self):
        before = {
            reason: _metric_line(
                "celestia_mempool_evictions_total", reason=reason
            ) or 0
            for reason in ("priority", "ttl", "recheck")
        }
        mp = PriorityMempool(max_pool_bytes=24, ttl_num_blocks=2)
        assert mp.insert(self._tx(1), 1, 0)
        assert mp.insert(self._tx(2), 2, 0)
        assert mp.insert(self._tx(3), 3, 0)
        # Pool full of 3x8 bytes: a higher-priority insert evicts tx 1.
        assert mp.insert(self._tx(4), 9, 0)
        assert not mp.has_tx(self._tx(1))
        assert (
            _metric_line("celestia_mempool_evictions_total", reason="priority")
            == before["priority"] + 1
        )
        # recheck eviction (remove_tx) now counts too.
        mp.remove_tx(self._tx(2))
        assert (
            _metric_line("celestia_mempool_evictions_total", reason="recheck")
            == before["recheck"] + 1
        )
        # TTL expiry at height 2 drops the height-0 remainder.
        mp.update(2, [])
        assert len(mp) == 0
        assert (
            _metric_line("celestia_mempool_evictions_total", reason="ttl")
            == before["ttl"] + 2
        )
        assert _metric_line("celestia_mempool_txs") == 0.0
        assert _metric_line("celestia_mempool_size_bytes") == 0.0

    def test_mempool_wait_phase_observed_on_first_reap_only(self):
        before = _metric_line(
            "celestia_e2e_seconds_count", phase="mempool_wait"
        ) or 0
        mp = PriorityMempool()
        mp.insert(self._tx(9), 1, 0, ctx=new_context())
        mp.reap()
        # A reaped-but-uncommitted tx is reaped again next block: its
        # residency must not be re-observed (duplicates would own the
        # histogram tail).
        mp.reap()
        assert _metric_line(
            "celestia_e2e_seconds_count", phase="mempool_wait"
        ) == before + 1


class TestSquareBuildTracing:
    def test_build_span_carries_counts_and_size(self):
        from celestia_app_tpu.square.builder import build

        ctx = new_context(layer="block")
        with use_context(ctx):
            sq, kept = build([], 16)
        rows = [
            r for r in _spans_for(ctx.trace_id) if r["name"] == "square_build"
        ]
        assert len(rows) == 1
        attrs = span_attributes(rows[0])
        assert attrs["k"] == str(sq.size)
        assert attrs["n_txs"] == "0" and attrs["n_blobs"] == "0"
        assert int(attrs["layout_solves"]) >= 1


class TestDeviceJournalTraceId:
    def test_block_journal_row_carries_active_trace(self):
        from celestia_app_tpu.da.eds import ExtendedDataSquare

        ctx = new_context(layer="block")
        with use_context(ctx):
            ExtendedDataSquare.compute(
                np.zeros((4, 4, SHARE_SIZE), dtype=np.uint8)
            )
        row = traced().table("block_journal")[-1]
        assert row["source"] == "compute" and row["trace_id"] == ctx.trace_id


class TestParitySentinel:
    def test_sentinel_matches_fused_against_staged(self, monkeypatch):
        from celestia_app_tpu.da import eds

        monkeypatch.setenv("CELESTIA_PARITY_SENTINEL", "1")
        before = _metric_line(
            "celestia_parity_checks_total", result="match"
        ) or 0
        eds.ExtendedDataSquare.compute(
            np.zeros((4, 4, SHARE_SIZE), dtype=np.uint8)
        )
        eds.drain_parity_checks(timeout_s=300.0)
        assert _metric_line(
            "celestia_parity_checks_total", result="match"
        ) == before + 1
        assert traced().table("parity_mismatch") == []

    def test_sentinel_disabled_by_default(self, monkeypatch):
        from celestia_app_tpu.da import eds

        monkeypatch.delenv("CELESTIA_PARITY_SENTINEL", raising=False)
        count_before = eds._PARITY_COUNT
        eds.ExtendedDataSquare.compute(
            np.zeros((4, 4, SHARE_SIZE), dtype=np.uint8)
        )
        assert eds._PARITY_COUNT == count_before


class TestHealthz:
    def test_bare_healthz_unchanged(self):
        from celestia_app_tpu.trace import exposition

        # Pin the no-providers shape regardless of what other tests left
        # registered (servers unregister on stop, but don't depend on it).
        with exposition._HEALTH_LOCK:
            saved = dict(exposition._HEALTH_PROVIDERS)
            exposition._HEALTH_PROVIDERS.clear()
        try:
            status, _, body = handle_observability_get("/healthz")
            payload = json.loads(body)
            # The SLO judgment block is always present (PR 7); with no
            # providers registered, nothing else is.
            assert status == 200
            assert payload["status"] == "SERVING"
            assert set(payload) == {"status", "slo"}
            assert payload["slo"]["status"] in ("OK", "BURNING")
        finally:
            with exposition._HEALTH_LOCK:
                exposition._HEALTH_PROVIDERS.update(saved)

    def test_layers_report_and_survive_provider_faults(self):
        def good():
            return {"height": 12, "mempool": {"txs": 3}}

        def bad():
            raise RuntimeError("boom")

        register_health_provider("good", good)
        register_health_provider("bad", bad)
        try:
            status, _, body = handle_observability_get("/healthz")
            payload = json.loads(body)
            assert status == 200 and payload["status"] == "SERVING"
            assert payload["layers"]["good"]["height"] == 12
            assert "RuntimeError" in payload["layers"]["bad"]["error"]
        finally:
            unregister_health_provider("good")
            unregister_health_provider("bad")
        status, _, body = handle_observability_get("/healthz")
        payload = json.loads(body)
        assert payload["status"] == "SERVING" and "layers" not in payload

    def test_unregister_checks_identity(self):
        def one():
            return {}

        def two():
            return {}

        register_health_provider("dup", one)
        register_health_provider("dup", two)  # replacement wins
        try:
            unregister_health_provider("dup", one)  # stale: must not unhook
            _, _, body = handle_observability_get("/healthz")
            assert "dup" in json.loads(body)["layers"]
        finally:
            unregister_health_provider("dup")


class TestFiveLayerAcceptance:
    def test_single_trace_id_spans_five_layers(self):
        """Acceptance: a trace_id issued at tx submission shows up on
        spans from rpc, mempool, app/square, device journal, and
        consensus — resolvable via /trace_tables/spans — and the e2e
        histogram carries every lifecycle phase."""
        pytest.importorskip("cryptography")
        from celestia_app_tpu.rpc.server import ServingNode
        from celestia_app_tpu.testutil.testnode import (
            deterministic_genesis,
            funded_keys,
        )
        from celestia_app_tpu.tx.messages import Coin, MsgSend
        from celestia_app_tpu.tx.sign import Fee, build_and_sign

        keys = funded_keys(2)
        node = ServingNode(genesis=deterministic_genesis(keys), keys=keys)
        addr = keys[0].public_key().address()
        to = keys[1].public_key().address()
        from celestia_app_tpu.state.accounts import AuthKeeper

        acct = AuthKeeper(node.app.cms.working).get_account(addr)
        raw = build_and_sign(
            [MsgSend(addr, to, (Coin("utia", 100),))],
            keys[0], node.chain_id, acct.account_number, acct.sequence,
            Fee((Coin("utia", 20_000),), 100_000),
        )
        reply = node.rpc_broadcast_tx(raw.hex(), relay=False)
        assert reply["code"] == 0
        trace_id = reply["trace_id"]
        node.produce_block()

        # Resolve the trace through the exposition surface.
        status, ctype, body = handle_observability_get("/trace_tables/spans")
        assert status == 200 and ctype == "application/x-ndjson"
        rows = [
            json.loads(l) for l in body.decode().strip().splitlines()
        ]
        mine = [r for r in rows if r["traceId"] == trace_id]
        layers = {span_attributes(r).get("layer") for r in mine}
        names = {r["name"] for r in mine}
        assert {"rpc", "mempool", "app", "square", "device", "consensus"} <= layers
        assert {
            "tx_submit", "mempool_insert", "mempool_reap", "block_propose",
            "prepare_proposal", "square_build", "square_pipeline",
            "block_prevotes", "block_precommits", "block_commit",
        } <= names
        # Parent links resolve within the trace (one tree, no orphans
        # beyond the roots created at submission/adoption).
        by_id = {r["spanId"] for r in mine}
        linked = [r for r in mine if r["parentSpanId"] in by_id]
        assert len(linked) >= 5

        # The device journal row for the block carries the same trace.
        jrows = [
            r for r in traced().table("block_journal")
            if r.get("trace_id") == trace_id
        ]
        assert jrows and jrows[-1]["source"] == "compute"

        # All lifecycle phases observed at least once.
        for phase in ("submit", "mempool_wait", "reap", "square_build",
                      "dispatch", "propose", "prevote", "precommit",
                      "commit", "total"):
            assert (_metric_line("celestia_e2e_seconds_count", phase=phase)
                    or 0) >= 1, phase

        # /healthz reports the node layer once serving wires it.
        from celestia_app_tpu.rpc.server import serve

        server = serve(node, port=0, block_interval_s=None)
        try:
            _, _, hbody = handle_observability_get("/healthz")
            payload = json.loads(hbody)
            layer = payload["layers"][f"node:{server.port}"]
            assert layer["height"] == node.app.height
            assert layer["mempool"]["txs"] == 0
        finally:
            server.stop()

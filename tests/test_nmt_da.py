"""NMT, merkle, and DA-layer tests: device kernels vs host oracles."""

import hashlib

import numpy as np
import pytest

from celestia_app_tpu import merkle
from celestia_app_tpu.constants import NAMESPACE_SIZE, SHARE_SIZE
from celestia_app_tpu.da import (
    DataAvailabilityHeader,
    ExtendedDataSquare,
    extend_shares,
    min_data_availability_header,
)
from celestia_app_tpu.gf import codec_for_width
from celestia_app_tpu.nmt import MAX_NAMESPACE, NamespacedMerkleTree, NmtHasher

RNG = np.random.default_rng(99)


def random_square(k: int) -> np.ndarray:
    """A namespace-ordered random ODS (k, k, SHARE_SIZE)."""
    n = k * k
    # sorted non-parity namespaces, then random share bodies
    ns = np.sort(RNG.integers(0, 200, n).astype(np.uint8))
    ods = RNG.integers(0, 256, (n, SHARE_SIZE), dtype=np.uint8)
    ods[:, :NAMESPACE_SIZE] = 0
    ods[:, NAMESPACE_SIZE - 1] = ns  # 29-byte ns: zeros + 1 varying byte
    return ods.reshape(k, k, SHARE_SIZE)


class TestMerkle:
    def test_empty_and_single(self):
        assert merkle.hash_from_byte_slices([]) == hashlib.sha256(b"").digest()
        leaf = b"hello"
        assert merkle.hash_from_byte_slices([leaf]) == hashlib.sha256(b"\x00" + leaf).digest()

    def test_split_point(self):
        assert [merkle.split_point(n) for n in (2, 3, 4, 5, 8, 9)] == [1, 2, 2, 4, 4, 8]

    @pytest.mark.parametrize("n", [1, 2, 3, 5, 8, 13, 16])
    def test_proofs_roundtrip(self, n):
        items = [RNG.integers(0, 256, 90, dtype=np.uint8).tobytes() for _ in range(n)]
        root = merkle.hash_from_byte_slices(items)
        for i in range(n):
            path = merkle.proof(items, i)
            assert merkle.verify_proof(root, items[i], i, n, path)
            if n > 1:
                assert not merkle.verify_proof(root, b"wrong", i, n, path)
                assert not merkle.verify_proof(root, items[i], (i + 1) % n, n, path)


class TestNmtHost:
    def test_leaf_digest_shape_and_ns(self):
        ndata = b"\x07" * NAMESPACE_SIZE + b"payload"
        d = NmtHasher.hash_leaf(ndata)
        assert len(d) == 90
        assert NmtHasher.min_namespace(d) == NmtHasher.max_namespace(d) == b"\x07" * 29
        assert d[58:] == hashlib.sha256(b"\x00" + ndata).digest()

    def test_node_ignore_max_namespace(self):
        l = NmtHasher.hash_leaf(b"\x01" * 29 + b"a")
        r_parity = NmtHasher.hash_leaf(MAX_NAMESPACE + b"b")
        node = NmtHasher.hash_node(l, r_parity)
        assert NmtHasher.min_namespace(node) == b"\x01" * 29
        assert NmtHasher.max_namespace(node) == b"\x01" * 29  # parity ignored
        r_normal = NmtHasher.hash_leaf(b"\x02" * 29 + b"b")
        node2 = NmtHasher.hash_node(l, r_normal)
        assert NmtHasher.max_namespace(node2) == b"\x02" * 29

    def test_node_rejects_unordered(self):
        l = NmtHasher.hash_leaf(b"\x05" * 29 + b"a")
        r = NmtHasher.hash_leaf(b"\x01" * 29 + b"b")
        with pytest.raises(ValueError):
            NmtHasher.hash_node(l, r)

    def test_tree_push_order_enforced(self):
        t = NamespacedMerkleTree()
        t.push(b"\x03" * 29 + b"x")
        with pytest.raises(ValueError):
            t.push(b"\x01" * 29 + b"y")

    def test_subtree_root_alignment(self):
        t = NamespacedMerkleTree()
        for i in range(8):
            t.push(bytes([0] * 28 + [i]) + b"data")
        lv = t.levels()
        assert len(lv) == 4 and len(lv[-1]) == 1
        assert t.subtree_root(0, 8) == t.root()
        assert t.subtree_root(2, 4) == lv[1][1]
        with pytest.raises(ValueError):
            t.subtree_root(1, 3)


@pytest.mark.parametrize("k", [1, 2, 8, 16], ids=lambda k: f"k{k}")
class TestEdsPipeline:
    def test_roots_match_host_oracle(self, k):
        ods = random_square(k)
        eds = ExtendedDataSquare.compute(ods)
        sq = eds.squared()
        codec = codec_for_width(k)
        parity_ns = MAX_NAMESPACE

        # host oracle: build each row/col tree with the reference hasher
        for i in range(2 * k):
            t = NamespacedMerkleTree()
            for j in range(2 * k):
                share = sq[i, j].tobytes()
                ns = share[:NAMESPACE_SIZE] if (i < k and j < k) else parity_ns
                t.push(ns + share)
            assert eds.row_roots()[i] == t.root(), f"row {i}"
        for j in range(2 * k):
            t = NamespacedMerkleTree()
            for i in range(2 * k):
                share = sq[i, j].tobytes()
                ns = share[:NAMESPACE_SIZE] if (i < k and j < k) else parity_ns
                t.push(ns + share)
            assert eds.col_roots()[j] == t.root(), f"col {j}"

        # data root matches the host merkle over roots
        dah = DataAvailabilityHeader.from_eds(eds)
        assert dah.hash() == eds.data_root()
        dah.validate_basic()
        assert dah.square_size() == k

        # RS extension consistent with the codec oracle
        assert np.array_equal(sq[0], codec.extend(ods[0]))

    def test_extend_shares_roundtrip(self, k):
        ods = random_square(k)
        shares = [ods.reshape(-1, SHARE_SIZE)[i].tobytes() for i in range(k * k)]
        eds = extend_shares(shares)
        assert eds.flattened_ods() == shares
        assert eds.width == 2 * k


def test_min_dah_deterministic():
    a = min_data_availability_header()
    b = min_data_availability_header()
    assert a.equals(b)
    assert len(a.hash()) == 32
    a.validate_basic()


def test_extend_shares_rejects_bad_counts():
    share = bytes(SHARE_SIZE)
    with pytest.raises(ValueError):
        extend_shares([share] * 3)  # not a perfect square
    with pytest.raises(ValueError):
        extend_shares([share] * 9)  # 3x3: not a power of two

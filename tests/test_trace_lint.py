"""Tier-1 seat for scripts/trace_lint.py: every registered metric name is
well-formed (`celestia_[a-z0-9_]+`) and documented in the README metrics
table, so exposition goldens and docs cannot drift; every metric LABEL
matches `[a-z][a-z0-9_]*`; and unbounded-cardinality labels (namespace)
only appear in modules routing through the top-N cap helper."""

from __future__ import annotations

import importlib.util
import os

_SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts", "trace_lint.py",
)


def _load():
    spec = importlib.util.spec_from_file_location("trace_lint", _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_metric_names_lint_clean():
    lint = _load()
    problems = lint.lint()
    assert problems == [], "\n".join(problems)


def test_lint_catches_undocumented_and_malformed_names(tmp_path):
    lint = _load()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "def f(reg):\n"
        "    reg.counter('celestia_documented_total', 'x')\n"
        "    reg.gauge('celestia_undocumented_thing', 'x')\n"
        "    reg.histogram('BadName_seconds', 'x')\n"
        "    reg.histogram(f'celestia_dyn_{1}_seconds', 'x')\n"
    )
    readme = tmp_path / "README.md"
    readme.write_text(
        "| `celestia_documented_total` | counter |\n"
        "| `celestia_dyn_<x>_seconds` | histogram |\n"
    )
    problems = lint.lint(str(pkg), str(readme))
    assert len(problems) == 2
    assert any("celestia_undocumented_thing" in p for p in problems)
    assert any("BadName_seconds" in p for p in problems)


def test_documented_placeholder_matches_suffix_not_just_prefix(tmp_path):
    # `celestia_dyn_<x>_seconds` must not whitelist arbitrary names that
    # merely share its prefix (the loophole `celestia_<span>_seconds`
    # used to open over the entire namespace).
    lint = _load()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "def f(reg):\n"
        "    reg.counter('celestia_dyn_foo_seconds', 'x')\n"
        "    reg.counter('celestia_dyn_foo_total', 'x')\n"
    )
    readme = tmp_path / "README.md"
    readme.write_text("| `celestia_dyn_<x>_seconds` | counter |\n")
    problems = lint.lint(str(pkg), str(readme))
    assert len(problems) == 1
    assert "celestia_dyn_foo_total" in problems[0]


def test_label_names_pinned_and_namespace_requires_cap_helper(tmp_path):
    lint = _load()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    # Bad label name + namespace label without the cap helper.
    (pkg / "rogue.py").write_text(
        "def f(reg, v):\n"
        "    reg.counter('celestia_ok_total', 'x').inc(BadLabel='y')\n"
        "    reg.gauge('celestia_ok_gauge', 'x').set(v, namespace='raw')\n"
    )
    # Same namespace label IS allowed when the module routes through the
    # cap helper.
    (pkg / "capped.py").write_text(
        "from celestia_app_tpu.trace.square_journal import "
        "capped_namespace_label\n"
        "def f(reg, v, ns):\n"
        "    reg.gauge('celestia_ok_gauge', 'x').set("
        "v, namespace=capped_namespace_label(ns))\n"
    )
    readme = tmp_path / "README.md"
    readme.write_text(
        "| `celestia_ok_total` | counter |\n"
        "| `celestia_ok_gauge` | gauge |\n"
    )
    problems = lint.lint(str(pkg), str(readme))
    assert len(problems) == 2
    assert any("BadLabel" in p for p in problems)
    assert any(
        "unbounded-cardinality" in p and "rogue.py" in p for p in problems
    )
    assert not any("capped.py" in p for p in problems)


def test_hot_path_broad_except_requires_chaos_ok_tag(tmp_path):
    lint = _load()
    pkg = tmp_path / "pkg"
    (pkg / "da").mkdir(parents=True)
    (pkg / "rpc").mkdir()
    # Hot-path module: one tagged handler (ok), one untagged (problem),
    # one bare `except:` untagged (problem), narrow catches ignored.
    (pkg / "da" / "mod.py").write_text(
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:  # chaos-ok: documented swallow\n"
        "        pass\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:\n"
        "        pass\n"
        "    try:\n"
        "        pass\n"
        "    except:\n"
        "        pass\n"
        "    try:\n"
        "        pass\n"
        "    except ValueError:\n"
        "        pass\n"
        "    try:\n"
        "        pass\n"
        "    except BaseException:\n"  # the broader catch is no escape
        "        pass\n"
    )
    # Non-hot-path module: broad catches are not this rule's business.
    (pkg / "rpc" / "mod.py").write_text(
        "def g():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:\n"
        "        pass\n"
    )
    readme = tmp_path / "README.md"
    readme.write_text("")
    problems = [p for p in lint.lint(str(pkg), str(readme))
                if "chaos-ok" in p]
    assert len(problems) == 3
    assert all("da" in p for p in problems)


def test_chaos_ok_tag_on_preceding_line_counts(tmp_path):
    # Long rationales wrap: the tag may sit on the line above the handler.
    lint = _load()
    pkg = tmp_path / "pkg"
    (pkg / "kernels").mkdir(parents=True)
    (pkg / "kernels" / "mod.py").write_text(
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    # chaos-ok: the rationale wrapped onto its own line\n"
        "    except Exception:\n"
        "        pass\n"
    )
    readme = tmp_path / "README.md"
    readme.write_text("")
    assert [p for p in lint.lint(str(pkg), str(readme))
            if "chaos-ok" in p] == []


def test_in_tree_hot_path_broad_excepts_all_tagged():
    # The real package already satisfies the rule (lint() clean is
    # asserted above); additionally pin that the collector actually SEES
    # in-tree sites, so the rule is enforced against something real.
    lint = _load()
    sites = lint.collect_broad_excepts()
    assert sites, "expected in-tree hot-path broad except handlers"
    assert all(tagged for _, _, tagged in sites)


def test_in_tree_namespace_labels_all_route_through_the_cap(tmp_str=None):
    # The real package must already satisfy the new rules (lint() clean
    # is asserted above); additionally pin that the modules known to
    # carry namespace labels DO reference the helper, so the exemption
    # is earned, not accidental.
    lint = _load()
    uses = lint.collect_label_uses()
    ns_files = {f for f, _, label, _ in uses if label in lint.UNBOUNDED_LABELS}
    assert ns_files, "expected in-tree namespace-labeled metrics"
    for f, _, label, has_helper in uses:
        if label in lint.UNBOUNDED_LABELS:
            assert has_helper, f"{f} uses {label!r} without the cap helper"


def test_routed_paths_must_be_documented(tmp_path):
    """Rule 6: every path routed by the shared observability handler
    must appear as a GET /<path> in the README endpoint table (prefix
    routes match a documented placeholder row)."""
    lint = _load()
    pkg = tmp_path / "pkg"
    (pkg / "trace").mkdir(parents=True)
    (pkg / "trace" / "exposition.py").write_text(
        "def handle_observability_get(path):\n"
        "    p = path.split('?', 1)[0]\n"
        "    if p != '/':\n"  # normalization compare: not a route
        "        p = p.rstrip('/')\n"
        "    if p == '/metrics':\n"
        "        return 1\n"
        "    if p == '/undocumented':\n"
        "        return 2\n"
        "    if p.startswith('/tables/'):\n"
        "        return 3\n"
        "    if p.startswith('/secret/'):\n"
        "        return 4\n"
        "    return None\n"
    )
    readme = tmp_path / "README.md"
    readme.write_text(
        "| `GET /metrics` | exposition |\n"
        "| `GET /tables/<name>` | a table |\n"
    )
    # collect_routed_paths only looks at trace/exposition.py -- but the
    # tmp package has it at the same relative location only if rooted
    # like the real tree; point EXPOSITION_REL at the tmp layout.
    saved = lint.EXPOSITION_REL
    lint.EXPOSITION_REL = "trace/exposition.py"
    try:
        import os as _os

        rel_trees = []
        for rel, tree, lines in lint._parse_package(str(pkg)):
            # _parse_package keys paths relative to the REPO root; re-key
            # them relative to the tmp package so the router is found.
            rel_trees.append((
                _os.path.relpath(_os.path.join(lint.REPO_ROOT, rel), str(pkg)),
                tree, lines,
            ))
        problems = [
            p for p in (
                f for f in _route_problems(lint, rel_trees, str(readme))
            )
        ]
    finally:
        lint.EXPOSITION_REL = saved
    assert any("/undocumented" in p for p in problems)
    assert any("/secret/" in p for p in problems)
    assert not any("/metrics" in p for p in problems)
    assert not any("/tables/" in p for p in problems)
    # The "/" normalization compare is never a route.
    assert not any("'/'" in p for p in problems)


def _route_problems(lint, trees, readme_path):
    endpoints = lint.readme_endpoint_paths(readme_path)
    for rel, lineno, kind, path in lint.collect_routed_paths(trees=trees):
        if kind == "exact":
            documented = path in endpoints
        else:
            documented = any(
                e.startswith(path) and len(e) > len(path) for e in endpoints
            )
        if not documented:
            yield f"{rel}:{lineno}: routed path {path!r} undocumented"


def test_in_tree_routes_are_seen_and_documented():
    # The real handler's routes are collected (so rule 6 bites on
    # something real) and /slo -- this PR's new endpoint -- is among
    # them, documented.
    lint = _load()
    routes = lint.collect_routed_paths()
    paths = {p for _, _, _, p in routes}
    assert "/slo" in paths
    assert "/metrics" in paths
    assert "/trace_tables/" in paths  # the prefix route
    assert "/das/share_proof" in paths and "/das/shares" in paths
    assert "/fleet" in paths and "/das/coverage" in paths
    assert "/" not in paths  # normalization compare is not a route


def test_fleet_routes_must_be_documented(tmp_path):
    """Rule 7a: every FLEET_ROUTES path must appear as GET /<path> in
    the README — the aggregator scrapes peers by these paths, so an
    undocumented one is invisible to whoever wires the fleet up."""
    lint = _load()
    pkg = tmp_path / "pkg"
    (pkg / "trace").mkdir(parents=True)
    (pkg / "trace" / "fleet.py").write_text(
        "FLEET_ROUTES = ('/fleet', '/das/coverage', '/undocumented_fleet')\n"
    )
    readme = tmp_path / "README.md"
    readme.write_text(
        "| `GET /fleet` | merged view |\n"
        "| `GET /das/coverage` | coverage map |\n"
    )
    saved = lint.FLEET_REL
    lint.FLEET_REL = os.path.join("..", "..", str(pkg / "trace" / "fleet.py"))
    try:
        # collect_fleet_routes matches on the repo-relative path;
        # re-key the tmp tree the way the rule-6 test does.
        trees = [
            (os.path.relpath(os.path.join(lint.REPO_ROOT, rel), str(pkg)),
             tree, lines)
            for rel, tree, lines in lint._parse_package(str(pkg))
        ]
        lint.FLEET_REL = "trace/fleet.py"
        routes = lint.collect_fleet_routes(trees=trees)
    finally:
        lint.FLEET_REL = saved
    paths = {p for _, _, p in routes}
    assert paths == {"/fleet", "/das/coverage", "/undocumented_fleet"}
    endpoints = lint.readme_endpoint_paths(str(readme))
    undocumented = [p for p in paths if p not in endpoints]
    assert undocumented == ["/undocumented_fleet"]


def test_rpc_mint_without_adopt_is_flagged(tmp_path):
    """Rule 7b: an rpc/ module calling new_context/use_context without
    referencing adopt_context/adopt_or_new splits the cross-node trace
    and must be flagged; one that adopts (or never mints) passes."""
    lint = _load()
    pkg = tmp_path / "pkg"
    (pkg / "rpc").mkdir(parents=True)
    (pkg / "trace").mkdir()
    # Minter that never adopts: both call sites flagged.
    (pkg / "rpc" / "rogue_plane.py").write_text(
        "from celestia_app_tpu.trace.context import new_context, use_context\n"
        "def handle(req):\n"
        "    ctx = new_context(layer='rpc')\n"
        "    with use_context(ctx):\n"
        "        return req\n"
    )
    # Minter that adopts first: the fallback mint is legitimate.
    (pkg / "rpc" / "good_plane.py").write_text(
        "from celestia_app_tpu.trace.context import (\n"
        "    adopt_context, new_context, use_context)\n"
        "def handle(header, req):\n"
        "    ctx = adopt_context(header) or new_context(layer='rpc')\n"
        "    with use_context(ctx):\n"
        "        return req\n"
    )
    # Same mint outside rpc/: not this rule's business.
    (pkg / "trace" / "tool.py").write_text(
        "from celestia_app_tpu.trace.context import new_context\n"
        "def f():\n"
        "    return new_context(layer='tool')\n"
    )
    trees = [
        (os.path.relpath(os.path.join(lint.REPO_ROOT, rel), str(pkg)).replace(
            os.sep, "/").replace("rpc/", "celestia_app_tpu/rpc/", 1),
         tree, lines)
        for rel, tree, lines in lint._parse_package(str(pkg))
    ]
    mints = lint.collect_rpc_context_mints(trees=trees)
    rogue = [(f, fn) for f, _, fn, adopts in mints if not adopts]
    good = [(f, fn) for f, _, fn, adopts in mints if adopts]
    assert len(rogue) == 2 and all("rogue_plane" in f for f, _ in rogue)
    assert {fn for _, fn in rogue} == {"new_context", "use_context"}
    assert good and all("good_plane" in f for f, _ in good)
    assert not any("tool" in f for f, _, fn, _ in mints)


def test_in_tree_rpc_planes_all_adopt():
    # The real rpc/ planes mint contexts (so rule 7b bites on something
    # real) and every minting module references the adoption API.
    lint = _load()
    mints = lint.collect_rpc_context_mints()
    assert mints, "expected in-tree rpc/ context mints"
    assert all(adopts for _, _, _, adopts in mints), [
        (f, ln, fn) for f, ln, fn, adopts in mints if not adopts
    ]


def test_unstamped_trace_writes_are_flagged(tmp_path):
    """Rule 9: a trace-table write with a resolvable name that stamps
    neither height= nor trace_id= (and is off the allowlist) is flagged;
    height=, trace_id=, a **splat, an allowlisted table, and file-like
    `.write(...)` payloads all pass."""
    lint = _load()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "TABLE = 'const_table'\n"
        "def f(tracer, fh, row):\n"
        "    tracer.write('naked_table', batch=3)\n"          # flagged
        "    tracer.write(TABLE, batch=3)\n"                  # flagged
        "    tracer.write('stamped_h', height=7)\n"
        "    tracer.write('stamped_t', trace_id='T')\n"
        "    tracer.write('spread_table', **row)\n"
        "    tracer.write('slo_page', slo='x')\n"             # allowlist
        "    tracer.write(row['t'], batch=3)\n"               # unresolvable
        "    fh.write('\\n')\n"                               # file payload
        "    fh.write(b'bytes')\n"
    )
    readme = tmp_path / "README.md"
    readme.write_text("")
    sites = lint.collect_unstitched_writes(str(pkg))
    tables = sorted(t for _, _, t in sites)
    assert tables == ["const_table", "naked_table"]
    problems = [p for p in lint.lint(str(pkg), str(readme))
                if "without height= or trace_id=" in p]
    assert len(problems) == 2


def test_in_tree_trace_writes_all_stamped():
    # The real package already passes rule 9 (lint() clean is asserted
    # above); additionally pin that the allowlist is EARNED — every
    # height-free table actually exists as a literal write site, so a
    # renamed table can't leave a stale exemption behind.
    lint = _load()
    assert lint.collect_unstitched_writes() == []
    import ast as _ast
    import os as _os

    literal_tables = set()
    for _rel, tree, _ in lint._parse_package():
        for node in _ast.walk(tree):
            if (
                isinstance(node, _ast.Call)
                and isinstance(node.func, _ast.Attribute)
                and node.func.attr == "write"
                and node.args
                and isinstance(node.args[0], _ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                literal_tables.add(node.args[0].value)
    stale = lint.HEIGHT_FREE_TABLES - literal_tables
    assert not stale, f"allowlisted tables never written: {sorted(stale)}"

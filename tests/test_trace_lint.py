"""Tier-1 seat for scripts/trace_lint.py: every registered metric name is
well-formed (`celestia_[a-z0-9_]+`) and documented in the README metrics
table, so exposition goldens and docs cannot drift."""

from __future__ import annotations

import importlib.util
import os

_SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts", "trace_lint.py",
)


def _load():
    spec = importlib.util.spec_from_file_location("trace_lint", _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_metric_names_lint_clean():
    lint = _load()
    problems = lint.lint()
    assert problems == [], "\n".join(problems)


def test_lint_catches_undocumented_and_malformed_names(tmp_path):
    lint = _load()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "def f(reg):\n"
        "    reg.counter('celestia_documented_total', 'x')\n"
        "    reg.gauge('celestia_undocumented_thing', 'x')\n"
        "    reg.histogram('BadName_seconds', 'x')\n"
        "    reg.histogram(f'celestia_dyn_{1}_seconds', 'x')\n"
    )
    readme = tmp_path / "README.md"
    readme.write_text(
        "| `celestia_documented_total` | counter |\n"
        "| `celestia_dyn_<x>_seconds` | histogram |\n"
    )
    problems = lint.lint(str(pkg), str(readme))
    assert len(problems) == 2
    assert any("celestia_undocumented_thing" in p for p in problems)
    assert any("BadName_seconds" in p for p in problems)

"""Tier-1 seat for scripts/trace_lint.py: every registered metric name is
well-formed (`celestia_[a-z0-9_]+`) and documented in the README metrics
table, so exposition goldens and docs cannot drift; every metric LABEL
matches `[a-z][a-z0-9_]*`; and unbounded-cardinality labels (namespace)
only appear in modules routing through the top-N cap helper."""

from __future__ import annotations

import importlib.util
import os

_SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts", "trace_lint.py",
)


def _load():
    spec = importlib.util.spec_from_file_location("trace_lint", _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_metric_names_lint_clean():
    lint = _load()
    problems = lint.lint()
    assert problems == [], "\n".join(problems)


def test_lint_catches_undocumented_and_malformed_names(tmp_path):
    lint = _load()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "def f(reg):\n"
        "    reg.counter('celestia_documented_total', 'x')\n"
        "    reg.gauge('celestia_undocumented_thing', 'x')\n"
        "    reg.histogram('BadName_seconds', 'x')\n"
        "    reg.histogram(f'celestia_dyn_{1}_seconds', 'x')\n"
    )
    readme = tmp_path / "README.md"
    readme.write_text(
        "| `celestia_documented_total` | counter |\n"
        "| `celestia_dyn_<x>_seconds` | histogram |\n"
    )
    problems = lint.lint(str(pkg), str(readme))
    assert len(problems) == 2
    assert any("celestia_undocumented_thing" in p for p in problems)
    assert any("BadName_seconds" in p for p in problems)


def test_documented_placeholder_matches_suffix_not_just_prefix(tmp_path):
    # `celestia_dyn_<x>_seconds` must not whitelist arbitrary names that
    # merely share its prefix (the loophole `celestia_<span>_seconds`
    # used to open over the entire namespace).
    lint = _load()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "def f(reg):\n"
        "    reg.counter('celestia_dyn_foo_seconds', 'x')\n"
        "    reg.counter('celestia_dyn_foo_total', 'x')\n"
    )
    readme = tmp_path / "README.md"
    readme.write_text("| `celestia_dyn_<x>_seconds` | counter |\n")
    problems = lint.lint(str(pkg), str(readme))
    assert len(problems) == 1
    assert "celestia_dyn_foo_total" in problems[0]


def test_label_names_pinned_and_namespace_requires_cap_helper(tmp_path):
    lint = _load()
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    # Bad label name + namespace label without the cap helper.
    (pkg / "rogue.py").write_text(
        "def f(reg, v):\n"
        "    reg.counter('celestia_ok_total', 'x').inc(BadLabel='y')\n"
        "    reg.gauge('celestia_ok_gauge', 'x').set(v, namespace='raw')\n"
    )
    # Same namespace label IS allowed when the module routes through the
    # cap helper.
    (pkg / "capped.py").write_text(
        "from celestia_app_tpu.trace.square_journal import "
        "capped_namespace_label\n"
        "def f(reg, v, ns):\n"
        "    reg.gauge('celestia_ok_gauge', 'x').set("
        "v, namespace=capped_namespace_label(ns))\n"
    )
    readme = tmp_path / "README.md"
    readme.write_text(
        "| `celestia_ok_total` | counter |\n"
        "| `celestia_ok_gauge` | gauge |\n"
    )
    problems = lint.lint(str(pkg), str(readme))
    assert len(problems) == 2
    assert any("BadLabel" in p for p in problems)
    assert any(
        "unbounded-cardinality" in p and "rogue.py" in p for p in problems
    )
    assert not any("capped.py" in p for p in problems)


def test_hot_path_broad_except_requires_chaos_ok_tag(tmp_path):
    lint = _load()
    pkg = tmp_path / "pkg"
    (pkg / "da").mkdir(parents=True)
    (pkg / "rpc").mkdir()
    # Hot-path module: one tagged handler (ok), one untagged (problem),
    # one bare `except:` untagged (problem), narrow catches ignored.
    (pkg / "da" / "mod.py").write_text(
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:  # chaos-ok: documented swallow\n"
        "        pass\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:\n"
        "        pass\n"
        "    try:\n"
        "        pass\n"
        "    except:\n"
        "        pass\n"
        "    try:\n"
        "        pass\n"
        "    except ValueError:\n"
        "        pass\n"
        "    try:\n"
        "        pass\n"
        "    except BaseException:\n"  # the broader catch is no escape
        "        pass\n"
    )
    # Non-hot-path module: broad catches are not this rule's business.
    (pkg / "rpc" / "mod.py").write_text(
        "def g():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:\n"
        "        pass\n"
    )
    readme = tmp_path / "README.md"
    readme.write_text("")
    problems = [p for p in lint.lint(str(pkg), str(readme))
                if "chaos-ok" in p]
    assert len(problems) == 3
    assert all("da" in p for p in problems)


def test_chaos_ok_tag_on_preceding_line_counts(tmp_path):
    # Long rationales wrap: the tag may sit on the line above the handler.
    lint = _load()
    pkg = tmp_path / "pkg"
    (pkg / "kernels").mkdir(parents=True)
    (pkg / "kernels" / "mod.py").write_text(
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    # chaos-ok: the rationale wrapped onto its own line\n"
        "    except Exception:\n"
        "        pass\n"
    )
    readme = tmp_path / "README.md"
    readme.write_text("")
    assert [p for p in lint.lint(str(pkg), str(readme))
            if "chaos-ok" in p] == []


def test_in_tree_hot_path_broad_excepts_all_tagged():
    # The real package already satisfies the rule (lint() clean is
    # asserted above); additionally pin that the collector actually SEES
    # in-tree sites, so the rule is enforced against something real.
    lint = _load()
    sites = lint.collect_broad_excepts()
    assert sites, "expected in-tree hot-path broad except handlers"
    assert all(tagged for _, _, tagged in sites)


def test_in_tree_namespace_labels_all_route_through_the_cap(tmp_str=None):
    # The real package must already satisfy the new rules (lint() clean
    # is asserted above); additionally pin that the modules known to
    # carry namespace labels DO reference the helper, so the exemption
    # is earned, not accidental.
    lint = _load()
    uses = lint.collect_label_uses()
    ns_files = {f for f, _, label, _ in uses if label in lint.UNBOUNDED_LABELS}
    assert ns_files, "expected in-tree namespace-labeled metrics"
    for f, _, label, has_helper in uses:
        if label in lint.UNBOUNDED_LABELS:
            assert has_helper, f"{f} uses {label!r} without the cap helper"

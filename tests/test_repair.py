"""Erasure repair tests (rsmt2d.Repair capability parity)."""

import numpy as np
import pytest

from celestia_app_tpu.constants import NAMESPACE_SIZE, SHARE_SIZE
from celestia_app_tpu.da import (
    DataAvailabilityHeader,
    ExtendedDataSquare,
    IrrecoverableSquare,
    RootMismatch,
    repair,
)

RNG = np.random.default_rng(17)


def random_eds(k: int):
    n = k * k
    ns = np.sort(RNG.integers(0, 200, n).astype(np.uint8))
    ods = RNG.integers(0, 256, (n, SHARE_SIZE), dtype=np.uint8)
    ods[:, :NAMESPACE_SIZE] = 0
    ods[:, NAMESPACE_SIZE - 1] = ns
    eds = ExtendedDataSquare.compute(ods.reshape(k, k, SHARE_SIZE))
    return eds, np.asarray(eds.squared())


@pytest.mark.parametrize("k", [4, 8])
def test_quadrant_erasure(k):
    """BASELINE config 4: drop one full quadrant (25%), repair, verify DAH."""
    eds, full = random_eds(k)
    dah = DataAvailabilityHeader.from_eds(eds)
    present = np.ones((2 * k, 2 * k), dtype=bool)
    present[k:, k:] = False  # Q3 gone
    damaged = full.copy()
    damaged[~present] = 0
    out = repair(damaged, present, dah)
    assert np.array_equal(out.squared(), full)


def test_random_erasure_pattern():
    k = 4
    eds, full = random_eds(k)
    dah = DataAvailabilityHeader.from_eds(eds)
    # Keep exactly k shares in every row: decodable in one row sweep.
    present = np.zeros((2 * k, 2 * k), dtype=bool)
    for r in range(2 * k):
        cols = RNG.choice(2 * k, size=k, replace=False)
        present[r, cols] = True
    damaged = np.where(present[..., None], full, 0).astype(np.uint8)
    out = repair(damaged, present, dah)
    assert np.array_equal(out.squared(), full)


def test_crossword_iteration():
    """A pattern unsolvable in one sweep: rows feed columns, then rows."""
    k = 4
    eds, full = random_eds(k)
    present = np.ones((2 * k, 2 * k), dtype=bool)
    # Row 0 keeps only 2 shares (< k): unsolvable until columns restore it.
    present[0, 2:] = False
    # Every column keeps >= k shares, so the column sweep fills row 0.
    damaged = np.where(present[..., None], full, 0).astype(np.uint8)
    out = repair(damaged, present)
    assert np.array_equal(out.squared(), full)


def test_irrecoverable():
    k = 4
    _, full = random_eds(k)
    present = np.zeros((2 * k, 2 * k), dtype=bool)
    present[:, :3] = True  # 3 < k shares per row; columns 0-2 complete only
    with pytest.raises(IrrecoverableSquare):
        repair(full, present)


def test_corrupted_survivor_rejected():
    k = 4
    eds, full = random_eds(k)
    dah = DataAvailabilityHeader.from_eds(eds)
    present = np.ones((2 * k, 2 * k), dtype=bool)
    present[k:, k:] = False
    damaged = full.copy()
    damaged[0, 0, 100] ^= 0xFF  # corrupt a "surviving" share
    with pytest.raises(RootMismatch):
        repair(damaged, present, dah)


@pytest.mark.slow
def test_quadrant_erasure_bigk_gf16():
    """k=256: the GF(2^16) regime (VERDICT r2 item 6 — repair was never
    exercised at k >= 256).  Full quadrant loss, repaired and DAH-verified
    end to end through the device-resident path."""
    k = 256
    eds, full = random_eds(k)
    dah = DataAvailabilityHeader.from_eds(eds)
    present = np.ones((2 * k, 2 * k), dtype=bool)
    present[k:, k:] = False
    damaged = full.copy()
    damaged[~present] = 0
    out = repair(damaged, present, dah)
    assert np.array_equal(out.squared(), full)

"""Erasure repair tests (rsmt2d.Repair capability parity)."""

import numpy as np
import pytest

from celestia_app_tpu.constants import NAMESPACE_SIZE, SHARE_SIZE
from celestia_app_tpu.da import (
    DataAvailabilityHeader,
    ExtendedDataSquare,
    IrrecoverableSquare,
    RootMismatch,
    repair,
)

RNG = np.random.default_rng(17)


def random_eds(k: int):
    n = k * k
    ns = np.sort(RNG.integers(0, 200, n).astype(np.uint8))
    ods = RNG.integers(0, 256, (n, SHARE_SIZE), dtype=np.uint8)
    ods[:, :NAMESPACE_SIZE] = 0
    ods[:, NAMESPACE_SIZE - 1] = ns
    eds = ExtendedDataSquare.compute(ods.reshape(k, k, SHARE_SIZE))
    return eds, np.asarray(eds.squared())


@pytest.mark.parametrize("k", [4, 8])
def test_quadrant_erasure(k):
    """BASELINE config 4: drop one full quadrant (25%), repair, verify DAH."""
    eds, full = random_eds(k)
    dah = DataAvailabilityHeader.from_eds(eds)
    present = np.ones((2 * k, 2 * k), dtype=bool)
    present[k:, k:] = False  # Q3 gone
    damaged = full.copy()
    damaged[~present] = 0
    out = repair(damaged, present, dah)
    assert np.array_equal(out.squared(), full)


def test_random_erasure_pattern():
    k = 4
    eds, full = random_eds(k)
    dah = DataAvailabilityHeader.from_eds(eds)
    # Keep exactly k shares in every row: decodable in one row sweep.
    present = np.zeros((2 * k, 2 * k), dtype=bool)
    for r in range(2 * k):
        cols = RNG.choice(2 * k, size=k, replace=False)
        present[r, cols] = True
    damaged = np.where(present[..., None], full, 0).astype(np.uint8)
    out = repair(damaged, present, dah)
    assert np.array_equal(out.squared(), full)


def test_crossword_iteration():
    """A pattern unsolvable in one sweep: rows feed columns, then rows."""
    k = 4
    eds, full = random_eds(k)
    present = np.ones((2 * k, 2 * k), dtype=bool)
    # Row 0 keeps only 2 shares (< k): unsolvable until columns restore it.
    present[0, 2:] = False
    # Every column keeps >= k shares, so the column sweep fills row 0.
    damaged = np.where(present[..., None], full, 0).astype(np.uint8)
    out = repair(damaged, present)
    assert np.array_equal(out.squared(), full)


def test_irrecoverable():
    k = 4
    _, full = random_eds(k)
    present = np.zeros((2 * k, 2 * k), dtype=bool)
    present[:, :3] = True  # 3 < k shares per row; columns 0-2 complete only
    with pytest.raises(IrrecoverableSquare):
        repair(full, present)


def test_corrupted_survivor_rejected():
    k = 4
    eds, full = random_eds(k)
    dah = DataAvailabilityHeader.from_eds(eds)
    present = np.ones((2 * k, 2 * k), dtype=bool)
    present[k:, k:] = False
    damaged = full.copy()
    damaged[0, 0, 100] ^= 0xFF  # corrupt a "surviving" share
    with pytest.raises(RootMismatch):
        repair(damaged, present, dah)


@pytest.mark.slow
def test_quadrant_erasure_bigk_gf16():
    """k=256: the GF(2^16) regime (VERDICT r2 item 6 — repair was never
    exercised at k >= 256).  Full quadrant loss, repaired and DAH-verified
    end to end through the device-resident path."""
    k = 256
    eds, full = random_eds(k)
    dah = DataAvailabilityHeader.from_eds(eds)
    present = np.ones((2 * k, 2 * k), dtype=bool)
    present[k:, k:] = False
    damaged = full.copy()
    damaged[~present] = 0
    out = repair(damaged, present, dah)
    assert np.array_equal(out.squared(), full)


def _damaged(full, present):
    return np.where(present[..., None], full, 0).astype(np.uint8)


class TestRepairEdgeCases:
    """ISSUE-10 satellite: erasure patterns at / below the recoverability
    threshold, axis-only erasures, and the batched-vs-grouped twin pin."""

    def test_row_only_erasure(self):
        """Entire rows gone (each surviving row complete): one column
        sweep must restore everything."""
        k = 4
        eds, full = random_eds(k)
        dah = DataAvailabilityHeader.from_eds(eds)
        present = np.ones((2 * k, 2 * k), dtype=bool)
        present[[1, 3, 5, 6], :] = False  # 4 of 8 rows gone (k survive per col)
        out = repair(_damaged(full, present), present, dah)
        assert np.array_equal(out.squared(), full)

    def test_col_only_erasure(self):
        k = 4
        eds, full = random_eds(k)
        dah = DataAvailabilityHeader.from_eds(eds)
        present = np.ones((2 * k, 2 * k), dtype=bool)
        present[:, [0, 2, 4, 7]] = False
        out = repair(_damaged(full, present), present, dah)
        assert np.array_equal(out.squared(), full)

    def test_randomized_at_threshold(self):
        """Exactly k survivors in every row — 75% of the square erased,
        the edge of recoverability — across several random draws."""
        k = 4
        rng = np.random.default_rng(77)
        eds, full = random_eds(k)
        dah = DataAvailabilityHeader.from_eds(eds)
        for _ in range(3):
            present = np.zeros((2 * k, 2 * k), dtype=bool)
            for r in range(2 * k):
                present[r, rng.choice(2 * k, size=k, replace=False)] = True
            out = repair(_damaged(full, present), present, dah)
            assert np.array_equal(out.squared(), full)

    def test_randomized_below_threshold_irrecoverable(self):
        """k-1 survivors in every row AND every column has < k: no sweep
        can start — IrrecoverableSquare, never a wrong square."""
        k = 4
        rng = np.random.default_rng(78)
        _, full = random_eds(k)
        for _ in range(3):
            present = np.zeros((2 * k, 2 * k), dtype=bool)
            # k-1 survivors per row, all packed into k-1 columns: every
            # row AND every column is below k.
            cols = rng.choice(2 * k, size=k - 1, replace=False)
            present[:, cols] = True
            with pytest.raises(IrrecoverableSquare):
                repair(_damaged(full, present), present)

    def test_ods_missing_data_crossword(self):
        """Missing ODS data that needs the crossword (rows under k
        survivors until columns restore them) — the batched solve's
        data-first strategy must still converge."""
        k = 4
        eds, full = random_eds(k)
        dah = DataAvailabilityHeader.from_eds(eds)
        present = np.ones((2 * k, 2 * k), dtype=bool)
        present[0, : k + 1] = False  # row 0: k-1 < k survivors, data gone
        # Columns still have 2k-1 >= k survivors: the column sweep
        # restores row 0's missing cells.
        out = repair(_damaged(full, present), present, dah)
        assert np.array_equal(out.squared(), full)


class TestBatchedGroupedTwin:
    """Regression pin: the batched sweep ($CELESTIA_REPAIR_SWEEP default)
    and the frozen per-pattern-group baseline produce byte-identical
    squares AND roots, randomized + quadrant erasures, both RS
    constructions."""

    @staticmethod
    def _both(damaged, present, dah, monkeypatch):
        monkeypatch.setenv("CELESTIA_REPAIR_SWEEP", "grouped")
        grouped = repair(damaged.copy(), present, dah)
        monkeypatch.delenv("CELESTIA_REPAIR_SWEEP")
        batched = repair(damaged.copy(), present, dah)
        assert np.array_equal(grouped.squared(), batched.squared())
        assert grouped.data_root() == batched.data_root()
        assert grouped.row_roots() == batched.row_roots()
        assert grouped.col_roots() == batched.col_roots()
        return batched

    @pytest.mark.parametrize("construction", ["vandermonde", "leopard"])
    @pytest.mark.parametrize("k", [2, 8])
    def test_twin_quadrant_and_randomized(self, monkeypatch, k, construction):
        monkeypatch.setenv("CELESTIA_RS_CONSTRUCTION", construction)
        eds, full = random_eds(k)
        dah = DataAvailabilityHeader.from_eds(eds)
        rng = np.random.default_rng(500 + k)
        # Quadrant erasure (pure parity: the batched path's zero-sweep case).
        present = np.ones((2 * k, 2 * k), dtype=bool)
        present[k:, k:] = False
        out = self._both(_damaged(full, present), present, dah, monkeypatch)
        assert np.array_equal(out.squared(), full)
        # Randomized erasure touching the ODS (real batched sweeps).
        present = np.zeros((2 * k, 2 * k), dtype=bool)
        for r in range(2 * k):
            present[r, rng.choice(2 * k, size=k, replace=False)] = True
        out = self._both(_damaged(full, present), present, dah, monkeypatch)
        assert np.array_equal(out.squared(), full)

    @pytest.mark.slow
    @pytest.mark.parametrize("construction", ["vandermonde", "leopard"])
    def test_twin_k32(self, monkeypatch, construction):
        k = 32
        monkeypatch.setenv("CELESTIA_RS_CONSTRUCTION", construction)
        eds, full = random_eds(k)
        dah = DataAvailabilityHeader.from_eds(eds)
        present = np.ones((2 * k, 2 * k), dtype=bool)
        present[k:, k:] = False
        present[0, :k] = False  # mixed: parity quadrant + a data row
        out = self._both(_damaged(full, present), present, dah, monkeypatch)
        assert np.array_equal(out.squared(), full)

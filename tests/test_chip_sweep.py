"""scripts/chip_sweep.py — the push-button chip sitting.  Tier-1 only
exercises the spawn-free surfaces: the pure plan builder, the --dryrun
journal (schema, round numbering, atomicity), and the --legs filter.
The real legs need the hardware the sweep exists to reach.
"""

from __future__ import annotations

import importlib.util
import json
import os

import pytest

_SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts", "chip_sweep.py",
)


def _load():
    spec = importlib.util.spec_from_file_location("chip_sweep", _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def cs():
    return _load()


def _args(cs, **over):
    import argparse

    ns = argparse.Namespace(
        dryrun=False, resume=None, legs=None, out_dir=".",
        leg_timeout_s=1800.0, probe_timeout_s=120.0,
        require_device=False, shards="1,8", giant_ks=cs.GIANT_KS,
        das_clients=1000, mempool_threads=8,
    )
    for k, v in over.items():
        setattr(ns, k, v)
    return ns


class TestBuildPlan:
    def test_plan_covers_the_standing_debt(self, cs):
        plan = cs.build_plan(_args(cs))
        names = [leg["name"] for leg in plan]
        assert names == [
            "parts", "stream", "repair",
            "compute_sharded_k1024", "compute_sharded_k2048",
            "compute_sharded_k4096",
            "panel_k1024", "panel_k2048", "panel_k4096",
            "das_shard_sweep", "mempool", "withhold_heal", "hbm_k512",
        ]
        # Pure function: no filesystem writes, no subprocess spawns —
        # every leg is still argv + env, nothing executed.
        for leg in plan:
            assert leg["argv"][0]  # resolved interpreter path
            assert isinstance(leg["env"], dict)
            assert leg["timeout_s"] == 1800.0

    def test_legs_filter_and_unknown_leg_rejected(self, cs):
        plan = cs.build_plan(_args(cs, legs="parts,mempool"))
        assert [leg["name"] for leg in plan] == ["parts", "mempool"]
        with pytest.raises(SystemExit):
            cs.build_plan(_args(cs, legs="parts,flux_capacitor"))

    def test_giant_ks_parameterize_the_sharded_legs(self, cs):
        plan = cs.build_plan(_args(cs, giant_ks=(64,)))
        names = [leg["name"] for leg in plan]
        assert "compute_sharded_k64" in names
        assert "compute_sharded_k1024" not in names

    def test_das_legs_write_round_artifacts_into_the_leg_dir(self, cs):
        plan = cs.build_plan(_args(cs, legs="das_shard_sweep,withhold_heal"))
        for leg in plan:
            assert any("__LEGDIR__" in a for a in leg["argv"])


class TestDryrun:
    def test_dryrun_journals_every_leg_without_spawning(self, cs, tmp_path):
        rc = cs.main(["--dryrun", "--out-dir", str(tmp_path)])
        assert rc == 0
        journal = json.loads((tmp_path / "SWEEP_r01.json").read_text())
        assert journal["schema"] == cs.SWEEP_SCHEMA
        assert journal["round"] == 1
        assert journal["dryrun"] is True
        assert journal["platform"] == "unprobed"
        assert len(journal["legs"]) == 13
        assert set(journal["plan"]) == set(journal["legs"])
        for rec in journal["legs"].values():
            assert rec["status"] == "planned"
            assert rec["argv"] and rec["note"]
        # Atomic write: no .tmp residue.
        assert not list(tmp_path.glob("*.tmp"))

    def test_round_numbering_increments(self, cs, tmp_path):
        assert cs.main(["--dryrun", "--out-dir", str(tmp_path)]) == 0
        assert cs.main(["--dryrun", "--out-dir", str(tmp_path)]) == 0
        assert (tmp_path / "SWEEP_r01.json").exists()
        assert (tmp_path / "SWEEP_r02.json").exists()

    def test_dryrun_respects_legs_filter(self, cs, tmp_path):
        rc = cs.main([
            "--dryrun", "--out-dir", str(tmp_path), "--legs", "parts",
        ])
        assert rc == 0
        journal = json.loads((tmp_path / "SWEEP_r01.json").read_text())
        assert list(journal["legs"]) == ["parts"]


class TestJournalHelpers:
    def test_next_round_path_skips_to_max_plus_one(self, cs, tmp_path):
        (tmp_path / "SWEEP_r07.json").write_text("{}")
        (tmp_path / "SWEEP_r03.json").write_text("{}")
        path = cs.next_round_path(str(tmp_path))
        assert path.endswith("SWEEP_r08.json")

    def test_write_journal_creates_parents_and_is_atomic(self, cs, tmp_path):
        path = str(tmp_path / "deep" / "SWEEP_r01.json")
        cs.write_journal(path, {"schema": cs.SWEEP_SCHEMA, "legs": {}})
        data = json.loads(open(path).read())
        assert data["schema"] == cs.SWEEP_SCHEMA
        assert not os.path.exists(path + ".tmp")

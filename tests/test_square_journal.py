"""Data-plane observability: square journal accounting, per-namespace
metrics with the top-N cardinality cap, mempool per-tenant gauges, the
/namespaces endpoint, and the /healthz last-square snapshot.

Everything here is crypto-free (builder + mempool + trace layer only),
the same tier test_tracing.py runs in.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from celestia_app_tpu.mempool import PriorityMempool
from celestia_app_tpu.shares.namespace import Namespace
from celestia_app_tpu.shares.sparse import Blob
from celestia_app_tpu.square import Builder, build, construct
from celestia_app_tpu.trace import square_journal
from celestia_app_tpu.trace.context import new_context, trace_span, use_context
from celestia_app_tpu.trace.exposition import handle_observability_get
from celestia_app_tpu.trace.metrics import registry
from celestia_app_tpu.trace.tracer import traced
from celestia_app_tpu.tx.envelopes import BlobTx

RNG = np.random.default_rng(7)


def rand_bytes(n: int) -> bytes:
    return RNG.integers(0, 256, n, dtype=np.uint8).tobytes()


def user_ns(tag: int) -> Namespace:
    return Namespace.v0(bytes([tag]) * 10)


def make_blob_tx(ns_tags: list[int], sizes: list[int]) -> bytes:
    blobs = tuple(
        Blob(user_ns(t), rand_bytes(s)) for t, s in zip(ns_tags, sizes)
    )
    return BlobTx(rand_bytes(64), blobs).marshal()


def _metric_line(name: str, **labels) -> float | None:
    """Sum of every series of `name` matching the label filter (the
    registry is process-wide; series with extra labels aggregate)."""
    total, seen = 0.0, False
    for line in registry().render().splitlines():
        if line.startswith(name) and all(
            f'{k}="{v}"' in line for k, v in labels.items()
        ):
            total += float(line.rsplit(" ", 1)[1])
            seen = True
    return total if seen else None


def _assert_sums(acct) -> None:
    assert (
        acct.tx_shares + acct.pfb_shares + acct.blob_shares
        + acct.reserved_padding + acct.namespace_padding + acct.tail_padding
        == acct.size * acct.size
    )
    assert acct.used_shares + acct.padding_shares == acct.total_shares


class TestSquareAccounting:
    def test_empty_square_is_all_tail_padding(self):
        acct = Builder(64).export().accounting
        assert acct.size == 1
        assert acct.tail_padding == 1 and acct.used_shares == 0
        assert acct.occupancy == 0.0
        assert acct.namespaces == ()
        _assert_sums(acct)

    def test_tx_only_square_has_no_blob_buckets(self):
        sq, kept = build([rand_bytes(40)], 64)
        acct = sq.accounting
        assert acct.tx_shares == 1 and acct.pfb_shares == 0
        assert acct.blob_shares == 0
        assert acct.reserved_padding == acct.namespace_padding == 0
        assert acct.occupancy == 1.0  # k=1, the single share is the tx
        _assert_sums(acct)

    def test_blob_immediately_after_pfb_range(self):
        # A one-share blob aligns to width 1: it starts right after the
        # PFB compact range — zero reserved AND zero namespace padding.
        sq, _ = build([make_blob_tx([1], [100])], 64)
        acct = sq.accounting
        assert acct.blob_shares == 1
        assert acct.reserved_padding == 0
        assert acct.namespace_padding == 0
        assert acct.tail_padding == acct.total_shares - acct.used_shares
        _assert_sums(acct)

    def test_adjacent_same_namespace_blobs_zero_namespace_padding(self):
        sq, _ = build([make_blob_tx([3, 3], [100, 100])], 64)
        acct = sq.accounting
        assert acct.blob_shares == 2 and acct.namespace_padding == 0
        assert len(acct.namespaces) == 1
        u = acct.namespaces[0]
        assert (u.blobs, u.shares, u.data_bytes) == (2, 2, 200)
        _assert_sums(acct)

    def test_alignment_gap_counts_as_namespace_padding(self):
        # A 1-share blob then a multi-share blob in a LATER namespace:
        # with threshold 1 the second blob aligns to a subtree boundary,
        # leaving a gap that must be namespace padding, never lost.
        sq, _ = build(
            [make_blob_tx([1], [100]), make_blob_tx([2], [4000])], 64,
            subtree_root_threshold=1,
        )
        acct = sq.accounting
        assert acct.namespace_padding > 0
        _assert_sums(acct)

    def test_reserved_padding_before_first_aligned_blob(self):
        # Txs push the compact range past the blob's subtree boundary
        # remainder -> an alignment gap before the FIRST blob, which is
        # reserved padding (not namespace padding).
        txs = [rand_bytes(300) for _ in range(2)]
        sq, _ = build(
            txs + [make_blob_tx([5], [4000])], 64, subtree_root_threshold=1
        )
        acct = sq.accounting
        assert acct.reserved_padding > 0
        assert acct.namespace_padding == 0
        _assert_sums(acct)

    def test_randomized_breakdowns_always_sum_to_k_squared(self):
        for seed in range(12):
            rng = np.random.default_rng(seed)
            txs = []
            for _ in range(int(rng.integers(0, 4))):
                txs.append(rng.integers(0, 256, 80, dtype=np.uint8).tobytes())
            for _ in range(int(rng.integers(0, 5))):
                tags = [int(t) for t in rng.integers(1, 6, rng.integers(1, 3))]
                sizes = [int(s) for s in rng.integers(1, 3000, len(tags))]
                txs.append(make_blob_tx(tags, sizes))
            sq, kept = build(txs, 32)
            _assert_sums(sq.accounting)
            if kept:
                _assert_sums(construct(kept, 32).accounting)

    def test_build_and_construct_agree_on_accounting(self):
        raw = [rand_bytes(64), make_blob_tx([1], [900]), make_blob_tx([2], [40])]
        sq, kept = build(raw, 64)
        assert construct(kept, 64).accounting == sq.accounting


class TestSquareJournal:
    def setup_method(self):
        square_journal._reset_for_tests()

    def test_row_per_phase_with_trace_id_and_exact_sums(self):
        ctx = new_context(layer="block", height=9)
        n_before = len(traced().table(square_journal.TABLE))
        with use_context(ctx):
            sq, kept = build([make_blob_tx([1, 2], [500, 1200])], 64)
            construct(kept, 64)
        rows = traced().table(square_journal.TABLE)[n_before:]
        assert [r["phase"] for r in rows] == ["build", "construct"]
        for row in rows:
            assert row["trace_id"] == ctx.trace_id
            assert row["height"] == 9
            assert (
                row["tx_shares"] + row["pfb_shares"] + row["blob_shares"]
                + row["reserved_padding"] + row["namespace_padding"]
                + row["tail_padding"]
                == row["k"] * row["k"] == row["total_shares"]
            )
            assert row["n_namespaces"] == 2
            assert set(row["namespaces"]) == {
                square_journal.namespace_label(user_ns(1).to_bytes()),
                square_journal.namespace_label(user_ns(2).to_bytes()),
            }

    def test_metrics_reflect_the_square(self):
        sq, _ = build([make_blob_tx([4], [600])], 64)
        acct = sq.accounting
        assert _metric_line(
            "celestia_square_occupancy_ratio", k=str(acct.size)
        ) == pytest.approx(acct.occupancy, abs=1e-6)
        for kind in ("reserved", "namespace", "tail"):
            assert _metric_line(
                "celestia_square_padding_shares_total", kind=kind
            ) is not None
        lbl = square_journal.namespace_label(user_ns(4).to_bytes())
        assert _metric_line(
            "celestia_namespace_blobs_total", namespace=lbl
        ) >= 1
        assert _metric_line(
            "celestia_namespace_bytes_total", namespace=lbl
        ) >= 600
        assert _metric_line(
            "celestia_namespace_shares_total", namespace=lbl
        ) >= acct.namespaces[0].shares

    def test_label_cardinality_is_capped(self, monkeypatch):
        monkeypatch.setenv("CELESTIA_NAMESPACE_TOP_N", "2")
        square_journal._reset_for_tests()
        other_before = _metric_line(
            "celestia_namespace_blobs_total",
            namespace=square_journal.OTHER_LABEL,
        ) or 0
        # One square with 4 tenants: the two biggest get labels, the
        # rest fold into `other`.
        build([make_blob_tx([t], [s]) for t, s in
               zip((11, 12, 13, 14), (4000, 3000, 100, 100))], 64)
        admitted = {
            square_journal.capped_namespace_label(
                square_journal.namespace_label(user_ns(t).to_bytes())
            )
            for t in (11, 12, 13, 14)
        }
        assert square_journal.OTHER_LABEL in admitted
        assert len(admitted - {square_journal.OTHER_LABEL}) == 2
        # The biggest tenants won the slots.
        assert square_journal.capped_namespace_label(
            square_journal.namespace_label(user_ns(11).to_bytes())
        ) != square_journal.OTHER_LABEL
        assert _metric_line(
            "celestia_namespace_blobs_total",
            namespace=square_journal.OTHER_LABEL,
        ) == other_before + 2
        # New tenants later never mint new labels.
        build([make_blob_tx([15], [50])], 64)
        assert square_journal.capped_namespace_label(
            square_journal.namespace_label(user_ns(15).to_bytes())
        ) == square_journal.OTHER_LABEL

    def test_namespaces_endpoint_and_payload(self):
        build([make_blob_tx([6], [300])], 64)
        resp = handle_observability_get("/namespaces")
        assert resp is not None and resp[0] == 200
        payload = json.loads(resp[2])
        assert payload == square_journal.namespaces_payload()
        lbl = square_journal.namespace_label(user_ns(6).to_bytes())
        assert payload["namespaces"][lbl]["bytes"] >= 300
        assert payload["last_square"]["k"] >= 1
        assert payload["top_n"] >= payload["admitted"]

    def test_last_square_distinguishes_empty_blocks(self):
        assert square_journal.last_square() is None
        Builder(64).export()  # export alone doesn't journal (no phase)
        assert square_journal.last_square() is None
        build([], 16)
        last = square_journal.last_square()
        assert last["occupancy"] == 0.0 and last["phase"] == "build"
        build([make_blob_tx([7], [100])], 64)
        assert square_journal.last_square()["occupancy"] > 0.0

    def test_snapshot_survives_trace_off(self, monkeypatch):
        monkeypatch.setenv("CELESTIA_TRACE", "off")
        square_journal._reset_for_tests()
        n_before = len(traced().table(square_journal.TABLE))
        build([make_blob_tx([8], [100])], 64)
        # No row, no metrics — but the liveness snapshot still updates.
        assert len(traced().table(square_journal.TABLE)) == n_before
        assert square_journal.last_square() is not None


class TestMempoolNamespaceAccounting:
    def setup_method(self):
        square_journal._reset_for_tests()

    def _gauges(self, lbl):
        return (
            _metric_line("celestia_mempool_namespace_txs", namespace=lbl),
            _metric_line(
                "celestia_mempool_namespace_size_bytes", namespace=lbl
            ),
        )

    def test_insert_and_commit_reconcile(self):
        mp = PriorityMempool()
        blob_tx = make_blob_tx([21], [100])
        lbl = square_journal.tx_namespace_label(blob_tx)
        assert lbl == square_journal.namespace_label(user_ns(21).to_bytes())
        assert mp.insert(blob_tx, 10, 0)
        assert mp.insert(b"\x01" * 16, 5, 0)  # normal tx -> `tx` bucket
        assert self._gauges(lbl) == (1, len(blob_tx))
        assert self._gauges("tx") == (1, 16)
        mp.update(1, [blob_tx])  # committed drop
        assert self._gauges(lbl) == (0, 0)
        assert self._gauges("tx") == (1, 16)

    def test_all_three_eviction_paths_decrement(self):
        mp = PriorityMempool(max_pool_bytes=600, ttl_num_blocks=2)
        txs = {t: make_blob_tx([t], [20]) for t in (31, 32, 33)}
        lbls = {
            t: square_journal.tx_namespace_label(raw)
            for t, raw in txs.items()
        }
        assert all(mp.insert(raw, t, 0) for t, raw in txs.items())
        size = len(txs[31])
        assert self._gauges(lbls[31]) == (1, size)

        # priority eviction: a big high-priority tx evicts ONLY the
        # lowest-priority resident (sizes tuned so one eviction fits).
        big = make_blob_tx([34], [180])
        assert mp.insert(big, 99, 1)
        assert mp.has_tx(txs[32]) and mp.has_tx(txs[33])
        assert not mp.has_tx(txs[31])
        assert self._gauges(lbls[31]) == (0, 0)
        assert _metric_line(
            "celestia_mempool_evictions_total",
            reason="priority", namespace=lbls[31],
        ) == 1

        # recheck eviction.
        mp.remove_tx(txs[32])
        assert self._gauges(lbls[32]) == (0, 0)
        assert _metric_line(
            "celestia_mempool_evictions_total",
            reason="recheck", namespace=lbls[32],
        ) == 1

        # ttl expiry (update()'s expired drop): tx 33 (height 0) ages
        # out at height 2; `big` (height 1) survives.
        mp.update(2, [])
        assert len(mp) == 1 and mp.has_tx(big)
        assert self._gauges(lbls[33]) == (0, 0)
        assert _metric_line(
            "celestia_mempool_evictions_total",
            reason="ttl", namespace=lbls[33],
        ) == 1
        for t, raw in txs.items():
            assert self._gauges(lbls[t]) == (0, 0)
        assert self._gauges(square_journal.tx_namespace_label(big)) == (
            1, len(big),
        )

    def test_infeasible_insert_evicts_nothing(self):
        # A(prio 1, small) + B(prio 9, big) fill the pool; C(prio 5)
        # cannot fit even after evicting A because B outranks it — the
        # old one-at-a-time loop destroyed A anyway, admitted nothing,
        # and ticked a priority eviction for it.
        a, b = make_blob_tx([61], [20]), make_blob_tx([62], [260])
        c = make_blob_tx([63], [40])
        mp = PriorityMempool(max_pool_bytes=len(a) + len(b))
        assert mp.insert(a, 1, 0) and mp.insert(b, 9, 0)
        assert not mp.insert(c, 5, 0)
        assert mp.has_tx(a) and mp.has_tx(b) and len(mp) == 2
        assert _metric_line(
            "celestia_mempool_evictions_total",
            reason="priority",
            namespace=square_journal.tx_namespace_label(a),
        ) is None

    def test_capped_tenants_share_the_other_bucket(self, monkeypatch):
        monkeypatch.setenv("CELESTIA_NAMESPACE_TOP_N", "1")
        square_journal._reset_for_tests()
        mp = PriorityMempool()
        a, b, c = (make_blob_tx([t], [30]) for t in (41, 42, 43))
        assert mp.insert(a, 1, 0) and mp.insert(b, 2, 0) and mp.insert(c, 3, 0)
        # First tenant took the only slot; the other two SUM into `other`.
        assert self._gauges(square_journal.OTHER_LABEL) == (
            2, len(b) + len(c),
        )


class TestE2eNamespaceView:
    def setup_method(self):
        square_journal._reset_for_tests()

    def test_namespace_baggage_labels_request_scoped_phases(self):
        ctx = new_context(layer="rpc").child(namespace="abc123")
        with use_context(ctx):
            with trace_span("ns_e2e_probe", e2e="submit"):
                pass
        assert _metric_line(
            "celestia_e2e_seconds_count", phase="submit", namespace="abc123"
        ) == 1

    def test_block_scoped_phases_never_carry_the_tenant(self):
        # The block adopts the first reaped tx's context, so its baggage
        # holds that tenant's namespace — but propose/commit measure the
        # WHOLE block and must stay unlabeled (billing a shared block to
        # the first-reaped tenant would fragment the phase series).
        ctx = new_context(layer="block").child(namespace="def456", height=3)
        with use_context(ctx):
            with trace_span("ns_block_probe", e2e="propose"):
                pass
            with trace_span("ns_block_probe2", e2e="commit"):
                pass
        for phase in ("propose", "commit"):
            assert _metric_line(
                "celestia_e2e_seconds_count", phase=phase, namespace="def456"
            ) is None
            assert _metric_line(
                "celestia_e2e_seconds_count", phase=phase
            ) >= 1

    def test_mempool_wait_and_total_carry_the_namespace(self):
        mp = PriorityMempool()
        raw = make_blob_tx([51], [40])
        lbl = square_journal.tx_namespace_label(raw)
        ctx = new_context(layer="rpc").child(namespace=lbl)
        assert mp.insert(raw, 1, 0, ctx=ctx)
        mp.reap()
        assert _metric_line(
            "celestia_e2e_seconds_count", phase="mempool_wait", namespace=lbl
        ) == 1
        mp.update(1, [raw])
        assert _metric_line(
            "celestia_e2e_seconds_count", phase="total", namespace=lbl
        ) == 1

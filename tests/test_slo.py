"""SLO burn-rate engine + quantile estimation (trace/slo.py,
trace/metrics.py snapshot/quantile).

Crypto-free on purpose: the judgment layer must be pinned even in slim
images (like the rest of the observability stack).  Engine tests inject
a fake clock so windows are deterministic; metric families use
test-unique names so the process-global registry never cross-talks.
"""

from __future__ import annotations

import pytest

from celestia_app_tpu.trace import slo
from celestia_app_tpu.trace.metrics import Registry, registry
from celestia_app_tpu.trace.slo import SLOEngine, SLOSpec


class TestQuantileEstimation:
    """Histogram.quantile + snapshot()/delta(): bucket-interpolated
    estimates against exact sample sets, usable standalone from the SLO
    engine (which builds its windows from exactly these)."""

    def _hist(self):
        r = Registry()
        return r.histogram("q_seconds", buckets=(0.1, 1.0, 10.0))

    def test_quantile_interpolates_within_bounding_bucket(self):
        h = self._hist()
        # 5 samples land in (0, 0.1], 4 in (0.1, 1.0], 1 in (1.0, 10.0].
        for v in [0.05] * 5 + [0.5] * 4 + [5.0]:
            h.observe(v, phase="total")
        # p50: rank 5 of 10 -> exactly fills bucket 1 -> its upper bound.
        assert h.quantile(0.5, phase="total") == 0.1
        # p90: rank 9 of 10 -> end of bucket 2 -> 1.0.
        assert abs(h.quantile(0.9, phase="total") - 1.0) < 1e-9
        # p99: rank 9.9 -> 0.9 into bucket 3's count of 1 -> 1 + 9*0.9.
        assert abs(h.quantile(0.99, phase="total") - 9.1) < 1e-9

    def test_inf_tail_clamps_to_largest_finite_bound(self):
        h = self._hist()
        for _ in range(10):
            h.observe(100.0)  # all in the +Inf tail
        assert h.quantile(0.99) == 10.0

    def test_empty_and_bad_q(self):
        h = self._hist()
        assert h.quantile(0.99) is None
        h.observe(0.5)
        with pytest.raises(ValueError):
            h.quantile(1.5)

    def test_label_selector_merges_subset_matches(self):
        h = self._hist()
        h.observe(0.05, phase="total", namespace="aa")
        h.observe(5.0, phase="total", namespace="bb")
        h.observe(0.05, phase="dispatch")
        snap = h.snapshot()
        # phase=total merges both per-namespace children...
        assert snap.count(phase="total") == 2
        # ...and the unlabeled selector merges everything.
        assert snap.count() == 3
        assert snap.count(phase="reap") == 0

    def test_snapshot_delta_isolates_the_window(self):
        h = self._hist()
        for _ in range(8):
            h.observe(0.05, phase="total")  # old, fast traffic
        s1 = h.snapshot()
        for _ in range(4):
            h.observe(5.0, phase="total")  # the window's slow burst
        delta = h.snapshot().delta(s1)
        assert delta.count(phase="total") == 4
        # Cumulative view is diluted; the window sees only the burst.
        assert h.quantile(0.5, phase="total") < 1.0
        assert delta.quantile(0.5, phase="total") > 1.0
        assert delta.fraction_over(1.0, phase="total") == 1.0

    def test_fraction_over_interpolates(self):
        h = self._hist()
        for _ in range(10):
            h.observe(0.5)  # all inside (0.1, 1.0]
        snap = h.snapshot()
        # Threshold 0.55 sits halfway through (0.1, 1.0]: interpolation
        # attributes half the bucket above it.
        assert abs(snap.fraction_over(0.55) - 0.5) < 1e-9
        assert snap.fraction_over(1.0) == 0.0
        assert snap.fraction_over(0.05) > 0.9

    def test_delta_tolerates_new_children_and_resets(self):
        h = self._hist()
        h.observe(0.5, k="4")
        s1 = h.snapshot()
        h.observe(0.5, k="8")  # child born inside the window
        delta = h.snapshot().delta(s1)
        assert delta.count(k="8") == 1
        assert delta.count(k="4") == 0


class _Clock:
    """Injectable monotonic clock."""

    def __init__(self):
        self.t = 1000.0

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


def _quantile_spec(metric: str, **over) -> SLOSpec:
    kw = dict(name="test_p99", metric=metric,
              labels=(("phase", "total"),), quantile=0.99, threshold=1.0)
    kw.update(over)
    return SLOSpec(**kw)


class TestSLOEngineQuantile:
    def test_good_traffic_burns_nothing(self, monkeypatch):
        monkeypatch.setenv("CELESTIA_SLO_FAST_S", "10")
        monkeypatch.setenv("CELESTIA_SLO_SLOW_S", "100")
        metric = "slo_t_good_seconds"
        hist = registry().histogram(metric, buckets=(0.1, 1.0, 10.0))
        clock = _Clock()
        eng = SLOEngine((_quantile_spec(metric),), clock=clock)
        eng.tick()
        for _ in range(50):
            hist.observe(0.05, phase="total")
        clock.advance(2)
        res = eng.tick()["test_p99"]
        assert res["state"] == "ok"
        assert res["burn"] == {"fast": 0.0, "slow": 0.0}
        assert res["window_count"] == 50
        assert res["current"] <= 0.1

    def test_sustained_badness_pages_fast_window(self, monkeypatch):
        monkeypatch.setenv("CELESTIA_SLO_FAST_S", "10")
        monkeypatch.setenv("CELESTIA_SLO_SLOW_S", "100")
        metric = "slo_t_bad_seconds"
        hist = registry().histogram(metric, buckets=(0.1, 1.0, 10.0))
        clock = _Clock()
        eng = SLOEngine((_quantile_spec(metric),), clock=clock)
        eng.tick()
        before = _counter_value("celestia_slo_violations_total",
                                slo="test_p99")
        for _ in range(20):
            hist.observe(5.0, phase="total")  # every event over threshold
        clock.advance(2)
        res = eng.tick()["test_p99"]
        # bad fraction 1.0 / budget 0.01 = burn 100 >= 14.4 -> page.
        assert res["state"] == "fast_burn"
        assert res["burn"]["fast"] == pytest.approx(100.0)
        assert eng.paged("test_p99")
        assert _counter_value(
            "celestia_slo_violations_total", slo="test_p99"
        ) == before + 1
        # Staying in fast_burn on the next tick is NOT a second page.
        clock.advance(1)
        hist.observe(5.0, phase="total")
        eng.tick()
        assert _counter_value(
            "celestia_slo_violations_total", slo="test_p99"
        ) == before + 1
        # Burn gauges published per window.
        text = registry().render()
        assert 'celestia_slo_burn_rate{slo="test_p99",window="fast"}' in text
        assert 'celestia_slo_burn_rate{slo="test_p99",window="slow"}' in text

    def test_fast_window_recovers_while_slow_still_burns(self, monkeypatch):
        monkeypatch.setenv("CELESTIA_SLO_FAST_S", "10")
        monkeypatch.setenv("CELESTIA_SLO_SLOW_S", "1000")
        metric = "slo_t_recover_seconds"
        hist = registry().histogram(metric, buckets=(0.1, 1.0, 10.0))
        clock = _Clock()
        spec = _quantile_spec(metric, slow_burn=50.0)
        eng = SLOEngine((spec,), clock=clock)
        eng.tick()
        for _ in range(20):
            hist.observe(5.0, phase="total")  # the incident
        clock.advance(2)
        assert eng.tick()["test_p99"]["state"] == "fast_burn"
        # The incident ends; good traffic resumes and the fast window
        # slides past the burst while the slow window still holds it.
        for step in range(6):
            clock.advance(4)
            for _ in range(10):
                hist.observe(0.05, phase="total")
            res = eng.tick()["test_p99"]
        assert res["burn"]["fast"] == 0.0
        assert res["burn"]["slow"] > 0.0
        assert res["state"] in ("ok", "slow_burn")

    def test_no_data_is_ok_not_error(self):
        eng = SLOEngine((_quantile_spec("slo_t_absent_seconds"),),
                        clock=_Clock())
        res = eng.tick()["test_p99"]
        assert res["state"] == "ok"
        assert res["burn"] == {"fast": 0.0, "slow": 0.0}


class TestSLOEngineGauge:
    def test_gauge_predicate_pages_and_recovers(self, monkeypatch):
        monkeypatch.setenv("CELESTIA_SLO_FAST_S", "10")
        monkeypatch.setenv("CELESTIA_SLO_SLOW_S", "40")
        metric = "slo_t_degraded"
        gauge = registry().gauge(metric)
        gauge.set(0.0, mode="staged")
        spec = SLOSpec(name="test_degraded", metric=metric, kind="gauge",
                       op="==", threshold=0.0, budget=0.01)
        clock = _Clock()
        eng = SLOEngine((spec,), clock=clock)
        assert eng.tick()["test_degraded"]["state"] == "ok"
        gauge.set(1.0, mode="staged")  # the breaker trips
        clock.advance(1)
        res = eng.tick()["test_degraded"]
        assert res["state"] == "fast_burn"
        assert res["violated_now"] == 1
        assert eng.paged("test_degraded")
        # Recovery: predicate holds again, the violated ticks age out of
        # the windows, the page clears.
        gauge.set(0.0, mode="staged")
        for _ in range(12):
            clock.advance(5)
            res = eng.tick()["test_degraded"]
        assert res["state"] == "ok"
        assert not eng.paged("test_degraded")

    def test_label_selector_restricts_samples(self):
        metric = "slo_t_occupancy"
        gauge = registry().gauge(metric)
        gauge.set(0.9, k="8")
        gauge.set(0.01, k="64")
        spec = SLOSpec(name="test_occ", metric=metric, kind="gauge",
                       op=">=", threshold=0.05,
                       labels=(("k", "8"),))
        eng = SLOEngine((spec,), clock=_Clock())
        assert eng.tick()["test_occ"]["violated_now"] == 0
        spec_all = SLOSpec(name="test_occ_all", metric=metric, kind="gauge",
                           op=">=", threshold=0.05)
        eng2 = SLOEngine((spec_all,), clock=_Clock())
        assert eng2.tick()["test_occ_all"]["violated_now"] == 1


class TestEngineSurface:
    def test_default_specs_evaluate_clean(self):
        eng = SLOEngine(clock=_Clock())
        res = eng.tick()
        assert {"e2e_total_p99", "dispatch_p99", "mempool_wait_p99",
                "square_occupancy", "degraded"} <= set(res)
        for r in res.values():
            assert "burn" in r and "state" in r, r

    def test_payload_and_health_block_shape(self):
        eng = SLOEngine(clock=_Clock())
        # Pre-tick: empty but well-formed (healthz must not explode on a
        # fresh process).
        assert eng.health_block() == {"status": "OK", "burning": []}
        eng.tick()
        payload = eng.payload()
        assert set(payload) == {"windows", "evaluated_unix_ms", "slos"}
        assert payload["slos"]["degraded"]["objective"]
        assert eng.health_block()["status"] in ("OK", "BURNING")

    def test_maybe_tick_rate_limit(self, monkeypatch):
        monkeypatch.setenv("CELESTIA_SLO_TICK_S", "100")
        clock = _Clock()
        eng = SLOEngine((), clock=clock)
        assert eng.maybe_tick() is True  # first tick always runs
        assert eng.maybe_tick() is False  # inside the interval
        clock.advance(101)
        assert eng.maybe_tick() is True

    def test_global_engine_reset(self):
        eng = slo._reset_for_tests()
        assert slo.engine() is eng


def _counter_value(name: str, **labels) -> float:
    for line in registry().render().splitlines():
        if line.startswith(name) and all(
            f'{k}="{v}"' in line for k, v in labels.items()
        ):
            return float(line.rsplit(" ", 1)[1])
    return 0.0

"""bench.py --metrics-out: Prometheus textfile + JSONL tables, no device.

Drives the writer with stub stage records (the shapes _run_child emits) so
the tier-1 suite pins the artifact format without ever touching a backend;
importing bench must stay jax-free for the same reason.
"""

from __future__ import annotations

import importlib.util
import json
import os


def _import_bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


STUB_RECS = [
    {"stage": "probe", "platform": "cpu", "n_devices": 8},
    {"stage": "parts@128", "mode": "parts", "k": 128,
     "parts_seconds": {"rs_dense": 0.5}, "tuned": None, "mb": 8.4,
     "wall_s": 3.0, "loadavg": 0.5, "platform": "cpu"},
    {"stage": "compute@128", "mode": "compute", "k": 128,
     "seconds_per_block": 0.0842, "mb": 8.4, "mb_per_s": 99.76,
     "wall_s": 2.0, "loadavg": 0.4, "platform": "cpu"},
    {"stage": "compute@128#2", "mode": "compute", "k": 128,
     "seconds_per_block": 0.088, "mb": 8.4, "mb_per_s": 95.45,
     "wall_s": 2.0, "loadavg": 0.4, "platform": "cpu"},
    {"stage": "stream@128", "mode": "stream", "k": 128,
     "seconds_per_block": 0.12, "mb": 8.4, "mb_per_s": 70.0,
     "wall_s": 2.5, "loadavg": 0.4, "platform": "cpu"},
    {"stage": "repair@256", "error": "RuntimeError: boom"},
    {"stage": "extend@512", "skipped": "budget", "remaining_s": 10.0},
    {"stage": "done"},
]


class TestMetricsOut:
    def test_writes_textfile_and_jsonl(self, tmp_path):
        bench = _import_bench()
        out_dir = tmp_path / "metrics"
        bench._write_metrics_out(
            str(out_dir), STUB_RECS, {"value": 99.76, "unit": "MB/s"}
        )
        prom = (out_dir / "bench_metrics.prom").read_text()
        assert '# TYPE celestia_bench_mb_per_s gauge' in prom
        assert ('celestia_bench_mb_per_s'
                '{k="128",mode="compute",stage="compute@128"} 99.76') in prom
        assert ('celestia_bench_mb_per_s'
                '{k="128",mode="stream",stage="stream@128"} 70') in prom
        assert ('celestia_bench_seconds_per_block'
                '{k="128",mode="compute",stage="compute@128"} 0.0842') in prom
        # the stability rerun keeps its own sample instead of overwriting
        assert ('celestia_bench_mb_per_s'
                '{k="128",mode="compute",stage="compute@128#2"} 95.45') in prom
        assert 'celestia_bench_errors_total{stage="repair@256"} 1' in prom
        assert 'celestia_bench_stages_skipped_total{stage="extend@512"} 1' in prom
        assert "celestia_bench_headline_mb_per_s 99.76" in prom
        rows = [
            json.loads(line)
            for line in (out_dir / "bench_rows.jsonl").read_text().splitlines()
        ]
        # probe/done bookkeeping rows are filtered; stage rows all land.
        assert {r["stage"] for r in rows} == {
            "parts@128", "compute@128", "compute@128#2", "stream@128",
            "repair@256", "extend@512",
        }
        assert all("ts_ns" in r for r in rows)

    def test_artifacts_survive_trace_off(self, tmp_path, monkeypatch):
        """--metrics-out is an explicit request: $CELESTIA_TRACE=off mutes
        the global layer, never these files."""
        bench = _import_bench()
        monkeypatch.setenv("CELESTIA_TRACE", "off")
        out_dir = tmp_path / "gated"
        bench._write_metrics_out(str(out_dir), STUB_RECS, {"value": 1.0})
        rows = (out_dir / "bench_rows.jsonl").read_text().strip().splitlines()
        assert len(rows) == 6

    def test_empty_run_still_writes_valid_files(self, tmp_path):
        bench = _import_bench()
        out_dir = tmp_path / "empty"
        bench._write_metrics_out(str(out_dir), [], {"value": 0})
        prom = (out_dir / "bench_metrics.prom").read_text()
        assert "celestia_bench_headline_mb_per_s 0" in prom
        assert (out_dir / "bench_rows.jsonl").read_text() == ""

    def test_metrics_out_flag_parsing(self, monkeypatch):
        bench = _import_bench()
        monkeypatch.delenv("BENCH_METRICS_OUT", raising=False)
        assert bench._parse_metrics_out([]) is None
        assert bench._parse_metrics_out(["--metrics-out", "/tmp/x"]) == "/tmp/x"
        monkeypatch.setenv("BENCH_METRICS_OUT", "/tmp/env")
        assert bench._parse_metrics_out([]) == "/tmp/env"
        # flag wins over env
        assert bench._parse_metrics_out(["--metrics-out", "/tmp/x"]) == "/tmp/x"
        # trailing flag without a value: fall back, don't crash
        assert bench._parse_metrics_out(["--metrics-out"]) == "/tmp/env"

"""Pinned RS generator matrices for both constructions (VERDICT r3 #8a).

The erasure code IS these matrices: a silent change to the Cantor-basis
derivation (gf/leopard.py), the field polynomials, the evaluation-point
layout, or the Vandermonde/inverse algebra would change parity bytes
chain-wide — consensus-critical drift that constant-share golden vectors
cannot catch (they are degenerate under any MDS code).  Each golden is
sha256 of the (k, k) generator in little-endian uint32, generated once
and committed (tests/golden/generators.json).

Reference seam: rsmt2d.NewLeoRSCodec at
/root/reference/pkg/appconsts/global_consts.go:92 — the leopard
construction's derived generator is the object that must eventually match
leopard's bit-for-bit once its hardcoded constants can be confirmed; any
in-repo drift from today's derivation fails here loudly.
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np
import pytest

from celestia_app_tpu.gf.rs import RSCodec

_GOLDENS = json.load(
    open(os.path.join(os.path.dirname(__file__), "golden", "generators.json"))
)


def _digest(codec: RSCodec) -> str:
    g = np.ascontiguousarray(codec.generator)
    return hashlib.sha256(g.astype("<u4").tobytes()).hexdigest()


@pytest.mark.parametrize("construction", ["vandermonde", "leopard"])
@pytest.mark.parametrize("k", [2, 4, 8, 16, 32, 64, 128])
def test_generator_matches_golden(construction, k):
    assert _digest(RSCodec(k, construction)) == _GOLDENS[f"{construction}/{k}"]


@pytest.mark.slow
@pytest.mark.parametrize("construction", ["vandermonde", "leopard"])
@pytest.mark.parametrize("k", [256, 512])
def test_generator_matches_golden_gf16(construction, k):
    assert _digest(RSCodec(k, construction)) == _GOLDENS[f"{construction}/{k}"]


def test_every_golden_has_a_test():
    ks = {2, 4, 8, 16, 32, 64, 128, 256, 512}
    assert set(_GOLDENS) == {
        f"{c}/{k}" for c in ("vandermonde", "leopard") for k in ks
    }

"""Bitsliced XOR RS lowering: bit-identity with the dense path.

kernels/rs_xor.py re-expresses the mod-2 generator matmul as uint32
XOR/AND-parity planes (arXiv 2108.02692's schedule on TPU register
shapes); its contract is byte-for-byte equality with kernels/rs.encode_axis
across every square size and BOTH RS constructions — that identity is what
lets the bench autotuner seat it as a pure perf choice.  Off-TPU the
kernel runs in interpret mode; hardware timing is bench.py's job (the
rs_xor parts candidate).
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from celestia_app_tpu.constants import (
    NAMESPACE_SIZE,
    PARITY_NAMESPACE_BYTES,
    SHARE_SIZE,
)
from celestia_app_tpu.gf.rs import RSCodec
from celestia_app_tpu.kernels.rs import encode_axis
from celestia_app_tpu.kernels.rs_xor import (
    encode_axis_xor,
    pack_data_words,
    pack_generator_words,
    xor_supported,
)


@pytest.mark.parametrize("construction", ["vandermonde", "leopard"])
@pytest.mark.parametrize("k", [2, 4, 16, 64, 128])
def test_bit_identity_both_axes(k, construction):
    """The ISSUE's golden matrix: every k the reference pins, both
    constructions, both contraction axes, against the dense lowering."""
    codec = RSCodec(k, construction)
    m = codec.field.m
    assert xor_supported(k, m)
    G_bits = jnp.asarray(codec.generator_bits())
    G_words = jnp.asarray(pack_generator_words(codec.generator_bits()))
    rng = np.random.default_rng(k * 7 + 1)
    data = jnp.asarray(rng.integers(0, 256, (3, k, 16), dtype=np.uint8))
    for axis in (0, 1):
        d = jnp.moveaxis(data, 1, axis)
        want = encode_axis(d, G_bits, m, axis)
        got = encode_axis_xor(d, G_words, m, axis, interpret=True)
        assert np.array_equal(np.asarray(got), np.asarray(want)), (
            k, construction, axis)


def test_unaligned_cols_are_padded():
    """cols not a multiple of the lane tile: padded in, sliced out."""
    k = 16
    codec = RSCodec(k, "vandermonde")
    m = codec.field.m
    G_bits = jnp.asarray(codec.generator_bits())
    G_words = jnp.asarray(pack_generator_words(codec.generator_bits()))
    rng = np.random.default_rng(5)
    # batch=1, width 72 -> cols = 72, far below the 256-lane tile
    data = jnp.asarray(rng.integers(0, 256, (1, k, 72), dtype=np.uint8))
    want = encode_axis(data, G_bits, m, 1)
    got = encode_axis_xor(data, G_words, m, 1, interpret=True)
    assert np.array_equal(np.asarray(got), np.asarray(want))


def test_generator_packing_bit_order():
    """Word w bit u of packed row i == G_bits[i, 32w + u] — the exact
    contraction order pack_data_words uses, else every parity is wrong."""
    codec = RSCodec(4, "vandermonde")
    G = codec.generator_bits()  # (32, 32)
    W = pack_generator_words(G)  # (1, 32)
    for i in range(G.shape[0]):
        for u in range(G.shape[1]):
            assert (int(W[u // 32, i]) >> (u % 32)) & 1 == int(G[i, u])


def test_data_packing_matches_unpack_order():
    """pack_data_words' uint32 bit 8q+t must hold the same contraction
    row the dense path's byte->bit unpack produces (j*m + 8b + t)."""
    rng = np.random.default_rng(9)
    n, bps, cols = 2, 2, 3  # m = 16
    x = jnp.asarray(rng.integers(0, 256, (n, bps, cols), dtype=np.uint8))
    words = np.asarray(pack_data_words(x))  # (1, cols)
    bits = np.asarray(
        (x[:, :, None, :] >> jnp.arange(8, dtype=jnp.uint8)[None, None, :, None])
        & 1
    ).reshape(n * bps * 8, cols)
    for c in range(cols):
        for r in range(n * bps * 8):
            assert (int(words[r // 32, c]) >> (r % 32)) & 1 == bits[r, c]


def test_encode_fn_env_seam(monkeypatch):
    """$CELESTIA_RS_XOR=on routes the library encode through the XOR
    kernel (interpret mode off-TPU) and the extension stays byte-exact."""
    from celestia_app_tpu.kernels.rs import extend_square_fn

    k = 4
    rng = np.random.default_rng(11)
    ods = rng.integers(0, 256, (k, k, 64), dtype=np.uint8)
    monkeypatch.delenv("CELESTIA_RS_XOR", raising=False)
    want = np.asarray(extend_square_fn(k)(jnp.asarray(ods)))
    monkeypatch.setenv("CELESTIA_RS_XOR", "on")
    got = np.asarray(extend_square_fn(k)(jnp.asarray(ods)))
    assert np.array_equal(got, want)


@pytest.mark.slow
def test_epilogue_kernel_extends_and_hashes(k=2):
    """The fused leaf-hash epilogue: the Pallas kernel's bottom shares
    AND their parity-namespace leaf digests match the staged composition
    (interpret mode — ~90 s of unrolled SHA rounds, hence the slow tier;
    the fast tier pins the library fused_epi mode's composition path in
    tests/test_fused_pipeline.py)."""
    from celestia_app_tpu.kernels.nmt import leaf_digests
    from celestia_app_tpu.kernels.rs_xor import extend_leaf_digests

    codec = RSCodec(k, "vandermonde")
    m = codec.field.m
    G_bits = jnp.asarray(codec.generator_bits())
    G_words = jnp.asarray(pack_generator_words(codec.generator_bits()))
    rng = np.random.default_rng(13)
    ods = jnp.asarray(
        rng.integers(0, 256, (k, k, SHARE_SIZE), dtype=np.uint8)
    )
    top = jnp.concatenate([ods, encode_axis(ods, G_bits, m, 1)], axis=1)
    want_bottom = encode_axis(top, G_bits, m, 0)
    parity = jnp.frombuffer(PARITY_NAMESPACE_BYTES, dtype=jnp.uint8)
    par_ns = jnp.broadcast_to(parity, (k, 2 * k, NAMESPACE_SIZE))
    _, _, want_hashes = leaf_digests(par_ns, want_bottom)
    bottom, hashes = extend_leaf_digests(top, G_words, m, interpret=True)
    assert np.array_equal(np.asarray(bottom), np.asarray(want_bottom))
    assert np.array_equal(np.asarray(hashes), np.asarray(want_hashes))

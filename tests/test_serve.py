"""The batched proof-serving plane (serve/): cache tiers, sampler queue,
chaos fallback, the DAS surface on the serving planes, loadgen smoke.

Runs without the signing stack: squares are deterministic synthetic
blocks admitted straight into a ForestCache; the full ServingNode
retention/commit flow is a crypto-gated test (importorskip).
"""

from __future__ import annotations

import importlib.util
import json
import os
import threading
import urllib.request

import numpy as np
import pytest

from celestia_app_tpu.constants import NAMESPACE_SIZE, SHARE_SIZE
from celestia_app_tpu.da.eds import ExtendedDataSquare
from celestia_app_tpu.serve.api import DasProvider, UnknownHeight, render
from celestia_app_tpu.serve.cache import ForestCache
from celestia_app_tpu.serve.sampler import ProofSampler, serve_mode
from celestia_app_tpu.trace.metrics import registry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def det_square(k: int, seed: int = 1) -> np.ndarray:
    rng = np.random.default_rng(seed)
    ns = np.sort(rng.integers(0, 128, k * k).astype(np.uint8))
    ods = rng.integers(0, 256, (k * k, SHARE_SIZE), dtype=np.uint8)
    ods[:, :NAMESPACE_SIZE] = 0
    ods[:, NAMESPACE_SIZE - 1] = ns
    return ods.reshape(k, k, SHARE_SIZE)


def make_eds(k: int = 4, seed: int = 1) -> ExtendedDataSquare:
    return ExtendedDataSquare.compute(det_square(k, seed))


class TestForestCache:
    def test_lru_eviction_spills_then_drops(self):
        cache = ForestCache(heights=2, spill=1)
        e1 = cache.put(1, make_eds(seed=1))
        e2 = cache.put(2, make_eds(seed=2))
        assert e1.device_resident and e2.device_resident
        cache.put(3, make_eds(seed=3))  # evicts 1 -> host tier
        entry, tier = cache.get(1)
        assert tier == "host" and entry is e1 and not e1.device_resident
        cache.put(4, make_eds(seed=4))  # evicts 2 -> host; 1 drops (spill=1)
        assert cache.get(1) == (None, "miss")
        _, tier2 = cache.get(2)
        assert tier2 == "host"
        stats = cache.stats()
        assert stats["device_heights"] == [3, 4]
        assert stats["host_heights"] == [2]
        assert stats["last_eviction"] == 2
        assert stats["misses"] >= 1
        assert stats["hit_ratio"] is not None

    def test_lookup_refreshes_lru_order(self):
        cache = ForestCache(heights=2, spill=2)
        cache.put(1, make_eds(seed=1))
        cache.put(2, make_eds(seed=2))
        cache.get(1)  # 1 is now most-recent
        cache.put(3, make_eds(seed=3))
        assert cache.get(1)[1] == "device"
        assert cache.get(2)[1] == "host"

    def test_reput_promotes_from_spill(self):
        cache = ForestCache(heights=1, spill=2)
        eds1 = make_eds(seed=1)
        cache.put(1, eds1)
        cache.put(2, make_eds(seed=2))  # spills 1
        assert cache.get(1)[1] == "host"
        cache.put(1, make_eds(seed=1))  # fresh admission promotes
        assert cache.get(1)[1] == "device"

    def test_retention_disabled_returns_none(self, monkeypatch):
        monkeypatch.setenv("CELESTIA_SERVE_HEIGHTS", "0")
        cache = ForestCache()
        assert cache.put(1, make_eds()) is None

    def test_hit_miss_counters_tick(self):
        cache = ForestCache(heights=1, spill=1)
        cache.put(1, make_eds())
        before_hits = _counter_value(
            "celestia_serve_cache_hits_total", tier="device"
        )
        before_miss = _counter_value("celestia_serve_cache_misses_total")
        cache.get(1)
        cache.get(99)
        assert _counter_value(
            "celestia_serve_cache_hits_total", tier="device"
        ) == before_hits + 1
        assert _counter_value(
            "celestia_serve_cache_misses_total"
        ) == before_miss + 1


def _counter_value(name: str, **labels) -> float:
    """Sum over samples matching the label SUBSET (a family may carry
    more labels than the query — e.g. proofs_served's capped namespace)."""
    metric = registry().get(name)
    if metric is None:
        return 0.0
    return sum(
        value for sample_labels, value in metric.samples()
        if all(sample_labels.get(k) == v for k, v in labels.items())
    )


class TestSamplerQueue:
    def test_concurrent_submitters_are_batched(self):
        cache = ForestCache(heights=1, spill=1)
        entry = cache.put(1, make_eds(k=4))
        sampler = ProofSampler()
        root = entry.eds.data_root()
        results: dict[int, object] = {}
        errors: list[Exception] = []
        barrier = threading.Barrier(6)

        def worker(i):
            try:
                barrier.wait(timeout=10)
                results[i] = sampler.share_proof(entry, i % 8, (i * 3) % 8)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert not errors
        assert len(results) == 6
        for i, proof in results.items():
            assert proof.verify(root)
            assert proof == sampler.host_proof(entry, i % 8, (i * 3) % 8)

    def test_host_mode_env_pins_the_fallback_path(self, monkeypatch):
        monkeypatch.setenv("CELESTIA_SERVE_MODE", "host")
        assert serve_mode() == "host"
        cache = ForestCache(heights=1, spill=1)
        entry = cache.put(1, make_eds(k=4))
        proofs = ProofSampler().sample_batch(entry, [(1, 2), (7, 0)])
        monkeypatch.delenv("CELESTIA_SERVE_MODE")
        batched = ProofSampler().sample_batch(entry, [(1, 2), (7, 0)])
        assert proofs == batched  # the seam's whole point

    def test_bad_coordinates_raise_before_any_dispatch(self):
        cache = ForestCache(heights=1, spill=1)
        entry = cache.put(1, make_eds(k=4))
        with pytest.raises(ValueError):
            ProofSampler().sample_batch(entry, [(0, 0), (8, 0)])


class TestChaosFallback:
    def test_injected_proof_fault_served_by_host_path_bit_identical(self):
        from celestia_app_tpu import chaos
        from celestia_app_tpu.chaos import degrade

        cache = ForestCache(heights=1, spill=1)
        entry = cache.put(1, make_eds(k=4, seed=9))
        sampler = ProofSampler()
        coords = [(0, 1), (5, 6), (3, 3)]
        baseline = sampler.sample_batch(entry, coords)
        before = _counter_value(
            "celestia_recoveries_total", seam="proof.serve", outcome="degraded"
        )
        chaos.install("seed=2,proof_fail=1.0")
        try:
            under_chaos = sampler.sample_batch(entry, coords)
        finally:
            chaos.uninstall()
            degrade.reset_for_tests()
        assert under_chaos == baseline
        assert _counter_value(
            "celestia_recoveries_total", seam="proof.serve", outcome="degraded"
        ) == before + 1
        assert _counter_value(
            "celestia_chaos_injections_total", seam="proof.serve"
        ) > 0

    def test_sampling_drill_smoke(self):
        """The chaos_soak sampling drill in tier-1 (small fixed seed)."""
        spec = importlib.util.spec_from_file_location(
            "chaos_soak", os.path.join(REPO_ROOT, "scripts", "chaos_soak.py")
        )
        soak = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(soak)
        result = soak.run_sampling_drill(k=4, samples=24)
        assert result["ok"], result
        assert result["bit_identical"] and result["all_verify"]
        assert result["injections"] > 0


class _ServeStubNode:
    """Crypto-free node surface for the REST/gRPC planes, carrying a live
    DasProvider over one cached deterministic square."""

    chain_id = "serve-test"

    def __init__(self):
        self.cache = ForestCache(heights=2, spill=2)
        self.eds = make_eds(k=4, seed=11)
        self.cache.put(1, self.eds)
        self._provider = DasProvider(cache=self.cache)

    def das_provider(self):
        return self._provider


class TestDasPlanes:
    """GET /das/* on the shared handler + the gRPC Das service: one
    payload renderer, byte-identical everywhere."""

    @pytest.fixture()
    def planes(self):
        pytest.importorskip("grpc")
        from celestia_app_tpu.rpc.api_gateway import serve_api
        from celestia_app_tpu.rpc.grpc_plane import GrpcNode, serve_grpc
        from celestia_app_tpu.trace.exposition import (
            register_das_provider,
            unregister_das_provider,
        )

        node = _ServeStubNode()
        register_das_provider(node.das_provider())
        gw = serve_api(node)
        plane = serve_grpc(node)
        client = GrpcNode(plane.target)
        try:
            yield node, gw, plane, client
        finally:
            client.close()
            gw.stop()
            plane.stop()
            unregister_das_provider()

    def test_rest_grpc_debug_and_grpc_service_byte_identical(self, planes):
        node, gw, plane, client = planes
        path = "/das/share_proof?height=1&row=2&col=5"
        bodies = []
        for url in (gw.url, plane.debug_url):
            with urllib.request.urlopen(url + path, timeout=10) as resp:
                assert resp.status == 200
                bodies.append(resp.read())
        assert bodies[0] == bodies[1]
        # The real gRPC service carries the SAME canonical bytes.
        assert client.share_proof_bytes(1, 2, 5) == bodies[0]
        payload = json.loads(bodies[0])
        assert payload["height"] == 1 and payload["square_size"] == 4
        # The served proof verifies against the committed data root.
        from celestia_app_tpu.rpc.codec import share_proof_from_json

        proof = share_proof_from_json(payload["proof"])
        assert proof.verify(bytes.fromhex(payload["data_root"]))

    def test_column_axis_on_every_plane(self, planes):
        node, gw, plane, client = planes
        path = "/das/share_proof?height=1&row=6&col=3&axis=col"
        bodies = []
        for url in (gw.url, plane.debug_url):
            with urllib.request.urlopen(url + path, timeout=10) as resp:
                bodies.append(resp.read())
        assert bodies[0] == bodies[1]
        assert client.share_proof_bytes(1, 6, 3, axis="col") == bodies[0]
        payload = json.loads(bodies[0])
        assert payload["axis"] == "col"
        from celestia_app_tpu.rpc.codec import share_proof_from_json

        proof = share_proof_from_json(payload["proof"])
        assert proof.verify(bytes.fromhex(payload["data_root"]))
        # Column roots occupy the second 2k leaves of the data-root tree.
        assert proof.row_proof.start_row == 2 * 4 + 3

    def test_namespace_route_identity_and_verify(self, planes):
        node, gw, plane, client = planes
        ns_hex = bytes(node.eds.ods_namespaces()[3].tobytes()).hex()
        path = f"/das/shares?height=1&namespace={ns_hex}"
        bodies = []
        for url in (gw.url, plane.debug_url):
            with urllib.request.urlopen(url + path, timeout=10) as resp:
                bodies.append(resp.read())
        assert bodies[0] == bodies[1]
        assert client.shares_by_namespace_bytes(1, ns_hex) == bodies[0]
        payload = json.loads(bodies[0])
        assert payload["found"] and payload["shares"] >= 1
        from celestia_app_tpu.rpc.codec import share_proof_from_json

        proof = share_proof_from_json(payload["proof"])
        assert proof.verify(bytes.fromhex(payload["data_root"]))

    def test_absent_namespace_answers_found_false(self, planes):
        node, gw, plane, client = planes
        payload = client.shares_by_namespace(1, "ee" * NAMESPACE_SIZE)
        assert payload["found"] is False and payload["proof"] is None

    def test_error_statuses(self, planes):
        import grpc

        node, gw, plane, client = planes
        # Unknown height: 404 on HTTP, NOT_FOUND on gRPC.
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                gw.url + "/das/share_proof?height=9&row=0&col=0", timeout=10
            )
        assert exc.value.code == 404
        with pytest.raises(grpc.RpcError) as gexc:
            client.share_proof_bytes(9, 0, 0)
        assert gexc.value.code() == grpc.StatusCode.NOT_FOUND
        # Bad params: 400 / INVALID_ARGUMENT.
        with pytest.raises(urllib.error.HTTPError) as exc2:
            urllib.request.urlopen(
                gw.url + "/das/share_proof?height=1&row=zap&col=0", timeout=10
            )
        assert exc2.value.code == 400
        with pytest.raises(grpc.RpcError) as gexc2:
            client.shares_by_namespace_bytes(1, "nothex")
        assert gexc2.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        # Out-of-square coordinate: 400, not a 500.
        with pytest.raises(urllib.error.HTTPError) as exc3:
            urllib.request.urlopen(
                gw.url + "/das/share_proof?height=1&row=0&col=99", timeout=10
            )
        assert exc3.value.code == 400

    def test_adversary_detections_map_to_typed_grpc_statuses(self, planes):
        """The gRPC plane must carry the same detection semantics the
        HTTP planes express as 410/502: a withheld share answers
        FAILED_PRECONDITION (ShareWithheld is a LookupError — without
        the typed clause it escaped as an opaque UNKNOWN) and a
        tampered square answers DATA_LOSS, never INVALID_ARGUMENT
        (BadProofDetected subclasses ValueError)."""
        import grpc

        from celestia_app_tpu import chaos

        node, gw, plane, client = planes
        chaos.install("seed=11,withhold_frac=0.25")
        try:
            adv = chaos.active_adversary()
            withheld = adv.withheld_set(1, 8)  # k=4 -> 8x8 EDS
            hit = next(iter(withheld))
            with pytest.raises(grpc.RpcError) as gexc:
                client.share_proof_bytes(1, *hit)
            assert gexc.value.code() == grpc.StatusCode.FAILED_PRECONDITION
            assert "withholding detected" in gexc.value.details()
            # The HTTP twin of the same coordinate: 410 Gone.
            with pytest.raises(urllib.error.HTTPError) as hexc:
                urllib.request.urlopen(
                    gw.url + "/das/share_proof?height=1"
                    f"&row={hit[0]}&col={hit[1]}",
                    timeout=10,
                )
            assert hexc.value.code == 410
        finally:
            chaos.uninstall()
        chaos.install("seed=11,wrong_root=1")
        try:
            with pytest.raises(grpc.RpcError) as gexc2:
                client.share_proof_bytes(1, 0, 0)
            assert gexc2.value.code() == grpc.StatusCode.DATA_LOSS
        finally:
            chaos.uninstall()

    def test_healing_in_progress_is_retryable_on_every_plane(self, planes):
        """ISSUE-12 satellite: a sample arriving mid-heal answers a
        RETRYABLE status — 503 + Retry-After on the HTTP twins
        (byte-identical bodies) and UNAVAILABLE on the gRPC Das service
        — never the terminal 410/502 the detections answer."""
        import grpc

        from celestia_app_tpu.serve.heal import HealingEngine

        node, gw, plane, client = planes
        engine = HealingEngine(
            node.das_provider(), name="planes", retry_after_s=2.0
        )
        try:
            assert engine.note("withheld", 1)  # mark mid-heal, no worker
            bodies = []
            for url in (gw.url, plane.debug_url):
                with pytest.raises(urllib.error.HTTPError) as exc:
                    urllib.request.urlopen(
                        url + "/das/share_proof?height=1&row=0&col=0",
                        timeout=10,
                    )
                assert exc.value.code == 503
                assert exc.value.headers.get("Retry-After") == "2"
                bodies.append(exc.value.read())
            assert bodies[0] == bodies[1]
            payload = json.loads(bodies[0])
            assert payload["healing"] is True
            with pytest.raises(grpc.RpcError) as gexc:
                client.share_proof_bytes(1, 0, 0)
            assert gexc.value.code() == grpc.StatusCode.UNAVAILABLE
            assert "healed" in gexc.value.details()
        finally:
            engine.close()

    def test_no_provider_is_503(self):
        from celestia_app_tpu.trace.exposition import (
            handle_observability_get,
            unregister_das_provider,
        )

        unregister_das_provider()
        status, _, body = handle_observability_get(
            "/das/share_proof?height=1&row=0&col=0"
        )
        assert status == 503
        assert b"no DAS provider" in body

    def test_proofs_served_counter_carries_the_plane(self, planes):
        node, gw, plane, client = planes
        before = _counter_value(
            "celestia_proofs_served_total", plane="rest", kind="share_proof"
        )
        urllib.request.urlopen(
            gw.url + "/das/share_proof?height=1&row=0&col=0", timeout=10
        ).read()
        assert _counter_value(
            "celestia_proofs_served_total", plane="rest", kind="share_proof"
        ) == before + 1
        gbefore = _counter_value(
            "celestia_proofs_served_total", plane="grpc", kind="share_proof"
        )
        client.share_proof_bytes(1, 0, 0)
        assert _counter_value(
            "celestia_proofs_served_total", plane="grpc", kind="share_proof"
        ) == gbefore + 1


class TestProviderRebuild:
    def test_miss_routes_through_rebuild_and_readmits(self):
        eds = make_eds(k=4, seed=21)
        calls = []

        def rebuild(height):
            calls.append(height)
            return eds if height == 7 else None

        provider = DasProvider(
            cache=ForestCache(heights=2, spill=2), rebuild=rebuild
        )
        payload = provider.share_proof_payload(7, 1, 1)
        assert calls == [7]
        assert payload["data_root"] == eds.data_root().hex()
        # Re-admitted: the second query is a cache hit, no rebuild.
        provider.share_proof_payload(7, 2, 2)
        assert calls == [7]
        with pytest.raises(UnknownHeight):
            provider.share_proof_payload(8, 0, 0)

    def test_payload_is_plane_free_and_canonical(self):
        provider = DasProvider(cache=ForestCache(heights=1, spill=1))
        provider.cache.put(3, make_eds(k=4, seed=22))
        payload = provider.share_proof_payload(3, 0, 0)
        blob = render(payload)
        assert json.loads(blob) == payload
        assert blob == render(json.loads(blob))  # canonical fixpoint


class TestSloAndHealth:
    def test_default_slos_include_proof_p99(self):
        from celestia_app_tpu.trace.slo import default_slos

        spec = {s.name: s for s in default_slos()}["proof_p99"]
        assert spec.metric == "celestia_proof_latency_seconds"
        assert dict(spec.labels) == {"phase": "total"}

    def test_burn_rate_engine_evaluates_proof_p99(self, monkeypatch):
        """The acceptance wire: served samples land on the histogram the
        engine's default proof_p99 spec judges every tick."""
        from celestia_app_tpu.trace import slo

        monkeypatch.setenv("CELESTIA_SLO_TICK_S", "0")
        engine = slo._reset_for_tests()
        try:
            cache = ForestCache(heights=1, spill=1)
            entry = cache.put(1, make_eds(k=4, seed=41))
            ProofSampler().share_proof(entry, 0, 0)
            engine.tick()  # snapshot baseline
            ProofSampler().share_proof(entry, 1, 1)
            results = engine.tick()
            assert results["proof_p99"]["state"] in ("ok", "fast_burn")
            assert "burn" in results["proof_p99"]
            assert results["proof_p99"]["kind"] == "quantile"
        finally:
            slo._reset_for_tests()

    def test_latency_histogram_has_all_phases(self):
        cache = ForestCache(heights=1, spill=1)
        entry = cache.put(1, make_eds(k=4, seed=31))
        ProofSampler().share_proof(entry, 0, 0)
        hist = registry().get("celestia_proof_latency_seconds")
        phases = {
            dict(key).get("phase")
            for key, _ in hist.snapshot().children.items()
        }
        assert {"queue_wait", "gather", "assemble", "total"} <= phases


class TestLoadgenSmoke:
    def test_loadgen_round_trip_and_artifacts(self, tmp_path):
        spec = importlib.util.spec_from_file_location(
            "das_loadgen", os.path.join(REPO_ROOT, "scripts", "das_loadgen.py")
        )
        lg = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lg)
        out = tmp_path / "metrics"
        round_out = tmp_path / "DAS_r09.json"
        rc = lg.main([
            "--heights", "2", "--k", "4", "--samples", "60", "--threads", "3",
            "--verify", "20",
            "--metrics-out", str(out), "--round-out", str(round_out),
        ])
        assert rc == 0
        record = json.loads(round_out.read_text())
        assert record["n"] == 9
        assert record["proofs_per_s"] > 0
        assert record["proof_p99_ms"] >= record["proof_p50_ms"]
        prom = (out / "das_loadgen.prom").read_text()
        assert "celestia_proof_latency_seconds" in prom
        # (The record's bench_trend das-series seat is pinned in
        # tests/test_bench_trend.py::TestDasSeries.)


class TestServingNodeFlow:
    def test_commit_retention_and_jsonrpc_methods(self):
        """The full crypto-gated flow: blocks commit -> heights retained
        -> rpc_get_share_proof serves them -> /healthz reports the cache."""
        pytest.importorskip("cryptography")
        from celestia_app_tpu.rpc.server import ServingNode
        from celestia_app_tpu.shares.namespace import Namespace
        from celestia_app_tpu.shares.sparse import Blob
        from celestia_app_tpu.testutil.testnode import (
            deterministic_genesis,
            funded_keys,
        )
        from celestia_app_tpu.user import TxClient

        keys = funded_keys(2)
        node = ServingNode(genesis=deterministic_genesis(keys), keys=keys)
        client = TxClient(node, keys)
        blob = Blob(Namespace.v0(b"\x07" * 10), b"\xab" * 2048)
        client.submit_pay_for_blob([blob])
        height = node.app.height
        stats = node.serve_cache.stats()
        assert height in stats["device_heights"]
        payload = node.rpc_get_share_proof(height, 0, 0)
        from celestia_app_tpu.rpc.codec import share_proof_from_json

        proof = share_proof_from_json(payload["proof"])
        root = bytes.fromhex(payload["data_root"])
        assert proof.verify(root)
        # The served root IS the committed block's data hash.
        assert root == node._blocks_by_height[height][0].hash
        # Namespace query for the submitted blob.
        ns_payload = node.rpc_get_shares_by_namespace(
            height, blob.namespace.to_bytes().hex()
        )
        assert ns_payload["found"] and ns_payload["shares"] >= 4
        nsp = share_proof_from_json(ns_payload["proof"])
        assert nsp.verify(root)
        # /healthz layer shape.
        snap = node.health_snapshot()
        assert snap["serve"]["device_heights"] == stats["device_heights"]
        assert snap["serve"]["hit_ratio"] is not None


class TestReadPathNamespaceAccounting:
    """ISSUE-10 satellite: the read path joins the PR 4 per-tenant
    accounting — celestia_proofs_served_total carries the payload's
    capped namespace, celestia_proof_latency_seconds{phase=total} the
    served share's."""

    def test_share_proof_payload_namespace_label(self):
        from celestia_app_tpu.serve.api import payload_namespace_label
        from celestia_app_tpu.trace.square_journal import (
            capped_namespace_label,
        )

        ns = bytes(28) + b"\x07"
        # The label routes through the process-wide cap: whatever the cap
        # says (admitted or folded to `other`) is what the payload gets.
        want = capped_namespace_label("7")
        assert payload_namespace_label(
            {"proof": {"namespace": ns.hex()}}
        ) == want
        assert payload_namespace_label({"namespace": ns.hex()}) == want
        # No namespace, absent payload, junk hex: the reserved bucket.
        assert payload_namespace_label({}) == "other"
        assert payload_namespace_label(None) == "other"
        assert payload_namespace_label({"namespace": "zz"}) == "other"
        # Parity shares are not a tenant: 0xff..ff folds to `other`,
        # matching the sampler's _proof_namespace_label twin (a uniform
        # DAS workload is 3/4 parity — it must not burn a capped slot or
        # split this counter from the latency histogram).
        from celestia_app_tpu.constants import PARITY_NAMESPACE_BYTES

        parity_hex = PARITY_NAMESPACE_BYTES.hex()
        assert payload_namespace_label(
            {"namespace": parity_hex}
        ) == "other"
        assert payload_namespace_label(
            {"proof": {"namespace": parity_hex}}
        ) == "other"

    def test_served_counter_carries_capped_namespace(self):
        from celestia_app_tpu.serve.api import count_served
        from celestia_app_tpu.trace.square_journal import (
            capped_namespace_label,
        )

        ns = bytes(28) + b"\x2a"
        want = capped_namespace_label("2a")
        before = _counter_value(
            "celestia_proofs_served_total",
            plane="test", kind="share_proof", namespace=want,
        )
        count_served("test", "share_proof",
                     {"proof": {"namespace": ns.hex()}})
        assert _counter_value(
            "celestia_proofs_served_total",
            plane="test", kind="share_proof", namespace=want,
        ) == before + 1

    def test_latency_total_labeled_by_served_namespace(self):
        cache = ForestCache(heights=1, spill=1)
        entry = cache.put(11, make_eds(k=2))
        sampler = ProofSampler()
        hist = registry().get("celestia_proof_latency_seconds")
        snap_before = hist.snapshot() if hist is not None else None
        proof = sampler.share_proof(entry, 0, 0)
        assert proof.verify(entry.eds.data_root())
        from celestia_app_tpu.trace.square_journal import (
            capped_namespace_label,
            namespace_label,
        )

        label = capped_namespace_label(namespace_label(proof.namespace))
        hist = registry().get("celestia_proof_latency_seconds")
        snap = hist.snapshot()
        if snap_before is not None:
            snap = snap.delta(snap_before)
        assert snap.count(phase="total", namespace=label) == 1
        # A parity-quadrant sample folds into the reserved bucket.
        other_before = snap.count(phase="total", namespace="other")
        sampler.share_proof(entry, 3, 3)  # parity quadrant at k=2
        snap2 = hist.snapshot()
        if snap_before is not None:
            snap2 = snap2.delta(snap_before)
        assert snap2.count(phase="total", namespace="other") == other_before + 1

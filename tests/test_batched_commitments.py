"""Device-batched commitments must match the host path bit-for-bit."""

import numpy as np
import pytest

from celestia_app_tpu.inclusion import create_commitment
from celestia_app_tpu.inclusion.batched import create_commitments_batched
from celestia_app_tpu.modules.blob.types import (
    BlobTxError,
    validate_blob_txs_batched,
)
from celestia_app_tpu.shares.namespace import Namespace
from celestia_app_tpu.shares.sparse import Blob
from celestia_app_tpu.tx.envelopes import BlobTx, unmarshal_blob_tx

RNG = np.random.default_rng(66)


def user_ns(tag: int) -> Namespace:
    return Namespace.v0(bytes([tag]) * 10)


def rand_blob(tag: int, size: int) -> Blob:
    return Blob(user_ns(tag), RNG.integers(0, 256, size, dtype=np.uint8).tobytes())


class TestBatchedCommitments:
    def test_matches_host_path(self):
        blobs = [
            rand_blob(1, 100),        # 1 share
            rand_blob(2, 478 * 3),    # 3 shares -> chunks [2, 1]
            rand_blob(3, 478 * 170),  # 170 shares -> 42x4 + 2
            rand_blob(4, 5000),
        ]
        batched = create_commitments_batched(blobs)
        assert batched == [create_commitment(b) for b in blobs]

    def test_empty(self):
        assert create_commitments_batched([]) == []

    def test_validate_batched_mixed(self):
        from tests.test_tx_blob import signed_pfb_blob_tx

        good = unmarshal_blob_tx(signed_pfb_blob_tx((rand_blob(5, 900),)))
        tampered = BlobTx(good.tx, (rand_blob(5, 900),))  # new random data
        out = validate_blob_txs_batched([good, tampered])
        assert not isinstance(out[0], BlobTxError)
        assert isinstance(out[1], BlobTxError)

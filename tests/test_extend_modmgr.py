"""ExtendBlock entry + versioned module manager tests."""

import pytest

from celestia_app_tpu.app.extend_block import extend_block, is_empty_block
from celestia_app_tpu.app.module_manager import ModuleManager, VersionedModule
from celestia_app_tpu.da import DataAvailabilityHeader
from celestia_app_tpu.shares.namespace import Namespace
from celestia_app_tpu.shares.sparse import Blob
from celestia_app_tpu.tx.envelopes import BlobTx


def test_extend_block_roundtrip():
    btx = BlobTx(b"\x01" * 40, (Blob(Namespace.v0(b"\x05" * 10), b"d" * 3000),)).marshal()
    eds = extend_block([btx])
    assert eds is not None
    dah = DataAvailabilityHeader.from_eds(eds)
    assert len(dah.hash()) == 32


def test_empty_block():
    assert is_empty_block([])
    assert extend_block([]) is None


class TestModuleManager:
    def test_active_sets_by_version(self):
        mm = ModuleManager()
        v1 = set(mm.active(1))
        v2 = set(mm.active(2))
        assert "blobstream" in v1 and "blobstream" not in v2
        assert "signal" not in v1 and "signal" in v2
        assert "minfee" not in v1 and "minfee" in v2
        assert {"auth", "bank", "mint", "blob"} <= (v1 & v2)

    def test_migrations_run_for_newly_active(self):
        from celestia_app_tpu.state.store import KVStore

        class Ctx:
            store = KVStore()

        mm = ModuleManager()
        migrated = mm.run_migrations(Ctx(), 1, 2)
        assert set(migrated) == {"signal", "minfee"}
        assert mm.run_migrations(Ctx(), 2, 2) == []

    def test_rejects_bad_ranges(self):
        with pytest.raises(ValueError):
            ModuleManager((VersionedModule("x", 3, 1),))
        with pytest.raises(ValueError):
            ModuleManager((VersionedModule("x", 1, 2), VersionedModule("x", 1, 2)))
